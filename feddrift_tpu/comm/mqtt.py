"""Minimal MQTT 3.1.1 wire protocol: broker and client.

The reference's MQTT transport is paho-mqtt against a real broker
(fedml_core/distributed/communication/mqtt/mqtt_comm_manager.py:14-135,
default broker.emqx.io:1883). `comm/netbroker.py` gives the same
pub/sub semantics over an NDJSON wire, which cannot interoperate with an
actual MQTT broker; this module closes that gap with real MQTT 3.1.1
framing (spec: OASIS mqtt-v3.1.1, control packets 1-14):

* ``MqttBroker`` — a broker speaking MQTT 3.1.1: CONNECT/CONNACK,
  PUBLISH (QoS 0), SUBSCRIBE/SUBACK, UNSUBSCRIBE/UNSUBACK,
  PINGREQ/PINGRESP, DISCONNECT. Any compliant client (e.g. paho-mqtt)
  can connect to it.
* ``MqttBrokerClient`` — a client exposing the same ``Broker`` interface
  as `comm/pubsub.py` (subscribe(topic) -> Queue, publish, unsubscribe),
  so ``PubSubCommManager(MqttBrokerClient(host, port), rank)`` is a
  drop-in swap — and the host:port may be ANY MQTT 3.1.1 broker, not
  just ours.

Scope, stated plainly: QoS 0 delivery (the reference publishes with the
paho default QoS 0); inbound QoS 1 publishes are PUBACK'd and delivered
once, QoS 2 connections are closed rather than silently downgraded;
standard '+'/'#' topic wildcards; no retained messages, wills, or auth.
The client sends keepalive=0 by default (no automatic ping timer — FL
clients are silent for minutes while training; see ``connect_packet``).
Payloads are UTF-8 strings (the JSON-serialised Message wire format,
matching the reference's json.dumps payloads).

Fan-out uses the same per-subscriber bounded-queue + writer-thread
pattern as netbroker so one stalled subscriber cannot wedge the broker.
"""

from __future__ import annotations

import queue
import socket
import struct
import threading
from collections import defaultdict

from feddrift_tpu import obs

from .netbroker import TcpFanoutServer

# Control packet types (MQTT 3.1.1 §2.2.1)
CONNECT, CONNACK, PUBLISH, PUBACK, SUBSCRIBE, SUBACK = 1, 2, 3, 4, 8, 9
UNSUBSCRIBE, UNSUBACK, PINGREQ, PINGRESP, DISCONNECT = 10, 11, 12, 13, 14


# ----------------------------------------------------------------------
# Frame encoding/decoding
def encode_varint(n: int) -> bytes:
    """Remaining-length varint (§2.2.3): 7 bits per byte, MSB = continue."""
    if not 0 <= n < 268_435_456:
        raise ValueError(f"remaining length out of range: {n}")
    out = bytearray()
    while True:
        n, digit = divmod(n, 128)
        out.append(digit | (0x80 if n else 0))
        if not n:
            return bytes(out)


def _read_varint(f) -> int | None:
    mult, value = 1, 0
    for _ in range(4):
        b = f.read(1)
        if not b:
            return None                    # connection closed
        value += (b[0] & 0x7F) * mult
        if not b[0] & 0x80:
            return value
        mult *= 128
    raise ValueError("malformed remaining length")


def _utf8(s: str) -> bytes:
    b = s.encode("utf-8")
    return struct.pack(">H", len(b)) + b


def _read_utf8(buf: bytes, off: int) -> tuple[str, int]:
    (n,) = struct.unpack_from(">H", buf, off)
    off += 2
    return buf[off : off + n].decode("utf-8"), off + n


def make_packet(ptype: int, flags: int, body: bytes) -> bytes:
    return bytes([(ptype << 4) | flags]) + encode_varint(len(body)) + body


def connect_packet(client_id: str, keepalive: int = 0) -> bytes:
    """CONNECT with clean-session (§3.1): protocol name 'MQTT', level 4.

    keepalive defaults to 0 = keep-alive mechanism OFF (§3.1.2.10): this
    client has no automatic ping timer, and a nonzero value would let a
    real broker drop it after 1.5x the interval of idleness (FL clients
    are routinely silent for minutes while training). Callers that want
    liveness probing pass a nonzero value and drive ``ping()`` themselves.
    """
    body = (_utf8("MQTT") + bytes([4])        # protocol level 3.1.1
            + bytes([0x02])                   # connect flags: clean session
            + struct.pack(">H", keepalive)
            + _utf8(client_id))
    return make_packet(CONNECT, 0, body)


def publish_packet(topic: str, payload: bytes) -> bytes:
    """PUBLISH, QoS 0 (§3.3): no packet identifier."""
    return make_packet(PUBLISH, 0, _utf8(topic) + payload)


def subscribe_packet(packet_id: int, topic: str) -> bytes:
    """SUBSCRIBE (§3.8): fixed-header flags MUST be 0b0010."""
    body = struct.pack(">H", packet_id) + _utf8(topic) + bytes([0])  # QoS 0
    return make_packet(SUBSCRIBE, 0x02, body)


def unsubscribe_packet(packet_id: int, topic: str) -> bytes:
    return make_packet(UNSUBSCRIBE, 0x02,
                       struct.pack(">H", packet_id) + _utf8(topic))


def read_packet(f) -> tuple[int, int, bytes] | None:
    """Read one control packet -> (type, flags, body); None at EOF."""
    h = f.read(1)
    if not h:
        return None
    length = _read_varint(f)
    if length is None:
        return None
    body = f.read(length) if length else b""
    if len(body) != length:
        return None
    return h[0] >> 4, h[0] & 0x0F, body


def topic_matches(flt: str, topic: str) -> bool:
    """Topic-filter match with '+' (one level) and '#' (tail) (§4.7)."""
    fparts, tparts = flt.split("/"), topic.split("/")
    for i, fp in enumerate(fparts):
        if fp == "#":
            return True
        if i >= len(tparts):
            return False
        if fp != "+" and fp != tparts[i]:
            return False
    return len(fparts) == len(tparts)


# ----------------------------------------------------------------------
class MqttBroker(TcpFanoutServer):
    """MQTT 3.1.1 broker (QoS 0 delivery). Shares the accept / reader /
    bounded-queue-writer lifecycle with netbroker.TcpFanoutServer; this
    class is only the MQTT framing."""

    _BINARY = True
    TRANSPORT = "mqtt"

    def _handle(self, conn: socket.socket, f) -> None:
        reg = obs.registry()
        msgs_in = reg.counter("broker_messages_in", transport=self.TRANSPORT)
        bytes_in = reg.counter("broker_bytes_in", transport=self.TRANSPORT)
        pkt = read_packet(f)
        if pkt is None or pkt[0] != CONNECT:
            return                           # §3.1: first packet MUST be CONNECT
        self._enqueue(conn, make_packet(CONNACK, 0, b"\x00\x00"))
        while True:
            pkt = read_packet(f)
            if pkt is None:
                return
            ptype, flags, body = pkt
            msgs_in.inc()
            bytes_in.inc(len(body) + 2)      # + fixed header approximation
            if ptype == PUBLISH:
                qos = (flags >> 1) & 0x03
                if qos == 3:
                    return                   # §3.3.1.2: malformed, close
                topic, off = _read_utf8(body, 0)
                if qos:                      # QoS 1/2 carry a packet id
                    (pid,) = struct.unpack_from(">H", body, off)
                    off += 2
                    if qos == 1:
                        self._enqueue(conn, make_packet(
                            PUBACK, 0, struct.pack(">H", pid)))
                    else:                    # QoS 2 unsupported: close
                        return               # rather than silently downgrade
                with self._lock:
                    targets = [c for flt, subs in self._subs.items()
                               if topic_matches(flt, topic)
                               for c in subs]
                frame = publish_packet(topic, body[off:])  # re-sent as QoS 0
                for c in dict.fromkeys(targets):   # dedupe, keep order
                    self._enqueue(c, frame)
            elif ptype == SUBSCRIBE:
                (pid,) = struct.unpack_from(">H", body, 0)
                off, codes = 2, bytearray()
                while off < len(body):
                    flt, off = _read_utf8(body, off)
                    off += 1                 # requested QoS byte
                    with self._lock:
                        if conn not in self._subs[flt]:
                            self._subs[flt].append(conn)
                    codes.append(0)          # granted QoS 0
                self._enqueue(conn, make_packet(
                    SUBACK, 0, struct.pack(">H", pid) + bytes(codes)))
            elif ptype == UNSUBSCRIBE:
                (pid,) = struct.unpack_from(">H", body, 0)
                off = 2
                while off < len(body):
                    flt, off = _read_utf8(body, off)
                    with self._lock:
                        if conn in self._subs.get(flt, ()):
                            self._subs[flt].remove(conn)
                self._enqueue(conn, make_packet(
                    UNSUBACK, 0, struct.pack(">H", pid)))
            elif ptype == PINGREQ:
                self._enqueue(conn, make_packet(PINGRESP, 0, b""))
            elif ptype == DISCONNECT:
                return


# ----------------------------------------------------------------------
class MqttBrokerClient:
    """MQTT 3.1.1 client exposing the in-process ``Broker`` interface
    (pubsub.Broker): subscribe(topic) -> Queue, publish, unsubscribe.

    Works against ``MqttBroker`` or any compliant MQTT 3.1.1 broker."""

    def __init__(self, host: str, port: int, client_id: str = "",
                 timeout: float = 10.0, on_disconnect=None) -> None:
        self._closed = False
        self.on_disconnect = on_disconnect   # fires once on UNEXPECTED death
        self._sock = socket.create_connection((host, port), timeout=timeout)
        # clear the connect timeout BEFORE the reader starts: an inherited
        # per-socket timeout would make the reader's first long idle recv
        # raise and silently kill the loop (handshake timeout is enforced
        # by the Event wait below instead, as netbroker does)
        self._sock.settimeout(None)
        self._wlock = threading.Lock()
        self._queues: dict[str, list[queue.Queue]] = defaultdict(list)
        self._qlock = threading.Lock()
        self._pid = 0
        self._connack = threading.Event()
        self._connack_code: int | None = None
        self._f = self._sock.makefile("rb")
        self._send(connect_packet(client_id or f"feddrift-{id(self):x}"))
        threading.Thread(target=self._read_loop, daemon=True).start()
        if not self._connack.wait(timeout):
            self._sock.close()
            raise ConnectionError("no CONNACK from broker")
        if self._connack_code:
            self._sock.close()
            raise ConnectionError(
                f"broker refused connection: return code "
                f"{self._connack_code} (§3.2.2.3)")

    def _send(self, frame: bytes) -> None:
        with self._wlock:
            self._sock.sendall(frame)
        reg = obs.registry()
        reg.counter("client_messages_out", transport="mqtt").inc()
        reg.counter("client_bytes_out", transport="mqtt").inc(len(frame))

    def _next_pid(self) -> int:
        self._pid = self._pid % 65535 + 1
        return self._pid

    def _read_loop(self) -> None:
        reg = obs.registry()
        msgs_in = reg.counter("client_messages_in", transport="mqtt")
        bytes_in = reg.counter("client_bytes_in", transport="mqtt")
        try:
            while True:
                pkt = read_packet(self._f)
                if pkt is None:
                    return
                ptype, _flags, body = pkt
                msgs_in.inc()
                bytes_in.inc(len(body) + 2)
                if ptype == CONNACK:
                    self._connack_code = body[1] if len(body) > 1 else 0xFF
                    self._connack.set()      # __init__ raises on refusal
                    if self._connack_code:
                        return
                elif ptype == PUBLISH:
                    topic, off = _read_utf8(body, 0)
                    try:
                        payload = body[off:].decode("utf-8")
                    except UnicodeDecodeError:
                        continue             # binary payload from a third-
                        # party client: skip it, keep the loop alive (our
                        # wire carries JSON strings only)
                    with self._qlock:
                        qs = [q for flt, lst in self._queues.items()
                              if topic_matches(flt, topic) for q in lst]
                    for q in qs:
                        q.put(payload)
                # SUBACK/UNSUBACK/PINGRESP need no action at QoS 0
        except (OSError, ValueError, struct.error):
            # struct.error: truncated PUBLISH body — treat like a closed
            # socket rather than silently killing only the reader thread
            pass
        finally:
            cb = self.on_disconnect
            if (cb is not None and not self._closed
                    and self._connack.is_set() and not self._connack_code):
                cb()                        # established session died, not
                                            # close() nor a refused CONNECT

    # -- Broker interface ----------------------------------------------
    def subscribe(self, topic: str, sink: "queue.Queue | None" = None) -> queue.Queue:
        q: queue.Queue = sink if sink is not None else queue.Queue()
        with self._qlock:
            first = not self._queues[topic]
            self._queues[topic].append(q)
            if first:
                self._send(subscribe_packet(self._next_pid(), topic))
        return q

    def publish(self, topic: str, payload: str, trace=None) -> None:
        # ``trace`` accepted for Broker-interface parity; MQTT 3.1.1 has
        # no frame metadata to carry it, the context rides the payload.
        self._send(publish_packet(topic, payload.encode("utf-8")))

    def unsubscribe(self, topic: str, q: queue.Queue) -> None:
        with self._qlock:
            subs = self._queues.get(topic, [])
            if q in subs:
                subs.remove(q)
            if not subs:
                self._queues.pop(topic, None)
                try:
                    self._send(unsubscribe_packet(self._next_pid(), topic))
                except OSError:
                    pass

    def ping(self) -> None:
        self._send(make_packet(PINGREQ, 0, b""))

    def close(self) -> None:
        self._closed = True                 # suppress on_disconnect
        try:
            self._send(make_packet(DISCONNECT, 0, b""))
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
