"""Role managers (fedml_core/distributed/{server/server_manager.py:11,
client/client_manager.py:12}) and the generic manager skeletons of
fedml_api/distributed/{base_framework,decentralized_framework}.

Handler-registry event loop preserved; termination is a clean loop stop
instead of MPI.COMM_WORLD.Abort() (server_manager.py:57) — a crashed peer
can't wedge the barrier because there is no cross-process barrier to wedge.
"""

from __future__ import annotations

import logging
from typing import Callable

from feddrift_tpu.comm.base import BaseCommManager, Observer
from feddrift_tpu.comm.message import Message

log = logging.getLogger("feddrift_tpu")


class _Manager(Observer):
    def __init__(self, rank: int, size: int,
                 com_manager: BaseCommManager) -> None:
        self.rank = rank
        self.size = size
        self.com_manager = com_manager
        self.com_manager.add_observer(self)
        self.message_handler_dict: dict[int, Callable[[Message], None]] = {}
        self.register_message_receive_handlers()

    # subclasses populate the registry (client_manager.py:41-46 pattern)
    def register_message_receive_handlers(self) -> None:
        ...

    def register_message_receive_handler(self, msg_type: int,
                                         handler: Callable[[Message], None]) -> None:
        self.message_handler_dict[msg_type] = handler

    def receive_message(self, msg_type: int, msg: Message) -> None:
        handler = self.message_handler_dict.get(msg_type)
        if handler is None:
            # drop + log rather than raise: an exception here would
            # propagate into the transport's receive loop and silently kill
            # a run_async daemon thread, wedging the endpoint
            log.warning("rank %d: dropping message with unhandled type %s "
                        "from rank %d", self.rank, msg_type, msg.sender_id)
            return
        try:
            handler(msg)
        except Exception:
            # same rationale as the unknown-type drop: a raising handler must
            # not kill the transport's (possibly daemon-threaded) receive loop
            log.exception("rank %d: handler for msg_type=%s raised; "
                          "message dropped", self.rank, msg_type)

    def send_message(self, msg: Message) -> None:
        self.com_manager.send_message(msg)

    def run(self) -> None:
        self.com_manager.handle_receive_message()

    def finish(self) -> None:
        self.com_manager.stop_receive_message()


class ServerManager(_Manager):
    """rank 0 by convention (FedAvgEnsAPI.py:86-92)."""


class ClientManager(_Manager):
    """ranks 1..N."""
