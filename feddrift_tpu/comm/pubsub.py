"""Topic-based pub/sub transport (the reference's MQTT alternative,
fedml_core/distributed/communication/mqtt/mqtt_comm_manager.py:14-135).

The reference publishes JSON-serialized messages to a public broker
(broker.emqx.io) with one topic per receiver id. Here the broker is an
in-process object with the same topic semantics and the same JSON wire
constraint — payloads must survive JSON round-trips (lists/floats, not live
arrays), which is exactly the MQTT manager's contract and what a real broker
binding would need. The actual network binding lives in
``comm/netbroker.py`` (TCP, newline-delimited JSON frames): it exposes this
module's ``Broker`` interface, so managers and message schema run unchanged
over a real socket.
"""

from __future__ import annotations

import json
import queue
import threading
from collections import defaultdict
from typing import Optional

import numpy as np

from feddrift_tpu.comm.base import BaseCommManager
from feddrift_tpu.comm.message import Message

_STOP = object()


def _jsonify(obj):
    """numpy/jax arrays -> nested lists (MQTT JSON wire format)."""
    if isinstance(obj, np.ndarray):
        return obj.tolist()
    if hasattr(obj, "__array__") and not isinstance(obj, (str, bytes)):
        return np.asarray(obj).tolist()      # jax.Array and array-likes
    if isinstance(obj, dict):
        return {k: _jsonify(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_jsonify(v) for v in obj]
    if isinstance(obj, (np.integer,)):
        return int(obj)
    if isinstance(obj, (np.floating,)):
        return float(obj)
    return obj


class Broker:
    """Topic -> subscriber queues. One topic per endpoint id, as the MQTT
    manager subscribes to its own client id topic."""

    def __init__(self) -> None:
        self._subs: dict[str, list[queue.Queue]] = defaultdict(list)
        self._lock = threading.Lock()

    def subscribe(self, topic: str, sink: Optional[queue.Queue] = None) -> queue.Queue:
        """``sink``: optionally reuse a caller-held queue (the resilience
        wrappers re-attach stable queues across sessions; every Broker
        implementation accepts it)."""
        q: queue.Queue = sink if sink is not None else queue.Queue()
        with self._lock:
            self._subs[topic].append(q)
        return q

    def publish(self, topic: str, payload: str, trace=None) -> None:
        # ``trace`` is accepted for interface parity with the network
        # clients (comm/netbroker.py): in-process delivery has no wire
        # hop worth a span, the context already rides the payload.
        # puts happen under the lock (queue.Queue is unbounded, so this
        # can't block): otherwise a concurrent unsubscribe could deregister
        # a queue between the snapshot and the put, losing the message into
        # an orphaned queue
        with self._lock:
            for q in self._subs.get(topic, ()):
                q.put(payload)

    def unsubscribe(self, topic: str, q: queue.Queue) -> None:
        with self._lock:
            subs = self._subs.get(topic, [])
            if q in subs:
                subs.remove(q)
            if not subs:
                self._subs.pop(topic, None)


class PubSubCommManager(BaseCommManager):
    """MQTT-shaped transport: JSON on the wire, topic = receiver id."""

    def __init__(self, broker: Broker, rank: int) -> None:
        super().__init__()
        self.broker = broker
        self.rank = rank
        self.topic = str(rank)
        self._inbox = broker.subscribe(self.topic)
        self._thread: Optional[threading.Thread] = None

    def send_message(self, msg: Message) -> None:
        wire = json.dumps({
            "msg_type": int(msg.msg_type),
            "sender_id": int(msg.sender_id),
            "receiver_id": int(msg.receiver_id),
            "params": _jsonify(msg.params),
        })
        self.broker.publish(str(msg.receiver_id), wire)

    def handle_receive_message(self) -> None:
        while True:
            item = self._inbox.get()
            if item is _STOP:
                return
            d = json.loads(item)
            self.notify(Message(d["msg_type"], d["sender_id"],
                                d["receiver_id"], d["params"]))

    def run_async(self) -> None:
        self._thread = threading.Thread(target=self.handle_receive_message,
                                        daemon=True)
        self._thread.start()

    def stop_receive_message(self) -> None:
        # deregister first so the broker never enqueues into a dead queue
        self.broker.unsubscribe(self.topic, self._inbox)
        self._inbox.put(_STOP)
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
