"""DARTS searchable-cell network with the reference's full search space.

Reference: fedml_api/model/cv/darts/ — the 8-op ``PRIMITIVES`` list and
Genotype tuple (genotypes.py:1-14), MixedOp/Cell/Network
(model_search.py:10-59, 172-241), the concrete ops incl. SepConv/DilConv/
FactorizedReduce (operations.py), and genotype derivation
(model_search.py:258-297).  Used by FedNAS (platform/fednas.py).

Design for TPU + federation:
- The DARTS "alphas" are TWO shared tensors ``arch_alphas_normal`` /
  ``arch_alphas_reduce`` of shape [k, 8] (k = sum_i (2+i) edges), exactly
  the reference's ``_initialize_alphas`` (model_search.py:232-241): every
  normal cell reads the same softmaxed weights, every reduction cell the
  other set.  They live at the top of the flax param tree with an ``arch_``
  name prefix; ``split_arch_params`` partitions a pytree into (weights,
  alphas) by that prefix.  FedNAS uses the split for the bilevel update;
  plain FedAvg over the whole pytree still works (alphas simply average,
  the reference server's behaviour, fednas/FedNASAggregator.py).
- Every candidate op runs and is mixed by softmax(alpha): no
  data-dependent control flow, so ONE traced XLA program covers all
  architectures — the DARTS continuous relaxation maps to TPU better than
  discrete NAS because the mixture is a dense weighted sum the compiler
  fuses.  ``none`` contributes a zero tensor (kept so the softmax
  normalisation and genotype semantics match the reference; XLA folds the
  multiply-by-zero into the sum).
- Cells are the reference's two-input DAG: states s0 (prev-prev cell) and
  s1 (prev cell) preprocessed to the cell width, ``steps`` intermediate
  nodes each summing mixed edges from all predecessors, output =
  concatenation of the last ``multiplier`` nodes.  Reduction cells (at
  layers//3 and 2*layers//3) stride-2 the two input edges.
"""

from __future__ import annotations

from typing import NamedTuple, Sequence

import flax.linen as nn
import jax.numpy as jnp
import numpy as np

from feddrift_tpu.models.resnet import _Norm

# Same names and order as the reference (genotypes.py:5-14) so exported
# genotypes are directly comparable.
PRIMITIVES: Sequence[str] = (
    "none",
    "max_pool_3x3",
    "avg_pool_3x3",
    "skip_connect",
    "sep_conv_3x3",
    "sep_conv_5x5",
    "dil_conv_3x3",
    "dil_conv_5x5",
)


class Genotype(NamedTuple):
    """(op_name, predecessor_state) pairs per node + concat node ids
    (genotypes.py:3)."""

    normal: list
    normal_concat: list
    reduce: list
    reduce_concat: list


def _relu_conv_bn(x, filters: int, kernel, strides, norm: str):
    """ReLUConvBN (operations.py): relu -> conv -> norm."""
    x = nn.relu(x)
    x = nn.Conv(filters, kernel, strides=strides, padding="SAME",
                use_bias=False)(x)
    return _Norm(norm)(x)


class FactorizedReduce(nn.Module):
    """Stride-2 channel-preserving reduce: concat of two offset 1x1
    stride-2 convs (operations.py FactorizedReduce)."""

    filters: int
    norm: str = "batch"

    @nn.compact
    def __call__(self, x):
        x = nn.relu(x)
        a = nn.Conv(self.filters // 2, (1, 1), strides=(2, 2),
                    use_bias=False)(x)
        b = nn.Conv(self.filters - self.filters // 2, (1, 1), strides=(2, 2),
                    use_bias=False)(x[:, 1:, 1:, :])
        return _Norm(self.norm)(jnp.concatenate([a, b], axis=-1))


class _Op(nn.Module):
    """One concrete candidate op (operations.py OPS table)."""

    kind: str
    filters: int
    stride: int = 1
    norm: str = "batch"

    @nn.compact
    def __call__(self, x):
        s = (self.stride, self.stride)
        if self.kind == "none":
            # Zero op at the strided output shape (operations.py Zero).
            return jnp.zeros_like(x[:, ::self.stride, ::self.stride, :])
        if self.kind in ("max_pool_3x3", "avg_pool_3x3"):
            pool = nn.max_pool if self.kind.startswith("max") else nn.avg_pool
            y = pool(x, (3, 3), strides=s, padding="SAME")
            # reference wraps pooling in an affine-less BN
            # (model_search.py:17-18); _Norm's batch mode is stateless.
            return _Norm(self.norm)(y)
        if self.kind == "skip_connect":
            if self.stride == 1:
                return x
            return FactorizedReduce(self.filters, self.norm)(x)
        if self.kind in ("sep_conv_3x3", "sep_conv_5x5"):
            k = 3 if self.kind.endswith("3x3") else 5
            # SepConv applies depthwise-separable twice, stride on the
            # first (operations.py SepConv).
            y = x
            for i, st in enumerate((s, (1, 1))):
                y = nn.relu(y)
                y = nn.Conv(y.shape[-1], (k, k), strides=st, padding="SAME",
                            feature_group_count=y.shape[-1],
                            use_bias=False)(y)
                y = nn.Conv(self.filters, (1, 1), use_bias=False)(y)
                y = _Norm(self.norm)(y)
            return y
        if self.kind in ("dil_conv_3x3", "dil_conv_5x5"):
            k = 3 if self.kind.endswith("3x3") else 5
            y = nn.relu(x)
            y = nn.Conv(y.shape[-1], (k, k), strides=s, padding="SAME",
                        kernel_dilation=(2, 2),
                        feature_group_count=y.shape[-1], use_bias=False)(y)
            y = nn.Conv(self.filters, (1, 1), use_bias=False)(y)
            return _Norm(self.norm)(y)
        raise ValueError(self.kind)


class MixedOp(nn.Module):
    """softmax(alpha)-weighted sum of all 8 candidates on one edge
    (model_search.py:10-23).  ``weights`` come from the shared cell-type
    alpha tensor — this module holds no arch params itself."""

    filters: int
    stride: int = 1
    norm: str = "batch"

    @nn.compact
    def __call__(self, x, weights):
        outs = [_Op(k, self.filters, self.stride, self.norm,
                    name=f"op_{k}")(x) for k in PRIMITIVES]
        return sum(weights[i] * outs[i] for i in range(len(PRIMITIVES)))


class Cell(nn.Module):
    """Two-input DARTS cell (model_search.py:26-59): preprocess s0/s1 to
    ``filters`` channels, build ``steps`` nodes over all predecessors,
    concat the last ``multiplier`` nodes."""

    filters: int
    steps: int = 4
    multiplier: int = 4
    reduction: bool = False
    reduction_prev: bool = False
    norm: str = "batch"

    @nn.compact
    def __call__(self, s0, s1, weights):
        if self.reduction_prev:
            s0 = FactorizedReduce(self.filters, self.norm,
                                  name="preprocess0")(s0)
        else:
            s0 = _relu_conv_bn(s0, self.filters, (1, 1), (1, 1), self.norm)
        s1 = _relu_conv_bn(s1, self.filters, (1, 1), (1, 1), self.norm)
        states = [s0, s1]
        offset = 0
        for i in range(self.steps):
            acc = None
            for j, h in enumerate(states):
                stride = 2 if self.reduction and j < 2 else 1
                y = MixedOp(self.filters, stride, self.norm,
                            name=f"edge_{offset + j}")(h, weights[offset + j])
                acc = y if acc is None else acc + y
            offset += len(states)
            states.append(acc)
        return jnp.concatenate(states[-self.multiplier:], axis=-1)


def num_edges(steps: int) -> int:
    """k = 2 + 3 + ... + (steps+1) mixed edges per cell type
    (model_search.py:233)."""
    return sum(2 + i for i in range(steps))


class DARTSNetwork(nn.Module):
    """The searchable network (model_search.py Network): stem, cells with
    reduction at layers//3 and 2*layers//3, global pool, classifier.

    Field names keep round-1's API: ``filters`` = init channels C,
    ``cells`` = layers, ``nodes`` = steps.  ``multiplier`` defaults to
    ``nodes`` (the reference's steps=multiplier=4 concats ALL intermediate
    nodes; same here for any node count)."""

    num_classes: int = 10
    filters: int = 16
    cells: int = 3
    nodes: int = 4
    multiplier: int = 0          # 0 -> use ``nodes``
    stem_multiplier: int = 3
    norm: str = "batch"

    @nn.compact
    def __call__(self, x):
        mult = self.multiplier or self.nodes
        k = num_edges(self.nodes)
        alphas_normal = self.param(
            "arch_alphas_normal", nn.initializers.normal(1e-3),
            (k, len(PRIMITIVES)))
        alphas_reduce = self.param(
            "arch_alphas_reduce", nn.initializers.normal(1e-3),
            (k, len(PRIMITIVES)))
        w_normal = nn.softmax(alphas_normal, axis=-1)
        w_reduce = nn.softmax(alphas_reduce, axis=-1)

        if x.ndim == 2:
            x = x.reshape((x.shape[0], 32, 32, 3))
        stem = nn.Conv(self.stem_multiplier * self.filters, (3, 3),
                       padding="SAME", use_bias=False)(x)
        s0 = s1 = _Norm(self.norm)(stem)

        c_curr = self.filters
        reduction_prev = False
        reduce_at = {self.cells // 3, 2 * self.cells // 3}
        for i in range(self.cells):
            reduction = i in reduce_at
            if reduction:
                c_curr *= 2
            cell = Cell(c_curr, self.nodes, mult, reduction,
                        reduction_prev, self.norm, name=f"cell_{i}")
            s0, s1 = s1, cell(s0, s1, w_reduce if reduction else w_normal)
            reduction_prev = reduction
        out = s1.mean(axis=(1, 2))
        return nn.Dense(self.num_classes)(out)


def derive_genotype(alphas_normal, alphas_reduce, steps: int,
                    multiplier: int | None = None) -> Genotype:
    """Discretize alphas into a reference-shaped Genotype
    (model_search.py genotype():258-297): per node keep the top-2
    predecessor edges ranked by their best non-``none`` weight; each kept
    edge's op is its argmax non-``none`` primitive."""

    def _parse(alpha):
        w = np.asarray(jnp.asarray(alpha), np.float64)
        w = np.exp(w - w.max(axis=-1, keepdims=True))
        w = w / w.sum(axis=-1, keepdims=True)
        none_idx = PRIMITIVES.index("none")
        gene = []
        start, n = 0, 2
        for _ in range(steps):
            W = w[start:start + n]
            best_non_none = np.delete(W, none_idx, axis=1).max(axis=1)
            edges = sorted(range(n), key=lambda j: -best_non_none[j])[:2]
            for j in edges:
                ops = W[j].copy()
                ops[none_idx] = -np.inf
                gene.append((PRIMITIVES[int(ops.argmax())], j))
            start += n
            n += 1
        return gene

    mult = multiplier or steps
    concat = list(range(2 + steps - mult, steps + 2))
    return Genotype(normal=_parse(alphas_normal), normal_concat=concat,
                    reduce=_parse(alphas_reduce), reduce_concat=concat)


def genotype_of(params, steps: int | None = None,
                multiplier: int | None = None) -> Genotype:
    """Extract the Genotype from a DARTSNetwork param pytree."""
    an, ar = params["arch_alphas_normal"], params["arch_alphas_reduce"]
    if steps is None:
        # invert k = steps*(steps+3)/2
        k = an.shape[0]
        steps = int((-3 + np.sqrt(9 + 8 * k)) / 2)
    return derive_genotype(an, ar, steps, multiplier)


def is_arch_param(path) -> bool:
    """True if a tree_map_with_path path addresses an architecture alpha."""
    return any(str(getattr(k, "key", getattr(k, "idx", k))).startswith("arch_")
               for k in path)


def split_arch_params(params):
    """Partition a DARTS param pytree into (weight_mask, arch_mask) boolean
    pytrees usable with ``optax.masked`` or manual update gating."""
    import jax
    arch_mask = jax.tree_util.tree_map_with_path(
        lambda p, _leaf: is_arch_param(p), params)
    weight_mask = jax.tree_util.tree_map(lambda a: not a, arch_mask)
    return weight_mask, arch_mask
