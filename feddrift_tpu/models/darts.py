"""DARTS searchable-cell network (reference: fedml_api/model/cv/darts/ —
model_search.py's MixedOp/Cell/Network used by FedNAS,
fedml_api/distributed/fednas/).

Design for TPU + federation:
- Architecture parameters (the DARTS "alphas") are ordinary flax params whose
  names start with ``arch_``; ``split_arch_params`` partitions a param pytree
  into (weights, alphas) by that prefix. FedNAS (platform/fednas.py) uses the
  split to run the bilevel update — weights on train data, alphas on search
  data — while plain FedAvg over the whole pytree still works (alphas simply
  average, which is exactly the reference server's behaviour,
  fednas/FedNASAggregator.py).
- Every candidate op runs and is mixed by softmax(alpha): no data-dependent
  control flow, so one traced XLA program covers all architectures. This is
  the DARTS continuous relaxation itself — it maps to TPU better than
  discrete NAS because the mixture is a dense weighted sum the compiler
  fuses.
"""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp

from feddrift_tpu.models.resnet import _Norm

OPS: Sequence[str] = ("skip", "conv3", "sep3", "avgpool", "maxpool")


class _Op(nn.Module):
    kind: str
    filters: int
    norm: str = "batch"

    @nn.compact
    def __call__(self, x):
        if self.kind == "skip":
            if x.shape[-1] != self.filters:
                x = nn.Conv(self.filters, (1, 1), use_bias=False)(x)
            return x
        if self.kind == "conv3":
            y = nn.Conv(self.filters, (3, 3), padding="SAME", use_bias=False)(x)
            return nn.relu(_Norm(self.norm)(y))
        if self.kind == "sep3":
            y = nn.Conv(x.shape[-1], (3, 3), padding="SAME",
                        feature_group_count=x.shape[-1], use_bias=False)(x)
            y = nn.Conv(self.filters, (1, 1), use_bias=False)(y)
            return nn.relu(_Norm(self.norm)(y))
        if self.kind == "avgpool":
            y = nn.avg_pool(x, (3, 3), strides=(1, 1), padding="SAME")
            if y.shape[-1] != self.filters:
                y = nn.Conv(self.filters, (1, 1), use_bias=False)(y)
            return y
        if self.kind == "maxpool":
            y = nn.max_pool(x, (3, 3), strides=(1, 1), padding="SAME")
            if y.shape[-1] != self.filters:
                y = nn.Conv(self.filters, (1, 1), use_bias=False)(y)
            return y
        raise ValueError(self.kind)


class MixedOp(nn.Module):
    """softmax(alpha)-weighted sum of all candidate ops (model_search.py MixedOp)."""

    filters: int
    norm: str = "batch"

    @nn.compact
    def __call__(self, x):
        alpha = self.param("arch_alpha", nn.initializers.normal(1e-3),
                           (len(OPS),))
        w = nn.softmax(alpha)
        outs = [_Op(k, self.filters, self.norm, name=f"op_{k}")(x) for k in OPS]
        return sum(w[i] * outs[i] for i in range(len(OPS)))


class Cell(nn.Module):
    """DARTS cell: ``nodes`` intermediate nodes, each summing mixed ops from
    all predecessors; output concatenates the intermediate nodes."""

    filters: int
    nodes: int = 3
    reduce: bool = False
    norm: str = "batch"

    @nn.compact
    def __call__(self, x):
        if self.reduce:
            x = nn.avg_pool(x, (2, 2), strides=(2, 2))
        states = [nn.Conv(self.filters, (1, 1), use_bias=False)(x)]
        for i in range(self.nodes):
            s = sum(MixedOp(self.filters, self.norm,
                            name=f"edge_{j}_{i}")(states[j])
                    for j in range(len(states)))
            states.append(s)
        return jnp.concatenate(states[1:], axis=-1)


class DARTSNetwork(nn.Module):
    """The searchable network (model_search.py Network): stem, alternating
    normal/reduce cells, global pool, classifier."""

    num_classes: int = 10
    filters: int = 16
    cells: int = 3
    nodes: int = 3
    norm: str = "batch"

    @nn.compact
    def __call__(self, x):
        if x.ndim == 2:
            x = x.reshape((x.shape[0], 32, 32, 3))
        x = nn.Conv(self.filters, (3, 3), padding="SAME", use_bias=False)(x)
        x = nn.relu(_Norm(self.norm)(x))
        for i in range(self.cells):
            reduce = i > 0 and i % 2 == 0
            x = Cell(self.filters * (2 if reduce else 1), self.nodes,
                     reduce=reduce, norm=self.norm, name=f"cell_{i}")(x)
        x = x.mean(axis=(1, 2))
        return nn.Dense(self.num_classes)(x)


def is_arch_param(path) -> bool:
    """True if a tree_map_with_path path addresses an architecture alpha."""
    return any(str(getattr(k, "key", getattr(k, "idx", k))).startswith("arch_")
               for k in path)


def split_arch_params(params):
    """Partition a DARTS param pytree into (weight_mask, arch_mask) boolean
    pytrees usable with ``optax.masked`` or manual update gating."""
    import jax
    arch_mask = jax.tree_util.tree_map_with_path(
        lambda p, _leaf: is_arch_param(p), params)
    weight_mask = jax.tree_util.tree_map(lambda a: not a, arch_mask)
    return weight_mask, arch_mask
