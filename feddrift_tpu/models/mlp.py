"""Linear / MLP models (reference: fedml_api/model/linear/lr.py,
fedml_api/model/fnn/fnn.py)."""

from __future__ import annotations

import flax.linen as nn


class LogisticRegression(nn.Module):
    """Sigmoid-squashed linear head, as the reference (lr.py:10-11) —
    note the reference feeds sigmoid outputs into CrossEntropyLoss; we keep
    that behavior for parity."""

    num_classes: int

    @nn.compact
    def __call__(self, x):
        x = x.reshape((x.shape[0], -1))
        return nn.sigmoid(nn.Dense(self.num_classes)(x))


class FeedForwardNN(nn.Module):
    """fc1 -> relu -> fc2 (fnn.py:5-15); the SEA/SINE/CIRCLE/MNIST workhorse."""

    num_classes: int
    hidden_dim: int = 10

    @nn.compact
    def __call__(self, x):
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(self.hidden_dim)(x))
        return nn.Dense(self.num_classes)(x)
