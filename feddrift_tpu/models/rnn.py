"""LSTM sequence models (reference: fedml_api/model/nlp/rnn.py:4-67).

CharLSTM replicates RNN_OriginalFedAvg (embed 8 -> 2-layer LSTM 256 -> fc to
vocab, last-position prediction); WordLSTM replicates RNN_StackOverFlow
(embed 96 -> LSTM 670 -> fc 96 -> fc vocab+4).

TPU-first: the sequence is unrolled with ``nn.RNN`` (lax.scan under the
hood) so the whole model stays a single XLA program; batch-first layout.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


class CharLSTM(nn.Module):
    vocab_size: int = 90
    embedding_dim: int = 8
    hidden_size: int = 256

    @nn.compact
    def __call__(self, tokens):
        x = nn.Embed(self.vocab_size, self.embedding_dim)(tokens.astype(jnp.int32))
        x = nn.RNN(nn.OptimizedLSTMCell(self.hidden_size))(x)
        x = nn.RNN(nn.OptimizedLSTMCell(self.hidden_size))(x)
        return nn.Dense(self.vocab_size)(x[:, -1])


class WordLSTM(nn.Module):
    vocab_size: int = 10000
    num_oov_buckets: int = 1
    embedding_size: int = 96
    latent_size: int = 670

    @nn.compact
    def __call__(self, tokens):
        extended = self.vocab_size + 3 + self.num_oov_buckets
        x = nn.Embed(extended, self.embedding_size)(tokens.astype(jnp.int32))
        x = nn.RNN(nn.OptimizedLSTMCell(self.latent_size))(x)
        x = nn.Dense(self.embedding_size)(x[:, -1])
        return nn.Dense(extended)(x)
