"""MobileNet (reference: fedml_api/model/cv/mobilenet.py:60) and a compact
DenseNet (torchvision densenet121 is the reference's pretrained option,
main_fedavg.py:219-222).

TPU-first notes: NHWC layout; depthwise convolutions use
``feature_group_count`` which XLA lowers to efficient TPU convolutions; the
width multiplier keeps channel counts multiples of 8 so tiles land on the MXU
cleanly. Norms are the stateless per-batch / GroupNorm variants shared with
the ResNets (see models/resnet.py) so the modules stay pure functions of
``(params, x)`` and stack under vmap on the model-pool axis.
"""

from __future__ import annotations

import flax.linen as nn

from feddrift_tpu.models.resnet import _Norm


class _DepthwiseSeparable(nn.Module):
    filters: int
    strides: int = 1
    norm: str = "batch"

    @nn.compact
    def __call__(self, x):
        # depthwise 3x3: one group per input channel
        x = nn.Conv(x.shape[-1], (3, 3), strides=(self.strides, self.strides),
                    padding="SAME", feature_group_count=x.shape[-1],
                    use_bias=False)(x)
        x = nn.relu(_Norm(self.norm)(x))
        # pointwise 1x1 — this is where the FLOPs (and the MXU work) are
        x = nn.Conv(self.filters, (1, 1), use_bias=False)(x)
        return nn.relu(_Norm(self.norm)(x))


class MobileNet(nn.Module):
    """MobileNetV1-style network (mobilenet.py:60), CIFAR-sized stem.

    ``alpha`` is the width multiplier; channels are rounded to multiples of 8.
    """

    num_classes: int = 10
    alpha: float = 1.0
    norm: str = "batch"

    @nn.compact
    def __call__(self, x):
        if x.ndim == 2:
            x = x.reshape((x.shape[0], 32, 32, 3))

        def ch(c: int) -> int:
            return max(8, int(c * self.alpha + 4) // 8 * 8)

        x = nn.Conv(ch(32), (3, 3), padding="SAME", use_bias=False)(x)
        x = nn.relu(_Norm(self.norm)(x))
        # (filters, strides) schedule of the V1 body, CIFAR-compressed: the
        # three stride-2 stages map 32x32 -> 4x4.
        for filters, strides in ((64, 1), (128, 2), (128, 1), (256, 2),
                                 (256, 1), (512, 2), (512, 1)):
            x = _DepthwiseSeparable(ch(filters), strides, self.norm)(x)
        x = x.mean(axis=(1, 2))
        return nn.Dense(self.num_classes)(x)


class _DenseBlock(nn.Module):
    layers: int
    growth: int
    norm: str = "batch"

    @nn.compact
    def __call__(self, x):
        import jax.numpy as jnp
        for _ in range(self.layers):
            y = nn.relu(_Norm(self.norm)(x))
            y = nn.Conv(4 * self.growth, (1, 1), use_bias=False)(y)
            y = nn.relu(_Norm(self.norm)(y))
            y = nn.Conv(self.growth, (3, 3), padding="SAME", use_bias=False)(y)
            x = jnp.concatenate([x, y], axis=-1)
        return x


class DenseNet(nn.Module):
    """Compact DenseNet-BC (densenet121 flavor at CIFAR scale)."""

    num_classes: int = 10
    growth: int = 12
    blocks: tuple = (6, 12, 8)
    norm: str = "batch"

    @nn.compact
    def __call__(self, x):
        if x.ndim == 2:
            x = x.reshape((x.shape[0], 32, 32, 3))
        x = nn.Conv(2 * self.growth, (3, 3), padding="SAME", use_bias=False)(x)
        for i, layers in enumerate(self.blocks):
            x = _DenseBlock(layers, self.growth, self.norm)(x)
            if i < len(self.blocks) - 1:   # transition: halve channels + pool
                x = nn.relu(_Norm(self.norm)(x))
                x = nn.Conv(x.shape[-1] // 2, (1, 1), use_bias=False)(x)
                x = nn.avg_pool(x, (2, 2), strides=(2, 2))
        x = nn.relu(_Norm(self.norm)(x))
        x = x.mean(axis=(1, 2))
        return nn.Dense(self.num_classes)(x)
