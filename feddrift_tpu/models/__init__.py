"""Model zoo registry.

Mirrors the reference's ``create_model`` dispatch
(fedml_experiments/distributed/fedavg_cont_ens/main_fedavg.py:207-224) but as
flax modules returning logits. Every model is a pure function of
``(params, x)`` so the pool can be stacked on a leading ``[M]`` axis and
trained under ``vmap``.
"""

from __future__ import annotations

from typing import Callable

import flax.linen as nn

from feddrift_tpu.data.drift_dataset import DriftDataset
from feddrift_tpu.models.mlp import LogisticRegression, FeedForwardNN
from feddrift_tpu.models.cnn import CNNFedAvg, CNNDropout
from feddrift_tpu.models.resnet import ResNetCifar, ResNet18
from feddrift_tpu.models.rnn import CharLSTM, WordLSTM

_BUILDERS: dict[str, Callable[..., nn.Module]] = {}


def register_model(*names: str):
    def deco(fn):
        for n in names:
            _BUILDERS[n] = fn
        return fn
    return deco


def available_models() -> list[str]:
    return sorted(_BUILDERS)


@register_model("lr")
def _lr(ds: DriftDataset, cfg) -> nn.Module:
    return LogisticRegression(num_classes=ds.num_classes)


@register_model("fnn")
def _fnn(ds: DriftDataset, cfg) -> nn.Module:
    # Reference: FeedForwardNN(input_dim, output_dim, hidden) with hidden from
    # main_fedavg model wiring; hidden_dim configurable here.
    return FeedForwardNN(num_classes=ds.num_classes,
                        hidden_dim=getattr(cfg, "fnn_hidden_dim", 10))


@register_model("cnn")
def _cnn(ds: DriftDataset, cfg) -> nn.Module:
    return CNNFedAvg(num_classes=ds.num_classes)


@register_model("cnn_dropout")
def _cnnd(ds: DriftDataset, cfg) -> nn.Module:
    return CNNDropout(num_classes=ds.num_classes)


@register_model("resnet", "resnet20")
def _resnet20(ds: DriftDataset, cfg) -> nn.Module:
    return ResNetCifar(num_classes=ds.num_classes, depth=20)


@register_model("resnet8")
def _resnet8(ds, cfg):
    # GKT client-side extractor size (reference fedgkt resnet_client ResNet-8)
    return ResNetCifar(num_classes=ds.num_classes, depth=8)


@register_model("resnet56")
def _resnet56(ds: DriftDataset, cfg) -> nn.Module:
    return ResNetCifar(num_classes=ds.num_classes, depth=56)


@register_model("resnet110")
def _resnet110(ds: DriftDataset, cfg) -> nn.Module:
    return ResNetCifar(num_classes=ds.num_classes, depth=110)


@register_model("resnet56_gn")
def _resnet56gn(ds: DriftDataset, cfg) -> nn.Module:
    return ResNetCifar(num_classes=ds.num_classes, depth=56, norm="group")


@register_model("resnet18")
def _resnet18(ds: DriftDataset, cfg) -> nn.Module:
    return ResNet18(num_classes=ds.num_classes)


@register_model("mobilenet")
def _mobilenet(ds: DriftDataset, cfg) -> nn.Module:
    from feddrift_tpu.models.mobilenet import MobileNet
    return MobileNet(num_classes=ds.num_classes)


@register_model("mobilenet_gn")
def _mobilenet_gn(ds: DriftDataset, cfg) -> nn.Module:
    from feddrift_tpu.models.mobilenet import MobileNet
    return MobileNet(num_classes=ds.num_classes, norm="group")


@register_model("densenet", "densenet121")
def _densenet(ds: DriftDataset, cfg) -> nn.Module:
    from feddrift_tpu.models.mobilenet import DenseNet
    return DenseNet(num_classes=ds.num_classes)


@register_model("darts")
def _darts(ds: DriftDataset, cfg) -> nn.Module:
    from feddrift_tpu.models.darts import DARTSNetwork
    return DARTSNetwork(num_classes=ds.num_classes)


@register_model("transformer")
def _transformer(ds: DriftDataset, cfg) -> nn.Module:
    from feddrift_tpu.models.transformer import TransformerLM
    return TransformerLM(vocab_size=ds.num_classes,
                         max_len=max(ds.feature_shape[0]
                                     if ds.is_sequence else 128, 128))


@register_model("rnn")
def _rnn(ds: DriftDataset, cfg) -> nn.Module:
    return CharLSTM(vocab_size=ds.num_classes)


@register_model("rnn_stackoverflow")
def _rnn_so(ds: DriftDataset, cfg) -> nn.Module:
    return WordLSTM(vocab_size=ds.num_classes)


def create_model(name: str, ds: DriftDataset, cfg=None) -> nn.Module:
    if name not in _BUILDERS:
        raise KeyError(f"unknown model {name!r}; available: {available_models()}")
    return _BUILDERS[name](ds, cfg)
