"""Decoder-only Transformer LM with optional sequence-parallel ring attention.

A model family the reference lacks (its sequence ceiling is a 2-layer LSTM at
seq len 80, fedml_api/model/nlp/rnn.py:4-33); added so the drift pipeline and
the long-context path share one architecture. With ``seq_axis=None`` the model
runs single-device blockwise (flash-style) attention; inside a shard_map over
a ('data', 'seq') mesh it uses ring attention and never materialises the full
sequence per chip. Blocks are wrapped in ``jax.checkpoint`` (remat) so long
sequences trade FLOPs for HBM.
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from feddrift_tpu.parallel.ring_attention import (blockwise_attention,
                                                  ring_attention)


class MultiHeadAttention(nn.Module):
    num_heads: int
    seq_axis: Optional[str] = None      # mesh axis name for ring attention
    causal: bool = True
    # 'auto': Pallas flash kernel on a TPU backend, jnp blockwise elsewhere;
    # 'pallas' / 'blockwise' force an implementation (testability + fallback
    # if Mosaic rejects a shape in production)
    attention_impl: str = "auto"

    @nn.compact
    def __call__(self, x):
        B, L, E = x.shape
        H = self.num_heads
        D = E // H
        qkv = nn.Dense(3 * E, use_bias=False, name="qkv")(x)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(B, L, H, D).transpose(0, 2, 1, 3)
        k = k.reshape(B, L, H, D).transpose(0, 2, 1, 3)
        v = v.reshape(B, L, H, D).transpose(0, 2, 1, 3)
        impl = self.attention_impl
        if impl not in ("auto", "pallas", "blockwise"):
            raise ValueError(f"attention_impl must be auto|pallas|blockwise, "
                             f"got {impl!r}")
        if impl == "auto":
            impl = "pallas" if jax.default_backend() == "tpu" else "blockwise"
        if self.seq_axis is not None:
            out = ring_attention(q, k, v, axis_name=self.seq_axis,
                                 causal=self.causal)
        elif impl == "pallas":
            # Mosaic flash kernel: ~6x the scan-based jnp path on-chip at
            # O(L * block) memory (parallel/pallas_attention.py)
            from feddrift_tpu.parallel.pallas_attention import flash_attention
            out = flash_attention(q, k, v, self.causal)
        else:
            out = blockwise_attention(q, k, v, causal=self.causal)
        out = out.transpose(0, 2, 1, 3).reshape(B, L, E)
        return nn.Dense(E, use_bias=False, name="proj")(out)


class Block(nn.Module):
    num_heads: int
    mlp_ratio: int = 4
    seq_axis: Optional[str] = None
    attention_impl: str = "auto"

    @nn.compact
    def __call__(self, x):
        E = x.shape[-1]
        h = MultiHeadAttention(self.num_heads, self.seq_axis,
                               attention_impl=self.attention_impl)(
            nn.LayerNorm()(x))
        x = x + h
        y = nn.LayerNorm()(x)
        y = nn.Dense(self.mlp_ratio * E)(y)
        y = nn.gelu(y)
        y = nn.Dense(E)(y)
        return x + y


class TransformerLM(nn.Module):
    """Next-token LM. Matches the drift pipeline's (tokens [B, L]) -> logits
    contract of CharLSTM (last-position prediction) when ``last_only=True``;
    with ``last_only=False`` returns per-position logits for long-context
    training."""

    vocab_size: int = 90
    d_model: int = 128
    num_heads: int = 4
    num_layers: int = 2
    max_len: int = 4096
    seq_axis: Optional[str] = None
    last_only: bool = True
    remat: bool = True
    attention_impl: str = "auto"        # auto | pallas | blockwise

    @nn.compact
    def __call__(self, tokens):
        B, L = tokens.shape
        x = nn.Embed(self.vocab_size, self.d_model, name="tok_embed")(
            tokens.astype(jnp.int32))
        # position offset: under sequence parallelism each shard's positions
        # start at axis_index * L
        if self.seq_axis is not None:
            off = jax.lax.axis_index(self.seq_axis) * L
        else:
            off = 0
        pos = off + jnp.arange(L)
        x = x + nn.Embed(self.max_len, self.d_model, name="pos_embed")(pos)[None]
        block_cls = Block
        if self.remat:
            block_cls = nn.remat(Block)
        for i in range(self.num_layers):
            x = block_cls(self.num_heads, seq_axis=self.seq_axis,
                          attention_impl=self.attention_impl,
                          name=f"block_{i}")(x)
        x = nn.LayerNorm()(x)
        if self.last_only:
            x = x[:, -1]
        return nn.Dense(self.vocab_size, name="lm_head")(x)
