"""CIFAR ResNets (reference: fedml_api/model/cv/resnet.py:113-232 resnet56/110,
cv/resnet_gn.py:108 GroupNorm variant, torchvision resnet18 at
main_fedavg.py:219-222).

TPU-first: NHWC, 3x3 convs sized to keep the MXU busy, GroupNorm option for
federated settings where BatchNorm's running stats are problematic (the usual
reason the reference ships resnet_gn). BatchNorm here is implemented *without*
cross-round running statistics — per-batch normalisation — which sidesteps
mutable batch-stats collections in the vmapped multi-model pool while staying
faithful to federated practice.
"""

from __future__ import annotations


import flax.linen as nn
import jax.numpy as jnp


class _Norm(nn.Module):
    kind: str = "batch"

    @nn.compact
    def __call__(self, x):
        if self.kind == "group":
            return nn.GroupNorm(num_groups=min(32, x.shape[-1]))(x)
        # Stateless per-batch normalisation over (N, H, W).
        scale = self.param("scale", nn.initializers.ones, (x.shape[-1],))
        bias = self.param("bias", nn.initializers.zeros, (x.shape[-1],))
        if x.dtype == jnp.float32:
            mean = x.mean(axis=(0, 1, 2), keepdims=True)
            var = x.var(axis=(0, 1, 2), keepdims=True)
            return (x - mean) / jnp.sqrt(var + 1e-5) * scale + bias
        # Half-width activations (the bf16 precision presets): jnp's
        # reductions upcast f16/bf16 inputs by materialising a full-size
        # f32 copy of the feature map per statistic, which costs more HBM
        # traffic than the f32 policy saved. Accumulate the two moments in
        # f32 THROUGH a dot instead (the feature map is only ever read at
        # its own width), then fold the tiny per-channel stats back to the
        # activation dtype for the full-size normalise.
        feats = x.shape[-1]
        xr = x.reshape(-1, feats)
        ones = jnp.ones((xr.shape[0],), x.dtype)
        s1 = jnp.matmul(ones, xr, preferred_element_type=jnp.float32)
        s2 = jnp.matmul(ones, xr * xr, preferred_element_type=jnp.float32)
        mean32 = s1 / xr.shape[0]
        var32 = jnp.maximum(s2 / xr.shape[0] - mean32 * mean32, 0.0)
        inv = (1.0 / jnp.sqrt(var32 + 1e-5) * scale).astype(x.dtype)
        return (x - mean32.astype(x.dtype)) * inv + bias


class BasicBlock(nn.Module):
    filters: int
    strides: int = 1
    norm: str = "batch"

    @nn.compact
    def __call__(self, x):
        residual = x
        y = nn.Conv(self.filters, (3, 3), strides=(self.strides, self.strides),
                    padding="SAME", use_bias=False)(x)
        y = nn.relu(_Norm(self.norm)(y))
        y = nn.Conv(self.filters, (3, 3), padding="SAME", use_bias=False)(y)
        y = _Norm(self.norm)(y)
        if residual.shape != y.shape:
            residual = nn.Conv(self.filters, (1, 1),
                               strides=(self.strides, self.strides),
                               use_bias=False)(x)
            residual = _Norm(self.norm)(residual)
        return nn.relu(y + residual)


class ResNetFeatures(nn.Module):
    """The stem + 16-filter stage of a 6n+2 CIFAR ResNet, emitting SPATIAL
    feature maps ``[B, 32, 32, 16]``.

    Doubles as the client-side GKT trunk (fedml_api/distributed/fedgkt/: the
    phone client runs a ResNet-8-sized extractor and uploads feature maps,
    not pooled vectors, to the server CNN). ``depth`` follows the 6n+2 rule
    (depth 8 -> n = 1 block).
    """

    depth: int = 8
    norm: str = "batch"

    @nn.compact
    def __call__(self, x):
        if x.ndim == 2:
            x = x.reshape((x.shape[0], 32, 32, 3))
        n = (self.depth - 2) // 6
        x = nn.Conv(16, (3, 3), padding="SAME", use_bias=False)(x)
        x = nn.relu(_Norm(self.norm)(x))
        for _ in range(n):
            x = BasicBlock(16, 1, self.norm)(x)
        return x


class ResNetHead(nn.Module):
    """Classifier on pooled trunk features (the small local head a GKT
    client distills with)."""

    num_classes: int = 10

    @nn.compact
    def __call__(self, feats):
        return nn.Dense(self.num_classes)(feats.mean(axis=(1, 2)))


class ResNetServerTail(nn.Module):
    """The 32/64-filter stages + pooled classifier of a 6n+2 CIFAR ResNet,
    applied to 16-filter feature maps.

    Doubles as the server-side GKT CNN (the reference's large server model
    that never sees raw data, only uploaded client feature maps)."""

    num_classes: int = 10
    depth: int = 56
    norm: str = "batch"

    @nn.compact
    def __call__(self, feats):
        n = (self.depth - 2) // 6
        x = feats
        for filters in (32, 64):
            for block in range(n):
                strides = 2 if block == 0 else 1
                x = BasicBlock(filters, strides, self.norm)(x)
        x = x.mean(axis=(1, 2))
        return nn.Dense(self.num_classes)(x)


class ResNetCifar(nn.Module):
    """6n+2 CIFAR ResNet (resnet.py:113: depth in {20, 56, 110}), composed
    as trunk -> tail so the full model and the GKT split share one
    definition of the stage logic."""

    num_classes: int = 10
    depth: int = 20
    norm: str = "batch"

    @nn.compact
    def __call__(self, x):
        feats = ResNetFeatures(depth=self.depth, norm=self.norm)(x)
        return ResNetServerTail(num_classes=self.num_classes,
                                depth=self.depth, norm=self.norm)(feats)


class ResNet18(nn.Module):
    """Compact ImageNet-style ResNet-18 (torchvision flavor, 2-2-2-2 blocks)."""

    num_classes: int = 10
    norm: str = "batch"

    @nn.compact
    def __call__(self, x):
        if x.ndim == 2:
            x = x.reshape((x.shape[0], 32, 32, 3))
        x = nn.Conv(64, (3, 3), padding="SAME", use_bias=False)(x)  # CIFAR stem
        x = nn.relu(_Norm(self.norm)(x))
        for stage, filters in enumerate((64, 128, 256, 512)):
            for block in range(2):
                strides = 2 if stage > 0 and block == 0 else 1
                x = BasicBlock(filters, strides, self.norm)(x)
        x = x.mean(axis=(1, 2))
        return nn.Dense(self.num_classes)(x)
