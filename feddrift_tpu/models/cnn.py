"""FedAvg CNNs (reference: fedml_api/model/cv/cnn.py:5-120).

TPU-first notes: NHWC layout (XLA's native conv layout on TPU), logits
output. The reference applies ``Softmax`` inside ``forward`` and then
``CrossEntropyLoss`` on top (cnn.py:66-68) — a double normalisation that
flattens gradients; we return logits instead, which trains the same task with
better conditioning (documented deviation).
"""

from __future__ import annotations

import flax.linen as nn


def _to_nhwc(x, side: int = 28, channels: int = 1):
    if x.ndim == 2:  # flat [B, side*side*channels]
        x = x.reshape((x.shape[0], side, side, channels))
    elif x.ndim == 3:  # [B, H, W]
        x = x[..., None]
    return x


class CNNFedAvg(nn.Module):
    """conv5x5(32) -> pool -> conv5x5(64) -> pool -> fc512 -> fc K
    (cnn.py:50-69); 1,663,370 params for 10 classes, matching the paper."""

    num_classes: int = 10

    @nn.compact
    def __call__(self, x):
        x = _to_nhwc(x)
        x = nn.Conv(32, (5, 5), padding="SAME")(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.Conv(64, (5, 5), padding="SAME")(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(512)(x))
        return nn.Dense(self.num_classes)(x)


class CNNDropout(nn.Module):
    """The dropout variant (cnn.py:71-120): conv3x3(32) -> conv3x3(64) ->
    pool -> dropout .25 -> fc128 -> dropout .5 -> fc K."""

    num_classes: int = 62

    @nn.compact
    def __call__(self, x, deterministic: bool = True):
        x = _to_nhwc(x)
        x = nn.relu(nn.Conv(32, (3, 3), padding="VALID")(x))
        x = nn.relu(nn.Conv(64, (3, 3), padding="VALID")(x))
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.Dropout(0.25, deterministic=deterministic)(x)
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(128)(x))
        x = nn.Dropout(0.5, deterministic=deterministic)(x)
        return nn.Dense(self.num_classes)(x)
