"""Synthetic drifting datasets: SEA, SINE, CIRCLE (and a hermetic MNIST stand-in).

Behavioral parity with the reference generators:

- SEA (fedml_api/data_preprocessing/sea/data_loader.py:37-82): 3 features
  uniform on [0, 10]; the label boundary is on f2 + f3 with per-concept
  thresholds {8, 9, 7, 9.5} and 10% base label noise — these values were
  verified empirically against the shipped concept CSVs
  (data/sea/concept{1-4}.csv: logistic fit gives coef ≈ [0, .5, .5] and label
  means 0.645/0.578/0.704/0.580, matching P(f2+f3 > theta) under 10% flip).
- SINE (sine/data_loader.py:37-47): 2 features uniform on [0, 1];
  concept 0: y = 1 iff x2 <= sin(x1); concept 1 flips the labels.
- CIRCLE (circle/data_loader.py:36-44): 2 features uniform on [0, 1];
  concept circles (c=(0.2,0.5), r=0.15) and (c=(0.6,0.5), r=0.25);
  y = 1 outside the circle.

All generators additionally apply the ``noise_prob`` label flip of the
reference (sea/data_loader.py:77; sine/data_loader.py add_noise), and route
concept choice per (t, c) through a change-point matrix with ``time_stretch``
dilation (sea/data_loader.py:66-73).
"""

from __future__ import annotations

import numpy as np

from feddrift_tpu.data.changepoints import concept_matrix
from feddrift_tpu.data.drift_dataset import DriftDataset

SEA_THRESHOLDS = (8.0, 9.0, 7.0, 9.5)
SEA_BASE_NOISE = 0.1


def _sea_sample(rng: np.random.Generator, n: int, concept: int) -> tuple[np.ndarray, np.ndarray]:
    x = rng.uniform(0.0, 10.0, size=(n, 3)).astype(np.float32)
    y = (x[:, 1] + x[:, 2] > SEA_THRESHOLDS[concept]).astype(np.int32)
    flip = rng.random(n) < SEA_BASE_NOISE
    y = np.where(flip, 1 - y, y)
    return x, y


def _sine_sample(rng: np.random.Generator, n: int, concept: int) -> tuple[np.ndarray, np.ndarray]:
    x = rng.random((n, 2)).astype(np.float32)
    below = x[:, 1] <= np.sin(x[:, 0])
    y = np.where(below, 1, 0) if concept == 0 else np.where(below, 0, 1)
    return x, y.astype(np.int32)


def _circle_sample(rng: np.random.Generator, n: int, concept: int) -> tuple[np.ndarray, np.ndarray]:
    x = rng.random((n, 2)).astype(np.float32)
    cx, cy, r = (0.2, 0.5, 0.15) if concept == 0 else (0.6, 0.5, 0.25)
    z = (x[:, 0] - cx) ** 2 + (x[:, 1] - cy) ** 2 - r**2
    return x, (z > 0).astype(np.int32)


_SAMPLERS = {
    "sea": (_sea_sample, 3, 2, 4),       # (fn, feature_dim, classes, concepts)
    "sine": (_sine_sample, 2, 2, 2),
    "circle": (_circle_sample, 2, 2, 2),
}


def generate_synthetic(
    name: str,
    change_points: np.ndarray,
    train_iterations: int,
    num_clients: int,
    sample_num: int,
    noise_prob: float = 0.0,
    time_stretch: int = 1,
    seed: int = 0,
    backend: str | None = None,
) -> DriftDataset:
    """Generate a full ``[C, T+1, N, F]`` drifting dataset.

    Step T (the extra slot) is the held-out test step for training step T-1,
    mirroring the reference's generation of ``train_iteration + 1`` per-step
    files (sea/data_loader.py:69).

    ``backend``: 'numpy' (default) or 'native' — the threaded C++ kernel
    (feddrift_tpu/native/drift_gen.cpp), same label rules, its own
    deterministic per-cell RNG streams. Env FEDDRIFT_NATIVE_DATA=1 makes
    native the default when the library builds.
    """
    sampler, fdim, n_classes, n_concepts = _SAMPLERS[name]
    if int(change_points.max()) >= n_concepts:
        raise ValueError(
            f"change-point matrix references concept {int(change_points.max())} "
            f"but dataset {name!r} defines only {n_concepts} concepts")
    T = train_iterations
    concepts = concept_matrix(change_points, T + 1, num_clients, time_stretch)

    if backend is None:
        import os
        backend = "native" if os.environ.get("FEDDRIFT_NATIVE_DATA") == "1" \
            else "numpy"
    if backend == "native":
        from feddrift_tpu import native
        if native.available():
            x, y = native.generate(name, concepts, sample_num, noise_prob, seed)
            return DriftDataset(x=x, y=y, num_classes=n_classes,
                                concepts=concepts, name=name)
        backend = "numpy"   # graceful fallback

    rng = np.random.default_rng(seed)
    x = np.zeros((num_clients, T + 1, sample_num, fdim), dtype=np.float32)
    y = np.zeros((num_clients, T + 1, sample_num), dtype=np.int32)
    for t in range(T + 1):
        for c in range(num_clients):
            concept = int(concepts[t, c])
            xs, ys = sampler(rng, sample_num, concept)
            if noise_prob > 0:
                flip = rng.random(sample_num) < noise_prob
                ys = np.where(flip, 1 - ys, ys)
            x[c, t], y[c, t] = xs, ys
    return DriftDataset(x=x, y=y, num_classes=n_classes, concepts=concepts, name=name)


def synthetic_feature_dim(name: str) -> int:
    return _SAMPLERS[name][1]


def synthetic_num_classes(name: str) -> int:
    return _SAMPLERS[name][2]
