"""Minimal pure-Python PNG decoder (zlib inflate + scanline unfiltering).

The reference ingests CINIC-10 as a torchvision ``ImageFolder`` tree of
32x32 PNGs (fedml_api/data_preprocessing/cinic10/data_loader.py,
datasets.py::ImageFolderTruncated). This decoder closes that format gap
with zero dependencies beyond numpy + the stdlib: 8-bit depth, gray /
RGB / RGBA / palette color types, non-interlaced — the subset CINIC-10
(and everything a CIFAR-shaped image folder produces) actually uses.
Cross-validated against PIL in tests/test_real_data.py.
"""

from __future__ import annotations

import struct
import zlib

import numpy as np

_SIGNATURE = b"\x89PNG\r\n\x1a\n"
_CHANNELS = {0: 1, 2: 3, 3: 1, 4: 2, 6: 4}   # color type -> samples/pixel


def _paeth(a: int, b: int, c: int) -> int:
    p = a + b - c
    pa, pb, pc = abs(p - a), abs(p - b), abs(p - c)
    if pa <= pb and pa <= pc:
        return a
    return b if pb <= pc else c


def _unfilter(raw: bytes, height: int, stride: int, bpp: int) -> np.ndarray:
    """Undo per-scanline filtering (PNG spec §6). Filters 0/1/2 cover what
    common encoders emit for small images and are vectorized; Average and
    Paeth carry a sequential left-dependency and fall back to a byte loop."""
    out = np.empty((height, stride), np.uint8)
    prev = np.zeros(stride, np.int64)
    pos = 0
    for r in range(height):
        ftype = raw[pos]
        line = np.frombuffer(raw, np.uint8, stride, pos + 1).astype(np.int64)
        pos += stride + 1
        if ftype == 0:                        # None
            cur = line
        elif ftype == 1:                      # Sub: prefix sum per channel
            cur = np.cumsum(line.reshape(-1, bpp), axis=0).reshape(-1) % 256
        elif ftype == 2:                      # Up
            cur = (line + prev) % 256
        elif ftype in (3, 4):                 # Average / Paeth
            cur = np.zeros(stride, np.int64)
            for i in range(stride):
                a = cur[i - bpp] if i >= bpp else 0
                b = prev[i]
                if ftype == 3:
                    cur[i] = (line[i] + (a + b) // 2) % 256
                else:
                    c = prev[i - bpp] if i >= bpp else 0
                    cur[i] = (line[i] + _paeth(int(a), int(b), int(c))) % 256
        else:
            raise ValueError(f"unknown PNG filter type {ftype}")
        out[r] = cur.astype(np.uint8)
        prev = cur
    return out


def decode_png(data: bytes) -> np.ndarray:
    """Decode one PNG byte string to a ``[H, W]`` (gray) or ``[H, W, C]``
    uint8 array. Raises ValueError on malformed or out-of-subset files."""
    if data[:8] != _SIGNATURE:
        raise ValueError("not a PNG file")
    width = height = bit_depth = color_type = interlace = None
    palette = None
    idat = []
    pos = 8
    while pos + 8 <= len(data):
        length, ctype = struct.unpack(">I4s", data[pos:pos + 8])
        chunk = data[pos + 8:pos + 8 + length]
        if len(chunk) < length:
            raise ValueError("truncated PNG chunk")
        pos += 12 + length                    # length + type + payload + crc
        if ctype == b"IHDR":
            (width, height, bit_depth, color_type,
             _comp, _filt, interlace) = struct.unpack(">IIBBBBB", chunk)
        elif ctype == b"PLTE":
            palette = np.frombuffer(chunk, np.uint8).reshape(-1, 3)
        elif ctype == b"IDAT":
            idat.append(chunk)
        elif ctype == b"IEND":
            break
    if width is None:
        raise ValueError("missing IHDR")
    if bit_depth != 8:
        raise ValueError(f"unsupported PNG bit depth {bit_depth}")
    if interlace:
        raise ValueError("interlaced PNG unsupported")
    nch = _CHANNELS.get(color_type)
    if nch is None:
        raise ValueError(f"unsupported PNG color type {color_type}")
    if not idat:
        raise ValueError("missing IDAT")
    raw = zlib.decompress(b"".join(idat))
    stride = width * nch
    if len(raw) != height * (stride + 1):
        raise ValueError("PNG pixel data size mismatch")
    px = _unfilter(raw, height, stride, nch).reshape(height, width, nch)
    if color_type == 3:                       # palette lookup -> RGB
        if palette is None:
            raise ValueError("palette PNG without PLTE")
        px = palette[px[..., 0]]
    return px[..., 0] if px.shape[-1] == 1 else px


def decode_png_rgb(data: bytes) -> np.ndarray:
    """decode_png normalized to ``[H, W, 3]``: gray is broadcast, alpha is
    dropped (torchvision's ImageFolder loads via ``Image.convert('RGB')``,
    which composites over black only for exotic modes; CINIC is plain RGB)."""
    img = decode_png(data)
    if img.ndim == 2:
        img = np.repeat(img[..., None], 3, axis=2)
    if img.shape[-1] == 4:
        img = img[..., :3]
    elif img.shape[-1] == 2:                  # gray + alpha
        img = np.repeat(img[..., :1], 3, axis=2)
    return img
