"""Client partitioning for non-drift datasets.

Re-design of the reference's partition logic shared by its CIFAR-10/100/
CINIC-10 loaders (fedml_api/data_preprocessing/cifar10/data_loader.py:
``partition_data`` — 'homo' uniform split and 'hetero' Dirichlet(alpha)
label-skew split with a minimum-size retry loop).
"""

from __future__ import annotations

import numpy as np


def partition_homo(n_samples: int, num_clients: int,
                   seed: int = 0) -> list[np.ndarray]:
    """Uniform random split of sample indices across clients."""
    rng = np.random.default_rng(seed)
    idx = rng.permutation(n_samples)
    return [np.sort(part) for part in np.array_split(idx, num_clients)]


def partition_hetero(y: np.ndarray, num_clients: int, alpha: float = 0.5,
                     min_size_floor: int = 10,
                     seed: int = 0) -> list[np.ndarray]:
    """Dirichlet(alpha) label-skew partition (data_loader.py 'hetero'):
    for each class, split its indices across clients by Dirichlet
    proportions, balanced so no client exceeds n/num_clients mid-draw;
    resample until every client has at least ``min_size_floor`` samples."""
    rng = np.random.default_rng(seed)
    n = len(y)
    classes = np.unique(y)
    min_size = 0
    while min_size < min_size_floor:
        idx_batch: list[list[int]] = [[] for _ in range(num_clients)]
        for k in classes:
            idx_k = np.where(y == k)[0]
            rng.shuffle(idx_k)
            p = rng.dirichlet(np.repeat(alpha, num_clients))
            # cap clients already at the uniform share (reference's balancing)
            p = np.array([pj * (len(b) < n / num_clients)
                          for pj, b in zip(p, idx_batch)])
            p = p / p.sum()
            cuts = (np.cumsum(p) * len(idx_k)).astype(int)[:-1]
            for b, part in zip(idx_batch, np.split(idx_k, cuts)):
                b.extend(part.tolist())
        min_size = min(len(b) for b in idx_batch)
    return [np.sort(np.asarray(b)) for b in idx_batch]


def partition_counts(y: np.ndarray, parts: list[np.ndarray],
                     num_classes: int) -> np.ndarray:
    """[C, K] label histogram per client — the reference logs this as the
    'data statistics' record (data_loader.py record_net_data_stats)."""
    return np.stack([np.bincount(y[p], minlength=num_classes) for p in parts])
