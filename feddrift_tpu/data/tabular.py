"""Tabular streaming datasets: UCI SUSY / Room-Occupancy and StackOverflow-LR.

Reference coverage (SURVEY.md §2b #35):

- UCI SUSY / RO feed the standalone decentralized online-learning experiments
  (fedml_api/data_preprocessing/UCI/data_loader_for_susy_and_ro.py: CSV rows
  -> per-client streaming {"x": [...], "y": 0/1} dicts, with a "beta" fraction
  of adversarially ordered data from KMeans clusters).
- stackoverflow_lr is bag-of-words tag prediction
  (fedml_api/data_preprocessing/stackoverflow_lr/data_loader.py: token/title
  text -> 10k-dim word-count vector, 500-way tag target, trained with a
  LogisticRegression head).

Here both become drift-composable ``DriftDataset``s (any dataset x any drift
algorithm, BASELINE.md note): real CSV/h5 files are used when present under
``data_dir``; otherwise data is synthesized hermetically with the same tensor
contract. Concepts rotate the decision boundary (UCI) or permute the
topic->tag mapping (stackoverflow_lr), so drift detectors observe real
accuracy drops at change points.

Scale note: the reference's stackoverflow vocabulary is 10000 with 500 tag
classes; dense [C, T, N, F] storage makes that ~2 GB per 10-client run, so the
default here is vocab 1000 / 50 tags — override with
``ExperimentConfig.so_vocab_size`` / ``so_tag_size`` for full scale.
"""

from __future__ import annotations

import csv
import json
import os

import numpy as np

from feddrift_tpu.data.changepoints import concept_matrix
from feddrift_tpu.data.drift_dataset import DriftDataset

UCI_SPECS = {
    # name: (feature_dim, csv filename under data_dir)
    "susy": (18, "SUSY.csv"),
    "ro": (5, "datatraining.txt"),
}


def _load_uci_csv(path: str, name: str, feature_dim: int,
                  max_rows: int) -> tuple[np.ndarray, np.ndarray] | None:
    """Reference CSV layouts: SUSY rows are [label, 18 features]; RO rows are
    [id, date, 5 features, label] (data_loader_for_susy_and_ro.py
    read_csv_file)."""
    if not os.path.exists(path):
        return None
    xs, ys = [], []
    with open(path, newline="") as f:
        reader = csv.reader(f)
        for row in reader:
            if len(xs) >= max_rows:   # count accepted rows, not raw lines —
                break                 # a skipped header must not shrink the cap
            try:
                # parse BOTH fields before appending either, so a row that
                # fails mid-parse cannot desynchronize xs from ys
                if name == "susy":
                    label = int(float(row[0]))
                    feats = [float(v) for v in row[1:1 + feature_dim]]
                else:
                    feats = [float(v) for v in row[2:2 + feature_dim]]
                    label = int(float(row[-1]))
            except (ValueError, IndexError):
                continue  # header / malformed row
            xs.append(feats)
            ys.append(label)
    if not xs:
        return None
    return (np.asarray(xs, dtype=np.float32),
            np.asarray(ys, dtype=np.int32))


def generate_uci_drift(
    name: str,
    change_points: np.ndarray,
    train_iterations: int,
    num_clients: int,
    sample_num: int,
    noise_prob: float = 0.0,
    time_stretch: int = 1,
    seed: int = 0,
    data_dir: str | None = None,
) -> DriftDataset:
    """SUSY / Room-Occupancy as a drifting binary-classification stream.

    With real CSVs the stream is sliced per (client, step) in file order —
    the reference's streaming semantics — keeping the true labels for
    concept 0; a drifted concept k flips the labels of the half-space
    ``x @ plane_k > 0``, so each concept is a genuinely different
    classification function grounded in the real task. On the synthetic
    path concept k labels by its own rotated hyperplane directly.
    """
    feature_dim, fname = UCI_SPECS[name]
    T = train_iterations
    rng = np.random.default_rng(seed)
    concepts = concept_matrix(change_points, T + 1, num_clients, time_stretch)
    n_concepts = max(int(concepts.max()) + 1, 2)
    crng = np.random.default_rng(3571)
    # Per-concept random unit normal vectors (decision hyperplanes).
    planes = crng.normal(size=(n_concepts, feature_dim)).astype(np.float32)
    planes /= np.linalg.norm(planes, axis=1, keepdims=True)

    real = None
    if data_dir:
        real = _load_uci_csv(os.path.join(data_dir, fname), name, feature_dim,
                             max_rows=num_clients * (T + 1) * sample_num)
    x = np.zeros((num_clients, T + 1, sample_num, feature_dim), np.float32)
    y = np.zeros((num_clients, T + 1, sample_num), np.int32)
    if real is not None:
        rx, ry = real
        mu, sd = rx.mean(0), rx.std(0) + 1e-6
        rx = (rx - mu) / sd
        idx = 0
        for t in range(T + 1):
            for c in range(num_clients):
                take = np.arange(idx, idx + sample_num) % len(rx)
                idx += sample_num
                xi = rx[take]
                k = int(concepts[t, c]) % n_concepts
                x[c, t] = xi
                yi = ry[take].copy()
                if k > 0:       # drift: flip labels of the k-th half-space
                    flip = xi @ planes[k] > 0
                    yi = np.where(flip, 1 - yi, yi)
                y[c, t] = yi.astype(np.int32)
    else:
        for t in range(T + 1):
            for c in range(num_clients):
                k = int(concepts[t, c]) % n_concepts
                xi = rng.normal(size=(sample_num, feature_dim)).astype(np.float32)
                x[c, t] = xi
                y[c, t] = (xi @ planes[k] > 0).astype(np.int32)
    if noise_prob > 0:
        flip = rng.random(y.shape) < noise_prob
        y = np.where(flip, 1 - y, y).astype(np.int32)
    return DriftDataset(x=x, y=y, num_classes=2, concepts=concepts,
                        name=name, meta={"source": "csv" if real is not None
                                         else "synthetic"})


def _try_load_stackoverflow_lr(
    data_dir: str, vocab_size: int, tag_size: int,
    max_samples: int = 100_000,
) -> tuple[np.ndarray, np.ndarray] | None:
    """Real TFF StackOverflow -> (bag-of-words [N, vocab], principal tag [N]).

    Layout (reference stackoverflow_lr/data_loader.py:18-26 +
    utils.py:5-25): ``stackoverflow/datasets/stackoverflow_train.h5`` with
    examples/<client>/{tokens,title,tags} byte strings;
    ``stackoverflow.word_count`` ("word count" per line, frequency-ranked);
    ``stackoverflow.tag_count`` (JSON dict, insertion-ordered by count).
    Samples whose tags all fall outside the top-``tag_size`` set are
    skipped, mirroring the reference's vectorize-on-known-tags behavior.
    """
    base = os.path.join(data_dir, "stackoverflow", "datasets")
    h5path = os.path.join(base, "stackoverflow_train.h5")
    wcpath = os.path.join(base, "stackoverflow.word_count")
    tcpath = os.path.join(base, "stackoverflow.tag_count")
    if not all(os.path.isfile(p) for p in (h5path, wcpath, tcpath)):
        return None
    from feddrift_tpu.data.text import iter_tff_clients, load_word_ranks
    word_id = {w: i for i, w in enumerate(load_word_ranks(wcpath, vocab_size))}
    with open(tcpath) as fh:
        tag_id = {t: i for i, t in enumerate(list(json.load(fh))[:tag_size])}
    import h5py
    X, Y = [], []
    with h5py.File(h5path, "r") as f:
        for ex in iter_tff_clients(f):
            if len(X) >= max_samples:   # the drift pipeline consumes only
                break                   # C*(T+1)*sample_num samples; a
                                        # bounded prefix avoids OOM on the
                                        # full ~135M-example split
            titles = ex["title"][()] if "title" in ex else [b""] * len(ex["tokens"])
            for tok, tit, tag in zip(ex["tokens"][()], titles, ex["tags"][()]):
                tags = [tag_id[t] for t in tag.decode("utf8").split("|")
                        if t in tag_id]
                if not tags:
                    continue
                vec = np.zeros(vocab_size, np.float32)
                for w in (tok.decode("utf8") + " " + tit.decode("utf8")).split():
                    if w in word_id:
                        vec[word_id[w]] += 1.0
                X.append(vec)
                Y.append(tags[0])
    if not X:
        return None
    return np.stack(X), np.asarray(Y, np.int32)


def generate_stackoverflow_lr_drift(
    change_points: np.ndarray,
    train_iterations: int,
    num_clients: int,
    sample_num: int,
    noise_prob: float = 0.0,
    time_stretch: int = 1,
    seed: int = 0,
    vocab_size: int = 1000,
    tag_size: int = 50,
    data_dir: str = "./data",
) -> DriftDataset:
    """Bag-of-words tag prediction under drift.

    Real TFF StackOverflow files under ``data_dir`` are used when present
    (word-count vectors over the frequency-ranked vocabulary, principal-tag
    target). Hermetic fallback: each tag class has a sparse topic
    distribution over the vocabulary; a sample is a word-count vector of
    ~30 tokens drawn from its tag's topic (the reference's
    preprocess_inputs word-count vectors, stackoverflow_lr/utils.py). In
    both cases a concept permutes the tag assignment, the bag-of-words
    analog of the MNIST label-swap drift. The reference's multi-hot
    multi-tag target is reduced to the principal tag so the dataset
    composes with the framework's single-label drift pipeline.
    """
    T = train_iterations
    rng = np.random.default_rng(seed)
    concepts = concept_matrix(change_points, T + 1, num_clients, time_stretch)
    n_concepts = max(int(concepts.max()) + 1, 2)

    real = _try_load_stackoverflow_lr(data_dir, vocab_size, tag_size)
    if real is not None:
        rx, ry = real
        trng = np.random.default_rng(7793)
        perms = np.stack(
            [np.arange(tag_size)] +
            [trng.permutation(tag_size) for _ in range(n_concepts - 1)])
        x = np.zeros((num_clients, T + 1, sample_num, vocab_size), np.float32)
        y = np.zeros((num_clients, T + 1, sample_num), np.int32)
        used = 0
        for t in range(T + 1):
            for c in range(num_clients):
                k = int(concepts[t, c]) % n_concepts
                take = np.arange(used, used + sample_num) % len(rx)
                used = (used + sample_num) % len(rx)
                x[c, t] = rx[take]
                y[c, t] = perms[k][ry[take]]
        if noise_prob > 0:
            flip = rng.random(y.shape) < noise_prob
            y = np.where(flip, rng.integers(0, tag_size, size=y.shape),
                         y).astype(np.int32)
        return DriftDataset(x=x, y=y, num_classes=tag_size, concepts=concepts,
                            name="stackoverflow_lr",
                            meta={"real_data": True})

    trng = np.random.default_rng(7793)
    # Per-tag topic: a peaked distribution over 20 signature words + noise.
    topics = np.full((tag_size, vocab_size), 0.05 / vocab_size, np.float64)
    for k in range(tag_size):
        sig = trng.choice(vocab_size, size=20, replace=False)
        topics[k, sig] += 0.95 / 20
    topics /= topics.sum(axis=1, keepdims=True)
    # Per-concept tag permutation (concept 0 = identity).
    perms = np.stack([np.arange(tag_size)] +
                     [trng.permutation(tag_size) for _ in range(n_concepts - 1)])

    x = np.zeros((num_clients, T + 1, sample_num, vocab_size), np.float32)
    y = np.zeros((num_clients, T + 1, sample_num), np.int32)
    for t in range(T + 1):
        for c in range(num_clients):
            k = int(concepts[t, c]) % n_concepts
            tags = rng.integers(0, tag_size, size=sample_num)
            for i, tag in enumerate(tags):
                words = rng.choice(vocab_size, size=30, p=topics[tag])
                np.add.at(x[c, t, i], words, 1.0)
            y[c, t] = perms[k][tags].astype(np.int32)
    if noise_prob > 0:
        flip = rng.random(y.shape) < noise_prob
        y = np.where(flip, rng.integers(0, tag_size, size=y.shape), y)
        y = y.astype(np.int32)
    return DriftDataset(x=x, y=y, num_classes=tag_size, concepts=concepts,
                        name="stackoverflow_lr")
