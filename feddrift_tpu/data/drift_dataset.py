"""DriftDataset: the TPU-native representation of drifting federated data.

The reference materialises one CSV per (client, time step)
(``client_{c}_iter_{t}.csv``, sea/data_loader.py:69-82) and re-reads them from
disk in every MPI process. Here the whole simulation's data is a pair of dense
arrays with static shapes — ideal for XLA:

    x: [C, T+1, N, ...]   features  (T+1: step T is the final held-out test step)
    y: [C, T+1, N]        int32 labels

Per-(t, c) sample counts are constant (``sample_num``, reference default 500,
run_fedavg_distributed_pytorch.sh:15), so no padding/ragged handling is needed.
Test data for training step t is step t+1 (temporal holdout, retrain.py:78-83).
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np


@dataclass
class DriftDataset:
    x: np.ndarray                # [C, T+1, N, *feature_shape] float32
    y: np.ndarray                # [C, T+1, N] int32
    num_classes: int
    concepts: np.ndarray         # [T+1, C] concept id per (step, client)
    name: str = "synthetic"
    # Optional sequence data flag (inputs are int token ids rather than floats)
    is_sequence: bool = False
    meta: dict = field(default_factory=dict)

    def __post_init__(self) -> None:
        assert self.x.shape[:3] == self.y.shape, (self.x.shape, self.y.shape)
        assert self.concepts.shape[0] == self.x.shape[1]

    @property
    def num_clients(self) -> int:
        return self.x.shape[0]

    @property
    def num_steps(self) -> int:
        """Number of *training* time steps T (last array slot is test-only)."""
        return self.x.shape[1] - 1

    @property
    def samples_per_step(self) -> int:
        return self.x.shape[2]

    @property
    def feature_shape(self) -> tuple[int, ...]:
        return self.x.shape[3:]

    @property
    def flat_feature_dim(self) -> int:
        return int(np.prod(self.feature_shape)) if self.feature_shape else 1

    def train_slice(self, t: int) -> tuple[np.ndarray, np.ndarray]:
        """Data of training step t across clients: ([C, N, ...], [C, N])."""
        return self.x[:, t], self.y[:, t]

    def test_slice(self, t: int) -> tuple[np.ndarray, np.ndarray]:
        """Temporal-holdout test data for step t = data of step t+1."""
        return self.x[:, t + 1], self.y[:, t + 1]
