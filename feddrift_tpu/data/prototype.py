"""Class-prototype image datasets with label-swap concept drift.

The reference's MNIST drift pipeline simulates concept drift by *label
swapping*: concept 1 swaps labels 1<->2, concept 2 swaps 3<->4, concept 3
swaps 5<->6 (fedml_api/data_preprocessing/MNIST/data_loader_cont.py:179-214).
The underlying images come from files that must be downloaded; in a hermetic
environment we synthesize class-conditional images instead (see
``PrototypeSampler``). This preserves the *learning problem structure* the
drift algorithms see — a classification task whose label semantics change at
change points — with identical tensor shapes (MNIST 784, FEMNIST 784/62-way,
CIFAR-10 32x32x3).

Real files under ``data_dir`` are used instead of prototypes when present:

- ``MNIST/train/*.json`` — LEAF JSON (reference MNIST/data_loader_cont.py);
- ``FederatedEMNIST/emnist_train.h5`` — TFF h5, pixels/label/id
  (reference FederatedEMNIST/data_loader.py:16-33);
- ``fed_cifar100/cifar100_train.h5`` — TFF h5, image/label/id
  (reference fed_cifar100/data_loader.py:15-32);
- ``cifar-10-batches-py/data_batch_{1..5}`` / ``cifar-100-python/train`` —
  the standard CIFAR python pickle batches torchvision downloads (the
  reference loads CIFAR via torchvision, cifar10/data_loader.py:104).

- ``cinic10/train/<class>/*.png`` — the torchvision-ImageFolder tree the
  reference mounts for CINIC-10 (cinic10/data_loader.py,
  datasets.py::ImageFolderTruncated), decoded by the bundled pure-Python
  PNG reader (``feddrift_tpu/data/png.py``); class index = sorted
  class-directory order, exactly ImageFolder's rule.
"""

from __future__ import annotations

import json
import os

import numpy as np

from feddrift_tpu.data.changepoints import concept_matrix
from feddrift_tpu.data.drift_dataset import DriftDataset

# Reference label swaps per concept id (data_loader_cont.py:188-201).
_LABEL_SWAPS = {1: (1, 2), 2: (3, 4), 3: (5, 6)}

SPECS = {
    # name: (feature_shape, num_classes)
    "MNIST": ((784,), 10),
    "femnist": ((784,), 62),
    "cifar10": ((32, 32, 3), 10),
    "cifar100": ((32, 32, 3), 100),
    "cinic10": ((32, 32, 3), 10),
    "fed_cifar100": ((32, 32, 3), 100),
}


def apply_label_swap(y: np.ndarray, concept: int, num_classes: int) -> np.ndarray:
    """Swap the concept's label pair; identity for concept 0 / unknown pairs."""
    if concept == 0:
        return y
    a, b = _LABEL_SWAPS.get(concept, ((2 * concept - 1) % num_classes,
                                      (2 * concept) % num_classes))
    out = y.copy()
    out[y == a] = b
    out[y == b] = a
    return out


def _spatial_dims(feature_shape: tuple[int, ...]) -> tuple[int, int] | None:
    """(H, W) of the image grid, or None when the shape has no 2D layout.

    Flat shapes like MNIST's (784,) are square images stored flattened
    (28x28); non-square flat shapes have no spatial structure to smooth.
    """
    if len(feature_shape) >= 2:
        return feature_shape[0], feature_shape[1]
    side = int(round(feature_shape[0] ** 0.5))
    return (side, side) if side * side == feature_shape[0] else None


def _smooth_rows(rows: np.ndarray, feature_shape: tuple[int, ...],
                 sigma: float) -> np.ndarray:
    """Gaussian-smooth each row over the image grid (channels untouched)."""
    hw = _spatial_dims(feature_shape)
    if hw is None or sigma <= 0:
        return rows
    from scipy.ndimage import gaussian_filter
    h, w = hw
    rest = int(np.prod(feature_shape)) // (h * w)   # channels (1 for flat)
    shaped = rows.reshape(-1, h, w, rest)
    # sigma 0 on the row and channel axes: smooth the image grid only
    out = gaussian_filter(shaped, sigma=(0, sigma, sigma, 0), mode="wrap")
    return out.reshape(rows.shape)


class PrototypeSampler:
    """Class-conditional sampler: low-rank class structure + strong noise.

    Round-2 finding: independent full-dimensional random prototypes are
    nearly linearly separable at any noise level (pairwise prototype
    distance grows with sqrt(D)), so conv runs saturated at Test/Acc 1.0
    and accuracy comparisons were meaningless. Classes now live in a
    shared ``rank``-dimensional subspace, separated by coefficient offsets
    of scale ``sep`` against sample noise of scale ``noise_scale`` — the
    class-distance/noise ratio no longer grows with image size, the Bayes
    accuracy is strictly below 1, and harder datasets (62/100 classes in
    the same subspace) are genuinely harder, qualitatively matching real
    MNIST < FEMNIST < CIFAR difficulty ordering.

    Round-4 finding: with a WHITE-NOISE basis the class signal is a global
    rank-``rank`` projection with no local spatial structure, which conv
    models cannot learn at any budget (a linear probe reaches 0.43 on
    femnist-62 while CNNFedAvg stays at chance — BASELINE.md probe).
    ``smooth_sigma > 0`` Gaussian-smooths each basis field over the image
    grid before normalisation, concentrating the class signal in low
    spatial frequencies: per-pixel sample noise stays white, so local
    averaging (exactly what conv + pooling stacks compute) raises the
    in-subspace SNR and the task becomes conv-learnable while the
    subspace geometry — and therefore the linear-probe ceiling
    calibration — is unchanged (the smoothed rows are renormalised, so
    noise projected onto each basis direction keeps std
    ``noise_scale``).
    """

    def __init__(self, feature_shape: tuple[int, ...], num_classes: int,
                 noise_scale: float = 0.8, sep: float = 0.7, rank: int = 16,
                 proto_seed: int = 1234, smooth_sigma: float = 0.0) -> None:
        # sep=0.7 calibration (subspace linear probe, 8k train samples):
        # MNIST-10 ~0.89, femnist-62 ~0.60, cifar10 ~0.86, cifar100 ~0.34
        # — below ceiling, above chance, ordered by class count.
        self.feature_shape = feature_shape
        self.num_classes = num_classes
        self.noise_scale = noise_scale
        self.smooth_sigma = smooth_sigma
        proto_rng = np.random.default_rng(proto_seed)
        dim = int(np.prod(feature_shape))
        basis = proto_rng.normal(size=(rank, dim))
        basis = _smooth_rows(basis, feature_shape, smooth_sigma)
        basis /= np.linalg.norm(basis, axis=1, keepdims=True)
        coef = proto_rng.normal(size=(num_classes, rank)) * sep
        self.prototypes = (0.5 + coef @ basis).reshape(
            num_classes, *feature_shape).astype(np.float32)

    def sample(self, rng: np.random.Generator, n: int) -> tuple[np.ndarray, np.ndarray]:
        y = rng.integers(0, self.num_classes, size=n).astype(np.int32)
        x = self.prototypes[y] + rng.normal(0.0, self.noise_scale,
                                            size=(n, *self.feature_shape)).astype(np.float32)
        return x.astype(np.float32), y


def _try_load_leaf_mnist(data_dir: str) -> tuple[np.ndarray, np.ndarray] | None:
    """Load LEAF-format MNIST train JSON if present (data_loader_cont.py:152-171)."""
    train_path = os.path.join(data_dir, "MNIST", "train")
    if not os.path.isdir(train_path):
        return None
    X, Y = [], []
    for f in sorted(os.listdir(train_path)):
        if not f.endswith(".json"):
            continue
        with open(os.path.join(train_path, f)) as fh:
            d = json.load(fh)
        for u in d["users"]:
            X.extend(d["user_data"][u]["x"])
            Y.extend(d["user_data"][u]["y"])
    if not X:
        return None
    nX = np.asarray(X, dtype=np.float32)
    nY = np.asarray(Y, dtype=np.int32)
    rng = np.random.default_rng(100)  # fixed shuffle seed as reference :168
    perm = rng.permutation(len(nX))
    return nX[perm], nY[perm]


def _try_load_tff_h5(path: str, x_key: str,
                     feature_shape: tuple[int, ...],
                     max_samples: int = 200_000,
                     ) -> tuple[np.ndarray, np.ndarray] | None:
    """Load a flat TFF-style image h5 (datasets ``<x_key>``/``label``/``id``).

    Covers the reference's FederatedEMNIST layout (pixels/label/id,
    FederatedEMNIST/data_loader.py:16-33) and fed_cifar100 layout
    (image/label/id, fed_cifar100/data_loader.py:15-32). The per-sample
    ``id`` client ownership is intentionally not used: the drift pipeline
    re-partitions by (client, time step) with its own change-point matrix,
    the same way the MNIST LEAF loader pools users before slicing. Only a
    ``max_samples`` prefix is read (h5 slicing never materializes the rest)
    — downstream consumes C*(T+1)*sample_num samples, and the full
    FederatedEMNIST split would be several float32 GB.
    """
    if not os.path.isfile(path):
        return None
    import h5py
    with h5py.File(path, "r") as f:
        if x_key not in f or "label" not in f:
            return None
        X = np.asarray(f[x_key][:max_samples], np.float32)
        Y = np.asarray(f["label"][:max_samples], np.int32)
    if X.size == 0:
        return None
    if X.max() > 1.5:              # uint8-encoded images -> [0, 1]
        X = X / 255.0
    X = X.reshape(len(X), *feature_shape)
    rng = np.random.default_rng(100)   # same fixed shuffle as LEAF MNIST
    perm = rng.permutation(len(X))
    return X[perm], Y[perm]


def _try_load_cifar_batches(data_dir: str, name: str
                            ) -> tuple[np.ndarray, np.ndarray] | None:
    """Load standard CIFAR python pickle batches (the layout torchvision's
    ``CIFAR10(download=True)`` produces, which is how the reference obtains
    CIFAR, cifar10/data_loader.py:104): ``cifar-10-batches-py/
    data_batch_{1..5}`` with b"data" [N, 3072] uint8 (CHW row-major) +
    b"labels"; ``cifar-100-python/train`` with b"fine_labels"."""
    import pickle
    if name == "cifar10":
        d = os.path.join(data_dir, "cifar-10-batches-py")
        files = [f"data_batch_{i}" for i in range(1, 6)]
        label_key = b"labels"
    else:
        d = os.path.join(data_dir, "cifar-100-python")
        files = ["train"]
        label_key = b"fine_labels"
    if not os.path.isdir(d):
        return None
    X, Y = [], []
    for fn in files:
        p = os.path.join(d, fn)
        if not os.path.isfile(p):
            continue
        with open(p, "rb") as fh:
            batch = pickle.load(fh, encoding="bytes")
        X.append(np.asarray(batch[b"data"], np.uint8))
        Y.extend(int(v) for v in batch[label_key])
    if not X:
        return None
    flat = np.concatenate(X).reshape(-1, 3, 32, 32)
    imgs = (flat.transpose(0, 2, 3, 1) / 255.0).astype(np.float32)
    rng = np.random.default_rng(100)   # same fixed shuffle as LEAF MNIST
    perm = rng.permutation(len(imgs))
    return imgs[perm], np.asarray(Y, np.int32)[perm]


def _try_load_image_folder(data_dir: str, feature_shape: tuple[int, ...]
                           ) -> tuple[np.ndarray, np.ndarray] | None:
    """Load a torchvision-ImageFolder PNG tree (the reference's CINIC-10
    layout, cinic10/data_loader.py): ``cinic10/train/<class>/*.png`` with
    class index assigned by sorted class-directory name. Non-PNG files are
    ignored; a PNG whose decoded shape doesn't match the dataset spec is a
    hard error (silent resizing would corrupt accuracy comparisons).

    Preprocessing diverges from the reference pipeline on purpose: images
    are served scaled to [0, 1] (this repo's convention for every image
    family), while the reference normalizes per channel with the CINIC
    mean/std and applies random crop + horizontal flip augmentation
    (cinic10/data_loader.py:82-143). Accuracy comparisons against
    reference CINIC-10 numbers are therefore NOT apples-to-apples — see
    PARITY.md's CINIC-10 note."""
    from feddrift_tpu.data.png import decode_png_rgb

    root = os.path.join(data_dir, "cinic10", "train")
    if not os.path.isdir(root):
        return None
    classes = sorted(d for d in os.listdir(root)
                     if os.path.isdir(os.path.join(root, d)))
    X, Y = [], []
    for ci, cls in enumerate(classes):
        d = os.path.join(root, cls)
        for fn in sorted(os.listdir(d)):
            if not fn.lower().endswith(".png"):
                continue
            with open(os.path.join(d, fn), "rb") as fh:
                img = decode_png_rgb(fh.read())
            if img.shape != feature_shape:
                raise ValueError(
                    f"{os.path.join(cls, fn)}: decoded shape {img.shape} != "
                    f"dataset spec {feature_shape}")
            X.append(img)
            Y.append(ci)
    if not X:
        return None
    imgs = (np.stack(X) / 255.0).astype(np.float32)
    rng = np.random.default_rng(100)   # same fixed shuffle as the others
    perm = rng.permutation(len(imgs))
    return imgs[perm], np.asarray(Y, np.int32)[perm]


def generate_prototype_drift(
    name: str,
    change_points: np.ndarray,
    train_iterations: int,
    num_clients: int,
    sample_num: int,
    noise_prob: float = 0.0,
    time_stretch: int = 1,
    seed: int = 0,
    data_dir: str = "./data",
    smooth_sigma: float = 0.0,
) -> DriftDataset:
    feature_shape, num_classes = SPECS[name]
    rng = np.random.default_rng(seed)
    T = train_iterations

    real: tuple[np.ndarray, np.ndarray] | None = None
    if smooth_sigma > 0:
        # The -smooth task family is ALWAYS the synthetic smoothed-basis
        # sampler, even when real files are mounted: it exists to give conv
        # models a controlled, reproducible synthetic benchmark (the
        # white-noise basis is conv-unlearnable, real digits are the only
        # other conv evidence source), and silently swapping in real data
        # would change the task under the same name.
        pass
    elif name == "MNIST":
        real = _try_load_leaf_mnist(data_dir)
    elif name == "femnist":
        real = _try_load_tff_h5(
            os.path.join(data_dir, "FederatedEMNIST", "emnist_train.h5"),
            "pixels", feature_shape)
    elif name == "fed_cifar100":
        real = _try_load_tff_h5(
            os.path.join(data_dir, "fed_cifar100", "cifar100_train.h5"),
            "image", feature_shape)
    elif name in ("cifar10", "cifar100"):
        real = _try_load_cifar_batches(data_dir, name)
    elif name == "cinic10":
        real = _try_load_image_folder(data_dir, feature_shape)
    sampler = PrototypeSampler(feature_shape, num_classes,
                               smooth_sigma=smooth_sigma)
    used = 0

    x = np.zeros((num_clients, T + 1, sample_num, *feature_shape), dtype=np.float32)
    y = np.zeros((num_clients, T + 1, sample_num), dtype=np.int32)
    concepts = concept_matrix(change_points, T + 1, num_clients, time_stretch)
    for t in range(T + 1):
        for c in range(num_clients):
            concept = int(concepts[t, c])
            if real is not None:
                rx, ry = real
                if used + sample_num > len(rx):  # wrap when exhausted (:181)
                    used = 0
                take = np.arange(used, used + sample_num) % len(rx)
                xs = rx[take].reshape(sample_num, *feature_shape)
                ys = ry[take].copy()
                used = (used + sample_num) % len(rx)
            else:
                xs, ys = sampler.sample(rng, sample_num)
            ys = apply_label_swap(ys, concept, num_classes)
            if noise_prob > 0:
                flip = rng.random(sample_num) < noise_prob
                ys = np.where(flip, (ys + 1) % num_classes, ys)
            x[c, t], y[c, t] = xs, ys
    meta = {"real_data": real is not None}
    if smooth_sigma > 0:
        meta["smooth_sigma"] = smooth_sigma
    return DriftDataset(x=x, y=y, num_classes=num_classes, concepts=concepts, name=name,
                        meta=meta)
