"""Class-prototype image datasets with label-swap concept drift.

The reference's MNIST drift pipeline simulates concept drift by *label
swapping*: concept 1 swaps labels 1<->2, concept 2 swaps 3<->4, concept 3
swaps 5<->6 (fedml_api/data_preprocessing/MNIST/data_loader_cont.py:179-214).
The underlying images come from LEAF-format JSON that must be downloaded; in a
hermetic environment we synthesize class-conditional images instead: each
class has a fixed random prototype image (seeded independently of the
experiment seed) and samples are prototype + Gaussian noise. This preserves
the *learning problem structure* the drift algorithms see — a classification
task whose label semantics change at change points — with identical tensor
shapes (MNIST 784, FEMNIST 784/62-way, CIFAR-10 32x32x3).

If real data is available at ``data_dir`` (LEAF JSON for MNIST/FEMNIST, numpy
batches for CIFAR), it is used instead of prototypes.
"""

from __future__ import annotations

import json
import os

import numpy as np

from feddrift_tpu.data.changepoints import concept_matrix
from feddrift_tpu.data.drift_dataset import DriftDataset

# Reference label swaps per concept id (data_loader_cont.py:188-201).
_LABEL_SWAPS = {1: (1, 2), 2: (3, 4), 3: (5, 6)}

SPECS = {
    # name: (feature_shape, num_classes)
    "MNIST": ((784,), 10),
    "femnist": ((784,), 62),
    "cifar10": ((32, 32, 3), 10),
    "cifar100": ((32, 32, 3), 100),
    "cinic10": ((32, 32, 3), 10),
    "fed_cifar100": ((32, 32, 3), 100),
}


def apply_label_swap(y: np.ndarray, concept: int, num_classes: int) -> np.ndarray:
    """Swap the concept's label pair; identity for concept 0 / unknown pairs."""
    if concept == 0:
        return y
    a, b = _LABEL_SWAPS.get(concept, ((2 * concept - 1) % num_classes,
                                      (2 * concept) % num_classes))
    out = y.copy()
    out[y == a] = b
    out[y == b] = a
    return out


class PrototypeSampler:
    """Class-conditional sampler: fixed per-class prototypes + noise."""

    def __init__(self, feature_shape: tuple[int, ...], num_classes: int,
                 noise_scale: float = 0.35, proto_seed: int = 1234) -> None:
        self.feature_shape = feature_shape
        self.num_classes = num_classes
        self.noise_scale = noise_scale
        proto_rng = np.random.default_rng(proto_seed)
        # Prototypes in [0, 1], smoothed to look image-like enough for convs.
        self.prototypes = proto_rng.random((num_classes, *feature_shape)).astype(np.float32)

    def sample(self, rng: np.random.Generator, n: int) -> tuple[np.ndarray, np.ndarray]:
        y = rng.integers(0, self.num_classes, size=n).astype(np.int32)
        x = self.prototypes[y] + rng.normal(0.0, self.noise_scale,
                                            size=(n, *self.feature_shape)).astype(np.float32)
        return x.astype(np.float32), y


def _try_load_leaf_mnist(data_dir: str) -> tuple[np.ndarray, np.ndarray] | None:
    """Load LEAF-format MNIST train JSON if present (data_loader_cont.py:152-171)."""
    train_path = os.path.join(data_dir, "MNIST", "train")
    if not os.path.isdir(train_path):
        return None
    X, Y = [], []
    for f in sorted(os.listdir(train_path)):
        if not f.endswith(".json"):
            continue
        with open(os.path.join(train_path, f)) as fh:
            d = json.load(fh)
        for u in d["users"]:
            X.extend(d["user_data"][u]["x"])
            Y.extend(d["user_data"][u]["y"])
    if not X:
        return None
    nX = np.asarray(X, dtype=np.float32)
    nY = np.asarray(Y, dtype=np.int32)
    rng = np.random.default_rng(100)  # fixed shuffle seed as reference :168
    perm = rng.permutation(len(nX))
    return nX[perm], nY[perm]


def _try_load_tff_h5(path: str, x_key: str,
                     feature_shape: tuple[int, ...]
                     ) -> tuple[np.ndarray, np.ndarray] | None:
    """Load a flat TFF-style image h5 (datasets ``<x_key>``/``label``/``id``).

    Covers the reference's FederatedEMNIST layout (pixels/label/id,
    FederatedEMNIST/data_loader.py:16-33) and fed_cifar100 layout
    (image/label/id, fed_cifar100/data_loader.py:15-32). The per-sample
    ``id`` client ownership is intentionally not used: the drift pipeline
    re-partitions by (client, time step) with its own change-point matrix,
    the same way the MNIST LEAF loader pools users before slicing.
    """
    if not os.path.isfile(path):
        return None
    import h5py
    with h5py.File(path, "r") as f:
        if x_key not in f or "label" not in f:
            return None
        X = np.asarray(f[x_key][()], np.float32)
        Y = np.asarray(f["label"][()], np.int32)
    if X.size == 0:
        return None
    if X.max() > 1.5:              # uint8-encoded images -> [0, 1]
        X = X / 255.0
    X = X.reshape(len(X), *feature_shape)
    rng = np.random.default_rng(100)   # same fixed shuffle as LEAF MNIST
    perm = rng.permutation(len(X))
    return X[perm], Y[perm]


def generate_prototype_drift(
    name: str,
    change_points: np.ndarray,
    train_iterations: int,
    num_clients: int,
    sample_num: int,
    noise_prob: float = 0.0,
    time_stretch: int = 1,
    seed: int = 0,
    data_dir: str = "./data",
) -> DriftDataset:
    feature_shape, num_classes = SPECS[name]
    rng = np.random.default_rng(seed)
    T = train_iterations

    real: tuple[np.ndarray, np.ndarray] | None = None
    if name == "MNIST":
        real = _try_load_leaf_mnist(data_dir)
    elif name == "femnist":
        real = _try_load_tff_h5(
            os.path.join(data_dir, "FederatedEMNIST", "emnist_train.h5"),
            "pixels", feature_shape)
    elif name == "fed_cifar100":
        real = _try_load_tff_h5(
            os.path.join(data_dir, "fed_cifar100", "cifar100_train.h5"),
            "image", feature_shape)
    sampler = PrototypeSampler(feature_shape, num_classes)
    used = 0

    x = np.zeros((num_clients, T + 1, sample_num, *feature_shape), dtype=np.float32)
    y = np.zeros((num_clients, T + 1, sample_num), dtype=np.int32)
    concepts = concept_matrix(change_points, T + 1, num_clients, time_stretch)
    for t in range(T + 1):
        for c in range(num_clients):
            concept = int(concepts[t, c])
            if real is not None:
                rx, ry = real
                if used + sample_num > len(rx):  # wrap when exhausted (:181)
                    used = 0
                take = np.arange(used, used + sample_num) % len(rx)
                xs = rx[take].reshape(sample_num, *feature_shape)
                ys = ry[take].copy()
                used = (used + sample_num) % len(rx)
            else:
                xs, ys = sampler.sample(rng, sample_num)
            ys = apply_label_swap(ys, concept, num_classes)
            if noise_prob > 0:
                flip = rng.random(sample_num) < noise_prob
                ys = np.where(flip, (ys + 1) % num_classes, ys)
            x[c, t], y[c, t] = xs, ys
    return DriftDataset(x=x, y=y, num_classes=num_classes, concepts=concepts, name=name,
                        meta={"real_data": real is not None})
