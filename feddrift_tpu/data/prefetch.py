"""Host->device prefetching for datasets that exceed device memory.

The drift pipeline keeps the whole ``[C, T1, N, ...]`` simulation on device
(data/drift_dataset.py) — the right call for the reference's scales (500
samples x 10 clients x 10 steps). Real FMoW-sized image sets outgrow HBM;
the reference answers that with per-process torch DataLoaders re-reading CSV
partitions from disk every iteration (fmow/data_loader.py:63-103,
SURVEY.md §7: "host data loading is the bottleneck"). The TPU-native answer
is a grain/tf.data-style background prefetcher: while the device trains on
time step t, the host stages step t+1 into device memory, so the transfer
hides behind compute instead of serializing with it.

``prefetch_to_device`` is the generic primitive; ``TimeStepStream`` applies
it to a host-resident DriftDataset, yielding client-sharded (x_t, y_t)
slices one step ahead of consumption.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Callable, Iterable, Iterator, Optional

import jax


class _End:
    pass


_END = _End()


def prefetch_to_device(it: Iterable[Any], size: int = 2,
                       place: Optional[Callable[[Any], Any]] = None
                       ) -> Iterator[Any]:
    """Iterate ``it`` with up to ``size`` elements staged onto device ahead
    of the consumer.

    ``place`` maps a host element to its device placement (default:
    ``jax.device_put``); it runs on the background thread, so the consumer
    overlaps device transfer with whatever it is doing — jax device puts are
    async, the consumer only blocks when it actually uses the array.
    Exceptions in the source iterator or placement propagate to the consumer
    at the point of the failing element; the background thread is a daemon
    and dies with the process on early abandonment.
    """
    if size < 1:
        raise ValueError(f"size must be >= 1, got {size}")
    put = place if place is not None else jax.device_put
    buf: queue.Queue = queue.Queue(maxsize=size)
    stop = threading.Event()

    def bounded_put(item) -> bool:
        """Put unless the consumer closed the iterator; True if delivered."""
        while not stop.is_set():
            try:
                buf.put(item, timeout=0.2)
                return True
            except queue.Full:
                continue
        return False

    def producer() -> None:
        try:
            for item in it:
                if stop.is_set() or not bounded_put(put(item)):
                    return
        except BaseException as e:           # noqa: BLE001 — re-raised below
            bounded_put(e)
            return
        bounded_put(_END)

    threading.Thread(target=producer, daemon=True).start()

    # Generator close() (or abandonment) sets the stop event via the finally
    # below, so the producer exits instead of blocking forever with staged
    # device buffers pinned.
    try:
        while True:
            item = buf.get()
            if isinstance(item, _End):
                return
            if isinstance(item, BaseException):
                raise item
            yield item
    finally:
        stop.set()


class _Staged:
    """A completed staging job: ``value`` is the staging fn's return,
    ``meta`` whatever the submitter attached (e.g. the cohort member ids
    the staged arrays were gathered for)."""

    __slots__ = ("value", "meta")

    def __init__(self, value, meta) -> None:
        self.value = value
        self.meta = meta


class AsyncStager:
    """Multi-slot background staging pipeline for cohort H2D.

    The runner submits gather+device_put closures keyed by iteration tag;
    by the time the driver loop (or the megastep plan loop) reaches
    iteration t the shards are (usually) already resident and ``take``
    returns instantly. Slots are independent, so a K-step megastep block
    can keep up to K gathers in flight — each plan step submits the next
    step's gather and the last one overlaps the whole fused device
    dispatch. One worker thread: gathers execute strictly in submission
    order, which is also registry-draw order, so device_put traffic never
    reorders against the bookkeeping that produced it.

    How deep the pipeline actually runs is the RUNNER's call, not this
    class's: each draw mutates the registry (churn) and reads
    failure-detector state the previous step updates, so the runner only
    submits a tag once that step's bookkeeping has committed.

    ``take(tag)`` pops and returns the staged ``.value``/``.meta`` holder
    for ``tag`` (blocking until the background fn finishes), or None when
    the tag was never staged — the caller falls back to inline staging, so
    a miss costs only the overlap, never correctness. Exceptions in the
    staging fn surface at ``take`` (future.result()).
    """

    def __init__(self, depth: int = 1) -> None:
        import concurrent.futures
        self._pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="cohort-stager")
        self.depth = max(1, int(depth))
        self._slots: dict = {}   # tag -> (future, meta)

    def submit(self, tag, fn: Callable[[], Any], meta: Any = None) -> None:
        """Stage ``fn()`` on the worker thread, keyed by ``tag``.
        Re-submitting a tag overwrites its unclaimed slot; when the
        pipeline is full the oldest unclaimed slot is dropped (its device
        buffers are simply freed — jax puts are async and unpinned once
        unreferenced)."""
        self._slots.pop(tag, None)
        while len(self._slots) >= self.depth:
            self._slots.pop(next(iter(self._slots)))

        def staged():
            # worker-thread ledger accounting: gather+put wall into the
            # "stager" subsystem, staged shard footprint as host bytes
            # (HostLedger is thread-safe; overhead is two perf_counter
            # calls per staged iteration)
            import time

            from feddrift_tpu.obs import hostprof
            t0 = time.perf_counter()
            out = fn()
            ledger = hostprof.ledger()
            ledger.add_seconds("stager", time.perf_counter() - t0)
            ledger.set_bytes("staged_shards", hostprof.nbytes_of(out))
            return out

        self._slots[tag] = (self._pool.submit(staged), meta)

    def has(self, tag) -> bool:
        """True when ``tag`` is staged (possibly still in flight)."""
        return tag in self._slots

    def take(self, tag) -> Optional[_Staged]:
        """Pop ``tag``'s slot if staged; None otherwise."""
        slot = self._slots.pop(tag, None)
        if slot is None:
            return None
        fut, meta = slot
        return _Staged(fut.result(), meta)

    def close(self) -> None:
        self._slots.clear()
        self._pool.shutdown(wait=False)


class TimeStepStream:
    """Client-sharded (x_t, y_t) device slices of a HOST-resident dataset,
    prefetched one time step ahead.

    For experiments whose data cannot live on device whole. Composes with
    window-style algorithms (win-N with small N); horizon-weighted algorithms
    (softcluster 'all', exp/lin) need the full past on device and should keep
    the resident layout.
    """

    def __init__(self, ds, mesh, size: int = 2) -> None:
        from feddrift_tpu.parallel.mesh import client_sharding

        self.ds = ds
        self._shx = client_sharding(mesh, ds.x[:, 0].ndim)
        self._shy = client_sharding(mesh, ds.y[:, 0].ndim)
        self.size = size

    def _place(self, step_arrays):
        x_t, y_t = step_arrays
        return (jax.device_put(x_t, self._shx), jax.device_put(y_t, self._shy))

    def steps(self, start: int = 0, stop: Optional[int] = None
              ) -> Iterator[tuple]:
        """Yield device-placed (x_t, y_t) for t in [start, stop)."""
        stop = self.ds.x.shape[1] if stop is None else stop

        def host_slices():
            for t in range(start, stop):
                yield (self.ds.x[:, t], self.ds.y[:, t])

        return prefetch_to_device(host_slices(), size=self.size,
                                  place=self._place)
