"""Host->device prefetching for datasets that exceed device memory.

The drift pipeline keeps the whole ``[C, T1, N, ...]`` simulation on device
(data/drift_dataset.py) — the right call for the reference's scales (500
samples x 10 clients x 10 steps). Real FMoW-sized image sets outgrow HBM;
the reference answers that with per-process torch DataLoaders re-reading CSV
partitions from disk every iteration (fmow/data_loader.py:63-103,
SURVEY.md §7: "host data loading is the bottleneck"). The TPU-native answer
is a grain/tf.data-style background prefetcher: while the device trains on
time step t, the host stages step t+1 into device memory, so the transfer
hides behind compute instead of serializing with it.

``prefetch_to_device`` is the generic primitive; ``TimeStepStream`` applies
it to a host-resident DriftDataset, yielding client-sharded (x_t, y_t)
slices one step ahead of consumption.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Callable, Iterable, Iterator, Optional

import jax


class _End:
    pass


_END = _End()


def prefetch_to_device(it: Iterable[Any], size: int = 2,
                       place: Optional[Callable[[Any], Any]] = None
                       ) -> Iterator[Any]:
    """Iterate ``it`` with up to ``size`` elements staged onto device ahead
    of the consumer.

    ``place`` maps a host element to its device placement (default:
    ``jax.device_put``); it runs on the background thread, so the consumer
    overlaps device transfer with whatever it is doing — jax device puts are
    async, the consumer only blocks when it actually uses the array.
    Exceptions in the source iterator or placement propagate to the consumer
    at the point of the failing element; the background thread is a daemon
    and dies with the process on early abandonment.
    """
    if size < 1:
        raise ValueError(f"size must be >= 1, got {size}")
    put = place if place is not None else jax.device_put
    buf: queue.Queue = queue.Queue(maxsize=size)
    stop = threading.Event()

    def bounded_put(item) -> bool:
        """Put unless the consumer closed the iterator; True if delivered."""
        while not stop.is_set():
            try:
                buf.put(item, timeout=0.2)
                return True
            except queue.Full:
                continue
        return False

    def producer() -> None:
        try:
            for item in it:
                if stop.is_set() or not bounded_put(put(item)):
                    return
        except BaseException as e:           # noqa: BLE001 — re-raised below
            bounded_put(e)
            return
        bounded_put(_END)

    threading.Thread(target=producer, daemon=True).start()

    # Generator close() (or abandonment) sets the stop event via the finally
    # below, so the producer exits instead of blocking forever with staged
    # device buffers pinned.
    try:
        while True:
            item = buf.get()
            if isinstance(item, _End):
                return
            if isinstance(item, BaseException):
                raise item
            yield item
    finally:
        stop.set()


class _Staged:
    """A completed staging job: ``value`` is the staging fn's return,
    ``meta`` whatever the submitter attached (e.g. the cohort member ids
    the staged arrays were gathered for)."""

    __slots__ = ("value", "meta")

    def __init__(self, value, meta) -> None:
        self.value = value
        self.meta = meta


class AsyncStager:
    """Single-slot background stager for double-buffered cohort H2D.

    The runner submits next iteration's gather+device_put closure right
    after the current iteration's checkpoint; by the time the driver loop
    reaches iteration t+1 the shards are (usually) already resident and
    ``take`` returns instantly. One worker thread, one slot: cohort staging
    is strictly look-ahead-1 (the NEXT draw depends on failure-detector
    state the current iteration updates), so deeper pipelining would stage
    from stale registry state.

    ``take(tag)`` returns the staged ``.value``/``.meta`` holder when the
    slot holds ``tag`` (blocking until the background fn finishes), or None
    on an empty slot or tag mismatch — the caller falls back to inline
    staging, so a miss costs only the overlap, never correctness.
    Exceptions in the staging fn surface at ``take`` (future.result()).
    """

    def __init__(self) -> None:
        import concurrent.futures
        self._pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="cohort-stager")
        self._tag = None
        self._meta = None
        self._future = None

    def submit(self, tag, fn: Callable[[], Any], meta: Any = None) -> None:
        """Stage ``fn()`` on the worker thread, keyed by ``tag``.
        Overwrites any unclaimed previous slot (its device buffers are
        simply dropped — jax puts are async and unpinned once unreferenced).
        """
        self._tag = tag
        self._meta = meta
        self._future = self._pool.submit(fn)

    def take(self, tag) -> Optional[_Staged]:
        """Claim the slot if it holds ``tag``; None otherwise. Clears the
        slot either way only on a hit."""
        if self._future is None or self._tag != tag:
            return None
        fut, meta = self._future, self._meta
        self._tag = self._meta = self._future = None
        return _Staged(fut.result(), meta)

    def close(self) -> None:
        self._pool.shutdown(wait=False)


class TimeStepStream:
    """Client-sharded (x_t, y_t) device slices of a HOST-resident dataset,
    prefetched one time step ahead.

    For experiments whose data cannot live on device whole. Composes with
    window-style algorithms (win-N with small N); horizon-weighted algorithms
    (softcluster 'all', exp/lin) need the full past on device and should keep
    the resident layout.
    """

    def __init__(self, ds, mesh, size: int = 2) -> None:
        from feddrift_tpu.parallel.mesh import client_sharding

        self.ds = ds
        self._shx = client_sharding(mesh, ds.x[:, 0].ndim)
        self._shy = client_sharding(mesh, ds.y[:, 0].ndim)
        self.size = size

    def _place(self, step_arrays):
        x_t, y_t = step_arrays
        return (jax.device_put(x_t, self._shx), jax.device_put(y_t, self._shy))

    def steps(self, start: int = 0, stop: Optional[int] = None
              ) -> Iterator[tuple]:
        """Yield device-placed (x_t, y_t) for t in [start, stop)."""
        stop = self.ds.x.shape[1] if stop is None else stop

        def host_slices():
            for t in range(start, stop):
                yield (self.ds.x[:, t], self.ds.y[:, t])

        return prefetch_to_device(host_slices(), size=self.size,
                                  place=self._place)
