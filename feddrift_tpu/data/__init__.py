from feddrift_tpu.data.drift_dataset import DriftDataset  # noqa: F401
from feddrift_tpu.data.changepoints import load_change_points, generate_random_change_points  # noqa: F401
from feddrift_tpu.data.registry import make_dataset  # noqa: F401
