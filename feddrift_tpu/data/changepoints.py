"""Change-point matrices: concept id per (time step, client).

A change-point matrix is a ``[T_cp, C]`` integer array; entry ``(t, c)`` is the
concept generating client ``c``'s data during time step ``t`` (reference:
data/changepoints/*.cp, consumed by ``generate_data_sea`` at
fedml_api/data_preprocessing/sea/data_loader.py:66-73).

The published presets A-F, R0-R9, W-Z (the benchmark definitions from the
FedDrift paper; 11x10 each) are shipped as data files under
``feddrift_tpu/data/changepoints/``. Random generation reproduces the
reference's ``rand`` semantics (sea/data_loader.py:48-64): one change point per
client, drawn uniformly from [1, T/stretch), optionally shared by all clients
(``drift_together``).
"""

from __future__ import annotations

import os

import numpy as np

_PRESET_DIR = os.path.join(os.path.dirname(__file__), "changepoints")


def available_presets() -> list[str]:
    return sorted(f[:-3] for f in os.listdir(_PRESET_DIR) if f.endswith(".cp"))


def load_change_points(name: str) -> np.ndarray:
    """Load a preset matrix by name (e.g. 'A'), or parse a whitespace matrix."""
    path = os.path.join(_PRESET_DIR, f"{name}.cp")
    if os.path.exists(path):
        return np.loadtxt(path, dtype=np.int32, ndmin=2)
    # Allow passing a literal matrix string ("0 0;1 0;..." or newline separated)
    if any(ch in name for ch in " ;\n"):
        rows = [r for r in name.replace(";", "\n").splitlines() if r.strip()]
        return np.asarray([[int(v) for v in r.split()] for r in rows], dtype=np.int32)
    raise FileNotFoundError(f"unknown change-point preset {name!r}; "
                            f"available: {available_presets()}")


def generate_random_change_points(
    train_iterations: int,
    num_clients: int,
    drift_together: int = 0,
    time_stretch: int = 1,
    seed: int | np.random.Generator = 0,
) -> np.ndarray:
    """Single-drift random matrix, reference semantics (sea/data_loader.py:48-64).

    Each client flips from concept 0 to concept 1 at a change point drawn
    uniformly from [1, T//stretch); with ``drift_together`` all clients share
    one change point. Matrix has T//stretch + 1 rows (so index t//stretch is
    valid for t = train_iterations, the held-out test step).
    """
    rng = seed if isinstance(seed, np.random.Generator) else np.random.default_rng(seed)
    t_rows = train_iterations // time_stretch
    if t_rows < 2:
        raise ValueError("train_iterations//time_stretch must be >= 2 for a drift")
    if drift_together:
        cp = int(rng.integers(1, t_rows))
        change_point_per_client = [cp] * num_clients
    else:
        change_point_per_client = [int(rng.integers(1, t_rows)) for _ in range(num_clients)]
    mat = np.zeros((t_rows + 1, num_clients), dtype=np.int32)
    for c, t in enumerate(change_point_per_client):
        mat[t:, c] = 1
    return mat


def concept_at(change_points: np.ndarray, t: int, client: int, time_stretch: int = 1) -> int:
    """Concept id of (time step t, client) with time-dilation semantics
    (reference: ``change_point[it//stretch_factor][c]``, sea/data_loader.py:73)."""
    row = min(t // time_stretch, change_points.shape[0] - 1)
    return int(change_points[row, client])


def concept_matrix(change_points: np.ndarray, num_steps: int, num_clients: int,
                   time_stretch: int = 1) -> np.ndarray:
    """Dense ``[num_steps, C]`` concept-id matrix for steps 0..num_steps-1."""
    out = np.zeros((num_steps, num_clients), dtype=np.int32)
    for t in range(num_steps):
        row = min(t // time_stretch, change_points.shape[0] - 1)
        out[t] = change_points[row, :num_clients]
    return out
