"""Party-split datasets for vertical federated learning.

Reference coverage (SURVEY.md §2b #31, #35): NUS-WIDE two/three-party loading
(fedml_api/data_preprocessing/NUS_WIDE/nus_wide_dataset.py:
get_labeled_data_with_2_party — party A holds the 634-dim low-level image
features, party B the 1000-dim tag vector, labels are the top-k one-hot
categories) and Lending-Club loan data
(fedml_api/data_preprocessing/lending_club_loan/lending_club_dataset.py:
loan_load_two_party_data / loan_load_three_party_data — qualification
features vs. loan-profile features, good/bad-loan binary label).

Real files are used when present under ``data_dir`` (NUS-WIDE Groundtruth/
Low_Level_Features layout; lending club processed CSV); otherwise party
features are synthesized with the reference dimensionalities and a shared
latent factor so that cross-party correlation exists for VFL to exploit.
The return contract matches platform/vertical.py: ``(party_features, y)``
where ``party_features`` is a list of [N, F_p] float32 arrays, one per party.
"""

from __future__ import annotations

import os

import numpy as np

# Reference dimensionalities.
NUS_WIDE_XA_DIM = 634    # low-level image features (nus_wide_dataset.py "634 columns")
NUS_WIDE_XB_DIM = 1000   # tag vector (get_labeled_data_with_2_party XB)
LENDING_QUAL_DIM = 17    # qualification_feat group (lending_club_feature_group.py)
LENDING_LOAN_DIM = 25    # loan/profile feature groups


def _synth_parties(dims: list[int], n: int, num_classes: int,
                   seed: int) -> tuple[list[np.ndarray], np.ndarray]:
    """Correlated party features: shared class-dependent latent + party noise."""
    rng = np.random.default_rng(seed)
    y = rng.integers(0, num_classes, size=n).astype(np.int32)
    latent = rng.normal(size=(num_classes, 16)).astype(np.float32)[y]
    latent += rng.normal(0, 0.5, size=latent.shape).astype(np.float32)
    parties = []
    for p, d in enumerate(dims):
        proj = np.random.default_rng(11 + p).normal(
            size=(16, d)).astype(np.float32) / 4.0
        parties.append(latent @ proj +
                       rng.normal(0, 0.3, size=(n, d)).astype(np.float32))
    return parties, y


def load_nus_wide(data_dir: str | None = None, n_samples: int = 2000,
                  num_parties: int = 2, top_k: int = 5,
                  seed: int = 0) -> tuple[list[np.ndarray], np.ndarray]:
    """NUS-WIDE party split. Two-party: [image 634, tags 1000]; three-party
    additionally splits the image features (first 300 / rest), mirroring the
    guest/host split of the reference's three-party VFL experiment."""
    if data_dir and os.path.isdir(os.path.join(data_dir, "Low_Level_Features")):
        xa, xb, y = _load_nus_wide_files(data_dir, top_k, n_samples)
    else:
        (xa, xb), y = _synth_parties([NUS_WIDE_XA_DIM, NUS_WIDE_XB_DIM],
                                     n_samples, top_k, seed)
    if num_parties == 2:
        return [xa, xb], y
    return [xa[:, :300], xa[:, 300:], xb], y


def _load_nus_wide_files(data_dir: str, top_k: int,
                         n_samples: int) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    import pandas as pd  # lazy: only on the real-data path
    label_dir = os.path.join(data_dir, "Groundtruth", "TrainTestLabels")
    labels = sorted(f.split("_")[1] for f in os.listdir(label_dir)
                    if f.endswith("_Train.txt"))[:top_k]
    dfs = [pd.read_csv(os.path.join(label_dir, f"Labels_{l}_Train.txt"),
                       header=None, names=[l]) for l in labels]
    lab = pd.concat(dfs, axis=1)
    sel = lab[lab.sum(axis=1) == 1].index[:n_samples]
    y = lab.loc[sel].to_numpy().argmax(1).astype(np.int32)
    feat_dir = os.path.join(data_dir, "Low_Level_Features")
    fdfs = [pd.read_csv(os.path.join(feat_dir, f), header=None, sep=" ").dropna(axis=1)
            for f in sorted(os.listdir(feat_dir)) if f.startswith("Train_Normalized")]
    xa = pd.concat(fdfs, axis=1).loc[sel].to_numpy().astype(np.float32)
    xb_path = os.path.join(data_dir, "NUS_WID_Tags", "Train_Tags1k.dat")
    xb = pd.read_csv(xb_path, header=None, sep="\t").dropna(axis=1) \
        .loc[sel].to_numpy().astype(np.float32)
    return xa, xb, y


def load_lending_club(data_dir: str | None = None, n_samples: int = 4000,
                      num_parties: int = 2,
                      seed: int = 0) -> tuple[list[np.ndarray], np.ndarray]:
    """Lending-club loan party split: qualification features vs. loan profile,
    binary good/bad-loan label (loan_load_two_party_data). Three-party splits
    the loan profile in half (loan_load_three_party_data)."""
    path = data_dir and os.path.join(data_dir, "loan_processed.csv")
    if path and os.path.exists(path):
        raw = np.loadtxt(path, delimiter=",", skiprows=1,
                         max_rows=n_samples).astype(np.float32)
        xq, xl, y = (raw[:, :LENDING_QUAL_DIM],
                     raw[:, LENDING_QUAL_DIM:LENDING_QUAL_DIM + LENDING_LOAN_DIM],
                     raw[:, -1].astype(np.int32))
    else:
        (xq, xl), y = _synth_parties([LENDING_QUAL_DIM, LENDING_LOAN_DIM],
                                     n_samples, 2, seed)
    if num_parties == 2:
        return [xq, xl], y
    h = LENDING_LOAN_DIM // 2
    return [xq, xl[:, :h], xl[:, h:]], y
