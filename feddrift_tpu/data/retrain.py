"""Retrain-window specifications as dense time-weight tensors.

The reference expresses "which past time steps feed a model's training" as a
string spec parsed into concatenated pandas frames
(fedml_api/data_preprocessing/common/retrain.py:7-85):

    all | win-N | weight-linear | weight-exp | sel-i,j,... |
    clientsel-<json per-client lists> | poisson

Here the same spec becomes a ``[C, T_total]`` float weight matrix over time
steps (duplication-based recency weighting maps to multiplicative weights, and
``poisson`` maps to per-sample Poisson(1) counts used by KUE's bootstrap,
retrain.py:65-74). A weight of w on step t means samples of that step are
drawn with relative probability w during local SGD — exactly equivalent to the
reference's duplicated-rows sampling because every step holds the same number
of samples.

Test data is always the *next* step (temporal holdout, retrain.py:78-83);
that is handled by ``DriftDataset.test_slice``.
"""

from __future__ import annotations

import json

import numpy as np


# Fallback horizon for grammar probing when the caller's true dimensions
# are unknown: far beyond any experiment's train_iterations.
_PROBE_STEPS = 4096


def is_retrain_spec(retrain_method: str, num_clients: int = 1,
                    total_steps: int = _PROBE_STEPS) -> bool:
    """True iff ``time_weights`` accepts the string.

    Validated by actually running the parse rather than prefix-matching, so
    near-miss specs like ``win-abc`` or ``weight-bogus`` are rejected here
    instead of raising mid-experiment (the LegacyClusterFL
    fall-back-to-win-1 guard relies on this, algorithms/statebased.py).
    Pass the experiment's real ``num_clients``/``total_steps`` to also
    reject specs that are structurally invalid at those dimensions
    (``sel-``/``clientsel-`` indices out of range, too-short per-client
    lists): with real dimensions every iteration index is probed, so
    late-step references are exercised too. The defaults validate grammar
    only (single probe at t=0 — probing 4096 steps would overflow
    ``weight-exp``'s 2**t and buys nothing at an imaginary horizon).
    """
    probe_ts = [0] if total_steps >= _PROBE_STEPS else range(total_steps)
    try:
        for t in probe_ts:
            time_weights(retrain_method, num_clients, t, total_steps)
    except Exception:
        return False
    return True


def time_weights(retrain_method: str, num_clients: int, current_iteration: int,
                 total_steps: int) -> np.ndarray:
    """Dense ``[C, total_steps]`` weights; zero for steps > current_iteration."""
    t = current_iteration
    w = np.zeros((num_clients, total_steps), dtype=np.float32)
    if retrain_method == "all":
        w[:, : t + 1] = 1.0
    elif retrain_method.startswith("win-"):
        win = int(retrain_method.removeprefix("win-"))
        w[:, max(0, t - win + 1) : t + 1] = 1.0
    elif retrain_method.startswith("weight-"):
        kind = retrain_method.removeprefix("weight-")
        if kind not in ("linear", "exp"):
            raise NameError(retrain_method)
        for it in range(t + 1):
            w[:, it] = (it + 1) if kind == "linear" else float(2**it)
    elif retrain_method.startswith("sel-"):
        spec = retrain_method.removeprefix("sel-")
        if spec:
            for it in spec.split(","):
                w[:, int(it)] = 1.0
    elif retrain_method.startswith("clientsel-"):
        per_client = json.loads(retrain_method.removeprefix("clientsel-"))
        for c in range(num_clients):
            for it in per_client[c]:
                w[c, int(it)] = 1.0
    elif retrain_method.startswith("poisson"):
        # Step-level weight is win-1; per-sample Poisson counts are produced
        # separately by ``poisson_sample_counts``.
        w[:, t] = 1.0
    else:
        raise NameError(retrain_method)
    return w


def poisson_sample_counts(num_clients: int, sample_num: int,
                          rng: np.random.Generator) -> np.ndarray:
    """Per-sample Poisson(1) bootstrap counts ``[C, N]`` (KUE; retrain.py:65-74).

    Clients whose counts sum to zero fall back to uniform weights, matching the
    reference's "if sum(weights) != 0" guard.
    """
    counts = rng.poisson(1.0, size=(num_clients, sample_num)).astype(np.float32)
    empty = counts.sum(axis=1) == 0
    counts[empty] = 1.0
    return counts
