"""Character-sequence datasets for RNN/LSTM models under drift.

The reference's sequence task is Shakespeare next-character prediction
(fedml_api/model/nlp/rnn.py:4-33, vocab 90, seq len 80) wired only into the
*non-drift* FedAvg pipeline. Here it composes with the drift pipeline like any
other dataset (BASELINE.md config 5 requires AUE over fed_shakespeare).

Hermetic generation: each concept is a distinct seeded Markov chain over the
character vocabulary; a drift changes the transition matrix, i.e. the language
statistics. Sequences are token-id arrays [seq_len] with the next character as
label — the same (x, y) contract as the reference's dataloader
(fed_shakespeare/utils.py::split: x = window[:-1], y = window[-1]).

Real data, when present under ``data_dir``, replaces synthesis:

- ``fed_shakespeare/datasets/shakespeare_train.h5`` — the TFF h5 layout
  (examples/<client>/snippets byte strings,
  reference fed_shakespeare/data_loader.py:20-56);
- ``shakespeare/train/*.json`` — the LEAF layout (users / user_data x,y
  sentence strings, reference shakespeare/data_loader.py:13-50);
- ``stackoverflow/datasets/stackoverflow_train.h5`` + ``.word_count`` —
  the TFF word-NWP layout (examples/<client>/tokens,
  reference stackoverflow_nwp/data_loader.py:18-45).

Concept drift on real text is an alphabet rotation: concept k serves the
same corpus with token ids rotated by a concept-specific offset. This is
the sequence analog of the reference's MNIST label-swap drift
(MNIST/data_loader_cont.py:179-214) — real content, changed symbol
semantics — chosen because the reference wires its text datasets only into
the non-drift pipeline and defines no text-drift transform of its own.
"""

from __future__ import annotations

import json
import os

import numpy as np

from feddrift_tpu.data.changepoints import concept_matrix
from feddrift_tpu.data.drift_dataset import DriftDataset

VOCAB_SIZE = 90   # reference rnn.py:18
SEQ_LEN = 80      # reference LEAF shakespeare sequence length. Default for
                  # DIRECT generate_text_drift callers only: the product
                  # path (data/registry.py) always passes
                  # ExperimentConfig.text_seq_len, whose default pins the
                  # same reference value.

# The TFF character vocabulary (fed_shakespeare/utils.py::CHAR_VOCAB, 86
# chars) plus the four structural slots (pad / bos / eos / oov) = 90 ids,
# matching the CharLSTM's embedding table (rnn.py:18). LEAF-JSON text is
# mapped through the same table (unknown chars -> oov) so both on-disk
# formats produce one id space.
CHAR_VOCAB = ('dhlptx@DHLPTX $(,048cgkoswCGKOSW[_#\'/37;?bfjnrvzBFJNRVZ"&*.26:'
              '\naeimquyAEIMQUY]!%)-159\r')
PAD_ID = 0
BOS_ID = len(CHAR_VOCAB) + 1    # 87
EOS_ID = len(CHAR_VOCAB) + 2    # 88
OOV_ID = len(CHAR_VOCAB) + 3    # 89
_CHAR_TO_ID = {ch: i + 1 for i, ch in enumerate(CHAR_VOCAB)}


def _char_ids(text: str) -> np.ndarray:
    return np.array([_CHAR_TO_ID.get(ch, OOV_ID) for ch in text], np.int32)


def load_word_ranks(path: str, k: int) -> list[str]:
    """Top-k words of a TFF ``word_count`` file ("word count" per line,
    frequency-ranked — the reference's get_most_frequent_words,
    stackoverflow_lr/utils.py:15-19). Shared by the NWP and LR loaders."""
    with open(path) as fh:
        return [ln.split()[0] for ln in fh if ln.strip()][:k]


def iter_tff_clients(h5file):
    """Yield the ``examples/<client>`` groups of a TFF-layout h5 in sorted
    client-key order (deterministic corpus identity across runs)."""
    for cid in sorted(h5file["examples"].keys()):
        yield h5file["examples"][cid]


# Window sampling only ever consumes C * (T+1) * sample_num windows, so a
# bounded prefix of a huge on-disk corpus (full TFF StackOverflow is ~1.7B
# tokens) gives identical coverage without materializing the whole stream.
_MAX_CORPUS_IDS = 2_000_000


def _try_load_char_corpus(data_dir: str, min_len: int,
                          max_len: int = _MAX_CORPUS_IDS) -> np.ndarray | None:
    """Real Shakespeare as one id stream, or None if no files are present."""
    h5path = os.path.join(data_dir, "fed_shakespeare", "datasets",
                          "shakespeare_train.h5")
    chunks: list[np.ndarray] = []
    total = 0
    if os.path.isfile(h5path):
        import h5py
        with h5py.File(h5path, "r") as f:
            for ex in iter_tff_clients(f):
                if total >= max_len:
                    break
                for snip in ex["snippets"][()]:
                    ids = _char_ids(snip.decode("utf8"))
                    chunks.append(np.concatenate(
                        [[BOS_ID], ids, [EOS_ID]]).astype(np.int32))
                    total += len(chunks[-1])
                    if total >= max_len:
                        break
    else:
        jdir = os.path.join(data_dir, "shakespeare", "train")
        if os.path.isdir(jdir):
            for fn in sorted(os.listdir(jdir)):
                if not fn.endswith(".json") or total >= max_len:
                    continue
                with open(os.path.join(jdir, fn)) as fh:
                    d = json.load(fh)
                for u in d["users"]:
                    if total >= max_len:
                        break
                    ud = d["user_data"][u]
                    for sent, nxt in zip(ud["x"], ud["y"]):
                        chunks.append(np.concatenate(
                            [_char_ids(sent + nxt), [EOS_ID]]).astype(np.int32))
                        total += len(chunks[-1])
    if not chunks:
        return None
    corpus = np.concatenate(chunks)[:max_len]
    return corpus if len(corpus) >= min_len else None


def _try_load_word_corpus(data_dir: str, vocab: int, min_len: int,
                          max_len: int = _MAX_CORPUS_IDS) -> np.ndarray | None:
    """Real StackOverflow token stream (TFF h5 + word_count vocab file)."""
    base = os.path.join(data_dir, "stackoverflow", "datasets")
    h5path = os.path.join(base, "stackoverflow_train.h5")
    wcpath = os.path.join(base, "stackoverflow.word_count")
    if not (os.path.isfile(h5path) and os.path.isfile(wcpath)):
        return None
    # word ids 1..vocab-2 by corpus frequency rank;
    # 0 is reserved (pad), vocab-1 is the oov bucket.
    word_id = {w: i + 1
               for i, w in enumerate(load_word_ranks(wcpath, vocab - 2))}
    import h5py
    ids: list[int] = []
    with h5py.File(h5path, "r") as f:
        for ex in iter_tff_clients(f):
            if len(ids) >= max_len:
                break
            for sent in ex["tokens"][()]:
                ids.extend(word_id.get(w, vocab - 1)
                           for w in sent.decode("utf8").split())
                if len(ids) >= max_len:
                    break
    if len(ids) < min_len:
        return None
    return np.asarray(ids[:max_len], np.int32)


def _real_text_windows(
    corpus: np.ndarray,
    concepts: np.ndarray,
    num_clients: int,
    sample_num: int,
    seq_len: int,
    vocab: int,
    rng: np.random.Generator,
    noise_prob: float,
    name: str,
) -> DriftDataset:
    """Serve (seq_len+1)-char windows of a real corpus; concept k rotates
    the alphabet (see module docstring)."""
    T1 = concepts.shape[0]
    x = np.zeros((num_clients, T1, sample_num, seq_len), np.int32)
    y = np.zeros((num_clients, T1, sample_num), np.int32)
    for t in range(T1):
        for c in range(num_clients):
            k = int(concepts[t, c])
            # valid starts: 0 .. len-seq_len-1 inclusive (window is
            # seq_len+1 ids); integers() high bound is exclusive
            starts = rng.integers(0, len(corpus) - seq_len,
                                  size=sample_num)
            win = corpus[starts[:, None] + np.arange(seq_len + 1)]
            if k:
                win = (win + 31 * k) % vocab
            x[c, t] = win[:, :seq_len]
            ys = win[:, seq_len].copy()
            if noise_prob > 0:
                flip = rng.random(sample_num) < noise_prob
                ys = np.where(flip, rng.integers(0, vocab, size=sample_num),
                              ys).astype(np.int32)
            y[c, t] = ys
    return DriftDataset(x=x, y=y, num_classes=vocab, concepts=concepts,
                        name=name, is_sequence=True,
                        meta={"vocab": vocab, "seq_len": seq_len,
                              "real_data": True})


def _concept_transition(concept: int, vocab: int) -> np.ndarray:
    """Row-stochastic transition matrix, deterministic per concept.

    Transitions are PEAKED (geometric weights over 8 successors), not
    uniform: with equal-weight successors the Bayes-optimal next-char
    accuracy is only 1/8 and argmax is an arbitrary tie-break, so "the
    model learns" is unobservable. Geometric weights put ~0.5 mass on the
    top successor — a trained model demonstrably beats the 1/90 chance
    floor (cf. real Shakespeare text, whose bigram distribution is
    similarly peaked)."""
    rng = np.random.default_rng(7919 + concept)
    logits = rng.normal(0, 1, size=(vocab, vocab))
    top = np.argsort(logits, axis=1)[:, -8:]
    mat = np.full((vocab, vocab), 1e-3)
    weights = 0.5 ** np.arange(8)[::-1]     # argsort ascending: last = top-1
    for i in range(vocab):
        mat[i, top[i]] += weights
    return mat / mat.sum(axis=1, keepdims=True)


def generate_word_drift(
    change_points: np.ndarray,
    train_iterations: int,
    num_clients: int,
    sample_num: int,
    noise_prob: float = 0.0,
    time_stretch: int = 1,
    seed: int = 0,
    seq_len: int = 20,
    vocab: int = 10000,
    data_dir: str = "./data",
) -> DriftDataset:
    """Word-level next-word-prediction drift (StackOverflow NWP scale,
    reference fedml_api/data_preprocessing/stackoverflow_nwp/, WordLSTM
    model rnn.py:36-67).

    Real TFF StackOverflow files under ``data_dir`` are preferred (see
    module docstring). Hermetic fallback: at 10k vocab a dense Markov
    matrix would be 800 MB per concept, so each concept k is instead an
    affine language: next = (a_k * cur + b_k) mod V with per-step uniform
    noise — a deterministic map the embedding LSTM can learn, whose
    parameters (the language statistics) change at drift points.
    """
    rng = np.random.default_rng(seed)
    T = train_iterations

    corpus = _try_load_word_corpus(data_dir, vocab, min_len=seq_len + 2)
    if corpus is not None:
        concepts = concept_matrix(change_points, T + 1, num_clients,
                                  time_stretch)
        return _real_text_windows(corpus, concepts, num_clients, sample_num,
                                  seq_len, vocab, rng, noise_prob,
                                  "stackoverflow_nwp")

    n_concepts = max(int(change_points.max()) + 1, 2)
    crng = np.random.default_rng(104729)
    a = crng.integers(2, vocab - 1, size=n_concepts)
    b = crng.integers(0, vocab, size=n_concepts)

    x = np.zeros((num_clients, T + 1, sample_num, seq_len), dtype=np.int32)
    y = np.zeros((num_clients, T + 1, sample_num), dtype=np.int32)
    concepts = concept_matrix(change_points, T + 1, num_clients, time_stretch)
    for t in range(T + 1):
        for c in range(num_clients):
            k = int(concepts[t, c]) % n_concepts
            seq = np.zeros((sample_num, seq_len + 1), dtype=np.int64)
            seq[:, 0] = rng.integers(0, vocab, size=sample_num)
            noise = rng.random((sample_num, seq_len)) < 0.1
            repl = rng.integers(0, vocab, size=(sample_num, seq_len))
            for s in range(seq_len):
                nxt = (a[k] * seq[:, s] + b[k]) % vocab
                seq[:, s + 1] = np.where(noise[:, s], repl[:, s], nxt)
            x[c, t] = seq[:, :seq_len].astype(np.int32)
            ys = seq[:, seq_len].astype(np.int32)
            if noise_prob > 0:
                flip = rng.random(sample_num) < noise_prob
                ys = np.where(flip, rng.integers(0, vocab, size=sample_num), ys)
            y[c, t] = ys
    return DriftDataset(x=x, y=y, num_classes=vocab, concepts=concepts,
                        name="stackoverflow_nwp", is_sequence=True,
                        meta={"vocab": vocab, "seq_len": seq_len})


def generate_text_drift(
    change_points: np.ndarray,
    train_iterations: int,
    num_clients: int,
    sample_num: int,
    noise_prob: float = 0.0,
    time_stretch: int = 1,
    seed: int = 0,
    seq_len: int = SEQ_LEN,
    vocab: int = VOCAB_SIZE,
    data_dir: str = "./data",
) -> DriftDataset:
    rng = np.random.default_rng(seed)
    T = train_iterations

    corpus = _try_load_char_corpus(data_dir, min_len=seq_len + 2)
    if corpus is not None:
        concepts = concept_matrix(change_points, T + 1, num_clients,
                                  time_stretch)
        return _real_text_windows(corpus, concepts, num_clients, sample_num,
                                  seq_len, vocab, rng, noise_prob,
                                  "shakespeare")

    n_concepts = int(change_points.max()) + 1
    chains = [_concept_transition(k, vocab) for k in range(max(n_concepts, 2))]

    x = np.zeros((num_clients, T + 1, sample_num, seq_len), dtype=np.int32)
    y = np.zeros((num_clients, T + 1, sample_num), dtype=np.int32)
    concepts = concept_matrix(change_points, T + 1, num_clients, time_stretch)
    for t in range(T + 1):
        for c in range(num_clients):
            concept = int(concepts[t, c])
            P = chains[concept % len(chains)]
            # Vectorised Markov rollout: [N, seq_len + 1]
            seq = np.zeros((sample_num, seq_len + 1), dtype=np.int32)
            seq[:, 0] = rng.integers(1, vocab, size=sample_num)
            u = rng.random((sample_num, seq_len))
            cdf = np.cumsum(P, axis=1)
            for s in range(seq_len):
                seq[:, s + 1] = (u[:, s, None] < cdf[seq[:, s]]).argmax(axis=1)
            x[c, t] = seq[:, :seq_len]
            ys = seq[:, seq_len]
            if noise_prob > 0:
                flip = rng.random(sample_num) < noise_prob
                ys = np.where(flip, rng.integers(0, vocab, size=sample_num), ys)
            y[c, t] = ys
    return DriftDataset(x=x, y=y, num_classes=vocab, concepts=concepts,
                        name="shakespeare", is_sequence=True,
                        meta={"vocab": vocab, "seq_len": seq_len})
