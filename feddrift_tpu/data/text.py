"""Character-sequence datasets for RNN/LSTM models under drift.

The reference's sequence task is Shakespeare next-character prediction
(fedml_api/model/nlp/rnn.py:4-33, vocab 90, seq len 80) wired only into the
*non-drift* FedAvg pipeline. Here it composes with the drift pipeline like any
other dataset (BASELINE.md config 5 requires AUE over fed_shakespeare).

Hermetic generation: each concept is a distinct seeded Markov chain over the
character vocabulary; a drift changes the transition matrix, i.e. the language
statistics. Sequences are token-id arrays [seq_len] with the next character as
label — the same (x, y) contract as the reference's dataloader.
"""

from __future__ import annotations

import numpy as np

from feddrift_tpu.data.changepoints import concept_matrix
from feddrift_tpu.data.drift_dataset import DriftDataset

VOCAB_SIZE = 90   # reference rnn.py:18
SEQ_LEN = 80      # reference LEAF shakespeare sequence length. Default for
                  # DIRECT generate_text_drift callers only: the product
                  # path (data/registry.py) always passes
                  # ExperimentConfig.text_seq_len, whose default pins the
                  # same reference value.


def _concept_transition(concept: int, vocab: int) -> np.ndarray:
    """Row-stochastic transition matrix, deterministic per concept.

    Transitions are PEAKED (geometric weights over 8 successors), not
    uniform: with equal-weight successors the Bayes-optimal next-char
    accuracy is only 1/8 and argmax is an arbitrary tie-break, so "the
    model learns" is unobservable. Geometric weights put ~0.5 mass on the
    top successor — a trained model demonstrably beats the 1/90 chance
    floor (cf. real Shakespeare text, whose bigram distribution is
    similarly peaked)."""
    rng = np.random.default_rng(7919 + concept)
    logits = rng.normal(0, 1, size=(vocab, vocab))
    top = np.argsort(logits, axis=1)[:, -8:]
    mat = np.full((vocab, vocab), 1e-3)
    weights = 0.5 ** np.arange(8)[::-1]     # argsort ascending: last = top-1
    for i in range(vocab):
        mat[i, top[i]] += weights
    return mat / mat.sum(axis=1, keepdims=True)


def generate_word_drift(
    change_points: np.ndarray,
    train_iterations: int,
    num_clients: int,
    sample_num: int,
    noise_prob: float = 0.0,
    time_stretch: int = 1,
    seed: int = 0,
    seq_len: int = 20,
    vocab: int = 10000,
) -> DriftDataset:
    """Word-level next-word-prediction drift (StackOverflow NWP scale,
    reference fedml_api/data_preprocessing/stackoverflow_nwp/, WordLSTM
    model rnn.py:36-67).

    At 10k vocab a dense Markov matrix would be 800 MB per concept, so each
    concept k is instead an affine language: next = (a_k * cur + b_k) mod V
    with per-step uniform noise — a deterministic map the embedding LSTM can
    learn, whose parameters (the language statistics) change at drift points.
    """
    rng = np.random.default_rng(seed)
    T = train_iterations
    n_concepts = max(int(change_points.max()) + 1, 2)
    crng = np.random.default_rng(104729)
    a = crng.integers(2, vocab - 1, size=n_concepts)
    b = crng.integers(0, vocab, size=n_concepts)

    x = np.zeros((num_clients, T + 1, sample_num, seq_len), dtype=np.int32)
    y = np.zeros((num_clients, T + 1, sample_num), dtype=np.int32)
    concepts = concept_matrix(change_points, T + 1, num_clients, time_stretch)
    for t in range(T + 1):
        for c in range(num_clients):
            k = int(concepts[t, c]) % n_concepts
            seq = np.zeros((sample_num, seq_len + 1), dtype=np.int64)
            seq[:, 0] = rng.integers(0, vocab, size=sample_num)
            noise = rng.random((sample_num, seq_len)) < 0.1
            repl = rng.integers(0, vocab, size=(sample_num, seq_len))
            for s in range(seq_len):
                nxt = (a[k] * seq[:, s] + b[k]) % vocab
                seq[:, s + 1] = np.where(noise[:, s], repl[:, s], nxt)
            x[c, t] = seq[:, :seq_len].astype(np.int32)
            ys = seq[:, seq_len].astype(np.int32)
            if noise_prob > 0:
                flip = rng.random(sample_num) < noise_prob
                ys = np.where(flip, rng.integers(0, vocab, size=sample_num), ys)
            y[c, t] = ys
    return DriftDataset(x=x, y=y, num_classes=vocab, concepts=concepts,
                        name="stackoverflow_nwp", is_sequence=True,
                        meta={"vocab": vocab, "seq_len": seq_len})


def generate_text_drift(
    change_points: np.ndarray,
    train_iterations: int,
    num_clients: int,
    sample_num: int,
    noise_prob: float = 0.0,
    time_stretch: int = 1,
    seed: int = 0,
    seq_len: int = SEQ_LEN,
    vocab: int = VOCAB_SIZE,
) -> DriftDataset:
    rng = np.random.default_rng(seed)
    T = train_iterations
    n_concepts = int(change_points.max()) + 1
    chains = [_concept_transition(k, vocab) for k in range(max(n_concepts, 2))]

    x = np.zeros((num_clients, T + 1, sample_num, seq_len), dtype=np.int32)
    y = np.zeros((num_clients, T + 1, sample_num), dtype=np.int32)
    concepts = concept_matrix(change_points, T + 1, num_clients, time_stretch)
    for t in range(T + 1):
        for c in range(num_clients):
            concept = int(concepts[t, c])
            P = chains[concept % len(chains)]
            # Vectorised Markov rollout: [N, seq_len + 1]
            seq = np.zeros((sample_num, seq_len + 1), dtype=np.int32)
            seq[:, 0] = rng.integers(1, vocab, size=sample_num)
            u = rng.random((sample_num, seq_len))
            cdf = np.cumsum(P, axis=1)
            for s in range(seq_len):
                seq[:, s + 1] = (u[:, s, None] < cdf[seq[:, s]]).argmax(axis=1)
            x[c, t] = seq[:, :seq_len]
            ys = seq[:, seq_len]
            if noise_prob > 0:
                flip = rng.random(sample_num) < noise_prob
                ys = np.where(flip, rng.integers(0, vocab, size=sample_num), ys)
            y[c, t] = ys
    return DriftDataset(x=x, y=y, num_classes=vocab, concepts=concepts,
                        name="shakespeare", is_sequence=True,
                        meta={"vocab": vocab, "seq_len": seq_len})
