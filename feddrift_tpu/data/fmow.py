"""FMoW-style satellite-image drift dataset.

The reference's FMoW pipeline (fedml_api/data_preprocessing/fmow/
data_loader.py:63-103) serves WILDS FMoW images (62 land-use classes) through
precomputed per-(client, iteration) index partitions under
``data/fmow/partitions/{A-F}/`` — the drift is *covariate/temporal*: the
label set is fixed while the image distribution shifts across years/regions.

Hermetic environment (no WILDS download): we preserve that structure with
concept-conditioned prototypes — each (class, concept) pair has its own
prototype image, so a concept change shifts the input distribution under
fixed label semantics, which is exactly the learning problem FMoW poses to
the drift algorithms (contrast the label-swap drift of the MNIST pipeline,
data/prototype.py). If real partitions exist under
``{data_dir}/fmow/partitions/{change_points}/`` as
``client_{c}_iter_{t}.npz`` files with ``x``/``y`` arrays, they are used
verbatim.

Images default to 32x32x3 (config ``fmow_image_size``) rather than the
reference's 224 crops: the drift algorithms' behaviour depends on the
classification problem, not the resolution, and small static shapes keep the
[C, T, N, H, W, 3] array device-resident.
"""

from __future__ import annotations

import os

import numpy as np

from feddrift_tpu.data.changepoints import concept_matrix
from feddrift_tpu.data.drift_dataset import DriftDataset

NUM_CLASSES = 62  # WILDS FMoW land-use categories (fmow/data_loader.py)


def _try_load_partitions(part_dir: str, num_clients: int, T: int,
                         sample_num: int, image_size: int):
    """Load real ``client_{c}_iter_{t}.npz`` partitions if all are present."""
    if not os.path.isdir(part_dir):
        return None
    x = np.zeros((num_clients, T + 1, sample_num, image_size, image_size, 3),
                 dtype=np.float32)
    y = np.zeros((num_clients, T + 1, sample_num), dtype=np.int32)
    for c in range(num_clients):
        for t in range(T + 1):
            p = os.path.join(part_dir, f"client_{c}_iter_{t}.npz")
            if not os.path.isfile(p):
                return None
            d = np.load(p)
            if d["x"].shape[1:3] != (image_size, image_size):
                raise ValueError(
                    f"{p}: partition images are {d['x'].shape[1:3]}, "
                    f"expected ({image_size}, {image_size}); re-export the "
                    f"partitions or set fmow_image_size accordingly")
            # wrap (oversample) short partitions so every slot holds real data
            take = np.arange(sample_num) % len(d["y"])
            x[c, t] = d["x"][take][..., :3]
            y[c, t] = d["y"][take]
    return x, y


def generate_fmow_drift(
    change_points: np.ndarray,
    train_iterations: int,
    num_clients: int,
    sample_num: int,
    noise_prob: float = 0.0,
    time_stretch: int = 1,
    seed: int = 0,
    data_dir: str = "./data",
    image_size: int = 32,
    change_points_name: str = "A",
    smooth_sigma: float = 0.0,
) -> DriftDataset:
    T = train_iterations
    concepts = concept_matrix(change_points, T + 1, num_clients, time_stretch)
    num_concepts = int(concepts.max()) + 1

    real = None if smooth_sigma > 0 else _try_load_partitions(
        os.path.join(data_dir, "fmow", "partitions", change_points_name),
        num_clients, T, sample_num, image_size)
    if real is not None:
        x, y = real
        if noise_prob > 0:   # label noise applies to real data too (parity
            rng = np.random.default_rng(seed)     # with prototype.py:131-133)
            flip = rng.random(y.shape) < noise_prob
            y = np.where(flip, (y + 1) % NUM_CLASSES, y).astype(np.int32)
        return DriftDataset(x=x, y=y, num_classes=NUM_CLASSES,
                            concepts=concepts, name="fmow",
                            meta={"real_data": True})

    # Synthetic fallback reuses the hardened low-rank PrototypeSampler
    # (class structure in a shared subspace, Bayes accuracy < 1 — see
    # prototype.py round-3 note) with a per-concept global input shift on
    # top: label semantics stay fixed while the image distribution moves,
    # the covariate/temporal drift real FMoW years exhibit. Prototype seed
    # is independent of the experiment seed so data identity survives
    # reseeding.
    from feddrift_tpu.data.prototype import PrototypeSampler, _smooth_rows
    proto_rng = np.random.default_rng(4242)
    shape = (image_size, image_size, 3)
    sampler = PrototypeSampler(shape, NUM_CLASSES, proto_seed=4242,
                               smooth_sigma=smooth_sigma)
    # per-concept global shift: simulates the sensor/season/region covariate
    # drift of real FMoW years. Under the -smooth family the shift is
    # smoothed too, so the drift signal itself lives in frequencies conv
    # stacks see after pooling.
    concept_shift = proto_rng.normal(0.0, 0.5,
                                     (num_concepts, *shape)).astype(np.float32)
    if smooth_sigma > 0:
        flat = concept_shift.reshape(num_concepts, -1)
        norms = np.linalg.norm(flat, axis=1, keepdims=True)
        flat = _smooth_rows(flat, shape, smooth_sigma)
        # keep the original shift magnitude (smoothing attenuates energy)
        flat *= norms / np.maximum(np.linalg.norm(flat, axis=1, keepdims=True),
                                   1e-12)
        concept_shift = flat.reshape(num_concepts, *shape).astype(np.float32)

    rng = np.random.default_rng(seed)
    x = np.zeros((num_clients, T + 1, sample_num, *shape), dtype=np.float32)
    y = np.zeros((num_clients, T + 1, sample_num), dtype=np.int32)
    for t in range(T + 1):
        for c in range(num_clients):
            k = int(concepts[t, c]) % num_concepts
            xs, ys = sampler.sample(rng, sample_num)
            xs = xs + concept_shift[k]
            if noise_prob > 0:
                flip = rng.random(sample_num) < noise_prob
                ys = np.where(flip, (ys + 1) % NUM_CLASSES, ys)
            x[c, t], y[c, t] = xs.astype(np.float32), ys
    meta = {"real_data": False}
    if smooth_sigma > 0:
        meta["smooth_sigma"] = smooth_sigma
    return DriftDataset(x=x, y=y, num_classes=NUM_CLASSES, concepts=concepts,
                        name="fmow", meta=meta)
