"""Dataset registry: dataset <-> drift-algorithm composition is orthogonal.

The reference hardwires its drift pipeline to five datasets via a closed
switch (fedml_experiments/distributed/fedavg_cont_ens/main_fedavg.py:145-179);
FederatedEMNIST / fed_shakespeare only exist in the non-drift pipeline
(BASELINE.md). Here any registered dataset composes with any drift algorithm.
"""

from __future__ import annotations

from typing import Callable

import numpy as np

from feddrift_tpu.config import ExperimentConfig
from feddrift_tpu.data import changepoints as cp
from feddrift_tpu.data.drift_dataset import DriftDataset
from feddrift_tpu.data.prototype import generate_prototype_drift
from feddrift_tpu.data.synthetic import generate_synthetic
from feddrift_tpu.data.text import generate_text_drift, generate_word_drift

_REGISTRY: dict[str, Callable[..., DriftDataset]] = {}


def register_dataset(*names: str):
    """Register a builder ``(cfg, change_points) -> DriftDataset`` under names."""
    def deco(fn: Callable[[ExperimentConfig, np.ndarray], DriftDataset]):
        for n in names:
            _REGISTRY[n] = fn
        return fn
    return deco


def available_datasets() -> list[str]:
    return sorted(_REGISTRY)


def _resolve_change_points(cfg: ExperimentConfig) -> np.ndarray:
    if cfg.change_points == "rand":
        return cp.generate_random_change_points(
            cfg.train_iterations, cfg.client_num_in_total, cfg.drift_together,
            cfg.time_stretch, seed=cfg.seed)
    return cp.load_change_points(cfg.change_points)


for _name in ("sea", "sine", "circle"):
    @register_dataset(_name)
    def _mk(cfg: ExperimentConfig, change_points: np.ndarray, *, _n=_name) -> DriftDataset:
        return generate_synthetic(
            _n, change_points, cfg.train_iterations, cfg.client_num_in_total,
            cfg.sample_num, cfg.noise_prob, cfg.time_stretch, cfg.seed)

# fed_cifar100 is cifar100 with the TFF per-client partition (reference
# fed_cifar100/data_loader.py); under the drift pipeline's per-(client, step)
# slicing the two share one generator.
# Plain "<name>": real files under data_dir when present, else the hardened
# white-noise-basis prototypes. "<name>-smooth": the conv-learnable
# synthetic family — same label-swap drift and subspace geometry, but the
# class basis is Gaussian-smoothed over the image grid (prototype.py
# round-4 note: the white-noise basis is a global projection conv models
# cannot learn); always synthetic, real files deliberately ignored so the
# task is reproducible anywhere.
for _name in ("MNIST", "femnist", "cifar10", "cifar100", "cinic10",
              "fed_cifar100"):
    for _suffix, _smooth in (("", False), ("-smooth", True)):
        @register_dataset(_name + _suffix)
        def _mk_img(cfg: ExperimentConfig, change_points: np.ndarray,
                    *, _n=_name, _sm=_smooth) -> DriftDataset:
            return generate_prototype_drift(
                _n, change_points, cfg.train_iterations,
                cfg.client_num_in_total, cfg.sample_num, cfg.noise_prob,
                cfg.time_stretch, cfg.seed, cfg.data_dir,
                smooth_sigma=cfg.smooth_sigma if _sm else 0.0)


for _suffix, _smooth in (("", False), ("-smooth", True)):
    @register_dataset("fmow" + _suffix)
    def _mk_fmow(cfg: ExperimentConfig, change_points: np.ndarray,
                 *, _sm=_smooth) -> DriftDataset:
        from feddrift_tpu.data.fmow import generate_fmow_drift
        return generate_fmow_drift(
            change_points, cfg.train_iterations, cfg.client_num_in_total,
            cfg.sample_num, cfg.noise_prob, cfg.time_stretch, cfg.seed,
            cfg.data_dir, cfg.fmow_image_size, cfg.change_points,
            smooth_sigma=cfg.smooth_sigma if _sm else 0.0)


@register_dataset("shakespeare", "fed_shakespeare")
def _mk_text(cfg: ExperimentConfig, change_points: np.ndarray) -> DriftDataset:
    return generate_text_drift(
        change_points, cfg.train_iterations, cfg.client_num_in_total,
        cfg.sample_num, cfg.noise_prob, cfg.time_stretch, cfg.seed,
        seq_len=cfg.text_seq_len, data_dir=cfg.data_dir)


@register_dataset("susy", "ro")
def _mk_uci(cfg: ExperimentConfig, change_points: np.ndarray) -> DriftDataset:
    from feddrift_tpu.data.tabular import generate_uci_drift
    return generate_uci_drift(
        cfg.dataset, change_points, cfg.train_iterations,
        cfg.client_num_in_total, cfg.sample_num, cfg.noise_prob,
        cfg.time_stretch, cfg.seed, cfg.data_dir)


@register_dataset("stackoverflow_lr")
def _mk_so_lr(cfg: ExperimentConfig, change_points: np.ndarray) -> DriftDataset:
    from feddrift_tpu.data.tabular import generate_stackoverflow_lr_drift
    return generate_stackoverflow_lr_drift(
        change_points, cfg.train_iterations, cfg.client_num_in_total,
        cfg.sample_num, cfg.noise_prob, cfg.time_stretch, cfg.seed,
        vocab_size=cfg.so_vocab_size, tag_size=cfg.so_tag_size,
        data_dir=cfg.data_dir)


@register_dataset("stackoverflow", "stackoverflow_nwp")
def _mk_word(cfg: ExperimentConfig, change_points: np.ndarray) -> DriftDataset:
    # word-NWP keeps its own default seq len (reference StackOverflow
    # windows are ~20 tokens); cfg.text_seq_len governs the char datasets
    return generate_word_drift(
        change_points, cfg.train_iterations, cfg.client_num_in_total,
        cfg.sample_num, cfg.noise_prob, cfg.time_stretch, cfg.seed,
        data_dir=cfg.data_dir)


def make_dataset(cfg: ExperimentConfig) -> DriftDataset:
    if cfg.dataset not in _REGISTRY:
        raise KeyError(f"unknown dataset {cfg.dataset!r}; available: {available_datasets()}")
    if cfg.population_size > 0:
        # Population mode: the dataset covers every REGISTERED client, not
        # just the device-visible cohort. The builders read
        # cfg.client_num_in_total, so hand them a data-shaped clone; the
        # published 10-column change-point presets tile across the
        # population (member i drifts like preset column i mod 10 — the
        # canonical benchmark drift patterns, replicated at scale).
        import dataclasses
        data_cfg = dataclasses.replace(
            cfg, population_size=0,
            client_num_in_total=cfg.population_size,
            client_num_per_round=min(cfg.client_num_per_round,
                                     cfg.population_size))
        change_points = _resolve_change_points(data_cfg)
        if change_points.shape[1] < data_cfg.client_num_in_total:
            reps = -(-data_cfg.client_num_in_total // change_points.shape[1])
            change_points = np.tile(change_points, (1, reps))
        return _REGISTRY[cfg.dataset](data_cfg, change_points)
    change_points = _resolve_change_points(cfg)
    if change_points.shape[1] < cfg.client_num_in_total:
        raise ValueError(
            f"change-point matrix has {change_points.shape[1]} clients < "
            f"client_num_in_total={cfg.client_num_in_total}")
    return _REGISTRY[cfg.dataset](cfg, change_points)
