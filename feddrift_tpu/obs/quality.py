"""Streaming model-quality plane for the serving read path.

Training-side eval sees quality once per iteration, offline, on the
trainer's own split. This module measures it ON the read path, live,
from real traffic — the E-step quality of the deployed assignment
(arXiv:2111.10192) accounted where arXiv:2307.06561 argues it must be:
server-side, per request, O(1).

Three estimators, all host-side and allocation-light:

- ``LabelJoiner`` — delayed-label join. Labels for online traffic arrive
  seconds-to-minutes after the prediction (the user clicked, the sensor
  confirmed), so every served request parks its prediction in a TTL ring
  keyed by request id; ``observe_label(request_id, y)`` closes the loop
  or misses (expired / evicted / unknown) without ever growing past the
  capacity bound.

- ``QualityMonitor`` — windowed per-model accuracy, mean confidence,
  output entropy and a streaming ECE calibration sketch over the joined
  stream. Feeds the ``model_accuracy_q{model=}`` / ``serve_entropy_q
  {model=}`` quantile sketches and emits one ``model_quality`` event
  every ``window`` labeled requests.

- ``EntropyShiftDetector`` — a windowed two-sample KS statistic on the
  prediction-entropy stream (reference window vs. sliding current
  window). A score past the threshold emits ``serve_drift_suspected``:
  drift detection on the READ path, where the trainer's oracle cannot
  see, and without waiting for labels at all.

Pure numpy + stdlib; safe to import from the jax-free CLI paths.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict, deque
from typing import Optional

import numpy as np

from feddrift_tpu.obs.events import emit
from feddrift_tpu.obs.instruments import registry

DEFAULT_ECE_BINS = 10


def softmax_1d(logits) -> np.ndarray:
    """Numerically stable softmax over one logits row (host-side)."""
    z = np.asarray(logits, dtype=np.float64).ravel()
    z = z - z.max()
    e = np.exp(z)
    return e / e.sum()


def prediction_stats(logits) -> tuple[int, float, float]:
    """(argmax, confidence, entropy) of one logits row — the per-request
    quality triple, O(classes)."""
    p = softmax_1d(logits)
    pred = int(np.argmax(p))
    conf = float(p[pred])
    # entropy in nats; clip avoids log(0) on saturated rows
    ent = float(-np.sum(p * np.log(np.clip(p, 1e-12, None))))
    return pred, conf, ent


class StreamingECE:
    """Expected Calibration Error sketch: fixed confidence bins, per-bin
    (count, confidence sum, correct sum). O(1) per labeled request, no
    sample retention — the streaming analogue of the binned ECE."""

    def __init__(self, bins: int = DEFAULT_ECE_BINS) -> None:
        self.bins = int(bins)
        self.count = np.zeros(self.bins, dtype=np.int64)
        self.conf_sum = np.zeros(self.bins, dtype=np.float64)
        self.correct_sum = np.zeros(self.bins, dtype=np.float64)

    def observe(self, confidence: float, correct: bool) -> None:
        b = min(int(confidence * self.bins), self.bins - 1)
        self.count[b] += 1
        self.conf_sum[b] += confidence
        self.correct_sum[b] += 1.0 if correct else 0.0

    def ece(self) -> Optional[float]:
        n = int(self.count.sum())
        if n == 0:
            return None
        mask = self.count > 0
        acc = self.correct_sum[mask] / self.count[mask]
        conf = self.conf_sum[mask] / self.count[mask]
        w = self.count[mask] / n
        return float(np.sum(w * np.abs(acc - conf)))


class _Pending:
    __slots__ = ("model", "client", "pred", "confidence", "entropy", "ts")

    def __init__(self, model: int, client: int, pred: int,
                 confidence: float, entropy: float, ts: float) -> None:
        self.model = model
        self.client = client
        self.pred = pred
        self.confidence = confidence
        self.entropy = entropy
        self.ts = ts


class LabelJoiner:
    """request_id -> prediction ring buffer with TTL.

    Insert-ordered (request ids are monotonic), so expiry is a pop from
    the front; ``capacity`` bounds memory when labels never arrive."""

    def __init__(self, ttl_s: float = 60.0, capacity: int = 65536,
                 time_fn=time.time) -> None:
        if ttl_s <= 0:
            raise ValueError("ttl_s must be > 0")
        self.ttl_s = float(ttl_s)
        self.capacity = int(capacity)
        self._time = time_fn
        self._ring: "OrderedDict[int, _Pending]" = OrderedDict()
        self.expired = 0
        self.evicted = 0

    def __len__(self) -> int:
        return len(self._ring)

    def _sweep(self, now: float) -> None:
        horizon = now - self.ttl_s
        while self._ring:
            _, entry = next(iter(self._ring.items()))
            if entry.ts >= horizon:
                break
            self._ring.popitem(last=False)
            self.expired += 1
        while len(self._ring) > self.capacity:
            self._ring.popitem(last=False)
            self.evicted += 1

    def record(self, request_id: int, entry: _Pending) -> None:
        self._ring[int(request_id)] = entry
        self._sweep(self._time())

    def pop(self, request_id: int) -> Optional[_Pending]:
        # labels arrive from EXTERNAL feedback loops — an id that never
        # was a request id (wrong type included) is a miss, not an error
        try:
            entry = self._ring.pop(int(request_id), None)
        except (TypeError, ValueError):
            return None
        if entry is None:
            return None
        if entry.ts < self._time() - self.ttl_s:
            self.expired += 1
            return None
        return entry


class EntropyShiftDetector:
    """Windowed KS-style shift score on the entropy stream.

    Anchors a reference window (the first ``window`` samples after
    construction or ``reset()``), then slides a current window and
    scores the two empirical CDFs with the two-sample KS statistic every
    ``window // 2`` samples. A score past ``threshold`` fires once and
    re-anchors the reference to the current window, so a sustained shift
    reports a step, not a spam stream."""

    def __init__(self, window: int = 64, threshold: float = 0.5) -> None:
        if window < 8:
            raise ValueError("drift window must be >= 8")
        self.window = int(window)
        self.threshold = float(threshold)
        self._ref: list[float] = []
        self._cur: deque = deque(maxlen=self.window)
        self._since_eval = 0

    def reset(self) -> None:
        """Re-anchor on the next ``window`` samples (e.g. after a swap —
        a new generation legitimately changes the output distribution)."""
        self._ref = []
        self._cur.clear()
        self._since_eval = 0

    @staticmethod
    def ks_statistic(a, b) -> float:
        """Two-sample KS: max CDF gap between sorted samples ``a``/``b``."""
        a = np.sort(np.asarray(a, dtype=np.float64))
        b = np.sort(np.asarray(b, dtype=np.float64))
        grid = np.concatenate([a, b])
        ca = np.searchsorted(a, grid, side="right") / a.size
        cb = np.searchsorted(b, grid, side="right") / b.size
        return float(np.max(np.abs(ca - cb)))

    def observe(self, entropy: float) -> Optional[float]:
        """Returns the KS score when the detector fires, else None."""
        if len(self._ref) < self.window:
            self._ref.append(float(entropy))
            return None
        self._cur.append(float(entropy))
        if len(self._cur) < self.window:
            return None
        self._since_eval += 1
        if self._since_eval < max(self.window // 2, 1):
            return None
        self._since_eval = 0
        score = self.ks_statistic(self._ref, list(self._cur))
        if score < self.threshold:
            return None
        self._ref = list(self._cur)
        self._cur.clear()
        return score


class _ModelWindow:
    """Windowed per-model aggregates over the labeled stream."""

    __slots__ = ("correct", "confidence", "entropy")

    def __init__(self, window: int) -> None:
        self.correct: deque = deque(maxlen=window)
        self.confidence: deque = deque(maxlen=window)
        self.entropy: deque = deque(maxlen=window)

    def stats(self) -> Optional[dict]:
        n = len(self.correct)
        if n == 0:
            return None
        return {
            "n": n,
            "accuracy": round(float(sum(self.correct)) / n, 4),
            "mean_confidence": round(
                float(sum(self.confidence)) / n, 4),
            "mean_entropy": round(float(sum(self.entropy)) / n, 4),
        }


class QualityMonitor:
    """The per-engine quality plane: joiner + windowed estimators +
    sketches + ``model_quality`` / ``serve_drift_suspected`` events.

    ``record_prediction`` runs on the serving dispatcher (one call per
    answered request, O(classes)); ``observe_label`` runs on whatever
    thread the label producer uses. One lock covers both — every
    operation under it is a deque append or a dict insert."""

    def __init__(self, window: int = 100, ttl_s: float = 60.0,
                 capacity: int = 65536, ece_bins: int = DEFAULT_ECE_BINS,
                 drift_window: int = 64, drift_threshold: float = 0.5,
                 time_fn=time.time) -> None:
        if window < 1:
            raise ValueError("quality window must be >= 1")
        self.window = int(window)
        self.joiner = LabelJoiner(ttl_s=ttl_s, capacity=capacity,
                                  time_fn=time_fn)
        self.ece = StreamingECE(bins=ece_bins)
        self.drift = EntropyShiftDetector(window=drift_window,
                                          threshold=drift_threshold)
        self._lock = threading.Lock()
        self._models: dict[int, _ModelWindow] = {}
        self._overall = _ModelWindow(self.window)
        self.labeled = 0
        self.missed = 0
        self._since_event = 0
        self.drift_suspected = 0
        self._reg = registry()

    # -- read-path half -------------------------------------------------
    def record_prediction(self, request_id: int, model: int, logits,
                          client: int = -1) -> None:
        pred, conf, ent = prediction_stats(logits)
        self._reg.quantile_sketch("serve_entropy_q",
                                  model=str(int(model))).observe(ent)
        with self._lock:
            self.joiner.record(request_id, _Pending(
                int(model), int(client), pred, conf, ent,
                self.joiner._time()))
            score = self.drift.observe(ent)
            if score is not None:
                self.drift_suspected += 1
        if score is not None:
            emit("serve_drift_suspected", score=round(score, 4),
                 threshold=self.drift.threshold,
                 window=self.drift.window, signal="entropy")

    # -- label half -----------------------------------------------------
    def observe_label(self, request_id: int, y) -> Optional[dict]:
        """Join one delayed label; returns the joined record (model,
        pred, correct, ...) or None when the prediction expired."""
        with self._lock:
            entry = self.joiner.pop(request_id)
            if entry is None:
                self.missed += 1
                return None
            correct = entry.pred == int(y)
            mw = self._models.get(entry.model)
            if mw is None:
                mw = self._models[entry.model] = _ModelWindow(self.window)
            for w in (mw, self._overall):
                w.correct.append(1 if correct else 0)
                w.confidence.append(entry.confidence)
                w.entropy.append(entry.entropy)
            self.ece.observe(entry.confidence, correct)
            self.labeled += 1
            self._since_event += 1
            fire = self._since_event >= self.window
            if fire:
                self._since_event = 0
            acc = float(sum(mw.correct)) / len(mw.correct)
        self._reg.quantile_sketch(
            "model_accuracy_q", model=str(entry.model)).observe(acc)
        if fire:
            emit("model_quality", **self._event_fields())
        return {"model": entry.model, "client": entry.client,
                "pred": entry.pred, "correct": correct,
                "confidence": entry.confidence, "entropy": entry.entropy}

    # -- snapshots ------------------------------------------------------
    def _event_fields(self) -> dict:
        with self._lock:
            per_model = {str(m): w.stats()
                         for m, w in sorted(self._models.items())}
            overall = self._overall.stats()
            return {
                "labeled": self.labeled,
                "missed": self.missed,
                "window": self.window,
                "accuracy": overall["accuracy"] if overall else None,
                "mean_confidence": (overall["mean_confidence"]
                                    if overall else None),
                "mean_entropy": (overall["mean_entropy"]
                                 if overall else None),
                "ece": (round(self.ece.ece(), 4)
                        if self.ece.ece() is not None else None),
                "per_model": per_model,
            }

    def snapshot(self) -> dict:
        """JSON-ready quality summary (bench artifacts, /status extras,
        engine.stats())."""
        out = self._event_fields()
        out["pending"] = len(self.joiner)
        out["expired"] = self.joiner.expired
        out["drift_suspected"] = self.drift_suspected
        return out

    def accuracy(self, model: Optional[int] = None) -> Optional[float]:
        with self._lock:
            w = self._overall if model is None \
                else self._models.get(int(model))
            if w is None or not w.correct:
                return None
            return float(sum(w.correct)) / len(w.correct)

    def on_swap(self) -> None:
        """Generation swap hook: re-anchor the shift detector (the new
        generation's output distribution is a legitimate step)."""
        with self._lock:
            self.drift.reset()
