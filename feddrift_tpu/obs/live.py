"""Live ops plane: /metrics + /healthz HTTP endpoints, fleet snapshot
publishing over the broker, and an SLO burn-rate engine on the event tap.

Everything observability built before this module is post-hoc: the
Prometheus exporter writes a textfile, ``report --follow`` tails one
file, ``critical_path`` replays a finished run. This module operates a
*running* process:

- ``OpsServer`` — a stdlib ``ThreadingHTTPServer`` on a background
  daemon thread (off the hot path; enabled via ``cfg.ops_port``, 0 =
  disabled) serving

  * ``/metrics``  — the live ``Registry.to_prometheus_text()`` (same
    exporter as the per-iteration ``metrics.prom`` textfile, minus the
    file),
  * ``/healthz``  — liveness: last-iteration beat age, broker-connection
    state aggregated from every live ``ReconnectingBrokerClient``
    (their heartbeat loopbacks), and active SLO burns; HTTP 503 when
    degraded,
  * ``/status``   — a JSON run summary (iteration, rounds/s,
    ``num_models``, live ``oracle_ari``, active alerts, live p50/p95/p99
    digests).

- the **fleet plane** — each process publishes periodic metric+health
  snapshots on ``<ns>/ops/<lane>`` broker topics (``OpsPublisher``),
  announcing its lane on ``<ns>/ops/announce`` so a ``FleetCollector``
  can discover and merge them; ``python -m feddrift_tpu fleet
  <host:port>`` renders the merged multi-process table.

- the **SLO engine** — declarative windowed objectives (rounds/s floor,
  ``host_overhead_frac`` ceiling, per-round wall ceiling, eval gap,
  broker liveness) with error-budget burn-rate rules, evaluated live on
  the event-bus tap (not file replay). A burning objective emits an
  ``slo_burn`` event, increments ``slo_burns{slo=...}`` and appends to
  the same ``alerts.jsonl`` the alert monitor uses
  (``obs.alerts.append_alert``).

The module is stdlib + obs.events/instruments/alerts only; the broker
client for the ``fleet`` CLI verb is imported lazily, so the verb stays
jax-free (routable before backend init like ``report``/``regress``).
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import socket
import threading
import time
import weakref
from dataclasses import dataclass
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional

from feddrift_tpu.obs import alerts as obs_alerts
from feddrift_tpu.obs.events import emit
from feddrift_tpu.obs.instruments import registry

log = logging.getLogger("feddrift_tpu")

OPS_NAMESPACE = "feddrift"


# ----------------------------------------------------------------------
# process status board: the single source /status, /healthz and fleet
# snapshots read. Fed by StatusTap (event-driven) or directly.
class StatusBoard:
    """Thread-safe latest-value store for the process's run state plus
    the last-iteration beat (monotonic, for /healthz age)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._fields: dict = {}
        self._beat_mono: Optional[float] = None
        self._beat_iteration: Optional[int] = None

    def beat(self, iteration: Optional[int] = None) -> None:
        with self._lock:
            self._beat_mono = time.monotonic()
            if iteration is not None:
                self._beat_iteration = iteration

    def update(self, **fields) -> None:
        with self._lock:
            self._fields.update(fields)

    def fields(self) -> dict:
        with self._lock:
            out = dict(self._fields)
            if self._beat_iteration is not None:
                out.setdefault("iteration", self._beat_iteration)
            return out

    def last_iteration_age(self) -> Optional[float]:
        with self._lock:
            if self._beat_mono is None:
                return None
            return time.monotonic() - self._beat_mono

    def reset(self) -> None:
        with self._lock:
            self._fields.clear()
            self._beat_mono = None
            self._beat_iteration = None


_status = StatusBoard()


def status_board() -> StatusBoard:
    return _status


class StatusTap:
    """EventBus tap feeding the status board: iteration_end beats +
    rounds/s, cluster_state num_models, cluster_assign oracle ARI."""

    def __init__(self, board: Optional[StatusBoard] = None) -> None:
        self.board = board if board is not None else _status

    def attach(self, bus) -> "StatusTap":
        bus.add_tap(self.observe)
        return self

    def observe(self, rec: dict) -> None:
        kind = rec.get("kind")
        if kind == "iteration_end":
            self.board.beat(rec.get("iteration"))
            self.board.update(
                rounds_per_s=rec.get("rounds_per_s"),
                test_acc=rec.get("test_acc"),
                last_iteration_wall_s=rec.get("wall_s"))
        elif kind == "cluster_state":
            self.board.update(num_models=rec.get("num_models"))
        elif kind == "cluster_assign":
            if rec.get("oracle_ari") is not None:
                self.board.update(oracle_ari=rec.get("oracle_ari"))
        elif kind == "run_start":
            self.board.beat(rec.get("iteration"))
            self.board.update(num_models=rec.get("num_models"),
                              run_phase="running")
        elif kind == "run_end":
            self.board.update(run_phase="done")


# ----------------------------------------------------------------------
# broker-connection health: every ReconnectingBrokerClient registers
# itself here (weakly) so /healthz can aggregate heartbeat liveness.
_BROKER_CLIENTS: "weakref.WeakSet" = weakref.WeakSet()


def register_broker_client(client) -> None:
    _BROKER_CLIENTS.add(client)


def broker_health() -> dict:
    detail = []
    for c in list(_BROKER_CLIENTS):
        try:
            if getattr(c, "_closed", False):
                continue
            detail.append(c.health())
        except Exception:           # a half-torn-down client must not 500
            continue
    return {
        "clients": len(detail),
        "healthy": all(h.get("healthy") for h in detail) if detail else True,
        "reconnects": sum(h.get("reconnects") or 0 for h in detail),
        "detail": detail,
    }


# ----------------------------------------------------------------------
# SLO engine: declarative windowed objectives + burn-rate rules.
@dataclass
class SLObjective:
    """One service-level objective over a stream of event-derived samples.

    ``value(rec)`` extracts a sample from a triggering event (None =
    no sample). ``direction`` says which side violates: ``"max"`` —
    value above ``objective`` is a violation; ``"min"`` — below. The
    error budget allows ``budget_frac`` of the window to violate; the
    rule *burns* when the observed violating fraction reaches
    ``budget_frac * burn_rate`` (with ``budget_frac == 0`` any violation
    burns, and a healthy sample resets the window — incident mode)."""

    name: str
    kinds: tuple
    value: Callable[[dict], Optional[float]]
    objective: float
    direction: str = "max"
    window: int = 20
    budget_frac: float = 0.1
    burn_rate: float = 2.0
    min_samples: int = 5
    cooldown_s: float = 30.0
    severity: str = "warn"
    description: str = ""

    def __post_init__(self) -> None:
        if self.direction not in ("max", "min"):
            raise ValueError(f"direction must be max|min, got "
                             f"{self.direction!r}")
        if self.window < 1 or self.min_samples < 1:
            raise ValueError("window and min_samples must be >= 1")


def default_slos(rounds_per_s: float = 0.0,
                 host_overhead: float = 0.0,
                 p99_round_wall_s: float = 0.0,
                 eval_gap: float = 0.0,
                 model_accuracy: float = 0.0) -> list:
    """The runner's objective set; a threshold of 0 disables that
    objective. Broker liveness is always on (it only samples on
    heartbeat/reconnect events, so it is free otherwise)."""
    objs = [
        SLObjective(
            "broker_liveness", ("heartbeat_missed", "conn_reconnect"),
            lambda r: 1.0 if r.get("kind") == "heartbeat_missed" else 0.0,
            objective=0.5, direction="max", window=8, budget_frac=0.0,
            burn_rate=1.0, min_samples=1, cooldown_s=5.0, severity="crit",
            description="broker heartbeat loopback went silent"),
    ]
    if rounds_per_s > 0:
        objs.append(SLObjective(
            "rounds_per_s_floor", ("iteration_end",),
            lambda r: r.get("rounds_per_s"),
            objective=rounds_per_s, direction="min", window=12,
            budget_frac=0.25, burn_rate=2.0, min_samples=4,
            cooldown_s=30.0, severity="warn",
            description="sustained rounds/s below the throughput floor"))
    if host_overhead > 0:
        objs.append(SLObjective(
            "host_overhead_ceiling", ("round_breakdown",),
            lambda r: r.get("host_overhead_frac"),
            objective=host_overhead, direction="max", window=12,
            budget_frac=0.25, burn_rate=2.0, min_samples=4,
            cooldown_s=30.0, severity="warn",
            description="host_overhead_frac persistently above ceiling"))
    if p99_round_wall_s > 0:
        objs.append(SLObjective(
            "p99_round_wall", ("round_breakdown",),
            lambda r: (r["wall_s"] / max(r.get("rounds") or 1, 1)
                       if r.get("wall_s") is not None else None),
            objective=p99_round_wall_s, direction="max", window=64,
            budget_frac=0.01, burn_rate=5.0, min_samples=8,
            cooldown_s=30.0, severity="crit",
            description="per-round wall tail above the p99 objective"))
    if eval_gap > 0:
        objs.append(SLObjective(
            "eval_gap", ("eval",),
            lambda r: (r["train_acc"] - r["test_acc"]
                       if r.get("train_acc") is not None
                       and r.get("test_acc") is not None else None),
            objective=eval_gap, direction="max", window=6,
            budget_frac=0.34, burn_rate=1.5, min_samples=2,
            cooldown_s=60.0, severity="warn",
            description="train-test accuracy gap above objective"))
    if model_accuracy > 0:
        objs.append(SLObjective(
            "model_accuracy_floor", ("model_quality",),
            lambda r: r.get("accuracy"),
            objective=model_accuracy, direction="min", window=8,
            budget_frac=0.25, burn_rate=2.0, min_samples=3,
            cooldown_s=30.0, severity="crit",
            description="serving joined-label accuracy below the floor "
                        "(obs/quality.py windowed estimate)"))
    return objs


class SLOEngine:
    """Evaluates SLObjectives live on the event tap; burning objectives
    emit ``slo_burn`` (cooldown-limited) and stay listed in ``active()``
    until a window evaluation clears them."""

    def __init__(self, objectives: Optional[list] = None,
                 path: Optional[str] = None, bus=None,
                 time_fn: Callable[[], float] = time.time,
                 max_bytes: int = 0) -> None:
        import collections
        self.objectives = objectives if objectives is not None \
            else default_slos()
        self.path = path
        self.max_bytes = int(max_bytes)   # alerts.jsonl size cap (0 = off)
        self.bus = bus
        self._time = time_fn
        self._lock = threading.RLock()
        self._win = {o.name: collections.deque(maxlen=o.window)
                     for o in self.objectives}
        self._active: dict[str, dict] = {}
        self._last_fired: dict[str, float] = {}
        self.burns: list[dict] = []
        self._by_kind: dict[str, list] = {}
        for o in self.objectives:
            for k in o.kinds:
                self._by_kind.setdefault(k, []).append(o)

    def attach(self, bus) -> "SLOEngine":
        self.bus = bus
        bus.add_tap(self.observe)
        return self

    def observe(self, rec: dict) -> None:
        kind = rec.get("kind")
        objs = self._by_kind.get(kind)
        if not objs or kind in ("slo_burn", "alert_raised"):
            return
        now = rec.get("_ts") or self._time()
        with self._lock:
            for obj in objs:
                try:
                    v = obj.value(rec)
                except (KeyError, TypeError):
                    v = None
                if v is None:
                    continue
                violating = (v > obj.objective if obj.direction == "max"
                             else v < obj.objective)
                win = self._win[obj.name]
                if obj.budget_frac == 0.0 and not violating:
                    win.clear()       # incident mode: healthy sample heals
                win.append(1 if violating else 0)
                frac = sum(win) / len(win)
                burn_at = (obj.budget_frac * obj.burn_rate
                           if obj.budget_frac > 0 else 1e-9)
                burning = len(win) >= obj.min_samples and frac >= burn_at
                if not burning:
                    self._active.pop(obj.name, None)
                    continue
                summary = {
                    "slo": obj.name, "severity": obj.severity,
                    "objective": obj.objective,
                    "direction": obj.direction,
                    "observed": round(float(v), 6),
                    "window": len(win), "violations": int(sum(win)),
                    "burn_frac": round(frac, 4),
                    "budget_frac": obj.budget_frac,
                    "burn_rate": obj.burn_rate,
                    "description": obj.description,
                }
                self._active[obj.name] = summary
                last = self._last_fired.get(obj.name)
                if last is not None and now - last < obj.cooldown_s:
                    continue
                self._last_fired[obj.name] = now
                self._fire(summary, rec)

    def _fire(self, summary: dict, trigger: dict) -> None:
        fields = {**summary, "rule": f"slo:{summary['slo']}",
                  "trigger_kind": trigger.get("kind")}
        if self.bus is not None:
            burn = self.bus.emit("slo_burn", **fields)
        else:
            burn = {"_ts": self._time(), "kind": "slo_burn",
                    "iteration": trigger.get("iteration"), **fields}
        self.burns.append(burn)
        try:
            registry().counter("slo_burns", slo=summary["slo"]).inc()
        except Exception:
            pass
        if self.path:
            obs_alerts.append_alert(self.path, burn,
                                    max_bytes=self.max_bytes)
        log.warning("SLO burn: %s (observed=%s objective=%s, %d/%d "
                    "window violations)", summary["slo"],
                    summary["observed"], summary["objective"],
                    summary["violations"], summary["window"])

    def active(self) -> list:
        with self._lock:
            return [dict(v) for v in self._active.values()]


# ----------------------------------------------------------------------
# health + status documents (shared by /healthz, /status and fleet
# snapshots)
def health_snapshot(slo: Optional[SLOEngine] = None,
                    stall_after_s: float = 0.0,
                    board: Optional[StatusBoard] = None) -> dict:
    board = board if board is not None else _status
    age = board.last_iteration_age()
    brokers = broker_health()
    active = slo.active() if slo is not None else []
    degraded = []
    if brokers["clients"] and not brokers["healthy"]:
        degraded.append("broker")
    if any(a.get("severity") == "crit" for a in active):
        degraded.append("slo_burn")
    if stall_after_s > 0 and age is not None and age > stall_after_s:
        degraded.append("stalled")
    return {
        "status": "degraded" if degraded else "ok",
        "degraded": degraded,
        "last_iteration_age_s": round(age, 3) if age is not None else None,
        "broker": brokers,
        "active_alerts": active,
        "pid": os.getpid(),
    }


def _quantile_digests(reg=None) -> dict:
    """Live p50/p95/p99 digests: every registered QuantileSketch series
    (snapshot keys carrying a quantiles sub-dict)."""
    snap = (reg if reg is not None else registry()).snapshot()
    return {k: v["quantiles"] for k, v in snap.items()
            if isinstance(v, dict) and "quantiles" in v}


# p99 exemplars: the latest outlier next to a sketch's digest (e.g. the
# worst serve request's trace id beside request_latency_seconds_q), so a
# tail spike in /status is one hop from its trace.json slice. A sketch
# keeps no samples, so the exemplar is the only survivor of the outlier.
_exemplars: dict[str, dict] = {}
_exemplars_lock = threading.Lock()


def record_exemplar(name: str, **fields) -> None:
    with _exemplars_lock:
        _exemplars[name] = {**fields, "ts": round(time.time(), 3)}


def exemplars() -> dict:
    with _exemplars_lock:
        return {k: dict(v) for k, v in _exemplars.items()}


def status_snapshot(slo: Optional[SLOEngine] = None,
                    board: Optional[StatusBoard] = None,
                    reg=None) -> dict:
    board = board if board is not None else _status
    doc = board.fields()
    doc["active_alerts"] = slo.active() if slo is not None else []
    doc["quantiles"] = _quantile_digests(reg)
    ex = exemplars()
    if ex:
        doc["exemplars"] = ex
    # host-plane observatory: process RSS + the ledger's biggest tracked
    # structures, so /status answers "what is this process's host memory
    # doing" without grepping events.jsonl
    from feddrift_tpu.obs import hostprof
    rss = hostprof.rss_bytes()
    led = hostprof.ledger()
    doc["host"] = {
        "rss_mb": round(rss / (1 << 20), 1) if rss else None,
        "rss_peak_mb": round(led.rss_peak_bytes / (1 << 20), 1)
        if led.rss_peak_bytes else None,
        "top_structures": {k: v for k, v in led.top_bytes(3)},
    }
    doc["pid"] = os.getpid()
    return doc


_METRIC_PREFIXES = (
    "broker_", "client_", "comm_bytes", "stragglers_masked",
    "rounds_degraded", "host_overhead_frac", "round_wall_seconds_q",
    "dispatch_gap_seconds_q", "num_models", "alerts_raised", "slo_burns",
    "heartbeats_missed", "edge_", "publish_retries",
    # serving read path + model-quality plane (platform/serving.py,
    # obs/quality.py, platform/canary.py) — "requests_" also covers the
    # shed/expired/abandoned overload counters; "frontend_"/"replica_"
    # are the admission + failover plane (platform/frontend.py)
    "requests_", "serve_", "pool_version", "pool_swaps",
    "request_latency_seconds_q", "model_accuracy_q", "canary_",
    "frontend_", "replica_",
    # host-plane observatory (obs/hostprof.py): per-subsystem seconds,
    # per-structure bytes, RSS, and the routing-rebuild counter
    "host_ledger_seconds", "host_bytes", "host_rss_bytes",
    "routing_rebuilds",
)


def snapshot_fields(lane: str, reg=None, slo: Optional[SLOEngine] = None,
                    board: Optional[StatusBoard] = None,
                    prefixes: tuple = _METRIC_PREFIXES,
                    extra: Optional[dict] = None) -> dict:
    """One fleet snapshot: lane identity + status + health + a filtered
    metric subset (full registry snapshots carry per-phase histograms —
    too heavy to ship every couple of seconds)."""
    reg = reg if reg is not None else registry()
    metrics = {k: v for k, v in reg.snapshot().items()
               if k.startswith(prefixes)}
    snap = {
        "lane": lane,
        "pid": os.getpid(),
        "ts": round(time.time(), 3),
        "status": status_snapshot(slo=slo, board=board, reg=reg),
        "health": health_snapshot(slo=slo, board=board),
        "metrics": metrics,
    }
    if extra:
        snap["extra"] = extra
    return snap


def emit_snapshot(lane: str, seq: int = 0,
                  slo: Optional[SLOEngine] = None,
                  board: Optional[StatusBoard] = None) -> dict:
    """Record a lean ops_snapshot event locally (the runner's snapshot
    cadence and every fleet publish go through here)."""
    board = board if board is not None else _status
    fields = board.fields()
    digests = _quantile_digests()
    p99 = (digests.get("round_wall_seconds_q") or {}).get("0.99")
    return emit(
        "ops_snapshot", lane=lane, seq=seq,
        health=health_snapshot(slo=slo, board=board)["status"],
        rounds_per_s=fields.get("rounds_per_s"),
        round_wall_p99_s=p99,
        active_alerts=len(slo.active()) if slo is not None else 0)


# ----------------------------------------------------------------------
# the HTTP ops server
class _OpsHandler(BaseHTTPRequestHandler):
    server_version = "feddrift-ops/1"

    def log_message(self, fmt, *args):  # noqa: N802 - stdlib API
        log.debug("ops %s " + fmt, self.client_address[0], *args)

    def _send(self, code: int, body: bytes, ctype: str) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):  # noqa: N802 - stdlib API
        ops = self.server.ops                       # type: ignore[attr-defined]
        path = self.path.split("?", 1)[0].rstrip("/") or "/"
        try:
            if path == "/metrics":
                body = ops.reg.to_prometheus_text().encode()
                self._send(200, body,
                           "text/plain; version=0.0.4; charset=utf-8")
            elif path == "/healthz":
                doc = health_snapshot(slo=ops.slo,
                                      stall_after_s=ops.stall_after_s,
                                      board=ops.board)
                code = 200 if doc["status"] == "ok" else 503
                self._send(code, _json_bytes(doc), "application/json")
            elif path in ("/", "/status"):
                doc = status_snapshot(slo=ops.slo, board=ops.board,
                                      reg=ops.reg)
                self._send(200, _json_bytes(doc), "application/json")
            else:
                self._send(404, b'{"error": "not found"}',
                           "application/json")
        except (BrokenPipeError, ConnectionResetError):
            pass
        except Exception as exc:        # never let a scrape kill the thread
            try:
                self._send(500, _json_bytes({"error": str(exc)}),
                           "application/json")
            except OSError:
                pass


def _json_bytes(doc: dict) -> bytes:
    return json.dumps(doc, default=obs_alerts._json_default).encode()


class OpsServer:
    """Per-process ops endpoint host. ``port=0`` binds an ephemeral port
    (read it back from ``.port``); the serving loop and every request run
    on daemon threads, entirely off the training hot path."""

    def __init__(self, port: int = 0, host: str = "127.0.0.1",
                 reg=None, slo: Optional[SLOEngine] = None,
                 board: Optional[StatusBoard] = None,
                 stall_after_s: float = 0.0) -> None:
        self.reg = reg if reg is not None else registry()
        self.slo = slo
        self.board = board if board is not None else _status
        self.stall_after_s = stall_after_s
        self._httpd = ThreadingHTTPServer((host, port), _OpsHandler)
        self._httpd.daemon_threads = True
        self._httpd.ops = self          # type: ignore[attr-defined]
        self.host = host
        self.port = self._httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "OpsServer":
        # Long poll interval on purpose: select() wakes instantly for an
        # incoming request regardless, so the interval only bounds how
        # fast serve_forever notices shutdown() — and on a single-core
        # host every idle wakeup preempts the training thread (a 0.2s
        # interval measurably costs rounds/s; see perf_gate stage 7).
        # close() pokes the socket so shutdown stays fast anyway.
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, kwargs={"poll_interval": 30.0},
            daemon=True, name=f"ops-server:{self.port}")
        self._thread.start()
        log.info("ops server listening on http://%s:%d "
                 "(/metrics /healthz /status)", self.host, self.port)
        return self

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def close(self) -> None:
        if self._thread is not None:
            stopper = threading.Thread(target=self._httpd.shutdown,
                                       daemon=True)
            stopper.start()
            # shutdown() only takes effect when the serve loop's select()
            # returns; connect to our own socket so it returns now instead
            # of after the (long) poll interval.
            deadline = time.time() + 5.0
            while stopper.is_alive() and time.time() < deadline:
                try:
                    socket.create_connection(
                        (self.host, self.port), timeout=0.2).close()
                except OSError:
                    pass
                stopper.join(timeout=0.1)
            stopper.join(timeout=1.0)
            self._thread.join(timeout=2)
            self._thread = None
        self._httpd.server_close()


# ----------------------------------------------------------------------
# fleet plane: per-process snapshot publishing + collector merge
def ops_topic(namespace: str, lane: str) -> str:
    return f"{namespace}/ops/{lane}"


def announce_topic(namespace: str) -> str:
    return f"{namespace}/ops/announce"


# --- ops/incident lane (obs/blackbox.py, obs/incident.py) -------------
# Request/response over the same broker the snapshots ride: a collector
# publishes a pull on ``<ns>/ops/incident/pull``; every publisher armed
# with a ``flight_fn`` answers on its own ``<ns>/ops/incident/<lane>``
# with a flight-recorder ring snapshot. The frontend uses this to merge
# per-replica black boxes into ONE bundle when a replica dies.
def incident_topic(namespace: str, lane: str) -> str:
    return f"{namespace}/ops/incident/{lane}"


def incident_pull_topic(namespace: str) -> str:
    return f"{namespace}/ops/incident/pull"


def pull_flights(client, lanes, namespace: str = OPS_NAMESPACE,
                 timeout_s: float = 3.0, poll_s: float = 0.1) -> dict:
    """Pull per-process flight snapshots from ``lanes`` over the
    ops/incident lane; returns ``{lane: payload}`` for every lane that
    answered within ``timeout_s`` (dead processes simply stay absent —
    their silence is itself evidence)."""
    lanes = sorted(set(lanes))
    qs = {lane: client.subscribe(incident_topic(namespace, lane))
          for lane in lanes}
    client.publish(incident_pull_topic(namespace),
                   json.dumps({"want": lanes}))
    out: dict[str, dict] = {}
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline and len(out) < len(lanes):
        for lane, q in qs.items():
            for raw in FleetCollector._drain(q):
                try:
                    out[lane] = json.loads(raw)
                except ValueError:
                    continue
        if len(out) < len(lanes):
            time.sleep(poll_s)
    return out


class OpsPublisher:
    """Publishes this process's snapshot on ``<ns>/ops/<lane>`` every
    ``interval_s`` (daemon thread), announcing the lane on
    ``<ns>/ops/announce`` so collectors can discover it. Works over any
    Broker-interface client; publish failures on a dying bare client are
    swallowed (a reconnecting client buffers them itself)."""

    def __init__(self, client, lane: str,
                 namespace: str = OPS_NAMESPACE, interval_s: float = 2.0,
                 reg=None, slo: Optional[SLOEngine] = None,
                 board: Optional[StatusBoard] = None,
                 extra_fn: Optional[Callable[[], dict]] = None,
                 flight_fn: Optional[Callable[[], dict]] = None) -> None:
        self.client = client
        self.lane = lane
        self.namespace = namespace
        self.interval_s = interval_s
        self.reg = reg
        self.slo = slo
        self.board = board
        self.extra_fn = extra_fn
        # ops/incident lane: answer flight-snapshot pulls with this
        # payload (None = lane not armed, no extra subscription)
        self.flight_fn = flight_fn
        self._pull_q = None
        self.seq = 0
        self._closed = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def publish_now(self) -> dict:
        self.seq += 1
        extra = None
        if self.extra_fn is not None:
            try:
                extra = self.extra_fn()
            except Exception:
                extra = None
        snap = snapshot_fields(self.lane, reg=self.reg, slo=self.slo,
                               board=self.board, extra=extra)
        snap["seq"] = self.seq
        try:
            self.client.publish(announce_topic(self.namespace),
                                json.dumps({"lane": self.lane}))
            self.client.publish(ops_topic(self.namespace, self.lane),
                                json.dumps(
                                    snap, default=obs_alerts._json_default))
        except (OSError, RuntimeError):
            pass                        # dead bare client; next tick retries
        emit_snapshot(self.lane, seq=self.seq, slo=self.slo,
                      board=self.board)
        return snap

    def _answer_pulls(self) -> None:
        """Answer any queued ops/incident pull with one flight-snapshot
        publish on this lane's incident topic."""
        if self.flight_fn is None or self._pull_q is None:
            return
        if not FleetCollector._drain(self._pull_q):
            return
        import os as _os
        try:
            payload = {"lane": self.lane, "pid": _os.getpid(),
                       "ts": round(time.time(), 3),
                       "seq": self.seq,
                       "flight": self.flight_fn()}
        except Exception:   # noqa: BLE001 — a failing dump never kills
            return          # the publisher thread
        try:
            self.client.publish(
                incident_topic(self.namespace, self.lane),
                json.dumps(payload, default=obs_alerts._json_default))
        except (OSError, RuntimeError):
            pass                        # dead bare client; pull re-asks

    def _loop(self) -> None:
        # with the incident lane armed, wake often enough that a pull is
        # answered well inside pull_flights' timeout; snapshots still
        # publish on the configured cadence
        wake = min(self.interval_s, 0.25) if self.flight_fn is not None \
            else self.interval_s
        elapsed = 0.0
        while not self._closed.wait(wake):
            self._answer_pulls()
            elapsed += wake
            if elapsed + 1e-9 >= self.interval_s:
                self.publish_now()
                elapsed = 0.0

    def start(self) -> "OpsPublisher":
        if self.flight_fn is not None and self._pull_q is None:
            try:
                self._pull_q = self.client.subscribe(
                    incident_pull_topic(self.namespace))
            except (OSError, RuntimeError):
                self._pull_q = None
        self._thread = threading.Thread(
            target=self._loop, daemon=True,
            name=f"ops-publisher:{self.lane}")
        self._thread.start()
        return self

    def close(self) -> None:
        self._closed.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None


class FleetCollector:
    """Merges fleet snapshots by process lane: subscribes the announce
    topic, subscribes each announced lane's ops topic, and keeps the
    latest snapshot per lane. Poll-driven (no threads of its own)."""

    def __init__(self, client, namespace: str = OPS_NAMESPACE) -> None:
        self.client = client
        self.namespace = namespace
        self.lanes: dict[str, dict] = {}
        self._announce_q = client.subscribe(announce_topic(namespace))
        self._lane_qs: dict[str, object] = {}

    @staticmethod
    def _drain(q) -> list:
        import queue as _queue
        out = []
        while True:
            try:
                out.append(q.get_nowait())
            except _queue.Empty:
                return out

    def poll(self) -> dict:
        for raw in self._drain(self._announce_q):
            try:
                lane = json.loads(raw).get("lane")
            except (ValueError, AttributeError):
                continue
            if lane and lane not in self._lane_qs:
                self._lane_qs[lane] = self.client.subscribe(
                    ops_topic(self.namespace, lane))
        for lane, q in self._lane_qs.items():
            for raw in self._drain(q):
                try:
                    snap = json.loads(raw)
                except ValueError:
                    continue
                prev = self.lanes.get(lane)
                if prev is None or snap.get("seq", 0) >= prev.get("seq", 0):
                    self.lanes[lane] = snap
        return self.lanes

    def collect(self, duration_s: float = 5.0, poll_s: float = 0.2,
                min_lanes: int = 0) -> dict:
        """Poll for up to ``duration_s``; returns early once
        ``min_lanes`` distinct lanes reported (0 = wait the full
        bound)."""
        deadline = time.monotonic() + duration_s
        while time.monotonic() < deadline:
            self.poll()
            if min_lanes and len(self.lanes) >= min_lanes:
                break
            time.sleep(poll_s)
        return self.poll()

    def pull_flights(self, lanes=None, timeout_s: float = 3.0) -> dict:
        """The ops/incident lane: pull per-process flight-recorder
        snapshots from ``lanes`` (default: every lane this collector
        has seen announce). Lanes that stay silent are absent from the
        result — a dead process cannot answer."""
        self.poll()
        return pull_flights(self.client,
                            lanes if lanes is not None else self.lanes,
                            namespace=self.namespace, timeout_s=timeout_s)


def _fmt(v, nd=3) -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.{nd}f}"
    return str(v)


def _metric(snap: dict, prefix: str):
    """Sum every metric series of one name across label sets (e.g.
    client_bytes_out{transport=...})."""
    total, seen = 0.0, False
    for k, v in (snap.get("metrics") or {}).items():
        if k == prefix or k.startswith(prefix + "{"):
            if isinstance(v, (int, float)):
                total, seen = total + v, True
    return total if seen else None


def _sketch_q(snap: dict, name: str, q: str):
    for k, v in (snap.get("metrics") or {}).items():
        if (k == name or k.startswith(name + "{")) and isinstance(v, dict):
            qv = (v.get("quantiles") or {}).get(q)
            if qv is not None:
                return qv
    return None


def render_fleet(lanes: dict, stale_after: Optional[float] = None,
                 now: Optional[float] = None) -> str:
    """The merged multi-process table the ``fleet`` CLI verb prints.

    ``stale_after`` (seconds) evicts lanes whose last snapshot is older
    than the bound: instead of rendering a frozen snapshot as if it were
    live, the lane collapses to an AGE + a loud ``(stale)`` marker. The
    AGE column always shows seconds since each lane's last snapshot
    ``ts`` (``-`` when the snapshot predates the ts field)."""
    cols = ("LANE", "PID", "AGE", "ITER", "ROUNDS/S", "P99 WALL",
            "BYTES OUT", "HOST-MB", "STRAGGLERS", "RECONNECTS", "REQ/S",
            "P99-REQ", "POOL-VER", "CANARY", "ALERTS", "HEALTH")
    now = time.time() if now is None else now
    rows = []
    for lane in sorted(lanes):
        snap = lanes[lane]
        ts = snap.get("ts")
        age = max(now - ts, 0.0) if isinstance(ts, (int, float)) else None
        if stale_after is not None and age is not None \
                and age > stale_after:
            # evicted: no frozen metrics, just the lane, its age and the
            # loud marker — silent freshness is the failure mode here
            rows.append((lane, _fmt(snap.get("pid")), f"{age:.0f}s",
                         *("-",) * 12, "(stale)"))
            continue
        st = snap.get("status") or {}
        health = snap.get("health") or {}
        extra = snap.get("extra") or {}
        bytes_out = _metric(snap, "client_bytes_out")
        if bytes_out is None:
            bytes_out = _metric(snap, "broker_bytes_out")
        pool_ver = _metric(snap, "pool_version")
        # process RSS from the host-plane ledger gauge; falls back to the
        # /status host block for lanes that snapshot status but no metrics
        rss = _metric(snap, "host_rss_bytes")
        host_mb = (round(rss / (1 << 20), 1) if rss
                   else (st.get("host") or {}).get("rss_mb"))
        rows.append((
            lane,
            _fmt(snap.get("pid")),
            f"{age:.0f}s" if age is not None else "-",
            _fmt(st.get("iteration")),
            _fmt(st.get("rounds_per_s")),
            _fmt(_sketch_q(snap, "round_wall_seconds_q", "0.99"), 4),
            _fmt(int(bytes_out) if bytes_out is not None else None),
            _fmt(host_mb, 1),
            _fmt(_metric(snap, "stragglers_masked")),
            _fmt((health.get("broker") or {}).get("reconnects")),
            _fmt(extra.get("requests_per_s"), 1),
            _fmt(_sketch_q(snap, "request_latency_seconds_q", "0.99"), 4),
            _fmt(int(pool_ver) if pool_ver is not None else None),
            _fmt(extra.get("canary")),
            _fmt(len(st.get("active_alerts") or [])),
            health.get("status", "-"),
        ))
    widths = [max(len(c), *(len(r[i]) for r in rows)) if rows else len(c)
              for i, c in enumerate(cols)]
    lines = ["  ".join(c.ljust(widths[i]) for i, c in enumerate(cols))]
    for r in rows:
        lines.append("  ".join(v.ljust(widths[i]) for i, v in enumerate(r)))
    if not rows:
        lines.append("(no lanes reported)")
    return "\n".join(lines)


def fleet_main(argv=None) -> int:
    """``python -m feddrift_tpu fleet <host:port>`` — collect fleet
    snapshots from a live broker and render the merged table. Pure
    host-side (no jax/backend initialisation)."""
    ap = argparse.ArgumentParser(
        prog="python -m feddrift_tpu fleet",
        description="render a live multi-process ops table from "
                    "<ns>/ops/* broker snapshots")
    ap.add_argument("broker", help="broker address, host:port")
    ap.add_argument("--namespace", default=OPS_NAMESPACE)
    ap.add_argument("--duration", type=float, default=5.0,
                    help="collection bound in seconds (default 5)")
    ap.add_argument("--poll", type=float, default=0.2)
    ap.add_argument("--min-lanes", type=int, default=0,
                    help="return as soon as this many lanes reported")
    ap.add_argument("--stale-after", type=float, default=60.0,
                    help="seconds after which a silent lane renders as "
                         "(stale) instead of its frozen last snapshot "
                         "(default 60; <= 0 disables)")
    ap.add_argument("--json", action="store_true",
                    help="print merged snapshots as JSON instead")
    args = ap.parse_args(argv)
    host, _, port = args.broker.rpartition(":")
    if not port.isdigit():
        ap.error(f"broker must be host:port, got {args.broker!r}")
    from feddrift_tpu.comm.netbroker import NetworkBrokerClient
    client = NetworkBrokerClient(host or "127.0.0.1", int(port))
    try:
        coll = FleetCollector(client, namespace=args.namespace)
        lanes = coll.collect(duration_s=args.duration, poll_s=args.poll,
                             min_lanes=args.min_lanes)
    finally:
        client.close()
    if args.json:
        print(json.dumps(lanes, indent=2,
                         default=obs_alerts._json_default))
    else:
        print(render_fleet(
            lanes,
            stale_after=args.stale_after if args.stale_after > 0 else None))
    return 0 if lanes else 1
