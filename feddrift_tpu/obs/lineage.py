"""Cluster lineage: the genealogy DAG + oracle scoring behind
``python -m feddrift_tpu lineage <run_dir>``.

The drift algorithms operate on a fixed pool of MODEL SLOTS: a slot is
created for a drifted client set, absorbs another slot in a hierarchical
merge, gets reset by FedDrift-C / softclusterreset, is bipartitioned by
CFL, and — crucially — is REUSED once the LRU allocator runs out of free
slots. Raw ``cluster_*`` events therefore tell a slot-indexed story in
which "model 1" can be three different concepts over a run. This module
replays the event stream and resolves slot reuse into stable LINEAGE
IDS (``L0``, ``L1``, ...): one id per concept-model incarnation, with
create/merge/split/delete edges forming a genealogy DAG.

The EM view of federated clustering (arXiv:2111.10192) frames the
per-client assignment as the E-step; the per-iteration ``cluster_assign``
events are exactly that state, and — for synthetic datasets whose
ground-truth ``concept_matrix`` rides along in the ``run_start`` event —
the assignment timeline is scored with per-iteration Adjusted Rand Index
and cluster purity ("oracle agreement", the paper's central claim made
measurable; FedCluster arXiv:2009.10748 uses the same quality-trajectory
lens for convergence debugging).

Pure host-side: numpy + stdlib only, safe to run from the jax-free CLI
path (like ``obs.report``).

    python -m feddrift_tpu lineage runs/sea-fnn-softcluster-H_A_C_1_10_0-s0
    python -m feddrift_tpu lineage <run_dir> --dot lineage.dot --json
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import Any, Optional

import numpy as np

# Event kinds the genealogy replay consumes (a subset of
# obs.events.EVENT_KINDS; the lineage builder ignores everything else).
GENEALOGY_KINDS = ("cluster_create", "cluster_merge", "cluster_delete",
                   "cluster_split", "cluster_assign")


# ----------------------------------------------------------------------
# oracle agreement metrics (hand-rolled: the report/lineage CLI path must
# stay dependency-light, and the closed-form ARI is ~15 lines)
def adjusted_rand_index(labels_true, labels_pred) -> float:
    """Adjusted Rand Index between two labelings (permutation-invariant).

    Standard Hubert-Arabie form via the contingency table. Both inputs
    are label vectors of equal length; label values are arbitrary ids
    (cluster slots vs. concept ids). Two trivial single-cluster
    partitions agree perfectly (1.0) rather than 0/0."""
    a = np.asarray(labels_true).ravel()
    b = np.asarray(labels_pred).ravel()
    if a.size != b.size:
        raise ValueError(f"label length mismatch: {a.size} vs {b.size}")
    n = a.size
    if n == 0:
        return 0.0
    _, ai = np.unique(a, return_inverse=True)
    _, bi = np.unique(b, return_inverse=True)
    cont = np.zeros((int(ai.max()) + 1, int(bi.max()) + 1), dtype=np.int64)
    np.add.at(cont, (ai, bi), 1)

    def comb2(x):
        x = np.asarray(x, dtype=np.float64)
        return x * (x - 1) / 2.0

    sum_ij = comb2(cont).sum()
    sum_a = comb2(cont.sum(axis=1)).sum()
    sum_b = comb2(cont.sum(axis=0)).sum()
    total = comb2(n)
    expected = sum_a * sum_b / total if total else 0.0
    max_index = (sum_a + sum_b) / 2.0
    if max_index == expected:        # both partitions trivial -> identical
        return 1.0
    return float((sum_ij - expected) / (max_index - expected))


def cluster_purity(labels_true, labels_pred) -> float:
    """Fraction of points whose predicted cluster's majority true label
    matches their own: sum over predicted clusters of the dominant true
    count, / n. 1.0 = every cluster is concept-pure."""
    a = np.asarray(labels_true).ravel()
    b = np.asarray(labels_pred).ravel()
    if a.size != b.size:
        raise ValueError(f"label length mismatch: {a.size} vs {b.size}")
    if a.size == 0:
        return 0.0
    correct = 0
    for cl in np.unique(b):
        members = a[b == cl]
        _, counts = np.unique(members, return_counts=True)
        correct += int(counts.max())
    return float(correct / a.size)


# ----------------------------------------------------------------------
# genealogy reconstruction
@dataclass
class LineageNode:
    """One incarnation of a cluster model: a pool slot between its
    creation (or first sighting) and its end (merge/delete/split/reuse)."""
    lid: str                          # stable id: "L0", "L1", ...
    slot: int                         # pool slot it occupied
    start: Optional[int]              # iteration created/first seen
    origin: str                       # root | drift_spawn | split | create
    parents: list = field(default_factory=list)     # lineage ids
    evidence: dict = field(default_factory=dict)    # creation evidence
    end: Optional[int] = None         # iteration the lineage ended
    end_reason: Optional[str] = None  # merged_into:<lid> | deleted:<r> |
    #                                   split | slot_reused
    absorbed: list = field(default_factory=list)    # merges INTO this node:
    #                                   {lid, iteration, evidence}
    children: list = field(default_factory=list)    # spawn/split children

    def to_json(self) -> dict:
        return {
            "lid": self.lid, "slot": self.slot, "start": self.start,
            "origin": self.origin, "parents": self.parents,
            "evidence": self.evidence, "end": self.end,
            "end_reason": self.end_reason, "absorbed": self.absorbed,
            "children": self.children,
        }


class Lineage:
    """The replayed genealogy: nodes + the per-iteration assignment rows."""

    def __init__(self) -> None:
        self.nodes: list[LineageNode] = []
        self.by_id: dict[str, LineageNode] = {}
        self._current: dict[int, LineageNode] = {}   # slot -> open node
        self.assignments: dict[int, dict] = {}       # iteration -> last event
        self.meta: dict[str, Any] = {}               # run_start payload

    # -- construction ---------------------------------------------------
    def _new_node(self, slot: int, start: Optional[int], origin: str,
                  parents: list[str], evidence: dict) -> LineageNode:
        node = LineageNode(lid=f"L{len(self.nodes)}", slot=int(slot),
                           start=start, origin=origin, parents=list(parents),
                           evidence=dict(evidence))
        self.nodes.append(node)
        self.by_id[node.lid] = node
        self._current[int(slot)] = node
        for p in parents:
            self.by_id[p].children.append(node.lid)
        return node

    def _ensure(self, slot: int, it: Optional[int]) -> LineageNode:
        """Open lineage on ``slot``; a slot referenced before any create
        event is a root (e.g. model 0, or every slot under IFCA/'F' init)."""
        node = self._current.get(int(slot))
        if node is None:
            node = self._new_node(slot, it, "root", [], {})
        return node

    def _end(self, node: LineageNode, it: Optional[int],
             reason: str) -> None:
        node.end = it
        node.end_reason = reason
        if self._current.get(node.slot) is node:
            del self._current[node.slot]

    def open_nodes(self) -> list[LineageNode]:
        return [n for n in self.nodes if n.end_reason is None]

    def roots(self) -> list[LineageNode]:
        return [n for n in self.nodes if not n.parents]


def build_lineage(events: list[dict]) -> Lineage:
    """Replay the event stream into a Lineage. Order = file order (the
    bus appends under one lock, so this is emission order)."""
    lin = Lineage()
    for e in events:
        kind = e.get("kind")
        it = e.get("iteration")
        if kind == "run_start":
            lin.meta = {k: v for k, v in e.items()
                        if k not in ("_ts", "kind")}
        elif kind == "cluster_create":
            slot = int(e["model"])
            init_from = e.get("init_from")
            parents = []
            if init_from is not None:
                parents = [lin._ensure(int(init_from), it).lid]
            old = lin._current.get(slot)
            if old is not None:        # LRU slot reuse: old incarnation ends
                lin._end(old, it, "slot_reused")
            evidence = {k: e[k] for k in ("client", "clients", "init_from")
                        if e.get(k) is not None}
            lin._new_node(slot, it, "drift_spawn", parents, evidence)
        elif kind == "cluster_merge":
            base = lin._ensure(int(e["base"]), it)
            merged = lin._ensure(int(e["merged"]), it)
            evidence = {k: e[k] for k in ("distance", "threshold",
                                          "distance_row", "in_use")
                        if e.get(k) is not None}
            lin._end(merged, it, f"merged_into:{base.lid}")
            base.absorbed.append({"lid": merged.lid, "iteration": it,
                                  "evidence": evidence})
        elif kind == "cluster_delete":
            node = lin._current.get(int(e["model"]))
            if node is not None:
                lin._end(node, it, f"deleted:{e.get('reason', '?')}")
        elif kind == "cluster_split":
            old = lin._ensure(int(e["model"]), it)
            lin._end(old, it, "split")
            evidence = {k: e[k] for k in ("clients_kept", "clients_moved",
                                          "alpha_cross", "gamma")
                        if e.get(k) is not None}
            lin._new_node(e["model"], it, "split", [old.lid],
                          {**evidence, "side": "kept"})
            lin._new_node(e["new_model"], it, "split", [old.lid],
                          {**evidence, "side": "moved"})
        elif kind == "cluster_assign":
            if it is not None:
                for slot in set(e.get("assignment", ())):
                    lin._ensure(int(slot), it)
                lin.assignments[int(it)] = e
    return lin


# ----------------------------------------------------------------------
# oracle scoring of the assignment timeline
def concept_matrix_from_events(events: list[dict]) -> Optional[np.ndarray]:
    """[T1, C] ground-truth concept matrix, carried by run_start for
    synthetic datasets (None for runs that predate it / huge matrices)."""
    for e in events:
        if e.get("kind") == "run_start":
            cm = e.get("concept_matrix")
            if cm:
                return np.asarray(cm, dtype=np.int64)
            return None
    return None


def score_timeline(lin: Lineage,
                   concept_matrix: Optional[np.ndarray]) -> list[dict]:
    """One row per iteration with a cluster_assign event: the assignment
    vector, models in use, and — when ground truth is available — ARI +
    purity recomputed against the concept matrix (falling back to the
    oracle_* fields the algorithm embedded live)."""
    rows = []
    for it in sorted(lin.assignments):
        e = lin.assignments[it]
        assign = e.get("assignment") or []
        row: dict[str, Any] = {
            "iteration": it,
            "assignment": [int(a) for a in assign],
            "num_models": len(set(assign)),
        }
        if concept_matrix is not None and it < concept_matrix.shape[0] \
                and len(assign) == concept_matrix.shape[1]:
            truth = concept_matrix[it]
            row["ari"] = round(adjusted_rand_index(truth, assign), 4)
            row["purity"] = round(cluster_purity(truth, assign), 4)
        elif e.get("oracle_ari") is not None:
            row["ari"] = e["oracle_ari"]
            row["purity"] = e.get("oracle_purity")
        rows.append(row)
    return rows


def oracle_summary(rows: list[dict]) -> Optional[dict]:
    aris = [r["ari"] for r in rows if r.get("ari") is not None]
    if not aris:
        return None
    purities = [r["purity"] for r in rows if r.get("purity") is not None]
    return {
        "final_ari": aris[-1],
        "best_ari": max(aris),
        "mean_ari": round(float(np.mean(aris)), 4),
        "final_purity": purities[-1] if purities else None,
    }


# ----------------------------------------------------------------------
# rendering
def _node_line(n: LineageNode) -> str:
    start = f"@t{n.start}" if n.start is not None else "@t?"
    bits = [f"{n.lid} [slot {n.slot}] {n.origin} {start}"]
    ev = n.evidence
    if n.origin == "drift_spawn":
        who = ev.get("client", ev.get("clients"))
        src = f"init from slot {ev['init_from']}" if "init_from" in ev else ""
        trig = f"client {who}" if who is not None else ""
        detail = ", ".join(x for x in (trig, src) if x)
        if detail:
            bits.append(f"({detail})")
    elif n.origin == "split" and "side" in ev:
        detail = f"({ev['side']}"
        if ev.get("alpha_cross") is not None:
            detail += f", alpha_cross={ev['alpha_cross']}"
        bits.append(detail + ")")
    if n.end_reason:
        at = f" @t{n.end}" if n.end is not None else ""
        bits.append(f"— {n.end_reason}{at}")
    else:
        bits.append("— active")
    return " ".join(bits)


def _absorb_lines(n: LineageNode) -> list[str]:
    out = []
    for ab in n.absorbed:
        ev = ab.get("evidence") or {}
        line = f"⇐ absorbed {ab['lid']} @t{ab.get('iteration', '?')}"
        if ev.get("distance") is not None:
            line += f" (dist {ev['distance']}"
            if ev.get("threshold") is not None:
                line += f" ≤ Δ'={ev['threshold']}"
            line += ")"
        out.append(line)
    return out


def render_tree(lin: Lineage) -> str:
    """ASCII forest over spawn/split edges; merges annotate the absorbing
    node (the DAG's cross edges, which a tree cannot hold)."""
    n_merge = sum(len(n.absorbed) for n in lin.nodes)
    L = [f"cluster genealogy ({len(lin.nodes)} lineages, "
         f"{n_merge} merges, {len(lin.open_nodes())} active)"]

    def walk(node: LineageNode, prefix: str, tail: bool) -> None:
        branch = "└─ " if tail else "├─ "
        L.append(prefix + branch + _node_line(node))
        child_prefix = prefix + ("   " if tail else "│  ")
        extras = _absorb_lines(node)
        kids = [lin.by_id[c] for c in node.children]
        for x in extras:
            L.append(child_prefix + ("│  " if kids else "   ") + x)
        for i, k in enumerate(kids):
            walk(k, child_prefix, i == len(kids) - 1)

    roots = lin.roots()
    for i, r in enumerate(roots):
        L.append(_node_line(r))
        extras = _absorb_lines(r)
        kids = [lin.by_id[c] for c in r.children]
        for x in extras:
            L.append(("│  " if kids else "   ") + x)
        for j, k in enumerate(kids):
            walk(k, "", j == len(kids) - 1)
    if not roots:
        L.append("  (no cluster events recorded)")
    return "\n".join(L)


def render_timeline(rows: list[dict]) -> str:
    if not rows:
        return "assignment timeline: (no cluster_assign events recorded)"
    has_oracle = any(r.get("ari") is not None for r in rows)
    head = "  t   assignment (client → model)"
    if has_oracle:
        head += "  models  ARI      purity"
    else:
        head += "  models"
    L = ["assignment timeline:", head]
    width = max(len(" ".join(str(a) for a in r["assignment"]))
                for r in rows)
    for r in rows:
        vec = " ".join(str(a) for a in r["assignment"])
        line = f"  {r['iteration']:<3} [{vec:<{width}}]  {r['num_models']:>5}"
        if has_oracle:
            ari = r.get("ari")
            pur = r.get("purity")
            line += (f"  {ari:>7.4f}" if ari is not None else "        —")
            line += (f"  {pur:>6.4f}" if pur is not None else "       —")
        L.append(line)
    return "\n".join(L)


def to_dot(lin: Lineage) -> str:
    """Graphviz DOT of the full DAG: solid spawn/split edges, dashed merge
    (absorption) edges labeled with the winning distance."""
    L = ["digraph cluster_lineage {",
         "  rankdir=TB;",
         '  node [shape=box, fontname="monospace"];']
    for n in lin.nodes:
        start = f"t{n.start}" if n.start is not None else "t?"
        label = f"{n.lid}\\nslot {n.slot}\\n{n.origin} {start}"
        if n.end_reason:
            label += f"\\n{n.end_reason} t{n.end}"
        style = ', style=filled, fillcolor="#e8f4e8"' if not n.end_reason \
            else ""
        L.append(f'  {n.lid} [label="{label}"{style}];')
    for n in lin.nodes:
        for c in n.children:
            L.append(f"  {n.lid} -> {c};")
        for ab in n.absorbed:
            ev = ab.get("evidence") or {}
            lbl = f"merge t{ab.get('iteration', '?')}"
            if ev.get("distance") is not None:
                lbl += f"\\nd={ev['distance']}"
            L.append(f'  {ab["lid"]} -> {n.lid} '
                     f'[style=dashed, label="{lbl}"];')
    L.append("}")
    return "\n".join(L) + "\n"


# ----------------------------------------------------------------------
# entry points
def _load_jsonl(path: str) -> list[dict]:
    records = []
    if not os.path.isfile(path):
        return records
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                records.append(json.loads(line))
            except json.JSONDecodeError:
                continue                 # tolerate a torn tail line
    return records


def summarize(run_dir: str) -> dict[str, Any]:
    """Machine-readable lineage summary (the --json output)."""
    events = _load_jsonl(os.path.join(run_dir, "events.jsonl"))
    lin = build_lineage(events)
    cm = concept_matrix_from_events(events)
    rows = score_timeline(lin, cm)
    return {
        "run_dir": run_dir,
        "has_events": bool(events),
        "meta": lin.meta,
        "nodes": [n.to_json() for n in lin.nodes],
        "timeline": rows,
        "oracle": oracle_summary(rows),
        "has_ground_truth": cm is not None,
    }


def main(argv: list[str] | None = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="feddrift_tpu lineage",
        description="reconstruct the cluster genealogy DAG from "
                    "events.jsonl, with oracle ARI/purity scoring for "
                    "synthetic ground truth")
    ap.add_argument("run_dir", help="run directory holding events.jsonl")
    ap.add_argument("--dot", metavar="PATH", default=None,
                    help="also write a Graphviz DOT export")
    ap.add_argument("--json", action="store_true", help="machine-readable")
    args = ap.parse_args(argv)

    import sys
    if not os.path.isdir(args.run_dir):
        print(f"lineage: run_dir {args.run_dir!r} does not exist",
              file=sys.stderr)
        return 1
    events_path = os.path.join(args.run_dir, "events.jsonl")
    events = _load_jsonl(events_path)
    if not events:
        print(f"lineage: {events_path} is missing or empty — the run "
              "predates the event bus or never started", file=sys.stderr)
        return 1

    lin = build_lineage(events)
    cm = concept_matrix_from_events(events)
    rows = score_timeline(lin, cm)

    if args.dot:
        with open(args.dot, "w") as f:
            f.write(to_dot(lin))

    if args.json:
        out = summarize(args.run_dir)
        if args.dot:
            out["dot"] = args.dot
        print(json.dumps(out, indent=2))
        return 0

    print(f"run: {args.run_dir}")
    meta = lin.meta
    if meta:
        print(f"  {meta.get('algo', '?')}/{meta.get('algo_arg', '?')} on "
              f"{meta.get('dataset', '?')} — {meta.get('clients', '?')} "
              f"clients, pool of {meta.get('num_models', '?')} models")
    print()
    print(render_tree(lin))
    print()
    print(render_timeline(rows))
    osum = oracle_summary(rows)
    if osum:
        print()
        print(f"oracle agreement (vs concept_matrix): "
              f"final ARI {osum['final_ari']:.4f}, "
              f"best {osum['best_ari']:.4f}, mean {osum['mean_ari']:.4f}"
              + (f", final purity {osum['final_purity']:.4f}"
                 if osum.get("final_purity") is not None else ""))
    elif cm is None:
        print()
        print("oracle agreement: unavailable (no concept_matrix in "
              "run_start — non-synthetic dataset or pre-lineage run)")
    if args.dot:
        print(f"\nDOT written: {args.dot}")
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
