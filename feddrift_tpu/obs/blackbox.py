"""Always-on flight recorder: the black box behind incident bundles.

Post-hoc triage (report/critical_path/lineage) reads the JSONL files a
run wrote — which is exactly the evidence that is missing when a process
dies with its buffers unflushed, or when a serving replica fails on a
host whose run dir nobody is tailing. This module keeps the *recent
past* resident: bounded in-memory ring buffers over the last N observed
events, the alert records among them, the per-iteration
``round_breakdown`` records, and periodic instrument snapshots. Span
history is NOT duplicated — the process-wide ``obs.spans`` recorder
already keeps its own ring, and ``dump()`` folds it in at capture time.

Cost model (the <2% paired-overhead budget in scripts/perf_gate.sh):
``observe()`` is a bus tap — one re-entrant lock acquire, one-to-two
deque appends, no serialization, no I/O. Rings are sized in **records,
not bytes**: capacity is a count, eviction is the deque's own maxlen,
and nothing is JSON-encoded until ``dump()`` runs on the (rare) capture
path. The recorder holds references to the same dicts the bus ring
holds, so the marginal memory is the deque slots themselves.

``obs/incident.py`` owns *when* to capture (triggers, debounce, bundle
layout); this module owns *what* is still in memory when it does.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Any, Optional

#: default ring capacity (records) for the main event ring; the alert /
#: breakdown / instrument-snapshot rings are sized down from it because
#: their records are rarer and individually heavier.
DEFAULT_CAPACITY = 512

# the event kinds teed into the dedicated alert ring so a dump keeps an
# alert trail even after the main ring wrapped past the firing
_ALERT_KINDS = ("alert_raised", "slo_burn")


class FlightRecorder:
    """Bounded rings over the recent event stream; attach as a bus tap.

    Thread-safe: ``observe`` runs on whatever thread emitted (runner
    main, broker background, serving dispatchers). The lock is
    re-entrant per the R3 tap discipline — ``dump()`` may be reached
    from code that itself runs under a tap.
    """

    def __init__(self, capacity: int = DEFAULT_CAPACITY,
                 alerts_capacity: Optional[int] = None,
                 breakdowns_capacity: Optional[int] = None,
                 snapshots_capacity: int = 16,
                 enabled: bool = True) -> None:
        capacity = max(int(capacity), 8)
        self.capacity = capacity
        # R3: re-entrant — a dump on the capture path can emit
        # (flight_dump) and tap straight back into observe()
        self._lock = threading.RLock()
        self.events: collections.deque = collections.deque(maxlen=capacity)
        self.alerts: collections.deque = collections.deque(
            maxlen=alerts_capacity if alerts_capacity is not None
            else max(capacity // 4, 8))
        self.breakdowns: collections.deque = collections.deque(
            maxlen=breakdowns_capacity if breakdowns_capacity is not None
            else max(capacity // 8, 8))
        self.snapshots: collections.deque = collections.deque(
            maxlen=max(int(snapshots_capacity), 1))
        self.enabled = enabled
        self.observed = 0                  # lifetime count (wraparound proof)
        self._bus = None

    # -- wiring ---------------------------------------------------------
    def attach(self, bus) -> "FlightRecorder":
        """Register as a live tap on an EventBus."""
        self._bus = bus
        bus.add_tap(self.observe)
        return self

    def detach(self) -> None:
        if self._bus is not None:
            try:
                self._bus.remove_tap(self.observe)
            except Exception:   # noqa: BLE001 — bus may be gone already
                pass
            self._bus = None

    # -- recording ------------------------------------------------------
    def observe(self, rec: dict) -> None:
        """Feed one event record (the bus tap). O(1): lock + append."""
        if not self.enabled:
            return
        kind = rec.get("kind")
        if kind is None:
            return
        with self._lock:
            self.observed += 1
            self.events.append(rec)
            if kind in _ALERT_KINDS:
                self.alerts.append(rec)
            elif kind == "round_breakdown":
                self.breakdowns.append(rec)

    def snapshot_instruments(self, reg=None) -> Optional[dict]:
        """Ring one instrument snapshot (runner iteration tail / capture
        path). Heavier than ``observe`` — every instrument takes its
        lock — so it is called per *iteration*, never per event."""
        if not self.enabled:
            return None
        from feddrift_tpu.obs.instruments import registry
        snap = {"ts": round(time.time(), 3),
                "metrics": (reg if reg is not None else registry()).snapshot()}
        with self._lock:
            self.snapshots.append(snap)
        return snap

    # -- capture --------------------------------------------------------
    def dump(self, events_limit: Optional[int] = None,
             include_spans: bool = True,
             include_instruments: bool = True) -> dict:
        """Serialize-ready snapshot of every ring. ``events_limit``
        bounds the event tail (broker-carried per-replica snapshots);
        None keeps the whole ring. Values are the live record dicts —
        callers serialize with ``obs.events._json_default``."""
        with self._lock:
            events = list(self.events)
            out: dict[str, Any] = {
                "captured_ts": round(time.time(), 3),
                "observed": self.observed,
                "capacity": self.capacity,
                "alerts": [dict(a) for a in self.alerts],
                "round_breakdowns": [dict(b) for b in self.breakdowns],
                "instrument_snapshots": list(self.snapshots),
            }
        if events_limit is not None and len(events) > events_limit:
            events = events[-int(events_limit):]
        out["events"] = [dict(e) for e in events]
        if include_spans:
            from feddrift_tpu.obs import spans as _spans
            out["spans"] = _spans.get_recorder().spans()
        if include_instruments:
            from feddrift_tpu.obs.instruments import registry
            out["instruments"] = registry().snapshot()
        return out


# ----------------------------------------------------------------------
# Process-local default recorder, mirroring obs.events / obs.spans: the
# runner (or a serving frontend script) configures it once per run,
# library layers reach it through get_flight_recorder(). It starts
# UNATTACHED: a process that never configures pays nothing.
_recorder = FlightRecorder()
_rec_lock = threading.Lock()


def get_flight_recorder() -> FlightRecorder:
    return _recorder


def configure(capacity: int = DEFAULT_CAPACITY, **kwargs) -> FlightRecorder:
    """Install a fresh process-wide recorder (detaching the previous
    one from whatever bus it tapped). Caller attaches it to a bus."""
    global _recorder
    with _rec_lock:
        old, _recorder = _recorder, FlightRecorder(capacity=capacity,
                                                   **kwargs)
        old.detach()
    return _recorder
