"""Unified trace timeline: spans + events → one Chrome-trace JSON.

A *span* is a named wall-clock interval (phase, iteration, comm publish)
recorded live; an *event* (obs/events.py) is a point occurrence. This
module records the former to ``<run_dir>/spans.jsonl`` and folds BOTH
into a single Chrome-trace-event JSON that Perfetto / ``chrome://tracing``
loads directly:

    python -m feddrift_tpu report <run_dir> --trace   # writes trace.json

Timeline layout: one **process lane per host process** (multihost runs
stamp ``jax.process_index()`` into every span, so merged traces keep one
lane each), and within a process one **thread lane per recording thread**
(the runner's main thread, comm-broker background threads) plus one
reserved ``events`` lane where every ``events.jsonl`` record appears as
an instant. Span ``ts`` is unix epoch microseconds — the same clock
events carry in ``_ts`` — so the two sources interleave correctly.

Recording is O(1) per span (one lock, one append, one optional file
write) and the recorder is disabled until ``configure()`` arms it, so
un-instrumented processes pay one attribute check on the hot path.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import Any, Iterator

import contextlib

RING_SIZE = 8192

# tid of the reserved per-process instant-event lane in trace.json
EVENTS_LANE_TID = 0


class SpanRecorder:
    """Thread-safe span sink: in-memory ring + optional JSONL file."""

    def __init__(self, path: str | None = None, pid: int = 0,
                 enabled: bool = True) -> None:
        self._lock = threading.Lock()
        self.ring: collections.deque = collections.deque(maxlen=RING_SIZE)
        self.pid = pid
        self.enabled = enabled
        self.path = path
        self._fh = None
        if path:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            self._fh = open(path, "a")

    def record(self, name: str, ts: float, dur: float, cat: str = "phase",
               **args: Any) -> dict | None:
        """Record one completed span. ``ts`` unix seconds, ``dur`` seconds."""
        if not self.enabled:
            return None
        rec = {"name": name, "cat": cat,
               "ts": round(ts * 1e6, 1),          # µs — trace-event unit
               "dur": round(dur * 1e6, 1),
               "pid": self.pid, "tid": threading.get_ident()}
        if args:
            rec["args"] = args
        with self._lock:
            self.ring.append(rec)
            if self._fh is not None:
                self._fh.write(json.dumps(rec) + "\n")
                self._fh.flush()
        return rec

    @contextlib.contextmanager
    def span(self, name: str, cat: str = "phase",
             **args: Any) -> Iterator[None]:
        """Context manager recording the enclosed interval."""
        if not self.enabled:
            yield
            return
        t0 = time.time()
        p0 = time.perf_counter()
        try:
            yield
        finally:
            self.record(name, t0, time.perf_counter() - p0, cat, **args)

    def spans(self, name: str | None = None) -> list[dict]:
        with self._lock:
            out = list(self.ring)
        return out if name is None else [s for s in out if s["name"] == name]

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None

    def __enter__(self) -> "SpanRecorder":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


# ----------------------------------------------------------------------
# Process-local default recorder, mirroring obs.events: layers record
# through the module-level helpers, the runner re-points the sink per run.
# Starts disabled so library use without a run context costs ~nothing.
_recorder = SpanRecorder(None, enabled=False)
_rec_lock = threading.Lock()


def get_recorder() -> SpanRecorder:
    return _recorder


def configure(path: str | None, pid: int = 0) -> SpanRecorder:
    """Install a fresh default recorder writing to ``path`` (None =
    memory-only, still enabled). Closes the previous recorder's sink."""
    global _recorder
    with _rec_lock:
        old, _recorder = _recorder, SpanRecorder(path, pid=pid)
        old.close()
    return _recorder


def span(name: str, cat: str = "phase", **args: Any):
    return _recorder.span(name, cat, **args)


def record(name: str, ts: float, dur: float, cat: str = "phase",
           **args: Any) -> dict | None:
    return _recorder.record(name, ts, dur, cat, **args)


# ----------------------------------------------------------------------
# Chrome-trace export
def _load_jsonl(path: str) -> list[dict]:
    rows: list[dict] = []
    if not os.path.isfile(path):
        return rows
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rows.append(json.loads(line))
            except json.JSONDecodeError:
                continue                         # tolerate a torn tail line
    return rows


def build_trace(run_dir: str) -> dict:
    """Chrome-trace-event JSON (object form) for one run directory.

    Sources ``spans.jsonl`` (duration events, ``ph: "X"``) and
    ``events.jsonl`` (instant events, ``ph: "i"``, one reserved lane per
    process). Output invariants, tested in tests/test_obs_perf.py: every
    event has name/ph/ts/pid/tid, durations are non-negative, the list is
    sorted by ts, and each (pid, tid) lane carries metadata naming it.
    """
    spans = _load_jsonl(os.path.join(run_dir, "spans.jsonl"))
    events = _load_jsonl(os.path.join(run_dir, "events.jsonl"))

    trace: list[dict] = []
    # (pid, raw tid) -> compact per-process tid; tid 0 = events lane
    lanes: dict[tuple[int, Any], int] = {}
    pids: set[int] = set()

    def lane(pid: int, raw_tid: Any) -> int:
        key = (pid, raw_tid)
        if key not in lanes:
            lanes[key] = 1 + sum(1 for (p, _) in lanes if p == pid)
        return lanes[key]

    for s in spans:
        pid = int(s.get("pid", 0))
        pids.add(pid)
        ev = {"name": s.get("name", "?"), "cat": s.get("cat", "phase"),
              "ph": "X", "ts": float(s.get("ts", 0.0)),
              "dur": max(float(s.get("dur", 0.0)), 0.0),
              "pid": pid, "tid": lane(pid, s.get("tid", "main"))}
        if s.get("args"):
            ev["args"] = s["args"]
        trace.append(ev)

    for e in events:
        if "_ts" not in e or "kind" not in e:
            continue
        pid = int(e.get("pid", 0))
        pids.add(pid)
        args = {k: v for k, v in e.items()
                if k not in ("_ts", "kind", "pid") and _json_scalarish(v)}
        trace.append({"name": e["kind"], "cat": "event", "ph": "i",
                      "s": "t", "ts": round(float(e["_ts"]) * 1e6, 1),
                      "pid": pid, "tid": EVENTS_LANE_TID, "args": args})

    trace.sort(key=lambda ev: ev["ts"])

    meta: list[dict] = []
    for pid in sorted(pids):
        meta.append({"ph": "M", "name": "process_name", "pid": pid,
                     "tid": 0, "args": {"name": f"process {pid}"}})
        meta.append({"ph": "M", "name": "thread_name", "pid": pid,
                     "tid": EVENTS_LANE_TID, "args": {"name": "events"}})
    for (pid, _raw), tid in sorted(lanes.items(), key=lambda kv: kv[1]):
        meta.append({"ph": "M", "name": "thread_name", "pid": pid,
                     "tid": tid, "args": {"name": f"thread {tid}"}})

    return {"traceEvents": meta + trace, "displayTimeUnit": "ms"}


def _json_scalarish(v: Any) -> bool:
    return isinstance(v, (str, int, float, bool, list)) or v is None


def write_trace(run_dir: str, out_path: str | None = None) -> str:
    """Build + write ``trace.json`` for a run dir; returns the path."""
    trace = build_trace(run_dir)
    out_path = out_path or os.path.join(run_dir, "trace.json")
    with open(out_path, "w") as f:
        json.dump(trace, f)
    return out_path
