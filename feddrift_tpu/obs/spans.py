"""Unified trace timeline: spans + events → one Chrome-trace JSON.

A *span* is a named wall-clock interval (phase, iteration, comm publish)
recorded live; an *event* (obs/events.py) is a point occurrence. This
module records the former to ``<run_dir>/spans.jsonl`` and folds BOTH
into a single Chrome-trace-event JSON that Perfetto / ``chrome://tracing``
loads directly:

    python -m feddrift_tpu report <run_dir> --trace   # writes trace.json

Timeline layout: one **process lane per host process** (multihost runs
stamp ``jax.process_index()`` into every span, so merged traces keep one
lane each), and within a process one **thread lane per recording thread**
(the runner's main thread, comm-broker background threads) plus one
reserved ``events`` lane where every ``events.jsonl`` record appears as
an instant. Span ``ts`` is unix epoch microseconds — the same clock
events carry in ``_ts`` — so the two sources interleave correctly.

Recording is O(1) per span (one lock, one append, one optional file
write) and the recorder is disabled until ``configure()`` arms it, so
un-instrumented processes pay one attribute check on the hot path.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
import uuid
from typing import Any, Callable, Iterator

import contextlib

RING_SIZE = 8192

# tid of the reserved per-process instant-event lane in trace.json
EVENTS_LANE_TID = 0


# ----------------------------------------------------------------------
# Trace context: the W3C-style (trace_id, span_id, parent_span_id) triple
# that rides broker frames so one client update is followable
# client -> compress -> wire -> edge -> server across process lanes.
# A context is a plain JSON dict; every hop that *receives* one records
# its own span as a child (``child_of``) and forwards its OWN context, so
# the chain is parent-linked end to end and ``build_trace`` can emit
# Perfetto flow arrows between the slices.

def new_trace() -> dict:
    """Root context for a fresh causal chain."""
    return {"trace_id": uuid.uuid4().hex[:16],
            "span_id": uuid.uuid4().hex[:16]}


def child_of(ctx: dict | None) -> dict:
    """Continue a received context: same trace, new span, parent linked.
    A None/malformed context starts a new root (never raises — tracing
    stays passive)."""
    if not isinstance(ctx, dict) or "trace_id" not in ctx:
        return new_trace()
    out = {"trace_id": str(ctx["trace_id"]),
           "span_id": uuid.uuid4().hex[:16]}
    if ctx.get("span_id"):
        out["parent_span_id"] = str(ctx["span_id"])
    return out


class SpanRecorder:
    """Thread-safe span sink: in-memory ring + optional JSONL file.

    ``max_bytes`` (0 = unbounded, the default) caps the JSONL sink:
    when a write pushes the file past the cap it is rotated to
    ``<path>.1`` (one generation kept) and a loud ``obs_rotated`` event
    marks the boundary, so 10^5-round runs cannot fill the disk.
    """

    def __init__(self, path: str | None = None, pid: int = 0,
                 enabled: bool = True, max_bytes: int = 0) -> None:
        self._lock = threading.Lock()
        self.ring: collections.deque = collections.deque(maxlen=RING_SIZE)
        self.pid = pid
        self.enabled = enabled
        self.path = path
        self.max_bytes = int(max_bytes)
        self.rotations = 0
        self._fh = None
        if path:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            self._fh = open(path, "a")

    def record(self, name: str, ts: float, dur: float, cat: str = "phase",
               **args: Any) -> dict | None:
        """Record one completed span. ``ts`` unix seconds, ``dur`` seconds."""
        if not self.enabled:
            return None
        rec = {"name": name, "cat": cat,
               "ts": round(ts * 1e6, 1),          # µs — trace-event unit
               "dur": round(dur * 1e6, 1),
               "pid": self.pid, "tid": threading.get_ident()}
        if args:
            rec["args"] = args
        rotated_bytes = 0
        with self._lock:
            self.ring.append(rec)
            if self._fh is not None:
                self._fh.write(json.dumps(rec) + "\n")
                self._fh.flush()
                if self.max_bytes and self._fh.tell() >= self.max_bytes:
                    rotated_bytes = self._rotate_locked()
        if rotated_bytes:
            # the bus lock is unrelated to ours, but emit outside our own
            # lock anyway: an event tap may legally record a span
            from feddrift_tpu.obs import events as _events
            try:
                _events.emit("obs_rotated", file=os.path.basename(self.path),
                             rotated_bytes=rotated_bytes,
                             generation=self.rotations)
            except Exception:   # noqa: BLE001 — observability stays passive
                pass
        return rec

    def _rotate_locked(self) -> int:
        """Swap the sink to a fresh file (caller holds the lock); returns
        the size of the rotated-out generation."""
        size = self._fh.tell()
        self._fh.close()
        try:
            os.replace(self.path, self.path + ".1")
        except OSError:
            pass
        self._fh = open(self.path, "a")
        self.rotations += 1
        return size

    @contextlib.contextmanager
    def span(self, name: str, cat: str = "phase",
             on_close: Callable[[float, float], None] | None = None,
             **args: Any) -> Iterator[None]:
        """Context manager recording the enclosed interval.

        ``on_close(wall_start_s, duration_s)`` fires after the span is
        recorded — the single timing code path PhaseTracer and other
        accumulators hang their accounting on. The interval is measured
        whenever an ``on_close`` is given, even on a disabled recorder
        (the caller's accounting must not depend on sink state).
        """
        if not self.enabled and on_close is None:
            yield
            return
        t0 = time.time()
        p0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - p0
            self.record(name, t0, dt, cat, **args)
            if on_close is not None:
                on_close(t0, dt)

    def spans(self, name: str | None = None) -> list[dict]:
        with self._lock:
            out = list(self.ring)
        return out if name is None else [s for s in out if s["name"] == name]

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None

    def __enter__(self) -> "SpanRecorder":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


# ----------------------------------------------------------------------
# Process-local default recorder, mirroring obs.events: layers record
# through the module-level helpers, the runner re-points the sink per run.
# Starts disabled so library use without a run context costs ~nothing.
_recorder = SpanRecorder(None, enabled=False)
_rec_lock = threading.Lock()


def get_recorder() -> SpanRecorder:
    return _recorder


def configure(path: str | None, pid: int = 0,
              max_bytes: int = 0) -> SpanRecorder:
    """Install a fresh default recorder writing to ``path`` (None =
    memory-only, still enabled). Closes the previous recorder's sink."""
    global _recorder
    with _rec_lock:
        old, _recorder = _recorder, SpanRecorder(path, pid=pid,
                                                 max_bytes=max_bytes)
        old.close()
    return _recorder


def span(name: str, cat: str = "phase", **args: Any):
    return _recorder.span(name, cat, **args)


def record(name: str, ts: float, dur: float, cat: str = "phase",
           **args: Any) -> dict | None:
    return _recorder.record(name, ts, dur, cat, **args)


# ----------------------------------------------------------------------
# Chrome-trace export
def _load_jsonl(path: str) -> list[dict]:
    rows: list[dict] = []
    if not os.path.isfile(path):
        return rows
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rows.append(json.loads(line))
            except json.JSONDecodeError:
                continue                         # tolerate a torn tail line
    return rows


def build_trace(run_dir: str) -> dict:
    """Chrome-trace-event JSON (object form) for one run directory.

    Sources ``spans.jsonl`` (duration events, ``ph: "X"``) and
    ``events.jsonl`` (instant events, ``ph: "i"``, one reserved lane per
    process). Output invariants, tested in tests/test_obs_perf.py: every
    event has name/ph/ts/pid/tid, durations are non-negative, the list is
    sorted by ts, and each (pid, tid) lane carries metadata naming it.

    Spans carrying trace-context args (``span_id`` + ``parent_span_id``,
    see ``new_trace``/``child_of``) additionally get Perfetto **flow
    arrows** (``ph: "s"``/``"f"`` pairs sharing an id) from each parent
    slice to its child slice — the rendering of one update's causal chain
    across pid lanes. A run with no trace contexts emits no flow events.
    """
    spans = _load_jsonl(os.path.join(run_dir, "spans.jsonl"))
    events = _load_jsonl(os.path.join(run_dir, "events.jsonl"))
    # rotated-out generations still belong to the timeline
    for fname in ("spans.jsonl.1", "events.jsonl.1"):
        extra = _load_jsonl(os.path.join(run_dir, fname))
        if fname.startswith("spans"):
            spans = extra + spans
        else:
            events = extra + events
    # sampling-profiler slices (obs/hostprof.py) share the span schema;
    # their string tids ("hostprof:<thread>") become their own named lanes
    spans = spans + _load_jsonl(os.path.join(run_dir, "hostprof.jsonl"))

    trace: list[dict] = []
    # (pid, raw tid) -> compact per-process tid; tid 0 = events lane
    lanes: dict[tuple[int, Any], int] = {}
    pids: set[int] = set()

    def lane(pid: int, raw_tid: Any) -> int:
        key = (pid, raw_tid)
        if key not in lanes:
            lanes[key] = 1 + sum(1 for (p, _) in lanes if p == pid)
        return lanes[key]

    for s in spans:
        pid = int(s.get("pid", 0))
        pids.add(pid)
        ev = {"name": s.get("name", "?"), "cat": s.get("cat", "phase"),
              "ph": "X", "ts": float(s.get("ts", 0.0)),
              "dur": max(float(s.get("dur", 0.0)), 0.0),
              "pid": pid, "tid": lane(pid, s.get("tid", "main"))}
        if s.get("args"):
            ev["args"] = s["args"]
        trace.append(ev)

    # Perfetto flow arrows between trace-context-linked spans: "s" bound
    # to the parent slice, "f" (bp "e": bind to enclosing slice) to the
    # child. Flow pairs are matched by (cat, id); ids are sequential —
    # each parent->child edge is its own arrow.
    by_span_id = {ev["args"]["span_id"]: ev for ev in trace
                  if "args" in ev and ev["args"].get("span_id")}
    flow_id = 0
    flows: list[dict] = []
    for ev in trace:
        parent_id = ev.get("args", {}).get("parent_span_id")
        parent = by_span_id.get(parent_id) if parent_id else None
        if parent is None or parent is ev:
            continue
        flow_id += 1
        flows.append({"name": "trace", "cat": "trace", "ph": "s",
                      "id": flow_id, "ts": parent["ts"],
                      "pid": parent["pid"], "tid": parent["tid"]})
        flows.append({"name": "trace", "cat": "trace", "ph": "f", "bp": "e",
                      "id": flow_id, "ts": max(ev["ts"], parent["ts"]),
                      "pid": ev["pid"], "tid": ev["tid"]})
    trace.extend(flows)

    for e in events:
        if "_ts" not in e or "kind" not in e:
            continue
        pid = int(e.get("pid", 0))
        pids.add(pid)
        args = {k: v for k, v in e.items()
                if k not in ("_ts", "kind", "pid") and _json_scalarish(v)}
        trace.append({"name": e["kind"], "cat": "event", "ph": "i",
                      "s": "t", "ts": round(float(e["_ts"]) * 1e6, 1),
                      "pid": pid, "tid": EVENTS_LANE_TID, "args": args})

    trace.sort(key=lambda ev: ev["ts"])

    meta: list[dict] = []
    for pid in sorted(pids):
        meta.append({"ph": "M", "name": "process_name", "pid": pid,
                     "tid": 0, "args": {"name": f"process {pid}"}})
        meta.append({"ph": "M", "name": "thread_name", "pid": pid,
                     "tid": EVENTS_LANE_TID, "args": {"name": "events"}})
    for (pid, raw), tid in sorted(lanes.items(), key=lambda kv: kv[1]):
        # descriptive raw tids (e.g. "hostprof:140…") name the lane
        # directly; integer thread idents keep the compact label
        name = raw if isinstance(raw, str) and not raw.isdigit() \
            else f"thread {tid}"
        meta.append({"ph": "M", "name": "thread_name", "pid": pid,
                     "tid": tid, "args": {"name": name}})

    return {"traceEvents": meta + trace, "displayTimeUnit": "ms"}


def _json_scalarish(v: Any) -> bool:
    return isinstance(v, (str, int, float, bool, list)) or v is None


def write_trace(run_dir: str, out_path: str | None = None) -> str:
    """Build + write ``trace.json`` for a run dir; returns the path."""
    trace = build_trace(run_dir)
    out_path = out_path or os.path.join(run_dir, "trace.json")
    with open(out_path, "w") as f:
        json.dump(trace, f)
    return out_path
