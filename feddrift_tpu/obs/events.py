"""Process-local structured event bus.

One event = one JSON object on one line of ``<run_dir>/events.jsonl``,
written next to ``metrics.jsonl``. Every event carries:

    _ts        float   unix seconds, stamped at emit time
    kind       str     one of EVENT_KINDS (closed taxonomy, validated)
    iteration  int     current time step, when set via set_context
    round      int     current global round, when set via set_context
    ...        any     kind-specific JSON-serializable fields

The bus is process-local and shared: the runner configures the sink once
per run (``configure(path)``), and every layer — including the comm
brokers' background threads and the fault injector — emits through the
module-level ``emit()``. Emission is thread-safe (one lock around the
in-memory ring append and the file write) and bounded: the in-memory
ring keeps the last ``RING_SIZE`` events for tests/diagnostics, the file
is append-only.

Unknown kinds raise ``ValueError`` at emit time, and
``scripts/check_events_schema.py`` statically cross-checks the emitted
kinds against docs/OBSERVABILITY.md — the two halves of the "no
undocumented events" guarantee.
"""

from __future__ import annotations

import collections
import contextlib
import json
import os
import threading
import time
from typing import Any

# ----------------------------------------------------------------------
# The closed event taxonomy. Documented one-per-row in
# docs/OBSERVABILITY.md; scripts/check_events_schema.py enforces the
# code <-> docs correspondence.
EVENT_KINDS = frozenset({
    # run / iteration lifecycle (simulation/runner.py)
    "run_start",            # config summary at Experiment construction
    "run_end",              # end of Experiment.run
    "iteration_start",      # time step begins
    "iteration_end",        # time step done: wall s, examples/s, phase totals
    "eval",                 # one eval point (round, Train/Test acc+loss)
    "checkpoint_save",      # atomic checkpoint written
    "megastep_gated",       # a feature forced the fusion span below megastep_k
    # XLA compile tracking (core/step.py)
    "jit_compile",          # first time a program sees an argument signature
    "jit_recompile",        # a NEW signature on an already-compiled program
    # performance accounting (obs/costmodel.py, utils/tracing.py)
    "program_cost",         # XLA cost/memory analysis of a compiled program
    "hbm_watermark",        # live device.memory_stats() snapshot
    "profile_captured",     # a jax.profiler trace was written (xla_trace)
    # drift / cluster decisions (algorithms/*)
    "drift_detected",       # per-client accuracy-drop trigger
    "cluster_create",       # a pool slot is (re)allocated for a new cluster
    "cluster_merge",        # hierarchical merge of two cluster models
    "cluster_delete",       # a model is deleted / reset out of use
    "cluster_split",        # CFL gradient bipartition fired
    "cluster_state",        # per-iteration summary: models in use etc.
    "cluster_assign",       # dense per-client -> model assignment (E-step)
    "model_replaced",       # ensemble rotation (AUE window, KUE worst model)
    # run-health monitor (obs/alerts.py)
    "alert_raised",         # a declarative health rule fired
    # comm transports (comm/netbroker.py, comm/mqtt.py)
    "conn_drop",            # a broker connection closed / was cleaned up
    "conn_wedged_drop",     # bounded outbound queue overflow -> force-drop
    # resilience layer (feddrift_tpu/resilience/)
    "conn_reconnect",       # reconnecting client re-established its session
    "publish_retry",        # unacked/unsent publish re-sent
    "heartbeat_missed",     # liveness loopback silent past the timeout
    "chaos_injected",       # chaos policy dropped/delayed/duplicated a message
    "preempt_checkpoint",   # SIGTERM/SIGINT -> checkpointed at iteration boundary
    "divergence_detected",  # NaN/Inf or loss spike -> params rolled back
    "checkpoint_corrupt",   # checksum/deserialization failure in a generation
    # fault injection / failure detection (platform/faults.py)
    "fault_injected",       # injected dropout this round, with client mask
    "client_killed",        # permanent kill
    "client_revived",
    "failure_suspected",    # detector's suspect set changed
    # adversary model / robust aggregation (platform/faults.py,
    # resilience/robust_agg.py, simulation/runner.py)
    "byzantine_injected",   # scheduled attackers active this round
    "robust_agg_applied",   # per-round robust-aggregation stats
    "acc_stale_excluded",   # stale acc entries dropped from a cluster decision
    "quorum_revive",        # quorum floor revived a client (not real liveness)
    # population-scale participation (platform/registry.py,
    # resilience/participation.py)
    "cohort_sampled",       # the iteration's cohort draw from the registry
    "client_join",          # members (re)joined the registered population
    "client_leave",         # members left the registered population
    "straggler_masked",     # sampled members missed the round deadline
    "round_degraded",       # on-time cohort below quorum: params kept
    # hierarchical two-tier aggregation + wire compression
    # (platform/hierarchical.py, platform/faults.py::EdgeFaultInjector,
    # comm/compress.py, simulation/runner.py)
    "edge_aggregated",      # per-round per-tier aggregation evidence
    "edge_failed",          # edge crash/stall/corrupt/kill this round
    "edge_rehomed",         # dead edge's clients re-homed to survivors
    "update_compressed",    # one update frame sent through a lossy codec
    "compress_corrupt",     # frame failed digest verification; nacked
    # causal tracing / round critical path (simulation/runner.py,
    # obs/events.py + obs/spans.py rotation)
    "round_breakdown",      # per-iteration segment split + dispatch gap
    "obs_rotated",          # a size-capped JSONL sink rotated a generation
    # host-plane observatory (obs/hostprof.py, simulation/runner.py)
    "host_ledger",          # per-iteration host-seconds/bytes ledger + RSS
    # live ops plane (obs/live.py)
    "ops_snapshot",         # periodic per-process metric+health snapshot
    "slo_burn",             # SLO error-budget burn-rate rule fired
    # serving read path (platform/serving.py)
    "request_served",       # one inference request answered (routing + latency)
    "pool_swapped",         # engine published a new pool/routing generation
    "routing_rebuilt",      # dense routing table rebuilt from the registry
    # serving frontend / replica plane (platform/frontend.py,
    # platform/serving.py)
    "frontend_shed",        # admission refused a request (queue/rate/backpressure)
    "replica_failed",       # a replica's dispatcher died mid-batch
    "replica_drained",      # frontend removed a replica from rotation
    # model-quality plane (obs/quality.py, platform/canary.py)
    "model_quality",        # windowed per-model live accuracy/confidence/ECE
    "serve_drift_suspected",  # read-path entropy-distribution shift detected
    "canary_started",       # cluster event intercepted -> shadow canary open
    "canary_verdict",       # canary decided: commit (swap) or rollback
    # secure aggregation (resilience/secure_round.py,
    # platform/faults.py::ShareDropInjector)
    "secure_round_started",  # protocol round opened: mode, cohort, threshold
    "share_sent",           # one secret share left for a holder (digest, bytes)
    "share_received",       # a holder acked a share intact
    "share_dropped",        # share lost/late/corrupt -> contributor/holder masked
    "secure_reconstructed",  # masked sum decoded from surviving shares
    "secure_degraded",      # survivors below threshold: prev params kept
    # incident plane (obs/blackbox.py, obs/incident.py)
    "incident_captured",    # a trigger debounced into a written incident bundle
    "flight_dump",          # flight-recorder rings serialized into a bundle
})

RING_SIZE = 4096


class EventBus:
    """Appends typed events to an optional JSONL sink + an in-memory ring.

    ``max_bytes`` (0 = unbounded, the default) size-caps the sink: a
    write past the cap rotates the file to ``<path>.1`` (one generation
    kept) and emits a loud ``obs_rotated`` event into the fresh file.
    """

    def __init__(self, path: str | None = None, max_bytes: int = 0) -> None:
        self._lock = threading.Lock()
        self._context: dict[str, Any] = {}
        self.ring: collections.deque = collections.deque(maxlen=RING_SIZE)
        self._taps: list = []
        self._fh = None
        self.path = path
        self.max_bytes = int(max_bytes)
        self.rotations = 0
        if path:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            self._fh = open(path, "a")

    # -- emission -------------------------------------------------------
    def emit(self, kind: str, **fields: Any) -> dict:
        """Record one event; returns the record (mostly for tests)."""
        if kind not in EVENT_KINDS:
            raise ValueError(
                f"unknown event kind {kind!r}; add it to obs.events.EVENT_KINDS "
                "and document it in docs/OBSERVABILITY.md")
        rotated_bytes = 0
        with self._lock:
            rec = {"_ts": time.time(), "kind": kind, **self._context, **fields}
            self.ring.append(rec)
            if self._fh is not None:
                self._fh.write(json.dumps(rec, default=_json_default) + "\n")
                self._fh.flush()
                if self.max_bytes and self._fh.tell() >= self.max_bytes:
                    rotated_bytes = self._rotate_locked()
            taps = tuple(self._taps)
        if rotated_bytes:
            # re-entrant emit AFTER the (non-reentrant) lock is released;
            # the fresh file is far below the cap, so this cannot recurse
            self.emit("obs_rotated", file=os.path.basename(self.path),
                      rotated_bytes=rotated_bytes,
                      generation=self.rotations)
        # Taps (the live alert monitor) run AFTER the bus lock is
        # released: a tap may legally re-enter emit() (alert_raised), and
        # a slow tap must not serialize hot-path emitters. A failing tap
        # never takes the run down with it.
        for tap in taps:
            try:
                tap(rec)
            except Exception:   # noqa: BLE001 — observability stays passive
                pass
        return rec

    def _rotate_locked(self) -> int:
        """Swap the sink to a fresh file (caller holds the lock); returns
        the size of the rotated-out generation."""
        size = self._fh.tell()
        self._fh.close()
        try:
            os.replace(self.path, self.path + ".1")
        except OSError:
            pass
        self._fh = open(self.path, "a")
        self.rotations += 1
        return size

    def add_tap(self, fn) -> None:
        """Register a callable observing every emitted record (called on
        the emitting thread, after the record is persisted)."""
        with self._lock:
            self._taps.append(fn)

    def remove_tap(self, fn) -> None:
        with self._lock:
            if fn in self._taps:
                self._taps.remove(fn)

    def set_context(self, **ctx: Any) -> None:
        """Merge ambient fields (iteration=..., round=...) into every
        subsequent event; a value of None removes the key."""
        with self._lock:
            for k, v in ctx.items():
                if v is None:
                    self._context.pop(k, None)
                else:
                    self._context[k] = v

    # -- queries (tests / diagnostics) ---------------------------------
    def events(self, kind: str | None = None) -> list[dict]:
        with self._lock:
            evs = list(self.ring)
        return evs if kind is None else [e for e in evs if e["kind"] == kind]

    # -- lifecycle ------------------------------------------------------
    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None

    def __enter__(self) -> "EventBus":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def _json_default(o):
    """numpy scalars/arrays show up in event fields; store plain JSON."""
    tolist = getattr(o, "tolist", None)
    if tolist is not None:
        return tolist()
    return str(o)


# ----------------------------------------------------------------------
# The process-local default bus. Layers emit through these module-level
# helpers so they need no handle on the Experiment; the runner re-points
# the sink per run via configure().
_bus = EventBus(None)
_bus_lock = threading.Lock()


def get_bus() -> EventBus:
    return _bus


def configure(path: str | None, max_bytes: int = 0) -> EventBus:
    """Install a fresh default bus writing to ``path`` (None = memory-only).

    Closes the previous bus's sink. Returns the new bus.
    """
    global _bus
    with _bus_lock:
        old, _bus = _bus, EventBus(path, max_bytes=max_bytes)
        old.close()
    return _bus


_capture_tls = threading.local()


@contextlib.contextmanager
def capture():
    """Buffer this THREAD's module-level ``emit()`` calls instead of
    recording them; yields the ``[(kind, fields), ...]`` buffer for later
    replay through ``emit()``.

    Exists for the runner's cohort pre-staging: the t+1 churn + cohort
    draw run at the END of iteration t (so the gather/H2D can overlap the
    iteration tail), but their events must appear — and persist to
    events.jsonl — only when iteration t+1 actually consumes the draw.
    Without deferral, a kill between staging and consumption leaves the
    draw's events on disk, and the resumed run (which re-draws) duplicates
    them with shifted iteration context."""
    prev = getattr(_capture_tls, "buffer", None)
    _capture_tls.buffer = buf = []
    try:
        yield buf
    finally:
        _capture_tls.buffer = prev


def emit(kind: str, **fields: Any) -> dict:
    buf = getattr(_capture_tls, "buffer", None)
    if buf is not None:
        if kind not in EVENT_KINDS:
            raise ValueError(
                f"unknown event kind {kind!r}; add it to "
                "obs.events.EVENT_KINDS and document it in "
                "docs/OBSERVABILITY.md")
        buf.append((kind, fields))
        return {"kind": kind, **fields}
    return _bus.emit(kind, **fields)


def set_context(**ctx: Any) -> None:
    _bus.set_context(**ctx)
