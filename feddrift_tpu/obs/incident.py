"""Incident plane: trigger → debounce → self-contained forensic bundle.

PRs 16–19 made failure a first-class runtime event (replica drains,
secure-agg degradation, SLO burns, divergence aborts) — but when one
fires, the evidence lives scattered across per-process run dirs and the
operator greps JSONL after the fact. This module turns those same
signals into an automatic capture: an :class:`IncidentManager` taps the
event bus, debounces, and writes a bundle directory containing
everything a post-mortem needs with zero archaeology:

    <run_dir>/incidents/incident-NNN-<reason>/
        meta.json           trigger, evidence, pid/host/git/env,
                            checkpoint pointer, fleet dead-replica list
        flight.json         flight-recorder ring dump (obs/blackbox.py)
        trace.json          Perfetto-loadable trailing trace built from
                            the in-memory span + event rings
        alerts_tail.jsonl   tail of alerts.jsonl (rotated gen folded)
        host_ledger.json    last host_ledger event + live RSS/top-bytes
        hostprof.folded     folded stacks, when the sampler is armed
        config.json         the run's ExperimentConfig
        MANIFEST.json       checkpoint manifest copy, when one exists
        fleet/<lane>.json   per-replica flight snapshots (merged bundle)

Triggers (``TRIGGERS``): crit ``alert_raised``, any ``slo_burn``,
``replica_failed``/``replica_drained``, ``secure_degraded``,
``preempt_checkpoint``, a rolled-back ``canary_verdict`` — plus the
non-event paths: the runner's top-level exception guard (divergence
aborts arrive here as ``DivergenceError``), a chained ``sys.excepthook``
and a SIGQUIT handler (``install_process_hooks``) that dumps all thread
stacks through ``faulthandler`` before capturing.

Debounce: one bundle per ``debounce_s`` window — a storm of concurrent
triggers (every replica draining at once) produces exactly one bundle;
suppressed triggers are counted. Exception/SIGQUIT captures bypass the
window (``force=True``): a crash after an alert-driven bundle still gets
its traceback on disk.

The ``incident`` CLI verb (``incident_main``) renders the triage story
from a bundle — what fired, the dominant critical-path segment, recent
swaps/canary verdicts with lineage ids, replica/broker health at
capture — entirely host-side (stdlib only, routed pre-jax in cli.py).
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
import threading
import time
import traceback
from typing import Any, Callable, Optional

from feddrift_tpu.obs import events as _events
from feddrift_tpu.obs.events import _json_default

#: event kind -> predicate: does this record trigger a capture?
TRIGGERS: dict[str, Callable[[dict], bool]] = {
    "alert_raised": lambda rec: rec.get("severity") == "crit",
    "slo_burn": lambda rec: True,
    "replica_failed": lambda rec: True,
    "replica_drained": lambda rec: True,
    "secure_degraded": lambda rec: True,
    "preempt_checkpoint": lambda rec: True,
    "canary_verdict": lambda rec: rec.get("verdict") == "rollback",
}

#: environment prefixes worth bundling (accelerator + runtime knobs)
_ENV_PREFIXES = ("JAX_", "XLA_", "TPU_", "LIBTPU", "CUDA_", "TF_",
                 "FEDDRIFT_", "PYTHONHASHSEED")

_ALERTS_TAIL = 200          # alerts_tail.jsonl record bound


class IncidentManager:
    """Debounced trigger → bundle writer. Attach as a bus tap; see the
    module docstring for the trigger set and bundle layout."""

    def __init__(self, run_dir: Optional[str], recorder=None,
                 debounce_s: float = 30.0, max_bundles: int = 8,
                 config_json: Optional[str] = None,
                 ckpt_path: Optional[str] = None,
                 clock: Callable[[], float] = time.monotonic) -> None:
        self.run_dir = run_dir
        self.recorder = recorder
        self.debounce_s = float(debounce_s)
        self.max_bundles = max(int(max_bundles), 1)
        self.config_json = config_json
        self.ckpt_path = ckpt_path
        # R3: re-entrant — writing a bundle emits incident_captured /
        # flight_dump, whose taps (this manager included) run on the
        # same thread while the capture lock is held
        self._lock = threading.RLock()
        self._clock = clock
        self._last_capture: Optional[float] = None
        self._seq = 0
        self.suppressed = 0
        self.captured: list[str] = []
        #: optional callable(reason, evidence) -> {"dead": [...],
        #: "lanes": {lane: snapshot}} merging per-replica flight
        #: snapshots into the bundle (set by ServingFrontend
        #: .attach_incidents); None = single-process bundles only
        self.fleet_source: Optional[Callable[[str, Optional[dict]],
                                             Optional[dict]]] = None
        self._bus = None

    # -- wiring ---------------------------------------------------------
    def attach(self, bus) -> "IncidentManager":
        """Tap ``bus`` for trigger events; also becomes the process's
        current manager for the excepthook/SIGQUIT paths."""
        self._bus = bus
        bus.add_tap(self.observe)
        set_current(self)
        return self

    def detach(self) -> None:
        if self._bus is not None:
            try:
                self._bus.remove_tap(self.observe)
            except Exception:   # noqa: BLE001
                pass
            self._bus = None
        if current_manager() is self:
            set_current(None)

    # -- triggers -------------------------------------------------------
    def observe(self, rec: dict) -> None:
        kind = rec.get("kind")
        pred = TRIGGERS.get(kind)
        if pred is None:
            return
        try:
            if not pred(rec):
                return
        except Exception:   # noqa: BLE001 — a bad record never raises here
            return
        reason = kind
        if kind == "alert_raised" and rec.get("rule"):
            reason = f"alert:{rec['rule']}"
        elif kind == "slo_burn" and rec.get("objective"):
            reason = f"slo:{rec['objective']}"
        self.trigger(reason, evidence=rec)

    def on_exception(self, exc: BaseException, tb=None) -> Optional[str]:
        """Capture an abnormal termination (runner exception guard,
        chained excepthook). Bypasses the debounce window — a crash
        must land its traceback even right after an alert bundle."""
        text = "".join(traceback.format_exception(
            type(exc), exc, tb if tb is not None else exc.__traceback__))
        return self.trigger(
            f"exception:{type(exc).__name__}",
            evidence={"error": repr(exc)[:500],
                      "traceback": text[-8000:]},
            force=True)

    def trigger(self, reason: str, evidence: Optional[dict] = None,
                force: bool = False) -> Optional[str]:
        """Debounce and capture; returns the bundle path or None when
        suppressed (debounce window / no run_dir)."""
        if self.run_dir is None:
            return None
        with self._lock:
            now = self._clock()
            if (not force and self._last_capture is not None
                    and now - self._last_capture < self.debounce_s):
                self.suppressed += 1
                return None
            self._last_capture = now
            self._seq += 1
            try:
                path = self._write_bundle(reason, evidence)
            except Exception:   # noqa: BLE001 — capture must never take
                return None     # down the process it is diagnosing
            self.captured.append(path)
        return path

    # -- bundle writing -------------------------------------------------
    def _write_bundle(self, reason: str, evidence: Optional[dict]) -> str:
        safe = re.sub(r"[^a-zA-Z0-9_.-]+", "_", reason)[:48] or "trigger"
        name = f"incident-{self._seq:03d}-{safe}"
        bdir = os.path.join(self.run_dir, "incidents", name)
        while os.path.exists(bdir):            # fresh manager, old run dir
            self._seq += 1
            name = f"incident-{self._seq:03d}-{safe}"
            bdir = os.path.join(self.run_dir, "incidents", name)
        os.makedirs(bdir, exist_ok=True)

        dump: dict = {}
        if self.recorder is not None:
            try:
                dump = self.recorder.dump()
            except Exception:   # noqa: BLE001
                dump = {}
        _write_json(os.path.join(bdir, "flight.json"), dump)
        try:
            _events.emit("flight_dump", bundle=name,
                         records=len(dump.get("events", ())),
                         spans=len(dump.get("spans", ())),
                         alerts=len(dump.get("alerts", ())))
        except Exception:   # noqa: BLE001 — bus may be closed mid-crash
            pass

        _write_json(os.path.join(bdir, "trace.json"), trailing_trace(dump))
        self._write_alerts_tail(bdir)
        self._write_host(bdir, dump)
        self._write_hostprof(bdir)
        if self.config_json:
            with open(os.path.join(bdir, "config.json"), "w") as f:
                f.write(self.config_json)
        self._copy_manifest(bdir)

        meta = {
            "bundle": name,
            "reason": reason,
            "evidence": _jsonable(evidence),
            "ts": round(time.time(), 3),
            "pid": os.getpid(),
            "host": _hostname(),
            "git_sha": _git_sha(),
            "python": sys.version.split()[0],
            "env": {k: v for k, v in sorted(os.environ.items())
                    if k.startswith(_ENV_PREFIXES)},
            "checkpoint": self._ckpt_pointer(),
            "ring": {"events": len(dump.get("events", ())),
                     "observed": dump.get("observed"),
                     "capacity": dump.get("capacity")},
            "suppressed_triggers": self.suppressed,
        }
        fleet = None
        if self.fleet_source is not None:
            try:
                fleet = self.fleet_source(reason, evidence)
            except Exception:   # noqa: BLE001
                fleet = None
        if fleet:
            os.makedirs(os.path.join(bdir, "fleet"), exist_ok=True)
            for lane, snap in (fleet.get("lanes") or {}).items():
                fname = re.sub(r"[^a-zA-Z0-9_.-]+", "_", lane) + ".json"
                _write_json(os.path.join(bdir, "fleet", fname), snap)
            meta["fleet"] = {"dead": sorted(fleet.get("dead") or []),
                             "lanes": sorted((fleet.get("lanes")
                                              or {}).keys())}
        _write_json(os.path.join(bdir, "meta.json"), meta)
        try:
            _events.emit("incident_captured", reason=reason, bundle=name,
                         path=bdir, fleet=bool(fleet),
                         records=meta["ring"]["events"])
        except Exception:   # noqa: BLE001
            pass
        self._prune()
        return bdir

    def _write_alerts_tail(self, bdir: str) -> None:
        if not self.run_dir:
            return
        rows: list[str] = []
        for fname in ("alerts.jsonl.1", "alerts.jsonl"):
            path = os.path.join(self.run_dir, fname)
            if os.path.isfile(path):
                try:
                    with open(path) as f:
                        rows.extend(ln for ln in f if ln.strip())
                except OSError:
                    pass
        if rows:
            with open(os.path.join(bdir, "alerts_tail.jsonl"), "w") as f:
                f.writelines(rows[-_ALERTS_TAIL:])

    def _write_host(self, bdir: str, dump: dict) -> None:
        from feddrift_tpu.obs import hostprof
        last_ledger = None
        for rec in reversed(dump.get("events", ())):
            if rec.get("kind") == "host_ledger":
                last_ledger = rec
                break
        try:
            top = hostprof.ledger().top_bytes(5)
        except Exception:   # noqa: BLE001
            top = []
        _write_json(os.path.join(bdir, "host_ledger.json"),
                    {"rss_bytes": hostprof.rss_bytes(),
                     "top_bytes": top,
                     "last_host_ledger": last_ledger})

    def _write_hostprof(self, bdir: str) -> None:
        from feddrift_tpu.obs import hostprof
        prof = hostprof.get_profiler()
        if prof is None:
            return
        try:
            text = prof.folded_text()
        except Exception:   # noqa: BLE001
            return
        if text:
            with open(os.path.join(bdir, "hostprof.folded"), "w") as f:
                f.write(text)

    def _copy_manifest(self, bdir: str) -> None:
        ckpt = self.ckpt_path
        if not ckpt:
            return
        src = os.path.join(ckpt, "MANIFEST.json")
        if os.path.isfile(src):
            try:
                with open(src) as f:
                    data = f.read()
                with open(os.path.join(bdir, "MANIFEST.json"), "w") as f:
                    f.write(data)
            except OSError:
                pass

    def _ckpt_pointer(self) -> Optional[dict]:
        if not self.ckpt_path:
            return None
        manifest = os.path.join(self.ckpt_path, "MANIFEST.json")
        out: dict[str, Any] = {"path": self.ckpt_path,
                               "exists": os.path.isfile(manifest)}
        if out["exists"]:
            try:
                with open(manifest) as f:
                    m = json.load(f)
                out["iteration"] = m.get("iteration")
                out["global_round"] = m.get("global_round")
            except (OSError, ValueError):
                pass
        return out

    def _prune(self) -> None:
        """Keep the newest ``max_bundles`` bundle dirs."""
        import shutil
        root = os.path.join(self.run_dir, "incidents")
        try:
            names = sorted(n for n in os.listdir(root)
                           if n.startswith("incident-"))
        except OSError:
            return
        for n in names[:-self.max_bundles]:
            shutil.rmtree(os.path.join(root, n), ignore_errors=True)


# ----------------------------------------------------------------------
# process hooks: excepthook + SIGQUIT (stack dump via faulthandler, then
# capture). The CLI run path installs these; tests install them in a
# subprocess. The hooks resolve the manager lazily through the
# process-local slot so re-configuring a run re-points them for free.
_current: Optional[IncidentManager] = None
_cur_lock = threading.Lock()
_hooks_installed = False


def current_manager() -> Optional[IncidentManager]:
    with _cur_lock:
        return _current


def set_current(manager: Optional[IncidentManager]) -> None:
    global _current
    with _cur_lock:
        _current = manager


def install_process_hooks(manager: Optional[IncidentManager] = None,
                          sigquit: bool = True,
                          excepthook: bool = True,
                          faulthandler_file=None) -> None:
    """Arm crash-time capture for this process.

    - ``sys.excepthook`` is chained: the current manager captures (with
      traceback, bypassing debounce), then the previous hook runs.
    - SIGQUIT gets a handler that dumps every thread's stack through
      ``faulthandler.dump_traceback`` (to ``faulthandler_file`` when
      given, stderr otherwise) and then captures a bundle — the classic
      "the process is wedged, kill -QUIT it and read the black box".
      Signal installation is main-thread-only, like resilience/preempt.

    Idempotent: repeated calls re-point the manager but install each
    hook once.
    """
    global _hooks_installed
    if manager is not None:
        set_current(manager)
    if _hooks_installed:
        return
    _hooks_installed = True
    if excepthook:
        prev = sys.excepthook

        def _hook(tp, val, tb):
            m = current_manager()
            if m is not None:
                try:
                    m.on_exception(val, tb=tb)
                except Exception:   # noqa: BLE001
                    pass
            prev(tp, val, tb)

        sys.excepthook = _hook
    if sigquit and hasattr(os, "kill") \
            and threading.current_thread() is threading.main_thread():
        import faulthandler
        import signal

        def _on_sigquit(signum, frame):
            try:
                faulthandler.dump_traceback(
                    file=faulthandler_file or sys.stderr, all_threads=True)
            except Exception:   # noqa: BLE001
                pass
            m = current_manager()
            if m is not None:
                m.trigger("sigquit", evidence={"signal": "SIGQUIT"},
                          force=True)

        try:
            signal.signal(signal.SIGQUIT, _on_sigquit)
        except (ValueError, OSError, AttributeError):
            pass                      # non-main thread / platform without it


# ----------------------------------------------------------------------
# small helpers
def _write_json(path: str, obj) -> None:
    with open(path, "w") as f:
        json.dump(obj, f, default=_json_default)


def _jsonable(obj):
    """Round-trip through the bus's tolerant encoder so numpy payloads
    in trigger evidence never poison meta.json."""
    if obj is None:
        return None
    try:
        return json.loads(json.dumps(obj, default=_json_default))
    except (TypeError, ValueError):
        return {"repr": repr(obj)[:500]}


def _hostname() -> str:
    import socket
    try:
        return socket.gethostname()
    except OSError:
        return "?"


def _git_sha() -> Optional[str]:
    """Best-effort HEAD sha of the package checkout; None outside git."""
    import subprocess
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    try:
        out = subprocess.run(["git", "rev-parse", "HEAD"], cwd=pkg,
                             capture_output=True, text=True, timeout=5)
    except (OSError, subprocess.SubprocessError):
        return None
    sha = out.stdout.strip()
    return sha if out.returncode == 0 and sha else None


def trailing_trace(dump: dict) -> dict:
    """Perfetto-loadable Chrome-trace JSON built from an in-memory ring
    dump (no files): span rings become duration slices, the event ring
    becomes instants on the reserved per-process events lane — the same
    layout ``obs.spans.build_trace`` gives a full run dir."""
    trace: list[dict] = []
    pids: set[int] = set()
    lanes: dict[tuple, int] = {}

    def lane(pid: int, raw_tid) -> int:
        key = (pid, raw_tid)
        if key not in lanes:
            lanes[key] = 1 + sum(1 for (p, _) in lanes if p == pid)
        return lanes[key]

    for s in dump.get("spans", ()):
        pid = int(s.get("pid", 0))
        pids.add(pid)
        ev = {"name": s.get("name", "?"), "cat": s.get("cat", "phase"),
              "ph": "X", "ts": float(s.get("ts", 0.0)),
              "dur": max(float(s.get("dur", 0.0)), 0.0),
              "pid": pid, "tid": lane(pid, s.get("tid", "main"))}
        if s.get("args"):
            ev["args"] = _jsonable(s["args"])
        trace.append(ev)
    for e in dump.get("events", ()):
        if "_ts" not in e or "kind" not in e:
            continue
        pid = int(e.get("pid", 0))
        pids.add(pid)
        trace.append({"name": e["kind"], "cat": "event", "ph": "i",
                      "s": "t", "ts": round(float(e["_ts"]) * 1e6, 1),
                      "pid": pid, "tid": 0})
    trace.sort(key=lambda ev: ev["ts"])
    meta: list[dict] = []
    for pid in sorted(pids):
        meta.append({"ph": "M", "name": "process_name", "pid": pid,
                     "tid": 0, "args": {"name": f"process {pid}"}})
        meta.append({"ph": "M", "name": "thread_name", "pid": pid,
                     "tid": 0, "args": {"name": "events"}})
    for (pid, _raw), tid in sorted(lanes.items(), key=lambda kv: kv[1]):
        meta.append({"ph": "M", "name": "thread_name", "pid": pid,
                     "tid": tid, "args": {"name": f"thread {tid}"}})
    return {"traceEvents": meta + trace, "displayTimeUnit": "ms"}


# ----------------------------------------------------------------------
# triage CLI: python -m feddrift_tpu incident <bundle-or-run_dir>
def resolve_bundle(target: str) -> Optional[str]:
    """A bundle dir (holds meta.json), or the NEWEST bundle under
    ``<target>/incidents/``; None when neither matches."""
    if os.path.isfile(os.path.join(target, "meta.json")):
        return target
    root = os.path.join(target, "incidents")
    if os.path.isdir(root):
        names = sorted(n for n in os.listdir(root)
                       if os.path.isfile(os.path.join(root, n, "meta.json")))
        if names:
            return os.path.join(root, names[-1])
    return None


def _load_json(path: str):
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _fmt_ev(rec: dict) -> str:
    it = rec.get("iteration")
    head = f"it {it}" if it is not None else "-"
    return f"{head:>8}  {rec.get('kind', '?')}"


def render_incident(bdir: str, meta: dict, flight: dict) -> str:
    """The triage story: what fired, the dominant critical-path
    segment, recent swaps/canary verdicts, replica/broker health."""
    lines: list[str] = []
    lines.append(f"== incident {meta.get('bundle', os.path.basename(bdir))} "
                 f"==")
    ts = meta.get("ts")
    when = time.strftime("%Y-%m-%d %H:%M:%S", time.localtime(ts)) \
        if isinstance(ts, (int, float)) else "?"
    lines.append(f"reason      {meta.get('reason', '?')}")
    lines.append(f"captured    {when}  pid {meta.get('pid', '?')}  "
                 f"host {meta.get('host', '?')}")
    if meta.get("git_sha"):
        lines.append(f"git         {meta['git_sha']}")
    ckpt = meta.get("checkpoint") or {}
    if ckpt:
        extra = f" (iteration {ckpt.get('iteration')})" \
            if ckpt.get("iteration") is not None else ""
        state = "present" if ckpt.get("exists") else "MISSING"
        lines.append(f"checkpoint  {ckpt.get('path')} — {state}{extra}")

    # -- what fired -----------------------------------------------------
    lines.append("")
    lines.append("-- what fired --")
    ev = meta.get("evidence") or {}
    msg = (ev.get("message") or ev.get("error") or ev.get("reason")
           or ev.get("signal"))
    if ev.get("rule"):
        lines.append(f"rule {ev['rule']} ({ev.get('severity', '?')})")
    if ev.get("objective"):
        lines.append(f"slo objective {ev['objective']}")
    if msg:
        lines.append(str(msg))
    if ev.get("traceback"):
        tb = str(ev["traceback"]).strip().splitlines()
        lines.extend(tb[-12:])
    alerts = flight.get("alerts") or []
    if alerts:
        lines.append(f"recent alerts ({len(alerts)} in ring):")
        for a in alerts[-5:]:
            lines.append(f"  {_fmt_ev(a)}  {a.get('rule') or a.get('objective') or ''}"
                         f" {a.get('severity', '')}".rstrip())

    # -- critical path at capture --------------------------------------
    breakdowns = flight.get("round_breakdowns") or []
    if breakdowns:
        last = breakdowns[-1]
        segs = last.get("segments") or {}
        lines.append("")
        lines.append("-- critical path (last round_breakdown, iteration "
                     f"{last.get('iteration', '?')}) --")
        wall = float(last.get("wall_s") or 0.0)
        if segs:
            dom = max(segs.items(), key=lambda kv: kv[1])
            frac = dom[1] / wall if wall > 0 else 0.0
            lines.append(f"dominant segment: {dom[0]} "
                         f"({dom[1]:.4f}s of {wall:.4f}s wall, "
                         f"{100 * frac:.0f}%)")
            for k, v in sorted(segs.items(), key=lambda kv: -kv[1])[:5]:
                lines.append(f"  {k:<22} {v:.4f}s")
        hof = last.get("host_overhead_frac")
        if hof is not None:
            lines.append(f"host_overhead_frac: {hof}")

    # -- swaps & canaries ----------------------------------------------
    swap_kinds = ("pool_swapped", "canary_started", "canary_verdict",
                  "cluster_merge", "cluster_split", "cluster_create",
                  "cluster_delete")
    swaps = [e for e in (flight.get("events") or ())
             if e.get("kind") in swap_kinds]
    if swaps:
        lines.append("")
        lines.append("-- recent swaps / canary verdicts --")
        for e in swaps[-8:]:
            detail = ""
            if e.get("lineage_ids"):
                detail = " lineage " + "<-".join(
                    str(x) for x in e["lineage_ids"])
            if e.get("kind") == "canary_verdict":
                detail += f" -> {e.get('verdict', '?')}" \
                          f" ({e.get('reason', '?')})"
            if e.get("version") is not None:
                detail += f" version {e['version']}"
            lines.append(f"  {_fmt_ev(e)}{detail}")

    # -- replica / broker health ---------------------------------------
    health_kinds = ("replica_failed", "replica_drained", "frontend_shed",
                    "conn_drop", "conn_reconnect", "heartbeat_missed")
    health = [e for e in (flight.get("events") or ())
              if e.get("kind") in health_kinds]
    fleet = meta.get("fleet") or {}
    if health or fleet:
        lines.append("")
        lines.append("-- replica / broker health at capture --")
        for e in health[-8:]:
            detail = ""
            if e.get("replica"):
                detail = f" replica {e['replica']}"
            if e.get("reason"):
                detail += f" ({e['reason']})"
            if e.get("remaining") is not None:
                detail += f" remaining={e['remaining']}"
            lines.append(f"  {_fmt_ev(e)}{detail}")
        if fleet:
            dead = fleet.get("dead") or []
            if dead:
                lines.append(f"DEAD REPLICAS: {', '.join(dead)}")
            lanes = fleet.get("lanes") or []
            lines.append(f"merged fleet snapshots: "
                         f"{', '.join(lanes) if lanes else '(none)'}")

    # -- bundle contents ------------------------------------------------
    lines.append("")
    lines.append("-- bundle files --")
    for root, _dirs, files in sorted(os.walk(bdir)):
        rel = os.path.relpath(root, bdir)
        for fn in sorted(files):
            p = os.path.join(root, fn)
            rp = fn if rel == "." else os.path.join(rel, fn)
            try:
                sz = os.path.getsize(p)
            except OSError:
                sz = 0
            lines.append(f"  {rp:<28} {sz} bytes")
    return "\n".join(lines)


def incident_main(argv=None) -> int:
    """``python -m feddrift_tpu incident <bundle-or-run_dir>`` — render
    the post-mortem triage story. Pure host-side (no jax)."""
    ap = argparse.ArgumentParser(
        prog="python -m feddrift_tpu incident",
        description="render the triage story from an incident bundle "
                    "(or the newest bundle under <run_dir>/incidents/)")
    ap.add_argument("target", help="bundle dir or run dir")
    ap.add_argument("--json", action="store_true",
                    help="print bundle meta + flight summary as JSON")
    args = ap.parse_args(argv)
    bdir = resolve_bundle(args.target)
    if bdir is None:
        print(f"no incident bundle found under {args.target!r} "
              "(expected meta.json or an incidents/ directory)",
              file=sys.stderr)
        return 1
    meta = _load_json(os.path.join(bdir, "meta.json")) or {}
    flight = _load_json(os.path.join(bdir, "flight.json")) or {}
    if args.json:
        print(json.dumps({
            "bundle": bdir, "meta": meta,
            "ring": {"events": len(flight.get("events", ())),
                     "alerts": len(flight.get("alerts", ())),
                     "spans": len(flight.get("spans", ()))},
        }, indent=2))
        return 0
    print(render_incident(bdir, meta, flight))
    return 0
