"""Streaming quantile sketches (P² algorithm), O(1) memory per quantile.

Histograms (obs/instruments.py) answer "how many rounds fell in
[0.5s, 1s)?" but their percentile resolution is capped by the bucket
grid. The live ops plane wants an actual p99 gauge that tracks the tail
without retaining samples; the P² algorithm (Jain & Chlamtac, CACM 1985)
maintains five markers per tracked quantile and adjusts them with a
piecewise-parabolic update on every observation — constant memory,
constant time, no sorting.

``QuantileSketch`` is the registrable instrument (see
``Registry.quantile_sketch``); it tracks a tuple of quantiles (default
p50/p95/p99) plus count/sum, and exports Prometheus summary-style
``name{quantile="0.99"}`` lines. Accuracy is typically within ~1% of the
exact percentile after a few hundred observations (tested against exact
percentiles in tests/test_live_ops.py).
"""

from __future__ import annotations

import math
import threading

DEFAULT_QUANTILES = (0.5, 0.95, 0.99)


class P2Estimator:
    """Single-quantile P² estimator: five markers, no sample retention.

    The first five observations are stored exactly; from the sixth on,
    marker heights are nudged toward their desired positions with the
    parabolic (fallback linear) interpolation from the paper.
    """

    __slots__ = ("p", "n", "_init", "q", "npos", "dn")

    def __init__(self, p: float) -> None:
        if not 0.0 < p < 1.0:
            raise ValueError(f"quantile must be in (0, 1), got {p}")
        self.p = float(p)
        self.n = 0
        self._init: list[float] | None = []
        self.q: list[float] | None = None      # marker heights
        self.npos: list[int] | None = None     # marker positions (1-based)
        self.dn = (0.0, p / 2.0, p, (1.0 + p) / 2.0, 1.0)

    def observe(self, x: float) -> None:
        x = float(x)
        self.n += 1
        if self.q is None:
            self._init.append(x)
            if len(self._init) == 5:
                self._init.sort()
                self.q = list(self._init)
                self.npos = [1, 2, 3, 4, 5]
                self._init = None
            return
        q, npos = self.q, self.npos
        if x < q[0]:
            q[0] = x
            k = 0
        elif x >= q[4]:
            q[4] = x
            k = 3
        else:
            k = 0
            while x >= q[k + 1]:
                k += 1
        for i in range(k + 1, 5):
            npos[i] += 1
        for i in (1, 2, 3):
            desired = 1.0 + (self.n - 1) * self.dn[i]
            d = desired - npos[i]
            if (d >= 1.0 and npos[i + 1] - npos[i] > 1) or \
                    (d <= -1.0 and npos[i - 1] - npos[i] < -1):
                step = 1 if d >= 0 else -1
                qn = self._parabolic(i, step)
                if not (q[i - 1] < qn < q[i + 1]):
                    qn = self._linear(i, step)
                q[i] = qn
                npos[i] += step

    def _parabolic(self, i: int, d: int) -> float:
        q, npos = self.q, self.npos
        return q[i] + d / (npos[i + 1] - npos[i - 1]) * (
            (npos[i] - npos[i - 1] + d) * (q[i + 1] - q[i])
            / (npos[i + 1] - npos[i])
            + (npos[i + 1] - npos[i] - d) * (q[i] - q[i - 1])
            / (npos[i] - npos[i - 1]))

    def _linear(self, i: int, d: int) -> float:
        q, npos = self.q, self.npos
        return q[i] + d * (q[i + d] - q[i]) / (npos[i + d] - npos[i])

    def quantile(self) -> float | None:
        """Current estimate; exact (nearest-rank) below five samples,
        None before the first observation."""
        if self.q is not None:
            return self.q[2]
        if not self._init:
            return None
        s = sorted(self._init)
        idx = min(len(s) - 1, max(0, math.ceil(self.p * len(s)) - 1))
        return s[idx]


class QuantileSketch:
    """Multi-quantile streaming sketch, instrument-shaped (thread-safe
    observe, locked snapshot) so it registers alongside Histogram."""

    __slots__ = ("_lock", "quantiles", "_est", "count", "sum",
                 "min", "max")

    def __init__(self, quantiles: tuple = DEFAULT_QUANTILES) -> None:
        self._lock = threading.Lock()
        self.quantiles = tuple(float(q) for q in quantiles)
        if not self.quantiles:
            raise ValueError("at least one quantile is required")
        self._est = {q: P2Estimator(q) for q in self.quantiles}
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf

    def observe(self, v: float) -> None:
        v = float(v)
        with self._lock:
            self.count += 1
            self.sum += v
            if v < self.min:
                self.min = v
            if v > self.max:
                self.max = v
            for est in self._est.values():
                est.observe(v)

    def query(self, q: float) -> float | None:
        with self._lock:
            est = self._est.get(float(q))
            return est.quantile() if est is not None else None

    def reset(self) -> None:
        """Restart the stream in place: benchmarks drop warm-up samples
        between phases so the exported digest covers only the measured
        window, without invalidating references to this instrument."""
        with self._lock:
            self._est = {q: P2Estimator(q) for q in self.quantiles}
            self.count = 0
            self.sum = 0.0
            self.min = math.inf
            self.max = -math.inf

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "count": self.count,
                "sum": self.sum,
                "min": self.min if self.count else None,
                "max": self.max if self.count else None,
                "quantiles": {f"{q:g}": est.quantile()
                              for q, est in self._est.items()},
            }
