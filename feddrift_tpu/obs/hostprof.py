"""Host-plane observatory: sampling stack profiler + subsystem ledger.

Every byte- and cost-accounting tool built before this module measures
the DEVICE side (obs/costmodel.py program costs, the round_breakdown
segment split); host cost appeared only as the opaque
``host_overhead_frac`` scalar. ROADMAP item 2 says the host control
plane — dense ``ClientRegistry`` columns, sequential cohort planning,
``RoutingTable.from_registry`` rebuilds — is the next scaling ceiling,
so this module makes host seconds and host bytes first-class:

- ``SamplingProfiler`` — a daemon thread sampling every OTHER thread's
  stack via ``sys._current_frames()`` at ``cfg.hostprof_hz`` (default
  off). Aggregates folded stacks (flamegraph-ready ``a;b;c count``
  text, ``write_folded``) and writes leaf-change slices to
  ``<run_dir>/hostprof.jsonl`` in the span schema, which
  ``report --trace`` merges into the Perfetto timeline as its own lane.
- ``HostLedger`` — named-subsystem accounting (``SUBSYSTEMS``:
  cohort_plan, registry_writeback, routing_rebuild, stager, broker_io,
  drift_decision) of host-seconds per round plus host bytes of the
  structures that scale with population (registry columns, assign_hist,
  routing tables, staged cohort shards) and the process RSS watermark.
  ``finalize()`` emits one ``host_ledger`` event per iteration and sets
  the ``host_ledger_seconds{subsystem=}`` / ``host_bytes{structure=}``
  instruments (plus ``host_ledger_seconds_total`` counters, which
  ``bench.py --hostscale`` divides by steady rounds).
- ``fit_scaling`` — the log-log least-squares exponent fit behind the
  HOSTSCALE artifact's per-subsystem scaling exponents (seconds/round
  and bytes vs population P), gated absolutely by the ``regress``
  hostscale axis.

Stdlib only (RSS comes from /proc, falling back to getrusage — no
psutil); recording is O(1) per call like obs/instruments.py.
"""

from __future__ import annotations

import contextlib
import json
import math
import os
import sys
import threading
import time
from typing import Any, Iterator, Optional

from feddrift_tpu.obs.instruments import registry

# The closed subsystem set the ledger accounts. Adding one is a doc
# change too (docs/OBSERVABILITY.md "Host-plane observatory").
SUBSYSTEMS = ("cohort_plan", "registry_writeback", "routing_rebuild",
              "stager", "broker_io", "drift_decision")


# ----------------------------------------------------------------------
# stdlib process-memory + nbytes helpers
def rss_bytes() -> Optional[int]:
    """Current resident set size in bytes: /proc/self/status VmRSS where
    available (Linux), ``getrusage`` peak otherwise; None when neither
    source works (observability stays passive, never raises)."""
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1]) * 1024
    except (OSError, ValueError, IndexError):
        pass
    try:
        import resource
        return int(resource.getrusage(resource.RUSAGE_SELF).ru_maxrss) * 1024
    except Exception:                  # noqa: BLE001 — best-effort probe
        return None


def nbytes_of(tree: Any) -> int:
    """Total ``.nbytes`` over every array-like leaf of a nested
    dict/list/tuple container (numpy and jax arrays both expose it);
    non-array leaves contribute zero."""
    if isinstance(tree, dict):
        return sum(nbytes_of(v) for v in tree.values())
    if isinstance(tree, (list, tuple)):
        return sum(nbytes_of(v) for v in tree)
    nb = getattr(tree, "nbytes", None)
    try:
        return int(nb) if nb is not None else 0
    except (TypeError, ValueError):
        return 0


def fit_scaling(xs, ys) -> Optional[float]:
    """Least-squares slope of log(y) on log(x): the empirical scaling
    exponent of ``y ~ x**e``. Non-positive pairs are dropped (a zeroed
    subsystem has no defined exponent); None when fewer than two valid
    points remain or x does not vary."""
    pts = [(float(x), float(y)) for x, y in zip(xs, ys)
           if x is not None and y is not None and x > 0 and y > 0]
    if len(pts) < 2:
        return None
    lx = [math.log(x) for x, _ in pts]
    ly = [math.log(y) for _, y in pts]
    n = len(pts)
    mx, my = sum(lx) / n, sum(ly) / n
    den = sum((a - mx) ** 2 for a in lx)
    if den <= 0:
        return None
    return sum((a - mx) * (b - my) for a, b in zip(lx, ly)) / den


# ----------------------------------------------------------------------
# the per-subsystem cost/memory ledger
class HostLedger:
    """Thread-safe accumulator of host-seconds per subsystem and host
    bytes per structure, finalized once per iteration into a
    ``host_ledger`` event + gauges/counters.

    Seconds are per-round state (cleared by ``finalize``); bytes are
    sticky latest-value state (a routing table rebuilt at iteration 3
    still occupies memory at iteration 7); the RSS watermark is the max
    ever observed by ``finalize`` since ``reset``.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._seconds: dict[str, float] = {}
        self._bytes: dict[str, int] = {}
        self._rss_peak = 0

    def reset(self) -> None:
        with self._lock:
            self._seconds.clear()
            self._bytes.clear()
            self._rss_peak = 0

    # -- accounting -----------------------------------------------------
    def add_seconds(self, subsystem: str, dt: float) -> None:
        if dt <= 0:
            return
        with self._lock:
            self._seconds[subsystem] = self._seconds.get(subsystem, 0.0) + dt

    @contextlib.contextmanager
    def timed(self, subsystem: str) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.add_seconds(subsystem, time.perf_counter() - t0)

    def set_bytes(self, structure: str, nbytes: int) -> None:
        with self._lock:
            self._bytes[structure] = int(nbytes)

    # -- views ----------------------------------------------------------
    def seconds(self) -> dict[str, float]:
        with self._lock:
            return dict(self._seconds)

    def bytes(self) -> dict[str, int]:
        with self._lock:
            return dict(self._bytes)

    def top_bytes(self, n: int = 3) -> list[tuple[str, int]]:
        """The ``n`` largest tracked structures, for /status."""
        with self._lock:
            items = sorted(self._bytes.items(), key=lambda kv: -kv[1])
        return items[:n]

    @property
    def rss_peak_bytes(self) -> int:
        with self._lock:
            return self._rss_peak

    # -- per-iteration finalize -----------------------------------------
    def finalize(self, iteration: Optional[int] = None, rounds: int = 1,
                 emit_event: bool = True) -> dict:
        """Snapshot + clear this round's seconds, refresh the
        instruments, and emit the per-iteration ``host_ledger`` event.
        Returns the event fields (tests and callers without a bus)."""
        with self._lock:
            sec = dict(self._seconds)
            self._seconds.clear()
            byt = dict(self._bytes)
        rss = rss_bytes()
        if rss is not None:
            with self._lock:
                self._rss_peak = max(self._rss_peak, rss)
                peak = self._rss_peak
        else:
            peak = self.rss_peak_bytes
        reg = registry()
        for name, s in sec.items():
            reg.gauge("host_ledger_seconds", subsystem=name).set(round(s, 6))
            reg.counter("host_ledger_seconds_total", subsystem=name).inc(s)
        for name, b in byt.items():
            reg.gauge("host_bytes", structure=name).set(b)
        if rss is not None:
            reg.gauge("host_rss_bytes").set(rss)
            reg.gauge("host_rss_peak_bytes").set(peak)
        rec = {
            "iteration": iteration, "rounds": int(rounds),
            "seconds": {k: round(v, 6) for k, v in sorted(sec.items())},
            "bytes": {k: int(v) for k, v in sorted(byt.items())},
            "rss_bytes": rss,
            "rss_peak_bytes": peak or None,
        }
        if emit_event:
            from feddrift_tpu.obs import events as _events
            try:
                _events.emit("host_ledger", **rec)
            except Exception:   # noqa: BLE001 — observability stays passive
                pass
        return rec


_ledger = HostLedger()


def ledger() -> HostLedger:
    """The process-local ledger every instrumented layer reports into
    (mirrors ``obs.registry()`` / ``obs.live.status_board()``)."""
    return _ledger


# ----------------------------------------------------------------------
# the sampling stack profiler
class SamplingProfiler:
    """Low-overhead wall-clock sampler over ``sys._current_frames()``.

    A daemon thread wakes every ``1/hz`` seconds and folds each OTHER
    thread's current stack into an aggregate ``{(frame, ...): count}``
    map. Consecutive samples sharing a leaf frame coalesce into one
    timeline *slice* written to ``path`` (span schema, lane
    ``hostprof:<tid>``) so ``report --trace`` shows where host threads
    actually spent their time between the instrumented spans.

    ``start``/``stop``/``close`` are idempotent and thread-safe; a
    sampling error never propagates (the profiled run must not care).
    """

    def __init__(self, hz: float, path: Optional[str] = None, pid: int = 0,
                 max_stack: int = 48) -> None:
        if hz <= 0:
            raise ValueError(f"hz must be > 0, got {hz}")
        self.hz = float(hz)
        self.period = 1.0 / self.hz
        self.path = path
        self.pid = pid
        self.max_stack = int(max_stack)
        self.samples = 0
        self._lock = threading.Lock()
        self._folded: dict[tuple, int] = {}
        # tid -> [leaf, folded-stack-str, t_start, t_last] of the open slice
        self._open: dict[int, list] = {}
        # code object -> "file.py:fn" label; memoized because formatting
        # every frame of every thread at 50 Hz is the sampler's hot cost
        self._labels: dict = {}
        # tid -> ((leaf frame id, f_lasti), stack tuple): threads parked
        # in a wait keep the same leaf frame at the same instruction, so
        # their stacks are reused without re-walking — on a 1-core host
        # most threads are parked at every sample
        self._last: dict[int, tuple] = {}
        # closed slices buffer: written in one batch at stop() — a 1-core
        # host cannot afford a write+flush per leaf change
        self._slices: list[dict] = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._fh = None

    # -- lifecycle ------------------------------------------------------
    def start(self) -> "SamplingProfiler":
        with self._lock:
            if self._thread is not None:
                return self                      # already running
            if self.path and self._fh is None:
                os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
                self._fh = open(self.path, "a")
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="hostprof-sampler")
            self._thread.start()
        return self

    def stop(self) -> None:
        with self._lock:
            thread, self._thread = self._thread, None
            self._stop.set()
        if thread is not None:
            thread.join(timeout=2.0)
        with self._lock:
            for tid, sl in self._open.items():
                self._close_slice_locked(tid, sl)
            self._open.clear()
            if self._fh is not None:
                for rec in self._slices:
                    self._fh.write(json.dumps(rec) + "\n")
                self._fh.close()
                self._fh = None
            self._slices = []

    close = stop

    def __enter__(self) -> "SamplingProfiler":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    @property
    def running(self) -> bool:
        with self._lock:
            return self._thread is not None

    # -- sampling -------------------------------------------------------
    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self._sample_once(time.time())
            except Exception:   # noqa: BLE001 — sampling must never kill a run
                pass
            self._stop.wait(self.period)

    def _stack_of(self, frame) -> tuple:
        labels = self._labels
        out = []
        depth = 0
        while frame is not None and depth < self.max_stack:
            code = frame.f_code
            lbl = labels.get(code)
            if lbl is None:
                lbl = f"{os.path.basename(code.co_filename)}:{code.co_name}"
                labels[code] = lbl
            out.append(lbl)
            frame = frame.f_back
            depth += 1
        out.reverse()                            # root;...;leaf folded order
        return tuple(out)

    def _sample_once(self, now: float) -> None:
        me = threading.get_ident()
        frames = sys._current_frames()
        last = self._last
        with self._lock:
            self.samples += 1
            for tid, frame in frames.items():
                if tid == me:
                    continue
                key = (id(frame), frame.f_lasti)
                cached = last.get(tid)
                if cached is not None and cached[0] == key:
                    stack = cached[1]            # parked thread: no walk
                else:
                    stack = self._stack_of(frame)
                    last[tid] = (key, stack)
                if not stack:
                    continue
                self._folded[stack] = self._folded.get(stack, 0) + 1
                self._fold_slice_locked(tid, stack, now)

    def _fold_slice_locked(self, tid: int, stack: tuple, now: float) -> None:
        leaf = stack[-1]
        sl = self._open.get(tid)
        if sl is not None and sl[0] == leaf:
            sl[3] = now                          # extend the open slice
            return
        if sl is not None:
            self._close_slice_locked(tid, sl)
        self._open[tid] = [leaf, stack[-12:], now, now]

    def _close_slice_locked(self, tid: int, sl: list) -> None:
        if self._fh is None:
            return
        leaf, stack, t0, t1 = sl
        # a single-sample slice still renders one sampling period wide
        dur = max(t1 - t0, self.period)
        self._slices.append(
            {"name": leaf, "cat": "hostprof",
             "ts": round(t0 * 1e6, 1), "dur": round(dur * 1e6, 1),
             "pid": self.pid, "tid": f"hostprof:{tid}",
             "args": {"stack": ";".join(stack)}})

    # -- export ---------------------------------------------------------
    def folded(self) -> dict[str, int]:
        """{"root;...;leaf": samples} aggregate."""
        with self._lock:
            return {";".join(s): c for s, c in self._folded.items()}

    def folded_text(self) -> str:
        """Flamegraph-ready folded-stack text, hottest stacks first."""
        items = sorted(self.folded().items(), key=lambda kv: (-kv[1], kv[0]))
        return "\n".join(f"{stack} {count}" for stack, count in items) \
            + ("\n" if items else "")

    def write_folded(self, path: str) -> str:
        with open(path, "w") as f:
            f.write(self.folded_text())
        return path


# Process-wide active sampler: constructing an Experiment re-points it
# (and stops the previous one), so back-to-back runs in one process —
# bench.py sweeps — never leak sampler threads.
_profiler: Optional[SamplingProfiler] = None
_prof_lock = threading.Lock()


def configure_profiler(hz: float, path: Optional[str] = None,
                       pid: int = 0) -> Optional[SamplingProfiler]:
    """Install (hz > 0) or clear (hz <= 0) the process-wide sampler,
    stopping any previous one first. Returns the active sampler."""
    global _profiler
    with _prof_lock:
        old, _profiler = _profiler, None
    if old is not None:
        old.stop()
    if hz > 0:
        prof = SamplingProfiler(hz, path=path, pid=pid).start()
        with _prof_lock:
            _profiler = prof
        return prof
    return None


def get_profiler() -> Optional[SamplingProfiler]:
    with _prof_lock:
        return _profiler
