"""Perf-regression gate over bench.py artifacts.

Five BENCH_r0*.json snapshots existed with nothing that compared them;
this module is the comparator, runnable in CI:

    python -m feddrift_tpu regress <bench.json> --baseline BENCH_r05.json

Accepts both raw ``bench.py`` stdout (a JSON object / last JSON line of a
capture) and the committed ``BENCH_r0*.json`` wrapper format (driver
snapshots with the bench object under ``"parsed"``). Compares the
metrics a throughput regression shows up in — rounds/s, wall seconds,
steady-state XLA compile counts, final test accuracy — and exits nonzero
iff any regresses past its threshold, printing a delta table either way.

Thresholds are *noise-aware* by construction: every limit is explicit,
relative where the metric scales (throughput, wall) and absolute where
it does not (accuracy, compile counts), with defaults sized for a noisy
1-core CI host. A metric missing from either side is reported as
``skip``, never a failure — older artifacts (no ``instruments`` key) and
``--smoke`` runs (no baselines) stay comparable on the metrics they do
carry. ``wall_s`` is only compared when both runs measured the same
number of rounds (otherwise wall scales with work, not speed).
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any

# (flag, default) — relative for throughput/wall, absolute for the rest
DEFAULT_TOL = {
    "rounds": 0.25,      # fail if rounds/s < baseline * (1 - tol)
    "wall": 0.30,        # fail if wall_s > baseline * (1 + tol)
    "acc": 0.02,         # fail if final_test_acc < baseline - tol
    "compiles": 0.0,     # fail if steady-state compiles > baseline + tol
    "bytes": 0.25,       # fail if bytes_per_round > baseline * (1 + tol)
    "host_overhead": 0.10,   # fail if host_overhead_frac > baseline + tol
    "p99": 0.75,         # fail if round_wall_p99_s > baseline * (1 + tol)
    "precision_acc": 0.05,   # fail if a reduced-precision row's accuracy
                             # < this run's own f32 row - tol
    "quality_acc": 0.05,     # fail if the streaming live-accuracy estimate
                             # drifts further than this from the offline
                             # oracle on the same labeled stream
    "secure_wall": 1.0,      # fail if the secure-agg engine wall/round >
                             # baseline * (1 + tol) — host-side numpy on
                             # shared CI, so the ceiling is generous
    "hostscale_exp": 0.2,    # fail if a fitted host-plane scaling exponent
                             # (host-seconds/round or bytes vs P, log-log
                             # slope) > baseline + tol — absolute headroom
                             # sized for fit noise on short sweeps
}


def load_bench(path: str) -> dict:
    """Load a bench artifact: raw bench.py output, a mixed-output capture
    (last parseable JSON line wins), or a BENCH_r0*.json driver wrapper
    (bench object under "parsed")."""
    with open(path) as f:
        text = f.read()
    try:
        d = json.loads(text)
    except json.JSONDecodeError:
        d = None
        for line in reversed(text.strip().splitlines()):
            try:
                d = json.loads(line)
                break
            except json.JSONDecodeError:
                continue
        if d is None:
            raise ValueError(f"{path}: no JSON object found")
    if not isinstance(d, dict):
        raise ValueError(f"{path}: expected a JSON object")
    if "parsed" in d and isinstance(d["parsed"], dict):
        d = d["parsed"]                # committed BENCH_r0*.json wrapper
    return d


def _compile_counts(bench: dict) -> tuple[float | None, float | None]:
    """(compiles, recompiles) summed over programs from the instruments
    snapshot, or (None, None) when the artifact predates instruments."""
    inst = bench.get("instruments")
    if not isinstance(inst, dict):
        return None, None
    comp = sum(v for k, v in inst.items()
               if k.startswith("jit_compiles") and isinstance(v, (int, float)))
    rec = sum(v for k, v in inst.items()
              if k.startswith("jit_recompiles") and isinstance(v, (int, float)))
    return comp, rec


def extract_metrics(bench: dict) -> dict[str, float | None]:
    comp, rec = _compile_counts(bench)
    return {
        "rounds_per_s": bench.get("value"),
        "wall_s": bench.get("wall_s"),
        "rounds": bench.get("rounds"),
        "final_test_acc": bench.get("final_test_acc"),
        "jit_compiles": comp,
        "jit_recompiles": rec,
        "host_overhead_frac": bench.get("host_overhead_frac"),
        "round_wall_p99_s": bench.get("round_wall_p99_s"),
    }


def compare(candidate: dict, baseline: dict,
            tol: dict[str, float] | None = None) -> list[dict[str, Any]]:
    """Delta rows, one per gated metric: {"metric", "baseline",
    "candidate", "delta_pct", "limit", "status"} with status ∈
    ok | regress | skip."""
    tol = {**DEFAULT_TOL, **(tol or {})}
    c, b = extract_metrics(candidate), extract_metrics(baseline)
    rows: list[dict[str, Any]] = []

    def row(metric, bv, cv, limit, regressed, note=None):
        r: dict[str, Any] = {"metric": metric, "baseline": bv,
                             "candidate": cv, "limit": limit,
                             "status": "regress" if regressed else "ok"}
        if bv not in (None, 0) and cv is not None:
            r["delta_pct"] = round(100.0 * (cv - bv) / bv, 2)
        if note:
            r["note"] = note
        return r

    def skip(metric, note):
        rows.append({"metric": metric, "baseline": b.get(metric),
                     "candidate": c.get(metric), "status": "skip",
                     "note": note})

    # throughput: higher is better, relative tolerance
    if b["rounds_per_s"] is None or c["rounds_per_s"] is None:
        skip("rounds_per_s", "missing from one side")
    else:
        floor = b["rounds_per_s"] * (1.0 - tol["rounds"])
        rows.append(row("rounds_per_s", b["rounds_per_s"], c["rounds_per_s"],
                        f">= {floor:.3f}", c["rounds_per_s"] < floor))

    # wall: lower is better; comparable only for equal measured rounds
    if b["wall_s"] is None or c["wall_s"] is None:
        skip("wall_s", "missing from one side")
    elif b["rounds"] != c["rounds"]:
        skip("wall_s", f"rounds differ ({b['rounds']} vs {c['rounds']})")
    else:
        ceil = b["wall_s"] * (1.0 + tol["wall"])
        rows.append(row("wall_s", b["wall_s"], c["wall_s"],
                        f"<= {ceil:.3f}", c["wall_s"] > ceil))

    # accuracy: higher is better, absolute tolerance
    if b["final_test_acc"] is None or c["final_test_acc"] is None:
        skip("final_test_acc", "missing from one side")
    else:
        floor = b["final_test_acc"] - tol["acc"]
        rows.append(row("final_test_acc", b["final_test_acc"],
                        c["final_test_acc"], f">= {floor:.4f}",
                        c["final_test_acc"] < floor))

    # host-overhead ceiling: lower is better, absolute tolerance (a
    # fraction in [0, 1] — relative deltas would blow up near zero).
    # Gates the critical-path attribution loop: work moved off the
    # device (slower dispatch, host-side stalls) raises this before it
    # shows up in wall clock on a fast accelerator.
    if (b["host_overhead_frac"] is None
            or c["host_overhead_frac"] is None):
        skip("host_overhead_frac", "missing from one side")
    else:
        ceil = b["host_overhead_frac"] + tol["host_overhead"]
        rows.append(row("host_overhead_frac", b["host_overhead_frac"],
                        c["host_overhead_frac"], f"<= {ceil:.4f}",
                        c["host_overhead_frac"] > ceil))

    # tail latency ceiling: lower is better, relative tolerance sized for
    # p99-of-few-hundred-samples noise on a shared host. Artifacts that
    # predate the streaming quantile sketch skip, never fail.
    if (b["round_wall_p99_s"] is None or c["round_wall_p99_s"] is None):
        skip("round_wall_p99_s", "missing from one side")
    else:
        ceil = b["round_wall_p99_s"] * (1.0 + tol["p99"])
        rows.append(row("round_wall_p99_s", b["round_wall_p99_s"],
                        c["round_wall_p99_s"], f"<= {ceil:.4f}",
                        c["round_wall_p99_s"] > ceil))

    # steady-state compile counts: lower is better, absolute tolerance
    for metric in ("jit_compiles", "jit_recompiles"):
        if b[metric] is None or c[metric] is None:
            skip(metric, "no instruments snapshot")
        else:
            ceil = b[metric] + tol["compiles"]
            rows.append(row(metric, b[metric], c[metric],
                            f"<= {ceil:g}", c[metric] > ceil))

    # population-scaling axis (bench.py --popscale; POPSCALE artifacts):
    # rounds/s per population point under the throughput tolerance, and
    # steady-state recompiles as an ABSOLUTE zero gate — growing the
    # population at fixed cohort must never change an XLA program shape.
    cps, bps = candidate.get("popscale"), baseline.get("popscale")
    if isinstance(cps, list) and isinstance(bps, list):
        by_pop = {e.get("population"): e for e in bps if isinstance(e, dict)}
        for e in cps:
            if not isinstance(e, dict):
                continue
            p = e.get("population")
            be = by_pop.get(p)
            if be is None:
                skip(f"popscale[{p}]", "population point missing in baseline")
                continue
            bv, cv = be.get("rounds_per_sec"), e.get("rounds_per_sec")
            if bv and cv:
                floor = bv * (1.0 - tol["rounds"])
                rows.append(row(f"popscale[{p}].rounds_per_s", bv, cv,
                                f">= {floor:.3f}", cv < floor))
            rec = e.get("steady_recompiles")
            if rec is not None:
                rows.append(row(f"popscale[{p}].steady_recompiles",
                                be.get("steady_recompiles"), rec, "== 0",
                                rec > 0,
                                note="compile-count invariance over "
                                     "population size"))
    elif isinstance(bps, list):
        skip("popscale", "candidate lacks the popscale axis")

    # host-plane scaling axis (bench.py --hostscale; HOSTSCALE artifacts):
    # the ISSUE-19 gate on the dense-O(P) host behaviors. Per population
    # point: rounds/s under the throughput tolerance and steady-state
    # recompiles as an ABSOLUTE zero gate (the ledger + profiler are pure
    # host work — enabling them must not mint programs). Then the fitted
    # log-log scaling exponents per subsystem (host-seconds/round vs P)
    # and per structure (bytes vs P) under an absolute +tol["hostscale_exp"]
    # headroom, and bytes/client at the largest P under the bytes ceiling —
    # the named numbers the ROADMAP item-2 refactor must beat.
    chs, bhs = candidate.get("hostscale"), baseline.get("hostscale")
    if isinstance(chs, dict) and isinstance(bhs, dict):
        b_rows = {e.get("population"): e
                  for e in (bhs.get("rows") or []) if isinstance(e, dict)}
        for e in (chs.get("rows") or []):
            if not isinstance(e, dict):
                continue
            p = e.get("population")
            be = b_rows.get(p)
            if be is None:
                skip(f"hostscale[{p}]",
                     "population point missing in baseline")
                continue
            bv, cv = be.get("rounds_per_sec"), e.get("rounds_per_sec")
            if bv and cv:
                floor = bv * (1.0 - tol["rounds"])
                rows.append(row(f"hostscale[{p}].rounds_per_s", bv, cv,
                                f">= {floor:.3f}", cv < floor))
            rec = e.get("steady_recompiles")
            if rec is not None:
                rows.append(row(f"hostscale[{p}].steady_recompiles",
                                be.get("steady_recompiles"), rec, "== 0",
                                rec > 0,
                                note="ledger + profiler are pure host "
                                     "work"))
        for axis, label in (("exp_seconds", "s/round"),
                            ("exp_bytes", "bytes")):
            b_exp = bhs.get(axis) or {}
            for sub, cv in sorted((chs.get(axis) or {}).items()):
                bv = b_exp.get(sub)
                name = f"hostscale.{axis}[{sub}]"
                if cv is None or bv is None:
                    skip(name, "exponent unfit on one side")
                    continue
                ceil = bv + tol["hostscale_exp"]
                rows.append(row(name, bv, cv, f"<= {ceil:.3f}", cv > ceil,
                                note=f"log-log {label} vs P slope"))
        b_bpc = bhs.get("bytes_per_client") or {}
        for s, cv in sorted((chs.get("bytes_per_client") or {}).items()):
            bv = b_bpc.get(s)
            name = f"hostscale.bytes_per_client[{s}]"
            if bv is None:
                skip(name, "structure missing in baseline")
                continue
            ceil = bv * (1.0 + tol["bytes"])
            rows.append(row(name, bv, cv, f"<= {ceil:.1f}", cv > ceil,
                            note="host bytes per registered client at "
                                 "max P"))
    elif isinstance(bhs, dict):
        skip("hostscale", "candidate lacks the hostscale axis")

    # multi-iteration megastep axis (bench.py --megastep; MEGASTEP
    # artifacts): rounds/s per K point under the throughput tolerance,
    # steady-state recompiles as an ABSOLUTE zero gate (fusing more
    # iterations must never grow the XLA program count — K is a static
    # arg, one program per K, compiled in warm-up), and a host-overhead
    # ceiling at every K>1 STRICTLY below the same artifact's K=1 row —
    # the whole point of the megastep is amortizing the host round-trip,
    # so a K>1 row with K=1-level host overhead is a regression even if
    # throughput still clears its floor.
    # Rows are keyed per (variant, K): legacy artifacts (MEGASTEP_r10)
    # carry no "variant" field and keep their bare megastep[{k}] keys
    # (treated as the "dense" variant); composed rows render as
    # megastep[{variant}:{k}]. The pop_hier variant additionally carries
    # an ABSOLUTE >= 2x speedup-vs-own-K=1 gate — the ISSUE-13 acceptance
    # bar for fusing population cohorts + hierarchy + chaos, immune to a
    # baseline that itself regressed.
    cms, bms = candidate.get("megastep"), baseline.get("megastep")
    if isinstance(cms, list) and isinstance(bms, list):
        def _vk(e):
            return (e.get("variant") or "dense", e.get("megastep_k"))

        def _key(variant, k):
            return (f"megastep[{k}]" if variant == "dense"
                    else f"megastep[{variant}:{k}]")

        by_vk = {_vk(e): e for e in bms if isinstance(e, dict)}
        k1_by_variant = {_vk(e)[0]: e for e in cms if isinstance(e, dict)
                         and e.get("megastep_k") == 1}
        for e in cms:
            if not isinstance(e, dict):
                continue
            variant, k = _vk(e)
            name = _key(variant, k)
            be = by_vk.get((variant, k))
            if be is None:
                skip(name, "variant/K point missing in baseline")
                continue
            bv, cv = be.get("rounds_per_sec"), e.get("rounds_per_sec")
            if bv and cv:
                floor = bv * (1.0 - tol["rounds"])
                rows.append(row(f"{name}.rounds_per_s", bv, cv,
                                f">= {floor:.3f}", cv < floor))
            rec = e.get("steady_recompiles")
            if rec is not None:
                rows.append(row(f"{name}.steady_recompiles",
                                be.get("steady_recompiles"), rec, "== 0",
                                rec > 0,
                                note="compile-count invariance over K"))
            hof = e.get("host_overhead_frac")
            hof1 = (k1_by_variant.get(variant)
                    or {}).get("host_overhead_frac")
            if k and k > 1 and hof is not None and hof1 is not None:
                rows.append(row(f"{name}.host_overhead_frac",
                                be.get("host_overhead_frac"), hof,
                                f"< {hof1:.4f}", hof >= hof1,
                                note="must beat this run's own-variant "
                                     "K=1 row"))
            sp = e.get("speedup_vs_k1")
            if variant == "pop_hier" and k and k > 1 and sp is not None:
                rows.append(row(f"{name}.speedup_vs_k1",
                                be.get("speedup_vs_k1"), sp, ">= 2",
                                sp < 2.0,
                                note="absolute composed-fusion floor vs "
                                     "own K=1"))
    elif isinstance(bms, list):
        skip("megastep", "candidate lacks the megastep axis")

    # two-tier wire axis (bench.py --hierarchy; COMM artifacts): broker
    # bytes/round per codec under the bytes ceiling, plus an ABSOLUTE
    # >= 3x reduction floor for every lossy codec — a codec that stops
    # compressing is a regression even if the baseline also regressed.
    ch, bh = candidate.get("hierarchy"), baseline.get("hierarchy")
    if isinstance(ch, list) and isinstance(bh, list):
        by_codec = {e.get("codec"): e for e in bh if isinstance(e, dict)}
        for e in ch:
            if not isinstance(e, dict):
                continue
            cd = e.get("codec")
            be = by_codec.get(cd)
            if be is None:
                skip(f"hierarchy[{cd}]", "codec missing in baseline")
                continue
            bv, cv = be.get("bytes_per_round"), e.get("bytes_per_round")
            if bv and cv:
                ceil = bv * (1.0 + tol["bytes"])
                rows.append(row(f"hierarchy[{cd}].bytes_per_round", bv, cv,
                                f"<= {ceil:.0f}", cv > ceil))
            ratio = e.get("ratio_vs_none")
            if cd != "none" and ratio is not None:
                rows.append(row(f"hierarchy[{cd}].ratio_vs_none",
                                be.get("ratio_vs_none"), ratio, ">= 3",
                                ratio < 3.0,
                                note="compression floor vs uncompressed"))
    elif isinstance(bh, list):
        skip("hierarchy", "candidate lacks the hierarchy axis")

    # end-to-end precision-policy axis (bench.py --precision; PRECISION
    # artifacts): one row per (variant, policy) from the paired sweep.
    # rounds/s under the throughput tolerance, steady-state recompiles as
    # an ABSOLUTE zero gate (a policy is one jit signature per program,
    # compiled in warm-up — never a per-round dtype lottery), accuracy of
    # every reduced-precision row within --tol-precision-acc of this
    # run's OWN f32 row (immune to a baseline that itself drifted), and
    # ABSOLUTE ceilings on the bf16_mixed cost-model/wire ratios — the
    # ISSUE-15 acceptance bars: program_bytes_accessed <= 0.60x and wire
    # bytes/round <= 0.55x of the paired f32 row. Rows are keyed
    # precision[{variant}:{policy}] so future model variants never
    # collide with the resnet rows.
    cpr, bpr = candidate.get("precision"), baseline.get("precision")
    if isinstance(cpr, list) and isinstance(bpr, list):
        def _vp(e):
            return (e.get("variant") or "resnet", e.get("policy"))

        by_vp = {_vp(e): e for e in bpr if isinstance(e, dict)}
        f32_by_variant = {_vp(e)[0]: e for e in cpr if isinstance(e, dict)
                          and e.get("policy") == "f32"}
        for e in cpr:
            if not isinstance(e, dict):
                continue
            variant, pol = _vp(e)
            name = f"precision[{variant}:{pol}]"
            be = by_vp.get((variant, pol))
            if be is None:
                skip(name, "variant/policy point missing in baseline")
                continue
            bv, cv = be.get("rounds_per_sec"), e.get("rounds_per_sec")
            if bv and cv:
                floor = bv * (1.0 - tol["rounds"])
                rows.append(row(f"{name}.rounds_per_s", bv, cv,
                                f">= {floor:.3f}", cv < floor))
            rec = e.get("steady_recompiles")
            if rec is not None:
                rows.append(row(f"{name}.steady_recompiles",
                                be.get("steady_recompiles"), rec, "== 0",
                                rec > 0,
                                note="one program per policy, compiled "
                                     "in warm-up"))
            acc = e.get("final_test_acc")
            acc32 = (f32_by_variant.get(variant)
                     or {}).get("final_test_acc")
            if pol != "f32" and acc is not None and acc32 is not None:
                floor = acc32 - tol["precision_acc"]
                rows.append(row(f"{name}.final_test_acc",
                                be.get("final_test_acc"), acc,
                                f">= {floor:.4f}", acc < floor,
                                note="vs this run's own f32 row"))
            br = e.get("bytes_accessed_ratio")
            if pol == "bf16_mixed" and br is not None:
                rows.append(row(f"{name}.bytes_accessed_ratio",
                                be.get("bytes_accessed_ratio"), br,
                                "<= 0.6", br > 0.60,
                                note="absolute HBM-traffic ceiling vs "
                                     "own f32 row"))
            wr = e.get("wire_bytes_ratio")
            if pol == "bf16_mixed" and wr is not None:
                rows.append(row(f"{name}.wire_bytes_ratio",
                                be.get("wire_bytes_ratio"), wr,
                                "<= 0.55", wr > 0.55,
                                note="absolute wire-bytes ceiling vs "
                                     "own f32 row"))
    elif isinstance(bpr, list):
        skip("precision", "candidate lacks the precision axis")

    # serving read-path axis (bench.py --serve; SERVE artifacts): one row
    # per (mode, max-bucket) point — in-process closed-loop rows plus the
    # mode="socket" frontend row (HTTP plane, 2 replicas, bounded
    # admission; carries the open-loop knee ladder and its gated
    # shed-rate bound).
    # requests/s under the throughput tolerance, request p99 under the
    # tail-latency tolerance, steady-state recompiles as an ABSOLUTE zero
    # gate (buckets are compiled in warm-up; mixed-cluster traffic must
    # never mint a new XLA program), plus an ABSOLUTE >= 3x floor on the
    # best batched speedup-vs-unbatched — micro-batching that stops paying
    # for itself is a regression even if the baseline also regressed.
    # Rows are keyed serve[{mode}:b{bucket}] so an unbatched bucket=1 row
    # and a batched row never collide across variants.
    csv_, bsv = candidate.get("serve"), baseline.get("serve")
    if isinstance(csv_, list) and isinstance(bsv, list):
        def _mb(e):
            return (e.get("mode") or "batched", e.get("bucket"))

        by_mb = {_mb(e): e for e in bsv if isinstance(e, dict)}
        best_speedup = None
        for e in csv_:
            if not isinstance(e, dict):
                continue
            mode, bucket = _mb(e)
            name = f"serve[{mode}:b{bucket}]"
            sp = e.get("speedup_vs_unbatched")
            if mode == "batched" and sp is not None:
                best_speedup = sp if best_speedup is None \
                    else max(best_speedup, sp)
            be = by_mb.get((mode, bucket))
            if be is None:
                skip(name, "mode/bucket point missing in baseline")
                continue
            bv, cv = be.get("requests_per_s"), e.get("requests_per_s")
            if bv and cv:
                floor = bv * (1.0 - tol["rounds"])
                rows.append(row(f"{name}.requests_per_s", bv, cv,
                                f">= {floor:.1f}", cv < floor))
            bp, cp = be.get("p99_ms"), e.get("p99_ms")
            if bp and cp:
                ceil = bp * (1.0 + tol["p99"])
                rows.append(row(f"{name}.p99_ms", bp, cp,
                                f"<= {ceil:.3f}", cp > ceil))
            rec = e.get("steady_recompiles")
            if rec is not None:
                rows.append(row(f"{name}.steady_recompiles",
                                be.get("steady_recompiles"), rec, "== 0",
                                rec > 0,
                                note="program invariance under "
                                     "mixed-cluster traffic"))
            sr = e.get("shed_rate")
            if mode == "socket" and sr is not None:
                # ABSOLUTE bound on the sub-knee open-loop point: a
                # frontend shedding comfortably below its own measured
                # capacity is misconfigured admission, regardless of
                # what the baseline did
                rows.append(row(f"{name}.shed_rate",
                                be.get("shed_rate"), sr, "<= 0.05",
                                sr > 0.05,
                                note="open-loop shed rate at 0.5x "
                                     "measured capacity"))
        if best_speedup is not None:
            bbest = [e.get("speedup_vs_unbatched") for e in bsv
                     if isinstance(e, dict)
                     and e.get("speedup_vs_unbatched") is not None]
            rows.append(row("serve.best_speedup_vs_unbatched",
                            max(bbest) if bbest else None, best_speedup,
                            ">= 3", best_speedup < 3.0,
                            note="absolute micro-batching floor vs "
                                 "this run's own unbatched row"))
    elif isinstance(bsv, list):
        skip("serve", "candidate lacks the serve axis")

    # model-quality axis (bench.py --quality; QUALITY artifacts): the
    # seeded drifting-traffic serve bench with live label joins and two
    # canaried merges (one good, one deliberately wrong). Gates are
    # mostly ABSOLUTE against this run's own rows — the acceptance bars,
    # immune to a baseline that itself regressed: streaming accuracy
    # within --tol-quality-acc of the offline oracle on the same stream,
    # the good merge canary-committed and the corrupted one rolled back,
    # zero rollbacks outside the deliberate corruption, shadow-on
    # throughput >= 0.95x shadow-off (the <5% duplicate-execute budget),
    # and zero steady-state recompiles (shadow forwards replay warm
    # signatures). p99/requests-per-s ride the usual relative tolerances
    # when the baseline carries the axis.
    cq, bq = candidate.get("quality"), baseline.get("quality")
    if isinstance(cq, dict):
        bqd = bq if isinstance(bq, dict) else {}
        gap = cq.get("live_oracle_gap")
        if gap is not None:
            rows.append(row("quality.live_oracle_gap",
                            bqd.get("live_oracle_gap"), gap,
                            f"<= {tol['quality_acc']:.4f}",
                            gap > tol["quality_acc"],
                            note="streaming estimate vs offline oracle "
                                 "on the same labeled stream"))
        gm = cq.get("good_merge_committed")
        if gm is not None:
            rows.append(row("quality.good_merge_committed",
                            bqd.get("good_merge_committed"), gm, "== 1",
                            gm != 1, note="clean merge must canary-commit"))
        bm = cq.get("bad_merge_rolled_back")
        if bm is not None:
            rows.append(row("quality.bad_merge_rolled_back",
                            bqd.get("bad_merge_rolled_back"), bm, "== 1",
                            bm != 1,
                            note="corrupted merge must canary-rollback"))
        cr = cq.get("clean_canary_rollbacks")
        if cr is not None:
            rows.append(row("quality.clean_canary_rollbacks",
                            bqd.get("clean_canary_rollbacks"), cr, "== 0",
                            cr > 0,
                            note="no false rollbacks on clean traffic"))
        sr = cq.get("shadow_overhead_ratio")
        if sr is not None:
            rows.append(row("quality.shadow_overhead_ratio",
                            bqd.get("shadow_overhead_ratio"), sr,
                            ">= 0.95", sr < 0.95,
                            note="shadow-on rps vs own shadow-off rps"))
        rec = cq.get("steady_recompiles")
        if rec is not None:
            rows.append(row("quality.steady_recompiles",
                            bqd.get("steady_recompiles"), rec, "== 0",
                            rec > 0,
                            note="shadow forwards replay warm signatures"))
        bp, cp = bqd.get("p99_ms"), cq.get("p99_ms")
        if bp and cp:
            ceil = bp * (1.0 + tol["p99"])
            rows.append(row("quality.p99_ms", bp, cp,
                            f"<= {ceil:.3f}", cp > ceil))
        bv, cv = bqd.get("requests_per_s"), cq.get("requests_per_s")
        if bv and cv:
            floor = bv * (1.0 - tol["rounds"])
            rows.append(row("quality.requests_per_s", bv, cv,
                            f">= {floor:.1f}", cv < floor))
    elif isinstance(bq, dict):
        skip("quality", "candidate lacks the quality axis")

    # secure-aggregation axis (bench.py --secure; SECAGG artifacts): rows
    # keyed secure[{mode}:{point}]. bytes_per_round under the bytes
    # ceiling (shamir is a real TCP wire measurement, turbo static frame
    # accounting — both deterministic for a fixed cohort/dim), engine
    # wall/round under the secure_wall ceiling (host-side numpy on shared
    # CI, hence the generous tolerance), and on the train rows an
    # ABSOLUTE zero gate on steady-state recompiles — the share protocol
    # runs on the host and must never mint a new XLA signature on the
    # otherwise-unchanged train program.
    cs, bs = candidate.get("secure"), baseline.get("secure")
    if isinstance(cs, list) and isinstance(bs, list):
        def _mp(e):
            return (e.get("mode"), e.get("point"))

        by_mp = {_mp(e): e for e in bs if isinstance(e, dict)}
        for e in cs:
            if not isinstance(e, dict):
                continue
            mode, point = _mp(e)
            name = f"secure[{mode}:{point}]"
            be = by_mp.get((mode, point))
            if be is None:
                skip(name, "mode/point missing in baseline")
                continue
            if point == "train":
                rec = e.get("steady_recompiles")
                if rec is not None:
                    rows.append(row(f"{name}.steady_recompiles",
                                    be.get("steady_recompiles"), rec,
                                    "== 0", rec > 0,
                                    note="secure round mode is host-side"))
                bv, cv = be.get("rounds_per_sec"), e.get("rounds_per_sec")
                if bv and cv:
                    floor = bv * (1.0 - tol["rounds"])
                    rows.append(row(f"{name}.rounds_per_sec", bv, cv,
                                    f">= {floor:.1f}", cv < floor))
                continue
            bv, cv = be.get("bytes_per_round"), e.get("bytes_per_round")
            if bv and cv:
                ceil = bv * (1.0 + tol["bytes"])
                rows.append(row(f"{name}.bytes_per_round", bv, cv,
                                f"<= {ceil:.0f}", cv > ceil))
            bw, cw = (be.get("wall_s_secure_per_round"),
                      e.get("wall_s_secure_per_round"))
            if bw and cw:
                ceil = bw * (1.0 + tol["secure_wall"])
                rows.append(row(f"{name}.wall_s_secure_per_round", bw, cw,
                                f"<= {ceil:.4g}", cw > ceil,
                                note="engine overhead ceiling"))
    elif isinstance(bs, list):
        skip("secure", "candidate lacks the secure axis")
    return rows


def render(rows: list[dict[str, Any]]) -> str:
    def fmt(v):
        if v is None:
            return "-"
        if isinstance(v, float):
            return f"{v:.4g}"
        return str(v)

    head = f"{'metric':<16} {'baseline':>10} {'candidate':>10} " \
           f"{'delta':>8} {'limit':>12}  status"
    lines = [head, "-" * len(head)]
    for r in rows:
        delta = (f"{r['delta_pct']:+.1f}%" if "delta_pct" in r else "-")
        status = r["status"].upper() if r["status"] == "regress" \
            else r["status"]
        note = f"  ({r['note']})" if r.get("note") else ""
        lines.append(f"{r['metric']:<16} {fmt(r.get('baseline')):>10} "
                     f"{fmt(r.get('candidate')):>10} {delta:>8} "
                     f"{fmt(r.get('limit')):>12}  {status}{note}")
    n_reg = sum(1 for r in rows if r["status"] == "regress")
    lines.append("")
    lines.append(f"{'REGRESSION' if n_reg else 'OK'}: "
                 f"{n_reg} regressed, "
                 f"{sum(1 for r in rows if r['status'] == 'ok')} ok, "
                 f"{sum(1 for r in rows if r['status'] == 'skip')} skipped")
    return "\n".join(lines)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="feddrift_tpu regress",
        description="compare a bench.py artifact against a baseline; "
                    "exit 1 on regression")
    ap.add_argument("candidate", help="bench JSON to gate")
    ap.add_argument("--baseline", required=True,
                    help="bench JSON to compare against (raw output or a "
                         "committed BENCH_r0*.json)")
    ap.add_argument("--tol-rounds", type=float, default=DEFAULT_TOL["rounds"],
                    help="relative rounds/s drop tolerated (default %(default)s)")
    ap.add_argument("--tol-wall", type=float, default=DEFAULT_TOL["wall"],
                    help="relative wall_s growth tolerated (default %(default)s)")
    ap.add_argument("--tol-acc", type=float, default=DEFAULT_TOL["acc"],
                    help="absolute final_test_acc drop tolerated "
                         "(default %(default)s)")
    ap.add_argument("--tol-compiles", type=float,
                    default=DEFAULT_TOL["compiles"],
                    help="absolute extra steady-state compiles tolerated "
                         "(default %(default)s)")
    ap.add_argument("--tol-bytes", type=float, default=DEFAULT_TOL["bytes"],
                    help="relative wire bytes/round growth tolerated "
                         "(default %(default)s)")
    ap.add_argument("--tol-host-overhead", type=float,
                    default=DEFAULT_TOL["host_overhead"],
                    help="absolute host_overhead_frac growth tolerated "
                         "(default %(default)s)")
    ap.add_argument("--tol-p99", type=float, default=DEFAULT_TOL["p99"],
                    help="relative round_wall_p99_s growth tolerated "
                         "(default %(default)s)")
    ap.add_argument("--tol-precision-acc", type=float,
                    default=DEFAULT_TOL["precision_acc"],
                    help="absolute accuracy drop tolerated for a reduced-"
                         "precision row vs its own run's f32 row "
                         "(default %(default)s)")
    ap.add_argument("--tol-quality-acc", type=float,
                    default=DEFAULT_TOL["quality_acc"],
                    help="absolute gap tolerated between the streaming "
                         "live-accuracy estimate and the offline oracle "
                         "on the same labeled stream (default %(default)s)")
    ap.add_argument("--tol-secure-wall", type=float,
                    default=DEFAULT_TOL["secure_wall"],
                    help="relative secure-agg engine wall/round growth "
                         "tolerated (default %(default)s)")
    ap.add_argument("--tol-hostscale-exp", type=float,
                    default=DEFAULT_TOL["hostscale_exp"],
                    help="absolute growth tolerated in a fitted host-plane "
                         "scaling exponent (default %(default)s)")
    ap.add_argument("--json", action="store_true", help="machine-readable")
    args = ap.parse_args(argv)

    try:
        candidate = load_bench(args.candidate)
        baseline = load_bench(args.baseline)
    except (OSError, ValueError) as e:
        print(f"regress: {e}", file=sys.stderr)
        return 2

    rows = compare(candidate, baseline,
                   tol={"rounds": args.tol_rounds, "wall": args.tol_wall,
                        "acc": args.tol_acc, "compiles": args.tol_compiles,
                        "bytes": args.tol_bytes,
                        "host_overhead": args.tol_host_overhead,
                        "p99": args.tol_p99,
                        "precision_acc": args.tol_precision_acc,
                        "quality_acc": args.tol_quality_acc,
                        "secure_wall": args.tol_secure_wall,
                        "hostscale_exp": args.tol_hostscale_exp})
    regressed = any(r["status"] == "regress" for r in rows)
    if args.json:
        print(json.dumps({"regressed": regressed, "rows": rows,
                          "candidate": args.candidate,
                          "baseline": args.baseline}, indent=2))
    else:
        print(f"candidate: {args.candidate}\nbaseline:  {args.baseline}\n")
        print(render(rows))
    return 1 if regressed else 0


if __name__ == "__main__":
    sys.exit(main())
