"""Rule-based run-health monitor: declarative rules over the event
stream → ``alert_raised`` events + ``<run_dir>/alerts.jsonl``.

The telemetry layers record *what* happened; this module watches the
stream for the patterns that, in practice, mean a run needs a human:
cluster-count churn (the pool thrashing spawn/merge instead of
converging), oracle-ARI collapse (clustering quality falling off a
cliff after having recovered the concepts), divergence rollbacks
co-occurring with an active Byzantine schedule (a defense being
overwhelmed rather than random numeric noise), a stalled
generalization gap, and client outages.

Two evaluation modes, same rules:

- **live** — the runner attaches an :class:`AlertMonitor` as an event-bus
  tap (``EventBus.add_tap``); every emitted event is observed on the
  emitting thread, fired alerts are appended to ``alerts.jsonl``
  (open-append-close per alert: alerts are rare and the file survives a
  crash mid-run) and re-emitted as ``alert_raised`` events so the
  ordinary event stream carries them too. Gated by ``cfg.alerts``.
- **offline** — ``report <run_dir> --follow`` feeds the tail of
  ``events.jsonl`` through a detached monitor (no file, no bus), so runs
  recorded without live alerting still get scored.

A rule is data: a name, severity, the event kinds that can trigger its
evaluation, and a check function over the monitor's bounded recent-event
windows. Checks run only on their trigger kinds and keep O(window)
state, so the live tap stays off the hot path's critical section (taps
run after the bus lock is released).
"""

from __future__ import annotations

import json
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

# ----------------------------------------------------------------------
# rule definition
@dataclass
class Rule:
    """One declarative health rule.

    ``check(monitor, event)`` runs when an event of a kind in ``kinds``
    is observed and the rule is off cooldown; returning a payload dict
    raises the alert (the dict becomes the alert's evidence fields),
    returning None stays quiet."""
    name: str
    severity: str                      # "warn" | "crit"
    description: str
    kinds: tuple
    check: Callable[["AlertMonitor", dict], Optional[dict]]
    cooldown: int = 1                  # min iterations between firings


# The structural cluster decisions counted by the churn rule.
CHURN_KINDS = ("cluster_create", "cluster_merge", "cluster_delete",
               "cluster_split")


def default_rules(churn_threshold: int = 4, churn_window: int = 3,
                  ari_arm: float = 0.5, ari_drop: float = 0.3,
                  byz_round_window: int = 16,
                  stall_evals: int = 4, stall_gap: float = 0.15,
                  stall_eps: float = 0.01,
                  quorum_miss_threshold: int = 2,
                  quorum_miss_window: int = 3) -> list[Rule]:
    """The built-in rule set, thresholds exposed for cfg overrides."""

    def check_churn(mon: "AlertMonitor", rec: dict) -> Optional[dict]:
        lo = mon.iteration - churn_window
        n = sum(1 for k in CHURN_KINDS for e in mon.recent[k]
                if (e.get("iteration") or 0) > lo)
        if n > churn_threshold:
            return {"message": f"{n} cluster create/merge/delete/split "
                               f"events in the last {churn_window} "
                               f"iterations (> {churn_threshold}) — the "
                               "pool is thrashing instead of converging",
                    "count": n, "window": churn_window,
                    "threshold": churn_threshold}
        return None

    def check_ari_collapse(mon: "AlertMonitor", rec: dict) -> Optional[dict]:
        ari = rec.get("oracle_ari")
        if ari is None:
            return None
        best = mon.state.get("best_ari", 0.0)
        mon.state["best_ari"] = max(best, ari)
        if best >= ari_arm and ari <= best - ari_drop:
            return {"message": f"oracle ARI collapsed to {ari:.3f} from a "
                               f"best of {best:.3f} — clustering quality "
                               "lost the recovered concepts",
                    "ari": ari, "best_ari": best}
        return None

    def check_div_byz(mon: "AlertMonitor", rec: dict) -> Optional[dict]:
        r = rec.get("round")
        byz = [e for e in mon.recent["byzantine_injected"]
               if r is None or e.get("round") is None
               or abs(e["round"] - r) <= byz_round_window]
        if byz:
            modes = sorted({e.get("mode", "?") for e in byz})
            return {"message": "divergence rollback while a Byzantine "
                               f"schedule is active (modes {modes}) — the "
                               "configured aggregation may be overwhelmed",
                    "reason": rec.get("reason"), "byz_modes": modes}
        return None

    def check_eval_stall(mon: "AlertMonitor", rec: dict) -> Optional[dict]:
        evs = list(mon.recent["eval"])[-stall_evals:]
        if len(evs) < stall_evals:
            return None
        gaps, accs = [], []
        for e in evs:
            tr, te = e.get("train_acc"), e.get("test_acc")
            if tr is None or te is None:
                return None
            gaps.append(tr - te)
            accs.append(te)
        if min(gaps) > stall_gap and max(accs) - min(accs) < stall_eps:
            return {"message": f"generalization gap stalled: train-test gap "
                               f"> {stall_gap} for the last {stall_evals} "
                               f"evals with Test/Acc flat at "
                               f"{accs[-1]:.3f} — likely an unadapted "
                               "concept drift",
                    "gap": round(min(gaps), 4),
                    "test_acc": round(accs[-1], 4)}
        return None

    def check_outage(mon: "AlertMonitor", rec: dict) -> Optional[dict]:
        if rec["kind"] == "client_killed":
            return {"message": f"client {rec.get('client')} permanently "
                               "killed — cluster decisions now run on a "
                               "reduced population",
                    "clients": [rec.get("client")]}
        clients = rec.get("clients") or []
        if clients:
            return {"message": f"failure detector suspects clients "
                               f"{clients} — their accuracy evidence is "
                               "stale",
                    "clients": clients}
        return None

    def check_quorum_miss(mon: "AlertMonitor", rec: dict) -> Optional[dict]:
        lo = mon.iteration - quorum_miss_window
        n = sum(1 for e in mon.recent["round_degraded"]
                if (e.get("iteration") or 0) > lo)
        if n >= quorum_miss_threshold:
            return {"message": f"{n} quorum-missed (degraded) rounds in the "
                               f"last {quorum_miss_window} iterations — the "
                               "cohort repeatedly cannot reach quorum; raise "
                               "cohort_overprovision / round_deadline or "
                               "lower quorum_frac",
                    "count": n, "window": quorum_miss_window,
                    "threshold": quorum_miss_threshold,
                    "quorum": rec.get("quorum"),
                    "on_time": rec.get("on_time")}
        return None

    return [
        Rule("cluster_churn", "warn",
             "structural cluster events per window above threshold",
             ("cluster_state",), check_churn, cooldown=1),
        Rule("ari_collapse", "crit",
             "oracle ARI dropped sharply from its best",
             ("cluster_assign",), check_ari_collapse, cooldown=1),
        Rule("divergence_byzantine", "crit",
             "divergence rollback co-occurring with an active adversary",
             ("divergence_detected",), check_div_byz, cooldown=1),
        Rule("eval_gap_stall", "warn",
             "train-test gap stalled across consecutive evals",
             ("eval",), check_eval_stall, cooldown=5),
        Rule("client_outage", "warn",
             "permanent kill or failure-suspected clients",
             ("client_killed", "failure_suspected"), check_outage,
             cooldown=1),
        Rule("quorum_miss", "crit",
             "repeated quorum-missed (degraded) rounds within the window",
             ("round_degraded",), check_quorum_miss, cooldown=2),
    ]


# ----------------------------------------------------------------------
# the monitor
RECENT_WINDOW = 512


class AlertMonitor:
    """Evaluates rules over observed events; thread-safe (the live tap
    runs on whatever thread emitted — runner main, broker background)."""

    def __init__(self, rules: Optional[list[Rule]] = None,
                 path: Optional[str] = None, bus=None,
                 max_bytes: int = 0) -> None:
        import collections
        self.rules = rules if rules is not None else default_rules()
        self.path = path
        self.max_bytes = int(max_bytes)   # alerts.jsonl size cap (0 = off)
        self.bus = bus
        self.state: dict[str, Any] = {}       # rule scratch (best_ari, ...)
        self.alerts: list[dict] = []          # every raised record
        self.iteration = 0
        # re-entrant: _raise (lock held) emits alert_raised through the
        # bus, and if that write trips the size-cap rotation the bus taps
        # this same thread with the obs_rotated record -> observe again
        self._lock = threading.RLock()
        self._last_fired: dict[str, int] = {}
        tracked = set(CHURN_KINDS) | {"byzantine_injected"}
        for r in self.rules:
            tracked.update(r.kinds)
        self.recent: dict[str, Any] = {
            k: collections.deque(maxlen=RECENT_WINDOW) for k in tracked}
        self._by_kind: dict[str, list[Rule]] = {}
        for r in self.rules:
            for k in r.kinds:
                self._by_kind.setdefault(k, []).append(r)

    # -- wiring ---------------------------------------------------------
    def attach(self, bus) -> "AlertMonitor":
        """Register as a live tap on an EventBus; fired alerts are
        re-emitted through that bus as alert_raised events."""
        self.bus = bus
        bus.add_tap(self.observe)
        return self

    # -- evaluation -----------------------------------------------------
    def observe(self, rec: dict) -> None:
        """Feed one event record (live tap or offline replay)."""
        kind = rec.get("kind")
        if kind is None or kind == "alert_raised":
            return                      # never recurse on our own output
        with self._lock:
            it = rec.get("iteration")
            if isinstance(it, int) and it > self.iteration:
                self.iteration = it
            if kind in self.recent:
                self.recent[kind].append(rec)
            for rule in self._by_kind.get(kind, ()):
                last = self._last_fired.get(rule.name)
                if last is not None and \
                        self.iteration - last < rule.cooldown:
                    continue
                payload = rule.check(self, rec)
                if payload:
                    self._raise(rule, payload)

    def _raise(self, rule: Rule, payload: dict) -> None:
        # lock already held; bus emission happens with OUR lock held but
        # the bus lock free (taps run unlocked). observe() drops
        # alert_raised before taking the lock, and the one genuine
        # re-entry — a size-cap rotation tripped by the alert_raised
        # write taps us back with obs_rotated — is safe on the RLock.
        self._last_fired[rule.name] = self.iteration
        fields = {"rule": rule.name, "severity": rule.severity, **payload}
        if self.bus is not None:
            rec = self.bus.emit("alert_raised", **fields)
        else:
            rec = {"_ts": time.time(), "kind": "alert_raised",
                   "iteration": self.iteration, **fields}
        self.alerts.append(rec)
        try:
            from feddrift_tpu.obs.instruments import registry
            registry().counter("alerts_raised", rule=rule.name).inc()
        except Exception:
            pass
        if self.path:
            append_alert(self.path, rec, max_bytes=self.max_bytes)


# per-path rotation generation counters for the append_alert size cap
# (the sink is open-append-close, so generation state lives here, not on
# a file handle like the events/spans sinks)
_rotations: dict[str, int] = {}
_rot_lock = threading.Lock()


def append_alert(path: str, rec: dict, max_bytes: int = 0) -> None:
    """Append one record to an alerts.jsonl sink (open-append-close, so
    concurrent writers — the alert monitor and the SLO engine in
    obs/live.py — interleave whole lines, never partial ones).

    ``max_bytes`` > 0 applies the same size-cap rotation events/spans
    get (``cfg.obs_max_file_mb``): when the write pushes the file past
    the cap it rotates to ``<path>.1`` (one generation kept) with a loud
    ``obs_rotated`` event — a long-running service with a flapping rule
    must not grow alerts.jsonl unboundedly."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    rotated_bytes = generation = 0
    with _rot_lock:
        with open(path, "a") as f:
            f.write(json.dumps(rec, default=_json_default) + "\n")
            if max_bytes and f.tell() >= max_bytes:
                rotated_bytes = f.tell()
        if rotated_bytes:
            try:
                os.replace(path, path + ".1")
            except OSError:
                rotated_bytes = 0
            else:
                generation = _rotations[path] = _rotations.get(path, 0) + 1
    if rotated_bytes:
        from feddrift_tpu.obs import events as _events
        try:
            _events.emit("obs_rotated", file=os.path.basename(path),
                         rotated_bytes=rotated_bytes,
                         generation=generation)
        except Exception:   # noqa: BLE001 — observability stays passive
            pass


def _json_default(o):
    tolist = getattr(o, "tolist", None)
    return tolist() if tolist is not None else str(o)


def replay(events: list[dict],
           rules: Optional[list[Rule]] = None) -> list[dict]:
    """Offline evaluation: run the rules over a recorded event stream and
    return the alerts they raise (report --follow / post-hoc triage)."""
    mon = AlertMonitor(rules=rules)
    for e in events:
        mon.observe(e)
    return mon.alerts
