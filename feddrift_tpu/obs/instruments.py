"""Counters, gauges, histograms with a Prometheus-textfile exporter.

For quantities too hot to be one-event-per-occurrence (bytes on the comm
path, per-phase latencies, compile counts) the event bus is the wrong
tool; these instruments record in O(1) with a per-instrument lock and no
allocation on the hot path:

    reg = obs.registry()
    reg.counter("comm_bytes_out", transport="netbroker").inc(len(frame))
    reg.gauge("num_models").set(3)
    reg.histogram("phase_seconds", phase="train_round").observe(dt)

A time series is keyed by (name, sorted label pairs), Prometheus-style.
``Registry.snapshot()`` returns a plain-dict snapshot (hooked into
bench.py / scripts/scaling_bench.py so BENCH_*.json carry compile counts
and phase histograms); ``Registry.to_prometheus_text()`` renders the
node-exporter textfile-collector format and ``write_textfile(path)``
writes it atomically for a textfile collector to scrape.

Histograms use fixed cumulative buckets (Prometheus semantics: ``le``
upper bounds, +Inf implicit) — recording is two integer increments and a
float add, never sample retention, so overhead stays bounded regardless
of run length. The default bounds span 100 µs .. 100 s, wide enough for
both per-phase wall times and per-round latencies.
"""

from __future__ import annotations

import bisect
import os
import threading
from typing import Any

from feddrift_tpu.obs.quantiles import DEFAULT_QUANTILES, QuantileSketch

DEFAULT_BUCKETS = (1e-4, 1e-3, 1e-2, 0.1, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
                   100.0)


class Counter:
    """Monotonically increasing count."""

    __slots__ = ("_lock", "value")

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.value = 0.0

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError("counters only go up")
        with self._lock:
            self.value += n


class Gauge:
    """A value that goes up and down."""

    __slots__ = ("_lock", "value")

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.value = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            self.value = float(v)

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self.value += n

    def dec(self, n: float = 1.0) -> None:
        self.inc(-n)


class Histogram:
    """Fixed-bucket histogram (cumulative ``le`` semantics on export)."""

    __slots__ = ("_lock", "bounds", "bucket_counts", "count", "sum")

    def __init__(self, buckets: tuple = DEFAULT_BUCKETS) -> None:
        self._lock = threading.Lock()
        self.bounds = tuple(sorted(float(b) for b in buckets))
        self.bucket_counts = [0] * (len(self.bounds) + 1)   # last = +Inf
        self.count = 0
        self.sum = 0.0

    def observe(self, v: float) -> None:
        idx = bisect.bisect_left(self.bounds, v)
        with self._lock:
            self.bucket_counts[idx] += 1
            self.count += 1
            self.sum += v

    def snapshot(self) -> dict:
        with self._lock:
            return {"count": self.count, "sum": self.sum,
                    "buckets": {("+Inf" if i == len(self.bounds)
                                 else repr(self.bounds[i])): c
                                for i, c in enumerate(self.bucket_counts)
                                if c}}


def _series_key(name: str, labels: dict[str, str]) -> tuple:
    return (name, tuple(sorted(labels.items())))


def _escape_label_value(v: str) -> str:
    """Prometheus exposition-format escaping: backslash, double quote and
    newline must be escaped inside a label value (in that order — the
    backslash first, or it re-escapes the others)."""
    return str(v).replace("\\", "\\\\").replace('"', '\\"') \
                 .replace("\n", "\\n")


def _label_str(labels: tuple) -> str:
    if not labels:
        return ""
    return "{" + ",".join(f'{k}="{_escape_label_value(v)}"'
                          for k, v in labels) + "}"


class Registry:
    """Process-local instrument registry, one time series per
    (name, labels). Get-or-create accessors are idempotent and
    type-checked: asking for an existing name with a different instrument
    type is a programming error and raises."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._series: dict[tuple, Any] = {}

    def _get(self, cls, name: str, labels: dict[str, str], **kw):
        key = _series_key(name, labels)
        with self._lock:
            inst = self._series.get(key)
            if inst is None:
                inst = self._series[key] = cls(**kw)
            elif not isinstance(inst, cls):
                raise TypeError(
                    f"instrument {name}{labels} already registered as "
                    f"{type(inst).__name__}, not {cls.__name__}")
            return inst

    def counter(self, name: str, **labels: str) -> Counter:
        return self._get(Counter, name, labels)

    def gauge(self, name: str, **labels: str) -> Gauge:
        return self._get(Gauge, name, labels)

    def histogram(self, name: str, buckets: tuple = DEFAULT_BUCKETS,
                  **labels: str) -> Histogram:
        return self._get(Histogram, name, labels, buckets=buckets)

    def quantile_sketch(self, name: str,
                        quantiles: tuple = DEFAULT_QUANTILES,
                        **labels: str) -> QuantileSketch:
        """Streaming P² percentile sketch (obs/quantiles.py) — exports
        summary-style ``name{quantile="0.99"}`` lines. A sketch and a
        histogram cannot share a name (Prometheus types collide); the
        convention is a ``_q`` suffix on the sketch."""
        return self._get(QuantileSketch, name, labels, quantiles=quantiles)

    def reset(self) -> None:
        """Drop every series (benchmarks reset between measurements so
        snapshots are per-measurement, not cumulative)."""
        with self._lock:
            self._series.clear()

    # -- export ---------------------------------------------------------
    def snapshot(self) -> dict:
        """{"name{label=...}": value-or-histogram-dict}, JSON-ready.
        Every read takes the instrument lock so a concurrent observe can
        never yield a torn (count vs. sum vs. buckets) view."""
        with self._lock:
            items = sorted(self._series.items())
        out: dict[str, Any] = {}
        for (name, labels), inst in items:
            key = name + _label_str(labels)
            if isinstance(inst, (Histogram, QuantileSketch)):
                out[key] = inst.snapshot()
            else:
                with inst._lock:
                    out[key] = inst.value
        return out

    def to_prometheus_text(self) -> str:
        """node-exporter textfile-collector format (untyped TYPE lines are
        omitted for gauges/counters whose kind is in the name; histograms
        render the standard _bucket/_sum/_count triplet; quantile
        sketches render summary-style quantile/_sum/_count lines).
        Histogram state is copied under the instrument lock first — the
        cumulative buckets, _sum and _count of one series always describe
        the same set of observations."""
        with self._lock:
            items = sorted(self._series.items())
        lines: list[str] = []
        typed: set[str] = set()
        for (name, labels), inst in items:
            if isinstance(inst, Histogram):
                with inst._lock:
                    bucket_counts = list(inst.bucket_counts)
                    hsum, hcount = inst.sum, inst.count
                if name not in typed:
                    lines.append(f"# TYPE {name} histogram")
                    typed.add(name)
                cum = 0
                for i, bound in enumerate(inst.bounds):
                    cum += bucket_counts[i]
                    ls = _label_str(labels + (("le", repr(bound)),))
                    lines.append(f"{name}_bucket{ls} {cum}")
                cum += bucket_counts[-1]
                ls = _label_str(labels + (("le", "+Inf"),))
                lines.append(f"{name}_bucket{ls} {cum}")
                lines.append(f"{name}_sum{_label_str(labels)} {hsum}")
                lines.append(f"{name}_count{_label_str(labels)} {hcount}")
            elif isinstance(inst, QuantileSketch):
                snap = inst.snapshot()
                if name not in typed:
                    lines.append(f"# TYPE {name} summary")
                    typed.add(name)
                for qs, qv in snap["quantiles"].items():
                    if qv is None:
                        continue
                    ls = _label_str(labels + (("quantile", qs),))
                    lines.append(f"{name}{ls} {qv}")
                lines.append(f"{name}_sum{_label_str(labels)} {snap['sum']}")
                lines.append(
                    f"{name}_count{_label_str(labels)} {snap['count']}")
            else:
                kind = "counter" if isinstance(inst, Counter) else "gauge"
                if name not in typed:
                    lines.append(f"# TYPE {name} {kind}")
                    typed.add(name)
                with inst._lock:
                    val = inst.value
                lines.append(f"{name}{_label_str(labels)} {val}")
        return "\n".join(lines) + ("\n" if lines else "")

    def write_textfile(self, path: str) -> None:
        """Atomic write (tmp + rename) — a textfile collector must never
        read a half-written snapshot."""
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write(self.to_prometheus_text())
        os.replace(tmp, path)


_registry = Registry()


def registry() -> Registry:
    return _registry
