"""Round critical-path attribution from a run's recorded streams.

    python -m feddrift_tpu critical_path <run_dir>

Replays ``spans.jsonl`` + ``events.jsonl`` (rotated ``.1`` generations
included) into a per-iteration segment table: for every ``iteration``
span the matching ``round_breakdown`` event contributes the measured
segments (cohort_prep / h2d / dispatch / device_compute / writeback /
drift_decision / eval and the residual dispatch_gap), the dominant
segment is named per iteration and overall, and iterations whose wall
time stretches past the run median are attributed to the concrete cause
recorded in the event stream — the straggler clients that missed the
deadline (``straggler_masked``) or the edge that failed
(``edge_failed``) during that iteration. Pure host-side: no jax, no
backend, safe to run while the run is still writing.

The segment sums are checked against the iteration span's wall clock
(``coverage`` column); by construction the residual dispatch_gap closes
the budget, so a coverage far from 1.0 means the two streams disagree
(clock skew, truncated file) and the row is flagged.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any

SEGMENT_ORDER = ("cohort_prep", "h2d", "dispatch", "device_compute",
                 "writeback", "drift_decision", "eval", "dispatch_gap")


def _load_jsonl(path: str) -> list[dict]:
    """Load a JSONL stream, oldest rotation generation first; a missing
    file is an empty stream and a truncated tail line is dropped."""
    out: list[dict] = []
    for p in (path + ".1", path):
        if not os.path.exists(p):
            continue
        with open(p) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    out.append(json.loads(line))
                except json.JSONDecodeError:
                    continue   # mid-write tail of a live run
    return out


def load_run(run_dir: str) -> tuple[list[dict], list[dict]]:
    spans = _load_jsonl(os.path.join(run_dir, "spans.jsonl"))
    events = _load_jsonl(os.path.join(run_dir, "events.jsonl"))
    if not spans and not events:
        raise FileNotFoundError(
            f"{run_dir}: neither spans.jsonl nor events.jsonl found")
    return spans, events


def _median(vals: list[float]) -> float:
    s = sorted(vals)
    n = len(s)
    if not n:
        return 0.0
    return s[n // 2] if n % 2 else 0.5 * (s[n // 2 - 1] + s[n // 2])


def analyze(run_dir: str) -> dict[str, Any]:
    """Per-iteration segment table + overall dominant-segment verdict."""
    spans, events = load_run(run_dir)
    iter_walls: dict[int, float] = {}
    for s in spans:
        if s.get("name") == "iteration":
            it = s.get("args", {}).get("iteration")
            if it is not None:
                # spans.jsonl stores ts/dur in microseconds (trace-event
                # convention, obs/spans.py)
                iter_walls[int(it)] = float(s.get("dur", 0.0)) / 1e6

    breakdowns: dict[int, dict] = {}
    culprits: dict[int, list[dict]] = {}
    for ev in events:
        it = ev.get("iteration")
        if it is None:
            continue
        it = int(it)
        kind = ev.get("kind")
        if kind == "round_breakdown":
            breakdowns[it] = ev
        elif kind == "straggler_masked":
            culprits.setdefault(it, []).append(
                {"cause": "straggler", "round": ev.get("part_round"),
                 "clients": ev.get("clients"),
                 "deadline_s": ev.get("deadline")})
        elif kind == "edge_failed":
            culprits.setdefault(it, []).append(
                {"cause": "edge_failed", "round": ev.get("fault_round"),
                 "edges": ev.get("edges"), "reason": ev.get("reason")})

    iterations: list[dict] = []
    totals: dict[str, float] = {}
    walls: list[float] = []
    for it in sorted(set(iter_walls) | set(breakdowns)):
        bd = breakdowns.get(it)
        wall = iter_walls.get(
            it, float(bd.get("wall_s", 0.0)) if bd else 0.0)
        segs = dict(bd.get("segments", {})) if bd else {}
        seg_sum = sum(segs.values())
        for k, v in segs.items():
            totals[k] = totals.get(k, 0.0) + v
        dominant = max(segs, key=segs.get) if segs else None
        iterations.append({
            "iteration": it,
            "wall_s": round(wall, 6),
            "segments": segs,
            "dominant": dominant,
            "coverage": round(seg_sum / wall, 4) if wall else None,
            "host_overhead_frac": bd.get("host_overhead_frac") if bd else None,
            "profiled_rounds": bd.get("profiled_rounds") if bd else None,
            "culprits": culprits.get(it, []),
        })
        if wall:
            walls.append(wall)

    # attribution: an iteration is "extended" when its wall runs past the
    # run median — name the recorded fault that stretched it, if any
    med = _median(walls)
    for row in iterations:
        row["extended"] = bool(med and row["wall_s"] > 1.25 * med)
        if row["extended"] and row["culprits"]:
            c = row["culprits"][0]
            if c["cause"] == "straggler":
                row["attribution"] = (
                    f"straggler client(s) {c.get('clients')} missed the "
                    f"{c.get('deadline_s')}s deadline in round "
                    f"{c.get('round')}")
            else:
                row["attribution"] = (
                    f"edge(s) {c.get('edges')} failed "
                    f"({c.get('reason')}) in round {c.get('round')}")
        elif row["extended"]:
            row["attribution"] = "no fault recorded (host-side variance)"

    overall_dominant = max(totals, key=totals.get) if totals else None
    hofs = [r["host_overhead_frac"] for r in iterations
            if r["host_overhead_frac"] is not None]
    return {
        "run_dir": run_dir,
        "iterations": iterations,
        "totals": {k: round(v, 6) for k, v in sorted(totals.items())},
        "dominant_segment": overall_dominant,
        "median_wall_s": round(med, 6),
        "host_overhead_frac_mean": (round(sum(hofs) / len(hofs), 6)
                                    if hofs else None),
    }


def render(result: dict[str, Any]) -> str:
    segs_present = [s for s in SEGMENT_ORDER if s in result["totals"]]
    segs_present += sorted(set(result["totals"]) - set(SEGMENT_ORDER))
    head = "iter " + " ".join(f"{s[:12]:>12}" for s in segs_present) \
        + f" {'wall':>9} {'cover':>6}  dominant"
    lines = [head, "-" * len(head)]
    for row in result["iterations"]:
        cells = " ".join(f"{row['segments'].get(s, 0.0):>12.4f}"
                         for s in segs_present)
        cover = (f"{row['coverage']:.2f}" if row["coverage"] is not None
                 else "-")
        lines.append(f"{row['iteration']:<4} {cells} {row['wall_s']:>9.3f} "
                     f"{cover:>6}  {row['dominant'] or '-'}")
        if row.get("attribution"):
            lines.append(f"     ^ extended iteration: {row['attribution']}")
    lines.append("")
    if result["dominant_segment"]:
        tot = result["totals"]
        dom = result["dominant_segment"]
        lines.append(
            f"critical path: {dom} dominates "
            f"({tot[dom]:.3f}s of {sum(tot.values()):.3f}s measured)")
    if result["host_overhead_frac_mean"] is not None:
        lines.append("host_overhead_frac (mean): "
                     f"{result['host_overhead_frac_mean']:.4f}")
    return "\n".join(lines)


def render_flame(run_dir: str, result: dict[str, Any],
                 top: int = 10) -> str:
    """The host-side view of the dominant segment: top-N folded stacks
    from the run's sampling profiler (``hostprof.folded``, written when
    ``cfg.hostprof_hz > 0``). A segment table says WHICH phase dominates;
    the flame rows say WHAT the host was executing during it."""
    path = os.path.join(run_dir, "hostprof.folded")
    if not os.path.exists(path):
        return ("no hostprof data: rerun with --hostprof_hz > 0 to sample "
                "host stacks (writes hostprof.folded next to spans.jsonl)")
    rows = []
    with open(path) as f:
        for line in f:
            stack, _, count = line.rstrip("\n").rpartition(" ")
            if stack and count.isdigit():
                rows.append((int(count), stack))
    if not rows:
        return "hostprof.folded is empty (profiler sampled no stacks)"
    rows.sort(key=lambda r: (-r[0], r[1]))
    total = sum(c for c, _ in rows)
    dom = result.get("dominant_segment") or "-"
    lines = [f"host stacks while '{dom}' dominated the critical path "
             f"({total} samples, top {min(top, len(rows))} of {len(rows)} "
             f"stacks):"]
    for count, stack in rows[:top]:
        # leaf-first: the sampled frame, then its callers
        frames = stack.split(";")
        lines.append(f"{count:>6} ({100.0 * count / total:5.1f}%)  "
                     f"{' <- '.join(reversed(frames[-4:]))}")
    return "\n".join(lines)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="feddrift_tpu critical_path",
        description="per-round critical-path breakdown + straggler/edge "
                    "attribution from a run dir's spans/events streams")
    ap.add_argument("run_dir")
    ap.add_argument("--json", action="store_true", help="machine-readable")
    ap.add_argument("--flame", action="store_true",
                    help="also print the top folded host stacks from the "
                         "run's sampling profiler (hostprof.folded)")
    ap.add_argument("--flame-top", type=int, default=10, metavar="N",
                    help="folded stacks to print with --flame (default 10)")
    args = ap.parse_args(argv)
    try:
        result = analyze(args.run_dir)
    except (OSError, FileNotFoundError) as e:
        print(f"critical_path: {e}", file=sys.stderr)
        return 2
    if not result["iterations"]:
        print(f"critical_path: {args.run_dir}: no iteration records",
              file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps(result, indent=2))
    else:
        print(render(result))
    if args.flame:
        print()
        print(render_flame(args.run_dir, result, top=args.flame_top))
    return 0


if __name__ == "__main__":
    sys.exit(main())
