"""XLA cost/memory accounting: what each compiled program costs the chip.

Every bench artifact before this module reported ``"mfu_estimate": null``:
the analytic FLOP rules could guess at compute, but nothing observed what
XLA actually compiled. This module closes that gap with three pieces:

**Program cost capture.** ``capture()`` runs at jit-compile time (hooked
from ``TrainStep._note_signature`` on every *first* argument signature):
it re-lowers the jitted program with the call's arguments and harvests
XLA's own accounting — ``cost_analysis()`` FLOPs / bytes accessed, and
(at the ``"compiled"`` level) ``memory_analysis()`` argument / output /
temp HBM sizes. Each capture emits one ``program_cost`` event and
refreshes the ``program_flops{fn=...}`` / ``program_bytes_accessed`` /
``program_peak_hbm_bytes`` gauges plus the cross-program
``hbm_peak_bytes`` high-water gauge. Levels (``cfg.cost_model``):

    off       no capture
    lowered   trace + lower only; FLOPs and bytes accessed (cheap —
              no second XLA compile; the default for runs)
    compiled  additionally compile the lowered module and read
              ``memory_analysis()`` — exact static HBM accounting, at
              the price of one extra XLA compile per program (bench.py
              uses this; the persistent compile cache halves the hit)

**Live HBM watermarks.** ``record_hbm_watermark()`` reads
``device.memory_stats()`` (``bytes_in_use`` / ``peak_bytes_in_use``),
emits an ``hbm_watermark`` event and folds the live peak into the
``hbm_peak_bytes`` gauge. CPU backends expose no memory stats: the call
returns ``None`` and emits nothing — graceful, never an error.

**Peaks + roofline.** ``peak_flops()`` / ``peak_bytes_per_s()`` give the
denominator MFU needs: a datasheet table for TPUs, and a *measured*
matmul / memory-stream microbenchmark for CPU hosts (an invented CPU
constant would make MFU meaningless; a measured one makes it "fraction
of what this silicon demonstrably does"). ``roofline()`` combines
achieved FLOP/s and bytes/s against those peaks and names the binding
resource. bench.py and scripts/roofline_report.py both source their
numbers here — one cost model, no per-script forks.
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import asdict, dataclass
from typing import Any

from feddrift_tpu.obs import events, instruments

log = logging.getLogger("feddrift_tpu")

CAPTURE_LEVELS = ("off", "lowered", "compiled")

# Datasheet peaks per chip. TPU v5 lite (v5e): ~197 TFLOP/s bf16,
# ~98 TFLOP/s f32, ~819 GB/s HBM BW per chip. (Moved here from bench.py so
# bench and scripts/roofline_report.py read one table.)
PEAK_FLOPS = {"tpu": {"bfloat16": 197e12, "float32": 98e12}}
PEAK_BYTES_PER_S = {"tpu": 8.19e11}


@dataclass
class ProgramCost:
    """XLA's accounting of ONE compiled program (one jit entry point)."""

    fn: str
    level: str                          # "lowered" | "compiled"
    flops: float | None = None          # per execution of the program
    bytes_accessed: float | None = None
    # Pre-optimization accounting of the same program: buffers counted at
    # the widths the program DECLARES. Backend optimizers may promote
    # narrow dtypes (XLA:CPU emulates bf16 matmuls/convs in f32, adding
    # convert traffic), so the optimized-HLO `bytes_accessed` above can
    # overstate a bf16 program's portable cost; this field is the
    # backend-independent dtype-economics signal the precision axis gates.
    lowered_bytes_accessed: float | None = None
    argument_bytes: int | None = None   # memory_analysis (compiled only)
    output_bytes: int | None = None
    temp_bytes: int | None = None
    generated_code_bytes: int | None = None
    peak_hbm_bytes: int | None = None   # see _peak_from_memory_analysis

    def to_event_fields(self) -> dict[str, Any]:
        return {k: v for k, v in asdict(self).items() if v is not None}


# ----------------------------------------------------------------------
# Process-local store of captured program costs, keyed by jit entry-point
# name — the same names the jit_compile events carry.
_costs: dict[str, ProgramCost] = {}
_lock = threading.Lock()


def costs() -> dict[str, ProgramCost]:
    """Snapshot of every captured program cost (by entry-point name)."""
    with _lock:
        return dict(_costs)


def get(fn: str) -> ProgramCost | None:
    with _lock:
        return _costs.get(fn)


def clear() -> None:
    with _lock:
        _costs.clear()


def _cost_dict(obj) -> dict | None:
    """cost_analysis() returns a dict, or [dict] on older jax."""
    try:
        cost = obj.cost_analysis()
    except Exception:
        return None
    if isinstance(cost, list):
        cost = cost[0] if cost else None
    return cost if isinstance(cost, dict) else None


def _peak_from_memory_analysis(mem) -> int | None:
    """Static peak-HBM estimate for one program.

    XLA reports a true ``peak_memory_in_bytes`` on some backends; where it
    is None (CPU) the sum argument + output + temp − aliased is the
    buffer-assignment upper bound: everything the executable touches that
    must be resident at once, donations already netted out via alias.
    """
    peak = getattr(mem, "peak_memory_in_bytes", None)
    if peak:
        return int(peak)
    total = 0
    for attr in ("argument_size_in_bytes", "output_size_in_bytes",
                 "temp_size_in_bytes"):
        total += int(getattr(mem, attr, 0) or 0)
    total -= int(getattr(mem, "alias_size_in_bytes", 0) or 0)
    return total if total > 0 else None


def _set_gauges(pc: ProgramCost) -> None:
    reg = instruments.registry()
    if pc.flops is not None:
        reg.gauge("program_flops", fn=pc.fn).set(pc.flops)
    if pc.bytes_accessed is not None:
        reg.gauge("program_bytes_accessed", fn=pc.fn).set(pc.bytes_accessed)
    if pc.peak_hbm_bytes is not None:
        reg.gauge("program_peak_hbm_bytes", fn=pc.fn).set(pc.peak_hbm_bytes)
        peak = hbm_peak_bytes()
        if peak is not None:
            reg.gauge("hbm_peak_bytes").set(peak)


def refresh_gauges() -> None:
    """Re-populate the program-cost gauges from the store.

    bench.py resets the instrument registry after warm-up so its snapshot
    covers exactly the timed steady state — but the programs compiled (and
    were captured) *during* warm-up. This puts their gauges back without
    re-capturing anything.
    """
    for pc in costs().values():
        _set_gauges(pc)


def capture(fn: str, jit_fn, args: tuple, kwargs: dict | None = None,
            level: str = "lowered") -> ProgramCost | None:
    """Harvest XLA's cost/memory accounting for one jitted entry point.

    ``jit_fn`` is the jax.jit-wrapped callable and ``args``/``kwargs`` the
    exact call about to be dispatched (lowering with donated argnums is
    abstract — no buffer is consumed). Failures are never fatal: the cost
    model is evidence, not a gate, so any backend/API gap logs a warning
    and returns None.
    """
    if level == "off":
        return None
    if level not in CAPTURE_LEVELS:
        raise ValueError(f"unknown cost-capture level {level!r}; "
                         f"one of {CAPTURE_LEVELS}")
    try:
        lowered = jit_fn.lower(*args, **(kwargs or {}))
        pc = ProgramCost(fn=fn, level=level)
        cost = _cost_dict(lowered)
        if cost and cost.get("bytes accessed") is not None:
            pc.lowered_bytes_accessed = float(cost["bytes accessed"])
        if level == "compiled":
            compiled = lowered.compile()
            # compiled cost_analysis reflects the optimized HLO; prefer it
            cost = _cost_dict(compiled) or cost
            try:
                mem = compiled.memory_analysis()
            except Exception:
                mem = None
            if mem is not None:
                pc.argument_bytes = int(
                    getattr(mem, "argument_size_in_bytes", 0) or 0)
                pc.output_bytes = int(
                    getattr(mem, "output_size_in_bytes", 0) or 0)
                pc.temp_bytes = int(
                    getattr(mem, "temp_size_in_bytes", 0) or 0)
                pc.generated_code_bytes = int(
                    getattr(mem, "generated_code_size_in_bytes", 0) or 0)
                pc.peak_hbm_bytes = _peak_from_memory_analysis(mem)
        if cost:
            if cost.get("flops") is not None:
                pc.flops = float(cost["flops"])
            if cost.get("bytes accessed") is not None:
                pc.bytes_accessed = float(cost["bytes accessed"])
    except Exception as e:                       # pragma: no cover - backend
        log.warning("costmodel: capture of %s failed: %s: %s",
                    fn, type(e).__name__, str(e)[:200])
        return None
    with _lock:
        _costs[fn] = pc
    _set_gauges(pc)
    events.emit("program_cost", **pc.to_event_fields())
    return pc


# ----------------------------------------------------------------------
# Live device-memory watermarks
def device_memory_stats() -> dict | None:
    """{"bytes_in_use", "peak_bytes_in_use", ...} for the first local
    device, or None where the backend exposes no allocator stats (CPU)."""
    try:
        import jax
        stats = jax.local_devices()[0].memory_stats()
    except Exception:
        return None
    if not stats:
        return None
    return dict(stats)


def record_hbm_watermark(**context: Any) -> dict | None:
    """Emit one ``hbm_watermark`` event + refresh the HBM gauges from live
    allocator stats. Returns the stats, or None (silently) on backends
    without ``memory_stats()`` — per-iteration callers need no guard."""
    stats = device_memory_stats()
    if stats is None:
        return None
    in_use = stats.get("bytes_in_use")
    peak = stats.get("peak_bytes_in_use")
    reg = instruments.registry()
    if in_use is not None:
        reg.gauge("hbm_bytes_in_use").set(in_use)
    if peak is not None:
        reg.gauge("hbm_live_peak_bytes").set(peak)
        best = hbm_peak_bytes()
        if best is not None:
            reg.gauge("hbm_peak_bytes").set(best)
    events.emit("hbm_watermark", bytes_in_use=in_use, peak_bytes=peak,
                **context)
    return stats


def hbm_peak_bytes() -> int | None:
    """Best-known peak HBM: max of the static per-program accounting and
    the live allocator watermark. None when neither source has data."""
    peaks = [pc.peak_hbm_bytes for pc in costs().values()
             if pc.peak_hbm_bytes is not None]
    live = device_memory_stats()
    if live and live.get("peak_bytes_in_use") is not None:
        peaks.append(int(live["peak_bytes_in_use"]))
    return max(peaks) if peaks else None


# ----------------------------------------------------------------------
# Peaks: the MFU / roofline denominators
_measured_peaks: dict[str, float] = {}


def _measure_cpu_peak_flops() -> float:
    """Achieved f32 matmul FLOP/s on this host — the honest MFU
    denominator where no datasheet applies. One-time, ~100 ms."""
    import jax
    import jax.numpy as jnp

    n = 512
    f = jax.jit(lambda a, b: a @ b)
    a = jnp.ones((n, n), jnp.float32)
    jax.block_until_ready(f(a, a))               # compile
    reps, best = 3, 0.0
    for _ in range(reps):
        t0 = time.perf_counter()
        jax.block_until_ready(f(a, a))
        dt = time.perf_counter() - t0
        best = max(best, (2 * n ** 3) / max(dt, 1e-9))
    return best


def _measure_cpu_peak_bytes() -> float:
    """Achieved memory-stream bytes/s (large-array copy) on this host."""
    import jax
    import jax.numpy as jnp

    n = 4 * 1024 * 1024                          # 16 MiB f32
    f = jax.jit(lambda a: a + 1.0)
    a = jnp.ones((n,), jnp.float32)
    jax.block_until_ready(f(a))
    reps, best = 3, 0.0
    for _ in range(reps):
        t0 = time.perf_counter()
        jax.block_until_ready(f(a))
        dt = time.perf_counter() - t0
        best = max(best, (2 * 4 * n) / max(dt, 1e-9))   # read + write
    return best


def peak_flops(backend: str, dtype: str = "float32") -> tuple[float, str]:
    """(peak FLOP/s, source) for MFU. TPU backends use the datasheet
    table; everything else gets a measured matmul microbenchmark
    (memoized per process) so MFU is non-null on every backend."""
    if backend.startswith("tpu"):
        table = PEAK_FLOPS["tpu"]
        return table.get(dtype, table["float32"]), "datasheet_tpu_v5e"
    key = "cpu_flops"
    if key not in _measured_peaks:
        _measured_peaks[key] = _measure_cpu_peak_flops()
    return _measured_peaks[key], "measured_matmul_f32"


def peak_bytes_per_s(backend: str) -> tuple[float, str]:
    """(peak bytes/s, source) for the bandwidth roofline axis."""
    if backend.startswith("tpu"):
        return PEAK_BYTES_PER_S["tpu"], "datasheet_tpu_v5e"
    key = "cpu_bytes"
    if key not in _measured_peaks:
        _measured_peaks[key] = _measure_cpu_peak_bytes()
    return _measured_peaks[key], "measured_stream"


def roofline(flops: float | None, bytes_accessed: float | None,
             seconds: float, backend: str,
             dtype: str = "float32") -> dict | None:
    """Achieved-vs-peak utilization on both roofline axes.

    Returns {"achieved_flops_per_s", "flops_utilization",
    "achieved_bytes_per_s", "bandwidth_utilization", "bound",
    "peak_flops", "peak_bytes_per_s", "peak_source"} — ``bound`` names
    whichever axis is closer to its peak (the binding resource).
    """
    if seconds <= 0 or (flops is None and bytes_accessed is None):
        return None
    pf, src = peak_flops(backend, dtype)
    pb, _ = peak_bytes_per_s(backend)
    out: dict[str, Any] = {"peak_flops": pf, "peak_bytes_per_s": pb,
                           "peak_source": src}
    fu = bu = None
    if flops is not None:
        out["achieved_flops_per_s"] = flops / seconds
        fu = out["flops_utilization"] = round(flops / seconds / pf, 6)
    if bytes_accessed is not None:
        out["achieved_bytes_per_s"] = bytes_accessed / seconds
        bu = out["bandwidth_utilization"] = round(
            bytes_accessed / seconds / pb, 6)
    out["bound"] = ("compute" if (fu or 0) >= (bu or 0) else "memory")
    return out


# ----------------------------------------------------------------------
# Model-level FLOP counting (shared by bench.py and
# scripts/roofline_report.py — previously an island in each)
def forward_flops_per_example(exp) -> float:
    """Forward FLOPs per example of an Experiment's model, preferring
    XLA's cost analysis of the compiled single-model forward (exact for
    convs, where the dense 2-FLOPs-per-param rule undercounts by orders
    of magnitude). Falls back to the dense analytic rule."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    batch = min(exp.cfg.batch_size, 256)
    try:
        # exp.ds is always populated (exp.x is None under stream_data)
        x1 = jnp.zeros((batch, *exp.ds.feature_shape), exp.ds.x.dtype)
        compiled = jax.jit(exp.pool.apply).lower(
            exp.pool.slot(0), x1).compile()
        cost = _cost_dict(compiled)
        return float(cost["flops"]) / batch
    except Exception:
        n_params = sum(int(np.prod(l.shape[1:]))   # leading M axis excluded
                       for l in jax.tree_util.tree_leaves(exp.pool.params))
        return 2.0 * n_params


def round_flops(exp) -> tuple[float, str]:
    """(FLOPs per communication round, source) for an Experiment.

    Prefers the captured cost of the program that actually runs the
    round: the fused ``train_iteration_eval`` executes ``comm_round``
    rounds (plus its in-program evals) per dispatch; ``train_round``
    executes one. Falls back to the analytic estimate (forward cost
    model × the round's step arithmetic) when no program was captured.
    """
    pc = get("train_iteration_eval")
    if pc is not None and pc.flops:
        return pc.flops / max(exp.cfg.comm_round, 1), "cost_analysis"
    pc = get("train_round")
    if pc is not None and pc.flops:
        # eval programs run separately on this path; amortise them in
        eval_pc = get("acc_matrix")
        per_eval = (2 * eval_pc.flops if eval_pc is not None and eval_pc.flops
                    else 0.0)
        return (pc.flops + per_eval / max(exp.cfg.frequency_of_the_test, 1),
                "cost_analysis")
    return analytic_round_flops(exp), "analytic"


def round_bytes(exp) -> float | None:
    """Bytes accessed per communication round from the captured round
    program, or None when nothing was captured."""
    pc = get("train_iteration_eval")
    if pc is not None and pc.bytes_accessed:
        return pc.bytes_accessed / max(exp.cfg.comm_round, 1)
    pc = get("train_round")
    if pc is not None and pc.bytes_accessed:
        return pc.bytes_accessed
    return None


def analytic_round_flops(exp) -> float:
    """Analytic round-FLOPs estimate: backward ≈ 2× forward, so a train
    step costs ~3× the forward. Per round: M × C local trainers each run
    ``epochs`` SGD steps on a ``batch_size`` batch; eval matrices add
    M × C full-step inferences every ``frequency_of_the_test`` rounds
    (amortised in)."""
    cfg, ds = exp.cfg, exp.ds
    fpe = forward_flops_per_example(exp)
    M, C = exp.pool.num_models, cfg.device_clients
    train = M * C * cfg.epochs * cfg.batch_size * fpe * 3
    eval_amortised = (M * C * ds.samples_per_step * fpe
                      / max(cfg.frequency_of_the_test, 1))
    return float(train + eval_amortised)
