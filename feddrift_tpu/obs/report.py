"""Render a human-readable run report from a run directory.

Consumes ``events.jsonl`` (the structured event bus stream) plus
``metrics.jsonl`` (the wandb-schema scalar series) and prints the view a
BENCH/PARITY debugging session previously reconstructed by re-reading
logs: phase breakdown, drift/cluster timeline, throughput, fault summary,
final accuracy.

    python -m feddrift_tpu report runs/sea-fnn-softcluster-H_A_C_1_10_0-s0
    python -m feddrift_tpu report --json <run_dir>

Runs that predate the telemetry subsystem (committed ``runs/*`` contain
only ``metrics.jsonl``) degrade gracefully: the metrics-derived sections
render, event-derived sections report their absence.
"""

from __future__ import annotations

import json
import os
from typing import Any

# Event kinds rendered on the drift/cluster timeline, in one place so the
# renderer and its tests agree.
TIMELINE_KINDS = ("drift_detected", "cluster_create", "cluster_merge",
                  "cluster_delete", "cluster_split", "model_replaced")
FAULT_KINDS = ("fault_injected", "client_killed", "client_revived",
               "failure_suspected")
RESILIENCE_KINDS = ("conn_reconnect", "publish_retry", "heartbeat_missed",
                    "chaos_injected", "preempt_checkpoint",
                    "divergence_detected", "checkpoint_corrupt")
ROBUSTNESS_KINDS = ("byzantine_injected", "robust_agg_applied",
                    "acc_stale_excluded", "quorum_revive")


def _load_jsonl(path: str) -> list[dict]:
    records = []
    if not os.path.isfile(path):
        return records
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                records.append(json.loads(line))
            except json.JSONDecodeError:
                continue                     # tolerate a torn tail line
    return records


def summarize(run_dir: str) -> dict[str, Any]:
    """Machine-readable run summary (the --json output and the renderer's
    single source)."""
    events = _load_jsonl(os.path.join(run_dir, "events.jsonl"))
    metrics = _load_jsonl(os.path.join(run_dir, "metrics.jsonl"))

    out: dict[str, Any] = {
        "run_dir": run_dir,
        "has_events": bool(events),
        "has_metrics": bool(metrics),
    }

    # -- accuracy trajectory (metrics.jsonl) ---------------------------
    test = [(r.get("iteration", 0), r.get("round", 0), r["Test/Acc"])
            for r in metrics if "Test/Acc" in r]
    if test:
        per_iter: dict[int, float] = {}
        for it, _, acc in test:
            per_iter[it] = acc
        out["accuracy"] = {
            "final_test_acc": test[-1][2],
            "best_test_acc": max(a for _, _, a in test),
            "iterations": len(per_iter),
            "rounds": test[-1][1] + 1,
            "per_iteration": [round(per_iter[k], 4) for k in sorted(per_iter)],
        }

    # -- phase breakdown + throughput (iteration_end events) -----------
    ends = [e for e in events if e["kind"] == "iteration_end"]
    phases: dict[str, dict[str, float]] = {}
    for e in ends:
        for name, s in (e.get("phases") or {}).items():
            agg = phases.setdefault(name, {"total_s": 0.0, "count": 0})
            agg["total_s"] += s.get("total_s", 0.0)
            agg["count"] += s.get("count", 0)
    if phases:
        out["phases"] = {k: {"total_s": round(v["total_s"], 4),
                             "count": int(v["count"])}
                         for k, v in sorted(phases.items())}
    if ends:
        wall = sum(e.get("wall_s", 0.0) for e in ends)
        examples = sum(e.get("examples", 0) for e in ends)
        rounds = sum(e.get("rounds", 0) for e in ends)
        out["throughput"] = {
            "wall_s": round(wall, 3),
            "rounds": rounds,
            "rounds_per_s": round(rounds / wall, 3) if wall else None,
            "examples_per_s": round(examples / wall, 1) if wall else None,
        }
    elif len(test) > 1 and metrics:
        # metrics-only fallback: wall-clock between first/last logged rows
        ts = [r["_ts"] for r in metrics if "_ts" in r]
        if len(ts) > 1 and ts[-1] > ts[0]:
            out["throughput"] = {
                "wall_s": round(ts[-1] - ts[0], 3),
                "rounds": test[-1][1] + 1,
                "rounds_per_s": round((test[-1][1] + 1) / (ts[-1] - ts[0]), 3),
                "examples_per_s": None,
            }

    # -- drift / cluster timeline --------------------------------------
    timeline = [e for e in events if e["kind"] in TIMELINE_KINDS]
    out["timeline"] = timeline
    states = [e for e in events if e["kind"] == "cluster_state"]
    if states:
        out["model_count"] = {
            "per_iteration": [(e.get("iteration"), e.get("num_models"))
                              for e in states],
            "final": states[-1].get("num_models"),
        }

    # -- faults ---------------------------------------------------------
    faults = [e for e in events if e["kind"] in FAULT_KINDS]
    if faults:
        injected = [e for e in faults if e["kind"] == "fault_injected"]
        dropped: set[int] = set()
        for e in injected:
            dropped.update(e.get("clients", []))
        suspects = [e for e in faults if e["kind"] == "failure_suspected"]
        out["faults"] = {
            "injected_rounds": len(injected),
            "clients_ever_dropped": sorted(dropped),
            "kills": sum(1 for e in faults if e["kind"] == "client_killed"),
            "last_suspected": (suspects[-1].get("clients") if suspects
                               else []),
        }

    # -- resilience ------------------------------------------------------
    # transport healing / preemption / divergence / checkpoint integrity
    # (feddrift_tpu/resilience/, docs/RESILIENCE.md)
    res_counts = {k: sum(1 for e in events if e["kind"] == k)
                  for k in RESILIENCE_KINDS}
    if any(res_counts.values()):
        res: dict[str, Any] = {k: v for k, v in res_counts.items() if v}
        div = [e for e in events if e["kind"] == "divergence_detected"]
        if div:
            res["divergence_reasons"] = sorted(
                {e.get("reason", "?") for e in div})
        pre = [e for e in events if e["kind"] == "preempt_checkpoint"]
        if pre:
            res["preempted_at_iteration"] = pre[-1].get("iteration")
        out["resilience"] = res

    # -- robustness ------------------------------------------------------
    # adversary schedule / robust aggregation / staleness exclusions
    # (platform/faults.py::ByzantineInjector, resilience/robust_agg.py)
    byz = [e for e in events if e["kind"] == "byzantine_injected"]
    ragg = [e for e in events if e["kind"] == "robust_agg_applied"]
    stale = [e for e in events if e["kind"] == "acc_stale_excluded"]
    qrev = [e for e in events if e["kind"] == "quorum_revive"]
    if byz or ragg or stale or qrev:
        rob: dict[str, Any] = {}
        if byz:
            attackers: set[int] = set()
            for e in byz:
                attackers.update(e.get("clients", []))
            rob["byzantine"] = {
                "rounds": len(byz),
                "clients": sorted(attackers),
                "modes": sorted({e.get("mode", "?") for e in byz}),
            }
        if ragg:
            rob["aggregation"] = {
                "strategy": ragg[-1].get("strategy"),
                "rounds": len(ragg),
                "rejected_total": sum(e.get("rejected", 0) for e in ragg),
                "clipped_total": sum(e.get("clipped", 0) for e in ragg),
            }
        if stale:
            rob["stale_exclusions"] = {
                "events": len(stale),
                "decisions": sorted({e.get("decision", "?") for e in stale}),
                "changed_decisions": sum(1 for e in stale if e.get("changed")),
            }
        if qrev:
            rob["quorum_revives"] = len(qrev)
        out["robustness"] = rob

    # -- cost model (obs/costmodel.py) -----------------------------------
    # XLA's own accounting per compiled program + live HBM watermarks
    prog_costs = [e for e in events if e["kind"] == "program_cost"]
    marks = [e for e in events if e["kind"] == "hbm_watermark"]
    profiles = [e for e in events if e["kind"] == "profile_captured"]
    if prog_costs or marks or profiles:
        cm: dict[str, Any] = {}
        if prog_costs:
            cm["programs"] = {
                e.get("fn", "?"): {k: e[k] for k in
                                   ("level", "flops", "bytes_accessed",
                                    "argument_bytes", "temp_bytes",
                                    "peak_hbm_bytes") if e.get(k) is not None}
                for e in prog_costs}
        peaks = [e["peak_hbm_bytes"] for e in prog_costs
                 if e.get("peak_hbm_bytes") is not None]
        peaks += [e["peak_bytes"] for e in marks
                  if e.get("peak_bytes") is not None]
        if peaks:
            cm["hbm_peak_bytes"] = max(peaks)
        if marks:
            cm["hbm_watermarks"] = len(marks)
        if profiles:
            cm["profiles_captured"] = sorted(
                {e.get("trace_dir", "?") for e in profiles})
        roof = _roofline_from_events(events, prog_costs, ends)
        if roof:
            cm["roofline"] = roof
        out["cost_model"] = cm

    # -- compiles --------------------------------------------------------
    compiles = [e for e in events if e["kind"] in ("jit_compile",
                                                   "jit_recompile")]
    if compiles:
        by_fn: dict[str, dict[str, int]] = {}
        for e in compiles:
            d = by_fn.setdefault(e.get("fn", "?"),
                                 {"compiles": 0, "recompiles": 0})
            d["compiles" if e["kind"] == "jit_compile" else "recompiles"] += 1
        out["compiles"] = by_fn

    return out


def _roofline_from_events(events: list[dict], prog_costs: list[dict],
                          ends: list[dict]) -> dict[str, Any] | None:
    """Achieved FLOP/s and bytes/s of the run from the captured round
    program's XLA cost + the iteration walls. Utilization against peak is
    added only when the run's backend was a TPU: the datasheet lookup is
    jax-free, whereas the CPU peak is a measured microbenchmark that the
    (pure host-side) report CLI must not run."""
    if not prog_costs or not ends:
        return None
    by_fn = {e.get("fn"): e for e in prog_costs}
    pc = by_fn.get("train_iteration_eval") or by_fn.get("train_round")
    if not pc or not pc.get("flops"):
        return None
    wall = sum(e.get("wall_s", 0.0) for e in ends)
    rounds = sum(e.get("rounds", 0) for e in ends)
    if wall <= 0 or not rounds:
        return None
    per_dispatch = max(rounds / len(ends), 1) \
        if pc["fn"] == "train_iteration_eval" else 1   # fused: R rounds/call
    flops_pr = pc["flops"] / per_dispatch
    bytes_pr = (pc.get("bytes_accessed") or 0) / per_dispatch
    out: dict[str, Any] = {
        "program": pc["fn"], "source": "cost_analysis",
        "flops_per_round": round(flops_pr, 1),
        "achieved_flops_per_s": round(flops_pr * rounds / wall, 1)}
    if bytes_pr:
        out["achieved_bytes_per_s"] = round(bytes_pr * rounds / wall, 1)
    start = next((e for e in events if e["kind"] == "run_start"), None)
    backend = (start or {}).get("backend", "") or ""
    if backend.startswith("tpu"):
        from feddrift_tpu.obs import costmodel
        dtype = (start or {}).get("compute_dtype", "float32")
        pf, src = costmodel.peak_flops(backend, dtype)
        out["flops_utilization"] = round(
            out["achieved_flops_per_s"] / pf, 6)
        if bytes_pr:
            pb, _ = costmodel.peak_bytes_per_s(backend)
            out["bandwidth_utilization"] = round(
                out["achieved_bytes_per_s"] / pb, 6)
        out["peak_source"] = src
    return out


def _fmt_event(e: dict) -> str:
    skip = {"_ts", "kind", "iteration", "round"}
    detail = ", ".join(f"{k}={v}" for k, v in e.items() if k not in skip)
    where = f"t={e.get('iteration', '?')}"
    if "round" in e:
        where += f" r={e['round']}"
    return f"  {where:<12} {e['kind']:<16} {detail}"


def render(summary: dict[str, Any]) -> str:
    """The human-readable report, one section per telemetry dimension."""
    L: list[str] = [f"run: {summary['run_dir']}"]

    acc = summary.get("accuracy")
    if acc:
        L.append(f"  Test/Acc final={acc['final_test_acc']:.4f} "
                 f"best={acc['best_test_acc']:.4f} "
                 f"({acc['iterations']} iterations, {acc['rounds']} rounds)")
        traj = ", ".join(f"{a:.3f}" for a in acc["per_iteration"])
        L.append(f"  per-iteration: {traj}")
    elif not summary.get("has_metrics"):
        L.append("  (no metrics.jsonl)")

    tp = summary.get("throughput")
    L.append("")
    L.append("throughput:")
    if tp:
        ex = (f", {tp['examples_per_s']} examples/s"
              if tp.get("examples_per_s") else "")
        L.append(f"  {tp['rounds']} rounds in {tp['wall_s']}s "
                 f"= {tp['rounds_per_s']} rounds/s{ex}")
    else:
        L.append("  (unavailable — run predates events.jsonl)")

    L.append("")
    L.append("phase breakdown:")
    phases = summary.get("phases")
    if phases:
        total = sum(v["total_s"] for v in phases.values()) or 1.0
        for name, v in sorted(phases.items(), key=lambda kv: -kv[1]["total_s"]):
            L.append(f"  {name:<14} {v['total_s']:>9.3f}s "
                     f"({100 * v['total_s'] / total:5.1f}%)  n={v['count']}")
    else:
        L.append("  (unavailable — run predates events.jsonl)")

    L.append("")
    mc = summary.get("model_count")
    timeline = summary.get("timeline") or []
    L.append("drift/cluster timeline:")
    if mc:
        L.append(f"  models in use, final: {mc['final']}")
    if timeline:
        L.extend(_fmt_event(e) for e in timeline)
    elif not mc:
        L.append("  (no drift/cluster events recorded)")

    faults = summary.get("faults")
    L.append("")
    L.append("faults:")
    if faults:
        L.append(f"  {faults['injected_rounds']} rounds with injected "
                 f"dropout; clients ever dropped: "
                 f"{faults['clients_ever_dropped']}; "
                 f"kills: {faults['kills']}; "
                 f"suspected now: {faults['last_suspected']}")
    else:
        L.append("  none recorded")

    res = summary.get("resilience")
    if res:
        L.append("")
        L.append("resilience:")
        counts = ", ".join(f"{k}={v}" for k, v in sorted(res.items())
                           if k in RESILIENCE_KINDS)
        L.append(f"  {counts}")
        if "divergence_reasons" in res:
            L.append(f"  divergence reasons: {res['divergence_reasons']}")
        if "preempted_at_iteration" in res:
            L.append(f"  preempted at iteration "
                     f"{res['preempted_at_iteration']} (resumable)")

    rob = summary.get("robustness")
    if rob:
        L.append("")
        L.append("robustness:")
        b = rob.get("byzantine")
        if b:
            L.append(f"  byzantine: {b['rounds']} attacked rounds, "
                     f"clients {b['clients']}, modes {b['modes']}")
        a = rob.get("aggregation")
        if a:
            L.append(f"  robust agg: {a['strategy']} over {a['rounds']} "
                     f"rounds, rejected={a['rejected_total']} "
                     f"clipped={a['clipped_total']}")
        s = rob.get("stale_exclusions")
        if s:
            L.append(f"  stale acc exclusions: {s['events']} "
                     f"({s['changed_decisions']} changed a decision; "
                     f"decisions: {s['decisions']})")
        if rob.get("quorum_revives"):
            L.append(f"  quorum revives: {rob['quorum_revives']}")

    comp = summary.get("compiles")
    if comp:
        L.append("")
        L.append("XLA programs:")
        for fn, d in sorted(comp.items()):
            L.append(f"  {fn:<24} compiles={d['compiles']} "
                     f"recompiles={d['recompiles']}")

    cm = summary.get("cost_model")
    if cm:
        L.append("")
        L.append("cost model (XLA accounting):")
        for fn, d in sorted((cm.get("programs") or {}).items()):
            bits = []
            if d.get("flops") is not None:
                bits.append(f"{d['flops'] / 1e6:.1f} MFLOP")
            if d.get("bytes_accessed") is not None:
                bits.append(f"{d['bytes_accessed'] / 1e6:.1f} MB accessed")
            if d.get("peak_hbm_bytes") is not None:
                bits.append(f"peak {d['peak_hbm_bytes'] / 1e6:.1f} MB")
            L.append(f"  {fn:<24} {', '.join(bits) or d.get('level', '?')}")
        if cm.get("hbm_peak_bytes") is not None:
            n = f" ({cm['hbm_watermarks']} live watermarks)" \
                if cm.get("hbm_watermarks") else ""
            L.append(f"  peak HBM: {cm['hbm_peak_bytes'] / 1e6:.1f} MB{n}")
        roof = cm.get("roofline")
        if roof:
            line = (f"  roofline ({roof['program']}): "
                    f"{roof['achieved_flops_per_s'] / 1e9:.3f} GFLOP/s")
            if roof.get("achieved_bytes_per_s"):
                line += f", {roof['achieved_bytes_per_s'] / 1e9:.3f} GB/s"
            if roof.get("flops_utilization") is not None:
                line += (f" — {100 * roof['flops_utilization']:.2f}% of "
                         f"{roof.get('peak_source', 'peak')}")
            L.append(line)
        if cm.get("profiles_captured"):
            L.append(f"  profiler traces: {cm['profiles_captured']}")
    return "\n".join(L)


def main(argv: list[str] | None = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="feddrift_tpu report",
        description="render a run report from events.jsonl + metrics.jsonl")
    ap.add_argument("run_dirs", nargs="+", help="run directories")
    ap.add_argument("--json", action="store_true", help="machine-readable")
    ap.add_argument("--trace", action="store_true",
                    help="also write <run_dir>/trace.json (Chrome-trace-"
                         "event timeline from spans.jsonl + events.jsonl)")
    args = ap.parse_args(argv)

    summaries = []
    for d in args.run_dirs:
        s = summarize(d)
        if not s["has_metrics"] and not s["has_events"]:
            print(f"{d}: no metrics.jsonl or events.jsonl found")
            return 1
        if args.trace:
            from feddrift_tpu.obs import spans
            path = spans.write_trace(d)
            with open(path) as f:
                n = len(json.load(f)["traceEvents"])
            s["trace"] = {"path": path, "events": n}
            print(f"trace written: {path} ({n} events)")
        summaries.append(s)

    if args.json:
        print(json.dumps(summaries if len(summaries) > 1 else summaries[0],
                         indent=2))
        return 0
    print("\n\n".join(render(s) for s in summaries))
    return 0
