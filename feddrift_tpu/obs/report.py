"""Render a human-readable run report from a run directory.

Consumes ``events.jsonl`` (the structured event bus stream) plus
``metrics.jsonl`` (the wandb-schema scalar series) and prints the view a
BENCH/PARITY debugging session previously reconstructed by re-reading
logs: phase breakdown, drift/cluster timeline, throughput, fault summary,
final accuracy.

    python -m feddrift_tpu report runs/sea-fnn-softcluster-H_A_C_1_10_0-s0
    python -m feddrift_tpu report --json <run_dir>

Runs that predate the telemetry subsystem (committed ``runs/*`` contain
only ``metrics.jsonl``) degrade gracefully: the metrics-derived sections
render, event-derived sections report their absence.
"""

from __future__ import annotations

import json
import os
from typing import Any

# Event kinds rendered on the drift/cluster timeline, in one place so the
# renderer and its tests agree.
TIMELINE_KINDS = ("drift_detected", "cluster_create", "cluster_merge",
                  "cluster_delete", "cluster_split", "model_replaced")
FAULT_KINDS = ("fault_injected", "client_killed", "client_revived",
               "failure_suspected")
RESILIENCE_KINDS = ("conn_reconnect", "publish_retry", "heartbeat_missed",
                    "chaos_injected", "preempt_checkpoint",
                    "divergence_detected", "checkpoint_corrupt")
ROBUSTNESS_KINDS = ("byzantine_injected", "robust_agg_applied",
                    "acc_stale_excluded", "quorum_revive")
HIERARCHY_KINDS = ("edge_aggregated", "edge_failed", "edge_rehomed",
                   "update_compressed", "compress_corrupt")


def _load_jsonl(path: str) -> list[dict]:
    records = []
    if not os.path.isfile(path):
        return records
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                records.append(json.loads(line))
            except json.JSONDecodeError:
                continue                     # tolerate a torn tail line
    return records


def summarize(run_dir: str) -> dict[str, Any]:
    """Machine-readable run summary (the --json output and the renderer's
    single source)."""
    # rotated generation first (size-capped runs), then the live file —
    # same fold order as critical_path's loader
    events = (_load_jsonl(os.path.join(run_dir, "events.jsonl.1"))
              + _load_jsonl(os.path.join(run_dir, "events.jsonl")))
    metrics = _load_jsonl(os.path.join(run_dir, "metrics.jsonl"))

    out: dict[str, Any] = {
        "run_dir": run_dir,
        "has_events": bool(events),
        "has_metrics": bool(metrics),
    }

    # -- accuracy trajectory (metrics.jsonl) ---------------------------
    test = [(r.get("iteration", 0), r.get("round", 0), r["Test/Acc"])
            for r in metrics if "Test/Acc" in r]
    if test:
        per_iter: dict[int, float] = {}
        for it, _, acc in test:
            per_iter[it] = acc
        out["accuracy"] = {
            "final_test_acc": test[-1][2],
            "best_test_acc": max(a for _, _, a in test),
            "iterations": len(per_iter),
            "rounds": test[-1][1] + 1,
            "per_iteration": [round(per_iter[k], 4) for k in sorted(per_iter)],
        }

    # -- phase breakdown + throughput (iteration_end events) -----------
    ends = [e for e in events if e["kind"] == "iteration_end"]
    phases: dict[str, dict[str, float]] = {}
    for e in ends:
        for name, s in (e.get("phases") or {}).items():
            agg = phases.setdefault(name, {"total_s": 0.0, "count": 0})
            agg["total_s"] += s.get("total_s", 0.0)
            agg["count"] += s.get("count", 0)
    if phases:
        out["phases"] = {k: {"total_s": round(v["total_s"], 4),
                             "count": int(v["count"])}
                         for k, v in sorted(phases.items())}
    if ends:
        wall = sum(e.get("wall_s", 0.0) for e in ends)
        examples = sum(e.get("examples", 0) for e in ends)
        rounds = sum(e.get("rounds", 0) for e in ends)
        out["throughput"] = {
            "wall_s": round(wall, 3),
            "rounds": rounds,
            "rounds_per_s": round(rounds / wall, 3) if wall else None,
            "examples_per_s": round(examples / wall, 1) if wall else None,
        }
    elif len(test) > 1 and metrics:
        # metrics-only fallback: wall-clock between first/last logged rows
        ts = [r["_ts"] for r in metrics if "_ts" in r]
        if len(ts) > 1 and ts[-1] > ts[0]:
            out["throughput"] = {
                "wall_s": round(ts[-1] - ts[0], 3),
                "rounds": test[-1][1] + 1,
                "rounds_per_s": round((test[-1][1] + 1) / (ts[-1] - ts[0]), 3),
                "examples_per_s": None,
            }

    # -- drift / cluster timeline --------------------------------------
    timeline = [e for e in events if e["kind"] in TIMELINE_KINDS]
    out["timeline"] = timeline
    states = [e for e in events if e["kind"] == "cluster_state"]
    if states:
        out["model_count"] = {
            "per_iteration": [(e.get("iteration"), e.get("num_models"))
                              for e in states],
            "final": states[-1].get("num_models"),
        }

    # -- assignment matrix + oracle agreement (cluster_assign events,
    # obs/lineage.py; ground truth rides in run_start.concept_matrix) ----
    assigns: dict[int, dict] = {}
    for e in events:
        if e["kind"] == "cluster_assign" and e.get("iteration") is not None:
            assigns[int(e["iteration"])] = e          # last one per t wins
    if assigns:
        out["assignments"] = [
            {"iteration": it,
             "assignment": assigns[it].get("assignment"),
             "oracle_ari": assigns[it].get("oracle_ari"),
             "oracle_purity": assigns[it].get("oracle_purity")}
            for it in sorted(assigns)]
        aris = [a["oracle_ari"] for a in out["assignments"]
                if a["oracle_ari"] is not None]
        if aris:
            purs = [a["oracle_purity"] for a in out["assignments"]
                    if a["oracle_purity"] is not None]
            out["oracle"] = {
                "final_ari": aris[-1], "best_ari": max(aris),
                "mean_ari": round(sum(aris) / len(aris), 4),
                "final_purity": purs[-1] if purs else None,
            }

    # -- alerts (obs/alerts.py: alerts.jsonl or live alert_raised) -------
    alert_recs = _load_jsonl(os.path.join(run_dir, "alerts.jsonl")) \
        or [e for e in events if e["kind"] == "alert_raised"]
    if alert_recs:
        by_rule: dict[str, int] = {}
        for a in alert_recs:
            by_rule[a.get("rule", "?")] = by_rule.get(a.get("rule", "?"), 0) + 1
        out["alerts"] = {
            "count": len(alert_recs),
            "by_rule": by_rule,
            "last": alert_recs[-5:],
        }

    # -- model-quality plane (obs/quality.py, platform/canary.py) --------
    # live per-model accuracy on the read path + shadow canary verdicts
    mq = [e for e in events if e["kind"] == "model_quality"]
    drifts = [e for e in events if e["kind"] == "serve_drift_suspected"]
    starts = [e for e in events if e["kind"] == "canary_started"]
    verdicts = [e for e in events if e["kind"] == "canary_verdict"]
    if mq or drifts or starts or verdicts:
        q: dict[str, Any] = {}
        if mq:
            last = mq[-1]
            q["live"] = {
                "snapshots": len(mq),
                "labeled": last.get("labeled"),
                "missed": last.get("missed"),
                "window": last.get("window"),
                "accuracy": last.get("accuracy"),
                "mean_confidence": last.get("mean_confidence"),
                "mean_entropy": last.get("mean_entropy"),
                "ece": last.get("ece"),
                "per_model": last.get("per_model"),
            }
        if drifts:
            q["drift_suspected"] = {
                "count": len(drifts),
                "last_score": drifts[-1].get("score"),
                "last_iteration": drifts[-1].get("iteration"),
            }
        if starts or verdicts:
            q["canary"] = {
                "started": len(starts),
                "commits": sum(1 for v in verdicts
                               if v.get("verdict") == "commit"),
                "rollbacks": sum(1 for v in verdicts
                                 if v.get("verdict") == "rollback"),
                "verdicts": [
                    {k: v.get(k) for k in
                     ("verdict", "reason", "decided_by", "samples",
                      "live_acc", "shadow_acc", "acc_delta", "agreement",
                      "slots", "lineage_ids")}
                    for v in verdicts[-8:]],
            }
        out["quality"] = q

    # -- faults ---------------------------------------------------------
    faults = [e for e in events if e["kind"] in FAULT_KINDS]
    if faults:
        injected = [e for e in faults if e["kind"] == "fault_injected"]
        dropped: set[int] = set()
        for e in injected:
            dropped.update(e.get("clients", []))
        suspects = [e for e in faults if e["kind"] == "failure_suspected"]
        out["faults"] = {
            "injected_rounds": len(injected),
            "clients_ever_dropped": sorted(dropped),
            "kills": sum(1 for e in faults if e["kind"] == "client_killed"),
            "last_suspected": (suspects[-1].get("clients") if suspects
                               else []),
        }

    # -- participation ---------------------------------------------------
    # population-scale cohort rounds (platform/registry.py,
    # resilience/participation.py; docs/RESILIENCE.md Participation model)
    cohorts = [e for e in events if e["kind"] == "cohort_sampled"]
    stragglers = [e for e in events if e["kind"] == "straggler_masked"]
    degraded = [e for e in events if e["kind"] == "round_degraded"]
    joins = [e for e in events if e["kind"] == "client_join"]
    leaves = [e for e in events if e["kind"] == "client_leave"]
    if cohorts or stragglers or degraded or joins or leaves:
        part: dict[str, Any] = {}
        if cohorts:
            last = cohorts[-1]
            part["cohorts"] = {
                "iterations": len(cohorts),
                "population": last.get("population"),
                "slots": last.get("slots"),
                "active_final": last.get("active"),
                "mean_reliability_final": last.get("mean_reliability"),
            }
        if stragglers:
            masked: set[int] = set()
            for e in stragglers:
                masked.update(e.get("clients", []))
            part["stragglers"] = {
                "rounds": len(stragglers),
                "masked_total": sum(len(e.get("clients", []))
                                    for e in stragglers),
                "distinct_clients": len(masked),
            }
        if degraded:
            part["degraded_rounds"] = {
                "count": len(degraded),
                "quorum": degraded[-1].get("quorum"),
                "last_on_time": degraded[-1].get("on_time"),
            }
        if joins or leaves:
            part["churn"] = {
                "joins": sum(len(e.get("clients", [])) for e in joins),
                "leaves": sum(len(e.get("clients", [])) for e in leaves),
            }
        out["participation"] = part

    # -- resilience ------------------------------------------------------
    # transport healing / preemption / divergence / checkpoint integrity
    # (feddrift_tpu/resilience/, docs/RESILIENCE.md)
    res_counts = {k: sum(1 for e in events if e["kind"] == k)
                  for k in RESILIENCE_KINDS}
    if any(res_counts.values()):
        res: dict[str, Any] = {k: v for k, v in res_counts.items() if v}
        div = [e for e in events if e["kind"] == "divergence_detected"]
        if div:
            res["divergence_reasons"] = sorted(
                {e.get("reason", "?") for e in div})
        pre = [e for e in events if e["kind"] == "preempt_checkpoint"]
        if pre:
            res["preempted_at_iteration"] = pre[-1].get("iteration")
        out["resilience"] = res

    # -- robustness ------------------------------------------------------
    # adversary schedule / robust aggregation / staleness exclusions
    # (platform/faults.py::ByzantineInjector, resilience/robust_agg.py)
    byz = [e for e in events if e["kind"] == "byzantine_injected"]
    ragg = [e for e in events if e["kind"] == "robust_agg_applied"]
    stale = [e for e in events if e["kind"] == "acc_stale_excluded"]
    qrev = [e for e in events if e["kind"] == "quorum_revive"]
    if byz or ragg or stale or qrev:
        rob: dict[str, Any] = {}
        if byz:
            attackers: set[int] = set()
            for e in byz:
                attackers.update(e.get("clients", []))
            rob["byzantine"] = {
                "rounds": len(byz),
                "clients": sorted(attackers),
                "modes": sorted({e.get("mode", "?") for e in byz}),
            }
        if ragg:
            rob["aggregation"] = {
                "strategy": ragg[-1].get("strategy"),
                "rounds": len(ragg),
                "rejected_total": sum(e.get("rejected", 0) for e in ragg),
                "clipped_total": sum(e.get("clipped", 0) for e in ragg),
            }
        if stale:
            rob["stale_exclusions"] = {
                "events": len(stale),
                "decisions": sorted({e.get("decision", "?") for e in stale}),
                "changed_decisions": sum(1 for e in stale if e.get("changed")),
            }
        if qrev:
            rob["quorum_revives"] = len(qrev)
        out["robustness"] = rob

    # -- hierarchy --------------------------------------------------------
    # two-tier edge aggregation + wire compression
    # (platform/hierarchical.py, comm/compress.py; docs/RESILIENCE.md
    # Hierarchical aggregation)
    eagg = [e for e in events if e["kind"] == "edge_aggregated"]
    efail = [e for e in events if e["kind"] == "edge_failed"]
    ereh = [e for e in events if e["kind"] == "edge_rehomed"]
    comp_ev = [e for e in events if e["kind"] == "update_compressed"]
    corrupt = [e for e in events if e["kind"] == "compress_corrupt"]
    if eagg or efail or ereh or comp_ev or corrupt:
        hier: dict[str, Any] = {}
        if eagg:
            last = eagg[-1]
            hier["tiers"] = {
                "rounds": len(eagg),
                "edges": len(last.get("edge_active") or []),
                "edge_strategy": last.get("edge_strategy"),
                "server_strategy": last.get("server_strategy"),
                "edge_rejected_total": sum(e.get("edge_rejected", 0)
                                           for e in eagg),
                "server_rejected_total": sum(e.get("server_rejected", 0)
                                             for e in eagg),
            }
        if efail:
            by_reason: dict[str, int] = {}
            for e in efail:
                r = e.get("reason", "?")
                by_reason[r] = by_reason.get(r, 0) + 1
            hier["edge_failures"] = {"count": len(efail),
                                     "by_reason": by_reason}
        if ereh:
            hier["rehomed"] = {
                "events": len(ereh),
                "clients_total": sum(len(e.get("clients", []))
                                     for e in ereh),
                "last": {"edge": ereh[-1].get("edge"),
                         "targets": ereh[-1].get("targets")},
            }
        if comp_ev:
            by_codec: dict[str, dict[str, int]] = {}
            for e in comp_ev:
                d = by_codec.setdefault(e.get("codec", "?"),
                                        {"frames": 0, "raw_bytes": 0,
                                         "wire_bytes": 0})
                d["frames"] += 1
                d["raw_bytes"] += e.get("raw_bytes", 0)
                d["wire_bytes"] += e.get("wire_bytes", 0)
            hier["compression"] = {
                c: {**d, "ratio": round(d["raw_bytes"]
                                        / max(d["wire_bytes"], 1), 2)}
                for c, d in by_codec.items()}
        if corrupt:
            hier["corrupt_frames"] = len(corrupt)
        out["hierarchy"] = hier

    # -- secure aggregation (resilience/secure_round.py) ------------------
    sec_started = [e for e in events if e["kind"] == "secure_round_started"]
    sec_rec = [e for e in events if e["kind"] == "secure_reconstructed"]
    sec_deg = [e for e in events if e["kind"] == "secure_degraded"]
    sec_drop = [e for e in events if e["kind"] == "share_dropped"]
    if sec_started or sec_rec or sec_deg:
        modes = sorted({e.get("mode", "?") for e in sec_started})
        drop_by_reason: dict[str, int] = {}
        for e in sec_drop:
            r = e.get("reason", "?")
            drop_by_reason[r] = drop_by_reason.get(r, 0) + int(
                e.get("count", 1))
        sec: dict[str, Any] = {
            "rounds": len(sec_started),
            "modes": modes,
            "reconstructed": len(sec_rec),
            "degraded": len(sec_deg),
        }
        if sec_started:
            sec["threshold"] = sec_started[-1].get("threshold")
            sec["holders"] = sec_started[-1].get("holders")
        if sec_rec:
            sec["max_abs_err"] = max(e.get("max_abs_err", 0.0)
                                     for e in sec_rec)
            sec["min_holders_alive"] = min(e.get("holders_alive", 0)
                                           for e in sec_rec)
        if drop_by_reason:
            sec["shares_dropped"] = drop_by_reason
        if sec_deg:
            deg_reasons: dict[str, int] = {}
            for e in sec_deg:
                r = e.get("reason", "?")
                deg_reasons[r] = deg_reasons.get(r, 0) + 1
            sec["degrade_reasons"] = deg_reasons
        out["secure_agg"] = sec

    # -- cost model (obs/costmodel.py) -----------------------------------
    # XLA's own accounting per compiled program + live HBM watermarks
    prog_costs = [e for e in events if e["kind"] == "program_cost"]
    marks = [e for e in events if e["kind"] == "hbm_watermark"]
    profiles = [e for e in events if e["kind"] == "profile_captured"]
    if prog_costs or marks or profiles:
        cm: dict[str, Any] = {}
        if prog_costs:
            cm["programs"] = {
                e.get("fn", "?"): {k: e[k] for k in
                                   ("level", "flops", "bytes_accessed",
                                    "argument_bytes", "temp_bytes",
                                    "peak_hbm_bytes") if e.get(k) is not None}
                for e in prog_costs}
        peaks = [e["peak_hbm_bytes"] for e in prog_costs
                 if e.get("peak_hbm_bytes") is not None]
        peaks += [e["peak_bytes"] for e in marks
                  if e.get("peak_bytes") is not None]
        if peaks:
            cm["hbm_peak_bytes"] = max(peaks)
        if marks:
            cm["hbm_watermarks"] = len(marks)
        if profiles:
            cm["profiles_captured"] = sorted(
                {e.get("trace_dir", "?") for e in profiles})
        roof = _roofline_from_events(events, prog_costs, ends)
        if roof:
            cm["roofline"] = roof
        out["cost_model"] = cm

    # -- compiles --------------------------------------------------------
    compiles = [e for e in events if e["kind"] in ("jit_compile",
                                                   "jit_recompile")]
    if compiles:
        by_fn: dict[str, dict[str, int]] = {}
        for e in compiles:
            d = by_fn.setdefault(e.get("fn", "?"),
                                 {"compiles": 0, "recompiles": 0})
            d["compiles" if e["kind"] == "jit_compile" else "recompiles"] += 1
        out["compiles"] = by_fn

    return out


def _roofline_from_events(events: list[dict], prog_costs: list[dict],
                          ends: list[dict]) -> dict[str, Any] | None:
    """Achieved FLOP/s and bytes/s of the run from the captured round
    program's XLA cost + the iteration walls. Utilization against peak is
    added only when the run's backend was a TPU: the datasheet lookup is
    jax-free, whereas the CPU peak is a measured microbenchmark that the
    (pure host-side) report CLI must not run."""
    if not prog_costs or not ends:
        return None
    by_fn = {e.get("fn"): e for e in prog_costs}
    pc = by_fn.get("train_iteration_eval") or by_fn.get("train_round")
    if not pc or not pc.get("flops"):
        return None
    wall = sum(e.get("wall_s", 0.0) for e in ends)
    rounds = sum(e.get("rounds", 0) for e in ends)
    if wall <= 0 or not rounds:
        return None
    per_dispatch = max(rounds / len(ends), 1) \
        if pc["fn"] == "train_iteration_eval" else 1   # fused: R rounds/call
    flops_pr = pc["flops"] / per_dispatch
    bytes_pr = (pc.get("bytes_accessed") or 0) / per_dispatch
    out: dict[str, Any] = {
        "program": pc["fn"], "source": "cost_analysis",
        "flops_per_round": round(flops_pr, 1),
        "achieved_flops_per_s": round(flops_pr * rounds / wall, 1)}
    if bytes_pr:
        out["achieved_bytes_per_s"] = round(bytes_pr * rounds / wall, 1)
    start = next((e for e in events if e["kind"] == "run_start"), None)
    backend = (start or {}).get("backend", "") or ""
    if backend.startswith("tpu"):
        from feddrift_tpu.obs import costmodel
        dtype = (start or {}).get("compute_dtype", "float32")
        pf, src = costmodel.peak_flops(backend, dtype)
        out["flops_utilization"] = round(
            out["achieved_flops_per_s"] / pf, 6)
        if bytes_pr:
            pb, _ = costmodel.peak_bytes_per_s(backend)
            out["bandwidth_utilization"] = round(
                out["achieved_bytes_per_s"] / pb, 6)
        out["peak_source"] = src
    return out


def _fmt_event(e: dict) -> str:
    skip = {"_ts", "kind", "iteration", "round"}
    detail = ", ".join(f"{k}={v}" for k, v in e.items() if k not in skip)
    where = f"t={e.get('iteration', '?')}"
    if "round" in e:
        where += f" r={e['round']}"
    return f"  {where:<12} {e['kind']:<16} {detail}"


def render(summary: dict[str, Any]) -> str:
    """The human-readable report, one section per telemetry dimension."""
    L: list[str] = [f"run: {summary['run_dir']}"]

    acc = summary.get("accuracy")
    if acc:
        L.append(f"  Test/Acc final={acc['final_test_acc']:.4f} "
                 f"best={acc['best_test_acc']:.4f} "
                 f"({acc['iterations']} iterations, {acc['rounds']} rounds)")
        traj = ", ".join(f"{a:.3f}" for a in acc["per_iteration"])
        L.append(f"  per-iteration: {traj}")
    elif not summary.get("has_metrics"):
        L.append("  (no metrics.jsonl)")

    tp = summary.get("throughput")
    L.append("")
    L.append("throughput:")
    if tp:
        ex = (f", {tp['examples_per_s']} examples/s"
              if tp.get("examples_per_s") else "")
        L.append(f"  {tp['rounds']} rounds in {tp['wall_s']}s "
                 f"= {tp['rounds_per_s']} rounds/s{ex}")
    else:
        L.append("  (unavailable — run predates events.jsonl)")

    L.append("")
    L.append("phase breakdown:")
    phases = summary.get("phases")
    if phases:
        total = sum(v["total_s"] for v in phases.values()) or 1.0
        for name, v in sorted(phases.items(), key=lambda kv: -kv[1]["total_s"]):
            L.append(f"  {name:<14} {v['total_s']:>9.3f}s "
                     f"({100 * v['total_s'] / total:5.1f}%)  n={v['count']}")
    else:
        L.append("  (unavailable — run predates events.jsonl)")

    L.append("")
    mc = summary.get("model_count")
    timeline = summary.get("timeline") or []
    L.append("drift/cluster timeline:")
    if mc:
        L.append(f"  models in use, final: {mc['final']}")
    if timeline:
        L.extend(_fmt_event(e) for e in timeline)
    elif not mc:
        L.append("  (no drift/cluster events recorded)")

    assigns = summary.get("assignments")
    if assigns:
        has_oracle = any(a.get("oracle_ari") is not None for a in assigns)
        head = "  assignment matrix (client → model"
        head += ", oracle ARI/purity):" if has_oracle else "):"
        L.append(head)
        shown = assigns if len(assigns) <= 40 else assigns[:39]
        for a in shown:
            vec = " ".join(str(v) for v in (a.get("assignment") or []))
            line = f"    t={a['iteration']:<3} [{vec}]"
            if a.get("oracle_ari") is not None:
                line += f"  ARI={a['oracle_ari']:.3f}"
            if a.get("oracle_purity") is not None:
                line += f" purity={a['oracle_purity']:.3f}"
            L.append(line)
        if len(assigns) > 40:
            L.append(f"    ... ({len(assigns) - 39} more iterations — "
                     "see `lineage` for the full timeline)")
        osum = summary.get("oracle")
        if osum:
            L.append(f"  oracle agreement: final ARI {osum['final_ari']:.4f} "
                     f"(best {osum['best_ari']:.4f}, "
                     f"mean {osum['mean_ari']:.4f})")

    q = summary.get("quality")
    if q:
        L.append("")
        L.append("quality:")
        lv = q.get("live")
        if lv:
            acc = lv.get("accuracy")
            line = (f"  live accuracy "
                    f"{'-' if acc is None else format(acc, '.4f')} "
                    f"(window {lv['window']}, labeled {lv['labeled']}, "
                    f"missed {lv['missed']}")
            if lv.get("ece") is not None:
                line += f", ECE {lv['ece']:.3f}"
            if lv.get("mean_entropy") is not None:
                line += f", entropy {lv['mean_entropy']:.3f}"
            L.append(line + ")")
            pm = lv.get("per_model") or {}
            bits = [f"m{m}={d['accuracy']:.3f}(n={d['n']})"
                    for m, d in sorted(pm.items()) if d]
            if bits:
                L.append(f"  per-model: {', '.join(bits)}")
        dr = q.get("drift_suspected")
        if dr:
            L.append(f"  serve drift suspected: {dr['count']}x "
                     f"(last KS score {dr['last_score']})")
        cn = q.get("canary")
        if cn:
            L.append(f"  canaries: {cn['started']} started, "
                     f"{cn['commits']} committed, "
                     f"{cn['rollbacks']} rolled back")
            for v in cn.get("verdicts") or []:
                lids = "<-".join(str(x) for x in (v.get("lineage_ids")
                                                  or [])) or "?"
                delta = v.get("acc_delta")
                why = (f"shadow acc {delta:+} over {v.get('samples')} labels"
                       if delta is not None else "no label evidence")
                L.append(f"    {v.get('reason', '?')} {lids} -> "
                         f"{v.get('verdict', '?')} ({why}, "
                         f"by {v.get('decided_by')})")

    faults = summary.get("faults")
    L.append("")
    L.append("faults:")
    if faults:
        L.append(f"  {faults['injected_rounds']} rounds with injected "
                 f"dropout; clients ever dropped: "
                 f"{faults['clients_ever_dropped']}; "
                 f"kills: {faults['kills']}; "
                 f"suspected now: {faults['last_suspected']}")
    else:
        L.append("  none recorded")

    part = summary.get("participation")
    if part:
        L.append("")
        L.append("participation:")
        co = part.get("cohorts")
        if co:
            L.append(f"  cohorts: {co['iterations']} iterations x "
                     f"{co['slots']} slots over population "
                     f"{co['population']} (active at end: "
                     f"{co['active_final']}, mean reliability "
                     f"{co['mean_reliability_final']})")
        st = part.get("stragglers")
        if st:
            L.append(f"  stragglers: {st['masked_total']} masked across "
                     f"{st['rounds']} rounds "
                     f"({st['distinct_clients']} distinct clients)")
        dg = part.get("degraded_rounds")
        if dg:
            L.append(f"  degraded rounds: {dg['count']} (quorum "
                     f"{dg['quorum']}, last on-time {dg['last_on_time']}) "
                     "— params kept, see quorum_miss alerts")
        ch = part.get("churn")
        if ch:
            L.append(f"  churn: {ch['joins']} joins, {ch['leaves']} leaves")

    res = summary.get("resilience")
    if res:
        L.append("")
        L.append("resilience:")
        counts = ", ".join(f"{k}={v}" for k, v in sorted(res.items())
                           if k in RESILIENCE_KINDS)
        L.append(f"  {counts}")
        if "divergence_reasons" in res:
            L.append(f"  divergence reasons: {res['divergence_reasons']}")
        if "preempted_at_iteration" in res:
            L.append(f"  preempted at iteration "
                     f"{res['preempted_at_iteration']} (resumable)")

    rob = summary.get("robustness")
    if rob:
        L.append("")
        L.append("robustness:")
        b = rob.get("byzantine")
        if b:
            L.append(f"  byzantine: {b['rounds']} attacked rounds, "
                     f"clients {b['clients']}, modes {b['modes']}")
        a = rob.get("aggregation")
        if a:
            L.append(f"  robust agg: {a['strategy']} over {a['rounds']} "
                     f"rounds, rejected={a['rejected_total']} "
                     f"clipped={a['clipped_total']}")
        s = rob.get("stale_exclusions")
        if s:
            L.append(f"  stale acc exclusions: {s['events']} "
                     f"({s['changed_decisions']} changed a decision; "
                     f"decisions: {s['decisions']})")
        if rob.get("quorum_revives"):
            L.append(f"  quorum revives: {rob['quorum_revives']}")

    hier = summary.get("hierarchy")
    if hier:
        L.append("")
        L.append("hierarchy:")
        ti = hier.get("tiers")
        if ti:
            L.append(f"  two-tier rounds: {ti['rounds']} over "
                     f"{ti['edges']} edges (edge={ti['edge_strategy']}, "
                     f"server={ti['server_strategy']}); rejected "
                     f"edge={ti['edge_rejected_total']} "
                     f"server={ti['server_rejected_total']}")
        ef = hier.get("edge_failures")
        if ef:
            reasons = ", ".join(f"{r}×{n}"
                                for r, n in sorted(ef["by_reason"].items()))
            L.append(f"  edge failures: {ef['count']} ({reasons})")
        rh = hier.get("rehomed")
        if rh:
            L.append(f"  re-homed: {rh['clients_total']} clients across "
                     f"{rh['events']} events (last: edge "
                     f"{rh['last']['edge']} → {rh['last']['targets']})")
        for codec, d in sorted((hier.get("compression") or {}).items()):
            L.append(f"  wire {codec}: {d['frames']} frames, "
                     f"{d['raw_bytes']} → {d['wire_bytes']} bytes "
                     f"({d['ratio']}x)")
        if hier.get("corrupt_frames"):
            L.append(f"  corrupt frames detected: {hier['corrupt_frames']} "
                     "(nacked, re-sent uncompressed)")

    sec = summary.get("secure_agg")
    if sec:
        L.append("")
        L.append("secure_agg:")
        L.append(f"  {sec['rounds']} secure rounds "
                 f"({', '.join(sec['modes'])}): "
                 f"{sec['reconstructed']} reconstructed, "
                 f"{sec['degraded']} degraded "
                 f"(T={sec.get('threshold', '?')}, "
                 f"holders={sec.get('holders', '?')})")
        if "max_abs_err" in sec:
            L.append(f"  quantization err vs plaintext: "
                     f"max {sec['max_abs_err']:.3g}; min holders alive "
                     f"{sec['min_holders_alive']}")
        if sec.get("shares_dropped"):
            reasons = ", ".join(
                f"{r}×{n}" for r, n in sorted(sec["shares_dropped"].items()))
            L.append(f"  shares dropped: {reasons}")
        if sec.get("degrade_reasons"):
            reasons = ", ".join(
                f"{r}×{n}" for r, n in sorted(sec["degrade_reasons"].items()))
            L.append(f"  degrade reasons: {reasons} (prev params kept)")

    al = summary.get("alerts")
    if al:
        L.append("")
        L.append("alerts:")
        rules = ", ".join(f"{r}×{n}" for r, n in sorted(al["by_rule"].items()))
        L.append(f"  {al['count']} raised — {rules}")
        for a in al["last"]:
            where = f"t={a.get('iteration', '?')}"
            L.append(f"  {where:<6} [{a.get('severity', '?')}] "
                     f"{a.get('rule', '?')}: {a.get('message', '')}")

    comp = summary.get("compiles")
    if comp:
        L.append("")
        L.append("XLA programs:")
        for fn, d in sorted(comp.items()):
            L.append(f"  {fn:<24} compiles={d['compiles']} "
                     f"recompiles={d['recompiles']}")

    cm = summary.get("cost_model")
    if cm:
        L.append("")
        L.append("cost model (XLA accounting):")
        for fn, d in sorted((cm.get("programs") or {}).items()):
            bits = []
            if d.get("flops") is not None:
                bits.append(f"{d['flops'] / 1e6:.1f} MFLOP")
            if d.get("bytes_accessed") is not None:
                bits.append(f"{d['bytes_accessed'] / 1e6:.1f} MB accessed")
            if d.get("peak_hbm_bytes") is not None:
                bits.append(f"peak {d['peak_hbm_bytes'] / 1e6:.1f} MB")
            L.append(f"  {fn:<24} {', '.join(bits) or d.get('level', '?')}")
        if cm.get("hbm_peak_bytes") is not None:
            n = f" ({cm['hbm_watermarks']} live watermarks)" \
                if cm.get("hbm_watermarks") else ""
            L.append(f"  peak HBM: {cm['hbm_peak_bytes'] / 1e6:.1f} MB{n}")
        roof = cm.get("roofline")
        if roof:
            line = (f"  roofline ({roof['program']}): "
                    f"{roof['achieved_flops_per_s'] / 1e9:.3f} GFLOP/s")
            if roof.get("achieved_bytes_per_s"):
                line += f", {roof['achieved_bytes_per_s'] / 1e9:.3f} GB/s"
            if roof.get("flops_utilization") is not None:
                line += (f" — {100 * roof['flops_utilization']:.2f}% of "
                         f"{roof.get('peak_source', 'peak')}")
            L.append(line)
        if cm.get("profiles_captured"):
            L.append(f"  profiler traces: {cm['profiles_captured']}")
    return "\n".join(L)


def follow(run_dir: str, timeout_s: float = 30.0, poll_s: float = 0.5,
           out=None) -> int:
    """Bounded tail mode: stream events.jsonl as it grows, print notable
    events (every alert_raised, plus offline rule evaluation via
    obs/alerts.py for runs recorded without live alerting), and render
    the ordinary report once the run ends — or the time bound expires.

    Returns 0; being cut off by the bound is the contract, not an error.
    """
    import sys
    import time as _time

    from feddrift_tpu.obs import alerts as obs_alerts

    out = out or sys.stdout
    path = os.path.join(run_dir, "events.jsonl")
    gen1 = path + ".1"
    mon = obs_alerts.AlertMonitor()          # offline: no file, no bus
    seen_alerts: set = set()                 # (rule, iteration) dedupe
    offset = 0
    deadline = _time.monotonic() + timeout_s
    done = False

    def fmt_alert(a: dict, origin: str) -> str:
        return (f"[{origin}] t={a.get('iteration', '?')} "
                f"{a.get('severity', '?')}/{a.get('rule', '?')}: "
                f"{a.get('message', '')}")

    def read_from(p: str, start: int) -> tuple[list, int]:
        """Read whole JSON lines from byte ``start``; a torn tail line is
        left unconsumed (re-read next poll)."""
        recs = []
        with open(p) as f:
            f.seek(start)
            chunk = f.read()
            end = f.tell()
        for line in chunk.splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                recs.append(json.loads(line))
            except json.JSONDecodeError:
                end -= len(line) + 1          # torn tail: re-read next poll
                break
        return recs, end

    print(f"following {path} (bound {timeout_s:.0f}s; "
          "ends at run_end)", file=out)
    # Fold an already-rotated generation first (size-capped runs —
    # obs_max_file_mb — move history to events.jsonl.1), like the other
    # readers (summarize/critical_path) do.
    pre_rotated: list = []
    if os.path.isfile(gen1):
        pre_rotated, _ = read_from(gen1, 0)
        print(f"(folded {len(pre_rotated)} events from rotated "
              f"{os.path.basename(gen1)})", file=out)
    while not done and _time.monotonic() < deadline:
        new, pre_rotated = pre_rotated, []
        if os.path.isfile(path):
            if os.path.getsize(path) < offset:
                # The file shrank below our offset: it rotated mid-follow
                # and our unread tail now lives in events.jsonl.1 — fold
                # it from the old offset instead of silently losing it.
                folded = []
                if os.path.isfile(gen1) and os.path.getsize(gen1) >= offset:
                    folded, _ = read_from(gen1, offset)
                new.extend(folded)
                print(f"(events.jsonl rotated mid-follow; folded "
                      f"{len(folded)} tail events from "
                      f"{os.path.basename(gen1)})", file=out)
                offset = 0
            recs, offset = read_from(path, offset)
            new.extend(recs)
        for e in new:
            kind = e.get("kind")
            if kind == "alert_raised":
                seen_alerts.add((e.get("rule"), e.get("iteration")))
                print(fmt_alert(e, "live"), file=out)
            else:
                n_before = len(mon.alerts)
                mon.observe(e)
                for a in mon.alerts[n_before:]:
                    key = (a.get("rule"), a.get("iteration"))
                    if key not in seen_alerts:
                        seen_alerts.add(key)
                        print(fmt_alert(a, "offline"), file=out)
            if kind == "iteration_end":
                print(f"t={e.get('iteration', '?')} done: "
                      f"Test/Acc={e.get('test_acc')} "
                      f"({e.get('rounds_per_s')} rounds/s)", file=out)
            if kind == "run_end":
                done = True
        if not done:
            _time.sleep(poll_s)

    print("", file=out)
    if not done:
        print(f"(bound reached after {timeout_s:.0f}s — report below is a "
              "snapshot of an unfinished run)", file=out)
    print(render(summarize(run_dir)), file=out)
    return 0


def main(argv: list[str] | None = None) -> int:
    import argparse
    import sys

    ap = argparse.ArgumentParser(
        prog="feddrift_tpu report",
        description="render a run report from events.jsonl + metrics.jsonl")
    ap.add_argument("run_dirs", nargs="+", help="run directories")
    ap.add_argument("--json", action="store_true", help="machine-readable")
    ap.add_argument("--trace", action="store_true",
                    help="also write <run_dir>/trace.json (Chrome-trace-"
                         "event timeline from spans.jsonl + events.jsonl)")
    ap.add_argument("--follow", action="store_true",
                    help="bounded tail mode: stream events + alerts until "
                         "run_end or --follow-timeout, then render the "
                         "report")
    ap.add_argument("--follow-timeout", type=float, default=30.0,
                    help="max seconds to follow (default 30)")
    ap.add_argument("--poll", type=float, default=0.5,
                    help="follow-mode poll interval in seconds")
    args = ap.parse_args(argv)

    for d in args.run_dirs:
        if not os.path.isdir(d):
            print(f"report: run_dir {d!r} does not exist", file=sys.stderr)
            return 1

    if args.follow:
        if len(args.run_dirs) != 1:
            print("report: --follow takes exactly one run_dir",
                  file=sys.stderr)
            return 1
        return follow(args.run_dirs[0], timeout_s=args.follow_timeout,
                      poll_s=args.poll)

    summaries = []
    for d in args.run_dirs:
        s = summarize(d)
        if not s["has_metrics"] and not s["has_events"]:
            print(f"report: {d}: no metrics.jsonl or events.jsonl — "
                  "nothing to report (is this a run directory?)",
                  file=sys.stderr)
            return 1
        if args.trace:
            from feddrift_tpu.obs import spans
            path = spans.write_trace(d)
            with open(path) as f:
                n = len(json.load(f)["traceEvents"])
            s["trace"] = {"path": path, "events": n}
            print(f"trace written: {path} ({n} events)")
        summaries.append(s)

    if args.json:
        print(json.dumps(summaries if len(summaries) > 1 else summaries[0],
                         indent=2))
        return 0
    print("\n\n".join(render(s) for s in summaries))
    return 0
