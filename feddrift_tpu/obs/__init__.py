"""Run telemetry: structured events, cross-layer instruments, logging setup.

The reference's only instrumentation is wandb scalar series plus one ad-hoc
"aggregate time cost" print (SURVEY.md §5); our own early reproduction had a
wall-clock ``PhaseTracer`` and a flat ``MetricsLogger`` and nothing else —
the comm brokers, drift/cluster decisions, XLA compiles and injected faults
were all invisible. This package is the missing observability layer:

- ``obs.events``      — a process-local structured EVENT BUS. Typed events
  (``kind`` from a closed taxonomy, ``_ts``, current iteration/round
  context) are appended to ``events.jsonl`` next to ``metrics.jsonl``.
  Layers emit through the module-level ``emit()``; background threads
  (comm brokers) share the same bus safely.
- ``obs.instruments`` — counters / gauges / histograms with
  bounded-overhead recording and a Prometheus-textfile exporter, for
  quantities that are too hot to be one-event-per-occurrence
  (bytes on the comm path, per-phase latency histograms, compile counts).
- ``obs.report``      — renders a human-readable run report from
  ``events.jsonl`` + ``metrics.jsonl`` (CLI: ``python -m feddrift_tpu
  report <run_dir>``).
- ``obs.costmodel``   — XLA cost/memory accounting per compiled program
  (FLOPs, bytes accessed, peak HBM), live ``device.memory_stats()``
  watermarks, measured/datasheet peaks, and the roofline math behind
  ``bench.py``'s ``mfu_estimate``.
- ``obs.hostprof``    — the host-plane observatory: a sampling stack
  profiler (``cfg.hostprof_hz``, folded-stack text + trace.json lanes)
  and the per-subsystem ``HostLedger`` of host-seconds and host bytes
  behind the ``host_ledger`` event and ``bench.py --hostscale``.
- ``obs.spans``       — wall-clock span recording (``spans.jsonl``) and
  the Chrome-trace-event exporter behind ``report <run_dir> --trace``
  (Perfetto-loadable ``trace.json``, one lane per process/thread).
- ``obs.regress``     — the perf-regression gate over bench artifacts
  (CLI: ``python -m feddrift_tpu regress <bench.json> --baseline ...``).
- ``obs.lineage``     — cluster genealogy DAG reconstruction + oracle
  ARI/purity scoring (CLI: ``python -m feddrift_tpu lineage <run_dir>``).
- ``obs.alerts``      — declarative rule-based health monitor: live as an
  event-bus tap (``cfg.alerts``) and offline via ``report --follow``,
  raising ``alert_raised`` events + ``alerts.jsonl``.
- ``obs.quantiles``   — streaming P² percentile sketches (O(1) memory)
  registrable alongside histograms for live p50/p95/p99 gauges.
- ``obs.live``        — the live ops plane: per-process /metrics,
  /healthz and /status HTTP endpoints, fleet snapshot publishing +
  ``FleetCollector`` merge over the broker (CLI: ``python -m
  feddrift_tpu fleet <broker>``), and an SLO engine whose error-budget
  burn-rate rules emit ``slo_burn`` events on the live tap.
- ``obs.blackbox``    — the always-on flight recorder: bounded
  in-memory rings over recent events/alerts/round_breakdowns plus
  periodic instrument snapshots, dumped into incident bundles.
- ``obs.incident``    — the incident plane: trigger taps (crit alerts,
  SLO burns, replica deaths, preemption, exceptions, SIGQUIT) debounced
  into self-contained forensic bundles under ``<run_dir>/incidents/``
  (CLI: ``python -m feddrift_tpu incident <bundle-or-run_dir>``).

Event kinds are a CLOSED set (``events.EVENT_KINDS``): ``emit()`` rejects
unknown kinds, and ``scripts/check_events_schema.py`` statically checks that
every kind emitted anywhere in the package is documented in
docs/OBSERVABILITY.md — new events cannot ship undocumented.

See docs/OBSERVABILITY.md for the taxonomy and formats.
"""

from __future__ import annotations

from feddrift_tpu.obs.events import (  # noqa: F401
    EVENT_KINDS,
    EventBus,
    capture,
    configure,
    emit,
    get_bus,
    set_context,
)
from feddrift_tpu.obs.instruments import (  # noqa: F401
    Registry,
    registry,
)
from feddrift_tpu.obs import (  # noqa: F401
    alerts,
    blackbox,
    costmodel,
    hostprof,
    incident,
    lineage,
    live,
    quantiles,
    spans,
)
# (import order: all depend only on obs.events/obs.instruments, bound above;
# lineage is numpy+stdlib only, alerts touches the bus solely via taps, and
# live — the ops-plane HTTP server / fleet publisher / SLO engine — is
# stdlib + events/instruments/alerts, importing comm transports lazily)

_LOG_FORMAT = "%(asctime)s %(name)s %(levelname)s %(message)s"


def setup_logging(level: str | int = "info") -> None:
    """The single logging configuration path for the package.

    Called from the CLI (``--log_level``) and usable from scripts; repeated
    calls reconfigure (``force=True``) so tests and multi-run processes can
    change verbosity. Configures the root handler AND pins the
    ``feddrift_tpu`` logger level, so ``--log_level debug`` surfaces the
    package's debug output without drowning in third-party debug noise
    (third-party loggers stay at the root level only).
    """
    import logging

    if isinstance(level, str):
        lvl = getattr(logging, level.upper(), None)
        if lvl is None:
            raise ValueError(f"unknown log level {level!r}")
    else:
        lvl = level
    logging.basicConfig(level=lvl, format=_LOG_FORMAT, force=True)
    logging.getLogger("feddrift_tpu").setLevel(lvl)
