"""ctypes loader for the native drift-data generator (drift_gen.cpp).

Builds lazily with ``make`` on first use (g++ is in the image); falls back
gracefully — ``available()`` returns False and callers keep the numpy path.
The native path is deterministic per (seed, client, step) cell independent of
thread count, so repeated generation is bitwise-reproducible.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading

import numpy as np

log = logging.getLogger("feddrift_tpu.native")

_DIR = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_DIR, "libdrift_gen.so")
_DATASET_IDS = {"sea": 0, "sine": 1, "circle": 2}

_lock = threading.Lock()
_lib = None
_build_failed = False


def _load():
    global _lib, _build_failed
    with _lock:
        if _lib is not None or _build_failed:
            return _lib
        if not os.path.exists(_SO):
            try:
                subprocess.run(["make", "-C", _DIR], check=True,
                               capture_output=True, timeout=120)
            except (subprocess.SubprocessError, FileNotFoundError) as e:
                log.warning("native drift_gen build failed (%s); "
                            "using numpy generator", e)
                _build_failed = True
                return None
        try:
            lib = ctypes.CDLL(_SO)
        except OSError as e:
            log.warning("could not load %s (%s)", _SO, e)
            _build_failed = True
            return None
        lib.fd_generate.restype = ctypes.c_int
        lib.fd_generate.argtypes = [
            ctypes.c_int,
            ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_double, ctypes.c_uint64, ctypes.c_int,
        ]
        lib.fd_feature_dim.restype = ctypes.c_int
        lib.fd_feature_dim.argtypes = [ctypes.c_int]
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


def generate(name: str, concepts: np.ndarray, sample_num: int,
             noise_prob: float, seed: int,
             n_threads: int = 0) -> tuple[np.ndarray, np.ndarray]:
    """Fill [C, T1, N, F] / [C, T1, N] arrays with the native kernel.

    ``concepts``: [T1, C] int matrix (already time-stretch dilated).
    """
    lib = _load()
    if lib is None:
        raise RuntimeError("native drift generator unavailable")
    if name not in _DATASET_IDS:
        raise KeyError(f"native generator supports {sorted(_DATASET_IDS)}, "
                       f"not {name!r}")
    ds_id = _DATASET_IDS[name]
    T1, C = concepts.shape
    F = int(lib.fd_feature_dim(ds_id))
    x = np.empty((C, T1, sample_num, F), dtype=np.float32)
    y = np.empty((C, T1, sample_num), dtype=np.int32)
    conc = np.ascontiguousarray(concepts, dtype=np.int32)
    rc = lib.fd_generate(
        ds_id,
        x.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        y.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        conc.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        C, T1, sample_num, float(noise_prob), np.uint64(seed), n_threads)
    if rc != 0:
        raise RuntimeError(f"fd_generate returned {rc}")
    return x, y
