// Native drift-data generator: the host-side data pipeline of the framework.
//
// The reference generates its drift data by writing one CSV per
// (client, time step) from single-threaded Python and re-reading the files in
// every MPI process (fedml_api/data_preprocessing/sea/data_loader.py:37-99,
// prepare_data.py). Here generation is an in-memory, multi-threaded C++
// kernel filling the framework's dense [C, T1, N, F] arrays directly — no
// files, no serialization, deterministic per (seed, client, step) cell
// regardless of thread count.
//
// Exposed via a plain C ABI consumed with ctypes
// (feddrift_tpu/native/__init__.py). Datasets: SEA / SINE / CIRCLE with the
// same label rules as the numpy path (feddrift_tpu/data/synthetic.py).
//
// Build: make -C feddrift_tpu/native   (g++ -O3 -shared -fPIC)

#include <cstdint>
#include <cmath>
#include <cstring>
#include <thread>
#include <vector>

namespace {

// ---------------------------------------------------------------------
// Deterministic counter-based RNG: splitmix64 streams keyed by
// (seed, client, step). Threading cannot change the output.
struct SplitMix64 {
  uint64_t state;
  explicit SplitMix64(uint64_t s) : state(s) {}
  uint64_t next() {
    uint64_t z = (state += 0x9E3779B97f4A7C15ULL);
    z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ULL;
    z = (z ^ (z >> 27)) * 0x94D049BB133111EBULL;
    return z ^ (z >> 31);
  }
  // uniform in [0, 1)
  double uniform() { return (next() >> 11) * (1.0 / 9007199254740992.0); }
};

inline uint64_t cell_seed(uint64_t seed, int64_t c, int64_t t) {
  // one multiply-xor mix per coordinate; distinct streams per cell
  uint64_t h = seed ^ 0xD6E8FEB86659FD93ULL;
  h ^= (uint64_t)(c + 1) * 0xA24BAED4963EE407ULL;
  h ^= (h >> 33);
  h ^= (uint64_t)(t + 1) * 0x9FB21C651E98DF25ULL;
  h ^= (h >> 29);
  return h;
}

constexpr double kSeaThresholds[4] = {8.0, 9.0, 7.0, 9.5};
constexpr double kSeaBaseNoise = 0.1;

enum Dataset { SEA = 0, SINE = 1, CIRCLE = 2 };

void fill_cell(Dataset ds, float* x, int32_t* y, int64_t n, int concept,
               double noise_prob, uint64_t cseed) {
  SplitMix64 rng(cseed);
  switch (ds) {
    case SEA: {
      for (int64_t i = 0; i < n; ++i) {
        float f0 = (float)(rng.uniform() * 10.0);
        float f1 = (float)(rng.uniform() * 10.0);
        float f2 = (float)(rng.uniform() * 10.0);
        x[i * 3 + 0] = f0;
        x[i * 3 + 1] = f1;
        x[i * 3 + 2] = f2;
        int32_t label = (f1 + f2 > kSeaThresholds[concept & 3]) ? 1 : 0;
        if (rng.uniform() < kSeaBaseNoise) label = 1 - label;
        y[i] = label;
      }
      break;
    }
    case SINE: {
      for (int64_t i = 0; i < n; ++i) {
        float f0 = (float)rng.uniform();
        float f1 = (float)rng.uniform();
        x[i * 2 + 0] = f0;
        x[i * 2 + 1] = f1;
        bool below = f1 <= std::sin(f0);
        y[i] = (concept == 0) ? (below ? 1 : 0) : (below ? 0 : 1);
      }
      break;
    }
    case CIRCLE: {
      for (int64_t i = 0; i < n; ++i) {
        float f0 = (float)rng.uniform();
        float f1 = (float)rng.uniform();
        x[i * 2 + 0] = f0;
        x[i * 2 + 1] = f1;
        double cx = concept == 0 ? 0.2 : 0.6;
        double cy = 0.5;
        double r = concept == 0 ? 0.15 : 0.25;
        double z = (f0 - cx) * (f0 - cx) + (f1 - cy) * (f1 - cy) - r * r;
        y[i] = z > 0.0 ? 1 : 0;
      }
      break;
    }
  }
  if (noise_prob > 0.0) {
    for (int64_t i = 0; i < n; ++i) {
      if (rng.uniform() < noise_prob) y[i] = 1 - y[i];
    }
  }
}

}  // namespace

extern "C" {

// x: [C, T1, N, F] float32; y: [C, T1, N] int32; concepts: [T1, C] int32.
// Returns 0 on success, -1 on unknown dataset.
int fd_generate(int dataset, float* x, int32_t* y, const int32_t* concepts,
                int64_t C, int64_t T1, int64_t N, double noise_prob,
                uint64_t seed, int n_threads) {
  if (dataset < 0 || dataset > 2) return -1;
  Dataset ds = (Dataset)dataset;
  int64_t fdim = (ds == SEA) ? 3 : 2;
  if (n_threads <= 0) {
    n_threads = (int)std::thread::hardware_concurrency();
    if (n_threads <= 0) n_threads = 1;
  }
  auto worker = [&](int64_t c_begin, int64_t c_end) {
    for (int64_t c = c_begin; c < c_end; ++c) {
      for (int64_t t = 0; t < T1; ++t) {
        int concept = concepts[t * C + c];
        float* xc = x + ((c * T1 + t) * N) * fdim;
        int32_t* yc = y + (c * T1 + t) * N;
        fill_cell(ds, xc, yc, N, concept, noise_prob, cell_seed(seed, c, t));
      }
    }
  };
  int64_t per = (C + n_threads - 1) / n_threads;
  std::vector<std::thread> threads;
  for (int64_t b = 0; b < C; b += per)
    threads.emplace_back(worker, b, std::min(b + per, C));
  for (auto& th : threads) th.join();
  return 0;
}

// Feature dimension per dataset id (SEA=3, SINE/CIRCLE=2).
int fd_feature_dim(int dataset) { return dataset == 0 ? 3 : 2; }

}  // extern "C"
