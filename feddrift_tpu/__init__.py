"""feddrift-tpu: a TPU-native federated-learning-under-concept-drift framework.

A from-scratch JAX/XLA re-design with the capabilities of microsoft/FedDrift
(AISTATS'23, "Federated Learning under Distributed Concept Drift"). Instead of
one MPI process per client exchanging pickled state dicts (reference:
fedml_api/distributed/fedavg_ens/FedAvgEnsAPI.py:86-92), clients and the model
ensemble are array axes of a single sharded XLA program:

- the model pool is a pytree stacked on a leading ``[M]`` axis,
- clients are a ``[C]`` axis sharded over the TPU mesh,
- per-(model, client) local SGD runs under ``vmap``/``shard_map``,
- FedAvg aggregation is a masked weighted mean lowered to XLA collectives,
- drift-clustering decisions (FedDrift hierarchical merge, drift detection,
  IFCA/CFL/AUE/KUE/DriftSurf/Ada state machines) run on host between steps.
"""

__version__ = "0.1.0"

from feddrift_tpu.config import ExperimentConfig  # noqa: F401
