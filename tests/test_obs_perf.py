"""Performance-observability tests: XLA cost model, unified trace
timeline, and the perf-regression gate (feddrift_tpu/obs/{costmodel,
spans,regress}.py + the xla_trace no-op guard). Pure host logic plus tiny
jit programs; the Experiment-sized integration and the full perf gate are
slow-tier."""

from __future__ import annotations

import json
import os
import subprocess
import sys
import types

import pytest

from feddrift_tpu import obs
from feddrift_tpu.obs import costmodel, regress, spans

ROOT = os.path.join(os.path.dirname(__file__), os.pardir)


@pytest.fixture()
def fresh_bus():
    """Memory-only event bus + empty cost store for isolated assertions."""
    bus = obs.configure(None)
    costmodel.clear()
    yield bus
    obs.configure(None)
    costmodel.clear()


# ----------------------------------------------------------------------
class TestCostModel:
    def test_capture_compiled_level(self, fresh_bus):
        """A tiny jitted matmul yields XLA's own FLOPs/bytes + static HBM
        accounting, one program_cost event, and refreshed gauges."""
        import jax
        import jax.numpy as jnp

        f = jax.jit(lambda a, b: (a @ b).sum())
        a = jnp.ones((32, 32))
        pc = costmodel.capture("toy_matmul", f, (a, a), level="compiled")
        assert pc is not None
        assert pc.flops and pc.flops >= 2 * 32 ** 3  # at least the matmul
        assert pc.bytes_accessed and pc.bytes_accessed > 0
        assert pc.peak_hbm_bytes and pc.peak_hbm_bytes > 0
        assert pc.argument_bytes == 2 * 32 * 32 * 4
        assert costmodel.get("toy_matmul") is pc
        (ev,) = fresh_bus.events("program_cost")
        assert ev["fn"] == "toy_matmul" and ev["level"] == "compiled"
        snap = obs.registry().snapshot()
        assert snap['program_flops{fn="toy_matmul"}'] == pc.flops
        assert snap["hbm_peak_bytes"] == pc.peak_hbm_bytes

    def test_capture_lowered_level_no_memory(self, fresh_bus):
        import jax
        import jax.numpy as jnp

        f = jax.jit(lambda a: a * 2 + 1)
        pc = costmodel.capture("toy_scale", f, (jnp.ones((16,)),),
                               level="lowered")
        assert pc is not None and pc.flops is not None
        assert pc.peak_hbm_bytes is None          # memory needs "compiled"

    def test_capture_off_and_unknown_level(self, fresh_bus):
        assert costmodel.capture("x", None, (), level="off") is None
        with pytest.raises(ValueError, match="unknown cost-capture level"):
            costmodel.capture("x", None, (), level="sideways")

    def test_hbm_watermark_graceful_none_on_cpu(self, fresh_bus):
        """CPU backends expose no memory_stats: no event, no raise."""
        assert costmodel.device_memory_stats() is None
        assert costmodel.record_hbm_watermark(iteration=0) is None
        assert fresh_bus.events("hbm_watermark") == []

    def test_peak_flops_sources(self):
        v, src = costmodel.peak_flops("tpu", "bfloat16")
        assert v == costmodel.PEAK_FLOPS["tpu"]["bfloat16"]
        assert src == "datasheet_tpu_v5e"
        v, src = costmodel.peak_flops("cpu")
        assert v > 0 and src == "measured_matmul_f32"
        # memoized: the microbenchmark runs once per process
        assert costmodel.peak_flops("cpu")[0] == v

    def test_roofline_math(self):
        r = costmodel.roofline(flops=197e12, bytes_accessed=8.19e11,
                               seconds=1.0, backend="tpu", dtype="bfloat16")
        assert r["flops_utilization"] == 1.0
        assert r["bandwidth_utilization"] == 1.0
        assert r["bound"] in ("compute", "memory")
        r = costmodel.roofline(flops=1e9, bytes_accessed=8.19e11,
                               seconds=1.0, backend="tpu", dtype="bfloat16")
        assert r["bound"] == "memory"
        assert costmodel.roofline(None, None, 1.0, "tpu") is None

    def test_round_flops_prefers_captured_program(self, fresh_bus):
        """The fused round program's own cost wins over the analytic rule,
        normalized by the rounds one dispatch executes."""
        with costmodel._lock:
            costmodel._costs["train_iteration_eval"] = costmodel.ProgramCost(
                fn="train_iteration_eval", level="lowered",
                flops=2000.0, bytes_accessed=4000.0)
        exp = types.SimpleNamespace(cfg=types.SimpleNamespace(
            comm_round=20, frequency_of_the_test=5))
        flops, source = costmodel.round_flops(exp)
        assert flops == 100.0 and source == "cost_analysis"
        assert costmodel.round_bytes(exp) == 200.0


# ----------------------------------------------------------------------
class TestSpans:
    def test_recorder_and_sink(self, tmp_path):
        path = str(tmp_path / "spans.jsonl")
        rec = spans.SpanRecorder(path, pid=3)
        with rec.span("train_round", cat="phase", r=1):
            pass
        rec.record("iteration", ts=100.0, dur=2.5, cat="runner", iteration=0)
        rec.close()
        rows = [json.loads(l) for l in open(path)]
        assert [r["name"] for r in rows] == ["train_round", "iteration"]
        assert all(r["pid"] == 3 for r in rows)
        assert rows[1]["ts"] == 100.0 * 1e6 and rows[1]["dur"] == 2.5 * 1e6
        assert rows[0]["args"] == {"r": 1}
        assert rec.spans("iteration")[0]["args"] == {"iteration": 0}

    def test_disabled_recorder_noops(self):
        rec = spans.SpanRecorder(None, enabled=False)
        with rec.span("x"):
            pass
        assert rec.record("y", 0.0, 1.0) is None
        assert rec.spans() == []

    def _synthetic_run_dir(self, tmp_path) -> str:
        """A two-process run: spans on two pids + a few instant events."""
        with open(tmp_path / "spans.jsonl", "w") as f:
            for pid, tid, name, ts, dur in (
                    (0, 111, "iteration", 1_000_000.0, 500_000.0),
                    (0, 111, "train_round", 1_050_000.0, 300_000.0),
                    (0, 222, "publish", 1_100_000.0, 10_000.0),
                    (1, 333, "iteration", 1_010_000.0, 480_000.0)):
                f.write(json.dumps({"name": name, "cat": "phase", "ts": ts,
                                    "dur": dur, "pid": pid, "tid": tid}) + "\n")
        with open(tmp_path / "events.jsonl", "w") as f:
            for ts, kind in ((1.2, "eval"), (1.3, "jit_compile"),
                             (1.1, "drift_detected")):
                f.write(json.dumps({"_ts": ts, "kind": kind,
                                    "iteration": 0}) + "\n")
        return str(tmp_path)

    def test_trace_golden_structure(self, tmp_path):
        """Valid Chrome-trace-event JSON: envelope fields on every event,
        non-negative monotonically consistent ts/dur, sorted timeline, one
        process lane per pid with named metadata."""
        trace = spans.build_trace(self._synthetic_run_dir(tmp_path))
        evs = trace["traceEvents"]
        assert trace["displayTimeUnit"] == "ms"
        meta = [e for e in evs if e["ph"] == "M"]
        data = [e for e in evs if e["ph"] != "M"]
        for e in evs:
            assert {"name", "ph", "pid", "tid"} <= set(e)
        for e in data:
            assert e["ts"] >= 0
            assert e["ph"] in ("X", "i")
            if e["ph"] == "X":
                assert e["dur"] >= 0
        # sorted timeline (monotonic ts across the data events)
        ts = [e["ts"] for e in data]
        assert ts == sorted(ts)
        # one process lane per pid, each named
        pids = {e["pid"] for e in data}
        assert pids == {0, 1}
        proc_meta = {e["pid"] for e in meta if e["name"] == "process_name"}
        assert proc_meta == pids
        # distinct recording threads get distinct per-process lanes,
        # disjoint from the reserved instant-events lane (tid 0)
        lanes_p0 = {e["tid"] for e in data
                    if e["pid"] == 0 and e["ph"] == "X"}
        assert len(lanes_p0) == 2 and spans.EVENTS_LANE_TID not in lanes_p0
        instants = [e for e in data if e["ph"] == "i"]
        assert len(instants) == 3
        assert all(e["tid"] == spans.EVENTS_LANE_TID for e in instants)
        assert {e["name"] for e in instants} == {"eval", "jit_compile",
                                                 "drift_detected"}

    def test_write_trace_and_report_cli(self, tmp_path, capsys):
        """`report <dir> --trace` writes the Perfetto-loadable file."""
        run_dir = self._synthetic_run_dir(tmp_path)
        # report needs metrics or events: events.jsonl already present
        from feddrift_tpu.cli import main
        assert main(["report", run_dir, "--trace"]) == 0
        out_path = os.path.join(run_dir, "trace.json")
        assert os.path.isfile(out_path)
        trace = json.load(open(out_path))
        assert trace["traceEvents"]
        assert "trace written:" in capsys.readouterr().out


# ----------------------------------------------------------------------
class TestReportCostModel:
    def test_roofline_section_from_events(self, tmp_path, capsys):
        """The report CLI derives achieved-vs-peak roofline utilization
        from program_cost + iteration_end events (datasheet peak for TPU
        runs — jax-free), and renders the cost-model section."""
        rows = [
            {"_ts": 1.0, "kind": "run_start", "backend": "tpu",
             "compute_dtype": "bfloat16"},
            {"_ts": 1.1, "kind": "program_cost", "fn": "train_iteration_eval",
             "level": "compiled", "flops": 4.6e10 * 20,
             "bytes_accessed": 1e9, "peak_hbm_bytes": 2_000_000_000},
            {"_ts": 2.0, "kind": "iteration_end", "wall_s": 2.0,
             "rounds": 20, "examples": 100},
            {"_ts": 3.0, "kind": "hbm_watermark", "bytes_in_use": 1e9,
             "peak_bytes": 2.1e9},
            {"_ts": 3.5, "kind": "profile_captured", "trace_dir": "/tmp/p"},
        ]
        with open(tmp_path / "events.jsonl", "w") as f:
            for r in rows:
                f.write(json.dumps(r) + "\n")
        from feddrift_tpu.obs.report import main, summarize
        cm = summarize(str(tmp_path))["cost_model"]
        roof = cm["roofline"]
        # fused program: 920 GFLOP per 20-round dispatch → 46 G/round,
        # 20 rounds in 2 s → 460 GFLOP/s → 0.2335% of 197 TFLOP/s bf16
        assert roof["flops_per_round"] == pytest.approx(4.6e10)
        assert roof["achieved_flops_per_s"] == pytest.approx(4.6e11)
        assert roof["flops_utilization"] == pytest.approx(0.002335)
        assert roof["source"] == "cost_analysis"
        assert cm["hbm_peak_bytes"] == pytest.approx(2.1e9)  # live > static
        assert main([str(tmp_path)]) == 0
        out = capsys.readouterr().out
        assert "cost model (XLA accounting):" in out
        assert "% of datasheet_tpu_v5e" in out

    def test_no_utilization_for_cpu_runs(self, tmp_path):
        """CPU runs report achieved rates only — the report CLI must not
        run the measured-peak microbenchmark (it would init a backend)."""
        rows = [
            {"_ts": 1.0, "kind": "run_start", "backend": "cpu"},
            {"_ts": 1.1, "kind": "program_cost", "fn": "train_round",
             "level": "lowered", "flops": 1e6},
            {"_ts": 2.0, "kind": "iteration_end", "wall_s": 1.0,
             "rounds": 10},
        ]
        with open(tmp_path / "events.jsonl", "w") as f:
            for r in rows:
                f.write(json.dumps(r) + "\n")
        from feddrift_tpu.obs.report import summarize
        roof = summarize(str(tmp_path))["cost_model"]["roofline"]
        assert roof["achieved_flops_per_s"] == pytest.approx(1e7)
        assert "flops_utilization" not in roof


# ----------------------------------------------------------------------
def _bench_fixture(value=100.0, wall=10.0, rounds=1000, acc=0.86,
                   compiles=3.0, recompiles=0.0, wrap=False, **extra):
    d = {"value": value, "wall_s": wall, "rounds": rounds,
         "final_test_acc": acc,
         "instruments": {'jit_compiles{fn="train_round"}': compiles,
                         'jit_recompiles{fn="train_round"}': recompiles},
         **extra}
    return {"parsed": d, "rc": 0} if wrap else d


def _write(path, obj):
    with open(path, "w") as f:
        json.dump(obj, f)
    return str(path)


class TestRegress:
    def test_identical_snapshots_pass(self, tmp_path, capsys):
        p = _write(tmp_path / "b.json", _bench_fixture())
        assert regress.main([p, "--baseline", p]) == 0
        out = capsys.readouterr().out
        assert "OK: 0 regressed" in out

    def test_thirty_pct_slowdown_fails(self, tmp_path, capsys):
        base = _write(tmp_path / "base.json", _bench_fixture())
        slow = _write(tmp_path / "slow.json",
                      _bench_fixture(value=70.0, wall=10.0 / 0.7))
        assert regress.main([slow, "--baseline", base]) == 1
        out = capsys.readouterr().out
        assert "REGRESS" in out and "rounds_per_s" in out

    def test_compile_count_regression(self, tmp_path):
        base = _write(tmp_path / "base.json", _bench_fixture())
        more = _write(tmp_path / "more.json",
                      _bench_fixture(recompiles=2.0))
        assert regress.main([more, "--baseline", base]) == 1
        # an explicit tolerance waives it
        assert regress.main([more, "--baseline", base,
                             "--tol-compiles", "2"]) == 0

    def test_accuracy_absolute_tolerance(self, tmp_path):
        base = _write(tmp_path / "base.json", _bench_fixture())
        worse = _write(tmp_path / "worse.json", _bench_fixture(acc=0.83))
        assert regress.main([worse, "--baseline", base]) == 1
        assert regress.main([worse, "--baseline", base,
                             "--tol-acc", "0.05"]) == 0

    def test_wall_skipped_when_rounds_differ(self, tmp_path, capsys):
        base = _write(tmp_path / "base.json", _bench_fixture(rounds=1600))
        cand = _write(tmp_path / "cand.json",
                      _bench_fixture(rounds=20, wall=99.0))
        assert regress.main([cand, "--baseline", base]) == 0
        assert "rounds differ" in capsys.readouterr().out

    def test_wrapper_format_and_missing_instruments(self, tmp_path, capsys):
        """Committed BENCH_r0*.json wrappers load; artifacts that predate
        the instruments snapshot skip compile gating instead of failing."""
        base = _bench_fixture(wrap=True)
        del base["parsed"]["instruments"]
        bp = _write(tmp_path / "base.json", base)
        cp = _write(tmp_path / "cand.json", _bench_fixture())
        assert regress.main([cp, "--baseline", bp]) == 0
        assert "no instruments snapshot" in capsys.readouterr().out

    def test_cli_verb_routes(self, tmp_path):
        from feddrift_tpu.cli import main
        p = _write(tmp_path / "b.json", _bench_fixture())
        assert main(["regress", p, "--baseline", p]) == 0
        slow = _write(tmp_path / "s.json", _bench_fixture(value=1.0))
        assert main(["regress", slow, "--baseline", p]) == 1

    def test_load_errors_exit_2(self, tmp_path):
        p = _write(tmp_path / "b.json", _bench_fixture())
        assert regress.main([str(tmp_path / "nope.json"),
                             "--baseline", p]) == 2
        bad = tmp_path / "bad.json"
        bad.write_text("not json at all")
        assert regress.main([str(bad), "--baseline", p]) == 2


# ----------------------------------------------------------------------
class TestXlaTraceGuard:
    def test_nested_trace_is_noop_and_event_emitted(self, tmp_path,
                                                    fresh_bus):
        """jax raises on nested start_trace; xla_trace must instead run
        the inner body without starting, and the OUTER capture completes
        with one profile_captured event."""
        import jax.numpy as jnp
        from feddrift_tpu.utils import tracing

        outer, inner = str(tmp_path / "o"), str(tmp_path / "i")
        with tracing.xla_trace(outer):
            with tracing.xla_trace(inner):       # no-op, must not raise
                x = jnp.ones((4,)) * 2
        assert float(x.sum()) == 8.0
        evs = fresh_bus.events("profile_captured")
        assert [e["trace_dir"] for e in evs] == [outer]
        assert tracing._trace_active is False    # guard released

    def test_reentry_after_capture(self, tmp_path, fresh_bus):
        from feddrift_tpu.utils import tracing

        for i in range(2):                       # sequential captures: fine
            with tracing.xla_trace(str(tmp_path / f"t{i}")):
                pass
        assert len(fresh_bus.events("profile_captured")) == 2


# ----------------------------------------------------------------------
class TestSchemaList:
    def test_list_mode_prints_taxonomy(self):
        from feddrift_tpu.obs.events import EVENT_KINDS
        out = subprocess.run(
            [sys.executable,
             os.path.join(ROOT, "scripts", "check_events_schema.py"),
             "--list"],
            capture_output=True, text=True)
        assert out.returncode == 0
        assert out.stdout.split() == sorted(EVENT_KINDS)
        for kind in ("program_cost", "profile_captured", "hbm_watermark"):
            assert kind in out.stdout.split()


# ----------------------------------------------------------------------
@pytest.mark.slow
class TestEndToEnd:
    def test_runner_emits_spans_costs_and_trace(self, tmp_path, capsys):
        """A real (tiny) run produces spans.jsonl + program_cost events,
        and `report --trace` exports a loadable timeline from them."""
        from feddrift_tpu.config import ExperimentConfig
        from feddrift_tpu.simulation.runner import Experiment

        costmodel.clear()
        d = str(tmp_path / "run")
        cfg = ExperimentConfig(
            dataset="sea", model="fnn", concept_drift_algo="win-1",
            train_iterations=2, comm_round=2, epochs=1, sample_num=16,
            batch_size=8, client_num_in_total=4, client_num_per_round=4,
            concept_num=2, frequency_of_the_test=1, report_client=0,
            cost_model="compiled", out_dir=d)
        Experiment(cfg, out_dir=d).run()

        span_rows = [json.loads(l) for l in open(os.path.join(
            d, "spans.jsonl"))]
        names = {r["name"] for r in span_rows}
        assert {"iteration", "train_round", "cluster"} <= names
        pc = costmodel.get("train_iteration_eval")
        assert pc is not None and pc.flops > 0 and pc.peak_hbm_bytes > 0

        from feddrift_tpu.cli import main
        assert main(["report", d, "--trace"]) == 0
        trace = json.load(open(os.path.join(d, "trace.json")))
        evs = trace["traceEvents"]
        xs = [e for e in evs if e["ph"] == "X"]
        instants = [e for e in evs if e["ph"] == "i"]
        assert {e["name"] for e in xs} >= {"iteration", "train_round"}
        assert any(e["name"] == "program_cost" for e in instants)
        assert any(e["name"] == "iteration_end" for e in instants)
        data_ts = [e["ts"] for e in evs if e["ph"] != "M"]
        assert data_ts == sorted(data_ts)
        # the cost-model section renders in the text report
        assert main(["report", d]) == 0
        assert "cost model" in capsys.readouterr().out

    def test_perf_gate(self):
        """scripts/perf_gate.sh: two warm smoke benches, cost-model field
        assertions, regress self-comparison + committed-baseline check."""
        out = subprocess.run(
            ["bash", os.path.join(ROOT, "scripts", "perf_gate.sh")],
            capture_output=True, text=True, timeout=1500)
        assert out.returncode == 0, (out.stdout[-2000:], out.stderr[-2000:])
        assert "perf_gate: OK" in out.stdout
