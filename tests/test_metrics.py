"""MetricsLogger unit tests (pure host logic, fast tier).

The logger carries the reference's wandb series names (SURVEY.md §5) into a
JSONL file; `truncate_from` is the resume-time guard against duplicated
rows (a run that crashed after its last checkpoint may have logged part of
the iteration that resume re-runs)."""

import json

from feddrift_tpu.utils.metrics import MetricsLogger


def _rows(path):
    with open(path) as f:
        return [json.loads(line) for line in f]


class TestTruncateFrom:
    def test_drops_rows_at_and_after_iteration(self, tmp_path):
        lg = MetricsLogger(str(tmp_path))
        for it in (0, 0, 1, 1, 2, 2):
            lg.log({"iteration": it, "round": it * 10, "Test/Acc": 0.5 + it})
        lg.truncate_from(2)

        path = tmp_path / "metrics.jsonl"
        assert [r["iteration"] for r in _rows(path)] == [0, 0, 1, 1]
        assert [r["iteration"] for r in lg.history] == [0, 0, 1, 1]

    def test_appends_cleanly_after_truncation(self, tmp_path):
        lg = MetricsLogger(str(tmp_path))
        lg.log({"iteration": 0, "Test/Acc": 0.5})
        lg.log({"iteration": 1, "Test/Acc": 0.6})
        lg.truncate_from(1)
        lg.log({"iteration": 1, "Test/Acc": 0.7})   # the re-run's row
        lg.close()

        rows = _rows(tmp_path / "metrics.jsonl")
        assert [(r["iteration"], r["Test/Acc"]) for r in rows] == \
            [(0, 0.5), (1, 0.7)]

    def test_noop_without_file(self):
        lg = MetricsLogger(None)
        lg.log({"iteration": 0, "Test/Acc": 0.5})
        lg.truncate_from(0)
        assert lg.history == []

    def test_rows_without_iteration_are_kept(self, tmp_path):
        lg = MetricsLogger(str(tmp_path))
        lg.log({"round": 0, "Test/Acc": 0.5})       # e.g. summary-ish rows
        lg.log({"iteration": 3, "Test/Acc": 0.6})
        lg.truncate_from(1)
        rows = _rows(tmp_path / "metrics.jsonl")
        assert len(rows) == 1 and "iteration" not in rows[0]
