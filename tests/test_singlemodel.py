"""Single-model baseline semantics (fast tier): retrain-window specs per
algorithm name (reference cont_one retrain_data arg,
run_fedavg_distributed_pytorch.sh:21)."""

import numpy as np

from feddrift_tpu.config import ExperimentConfig
from feddrift_tpu.core.pool import ModelPool
from feddrift_tpu.core.step import TrainStep, make_optimizer
from feddrift_tpu.data.registry import make_dataset
from feddrift_tpu.models import create_model


def _algo(name, **kw):
    import jax.numpy as jnp
    from feddrift_tpu.algorithms import make_algorithm
    cfg = ExperimentConfig(dataset="sea", model="fnn", concept_drift_algo=name,
                           train_iterations=3, sample_num=8, batch_size=4,
                           client_num_in_total=2, client_num_per_round=2, **kw)
    ds = make_dataset(cfg)
    module = create_model(cfg.model, ds, cfg)
    pool = ModelPool.create(module, jnp.asarray(ds.x[0, 0, :2]),
                            cfg.num_models, seed=0)
    step = TrainStep(pool.apply, make_optimizer("adam", cfg.lr, cfg.wd),
                     cfg.batch_size, cfg.epochs, ds.num_classes)
    return make_algorithm(cfg, ds, pool, step)


def _weights_at(algo, t):
    algo.begin_iteration(t)
    tw = np.asarray(algo.round_inputs(t, 0)[0])   # [1, C, T1]
    return tw[0, 0]                               # client 0's time weights


def test_win1_trains_on_current_step_only():
    w = _weights_at(_algo("win-1"), 2)
    assert w[2] > 0 and w[:2].sum() == 0 and w[3:].sum() == 0


def test_oblivious_trains_on_all_past_steps():
    """'oblivious' is the paper's drift-oblivious baseline: ONE model on ALL
    data — it must NOT inherit cfg.retrain_data's win-1 default (that bug
    made oblivious == win-1 trajectories bitwise-identical)."""
    w = _weights_at(_algo("oblivious"), 2)
    assert (w[:3] > 0).all() and w[3:].sum() == 0


def test_all_equals_oblivious_window():
    wa = _weights_at(_algo("all"), 2)
    wo = _weights_at(_algo("oblivious"), 2)
    np.testing.assert_array_equal(wa, wo)


def test_window_respects_retrain_data():
    w = _weights_at(_algo("window", retrain_data="win-2"), 2)
    assert (w[1:3] > 0).all() and w[0] == 0


def test_clusterfl_ignores_foreign_packed_args():
    """LegacyClusterFL's arg is a retrain spec; other algorithms' packed
    strings (incl. the config default) must fall back to win-1 instead of
    crashing in time_weights."""
    from feddrift_tpu.data.retrain import is_retrain_spec
    assert is_retrain_spec("win-3") and is_retrain_spec("all")
    assert not is_retrain_spec("H_A_C_1_10_0")
    # near-miss specs: right prefix, unparsable remainder (ADVICE r2)
    assert not is_retrain_spec("win-abc")
    assert not is_retrain_spec("weight-bogus")
    assert is_retrain_spec("weight-exp") and is_retrain_spec("weight-linear")
    # structurally invalid at the experiment's real dimensions
    assert not is_retrain_spec("sel-20", num_clients=10, total_steps=10)
    assert is_retrain_spec("sel-2", num_clients=10, total_steps=10)
    assert not is_retrain_spec("clientsel-[[0]]", num_clients=10,
                               total_steps=10)
    for arg in ("H_A_C_1_10_0", "", "cfl_0.4_win-1"):
        algo = _algo("clusterfl", concept_drift_algo_arg=arg, concept_num=2)
        assert algo.retrain == "win-1"
    algo = _algo("clusterfl", concept_drift_algo_arg="all", concept_num=2)
    assert algo.retrain == "all"
