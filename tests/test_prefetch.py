"""Host->device prefetcher (data/prefetch.py)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from feddrift_tpu.data.prefetch import TimeStepStream, prefetch_to_device


class TestPrefetchToDevice:
    def test_order_and_values(self):
        items = [np.full((4,), i, dtype=np.float32) for i in range(7)]
        out = list(prefetch_to_device(iter(items), size=2))
        assert len(out) == 7
        for i, arr in enumerate(out):
            assert isinstance(arr, jax.Array)
            np.testing.assert_array_equal(np.asarray(arr), items[i])

    def test_source_exception_propagates(self):
        def gen():
            yield np.zeros(2)
            raise RuntimeError("boom")
        it = prefetch_to_device(gen(), size=2)
        next(it)
        with pytest.raises(RuntimeError, match="boom"):
            next(it)

    def test_placement_exception_propagates(self):
        def bad_place(_):
            raise ValueError("cannot place")
        with pytest.raises(ValueError, match="cannot place"):
            list(prefetch_to_device(iter([np.zeros(2)]), place=bad_place))

    def test_invalid_size(self):
        with pytest.raises(ValueError):
            list(prefetch_to_device(iter([]), size=0))

    def test_custom_placement_sharding(self):
        from feddrift_tpu.parallel.mesh import client_sharding, make_mesh
        mesh = make_mesh(8)
        sh = client_sharding(mesh, 2)
        out = list(prefetch_to_device(
            (np.ones((8, 3), np.float32) * i for i in range(3)),
            place=lambda a: jax.device_put(a, sh)))
        assert all(o.sharding == sh for o in out)


class TestTimeStepStream:
    def test_streams_dataset_slices_sharded(self):
        from feddrift_tpu.config import ExperimentConfig
        from feddrift_tpu.data.registry import make_dataset
        from feddrift_tpu.parallel.mesh import make_mesh

        cfg = ExperimentConfig(dataset="sea", train_iterations=3,
                               client_num_in_total=8, client_num_per_round=8,
                               sample_num=16)
        ds = make_dataset(cfg)
        mesh = make_mesh(8)
        stream = TimeStepStream(ds, mesh)
        steps = list(stream.steps())
        assert len(steps) == ds.num_steps + 1
        for t, (x_t, y_t) in enumerate(steps):
            assert x_t.shape == (8, 16, *ds.feature_shape)
            np.testing.assert_array_equal(np.asarray(y_t), ds.y[:, t])
            # one client shard per device
            assert len(x_t.sharding.device_set) == 8

        # a consumer can run the eval program directly on streamed slices
        from feddrift_tpu.core.pool import ModelPool
        from feddrift_tpu.core.step import TrainStep, make_optimizer
        from feddrift_tpu.models import create_model
        module = create_model("fnn", ds, cfg)
        pool = ModelPool.create(module, jnp.asarray(ds.x[0, 0, :2]), 2, seed=0)
        step = TrainStep(pool.apply, make_optimizer("adam", 0.01, 0.0),
                         8, 1, ds.num_classes)
        fm = jnp.ones((2, *ds.feature_shape), jnp.float32)
        for x_t, y_t in stream.steps(stop=2):
            correct, _, total = step.acc_matrix(pool.params, x_t, y_t, fm)
            assert correct.shape == (2, 8)
