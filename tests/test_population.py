"""Population-scale cohort rounds (ISSUE 6): client registry, seeded
cohort sampling, deadline/quorum participation, straggler + churn chaos.

The load-bearing guarantees under test:

- bitwise equivalence of the full-participation cohort path with the
  legacy dense path, on both the per-round and fused programs;
- compile-count invariance: growing the population 10^2 -> 10^4 at fixed
  cohort triggers zero steady-state recompiles (PR 1 detector);
- unknown != absent: an unsampled member never accrues absence evidence
  (the FailureDetector false-suspicion regression, and the registry's
  generalization of it);
- a killed + resumed run replays the identical cohort schedule;
- chaos e2e: 20% stragglers + churn over a 10^3 population completes
  within 0.10 of the fault-free run, with evidence events.
"""

from __future__ import annotations

import dataclasses
import json
import os

import numpy as np
import pytest

from feddrift_tpu import obs
from feddrift_tpu.config import ExperimentConfig
from feddrift_tpu.obs.alerts import AlertMonitor, default_rules
from feddrift_tpu.platform.faults import (ChurnSchedule, FailureDetector,
                                          StragglerInjector)
from feddrift_tpu.platform.registry import ClientRegistry, CohortSampler
from feddrift_tpu.resilience.participation import ParticipationPolicy


@pytest.fixture(autouse=True)
def _fresh_bus():
    """Memory-only event bus per test so event asserts are hermetic."""
    obs.configure(None)
    yield
    obs.configure(None)


def _events(kind):
    return obs.get_bus().events(kind)


# ----------------------------------------------------------------------
class TestClientRegistry:
    def test_absence_only_for_sampled(self):
        reg = ClientRegistry(6, num_steps=4)
        reg.record_round([0, 1, 2], [True, False, True], 0)
        assert reg.absent_streak.tolist() == [0, 1, 0, 0, 0, 0]
        # member 1 NOT sampled this round: its streak must not move
        reg.record_round([0, 3, 4], [True, True, True], 1)
        assert reg.absent_streak[1] == 1
        # sampled-but-silent again: accrues; on-time resets
        reg.record_round([1, 2], [False, True], 2)
        assert reg.absent_streak[1] == 2
        reg.record_round([1], [True], 3)
        assert reg.absent_streak[1] == 0
        assert reg.suspected(2).tolist() == []

    def test_reliability_ewma_and_rejoin_reset(self):
        reg = ClientRegistry(4, num_steps=3)
        for r in range(5):
            reg.record_round([0, 1], [True, False], r)
        assert reg.reliability[0] == pytest.approx(1.0)
        assert reg.reliability[1] < 0.5
        assert reg.absent_streak[1] == 5
        reg.apply_churn(joins=[], leaves=[1], iteration=1)
        assert not reg.active[1]
        reg.apply_churn(joins=[1], leaves=[], iteration=2)
        # a rejoin is a fresh start: old absence evidence cleared
        assert reg.active[1] and reg.absent_streak[1] == 0
        assert len(_events("client_leave")) == 1
        assert _events("client_join")[0]["clients"] == [1]

    def test_writeback_history_and_remaps(self):
        reg = ClientRegistry(5, num_steps=4)
        reg.writeback(0, np.array([0, 1, 2]), np.array([0, 1, 1]))
        reg.writeback(1, np.array([0, 3, -1]), np.array([2, 1, 7]))
        assert reg.cluster.tolist() == [2, 1, 1, 1, -1]
        assert reg.assign_hist[0].tolist() == [0, 2, -1, -1]
        assert reg.reserved_models() == {1, 2}
        reg.remap_model("merge", 0, 1)          # 1 -> 0 everywhere
        assert reg.cluster.tolist() == [2, 0, 0, 0, -1]
        assert reg.assign_hist[1, 0] == 0
        reg.remap_model("clear", 2)             # model 2 reused: unknown
        assert reg.cluster[0] == -1 and reg.assign_hist[0, 1] == -1

    def test_cohort_view_phantom_rows(self):
        reg = ClientRegistry(3, num_steps=3)
        reg.writeback(0, np.array([2]), np.array([1]), np.array([0.75]))
        hist, arm = reg.cohort_view(np.array([2, -1]))
        assert hist[0].tolist() == [1, -1, -1]
        assert hist[1].tolist() == [-1, -1, -1]
        assert arm[0] == pytest.approx(0.75) and np.isnan(arm[1])

    def test_state_roundtrip(self):
        reg = ClientRegistry(4, num_steps=3)
        reg.record_round([0, 1], [True, False], 0)
        reg.writeback(0, np.array([0, 1]), np.array([0, 1]))
        reg2 = ClientRegistry(4, num_steps=3)
        reg2.load_state_dict(reg.state_dict())
        for k, v in reg.state_dict().items():
            np.testing.assert_array_equal(np.asarray(v),
                                          np.asarray(reg2.state_dict()[k]))


class TestCohortSampler:
    def test_deterministic_sorted_schedule(self):
        reg = ClientRegistry(50, num_steps=3)
        s1 = CohortSampler(reg, 8, seed=3)
        s2 = CohortSampler(ClientRegistry(50, num_steps=3), 8, seed=3)
        for t in range(4):
            a, b = s1.sample(t), s2.sample(t)
            np.testing.assert_array_equal(a, b)
            assert (np.diff(a) > 0).all()       # sorted, no repeats
        assert not np.array_equal(s1.sample(0), s1.sample(1))

    def test_full_population_is_identity(self):
        reg = ClientRegistry(6, num_steps=3)
        assert CohortSampler(reg, 6, seed=0).sample(2).tolist() == \
            list(range(6))

    def test_excludes_inactive_and_pads(self):
        reg = ClientRegistry(6, num_steps=3)
        reg.apply_churn([], [0, 1, 2, 3], iteration=0)
        members = CohortSampler(reg, 4, seed=0).sample(0)
        assert members[:2].tolist() == [4, 5]
        assert members[2:].tolist() == [-1, -1]
        ev = _events("cohort_sampled")[-1]
        assert ev["sampled"] == 2 and ev["slots"] == 4 and ev["active"] == 2


class TestStragglerChurn:
    def test_straggler_deterministic_and_slow_bias(self):
        a = StragglerInjector(200, prob=0.1, slow_frac=0.3, deadline=1.0,
                              seed=7)
        b = StragglerInjector(200, prob=0.1, slow_frac=0.3, deadline=1.0,
                              seed=7)
        np.testing.assert_array_equal(a.latencies(5), b.latencies(5))
        miss = np.zeros(200)
        for r in range(30):
            miss += a.latencies(r) > 1.0
        assert miss[a.slow].mean() > 20         # ~0.9 miss rate
        assert miss[~a.slow].mean() < 8         # ~0.1 miss rate

    def test_churn_deterministic_flap(self):
        c = ChurnSchedule(100, leave_prob=0.3, join_prob=0.4, seed=1)
        active = np.ones(100, dtype=bool)
        j1, l1 = c.events(0, active)
        j2, l2 = ChurnSchedule(100, 0.3, 0.4, seed=1).events(0, active)
        np.testing.assert_array_equal(l1, l2)
        assert j1.size == 0 and l1.size > 0     # all active: only leaves
        active[l1] = False
        j3, _ = c.events(1, active)
        assert j3.size > 0                      # flap: leavers can rejoin


class TestParticipationPolicy:
    def test_deadline_masks_stragglers(self):
        pol = ParticipationPolicy(deadline=1.0, quorum_frac=0.5,
                                  cohort_size=4)
        members = np.array([3, 5, 9, -1])
        out = pol.close_round(members, np.array([0.2, 1.7, 0.4, 0.1]), 11)
        assert out.on_time.tolist() == [True, False, True, False]
        assert not out.degraded and out.stragglers.tolist() == [5]
        ev = _events("straggler_masked")[-1]
        assert ev["clients"] == [5] and ev["part_round"] == 11
        assert not _events("round_degraded")

    def test_quorum_degrades_gracefully(self):
        pol = ParticipationPolicy(deadline=1.0, quorum_frac=0.75,
                                  cohort_size=4)
        out = pol.close_round(np.array([1, 2, 3, 4]),
                              np.array([0.2, 9.0, 9.0, 9.0]), 3)
        assert out.degraded and out.quorum == 3
        ev = _events("round_degraded")[-1]
        assert ev["on_time"] == 1 and ev["quorum"] == 3
        assert sorted(ev["stragglers"]) == [2, 3, 4]

    def test_no_latencies_means_everyone_on_time(self):
        pol = ParticipationPolicy(1.0, 0.5, 4)
        out = pol.close_round(np.array([1, 2, -1, -1]), None, 0)
        assert out.on_time.tolist() == [True, True, False, False]


# ----------------------------------------------------------------------
class TestFailureDetectorSampling:
    """Regression: absence semantics under client sampling — an unsampled
    client must never accrue absence/suspicion (false-suspicion bug);
    only sampled-but-silent clients do."""

    def test_unsampled_never_suspected(self):
        det = FailureDetector(6, patience=2)
        observed = np.zeros(6, dtype=bool)
        observed[[0, 1]] = True
        part = np.zeros(6)
        part[[0, 1]] = 1.0
        for _ in range(5):      # clients 2-5 unsampled for 5 rounds
            det.observe(part, observed)
        assert det.suspected.tolist() == []
        assert det.absent_streak[2:].tolist() == [0, 0, 0, 0]

    def test_sampled_but_silent_is_suspected(self):
        det = FailureDetector(4, patience=2)
        observed = np.array([True, True, False, False])
        part = np.array([1.0, 0.0, 0.0, 0.0])   # 1 polled and silent
        det.observe(part, observed)
        det.observe(part, observed)
        assert det.suspected.tolist() == [1]

    def test_observe_many_carries_observed(self):
        det = FailureDetector(4, patience=2)
        masks = np.zeros((3, 4))
        masks[:, 0] = 1.0
        observed = np.zeros((3, 4), dtype=bool)
        observed[:, :2] = True                   # only 0, 1 ever polled
        det.observe_many(masks, observed)
        assert det.suspected.tolist() == [1]     # 2, 3 stay unknown
        # legacy call without observed = every client polled every round
        det2 = FailureDetector(4, patience=2)
        det2.observe_many(masks)
        assert det2.suspected.tolist() == [1, 2, 3]


class TestQuorumMissAlert:
    def _degraded(self, it):
        return {"kind": "round_degraded", "iteration": it, "round": it,
                "on_time": 1, "quorum": 5, "stragglers": [1, 2]}

    def test_fires_on_repeat(self):
        mon = AlertMonitor(rules=default_rules(quorum_miss_threshold=2,
                                               quorum_miss_window=3))
        mon.observe(self._degraded(1))
        assert [a["rule"] for a in mon.alerts] == []
        mon.observe(self._degraded(1))
        assert [a["rule"] for a in mon.alerts] == ["quorum_miss"]
        assert mon.alerts[0]["severity"] == "crit"
        assert mon.alerts[0]["count"] == 2

    def test_stays_quiet_outside_window(self):
        mon = AlertMonitor(rules=default_rules(quorum_miss_threshold=2,
                                               quorum_miss_window=2))
        mon.observe(self._degraded(1))
        mon.observe(self._degraded(8))           # first fell out of window
        assert mon.alerts == []

    def test_cooldown(self):
        mon = AlertMonitor(rules=default_rules(quorum_miss_threshold=1,
                                               quorum_miss_window=3))
        mon.observe(self._degraded(1))
        mon.observe(self._degraded(2))           # within cooldown=2
        mon.observe(self._degraded(3))           # cooldown elapsed
        assert [a["rule"] for a in mon.alerts] == ["quorum_miss"] * 2


# ----------------------------------------------------------------------
class TestConfigValidation:
    def test_population_smaller_than_cohort_rejected(self):
        with pytest.raises(ValueError, match="population_size"):
            ExperimentConfig(population_size=5, cohort_size=8)

    def test_dense_fault_injection_rejected(self):
        with pytest.raises(ValueError, match="fault injection"):
            ExperimentConfig(population_size=100, fault_dropout_prob=0.1)

    def test_byzantine_rejected(self):
        with pytest.raises(ValueError, match="byzantine"):
            ExperimentConfig(population_size=100, byzantine_clients="0,1")

    def test_cohort_incapable_algorithm_rejected(self):
        from feddrift_tpu.simulation.runner import Experiment
        cfg = ExperimentConfig(
            dataset="sea", model="fnn", concept_drift_algo="aue",
            population_size=20, cohort_size=4, train_iterations=2,
            comm_round=2, sample_num=8, batch_size=8, report_client=0)
        with pytest.raises(ValueError, match="cohort-capable"):
            Experiment(cfg)


# ----------------------------------------------------------------------
def _base_cfg(**overrides):
    base = dict(
        dataset="sine", model="fnn", concept_num=2,
        concept_drift_algo="softcluster", concept_drift_algo_arg="mmacc_10",
        client_num_in_total=5, client_num_per_round=5,
        train_iterations=3, comm_round=4, epochs=2, sample_num=24,
        batch_size=12, frequency_of_the_test=2, report_client=0,
        checkpoint_every_iteration=False, seed=0)
    base.update(overrides)
    return ExperimentConfig(**base)


def _run(cfg, out_dir=None):
    from feddrift_tpu.simulation.runner import Experiment
    exp = Experiment(cfg, out_dir=out_dir)
    exp.run()
    return exp


def _history(exp):
    """metrics.jsonl rows minus wall-clock noise."""
    return [{k: v for k, v in row.items() if k != "_ts"}
            for row in exp.logger.history]


def _leaves(params):
    import jax
    return jax.tree_util.tree_leaves(params)


class TestPopulationRuns:
    @pytest.mark.parametrize("chunk_rounds", [False, True],
                             ids=["per_round", "fused"])
    def test_full_participation_bitwise_matches_dense(self, chunk_rounds):
        """population == cohort, no chaos: the cohort path must reproduce
        the legacy dense trajectory bit for bit on both program paths."""
        dense = _run(_base_cfg(chunk_rounds=chunk_rounds))
        pop = _run(_base_cfg(chunk_rounds=chunk_rounds,
                             population_size=5, cohort_size=5))
        assert _history(pop) == _history(dense)
        for a, b in zip(_leaves(dense.pool.params), _leaves(pop.pool.params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_all_degraded_rounds_keep_params(self):
        """Every round below quorum: params must come out of the iteration
        exactly as they went in (the all-zero mask is a masked no-op)."""
        import jax
        from feddrift_tpu.simulation.runner import Experiment
        cfg = _base_cfg(population_size=40, cohort_size=5,
                        straggler_prob=0.6, quorum_frac=1.0,
                        train_iterations=1)
        exp = Experiment(cfg)
        before = [np.asarray(l).copy() for l in _leaves(exp.pool.params)]
        exp.run()
        degraded = _events("round_degraded")
        assert len(degraded) == cfg.comm_round   # every round missed quorum
        for a, b in zip(before, _leaves(exp.pool.params)):
            np.testing.assert_array_equal(a, np.asarray(b))

    def test_stragglers_are_masked_not_fatal(self):
        cfg = _base_cfg(population_size=40, cohort_size=5,
                        cohort_overprovision=2, straggler_prob=0.3)
        exp = _run(cfg)
        assert _events("straggler_masked")
        assert not _events("round_degraded")     # overprovision held quorum
        assert exp.logger.last("Test/Acc") is not None
        # registry saw the misses: stragglers' reliability dipped
        assert exp.registry.summary()["mean_reliability"] < 1.0

    def test_resume_replays_cohort_schedule(self, tmp_path):
        """kill -> --auto_resume must draw the identical cohorts and land
        on the identical metrics (sampler is a pure fn of (seed, t) and
        the registry rides in the checkpoint)."""
        from feddrift_tpu.simulation.runner import Experiment

        def cohorts(run_dir):
            evs = [json.loads(l)
                   for l in open(os.path.join(run_dir, "events.jsonl"))]
            return [(e["iteration"], e["members"]) for e in evs
                    if e["kind"] == "cohort_sampled"]

        cfg = _base_cfg(population_size=30, cohort_size=5,
                        straggler_prob=0.2, churn_leave_prob=0.05,
                        churn_join_prob=0.05, train_iterations=4,
                        checkpoint_every_iteration=True)
        full_dir = str(tmp_path / "full")
        full = _run(cfg, out_dir=full_dir)

        part_dir = str(tmp_path / "resumed")
        exp = Experiment(cfg, out_dir=part_dir)
        exp.run_iteration(0)
        exp.run_iteration(1)
        exp.events.close()                       # simulate the kill
        resumed = Experiment.resume(cfg, part_dir)
        assert resumed.start_iteration == 2
        resumed.run()

        assert cohorts(part_dir) == cohorts(full_dir)
        assert _history(resumed)[-1] == _history(full)[-1]
        for a, b in zip(_leaves(full.pool.params),
                        _leaves(resumed.pool.params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_compile_count_invariance_over_population(self):
        """10^2 -> 10^4 population at fixed cohort: identical program
        signatures, zero steady-state recompiles (the PR 1 detector)."""
        compiles = {}
        for population in (100, 10000):
            obs.configure(None)
            obs.registry().reset()
            cfg = _base_cfg(population_size=population, cohort_size=5,
                            cohort_overprovision=1, straggler_prob=0.1,
                            churn_leave_prob=0.01, churn_join_prob=0.02,
                            train_iterations=3, sample_num=12, batch_size=8)
            _run(cfg)
            snap = obs.registry().snapshot()
            assert not any(k.startswith("jit_recompiles")
                           for k in snap), snap
            compiles[population] = {k: v for k, v in snap.items()
                                    if k.startswith("jit_compiles")}
        assert compiles[100] == compiles[10000]

    def test_churn_emits_membership_events(self):
        cfg = _base_cfg(population_size=60, cohort_size=5,
                        churn_leave_prob=0.2, churn_join_prob=0.3,
                        train_iterations=3)
        exp = _run(cfg)
        assert _events("client_leave") and _events("client_join")
        summ = exp.registry.summary()
        assert 0 < summ["active"] <= 60


@pytest.mark.slow
class TestChaosEndToEnd:
    def test_population_chaos_within_tolerance_of_clean(self):
        """Acceptance: 10^3 population, 20% stragglers + churn completes
        within 0.10 final accuracy of the fault-free run, with
        cohort_sampled + straggler_masked evidence."""
        base = dict(
            dataset="sea", model="fnn", concept_num=4,
            concept_drift_algo="softcluster",
            concept_drift_algo_arg="H_A_C_1_10_0",
            population_size=1000, cohort_size=10, cohort_overprovision=2,
            train_iterations=4, comm_round=6, epochs=3, sample_num=40,
            batch_size=20, frequency_of_the_test=3, lr=0.03,
            report_client=0, checkpoint_every_iteration=False, seed=0)
        clean = _run(ExperimentConfig(**base))
        obs.configure(None)
        chaotic = _run(ExperimentConfig(
            **base, straggler_prob=0.2, straggler_slow_frac=0.05,
            churn_leave_prob=0.02, churn_join_prob=0.05))
        assert _events("cohort_sampled")
        assert _events("straggler_masked")
        acc_clean = clean.logger.last("Test/Acc")
        acc_chaos = chaotic.logger.last("Test/Acc")
        assert acc_chaos >= acc_clean - 0.10, (acc_clean, acc_chaos)


class TestPopscaleRegressGate:
    def test_throughput_tolerance_and_zero_recompile_gate(self):
        from feddrift_tpu.obs.regress import compare
        base = {"popscale": [{"population": 100, "rounds_per_sec": 100.0,
                              "steady_recompiles": 0}]}
        ok = compare({"popscale": [{"population": 100,
                                    "rounds_per_sec": 95.0,
                                    "steady_recompiles": 0}]}, base)
        ps = {r["metric"]: r for r in ok if r["metric"].startswith("popscale")}
        assert ps["popscale[100].rounds_per_s"]["status"] == "ok"
        assert ps["popscale[100].steady_recompiles"]["status"] == "ok"
        bad = compare({"popscale": [{"population": 100,
                                     "rounds_per_sec": 50.0,
                                     "steady_recompiles": 2}]}, base)
        ps = {r["metric"]: r for r in bad
              if r["metric"].startswith("popscale")}
        assert ps["popscale[100].rounds_per_s"]["status"] == "regress"
        # the zero-recompile gate is absolute, not tolerance-based
        assert ps["popscale[100].steady_recompiles"]["status"] == "regress"

    def test_committed_artifact_passes_self_regress(self):
        from feddrift_tpu.obs.regress import compare, load_bench
        art = load_bench(os.path.join(os.path.dirname(__file__), "..",
                                      "POPSCALE_r06.json"))
        rows = compare(art, art)
        assert all(r["status"] != "regress" for r in rows)
        assert any(r["metric"].startswith("popscale") for r in rows)


class TestReportParticipation:
    def test_report_renders_participation_section(self, tmp_path):
        from feddrift_tpu.obs.report import render, summarize
        cfg = _base_cfg(concept_drift_algo="win-1", concept_num=1,
                        population_size=30, cohort_size=4,
                        cohort_overprovision=1, straggler_prob=0.3,
                        churn_leave_prob=0.1, churn_join_prob=0.1,
                        train_iterations=2)
        run_dir = str(tmp_path / "run")
        _run(cfg, out_dir=run_dir)
        summary = summarize(run_dir)
        part = summary["participation"]
        assert part["cohorts"]["population"] == 30
        assert part["stragglers"]["masked_total"] > 0
        assert part["churn"]["joins"] + part["churn"]["leaves"] > 0
        text = render(summary)
        assert "participation:" in text
        assert "stragglers:" in text
