"""Pallas flash-attention kernel tests (interpret mode on the CPU mesh)."""

import jax
import numpy as np
import pytest

from tests.test_ring_attention import naive_attention, _qkv


class TestFlashAttention:
    @pytest.mark.parametrize("causal", [True, False])
    @pytest.mark.parametrize("L", [64, 100])
    def test_matches_naive(self, causal, L):
        from feddrift_tpu.parallel.pallas_attention import flash_attention
        q, k, v = _qkv(jax.random.PRNGKey(0), L=L)
        out = flash_attention(q, k, v, causal, 32, 32, True)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(naive_attention(q, k, v, causal)),
            atol=1e-5)

    def test_gradients_match_naive(self):
        from feddrift_tpu.parallel.pallas_attention import flash_attention
        q, k, v = _qkv(jax.random.PRNGKey(1), L=64)

        def loss_flash(q, k, v):
            return flash_attention(q, k, v, True, 32, 32, True).sum()

        def loss_naive(q, k, v):
            return naive_attention(q, k, v, True).sum()

        gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        gn = jax.grad(loss_naive, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gf, gn):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-4)

    def test_jit_and_small_blocks(self):
        from feddrift_tpu.parallel.pallas_attention import flash_attention
        q, k, v = _qkv(jax.random.PRNGKey(2), B=1, H=1, L=24, D=8)
        f = jax.jit(lambda q, k, v: flash_attention(q, k, v, True, 16, 16,
                                                    True))
        out = f(q, k, v)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(naive_attention(q, k, v, True)),
            atol=1e-5)
