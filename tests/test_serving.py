"""Cluster-routed inference engine tests (platform/serving.py).

The serving read path has four load-bearing invariants, each pinned here:

- routing equals the trainer's ground truth (``ClientRegistry.cluster``
  with ``assign_hist`` fallback) — a client is answered by ITS cluster
  model, never slot 0;
- a coalesced mixed-cluster micro-batch is BITWISE identical to serving
  each request alone through ``pool.apply`` — batching is a pure
  throughput transform, not a numerics change;
- bucketed admission never recompiles at steady state: every bucket is
  compiled once in warm-up, then arbitrary batch sizes replay known
  signatures (the PR 1 compile detector is the witness);
- hot swaps under concurrent load are atomic: every answer is consistent
  with exactly ONE published generation (no torn params, no
  params/routing skew).
"""

import json
import threading
import time
from concurrent.futures import ThreadPoolExecutor

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from feddrift_tpu.config import ExperimentConfig
from feddrift_tpu.core.pool import ModelPool
from feddrift_tpu.data.registry import make_dataset
from feddrift_tpu.models import create_model
from feddrift_tpu.platform.serving import (
    DeadlineExceededError, EngineOverloaded, EngineStopped,
    InferenceEngine, MalformedRequestError, RoutingTable,
    UnknownClientError)


def _pool(M=3, identical=False):
    cfg = ExperimentConfig(dataset="sea", train_iterations=2, sample_num=16)
    ds = make_dataset(cfg)
    mod = create_model("fnn", ds, cfg)
    return ModelPool.create(mod, jnp.zeros((2, 3)), M, seed=7,
                            identical=identical)


def _engine(pool, table, **kw):
    kw.setdefault("buckets", (1, 2, 4))
    kw.setdefault("max_wait_s", 0.002)
    return InferenceEngine(pool, RoutingTable(table), **kw)


class TestRoutingTable:
    def test_from_registry_matches_ground_truth(self):
        from feddrift_tpu.platform.registry import ClientRegistry
        reg = ClientRegistry(population=5, num_steps=4)
        # client 0/1: live assignment wins
        reg.cluster[0], reg.cluster[1] = 2, 0
        reg.assign_hist[0] = [0, 0, 1, 2]
        # client 2: live assignment cleared -> last known history entry
        reg.cluster[2] = -1
        reg.assign_hist[2] = [1, 2, -1, -1]
        # client 3: never assigned anywhere -> unroutable
        # client 4: history only
        reg.assign_hist[4] = [-1, 0, -1, -1]
        rt = RoutingTable.from_registry(reg)
        assert rt.route(0) == 2 and rt.route(1) == 0
        assert rt.route(2) == 2        # last non-negative hist entry
        assert rt.route(4) == 0
        with pytest.raises(UnknownClientError):
            rt.route(3)

    def test_out_of_population(self):
        rt = RoutingTable([0, 1])
        with pytest.raises(UnknownClientError):
            rt.route(2)
        with pytest.raises(UnknownClientError):
            rt.route(-1)


class TestBatchParity:
    def test_mixed_cluster_batch_bitwise_equals_per_request(self):
        pool = _pool(M=3)
        table = [0, 1, 2, 1, 0, 2, 2, 1]
        eng = _engine(pool, table).start()
        try:
            eng.warmup()
            rng = np.random.RandomState(0)
            xs = rng.standard_normal((8, 3)).astype(np.float32)
            with ThreadPoolExecutor(max_workers=8) as ex:
                futs = [ex.submit(eng.submit, c, xs[c]) for c in range(8)]
                results = [f.result(timeout=30) for f in futs]
            for c, r in enumerate(results):
                assert r.model == table[c]
                expect = pool.apply(pool.slot(table[c]), xs[c][None])[0]
                np.testing.assert_array_equal(r.logits, np.asarray(expect))
        finally:
            eng.close()


class TestZeroRecompiles:
    def test_bucketed_traffic_never_recompiles(self):
        from feddrift_tpu import obs

        def serve_counts():
            snap = obs.registry().snapshot()
            comp = sum(v for k, v in snap.items()
                       if k.startswith('jit_compiles{fn="serve_forward'))
            rec = sum(v for k, v in snap.items()
                      if k.startswith('jit_recompiles{fn="serve_forward'))
            return comp, rec

        pool = _pool(M=2)
        eng = _engine(pool, [0, 1, 0, 1, 0, 1], buckets=(1, 2, 4)).start()
        try:
            comp0, rec0 = serve_counts()
            eng.warmup()
            comp1, rec1 = serve_counts()
            assert comp1 - comp0 == 3       # one program per bucket
            assert rec1 == rec0
            # mixed batch sizes (1..6 concurrent) all pad to known buckets
            rng = np.random.RandomState(1)
            for n in (1, 2, 3, 4, 5, 6):
                with ThreadPoolExecutor(max_workers=n) as ex:
                    futs = [ex.submit(eng.submit, c % 6,
                                      rng.standard_normal(3)
                                         .astype(np.float32))
                            for c in range(n)]
                    for f in futs:
                        f.result(timeout=30)
            # a swap must replay the same signatures too (committed-ness
            # of the placed params matches warm-up)
            eng.swap(params=jax.tree_util.tree_map(lambda p: p + 1.0,
                                                   pool.params))
            eng.submit(0, np.zeros(3, np.float32))
            comp2, rec2 = serve_counts()
            assert comp2 == comp1, "steady state compiled a new program"
            assert rec2 == rec1, "steady state recompiled"
        finally:
            eng.close()


class TestHotSwap:
    def test_no_torn_params_under_concurrent_load(self):
        pool = _pool(M=2)
        table = [0, 1, 0, 1]
        eng = _engine(pool, table).start()
        try:
            eng.warmup()
            params_a = pool.params
            params_b = jax.tree_util.tree_map(lambda p: p + 1.0, params_a)
            x = np.ones(3, np.float32)
            # expected logits per (tag, model) — v1 serves A
            expect = {}
            for tag, params in (("A", params_a), ("B", params_b)):
                for m in range(2):
                    one = jax.tree_util.tree_map(lambda p: p[m], params)
                    expect[tag, m] = np.asarray(
                        pool.apply(one, x[None])[0])
            tag_of = {1: "A"}
            stop = threading.Event()

            def swapper():
                flip = 0
                while not stop.is_set():
                    flip += 1
                    p = params_b if flip % 2 else params_a
                    v = eng.swap(params=p, reason="test")
                    tag_of[v] = "B" if flip % 2 else "A"

            th = threading.Thread(target=swapper, daemon=True)
            th.start()
            try:
                with ThreadPoolExecutor(max_workers=8) as ex:
                    futs = [ex.submit(eng.submit, c % 4, x)
                            for c in range(200)]
                    results = [f.result(timeout=30) for f in futs]
            finally:
                stop.set()
                th.join(timeout=10)
            for c, r in enumerate(results):
                assert r.model == table[c % 4]
                tag = tag_of[r.version]
                np.testing.assert_array_equal(
                    r.logits, expect[tag, r.model],
                    err_msg=f"torn read: version {r.version} ({tag}) "
                            f"model {r.model}")
        finally:
            eng.close()

    def test_merge_reroutes_to_surviving_lineage(self):
        pool = _pool(M=3)
        eng = _engine(pool, [0, 1, 2]).start()
        try:
            eng.warmup()
            v = eng.apply_cluster_event(
                {"kind": "cluster_merge", "base": 0, "merged": 1})
            assert v == 2
            assert eng.submit(1, np.zeros(3, np.float32)).model == 0
            assert eng.submit(2, np.zeros(3, np.float32)).model == 2
        finally:
            eng.close()

    def test_split_moves_clients_and_copies_parent_slot(self):
        pool = _pool(M=3)
        eng = _engine(pool, [0, 0, 0]).start()
        try:
            eng.warmup()
            eng.apply_cluster_event(
                {"kind": "cluster_split", "model": 0, "new_model": 2,
                 "clients_kept": [0], "clients_moved": [1, 2]})
            x = np.ones(3, np.float32)
            r_kept, r_moved = eng.submit(0, x), eng.submit(1, x)
            assert r_kept.model == 0 and r_moved.model == 2
            # child slot inherits the parent's params until retrained
            np.testing.assert_array_equal(r_kept.logits, r_moved.logits)
        finally:
            eng.close()

    def test_delete_makes_clients_unroutable(self):
        pool = _pool(M=2)
        eng = _engine(pool, [0, 1]).start()
        try:
            eng.warmup()
            eng.apply_cluster_event(
                {"kind": "cluster_delete", "model": 1, "reason": "test"})
            with pytest.raises(UnknownClientError):
                eng.submit(1, np.zeros(3, np.float32))
            assert eng.submit(0, np.zeros(3, np.float32)).model == 0
        finally:
            eng.close()

    def test_broker_feed_applies_events(self):
        from feddrift_tpu.comm.pubsub import Broker
        pool = _pool(M=2)
        eng = _engine(pool, [0, 0]).start()
        broker = Broker()
        try:
            eng.warmup()
            eng.attach_broker(broker, topic="serve/cluster")
            broker.publish("serve/cluster", json.dumps(
                {"kind": "cluster_assign", "assignment": [1, 1]}))
            deadline = 50
            while eng.version < 2 and deadline:
                threading.Event().wait(0.05)
                deadline -= 1
            assert eng.version >= 2
            assert eng.submit(0, np.zeros(3, np.float32)).model == 1
        finally:
            eng.close()


class TestClusterEventSequences:
    """Multi-event lifecycles over ``apply_cluster_event``: the routing
    and params state must stay coherent across chained rewires, not just
    after a single one."""

    def test_split_then_merge_same_slot_roundtrips(self):
        pool = _pool(M=3)
        eng = _engine(pool, [0, 0, 0]).start()
        try:
            eng.warmup()
            eng.apply_cluster_event(
                {"kind": "cluster_split", "model": 0, "new_model": 2,
                 "clients_kept": [0], "clients_moved": [1, 2]})
            assert eng.submit(1, np.zeros(3, np.float32)).model == 2
            # the split's child is reabsorbed into its parent slot
            eng.apply_cluster_event(
                {"kind": "cluster_merge", "base": 0, "merged": 2})
            x = np.ones(3, np.float32)
            for c in range(3):
                r = eng.submit(c, x)
                assert r.model == 0
                expect = pool.apply(pool.slot(0), x[None])[0]
                np.testing.assert_array_equal(r.logits,
                                              np.asarray(expect))
        finally:
            eng.close()

    def test_delete_under_live_load_degrades_to_unroutable(self):
        pool = _pool(M=2)
        eng = _engine(pool, [0, 1, 1, 1]).start()
        try:
            eng.warmup()
            x = np.zeros(3, np.float32)
            outcomes = []

            def hammer(c):
                for _ in range(40):
                    try:
                        outcomes.append(("ok", eng.submit(c, x).model))
                    except UnknownClientError:
                        outcomes.append(("unroutable", None))

            with ThreadPoolExecutor(max_workers=3) as ex:
                futs = [ex.submit(hammer, c) for c in (1, 2, 3)]
                eng.apply_cluster_event(
                    {"kind": "cluster_delete", "model": 1,
                     "reason": "test"})
                for f in futs:
                    f.result(timeout=30)
            # every in-flight request either answered by the still-live
            # generation's model 1 or cleanly refused — never crashed,
            # never misrouted to another slot
            assert all(m == 1 for kind, m in outcomes if kind == "ok")
            # after the swap the clients are durably unroutable...
            with pytest.raises(UnknownClientError):
                eng.submit(2, x)
            # ...and untouched clients keep being served
            assert eng.submit(0, x).model == 0
        finally:
            eng.close()

    def test_event_replay_after_broker_reconnect(self):
        from feddrift_tpu.comm.netbroker import (NetworkBroker,
                                                 NetworkBrokerClient)
        from feddrift_tpu.resilience import (ReconnectingBrokerClient,
                                             RetryPolicy)
        import time as _time

        broker = NetworkBroker()
        host, port = broker.host, broker.port
        cli = ReconnectingBrokerClient(
            lambda: NetworkBrokerClient(host, port),
            retry=RetryPolicy(base_delay=0.05, max_delay=0.2,
                              max_attempts=60, deadline_s=30, seed=0),
            ack_timeout=0.2)
        pool = _pool(M=2)
        eng = _engine(pool, [0, 0]).start()
        broker2 = None
        try:
            eng.warmup()
            eng.attach_broker(cli, topic="serve/cluster")
            cli.publish("serve/cluster", json.dumps(
                {"kind": "cluster_assign", "assignment": [1, 1]}))
            deadline = _time.monotonic() + 30
            while eng.version < 2 and _time.monotonic() < deadline:
                _time.sleep(0.05)
            assert eng.submit(0, np.zeros(3, np.float32)).model == 1

            broker.close()                   # broker dies mid-stream
            _time.sleep(0.2)
            cli.publish("serve/cluster", json.dumps(
                {"kind": "cluster_assign", "assignment": [0, 0]}))
            broker2 = NetworkBroker(host=host, port=port)  # same address
            # the reconnect wrapper replays the subscription AND the
            # unconfirmed publish; the engine applies it on arrival
            deadline = _time.monotonic() + 60
            while _time.monotonic() < deadline:
                try:
                    if eng.submit(0, np.zeros(3, np.float32)).model == 0:
                        break
                except UnknownClientError:
                    pass
                _time.sleep(0.1)
            assert eng.submit(0, np.zeros(3, np.float32)).model == 0
            assert cli.reconnects >= 1
        finally:
            cli.close()
            eng.close()
            broker.close()
            if broker2 is not None:
                broker2.close()


class TestErrorPaths:
    def test_unknown_client(self):
        eng = _engine(_pool(M=2), [0, -1]).start()
        try:
            eng.warmup()
            with pytest.raises(UnknownClientError):
                eng.submit(7, np.zeros(3, np.float32))   # out of population
            with pytest.raises(UnknownClientError):
                eng.submit(1, np.zeros(3, np.float32))   # never assigned
        finally:
            eng.close()

    def test_malformed_request(self):
        eng = _engine(_pool(M=2), [0, 1]).start()
        try:
            with pytest.raises(MalformedRequestError):
                eng.submit("not-an-int", np.zeros(3, np.float32))
            with pytest.raises(MalformedRequestError):
                eng.submit(0, np.zeros(5, np.float32))   # wrong geometry
            with pytest.raises(MalformedRequestError):
                eng.submit(0, [["x", "y", "z"]])         # non-numeric body
        finally:
            eng.close()

    def test_submit_before_start(self):
        eng = _engine(_pool(M=2), [0, 1])
        with pytest.raises(RuntimeError):
            eng.submit(0, np.zeros(3, np.float32))


class TestLatencyExemplar:
    def test_p99_exemplar_rearms_past_max_age(self):
        # an ancient outlier must not pin the exemplar slot forever: past
        # exemplar_max_age_s the holder is replaced by the next request
        eng = _engine(_pool(M=2), [0, 1]).start()
        try:
            eng.warmup()
            eng._lat_p99_exemplar = (999.0, "ancient", 0, 0.0)
            eng.exemplar_max_age_s = 0.0     # everything is stale
            eng.submit(0, np.zeros(3, np.float32))
            lat, trace_id, _client, _armed = eng._lat_p99_exemplar
            assert lat < 999.0 and trace_id != "ancient"
        finally:
            eng.close()

    def test_reset_clears_exemplar(self):
        eng = _engine(_pool(M=2), [0, 1]).start()
        try:
            eng.warmup()
            eng.submit(0, np.zeros(3, np.float32))
            assert eng._lat_p99_exemplar[0] > 0.0
            eng.reset_latency_stats()
            assert eng._lat_p99_exemplar == (0.0, None, None, 0.0)
        finally:
            eng.close()


class TestShutdownAndAbandonment:
    """The two queue-lifecycle bugfixes: stop() must FAIL queued requests
    (explicitly, so a failover layer can react), and a timed-out caller's
    request must never reach the forward program."""

    @staticmethod
    def _stub_dispatcher(eng):
        # a finished-but-started thread passes the "engine started" check
        # without ever draining the queue — requests sit exactly where a
        # wedged dispatcher would leave them
        t = threading.Thread(target=lambda: None)
        t.start()
        t.join()
        eng._thread = t

    def test_close_fails_queued_requests_with_engine_stopped(self):
        eng = _engine(_pool(M=2), [0, 1])
        self._stub_dispatcher(eng)
        caught = {}

        def call():
            try:
                eng.submit(0, np.zeros(3, np.float32), timeout=10.0)
            except BaseException as e:       # noqa: BLE001 — the assert
                caught["e"] = e

        th = threading.Thread(target=call)
        th.start()
        deadline = time.perf_counter() + 5.0
        while not eng._queue and time.perf_counter() < deadline:
            time.sleep(0.005)
        assert eng._queue, "request never queued"
        eng.close()
        th.join(timeout=5)
        # the caller got the EXPLICIT shutdown error, not its own timeout
        assert isinstance(caught.get("e"), EngineStopped)
        # and post-stop submits fast-fail the same way
        with pytest.raises(EngineStopped):
            eng.submit(0, np.zeros(3, np.float32))

    def test_timed_out_caller_is_skipped_at_batch_formation(self):
        eng = _engine(_pool(M=2), [0, 1])
        # unnamed engines share the process-global registry counters:
        # assert DELTAS, not absolutes
        abandoned0 = int(eng._abandoned.value)
        served0 = int(eng._served.value)
        self._stub_dispatcher(eng)
        with pytest.raises(TimeoutError):
            eng.submit(0, np.zeros(3, np.float32), timeout=0.05)
        assert len(eng._queue) == 1
        assert eng._queue[0].abandoned       # marked, still queued
        # now let a REAL dispatcher at the queue: the abandoned request
        # must be skipped (counted), never served
        eng._thread = None
        eng.start()
        try:
            deadline = time.perf_counter() + 10.0
            while int(eng._abandoned.value) < abandoned0 + 1 \
                    and time.perf_counter() < deadline:
                time.sleep(0.01)
            assert int(eng._abandoned.value) == abandoned0 + 1
            assert int(eng._served.value) == served0
            # the engine is healthy for live callers afterwards
            assert eng.submit(1, np.zeros(3, np.float32)).model == 1
        finally:
            eng.close()

    def test_expired_deadline_dropped_at_batch_formation(self):
        from feddrift_tpu.obs import spans
        from feddrift_tpu.platform.serving import _Request
        eng = _engine(_pool(M=2), [0, 1]).start()
        expired0 = int(eng._expired.value)
        try:
            eng.warmup()
            req = _Request(0, np.zeros(3, np.float32), spans.new_trace(),
                           rid=10**9, deadline=time.perf_counter() - 1.0)
            with eng._cond:
                eng._queue.append(req)
                eng._cond.notify()
            assert req.done.wait(10.0)
            assert isinstance(req.error, DeadlineExceededError)
            assert req.result is None        # never reached the forward
            assert int(eng._expired.value) == expired0 + 1
        finally:
            eng.close()

    def test_bounded_queue_sheds_with_retry_hint(self):
        eng = _engine(_pool(M=2), [0, 1], max_queue=2)
        self._stub_dispatcher(eng)
        callers = []
        for _ in range(2):
            th = threading.Thread(
                target=lambda: pytest.raises(
                    EngineStopped,
                    eng.submit, 0, np.zeros(3, np.float32), 10.0))
            th.start()
            callers.append(th)
        deadline = time.perf_counter() + 5.0
        while len(eng._queue) < 2 and time.perf_counter() < deadline:
            time.sleep(0.005)
        with pytest.raises(EngineOverloaded) as ei:
            eng.submit(0, np.zeros(3, np.float32))
        assert ei.value.retry_after_s > 0
        eng.close()                          # releases the queued callers
        for th in callers:
            th.join(timeout=5)
