"""Byzantine- and staleness-tolerant rounds (resilience/robust_agg.py,
platform/faults.py::ByzantineInjector, fault-aware clustering decisions).

Covers the acceptance criteria of the robustness PR:
- every registered aggregator against hand-computed [C] stacks, including
  masked rows that must NEVER influence median/trimmed/Krum output;
- deterministic, seeded attack schedules (resumability guarantee);
- the quorum-floor/failure-detector interaction fix (a quorum revival is
  not a liveness signal);
- staleness-excluded accuracy entries no longer churn clusters;
- the e2e chaos+adversary scenario: trimmed_mean stays near the clean
  run's accuracy while plain mean degrades more.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from feddrift_tpu import obs
from feddrift_tpu.config import ExperimentConfig
from feddrift_tpu.platform.faults import (BYZ_MODES, ByzantineInjector,
                                          apply_byzantine_updates)
from feddrift_tpu.resilience.robust_agg import (RobustAggConfig, aggregate,
                                                available_aggregators)
from feddrift_tpu.simulation.runner import Experiment, run_experiment

KEY = jax.random.PRNGKey(0)


def _agg(name, stack, n, prev=None, **kw):
    """One-cluster helper: stack [C, P] -> aggregated [P] + stats row."""
    stack = jnp.asarray(stack, jnp.float32)
    prev = (jnp.zeros(stack.shape[1:], jnp.float32) if prev is None
            else jnp.asarray(prev, jnp.float32))
    out, stats = jax.jit(
        lambda cp, nn, pp: aggregate(name, cp, nn, pp, KEY,
                                     RobustAggConfig(**kw)))(
        {"w": stack[None]}, jnp.asarray(n, jnp.float32)[None],
        {"w": prev[None]})
    return np.asarray(out["w"][0]), np.asarray(stats[0])


class TestAggregators:
    """Hand-computed [C]-stack cases; masked rows hold garbage on purpose."""

    STACK = np.array([[1.0, 10.0],
                      [2.0, 20.0],
                      [3.0, 30.0],
                      [1e9, -1e9],      # masked: must never matter
                      [4.0, 40.0]])
    N = np.array([1.0, 1.0, 1.0, 0.0, 1.0])

    def test_registry_is_complete(self):
        assert set(available_aggregators()) == {
            "mean", "median", "trimmed_mean", "krum", "multi_krum",
            "norm_clip"}

    def test_mean_matches_weighted_average(self):
        n = np.array([1.0, 3.0, 0.0, 0.0, 0.0])
        out, stats = _agg("mean", self.STACK, n)
        np.testing.assert_allclose(
            out, (1 * self.STACK[0] + 3 * self.STACK[1]) / 4, rtol=1e-6)
        assert stats[0] == 2

    def test_median_ignores_masked_rows(self):
        out, stats = _agg("median", self.STACK, self.N)
        # active rows {1,2,3,4}: even count -> mean of the two middle values
        np.testing.assert_allclose(out, [2.5, 25.0], rtol=1e-6)
        assert stats[0] == 4

    def test_median_odd_count(self):
        out, _ = _agg("median", self.STACK[:3], np.ones(3))
        np.testing.assert_allclose(out, [2.0, 20.0], rtol=1e-6)

    def test_trimmed_mean_drops_extremes_not_masked_zeros(self):
        out, stats = _agg("trimmed_mean", self.STACK, self.N, trim_frac=0.3)
        # k=4, t=1: drop min and max among ACTIVE values per coordinate
        np.testing.assert_allclose(out, [2.5, 25.0], rtol=1e-6)
        assert stats[1] == 2           # 2 rejected (one per end)

    def test_trimmed_mean_zero_trim_equals_uniform_mean(self):
        out, _ = _agg("trimmed_mean", self.STACK, self.N, trim_frac=0.0)
        np.testing.assert_allclose(out, [2.5, 25.0], rtol=1e-6)

    def test_krum_picks_the_clustered_update(self):
        # three tight honest updates + one far outlier + one masked garbage
        stack = np.array([[1.0, 1.0], [1.1, 1.0], [0.9, 1.0],
                          [50.0, -50.0], [1e9, 1e9]])
        n = np.array([1.0, 1.0, 1.0, 1.0, 0.0])
        out, stats = _agg("krum", stack, n, krum_f=1)
        assert out.tolist() in ([1.0, 1.0], [1.1, 1.0], [0.9, 1.0])
        assert stats[0] == 4 and stats[1] == 3

    def test_multi_krum_averages_the_best(self):
        stack = np.array([[1.0, 1.0], [1.1, 1.0], [0.9, 1.0],
                          [50.0, -50.0], [1e9, 1e9]])
        n = np.array([1.0, 1.0, 1.0, 1.0, 0.0])
        out, stats = _agg("multi_krum", stack, n, krum_f=1)
        # k=4, f=1 -> 3 selected: the tight cluster; outlier rejected
        np.testing.assert_allclose(out, [1.0, 1.0], atol=0.05)
        assert stats[1] == 1

    def test_norm_clip_bounds_and_counts(self):
        stack = np.array([[3.0, 4.0],        # norm 5 -> clipped to 1
                          [0.3, 0.4],        # norm .5 -> untouched
                          [1e9, 1e9]])
        n = np.array([1.0, 1.0, 0.0])
        out, stats = _agg("norm_clip", stack, n, clip_norm=1.0)
        np.testing.assert_allclose(out, [(0.6 + 0.3) / 2, (0.8 + 0.4) / 2],
                                   rtol=1e-5)
        assert stats[2] == 1            # one client clipped

    def test_all_masked_keeps_previous_params(self):
        prev = np.array([7.0, -7.0])
        for name in available_aggregators():
            out, stats = _agg(name, self.STACK, np.zeros(5), prev=prev)
            np.testing.assert_allclose(out, prev, err_msg=name)
            assert stats[0] == 0

    def test_dp_noise_composes(self):
        a, _ = _agg("median", self.STACK, self.N, dp_stddev=0.0)
        b, _ = _agg("median", self.STACK, self.N, dp_stddev=0.5)
        assert not np.allclose(a, b)

    def test_unknown_aggregator_raises(self):
        with pytest.raises(KeyError):
            aggregate("nope", {"w": jnp.zeros((1, 2, 3))},
                      jnp.ones((1, 2)), {"w": jnp.zeros((1, 3))}, KEY,
                      RobustAggConfig())


class TestByzantineInjector:
    def test_schedules_are_deterministic(self):
        a = ByzantineInjector(8, [1, 5], mode="gauss", prob=0.5, seed=3)
        b = ByzantineInjector(8, [1, 5], mode="gauss", prob=0.5, seed=3)
        np.testing.assert_array_equal(a.schedule(range(30)),
                                      b.schedule(range(30)))
        c = ByzantineInjector(8, [1, 5], mode="gauss", prob=0.5, seed=4)
        assert (a.schedule(range(30)) != c.schedule(range(30))).any()

    def test_modes_hit_only_configured_clients(self):
        inj = ByzantineInjector(6, [0, 2], mode="sign_flip")
        m = inj.modes(7)
        assert m.tolist() == [BYZ_MODES["sign_flip"], 0,
                              BYZ_MODES["sign_flip"], 0, 0, 0]

    def test_emits_events(self):
        obs.configure(None)
        ByzantineInjector(4, [3], mode="scale").modes(0)
        evs = obs.get_bus().events("byzantine_injected")
        assert evs and evs[-1]["clients"] == [3] and evs[-1]["mode"] == "scale"

    def test_rejects_bad_args(self):
        with pytest.raises(ValueError):
            ByzantineInjector(4, [0], mode="nuke")
        with pytest.raises(ValueError):
            ByzantineInjector(4, [9])
        with pytest.raises(ValueError):
            ByzantineInjector(4, [0], prob=1.5)


class TestApplyByzantine:
    def _stack(self):
        cp = {"w": jnp.ones((1, 3, 2), jnp.float32) * 2.0}
        gp = {"w": jnp.ones((1, 2), jnp.float32)}       # delta = +1
        return cp, gp

    def test_sign_flip_and_scale(self):
        cp, gp = self._stack()
        modes = jnp.asarray([BYZ_MODES["sign_flip"], BYZ_MODES["scale"], 0])
        out = apply_byzantine_updates(cp, gp, modes, None, KEY, 3.0, 1.0)
        w = np.asarray(out["w"][0])
        np.testing.assert_allclose(w[0], 1.0 - 3.0)     # g - λ·delta
        np.testing.assert_allclose(w[1], 1.0 + 3.0)     # g + λ·delta
        np.testing.assert_allclose(w[2], 2.0)           # honest untouched

    def test_stale_replay_resends_previous_submission(self):
        cp, gp = self._stack()
        stale = {"w": jnp.full((1, 3, 2), -5.0, jnp.float32)}
        modes = jnp.asarray([BYZ_MODES["stale_replay"], 0, 0])
        out = apply_byzantine_updates(cp, gp, modes, stale, KEY, 1.0, 1.0)
        w = np.asarray(out["w"][0])
        np.testing.assert_allclose(w[0], -5.0)
        np.testing.assert_allclose(w[1:], 2.0)

    def test_gauss_replaces_update(self):
        cp, gp = self._stack()
        modes = jnp.asarray([BYZ_MODES["gauss"], 0, 0])
        out = apply_byzantine_updates(cp, gp, modes, None, KEY, 1.0, 0.5)
        w = np.asarray(out["w"][0])
        assert not np.allclose(w[0], 2.0)
        np.testing.assert_allclose(w[1:], 2.0)


def _cfg(**kw):
    base = dict(dataset="sine", model="fnn", concept_drift_algo="win-1",
                train_iterations=2, comm_round=8, epochs=2, sample_num=48,
                batch_size=24, frequency_of_the_test=4, lr=0.05,
                client_num_in_total=10, client_num_per_round=10, seed=0,
                report_client=0, divergence_guard=False)
    base.update(kw)
    base.setdefault("client_num_per_round",
                    min(10, base["client_num_in_total"]))
    if base["client_num_per_round"] > base["client_num_in_total"]:
        base["client_num_per_round"] = base["client_num_in_total"]
    return ExperimentConfig(**base)


class TestQuorumReviveDetectorFix:
    def test_revival_is_not_liveness(self):
        """A quorum-revived client was revived BECAUSE everything dropped;
        its failure streak must keep growing and the revival must be
        recorded distinctly (quorum_revive event)."""
        cfg = _cfg(client_num_in_total=6, client_num_per_round=3,
                   fault_enabled=True, failure_patience=2)
        exp = Experiment(cfg)
        exp.fault_injector.schedule_outage(0, 4, list(range(6)))
        masks = exp._client_masks(0, range(4))
        # quorum floor kept every round alive...
        assert (masks.sum(axis=1) >= 1).all()
        # ...yet every SAMPLED client's genuine outage streak advanced
        # (the injector's own floor keeps client 0 up, so exclude it)
        assert exp.failure_detector.absent_streak[1:].max() >= 2
        revs = obs.get_bus().events("quorum_revive")
        assert revs and all("client" in e for e in revs)

    def test_exclude_suspected_zeroes_weight(self):
        """Defense-in-depth knob: suspected clients carry zero aggregation
        weight, and the run still completes normally."""
        cfg = _cfg(client_num_in_total=4, fault_enabled=True,
                   failure_patience=1, exclude_suspected_from_agg=True)
        exp = Experiment(cfg)
        exp.fault_injector.kill(3)
        masks = exp._client_masks(0, range(4))
        assert (masks[:, 3] == 0).all()
        assert 3 in exp.failure_detector.suspected
        exp.run()
        assert exp.logger.last("Test/Acc") > 0.6


class TestStalenessExcludedDecisions:
    # only client 2 — the one the tests kill — ever drifts
    CP_ONLY_CLIENT_2 = "0 0 0 0 0 0;0 0 1 0 0 0;0 0 1 0 0 0"

    def _experiment(self, limit):
        cfg = _cfg(concept_drift_algo="softcluster",
                   concept_drift_algo_arg="mmacc_10", concept_num=4,
                   client_num_in_total=6, fault_enabled=True,
                   failure_patience=2, acc_staleness_limit=limit,
                   change_points=self.CP_ONLY_CLIENT_2)
        return Experiment(cfg)

    def test_stale_client_cannot_trigger_spawn(self):
        """Unit-level: the same accuracy drop spawns a model when the
        client is live and must NOT when the client is staleness-excluded."""
        for limit, want_spawn in ((0, True), (3, False)):
            exp = self._experiment(limit)
            algo = exp.algo
            acc = np.full((algo.M, algo.C), 0.9)
            acc[:, 2] = 0.2                     # client 2's column collapsed
            algo.weights[0, 0, :] = 1.0         # everyone on model 0 at t=0
            algo.mmacc_acc[:] = 0.9             # armed detector
            algo.acc_matrix_at = lambda t, feat_mask=None: acc
            algo.set_client_staleness(
                np.array([0, 0, 10, 0, 0, 0]), suspected=(2,))
            spawns0 = algo.event_counts["spawns"]
            algo._cluster_mmacc2(1)
            spawned = algo.event_counts["spawns"] > spawns0
            assert spawned == want_spawn, f"limit={limit}"

    def test_killed_client_keeps_cluster_count_flat(self):
        """E2E acceptance: kill the only-drifting client mid-stream. The
        pre-fix behavior (limit=0) spawns a cluster off the dead client's
        stale accuracy column; with staleness exclusion the cluster count
        stays flat and the exclusion is visible in the event stream."""
        # control: historical trusting behavior churns
        obs.configure(None)
        exp = self._experiment(limit=0)
        exp.fault_injector.kill(2)
        exp.run()
        states = [e["num_models"]
                  for e in obs.get_bus().events("cluster_state")]
        assert max(states) > states[0], states

        # fix: stale column excluded -> no spurious model
        obs.configure(None)
        exp = self._experiment(limit=2)
        exp.fault_injector.kill(2)
        exp.run()
        states = [e["num_models"]
                  for e in obs.get_bus().events("cluster_state")]
        assert states and all(s == states[0] for s in states), states
        assert obs.get_bus().events("acc_stale_excluded")


class TestEndToEndDefense:
    """10 clients, 20% dropout, 2 Byzantine sign-flippers: trimmed_mean
    must stay within DELTA of the clean run while plain mean degrades
    more (the documented acceptance scenario; also the chaos_smoke.sh
    Byzantine stage)."""

    DELTA = 0.10

    def test_trimmed_mean_defends_where_mean_fails(self):
        clean = run_experiment(_cfg()).logger.last("Test/Acc")
        byz = dict(byzantine_clients="0,1", byzantine_mode="sign_flip",
                   fault_dropout_prob=0.2)
        acc_mean = run_experiment(_cfg(**byz)).logger.last("Test/Acc")
        acc_trim = run_experiment(
            _cfg(**byz, robust_agg="trimmed_mean",
                 robust_trim_frac=0.3)).logger.last("Test/Acc")
        assert acc_trim >= clean - self.DELTA, (clean, acc_trim)
        assert acc_mean < acc_trim - 0.05, (acc_mean, acc_trim)
        # the attack and the defense are both visible in the event stream
        kinds = {e["kind"] for e in obs.get_bus().events()}
        assert {"byzantine_injected", "robust_agg_applied"} <= kinds
        ev = obs.get_bus().events("robust_agg_applied")[-1]
        assert ev["strategy"] == "trimmed_mean" and ev["rejected"] > 0

    def test_masked_and_phantom_rows_cannot_poison(self):
        """Same run on a client count that forces phantom padding on
        multi-device meshes plus dropout: robust aggregation must not
        average in masked rows (it would tank accuracy)."""
        acc = run_experiment(
            _cfg(client_num_in_total=7, client_num_per_round=5,
                 robust_agg="median")).logger.last("Test/Acc")
        assert acc > 0.6


class TestDeterminismGuard:
    """Identical seeds must give bitwise-identical attack schedules AND
    bitwise-identical robust-aggregated parameters (protects --auto_resume
    replay from PR 2)."""

    def test_two_runs_bitwise_identical(self):
        cfg = _cfg(byzantine_clients="0,1", byzantine_mode="sign_flip",
                   byzantine_prob=0.7, fault_dropout_prob=0.2,
                   robust_agg="trimmed_mean", robust_trim_frac=0.3)
        a = run_experiment(cfg)
        b = run_experiment(cfg)
        assert a.logger.series("Test/Acc") == b.logger.series("Test/Acc")
        for la, lb in zip(jax.tree_util.tree_leaves(a.pool.params),
                          jax.tree_util.tree_leaves(b.pool.params)):
            np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))

    def test_stale_replay_deterministic_across_paths(self):
        """stale_replay carries state through the scan; fused and per-round
        execution must still agree bitwise."""
        kw = dict(byzantine_clients="0", byzantine_mode="stale_replay",
                  robust_agg="median", client_num_in_total=6)
        a = run_experiment(_cfg(**kw, chunk_rounds=True))
        b = run_experiment(_cfg(**kw, chunk_rounds=False))
        assert a.logger.series("Test/Acc") == b.logger.series("Test/Acc")
        for la, lb in zip(jax.tree_util.tree_leaves(a.pool.params),
                          jax.tree_util.tree_leaves(b.pool.params)):
            np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))


class TestReportRobustnessSection:
    def test_summarize_renders_robustness(self, tmp_path):
        import json

        from feddrift_tpu.obs.report import render, summarize
        evs = [
            {"_ts": 1.0, "kind": "byzantine_injected", "byz_round": 0,
             "clients": [0, 1], "mode": "sign_flip"},
            {"_ts": 1.1, "kind": "robust_agg_applied", "round": 0,
             "strategy": "trimmed_mean", "active": [8], "rejected": 4,
             "clipped": 0},
            {"_ts": 1.2, "kind": "acc_stale_excluded", "clients": [2],
             "decision": "drift_trigger", "changed": True},
            {"_ts": 1.3, "kind": "quorum_revive", "fault_round": 3,
             "client": 0},
        ]
        with open(tmp_path / "events.jsonl", "w") as f:
            for e in evs:
                f.write(json.dumps(e) + "\n")
        s = summarize(str(tmp_path))
        rob = s["robustness"]
        assert rob["byzantine"]["clients"] == [0, 1]
        assert rob["aggregation"]["strategy"] == "trimmed_mean"
        assert rob["aggregation"]["rejected_total"] == 4
        assert rob["stale_exclusions"]["changed_decisions"] == 1
        assert rob["quorum_revives"] == 1
        text = render(s)
        assert "robustness:" in text and "trimmed_mean" in text
