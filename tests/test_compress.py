"""Verified wire compression (comm/compress.py): codec round-trips,
sha256 digest rejection, negotiation + nack fallback over both the
in-process and the TCP broker, the ≥3x broker-bytes reduction, and the
bitwise agreement between the numpy wire int8 codec and the in-program
jax simulation.
"""

import json

import numpy as np
import pytest

from feddrift_tpu import obs
from feddrift_tpu.comm.compress import (WIRE_CODECS, CorruptFrameError,
                                        UpdateReceiver, UpdateSender,
                                        decode_frame, encode_frame,
                                        simulate_codec)
from feddrift_tpu.comm.pubsub import Broker

RNG = np.random.RandomState(0)
ARR = RNG.randn(40, 37).astype(np.float32)


class TestCodecRoundTrips:
    def test_none_is_lossless(self):
        out = decode_frame(encode_frame(ARR, "none"))
        assert (out == ARR).all()

    def test_int8_within_quantization_tolerance(self):
        out = decode_frame(encode_frame(ARR, "int8"))
        step = (ARR.max() - ARR.min()) / 255.0
        assert np.abs(out - ARR).max() <= step / 2 + 1e-6

    def test_topk_keeps_largest_coordinates(self):
        out = decode_frame(encode_frame(ARR, "topk", topk_frac=0.25))
        k = int(np.ceil(0.25 * ARR.size))
        kept = np.flatnonzero(out.reshape(-1))
        assert len(kept) <= k
        # the largest-magnitude coordinate survives, near its value
        top = np.argmax(np.abs(ARR))
        assert abs(out.reshape(-1)[top] - ARR.reshape(-1)[top]) < 0.05

    def test_delta_chain_error_does_not_accumulate(self):
        prev_tx = prev_rx = None
        step = (2.0 / 255.0)   # generous bound; arrays are ~N(0,1)
        for i in range(12):
            arr = RNG.randn(30, 11).astype(np.float32)
            frame = encode_frame(arr, "delta", prev=prev_tx)
            out = decode_frame(frame, prev=prev_rx)
            prev_tx = prev_rx = out          # both ends carry the DECODED
            err = np.abs(out - arr).max()
            assert err < 0.1, (i, err)       # bounded at every link, not O(i)

    def test_large_array_uses_wide_indices(self):
        big = RNG.randn(300, 300).astype(np.float32)   # 90k > 64Ki
        frame = encode_frame(big, "topk", topk_frac=0.01)
        assert frame["p"]["iw"] == 4
        out = decode_frame(frame)
        assert out.shape == big.shape

    def test_unknown_codec_raises(self):
        with pytest.raises(ValueError):
            encode_frame(ARR, "gzip")


class TestDigestVerification:
    def test_bit_flip_in_payload_detected(self):
        frame = encode_frame(ARR, "int8")
        data = bytearray(frame["p"]["data"].encode())
        data[len(data) // 2] ^= 0x01         # flip one bit mid-payload
        frame["p"]["data"] = data.decode("latin1")
        with pytest.raises(CorruptFrameError):
            decode_frame(frame)

    def test_tampered_metadata_detected(self):
        frame = encode_frame(ARR, "int8")
        frame["p"]["lo"] = frame["p"]["lo"] + 1.0
        with pytest.raises(CorruptFrameError):
            decode_frame(frame)

    def test_truncated_frame_detected(self):
        frame = encode_frame(ARR, "none")
        frame["p"]["data"] = frame["p"]["data"][:-8]
        with pytest.raises(CorruptFrameError):
            decode_frame(frame)

    def test_every_codec_verifies(self):
        for codec in WIRE_CODECS:
            frame = encode_frame(ARR, codec)
            frame["digest"] = "0" * 64
            with pytest.raises(CorruptFrameError):
                decode_frame(frame)


class TestNegotiatedTransport:
    def _pair(self, codec):
        broker = Broker()
        tx = UpdateSender(broker, "fl/update", codec=codec)
        rx = UpdateReceiver(broker, "fl/update")
        tx.offer()
        rx.serve_ctl(timeout=1.0)
        assert tx.wait_accept(timeout=1.0) == codec
        return tx, rx

    @pytest.mark.parametrize("codec", ["int8", "topk", "delta"])
    def test_negotiate_send_recv(self, codec):
        obs.configure(None)
        tx, rx = self._pair(codec)
        arr = RNG.randn(20, 13).astype(np.float32)
        tx.send("layer0", arr)
        name, got = rx.recv(timeout=1.0)
        assert name == "layer0"
        if codec == "topk":
            # kept coordinates near-exact, dropped ones exactly zero
            kept = got.reshape(-1) != 0
            assert np.abs(got.reshape(-1)[kept]
                          - arr.reshape(-1)[kept]).max() < 0.05
        else:
            assert np.abs(got - arr).max() < 0.05
        evs = obs.get_bus().events("update_compressed")
        assert evs and evs[-1]["codec"] == codec
        assert evs[-1]["wire_bytes"] < evs[-1]["raw_bytes"]
        saved = obs.registry().counter("bytes_saved", codec=codec).value
        assert saved > 0

    def test_unsupported_codec_falls_back_to_none(self):
        broker = Broker()
        tx = UpdateSender(broker, "fl/u", codec="delta")
        rx = UpdateReceiver(broker, "fl/u", codecs=("none", "int8"))
        tx.offer()
        rx.serve_ctl(timeout=1.0)
        assert tx.wait_accept(timeout=1.0) == "none"

    def test_corrupt_frame_nacked_then_resent_uncompressed(self):
        obs.configure(None)
        broker = Broker()
        tx = UpdateSender(broker, "fl/u", codec="int8")
        rx = UpdateReceiver(broker, "fl/u")
        arr = RNG.randn(16, 5).astype(np.float32)

        # intercept the published frame and flip a payload bit
        frame = tx.send("w", arr)
        bad = json.loads(json.dumps(frame))
        data = bytearray(bad["p"]["data"].encode())
        data[4] ^= 0x10
        bad["p"]["data"] = data.decode("latin1")
        # drain the clean frame the receiver already has queued
        assert rx.recv(timeout=1.0) is not None
        broker.publish("fl/u", json.dumps(bad))
        assert rx.recv(timeout=1.0) is None              # corrupt -> dropped
        assert obs.get_bus().events("compress_corrupt")
        assert obs.registry().counter("frames_corrupt").value == 1

        # the nack triggers an uncompressed, LOSSLESS re-send
        assert tx.poll_nacks(timeout=1.0) == 1
        name, got = rx.recv(timeout=1.0)
        assert name == "w"
        assert (got == arr).all()

    def test_works_over_tcp_broker(self):
        from feddrift_tpu.comm.netbroker import (NetworkBroker,
                                                 NetworkBrokerClient)
        obs.configure(None)
        broker = NetworkBroker()
        try:
            ctx = NetworkBrokerClient(broker.host, broker.port)
            crx = NetworkBrokerClient(broker.host, broker.port)
            rx = UpdateReceiver(crx, "fl/u")
            tx = UpdateSender(ctx, "fl/u", codec="int8")
            # TCP subscribe is async: sync both clients via a loopback
            for c in (ctx, crx):
                q = c.subscribe("__sync__")
                c.publish("__sync__", "ready")
                assert q.get(timeout=5) == "ready"
            tx.offer()
            rx.serve_ctl(timeout=5.0)
            assert tx.wait_accept(timeout=5.0) == "int8"
            arr = RNG.randn(24, 7).astype(np.float32)
            tx.send("w", arr)
            name, got = rx.recv(timeout=5.0)
            assert name == "w"
            assert np.abs(got - arr).max() < 0.05
            ctx.close(); crx.close()
        finally:
            broker.close()


class TestBrokerBytesReduction:
    """The acceptance gate: each lossy codec moves >= 3x fewer bytes
    through the broker than the uncompressed baseline for the same
    payloads (measured on the broker_bytes_out counter, netbroker)."""

    @pytest.mark.parametrize("codec", ["int8", "topk", "delta"])
    def test_at_least_3x_fewer_bytes(self, codec):
        from feddrift_tpu.comm.netbroker import (NetworkBroker,
                                                 NetworkBrokerClient)
        arrs = [RNG.randn(64, 64).astype(np.float32) for _ in range(4)]

        def run(use_codec):
            obs.configure(None)
            obs.registry().reset()
            broker = NetworkBroker()
            try:
                ctx = NetworkBrokerClient(broker.host, broker.port)
                crx = NetworkBrokerClient(broker.host, broker.port)
                rx = UpdateReceiver(crx, "fl/u")
                tx = UpdateSender(ctx, "fl/u", codec=use_codec)
                for c in (ctx, crx):
                    q = c.subscribe("__sync__")
                    c.publish("__sync__", "ready")
                    assert q.get(timeout=5) == "ready"
                for i, a in enumerate(arrs):
                    tx.send(f"w{i}", a)
                    assert rx.recv(timeout=5.0) is not None
                return obs.registry().counter(
                    "broker_bytes_out", transport="netbroker").value
            finally:
                broker.close()

        raw = run("none")
        wire = run(codec)
        assert raw / wire >= 3.0, (codec, raw, wire, raw / wire)


class TestDeviceWireAgreement:
    """The jax in-program int8 simulation and the numpy wire codec share
    the 255-level affine formula: same input slice, same reconstruction
    (within float32 arithmetic)."""

    def test_int8_simulation_matches_wire(self):
        d = RNG.randn(2, 3, 5, 4).astype(np.float32)     # [M, C, ...]
        sim, _ = simulate_codec({"w": d}, "int8")
        sim = np.asarray(sim["w"])
        for m in range(2):
            for c in range(3):
                wire = decode_frame(encode_frame(d[m, c], "int8"))
                np.testing.assert_allclose(sim[m, c], wire, atol=1e-5)

    def test_simulation_none_is_identity(self):
        d = {"w": np.ones((1, 2, 3), np.float32)}
        out, carry = simulate_codec(d, "none")
        assert out is d and carry is None

    def test_delta_simulation_carries_decoded(self):
        d = {"w": RNG.randn(1, 2, 6).astype(np.float32)}
        prev = {"w": np.zeros((1, 2, 6), np.float32)}
        out1, carry1 = simulate_codec(d, "delta", prev=prev)
        assert carry1 is not None
        np.testing.assert_allclose(np.asarray(out1["w"]),
                                   np.asarray(carry1["w"]))


class TestRegressHierarchyRows:
    """The `regress` gate grows bytes-per-round rows off the COMM artifact
    (bench.py --hierarchy): growth past the bytes tolerance or a lossy
    codec dropping under its 3x floor is a regression."""

    BASE = {"hierarchy": [
        {"codec": "none", "bytes_per_round": 400000.0, "ratio_vs_none": 1.0},
        {"codec": "int8", "bytes_per_round": 100000.0, "ratio_vs_none": 4.0},
    ]}

    def _rows(self, cand):
        from feddrift_tpu.obs import regress
        return {r["metric"]: r for r in regress.compare(cand, self.BASE)}

    def test_unchanged_is_ok(self):
        rows = self._rows(self.BASE)
        assert rows["hierarchy[int8].bytes_per_round"]["status"] == "ok"
        assert rows["hierarchy[int8].ratio_vs_none"]["status"] == "ok"

    def test_bytes_growth_past_tolerance_regresses(self):
        cand = {"hierarchy": [
            {"codec": "int8", "bytes_per_round": 130000.0,   # +30% > 25%
             "ratio_vs_none": 3.1}]}
        rows = self._rows(cand)
        assert rows["hierarchy[int8].bytes_per_round"]["status"] == "regress"

    def test_ratio_below_absolute_floor_regresses(self):
        cand = {"hierarchy": [
            {"codec": "int8", "bytes_per_round": 100000.0,
             "ratio_vs_none": 2.5}]}
        rows = self._rows(cand)
        assert rows["hierarchy[int8].ratio_vs_none"]["status"] == "regress"

    def test_committed_comm_artifact_self_compares_clean(self):
        import os
        from feddrift_tpu.obs import regress
        path = os.path.join(os.path.dirname(__file__), "..", "COMM_r08.json")
        art = regress.load_bench(path)
        rows = regress.compare(art, art)
        assert all(r["status"] != "regress" for r in rows)
        assert any(r["metric"].startswith("hierarchy[") for r in rows)


@pytest.mark.slow
class TestCodecAccuracy:
    """Lossy in-program codecs stay within 0.02 Test/Acc of the
    uncompressed run on the small e2e config (both execution paths agree
    bitwise, so one path suffices here — parity is covered in
    test_hierarchy.py)."""

    def test_each_codec_within_tolerance(self):
        from feddrift_tpu.config import ExperimentConfig
        from feddrift_tpu.simulation.runner import run_experiment
        base = dict(dataset="sine", model="fnn", concept_drift_algo="win-1",
                    train_iterations=2, comm_round=8, epochs=2,
                    sample_num=48, batch_size=24, frequency_of_the_test=4,
                    lr=0.05, client_num_in_total=10, client_num_per_round=10,
                    seed=0, report_client=0, divergence_guard=False)
        ref = run_experiment(
            ExperimentConfig(**base)).logger.last("Test/Acc")
        for codec in ("int8", "topk", "delta"):
            acc = run_experiment(ExperimentConfig(
                **base, compress_codec=codec)).logger.last("Test/Acc")
            assert abs(acc - ref) <= 0.02, (codec, ref, acc)
