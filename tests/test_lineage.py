"""Decision observability: lineage reconstruction (genealogy DAG with
slot reuse), oracle ARI/purity, the alert monitor, and the lineage CLI.
Pure host logic except the slow-marked e2e runs."""

from __future__ import annotations

import json
import os

import numpy as np
import pytest

from feddrift_tpu.obs import lineage
from feddrift_tpu.obs.alerts import AlertMonitor, default_rules, replay
from feddrift_tpu.obs.events import EventBus


# ----------------------------------------------------------------------
class TestOracleMetrics:
    def test_ari_hand_computed_three_clients(self):
        """truth [0,0,1] vs pred [0,1,1]: contingency [[1,1],[0,1]] →
        ARI = (0 - 1/3) / (1 - 1/3) = -0.5 by the Hubert-Arabie form."""
        assert lineage.adjusted_rand_index([0, 0, 1], [0, 1, 1]) == \
            pytest.approx(-0.5)

    def test_purity_hand_computed_three_clients(self):
        # pred cluster 0 = {c0} (pure), cluster 1 = {c1, c2} with truth
        # labels {0, 1} → majority 1 each: (1 + 1) / 3
        assert lineage.cluster_purity([0, 0, 1], [0, 1, 1]) == \
            pytest.approx(2 / 3)

    def test_ari_identical_and_permuted(self):
        assert lineage.adjusted_rand_index([0, 1, 1, 2], [0, 1, 1, 2]) == 1.0
        # permutation-invariant: relabeling clusters changes nothing
        assert lineage.adjusted_rand_index([0, 0, 1, 1], [5, 5, 3, 3]) == 1.0

    def test_ari_trivial_partitions_agree(self):
        # both single-cluster → identical, not 0/0
        assert lineage.adjusted_rand_index([0, 0, 0], [2, 2, 2]) == 1.0

    def test_ari_against_sklearn(self):
        from sklearn.metrics import adjusted_rand_score
        rng = np.random.default_rng(7)
        for _ in range(20):
            a = rng.integers(0, 4, size=30)
            b = rng.integers(0, 3, size=30)
            assert lineage.adjusted_rand_index(a, b) == \
                pytest.approx(adjusted_rand_score(a, b))

    def test_length_mismatch_raises(self):
        with pytest.raises(ValueError):
            lineage.adjusted_rand_index([0, 1], [0, 1, 2])
        with pytest.raises(ValueError):
            lineage.cluster_purity([0, 1], [0, 1, 2])


# ----------------------------------------------------------------------
# A golden event stream exercising every genealogy transition, including
# the LRU SLOT-REUSE case (slot 1 hosts two different lineages).
GOLDEN_EVENTS = [
    {"kind": "run_start", "algo": "softcluster", "dataset": "sea",
     "clients": 3, "num_models": 2,
     "concept_matrix": [[0, 0, 0], [0, 1, 1], [0, 1, 1], [0, 0, 1]]},
    {"kind": "cluster_assign", "iteration": 0, "assignment": [0, 0, 0]},
    {"kind": "drift_detected", "iteration": 1, "client": 1,
     "acc_drop": 0.3, "threshold": 0.1},
    {"kind": "cluster_create", "iteration": 1, "model": 1, "init_from": 0,
     "client": 1},
    {"kind": "cluster_assign", "iteration": 1, "assignment": [0, 1, 1]},
    {"kind": "cluster_merge", "iteration": 2, "base": 0, "merged": 1,
     "distance": 0.02, "threshold": 0.1, "in_use": [0, 1],
     "distance_row": [0.02, 0.0]},
    {"kind": "cluster_assign", "iteration": 2, "assignment": [0, 0, 0]},
    # slot 1 REUSED for a brand-new lineage after the merge freed it
    {"kind": "cluster_create", "iteration": 3, "model": 1, "init_from": 0,
     "client": 2},
    {"kind": "cluster_assign", "iteration": 3, "assignment": [0, 0, 1]},
]


class TestGenealogy:
    def test_golden_dag_with_slot_reuse(self):
        lin = lineage.build_lineage(GOLDEN_EVENTS)
        # L0 root on slot 0; L1 spawn on slot 1 (merged away);
        # L2 = the REUSE of slot 1 as a distinct lineage
        assert [n.lid for n in lin.nodes] == ["L0", "L1", "L2"]
        l0, l1, l2 = lin.nodes
        assert l0.origin == "root" and l0.slot == 0
        assert l0.end_reason is None                    # still active
        assert l1.slot == 1 and l1.parents == ["L0"]
        assert l1.evidence["client"] == 1
        assert l1.end_reason == "merged_into:L0" and l1.end == 2
        assert l0.absorbed[0]["lid"] == "L1"
        assert l0.absorbed[0]["evidence"]["distance"] == 0.02
        # the reused slot is a NEW lineage, not a resurrection of L1
        assert l2.slot == 1 and l2.lid != l1.lid
        assert l2.parents == ["L0"] and l2.end_reason is None
        assert l0.children == ["L1", "L2"]

    def test_slot_reuse_without_merge_marks_old_lineage(self):
        events = [
            {"kind": "cluster_assign", "iteration": 0, "assignment": [0, 1]},
            {"kind": "cluster_create", "iteration": 2, "model": 1,
             "init_from": 0},
        ]
        lin = lineage.build_lineage(events)
        old = next(n for n in lin.nodes if n.slot == 1 and n.origin == "root")
        assert old.end_reason == "slot_reused" and old.end == 2

    def test_split_creates_two_children(self):
        events = [
            {"kind": "cluster_assign", "iteration": 0, "assignment": [0, 0]},
            {"kind": "cluster_split", "iteration": 1, "model": 0,
             "new_model": 1, "clients_kept": [0], "clients_moved": [1],
             "alpha_cross": -0.4, "gamma": 0.1},
        ]
        lin = lineage.build_lineage(events)
        old = lin.nodes[0]
        assert old.end_reason == "split"
        kids = [lin.by_id[c] for c in old.children]
        assert {k.slot for k in kids} == {0, 1}
        assert all(k.origin == "split" for k in kids)
        assert {k.evidence["side"] for k in kids} == {"kept", "moved"}

    def test_delete_ends_lineage_with_reason(self):
        events = [
            {"kind": "cluster_assign", "iteration": 0, "assignment": [0, 1]},
            {"kind": "cluster_delete", "iteration": 1, "model": 1,
             "reason": "noncompetitive_reset"},
        ]
        lin = lineage.build_lineage(events)
        node = next(n for n in lin.nodes if n.slot == 1)
        assert node.end_reason == "deleted:noncompetitive_reset"

    def test_timeline_scored_against_concept_matrix(self):
        lin = lineage.build_lineage(GOLDEN_EVENTS)
        cm = lineage.concept_matrix_from_events(GOLDEN_EVENTS)
        rows = lineage.score_timeline(lin, cm)
        by_t = {r["iteration"]: r for r in rows}
        # t=0: both trivial → 1.0; t=1: exact recovery → 1.0
        assert by_t[0]["ari"] == 1.0
        assert by_t[1]["ari"] == 1.0
        # t=2: truth [0,1,1] vs single-cluster pred → ARI 0
        assert by_t[2]["ari"] == 0.0
        # t=3: truth [0,0,1] vs pred [0,0,1] → exact again
        assert by_t[3]["ari"] == 1.0
        assert by_t[2]["purity"] == pytest.approx(2 / 3, abs=1e-3)

    def test_render_tree_and_dot(self):
        lin = lineage.build_lineage(GOLDEN_EVENTS)
        tree = lineage.render_tree(lin)
        assert "cluster genealogy (3 lineages, 1 merges" in tree
        assert "L1 [slot 1] drift_spawn @t1" in tree
        assert "absorbed L1 @t2 (dist 0.02" in tree
        dot = lineage.to_dot(lin)
        assert "L0 -> L1;" in dot
        assert 'L1 -> L0 [style=dashed' in dot          # merge edge
        assert dot.startswith("digraph")


class TestLineageCLI:
    def _write_run(self, tmp_path):
        with open(tmp_path / "events.jsonl", "w") as f:
            for e in GOLDEN_EVENTS:
                f.write(json.dumps({"_ts": 1.0, **e}) + "\n")

    def test_missing_dir_fails(self, tmp_path, capsys):
        assert lineage.main([str(tmp_path / "nope")]) == 1
        assert "does not exist" in capsys.readouterr().err

    def test_empty_dir_fails(self, tmp_path, capsys):
        assert lineage.main([str(tmp_path)]) == 1
        assert "missing or empty" in capsys.readouterr().err

    def test_renders_tree_timeline_and_oracle(self, tmp_path, capsys):
        self._write_run(tmp_path)
        assert lineage.main([str(tmp_path)]) == 0
        out = capsys.readouterr().out
        assert "cluster genealogy" in out
        assert "assignment timeline:" in out
        assert "ARI" in out
        assert "oracle agreement" in out

    def test_dot_export_and_json(self, tmp_path, capsys):
        self._write_run(tmp_path)
        dot_path = str(tmp_path / "lineage.dot")
        assert lineage.main([str(tmp_path), "--dot", dot_path,
                             "--json"]) == 0
        assert open(dot_path).read().startswith("digraph")
        d = json.loads(capsys.readouterr().out)
        assert len(d["nodes"]) == 3
        assert d["oracle"]["final_ari"] == 1.0
        assert d["has_ground_truth"]

    def test_cli_verb_routes_without_jax(self, tmp_path, capsys):
        from feddrift_tpu.cli import main
        self._write_run(tmp_path)
        assert main(["lineage", str(tmp_path)]) == 0
        assert "cluster genealogy" in capsys.readouterr().out


# ----------------------------------------------------------------------
class TestAlertRules:
    def test_cluster_churn_fires_over_threshold(self):
        mon = AlertMonitor(rules=default_rules(churn_threshold=2,
                                               churn_window=2))
        for i in range(3):
            mon.observe({"kind": "cluster_create", "iteration": 1,
                         "model": i})
        mon.observe({"kind": "cluster_state", "iteration": 1,
                     "num_models": 3})
        assert len(mon.alerts) == 1
        assert mon.alerts[0]["rule"] == "cluster_churn"
        assert mon.alerts[0]["count"] == 3

    def test_churn_quiet_below_threshold(self):
        mon = AlertMonitor(rules=default_rules(churn_threshold=4,
                                               churn_window=2))
        mon.observe({"kind": "cluster_merge", "iteration": 1})
        mon.observe({"kind": "cluster_state", "iteration": 1})
        assert mon.alerts == []

    def test_ari_collapse_needs_armed_best(self):
        mon = AlertMonitor()
        # climbing: never fires
        for t, ari in enumerate((0.2, 0.6, 0.9)):
            mon.observe({"kind": "cluster_assign", "iteration": t,
                         "oracle_ari": ari, "assignment": []})
        assert mon.alerts == []
        mon.observe({"kind": "cluster_assign", "iteration": 3,
                     "oracle_ari": 0.1, "assignment": []})
        assert [a["rule"] for a in mon.alerts] == ["ari_collapse"]
        assert mon.alerts[0]["severity"] == "crit"

    def test_ari_collapse_unarmed_low_best_is_quiet(self):
        mon = AlertMonitor()
        mon.observe({"kind": "cluster_assign", "iteration": 0,
                     "oracle_ari": 0.3, "assignment": []})
        mon.observe({"kind": "cluster_assign", "iteration": 1,
                     "oracle_ari": 0.0, "assignment": []})
        assert mon.alerts == []

    def test_divergence_byzantine_cooccurrence(self):
        mon = AlertMonitor()
        # divergence alone: quiet
        mon.observe({"kind": "divergence_detected", "iteration": 0,
                     "round": 5, "reason": "nonfinite"})
        assert mon.alerts == []
        mon.observe({"kind": "byzantine_injected", "iteration": 1,
                     "round": 20, "clients": [0], "mode": "sign_flip"})
        mon.observe({"kind": "divergence_detected", "iteration": 1,
                     "round": 24, "reason": "loss_spike"})
        assert [a["rule"] for a in mon.alerts] == ["divergence_byzantine"]
        assert mon.alerts[0]["byz_modes"] == ["sign_flip"]

    def test_eval_gap_stall(self):
        mon = AlertMonitor(rules=default_rules(stall_evals=3,
                                               stall_gap=0.1,
                                               stall_eps=0.01))
        for r in range(3):
            mon.observe({"kind": "eval", "iteration": r, "round": r,
                         "train_acc": 0.9, "test_acc": 0.6})
        assert [a["rule"] for a in mon.alerts] == ["eval_gap_stall"]

    def test_eval_improving_is_quiet(self):
        mon = AlertMonitor(rules=default_rules(stall_evals=3,
                                               stall_gap=0.1,
                                               stall_eps=0.01))
        for r, te in enumerate((0.5, 0.6, 0.7)):
            mon.observe({"kind": "eval", "iteration": r, "round": r,
                         "train_acc": 0.9, "test_acc": te})
        assert mon.alerts == []

    def test_client_outage_on_kill_and_suspects(self):
        mon = AlertMonitor()
        mon.observe({"kind": "client_killed", "iteration": 0, "client": 3})
        mon.observe({"kind": "failure_suspected", "iteration": 1,
                     "clients": [3, 5]})
        assert [a["rule"] for a in mon.alerts] == ["client_outage",
                                                   "client_outage"]

    def test_cooldown_suppresses_refiring(self):
        mon = AlertMonitor()
        mon.observe({"kind": "client_killed", "iteration": 2, "client": 0})
        mon.observe({"kind": "client_killed", "iteration": 2, "client": 1})
        assert len(mon.alerts) == 1                     # same iteration
        mon.observe({"kind": "client_killed", "iteration": 3, "client": 2})
        assert len(mon.alerts) == 2                     # next iteration ok

    def test_replay_offline(self):
        alerts = replay([
            {"kind": "client_killed", "iteration": 0, "client": 1},
            {"kind": "eval", "iteration": 0, "round": 0,
             "train_acc": 0.9, "test_acc": 0.5},
        ])
        assert [a["rule"] for a in alerts] == ["client_outage"]


class TestAlertMonitorWiring:
    def test_bus_tap_raises_alert_raised_without_recursion(self, tmp_path):
        bus = EventBus(str(tmp_path / "events.jsonl"))
        mon = AlertMonitor(path=str(tmp_path / "alerts.jsonl")).attach(bus)
        bus.emit("client_killed", client=4)
        raised = bus.events("alert_raised")
        assert len(raised) == 1 and raised[0]["rule"] == "client_outage"
        # alerts.jsonl carries the same record; file survives bus close
        bus.close()
        rows = [json.loads(l)
                for l in open(tmp_path / "alerts.jsonl")]
        assert rows[0]["rule"] == "client_outage"
        assert rows[0]["kind"] == "alert_raised"
        assert len(mon.alerts) == 1

    def test_failing_tap_never_breaks_emission(self):
        bus = EventBus(None)

        def bad_tap(rec):
            raise RuntimeError("observer crash")

        bus.add_tap(bad_tap)
        rec = bus.emit("eval", test_acc=0.5)            # no raise
        assert rec["kind"] == "eval"
        bus.remove_tap(bad_tap)

    def test_tap_sees_every_record(self):
        bus = EventBus(None)
        seen = []
        bus.add_tap(seen.append)
        bus.set_context(iteration=7)
        bus.emit("eval", test_acc=0.1)
        assert seen[0]["iteration"] == 7 and seen[0]["kind"] == "eval"


# ----------------------------------------------------------------------
def _write_jsonl(path, rows):
    with open(path, "w") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")


class TestReportDecisionSections:
    def test_assignment_matrix_and_alerts_render(self, tmp_path, capsys):
        from feddrift_tpu.obs.report import main
        _write_jsonl(tmp_path / "metrics.jsonl",
                     [{"_ts": 1.0, "iteration": 0, "round": 0,
                       "Test/Acc": 0.5}])
        _write_jsonl(tmp_path / "events.jsonl", [
            {"_ts": 1.0, "kind": "cluster_assign", "iteration": 0,
             "assignment": [0, 0, 1], "oracle_ari": 0.4,
             "oracle_purity": 0.8},
            {"_ts": 1.1, "kind": "cluster_assign", "iteration": 1,
             "assignment": [0, 1, 1], "oracle_ari": 1.0,
             "oracle_purity": 1.0},
            {"_ts": 1.2, "kind": "alert_raised", "iteration": 1,
             "rule": "cluster_churn", "severity": "warn",
             "message": "pool is thrashing"},
        ])
        assert main([str(tmp_path)]) == 0
        out = capsys.readouterr().out
        assert "assignment matrix" in out
        assert "[0 1 1]  ARI=1.000" in out
        assert "oracle agreement: final ARI 1.0000" in out
        assert "alerts:" in out
        assert "cluster_churn: pool is thrashing" in out

    def test_alerts_jsonl_preferred_over_events(self, tmp_path):
        from feddrift_tpu.obs.report import summarize
        _write_jsonl(tmp_path / "metrics.jsonl", [{"_ts": 1.0}])
        _write_jsonl(tmp_path / "alerts.jsonl", [
            {"_ts": 1.0, "kind": "alert_raised", "rule": "client_outage",
             "severity": "warn", "message": "m", "iteration": 0}])
        s = summarize(str(tmp_path))
        assert s["alerts"]["count"] == 1
        assert s["alerts"]["by_rule"] == {"client_outage": 1}

    def test_missing_run_dir_exits_nonzero(self, tmp_path, capsys):
        from feddrift_tpu.obs.report import main
        assert main([str(tmp_path / "absent")]) == 1
        assert "does not exist" in capsys.readouterr().err

    def test_follow_bounded_and_renders(self, tmp_path, capsys):
        from feddrift_tpu.obs.report import main
        _write_jsonl(tmp_path / "metrics.jsonl",
                     [{"_ts": 1.0, "iteration": 0, "round": 0,
                       "Test/Acc": 0.5}])
        _write_jsonl(tmp_path / "events.jsonl", [
            {"_ts": 1.0, "kind": "client_killed", "iteration": 0,
             "client": 2},
            {"_ts": 1.5, "kind": "iteration_end", "iteration": 0,
             "wall_s": 1.0, "rounds": 2, "examples": 10,
             "test_acc": 0.5, "rounds_per_s": 2.0},
            {"_ts": 2.0, "kind": "run_end", "test_acc": 0.5},
        ])
        # run_end present -> returns well inside the bound
        assert main([str(tmp_path), "--follow",
                     "--follow-timeout", "10", "--poll", "0.05"]) == 0
        out = capsys.readouterr().out
        assert "following" in out
        # the offline monitor catches the kill (no live alerts recorded)
        assert "[offline]" in out and "client_outage" in out
        assert "run:" in out                 # final report rendered

    def test_follow_timeout_on_unfinished_run(self, tmp_path, capsys):
        from feddrift_tpu.obs.report import main
        _write_jsonl(tmp_path / "metrics.jsonl", [{"_ts": 1.0}])
        _write_jsonl(tmp_path / "events.jsonl",
                     [{"_ts": 1.0, "kind": "iteration_start",
                       "iteration": 0}])
        assert main([str(tmp_path), "--follow",
                     "--follow-timeout", "0.3", "--poll", "0.05"]) == 0
        assert "bound reached" in capsys.readouterr().out


# ----------------------------------------------------------------------
def _sine_cfg(**kw):
    from feddrift_tpu.config import ExperimentConfig
    base = dict(dataset="sine", model="fnn", concept_num=4,
                concept_drift_algo="softcluster",
                concept_drift_algo_arg="H_A_C_1_10_0",
                train_iterations=4, comm_round=6, epochs=3, sample_num=50,
                batch_size=25, frequency_of_the_test=3, lr=0.05,
                client_num_in_total=10, client_num_per_round=10,
                report_client=0, seed=0)
    base.update(kw)
    return ExperimentConfig(**base)


class TestLiveEmission:
    def test_geni_oracle_scores_perfect_ari(self, tmp_path):
        """The change-point oracle assigns exactly by ground truth, so the
        live oracle_ari on every cluster_assign must be 1.0 — pinning the
        whole emission path (concepts -> assignment -> ARI) end to end."""
        from feddrift_tpu.simulation.runner import run_experiment
        out = str(tmp_path / "run")
        run_experiment(_sine_cfg(concept_drift_algo_arg="geni",
                                 train_iterations=3, comm_round=4,
                                 epochs=2), out_dir=out)
        events = [json.loads(l) for l in open(os.path.join(out,
                                                           "events.jsonl"))]
        assigns = [e for e in events if e["kind"] == "cluster_assign"]
        assert len(assigns) == 3
        assert all(e["oracle_ari"] == 1.0 for e in assigns), assigns
        assert all(e["oracle_purity"] == 1.0 for e in assigns)
        # run_start carries the scoring ground truth for offline replay
        start = next(e for e in events if e["kind"] == "run_start")
        assert np.asarray(start["concept_matrix"]).shape[1] == 10

    def test_merge_events_carry_distance_evidence(self, tmp_path):
        from feddrift_tpu.simulation.runner import run_experiment
        out = str(tmp_path / "run")
        run_experiment(_sine_cfg(), out_dir=out)
        events = [json.loads(l) for l in open(os.path.join(out,
                                                           "events.jsonl"))]
        drifts = [e for e in events if e["kind"] == "drift_detected"]
        assert drifts and all(e.get("threshold") == 0.1 for e in drifts)
        creates = [e for e in events if e["kind"] == "cluster_create"]
        assert creates and all(e.get("client") is not None for e in creates)
        merges = [e for e in events if e["kind"] == "cluster_merge"]
        if merges:      # this preset/config merges; guard stays honest
            for m in merges:
                assert m["distance"] <= m["threshold"]
                assert len(m["distance_row"]) == len(m["in_use"])

    def test_lineage_cli_on_real_run(self, tmp_path, capsys):
        from feddrift_tpu.simulation.runner import run_experiment
        out = str(tmp_path / "run")
        run_experiment(_sine_cfg(), out_dir=out)
        assert lineage.main([out]) == 0
        txt = capsys.readouterr().out
        assert "cluster genealogy" in txt
        assert "drift_spawn" in txt
        assert "oracle agreement" in txt


@pytest.mark.slow
class TestEndToEndOracle:
    def test_sea_softcluster_final_ari_above_floor(self, tmp_path):
        """The acceptance scenario: SEA + FedDrift (paper delta 0.04) must
        end with oracle ARI above a loose floor — the clustering really
        recovers the concept structure, not just spawn noise. Fixed seed;
        the trajectory is deterministic on CPU like the rest of the e2e
        suite."""
        from feddrift_tpu.simulation.runner import run_experiment
        out = str(tmp_path / "run")
        run_experiment(_sine_cfg(dataset="sea", concept_num=5,
                                 concept_drift_algo_arg="H_A_C_1_4_4",
                                 train_iterations=5, comm_round=30,
                                 epochs=8, sample_num=200, batch_size=50,
                                 frequency_of_the_test=15),
                       out_dir=out)
        s = lineage.summarize(out)
        assert s["has_ground_truth"]
        assert s["oracle"]["final_ari"] > 0.3, s["oracle"]
        assert s["oracle"]["best_ari"] > 0.5, s["oracle"]
        # genealogy shows real structure: spawns happened
        assert len(s["nodes"]) >= 2
