"""Host-plane observatory tests (obs/hostprof.py): sampling profiler
lifecycle + thread-safety, ledger golden byte accounting against a
hand-computed registry layout, scaling-exponent fits on synthetic
curves, the regress hostscale axis, and the fleet HOST-MB column.
Pure host logic — no compiled programs."""

from __future__ import annotations

import os
import threading
import time

from feddrift_tpu.obs import hostprof, live
from feddrift_tpu.obs.hostprof import (HostLedger, SamplingProfiler,
                                       fit_scaling, nbytes_of)
from feddrift_tpu.obs.instruments import registry


class TestSamplingProfiler:
    def test_start_stop_idempotent_and_restartable(self, tmp_path):
        path = str(tmp_path / "hostprof.jsonl")
        prof = SamplingProfiler(hz=200.0, path=path)
        assert not prof.running
        prof.start()
        prof.start()                              # second start is a no-op
        assert prof.running
        time.sleep(0.05)
        prof.stop()
        prof.stop()                               # second stop is a no-op
        prof.close()                              # close is an alias
        assert not prof.running
        n1 = prof.samples
        assert n1 > 0
        # restartable: the perf-gate toggle loop depends on this
        prof.start()
        time.sleep(0.05)
        prof.stop()
        assert prof.samples > n1
        assert os.path.exists(path)

    def test_samples_other_threads_and_folds_stacks(self, tmp_path):
        stop = threading.Event()

        def parked_worker():
            while not stop.wait(0.002):
                pass

        t = threading.Thread(target=parked_worker, daemon=True,
                             name="hp-test-worker")
        t.start()
        prof = SamplingProfiler(hz=500.0,
                                path=str(tmp_path / "hostprof.jsonl"))
        with prof:
            time.sleep(0.15)
        stop.set()
        t.join(timeout=2.0)
        folded = prof.folded()
        assert folded, "no stacks captured"
        # the worker's wait() leaf must appear in some folded stack, and
        # folded stacks are root->leaf ';'-joined frame labels
        assert any("parked_worker" in stack for stack in folded)
        text = prof.folded_text()
        lines = [l for l in text.splitlines() if l]
        counts = [int(l.rsplit(" ", 1)[1]) for l in lines]
        assert counts == sorted(counts, reverse=True)
        assert sum(counts) >= prof.samples  # >=1 thread folded per sample
        out = prof.write_folded(str(tmp_path / "x.folded"))
        assert open(out).read() == text

    def test_trace_slices_use_hostprof_lanes(self, tmp_path):
        import json
        path = str(tmp_path / "hostprof.jsonl")
        prof = SamplingProfiler(hz=500.0, path=path, pid=3)
        with prof:
            time.sleep(0.1)
        rows = [json.loads(l) for l in open(path)]
        assert rows, "no slices written"
        for r in rows:
            assert r["cat"] == "hostprof"
            assert r["tid"].startswith("hostprof:")
            assert r["pid"] == 3
            assert r["dur"] > 0
            assert ";" in r["args"]["stack"] or r["args"]["stack"]

    def test_concurrent_start_stop_is_safe(self):
        prof = SamplingProfiler(hz=1000.0)
        errs = []

        def churn():
            try:
                for _ in range(20):
                    prof.start()
                    prof.stop()
            except Exception as e:  # noqa: BLE001 — the assertion target
                errs.append(e)

        threads = [threading.Thread(target=churn) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=10.0)
        prof.stop()
        assert not errs
        assert not prof.running

    def test_configure_profiler_replaces_and_clears(self, tmp_path):
        try:
            p1 = hostprof.configure_profiler(
                100.0, path=str(tmp_path / "a.jsonl"))
            assert p1 is hostprof.get_profiler() and p1.running
            p2 = hostprof.configure_profiler(
                100.0, path=str(tmp_path / "b.jsonl"))
            assert not p1.running                 # old sampler stopped
            assert p2 is hostprof.get_profiler() and p2.running
            assert hostprof.configure_profiler(0.0) is None
            assert hostprof.get_profiler() is None
            assert not p2.running
        finally:
            hostprof.configure_profiler(0.0)


class TestHostLedgerGolden:
    def test_registry_column_bytes_hand_computed(self):
        """P=4 clients, T=3 steps: active 4x bool = 4 B; five int64
        columns (joined/last_seen/last_sampled/absent_streak/cluster)
        4x8 = 32 B each; two float64 columns (reliability, arm_acc)
        32 B each; assign_hist [4,3] int32 = 48 B. Total 276 B."""
        from feddrift_tpu.platform.registry import ClientRegistry
        reg = ClientRegistry(population=4, num_steps=3)
        cb = reg.column_bytes()
        assert cb["active"] == 4
        for col in ("joined_round", "last_seen_round",
                    "last_sampled_round", "absent_streak", "cluster"):
            assert cb[col] == 32, col
        assert cb["reliability"] == 32
        assert cb["arm_acc"] == 32
        assert cb["assign_hist"] == 4 * 3 * 4
        assert sum(cb.values()) == 276
        assert nbytes_of(reg.state_dict()) == 276

    def test_finalize_accounting_instruments_and_event_record(self):
        reg = registry()
        reg.reset()
        try:
            led = HostLedger()
            led.add_seconds("cohort_plan", 0.25)
            led.add_seconds("cohort_plan", 0.25)  # accumulates
            with led.timed("registry_writeback"):
                time.sleep(0.01)
            led.add_seconds("noise", -1.0)        # non-positive ignored
            led.set_bytes("registry_columns", 276)
            led.set_bytes("assign_hist", 48)
            led.set_bytes("routing_table", 1000)
            rec = led.finalize(iteration=7, rounds=4, emit_event=False)
            assert rec["iteration"] == 7 and rec["rounds"] == 4
            assert rec["seconds"]["cohort_plan"] == 0.5
            assert rec["seconds"]["registry_writeback"] >= 0.01
            assert "noise" not in rec["seconds"]
            assert rec["bytes"] == {"assign_hist": 48,
                                    "registry_columns": 276,
                                    "routing_table": 1000}
            assert rec["rss_bytes"] and rec["rss_peak_bytes"] >= \
                rec["rss_bytes"] > 0
            snap = reg.snapshot()
            assert snap['host_ledger_seconds{subsystem="cohort_plan"}'] == 0.5
            assert snap[
                'host_ledger_seconds_total{subsystem="cohort_plan"}'] == 0.5
            assert snap['host_bytes{structure="registry_columns"}'] == 276
            assert snap["host_rss_bytes"] > 0
            # seconds are per-iteration (cleared); bytes + counter persist
            rec2 = led.finalize(iteration=8, rounds=4, emit_event=False)
            assert rec2["seconds"] == {}
            assert rec2["bytes"]["routing_table"] == 1000
            led.add_seconds("cohort_plan", 0.5)
            led.finalize(iteration=9, rounds=4, emit_event=False)
            snap = reg.snapshot()
            assert snap[
                'host_ledger_seconds_total{subsystem="cohort_plan"}'] == 1.0
            assert led.top_bytes(2) == [("routing_table", 1000),
                                        ("registry_columns", 276)]
            led.reset()
            assert led.bytes() == {} and led.rss_peak_bytes == 0
        finally:
            reg.reset()


class TestFitScaling:
    def test_recovers_constant_and_linear_exponents(self):
        xs = [100, 1000, 10000, 100000]
        flat = fit_scaling(xs, [3.0, 3.0, 3.0, 3.0])
        assert abs(flat) < 1e-9                   # O(1) -> slope 0
        linear = fit_scaling(xs, [2.0 * x for x in xs])
        assert abs(linear - 1.0) < 1e-9           # O(P) -> slope 1
        quad = fit_scaling(xs, [x * x for x in xs])
        assert abs(quad - 2.0) < 1e-9             # O(P^2) -> slope 2

    def test_degenerate_inputs_return_none(self):
        assert fit_scaling([100], [1.0]) is None          # one point
        assert fit_scaling([100, 100], [1.0, 2.0]) is None  # x constant
        assert fit_scaling([100, 1000], [0.0, 0.0]) is None  # y <= 0 dropped
        assert fit_scaling([100, 1000], [None, 1.0]) is None
        # zeros are dropped, surviving points still fit
        e = fit_scaling([100, 1000, 10000], [0.0, 10.0, 100.0])
        assert abs(e - 1.0) < 1e-9


class TestHostscaleRegressAxis:
    BASE = {"hostscale": {
        "populations": [100, 1000],
        "rows": [
            {"population": 100, "rounds_per_sec": 100.0,
             "steady_recompiles": 0},
            {"population": 1000, "rounds_per_sec": 90.0,
             "steady_recompiles": 0},
        ],
        "exp_seconds": {"cohort_plan": 0.1, "registry_writeback": 1.0},
        "exp_bytes": {"registry_columns": 1.0},
        "bytes_per_client": {"registry_columns": 100.0},
    }}

    def test_pass_fail_and_skip_rows(self):
        import copy
        from feddrift_tpu.obs.regress import compare
        ok = compare(copy.deepcopy(self.BASE), self.BASE)
        hs = {r["metric"]: r for r in ok
              if r["metric"].startswith("hostscale")}
        assert hs["hostscale[100].rounds_per_s"]["status"] == "ok"
        assert hs["hostscale[100].steady_recompiles"]["status"] == "ok"
        assert hs["hostscale.exp_seconds[cohort_plan]"]["status"] == "ok"
        assert hs["hostscale.exp_bytes[registry_columns]"]["status"] == "ok"
        assert hs[
            "hostscale.bytes_per_client[registry_columns]"]["status"] == "ok"

        bad = copy.deepcopy(self.BASE)
        row = bad["hostscale"]["rows"][0]
        row["rounds_per_sec"], row["steady_recompiles"] = 10.0, 1
        # an O(1) subsystem went O(P); a structure outgrew its ceiling
        bad["hostscale"]["exp_seconds"]["cohort_plan"] = 1.0
        bad["hostscale"]["bytes_per_client"]["registry_columns"] = 200.0
        rows = compare(bad, self.BASE)
        hs = {r["metric"]: r for r in rows
              if r["metric"].startswith("hostscale")}
        assert hs["hostscale[100].rounds_per_s"]["status"] == "regress"
        assert hs["hostscale[100].steady_recompiles"]["status"] == "regress"
        assert hs["hostscale.exp_seconds[cohort_plan]"]["status"] == "regress"
        assert hs["hostscale.exp_seconds[registry_writeback]"][
            "status"] == "ok"
        assert hs["hostscale.bytes_per_client[registry_columns]"][
            "status"] == "regress"

        # exponent unfit on either side, or a missing axis -> skip
        unfit = copy.deepcopy(self.BASE)
        unfit["hostscale"]["exp_seconds"]["cohort_plan"] = None
        rows = compare(unfit, self.BASE)
        hs = {r["metric"]: r for r in rows
              if r["metric"].startswith("hostscale")}
        assert hs["hostscale.exp_seconds[cohort_plan]"]["status"] == "skip"
        rows = compare({}, self.BASE)
        hs = {r["metric"]: r for r in rows
              if r["metric"].startswith("hostscale")}
        assert hs["hostscale"]["status"] == "skip"
        # baseline without the axis: no hostscale rows at all, no failure
        rows = compare(copy.deepcopy(self.BASE), {})
        assert not any(r["metric"].startswith("hostscale") for r in rows)

    def test_committed_artifact_passes_self_regress(self):
        from feddrift_tpu.obs.regress import compare, load_bench
        art = load_bench(os.path.join(os.path.dirname(__file__), "..",
                                      "HOSTSCALE_r19.json"))
        rows = compare(art, art)
        assert all(r["status"] != "regress" for r in rows)
        hs = [r for r in rows if r["metric"].startswith("hostscale")]
        assert any(r["metric"].endswith("steady_recompiles") for r in hs)
        assert any(".exp_seconds[" in r["metric"] for r in hs)
        assert any(".bytes_per_client[" in r["metric"] for r in hs)

    def test_exponent_tolerance_is_absolute_headroom(self):
        import copy
        from feddrift_tpu.obs.regress import compare
        cand = copy.deepcopy(self.BASE)
        cand["hostscale"]["exp_seconds"]["cohort_plan"] = 0.29  # within +0.2
        rows = compare(cand, self.BASE)
        hs = {r["metric"]: r for r in rows
              if r["metric"].startswith("hostscale")}
        assert hs["hostscale.exp_seconds[cohort_plan]"]["status"] == "ok"
        cand["hostscale"]["exp_seconds"]["cohort_plan"] = 0.31
        rows = compare(cand, self.BASE)
        hs = {r["metric"]: r for r in rows
              if r["metric"].startswith("hostscale")}
        assert hs["hostscale.exp_seconds[cohort_plan]"]["status"] == "regress"


class TestLivePlaneHostColumn:
    def test_status_snapshot_host_block(self):
        led = hostprof.ledger()
        led.set_bytes("routing_table", 5 << 20)
        led.set_bytes("assign_hist", 1 << 20)
        try:
            doc = live.status_snapshot()
            host = doc["host"]
            assert host["rss_mb"] and host["rss_mb"] > 0
            assert host["top_structures"]["routing_table"] == 5 << 20
        finally:
            led.reset()

    def test_render_fleet_host_mb_column(self):
        lanes = {
            "runner": {"pid": 1, "status": {"iteration": 3},
                       "metrics": {"host_rss_bytes": 256 << 20}},
            # no metrics lane: falls back to the /status host block
            "edge/0": {"pid": 2, "status": {"host": {"rss_mb": 99.5}}},
        }
        table = live.render_fleet(lanes)
        lines = table.splitlines()
        header = lines[0].split()
        assert "HOST-MB" in " ".join(header)
        assert header.index("HOST-MB") == header.index("OUT") + 1
        runner = [l for l in lines if l.startswith("runner")][0]
        assert "256.0" in runner
        edge = [l for l in lines if l.startswith("edge/0")][0]
        assert "99.5" in edge
