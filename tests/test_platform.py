"""Platform-parity subsystem tests: robust aggregation, topologies, FedOpt,
secure aggregation, split learning."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest


class TestRobust:
    def _trees(self):
        g = {"w": jnp.zeros((4,)), "b": jnp.zeros(())}
        c = {"w": jnp.stack([jnp.ones((4,)) * 10, jnp.ones((4,)) * 0.1]),
             "b": jnp.zeros((2,))}
        return c, g

    def test_clipping_bounds_norm(self):
        from feddrift_tpu.platform.robust import clip_client_updates
        c, g = self._trees()
        clipped = clip_client_updates(c, g, jnp.float32(1.0))
        n0 = float(jnp.linalg.norm(clipped["w"][0]))
        n1 = float(jnp.linalg.norm(clipped["w"][1]))
        assert n0 == pytest.approx(1.0, rel=1e-5)       # clipped to bound
        assert n1 == pytest.approx(0.2, rel=1e-4)       # small update untouched

    def test_noise_and_aggregate(self):
        from feddrift_tpu.platform.robust import robust_fedavg
        c, g = self._trees()
        out = robust_fedavg(c, g, jnp.asarray([1.0, 1.0]),
                            jax.random.PRNGKey(0), jnp.float32(100.0),
                            jnp.float32(0.0))
        np.testing.assert_allclose(np.asarray(out["w"]),
                                   np.asarray((c["w"][0] + c["w"][1]) / 2),
                                   rtol=1e-5)


class TestTopology:
    def test_symmetric_row_stochastic(self):
        from feddrift_tpu.platform.topology import SymmetricTopologyManager
        m = SymmetricTopologyManager(6, 2)
        m.generate_topology()
        np.testing.assert_allclose(m.topology.sum(axis=1), 1.0, rtol=1e-6)
        assert m.topology.shape == (6, 6)
        assert len(m.get_out_neighbor_idx_list(1)) >= 2

    def test_asymmetric_neighbors(self):
        from feddrift_tpu.platform.topology import AsymmetricTopologyManager
        m = AsymmetricTopologyManager(8, 2, 2)
        m.generate_topology()
        np.testing.assert_allclose(m.topology.sum(axis=1), 1.0, rtol=1e-6)
        assert len(m.get_in_neighbor_idx_list(0)) > 0

    def test_gossip_converges_to_mean(self):
        from feddrift_tpu.platform.topology import (SymmetricTopologyManager,
                                                    gossip_mix)
        m = SymmetricTopologyManager(8, 4)
        m.generate_topology()
        W = jnp.asarray(m.topology)
        params = {"w": jnp.arange(8.0)[:, None] * jnp.ones((8, 3))}
        target = float(jnp.mean(jnp.arange(8.0)))
        for _ in range(60):
            params = gossip_mix(params, W)
        np.testing.assert_allclose(np.asarray(params["w"]), target, atol=1e-2)

    def test_push_sum_directed(self):
        from feddrift_tpu.platform.topology import (AsymmetricTopologyManager,
                                                    push_sum_step)
        m = AsymmetricTopologyManager(6, 2, 2)
        m.generate_topology()
        # column-stochastic for push-sum
        W = jnp.asarray(m.topology / m.topology.sum(axis=0, keepdims=True))
        params = {"w": jnp.arange(6.0)[:, None] * jnp.ones((6, 2))}
        weights = jnp.ones((6,))
        est = None
        for _ in range(80):
            params, weights, est = push_sum_step(params, weights, W)
        np.testing.assert_allclose(np.asarray(est["w"]), 2.5, atol=1e-2)


class TestFedOpt:
    def test_registry_names(self):
        from feddrift_tpu.platform.fedopt import OptRepo
        names = OptRepo.get_opt_names()
        assert "adam" in names and "sgd" in names and "yogi" in names
        with pytest.raises(KeyError):
            OptRepo.name2cls("nope")

    def test_server_sgd_step_moves_toward_clients(self):
        from feddrift_tpu.platform.fedopt import FedOptServer
        srv = FedOptServer("sgd", lr=1.0)
        g = {"w": jnp.zeros((3,))}
        c = {"w": jnp.stack([jnp.ones((3,)), 3 * jnp.ones((3,))])}
        out = srv.step(g, c, jnp.asarray([1.0, 1.0]))
        np.testing.assert_allclose(np.asarray(out["w"]), 2.0, rtol=1e-5)


class TestSecureAgg:
    def test_modular_inv(self):
        from feddrift_tpu.platform.secure_agg import P_DEFAULT, modular_inv
        a = np.array([2, 3, 12345], dtype=np.int64)
        inv = modular_inv(a)
        np.testing.assert_array_equal((a * inv) % P_DEFAULT, 1)

    def test_bgw_roundtrip(self):
        from feddrift_tpu.platform.secure_agg import bgw_decode, bgw_encode
        rng = np.random.default_rng(0)
        X = rng.integers(0, 1000, size=(2, 5), dtype=np.int64)
        shares = bgw_encode(X, N=5, T=2, rng=rng)
        rec = bgw_decode(shares[:3].reshape(3, -1), [0, 1, 2])
        np.testing.assert_array_equal(rec.reshape(2, 5), X)

    def test_lcc_roundtrip(self):
        from feddrift_tpu.platform.secure_agg import lcc_decode, lcc_encode
        rng = np.random.default_rng(1)
        X = rng.integers(0, 1000, size=(4, 3), dtype=np.int64)
        K, T, N = 2, 1, 5
        enc = lcc_encode(X, N=N, K=K, T=T, rng=rng)
        rec = lcc_decode(enc[: K + T], np.arange(K + T), K, T, N)
        np.testing.assert_array_equal(rec.reshape(4, 3), X)

    def test_additive_shares_sum_zero(self):
        from feddrift_tpu.platform.secure_agg import P_DEFAULT, gen_additive_ss
        s = gen_additive_ss(7, 4)
        np.testing.assert_array_equal(s.sum(axis=0) % P_DEFAULT, 0)

    def test_secure_sum_matches_plain_sum(self):
        from feddrift_tpu.platform.secure_agg import secure_sum
        rng = np.random.default_rng(2)
        v = rng.normal(size=(4, 6)).astype(np.float64)
        out = secure_sum(v, T=1)
        np.testing.assert_allclose(out, v.sum(axis=0), atol=1e-3)


class TestSplitNN:
    def test_split_training_learns(self):
        import optax
        from feddrift_tpu.platform.splitnn import SplitNNTrainer, make_split_mlp
        bottom, top = make_split_mlp(16, 2)
        rng = np.random.default_rng(0)
        x = rng.normal(size=(64, 4)).astype(np.float32)
        y = (x[:, 0] + x[:, 1] > 0).astype(np.int32)
        cp = bottom.init(jax.random.PRNGKey(0), x[:2])["params"]
        acts = bottom.apply({"params": cp}, x[:2])
        sp = top.init(jax.random.PRNGKey(1), acts)["params"]
        tr = SplitNNTrainer(
            client_apply=lambda p, xx: bottom.apply({"params": p}, xx),
            server_apply=lambda p, a: top.apply({"params": p}, a),
            client_opt=optax.sgd(0.5), server_opt=optax.sgd(0.5))
        c_opt, s_opt = tr.init_states(cp, sp)
        for _ in range(60):
            cp, sp, c_opt, s_opt, loss = tr.train_step(
                cp, sp, c_opt, s_opt, jnp.asarray(x), jnp.asarray(y))
        acc = tr.eval_step(cp, sp, jnp.asarray(x), jnp.asarray(y))
        assert float(acc) > 0.9


class TestDecentralizedOnline:
    def _stream(self, n=8, d=4, T=40, seed=0):
        rng = np.random.default_rng(seed)
        w_true = rng.normal(size=(d,)).astype(np.float32)
        xs = rng.normal(size=(T, n, d)).astype(np.float32)
        ys = np.sign(xs @ w_true).astype(np.float32)
        return xs, ys

    def _params(self, n=8, d=4):
        return {"w": jnp.zeros((n, d), jnp.float32),
                "b": jnp.zeros((n,), jnp.float32)}

    def test_dsgd_learns_and_reaches_consensus(self):
        from feddrift_tpu.platform.decentralized import (
            run_dsgd, consensus_distance)
        from feddrift_tpu.platform.topology import SymmetricTopologyManager
        n = 8
        topo = SymmetricTopologyManager(n, 4)
        topo.generate_topology()
        W = jnp.asarray(topo.topology)
        xs, ys = self._stream(n)
        params, losses = run_dsgd(self._params(n), W, jnp.asarray(xs),
                                  jnp.asarray(ys), lr=0.5)
        losses = np.asarray(losses)
        assert losses[-1].mean() < losses[0].mean() * 0.7
        assert float(consensus_distance(params)) < 0.05

    def test_push_sum_directed(self):
        from feddrift_tpu.platform.decentralized import run_push_sum
        from feddrift_tpu.platform.topology import AsymmetricTopologyManager
        n = 8
        topo = AsymmetricTopologyManager(n)
        topo.generate_topology()
        # push-sum wants column-stochastic mixing
        W = np.asarray(topo.topology).T
        W = W / W.sum(axis=0, keepdims=True)
        xs, ys = self._stream(n, seed=1)
        est, losses = run_push_sum(self._params(n), jnp.asarray(W),
                                   jnp.asarray(xs), jnp.asarray(ys), lr=0.5)
        losses = np.asarray(losses)
        assert np.isfinite(losses).all()
        assert losses[-1].mean() < losses[0].mean()


@pytest.mark.slow
class TestFedNAS:
    def test_search_round_updates_alphas_and_weights(self):
        from feddrift_tpu.platform.fednas import FedNAS
        from feddrift_tpu.models.darts import DARTSNetwork
        C, B = 2, 4
        net = DARTSNetwork(num_classes=3, filters=4, cells=1, nodes=2)
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.normal(size=(C, B, 8, 8, 3)).astype(np.float32))
        y = jnp.asarray(rng.integers(0, 3, size=(C, B)).astype(np.int32))
        nas = FedNAS(net, x[0, :1], C, local_steps=1, w_lr=0.1, arch_lr=0.1)
        before = jax.tree_util.tree_leaves(nas.params)
        params, arch, losses = nas.search(2, x, y, x, y,
                                          jnp.ones((C,), jnp.float32))
        after = jax.tree_util.tree_leaves(params)
        changed = [not np.allclose(a, b) for a, b in zip(before, after)]
        assert any(changed)
        assert losses.shape == (C,)
        # reference-shaped genotype: (op, predecessor) per kept edge
        from feddrift_tpu.models.darts import PRIMITIVES
        assert len(arch.normal) == 2 * 2 and len(arch.reduce) == 2 * 2
        for op, j in arch.normal + arch.reduce:
            assert op in PRIMITIVES and op != "none"
            assert 0 <= j < 4
        assert arch.normal_concat == [2, 3]

    def test_second_order_unrolled_search(self):
        from feddrift_tpu.models.darts import DARTSNetwork, split_arch_params
        from feddrift_tpu.platform.fednas import FedNAS
        C, B = 2, 4
        net = DARTSNetwork(num_classes=3, filters=4, cells=1, nodes=2)
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.normal(size=(C, B, 8, 8, 3)).astype(np.float32))
        y = jnp.asarray(rng.integers(0, 3, size=(C, B)).astype(np.int32))
        n = jnp.ones((C,), jnp.float32)

        first = FedNAS(net, x[0, :1], C, local_steps=1, w_lr=0.1, arch_lr=0.1)
        second = FedNAS(net, x[0, :1], C, local_steps=1, w_lr=0.1,
                        arch_lr=0.1, arch_search="second_order")
        p1, _, l1 = first.search(2, x, y, x, y, n)
        p2, _, l2 = second.search(2, x, y, x, y, n)
        assert np.isfinite(np.asarray(l2)).all()
        # the unrolled arch gradient must differ from first-order on alphas
        _, arch_mask = split_arch_params(p1)
        diffs = [np.abs(np.asarray(a) - np.asarray(b)).max()
                 for a, b, m in zip(jax.tree_util.tree_leaves(p1),
                                    jax.tree_util.tree_leaves(p2),
                                    jax.tree_util.tree_leaves(arch_mask)) if m]
        assert max(diffs) > 0

    def test_invalid_arch_search_rejected(self):
        from feddrift_tpu.models.darts import DARTSNetwork
        from feddrift_tpu.platform.fednas import FedNAS
        net = DARTSNetwork(num_classes=3, filters=4, cells=1, nodes=2)
        with pytest.raises(ValueError, match="arch_search"):
            FedNAS(net, jnp.zeros((1, 8, 8, 3)), 2, arch_search="nope")
