"""Incident plane: flight-recorder rings, trigger debounce, bundle
capture, the merged fleet dimension, and the ``incident`` triage CLI
(obs/blackbox.py, obs/incident.py; docs/OBSERVABILITY.md Incident
plane)."""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import time

from feddrift_tpu.obs import live
from feddrift_tpu.obs.blackbox import FlightRecorder
from feddrift_tpu.obs.events import EventBus
from feddrift_tpu.obs.incident import (IncidentManager, incident_main,
                                       resolve_bundle)


class TestFlightRecorder:
    def test_ring_wraparound_at_capacity(self):
        """The event ring is bounded: 100 events through a 16-slot ring
        keep exactly the newest 16, while the lifetime counter proves
        the rest were observed (not dropped on the record path)."""
        rec = FlightRecorder(capacity=16)
        for i in range(100):
            rec.observe({"kind": "metrics_logged", "i": i})
        d = rec.dump(include_spans=False, include_instruments=False)
        assert len(d["events"]) == 16
        assert [e["i"] for e in d["events"]] == list(range(84, 100))
        assert d["observed"] == 100
        assert d["capacity"] == 16

    def test_alert_tee_survives_main_ring_wrap(self):
        """Alerts are teed into their own ring, so a burst of ordinary
        events wrapping the main ring does not evict the alert trail."""
        rec = FlightRecorder(capacity=16)
        rec.observe({"kind": "alert_raised", "rule": "x",
                     "severity": "crit"})
        for i in range(50):
            rec.observe({"kind": "metrics_logged", "i": i})
        d = rec.dump(include_spans=False, include_instruments=False)
        assert not any(e["kind"] == "alert_raised" for e in d["events"])
        assert [a["rule"] for a in d["alerts"]] == ["x"]

    def test_disabled_recorder_is_inert(self):
        rec = FlightRecorder(capacity=16, enabled=False)
        rec.observe({"kind": "metrics_logged"})
        assert rec.snapshot_instruments() is None
        d = rec.dump(include_spans=False, include_instruments=False)
        assert d["events"] == [] and d["observed"] == 0

    def test_bus_tap_feeds_rings(self, tmp_path):
        bus = EventBus(str(tmp_path / "events.jsonl"))
        rec = FlightRecorder(capacity=8).attach(bus)
        with bus:
            bus.emit("run_start")
            bus.emit("round_breakdown", wall_s=1.0,
                     segments={"train": 0.9})
        d = rec.dump(include_spans=False, include_instruments=False)
        assert [e["kind"] for e in d["events"]] == ["run_start",
                                                    "round_breakdown"]
        assert len(d["round_breakdowns"]) == 1
        rec.detach()


class TestIncidentManager:
    def test_debounce_window(self, tmp_path):
        """One bundle per debounce window; suppressed triggers are
        counted; ``force`` (the crash path) bypasses the window."""
        t = [0.0]
        m = IncidentManager(str(tmp_path), debounce_s=30.0,
                            clock=lambda: t[0])
        assert m.trigger("first") is not None
        assert m.trigger("second") is None
        assert m.suppressed == 1
        t[0] = 29.0
        assert m.trigger("third") is None
        t[0] = 31.0
        assert m.trigger("fourth") is not None
        assert m.trigger("crash", force=True) is not None
        bundles = sorted(os.listdir(tmp_path / "incidents"))
        assert len(bundles) == 3

    def test_concurrent_trigger_storm_yields_one_bundle(self, tmp_path):
        """Every replica draining at once is ONE incident: 8 threads
        firing through the same debounce window produce exactly one
        bundle, and the bundle records how many triggers it absorbed."""
        m = IncidentManager(str(tmp_path), debounce_s=60.0)
        barrier = threading.Barrier(8)
        results = []

        def fire(i):
            barrier.wait()
            results.append(m.trigger(f"storm-{i}"))

        threads = [threading.Thread(target=fire, args=(i,))
                   for i in range(8)]
        for th in threads:
            th.start()
        for th in threads:
            th.join(timeout=30)
        captured = [r for r in results if r is not None]
        assert len(captured) == 1
        assert sorted(os.listdir(tmp_path / "incidents")) \
            == [os.path.basename(captured[0])]
        meta = json.load(open(os.path.join(captured[0], "meta.json")))
        assert meta["suppressed_triggers"] == 0  # counted before write
        assert m.suppressed == 7

    def test_trigger_predicates(self, tmp_path):
        """Only crit alerts / rollback verdicts trigger; warns and
        promote verdicts do not."""
        m = IncidentManager(str(tmp_path), debounce_s=0.0)
        m.observe({"kind": "alert_raised", "rule": "r", "severity": "warn"})
        m.observe({"kind": "canary_verdict", "verdict": "promote"})
        assert not os.path.isdir(tmp_path / "incidents")
        m.observe({"kind": "alert_raised", "rule": "ari_collapse",
                   "severity": "crit"})
        bundles = os.listdir(tmp_path / "incidents")
        assert len(bundles) == 1 and "alert_ari_collapse" in bundles[0]

    def test_bundle_contents_and_prune(self, tmp_path):
        rec = FlightRecorder(capacity=32)
        rec.observe({"kind": "run_start", "_ts": 1.0})
        (tmp_path / "alerts.jsonl").write_text(
            json.dumps({"rule": "x"}) + "\n")
        m = IncidentManager(str(tmp_path), recorder=rec, debounce_s=0.0,
                            max_bundles=2,
                            config_json=json.dumps({"dataset": "sea"}))
        for i in range(4):
            assert m.trigger(f"t{i}") is not None
        names = sorted(os.listdir(tmp_path / "incidents"))
        assert len(names) == 2 and names[-1].endswith("t3")
        bdir = os.path.join(tmp_path, "incidents", names[-1])
        files = sorted(os.listdir(bdir))
        for expect in ("alerts_tail.jsonl", "config.json", "flight.json",
                       "host_ledger.json", "meta.json", "trace.json"):
            assert expect in files
        meta = json.load(open(os.path.join(bdir, "meta.json")))
        assert meta["reason"] == "t3" and meta["pid"] == os.getpid()
        flight = json.load(open(os.path.join(bdir, "flight.json")))
        assert flight["events"][0]["kind"] == "run_start"
        trace = json.load(open(os.path.join(bdir, "trace.json")))
        assert any(ev.get("name") == "run_start"
                   for ev in trace["traceEvents"])

    def test_on_exception_bypasses_debounce(self, tmp_path):
        m = IncidentManager(str(tmp_path), debounce_s=600.0)
        assert m.trigger("first") is not None
        try:
            raise ValueError("model diverged")
        except ValueError as err:
            path = m.on_exception(err)
        assert path is not None and "exception_ValueError" in path
        meta = json.load(open(os.path.join(path, "meta.json")))
        assert "model diverged" in meta["evidence"]["traceback"]

    def test_no_run_dir_is_inert(self):
        m = IncidentManager(None, debounce_s=0.0)
        assert m.trigger("x") is None
        assert m.trigger("x", force=True) is None


class TestFleetDimension:
    def test_merged_bundle_names_dead_replica(self, tmp_path, capsys):
        """A replica death mid-traffic produces ONE bundle holding the
        per-replica flight snapshots, and the triage CLI attributes the
        dead replica loudly (and exits 0)."""
        rec = FlightRecorder(capacity=32)
        rec.observe({"kind": "replica_failed", "replica": "r1",
                     "reason": "fault:crash", "_ts": 1.0})
        m = IncidentManager(str(tmp_path), recorder=rec, debounce_s=0.0)
        m.fleet_source = lambda reason, ev: {
            "dead": ["r1"],
            "lanes": {"serve/r0": {"replica": "r0", "failed": None},
                      "serve/r1": {"replica": "r1",
                                   "failed": "Boom('crash')"}}}
        bdir = m.trigger("replica_failed", evidence={"replica": "r1"})
        meta = json.load(open(os.path.join(bdir, "meta.json")))
        assert meta["fleet"]["dead"] == ["r1"]
        assert meta["fleet"]["lanes"] == ["serve/r0", "serve/r1"]
        assert sorted(os.listdir(os.path.join(bdir, "fleet"))) \
            == ["serve_r0.json", "serve_r1.json"]
        assert resolve_bundle(str(tmp_path)) == bdir
        assert incident_main([str(tmp_path)]) == 0
        out = capsys.readouterr().out
        assert "DEAD REPLICAS: r1" in out
        assert "serve/r0" in out and "replica_failed" in out

    def test_pull_flights_round_trip(self):
        """The ops/incident lane over a loopback client: a publisher
        armed with a flight_fn answers a collector's pull with its ring
        snapshot."""
        class LoopClient:
            def __init__(self):
                self.qs = {}

            def subscribe(self, topic, sink=None):
                import queue as _q
                q = sink if sink is not None else _q.Queue()
                self.qs.setdefault(topic, []).append(q)
                return q

            def publish(self, topic, payload):
                for q in self.qs.get(topic, []):
                    q.put(payload)

        c = LoopClient()
        rec = FlightRecorder(capacity=8)
        rec.observe({"kind": "serve_request", "replica": "r0"})
        pub = live.OpsPublisher(
            c, "serve/r0", namespace="t", interval_s=5.0,
            flight_fn=lambda: rec.dump(include_spans=False,
                                       include_instruments=False))
        pub.start()
        try:
            got = live.pull_flights(c, ["serve/r0"], namespace="t",
                                    timeout_s=10.0, poll_s=0.05)
            assert "serve/r0" in got
            snap = got["serve/r0"]
            assert snap["lane"] == "serve/r0"
            assert snap["flight"]["events"][0]["kind"] == "serve_request"
            # a lane nobody serves stays silently absent
            got = live.pull_flights(c, ["serve/ghost"], namespace="t",
                                    timeout_s=0.3, poll_s=0.05)
            assert got == {}
        finally:
            pub.close()


class TestProcessHooks:
    def test_sigquit_captures_bundle_in_subprocess(self, tmp_path):
        """kill -QUIT on a wedged process dumps all-thread stacks to the
        faulthandler log AND snapshots an incident bundle — exercised in
        a real subprocess so the signal path is the production one."""
        script = r"""
import os, signal, sys, time
run_dir = sys.argv[1]
from feddrift_tpu.obs import events
from feddrift_tpu.obs import incident
from feddrift_tpu.obs.blackbox import FlightRecorder
bus = events.get_bus()
rec = FlightRecorder(capacity=32).attach(bus)
m = incident.IncidentManager(run_dir, recorder=rec,
                             debounce_s=600.0).attach(bus)
fh = open(os.path.join(run_dir, "faulthandler.log"), "w")
incident.install_process_hooks(m, faulthandler_file=fh)
os.kill(os.getpid(), signal.SIGQUIT)
time.sleep(0.2)     # let the handler run at the next bytecode boundary
bundles = os.listdir(os.path.join(run_dir, "incidents"))
assert len(bundles) == 1 and "sigquit" in bundles[0], bundles
fh.flush()
print("OK", bundles[0])
"""
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        proc = subprocess.run(
            [sys.executable, "-c", script, str(tmp_path)],
            capture_output=True, text=True, timeout=120, env=env)
        assert proc.returncode == 0, proc.stderr
        assert "OK" in proc.stdout
        log = (tmp_path / "faulthandler.log").read_text()
        assert "Thread" in log or "File" in log  # real stack dump landed
        bdir = resolve_bundle(str(tmp_path))
        meta = json.load(open(os.path.join(bdir, "meta.json")))
        assert meta["reason"] == "sigquit"

    def test_excepthook_chain_captures(self, tmp_path):
        from feddrift_tpu.obs import incident as incident_mod
        m = IncidentManager(str(tmp_path), debounce_s=600.0)
        prev_current = incident_mod.current_manager()
        prev_hook = sys.excepthook
        try:
            incident_mod.set_current(m)
            # simulate what install_process_hooks' chained hook does
            try:
                raise RuntimeError("boom")
            except RuntimeError as err:
                mgr = incident_mod.current_manager()
                assert mgr is m
                path = mgr.on_exception(err)
            assert path is not None
        finally:
            sys.excepthook = prev_hook
            incident_mod.set_current(prev_current)


class TestAlertsRotation:
    def test_rotation_boundary(self, tmp_path):
        """alerts.jsonl honours the obs_max_file_mb cap like events/
        spans: crossing the byte bound renames to .1 and every line in
        BOTH generations stays a whole JSON record (no torn writes)."""
        from feddrift_tpu.obs import alerts as obs_alerts
        path = str(tmp_path / "alerts.jsonl")
        for i in range(12):
            obs_alerts.append_alert(
                path, {"rule": "budget", "severity": "warn", "i": i,
                       "message": "m" * 80},
                max_bytes=400)
        assert os.path.isfile(path + ".1")
        rows = []
        for fname in (path + ".1", path):
            if not os.path.isfile(fname):
                continue        # the very last append may have rotated
            with open(fname) as f:
                for ln in f:
                    rows.append(json.loads(ln))   # raises on a torn line
        assert rows, "rotation dropped every record"
        # the retained generations are cut at the boundary, never
        # unbounded: each file holds at most cap + one whole record
        for fname in (path + ".1", path):
            if os.path.isfile(fname):
                assert os.path.getsize(fname) <= 400 + 200

    def test_monitor_passes_cap_through(self, tmp_path):
        from feddrift_tpu.obs.alerts import AlertMonitor
        mon = AlertMonitor(path=str(tmp_path / "alerts.jsonl"),
                           max_bytes=123)
        assert mon.max_bytes == 123


class TestFleetStale:
    def test_stale_lane_evicted_and_marked(self):
        now = 1000.0
        lanes = {
            "runner": {"lane": "runner", "pid": 11, "ts": 995.0, "seq": 3,
                       "status": {"iteration": 7},
                       "health": {"status": "ok"}},
            "serve/r1": {"lane": "serve/r1", "pid": 22, "ts": 880.0,
                         "seq": 9, "status": {"iteration": 2},
                         "health": {"status": "ok"}},
        }
        table = live.render_fleet(lanes, stale_after=60.0, now=now)
        lines = table.splitlines()
        assert lines[0].split()[:3] == ["LANE", "PID", "AGE"]
        live_row = next(l for l in lines if l.startswith("runner"))
        stale_row = next(l for l in lines if l.startswith("serve/r1"))
        assert "5s" in live_row and "(stale)" not in live_row
        assert "120s" in stale_row and "(stale)" in stale_row
        assert "ok" not in stale_row       # frozen metrics not rendered
        # disabled: the frozen snapshot renders as usual
        table = live.render_fleet(lanes, stale_after=None, now=now)
        assert "(stale)" not in table

    def test_fleet_cli_accepts_stale_after(self):
        """The flag parses and <=0 disables eviction (smoke via
        argparse path: bad broker exits via error, so only check the
        parser wiring on render)."""
        lanes = {"a": {"lane": "a", "pid": 1, "ts": 0.0, "seq": 1}}
        out = live.render_fleet(lanes, stale_after=None, now=1e9)
        assert "(stale)" not in out


class TestCliRouting:
    def test_incident_verb_routes_pre_jax(self, tmp_path, capsys):
        from feddrift_tpu.cli import main
        m = IncidentManager(str(tmp_path), debounce_s=0.0)
        m.trigger("alert:test", evidence={"rule": "test",
                                          "severity": "crit"})
        assert main(["incident", str(tmp_path)]) == 0
        out = capsys.readouterr().out
        assert "alert:test" in out
        assert main(["incident", str(tmp_path), "--json"]) == 0
        payload = json.loads(capsys.readouterr().out)
        assert payload["meta"]["reason"] == "alert:test"

    def test_incident_verb_missing_bundle_exits_1(self, tmp_path, capsys):
        from feddrift_tpu.cli import main
        assert main(["incident", str(tmp_path)]) == 1
        assert "no incident bundle" in capsys.readouterr().err
