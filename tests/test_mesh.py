"""parallel/mesh.py: device mesh construction and pool sharding layout.

Runs on the harness's 8 virtual CPU devices (conftest forces
``--xla_force_host_platform_device_count=8``), so 1-D and 2-D
``(models, clients)`` layouts and real multi-shard placement are
exercised without TPU hardware.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from feddrift_tpu.parallel.mesh import (
    client_sharding,
    constrain_pool,
    make_mesh,
    pool_spec,
    replicate,
    shard_client_arrays,
)


class TestMakeMesh:
    def test_default_is_1d_clients_over_all_devices(self):
        mesh = make_mesh()
        assert mesh.axis_names == ("clients",)
        assert mesh.shape["clients"] == len(jax.devices())

    def test_num_devices_slices_prefix(self):
        mesh = make_mesh(num_devices=4)
        assert mesh.shape["clients"] == 4
        assert list(mesh.devices.flat) == jax.devices()[:4]

    def test_2d_shape_layout(self):
        mesh = make_mesh(shape={"models": 2, "clients": 4})
        assert mesh.axis_names == ("models", "clients")
        assert mesh.devices.shape == (2, 4)
        # row-major fill over the device prefix
        assert list(mesh.devices.flat) == jax.devices()[:8]

    def test_2d_shape_too_large_raises(self):
        with pytest.raises(ValueError, match="devices"):
            make_mesh(shape={"models": 4, "clients": 8})


class TestShardingSpecs:
    def test_client_sharding_rank_and_axis(self):
        mesh = make_mesh()
        s = client_sharding(mesh, rank=3, client_axis=0)
        assert s.spec == P("clients", None, None)
        s = client_sharding(mesh, rank=4, client_axis=1)
        assert s.spec == P(None, "clients", None, None)

    def test_shard_client_arrays_places_shards(self):
        mesh = make_mesh()
        x = jnp.arange(8 * 3, dtype=jnp.float32).reshape(8, 3)
        sx = shard_client_arrays(mesh, x)
        assert isinstance(sx.sharding, NamedSharding)
        assert sx.sharding.spec == P("clients", None)
        shards = sx.addressable_shards
        assert len(shards) == 8 and shards[0].data.shape == (1, 3)
        np.testing.assert_array_equal(np.asarray(sx), np.asarray(x))

    def test_replicate_commits_full_copy_per_device(self):
        mesh = make_mesh()
        tree = {"w": jnp.ones((2, 3)), "b": jnp.zeros(3)}
        rt = replicate(mesh, tree)
        for leaf in jax.tree_util.tree_leaves(rt):
            assert leaf.sharding.spec == P()
            assert leaf.committed
            assert all(s.data.shape == leaf.shape
                       for s in leaf.addressable_shards)


class TestPoolSpec:
    def test_2d_mesh_places_divisible_axes(self):
        mesh = make_mesh(shape={"models": 2, "clients": 4})
        assert pool_spec(mesh, (4, 8, 3), model_axis=0, client_axis=1) \
            == P("models", "clients", None)

    def test_indivisible_axis_degrades_to_replicated(self):
        mesh = make_mesh(shape={"models": 2, "clients": 4})
        # M=3 % 2 != 0: models axis must degrade, clients still placed
        assert pool_spec(mesh, (3, 8), model_axis=0, client_axis=1) \
            == P(None, "clients")
        # C=6 % 4 != 0: both degrade
        assert pool_spec(mesh, (3, 6), model_axis=0, client_axis=1) == P(None, None)

    def test_legacy_1d_mesh_never_places_models(self):
        mesh = make_mesh()
        assert pool_spec(mesh, (4, 8), model_axis=0, client_axis=1) \
            == P(None, "clients")


class TestConstrainPool:
    def test_noop_on_none_and_non_splitting_mesh(self):
        tree = {"w": jnp.ones((2, 4))}
        assert constrain_pool(None, tree) is tree
        # 1-device mesh: an all-replicated constraint would COMMIT outputs
        # and change downstream jit cache keys — must return unchanged
        mesh1 = make_mesh(num_devices=1)
        assert constrain_pool(mesh1, tree) is tree

    def test_2d_mesh_constraint_is_value_preserving(self):
        mesh = make_mesh(shape={"models": 2, "clients": 4})
        x = jnp.arange(2 * 8 * 3, dtype=jnp.float32).reshape(2, 8, 3)

        @jax.jit
        def f(v):
            return constrain_pool(mesh, v, model_axis=0, client_axis=1)

        out = f(x)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(x))
        # jit normalizes away trailing Nones in the propagated spec
        assert out.sharding.spec == P("models", "clients")
