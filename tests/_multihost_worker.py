"""Worker for the 2-process multi-controller integration test.

Each process owns 2 virtual CPU devices; together they form a 4-device
global mesh over which the REAL federated round program runs SPMD — the
closest a single box gets to the reference's mpirun-launched multi-host
deployment (FedAvgEnsAPI.py:25-29), with the client mesh axis spanning the
process (DCN) boundary exactly as it would on a multi-host pod.

Usage: python tests/_multihost_worker.py <process_id> <num_processes> <addr>
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 2)


def main() -> None:
    pid, n, addr = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]

    from feddrift_tpu.comm import multihost

    multihost.initialize(coordinator_address=addr, num_processes=n,
                         process_id=pid)
    assert jax.process_count() == n, jax.process_count()
    assert multihost.process_count() == n
    assert multihost.is_coordinator() == (pid == 0)

    import jax.numpy as jnp
    import numpy as np

    # control-plane helpers across real process boundaries
    val = multihost.broadcast_from_coordinator(jnp.float32(41.0 + pid))
    assert float(val) == 41.0, val
    s = multihost.broadcast_sum(np.float32(pid + 1))
    assert float(s) == n * (n + 1) / 2, s

    # the actual round program, client axis spanning both processes
    from jax.sharding import Mesh

    from feddrift_tpu.config import ExperimentConfig
    from feddrift_tpu.core.pool import ModelPool
    from feddrift_tpu.core.step import TrainStep, make_optimizer
    from feddrift_tpu.data.registry import make_dataset
    from feddrift_tpu.models import create_model
    from feddrift_tpu.parallel.mesh import shard_client_arrays

    C = len(jax.devices())            # one client per global device
    cfg = ExperimentConfig(dataset="sea", model="fnn", train_iterations=2,
                           sample_num=32, batch_size=16, epochs=2,
                           client_num_in_total=C, client_num_per_round=C,
                           concept_num=2, seed=0)
    ds = make_dataset(cfg)            # same seed -> identical on every process
    module = create_model(cfg.model, ds, cfg)
    pool = ModelPool.create(module, jnp.asarray(ds.x[0, 0, :2]),
                            cfg.num_models, seed=0)
    step = TrainStep(pool.apply, make_optimizer("adam", cfg.lr, cfg.wd),
                     cfg.batch_size, cfg.epochs, ds.num_classes)

    mesh = Mesh(np.asarray(jax.devices()), ("clients",))
    x = shard_client_arrays(mesh, jnp.asarray(ds.x))
    y = shard_client_arrays(mesh, jnp.asarray(ds.y))
    M, T1, N = cfg.num_models, ds.num_steps + 1, ds.samples_per_step
    tw = shard_client_arrays(mesh, jnp.ones((M, C, T1), jnp.float32),
                             client_axis=1)
    sw = shard_client_arrays(mesh, jnp.ones((M, C, N), jnp.float32),
                             client_axis=1)
    fm = jnp.ones((M, *ds.feature_shape), jnp.float32)
    opt = step.init_opt_states(pool.params, M, C)

    new_params, _, _, n_arr, losses = step.train_round(
        pool.params, opt, jax.random.PRNGKey(0), x, y, tw, sw, fm,
        jnp.float32(1.0))
    jax.block_until_ready(new_params)

    # aggregated params are replicated: every process sees identical values
    leaf0 = np.asarray(jax.tree_util.tree_leaves(new_params)[0])
    digest = float(np.abs(leaf0).sum())
    digests = multihost.broadcast_sum(np.float32(digest))
    assert abs(float(digests) - n * digest) < 1e-3 * max(1.0, abs(digest)), (
        digest, float(digests))

    correct, _, total = step.acc_matrix(new_params, x[:, 0], y[:, 0], fm)
    jax.block_until_ready(correct)
    print(f"WORKER_OK {pid} digest={digest:.4f}", flush=True)


if __name__ == "__main__":
    main()
