"""Worker for the 2-process multi-controller integration test.

Each process owns 2 virtual CPU devices; together they form a 4-device
global mesh over which the REAL federated round program runs SPMD — the
closest a single box gets to the reference's mpirun-launched multi-host
deployment (FedAvgEnsAPI.py:25-29), with the client mesh axis spanning the
process (DCN) boundary exactly as it would on a multi-host pod.

Usage: python tests/_multihost_worker.py <process_id> <num_processes> <addr>
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 2)


def main() -> None:
    pid, n, addr = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]

    from feddrift_tpu.comm import multihost

    multihost.initialize(coordinator_address=addr, num_processes=n,
                         process_id=pid)
    assert jax.process_count() == n, jax.process_count()
    assert multihost.process_count() == n
    assert multihost.is_coordinator() == (pid == 0)

    import jax.numpy as jnp
    import numpy as np

    # control-plane helpers across real process boundaries
    val = multihost.broadcast_from_coordinator(jnp.float32(41.0 + pid))
    assert float(val) == 41.0, val
    s = multihost.broadcast_sum(np.float32(pid + 1))
    assert float(s) == n * (n + 1) / 2, s

    # The PRODUCT round loop, unmodified, client axis spanning both
    # processes: Experiment builds the global mesh itself, algorithms fetch
    # eval matrices through multihost.fetch, and only the coordinator
    # writes logs/checkpoints (runner.py coordinator gating).
    from feddrift_tpu.config import ExperimentConfig
    from feddrift_tpu.simulation.runner import Experiment

    C = len(jax.devices())            # one client per global device
    cfg = ExperimentConfig(dataset="sea", model="fnn",
                           concept_drift_algo="softcluster",
                           concept_drift_algo_arg="H_A_C_1_10_0",
                           change_points="rand", drift_together=1,
                           train_iterations=2, comm_round=2,
                           sample_num=32, batch_size=16, epochs=2,
                           client_num_in_total=C, client_num_per_round=C,
                           concept_num=2, seed=0, frequency_of_the_test=1)
    exp = Experiment(cfg)             # same seed -> identical on every process
    assert exp.is_coordinator == (pid == 0)
    for t in range(cfg.train_iterations):
        exp.run_iteration(t)

    acc = float(exp.logger.last("Test/Acc"))
    assert np.isfinite(acc), acc

    # aggregated pool params are replicated: every process holds identical
    # values, and host-side metric state stayed in lockstep
    leaf0 = np.asarray(jax.tree_util.tree_leaves(exp.pool.params)[0])
    digest = float(np.abs(leaf0).sum())
    digests = multihost.broadcast_sum(np.float32(digest))
    assert abs(float(digests) - n * digest) < 1e-3 * max(1.0, abs(digest)), (
        digest, float(digests))
    accs = multihost.broadcast_sum(np.float32(acc))
    assert abs(float(accs) - n * acc) < 1e-5, (acc, float(accs))
    print(f"WORKER_OK {pid} digest={digest:.4f}", flush=True)


if __name__ == "__main__":
    main()
