"""Real-format data ingestion: miniature fixture files in the reference's
on-disk layouts must be loaded by the registry instead of prototype
synthesis (VERDICT round-1 item 4).

Formats covered:
- LEAF MNIST train JSON  (reference MNIST/data_loader_cont.py:152-171)
- FMoW npz partitions    (reference fmow/data_loader.py:63-103 layout)
- UCI SUSY / RO CSV      (reference data_loader_for_susy_and_ro.py)
"""

import json
import os

import numpy as np
import pytest

from feddrift_tpu.config import ExperimentConfig
from feddrift_tpu.data.registry import make_dataset

C, T, N = 2, 2, 5   # tiny: 2 clients, 2 iterations (+1 test step), 5 samples


def _cfg(tmp_path, dataset, **kw):
    return ExperimentConfig(
        dataset=dataset, model="fnn", concept_drift_algo="win-1",
        change_points="rand", drift_together=1,
        client_num_in_total=C, client_num_per_round=C,
        train_iterations=T, comm_round=1, sample_num=N,
        data_dir=str(tmp_path), **kw)


# ----------------------------------------------------------------- LEAF MNIST
def _write_leaf_mnist(tmp_path, n_samples=40):
    rng = np.random.default_rng(7)
    d = os.path.join(tmp_path, "MNIST", "train")
    os.makedirs(d)
    users = ["f_0001", "f_0002"]
    xs = rng.random((n_samples, 784)).round(4)
    ys = rng.integers(0, 10, n_samples)
    half = n_samples // 2
    payload = {
        "users": users,
        "num_samples": [half, n_samples - half],
        "user_data": {
            users[0]: {"x": xs[:half].tolist(), "y": ys[:half].tolist()},
            users[1]: {"x": xs[half:].tolist(), "y": ys[half:].tolist()},
        },
    }
    with open(os.path.join(d, "all_data_niid_0_keep_10_train_9.json"), "w") as f:
        json.dump(payload, f)
    return xs, ys


def test_leaf_mnist_json_is_loaded(tmp_path):
    xs, _ = _write_leaf_mnist(tmp_path)
    ds = make_dataset(_cfg(tmp_path, "MNIST"))
    assert ds.meta["real_data"] is True
    # every served sample is one of the fixture images (shuffled + wrapped,
    # never synthesized)
    source = {r.tobytes() for r in xs.astype(np.float32)}
    flat = ds.x.reshape(-1, 784)
    for row in flat[:: max(1, len(flat) // 16)]:
        assert np.asarray(row, np.float32).tobytes() in source


def test_leaf_mnist_label_swap_applies_to_real_labels(tmp_path):
    """Drift semantics on real data: a concept-k step serves the same images
    with the reference's swapped label pairs (data_loader_cont.py:179-214)."""
    from feddrift_tpu.data.prototype import apply_label_swap
    xs, ys = _write_leaf_mnist(tmp_path)
    cfg = _cfg(tmp_path, "MNIST", concept_num=2)
    ds = make_dataset(cfg)
    by_img = {xs[i].astype(np.float32).tobytes(): int(ys[i])
              for i in range(len(xs))}
    flat_x = np.asarray(ds.x).reshape(C, T + 1, N, 784)
    for c in range(C):
        for t in range(T + 1):
            k = int(ds.concepts[t, c])
            true = np.array([by_img[flat_x[c, t, i].astype(np.float32)
                                    .tobytes()] for i in range(N)], np.int32)
            np.testing.assert_array_equal(
                np.asarray(ds.y[c, t]), apply_label_swap(true, k, 10))


def test_missing_leaf_dir_falls_back_to_prototypes(tmp_path):
    ds = make_dataset(_cfg(tmp_path, "MNIST"))
    assert ds.meta["real_data"] is False


# ----------------------------------------------------------------- FMoW npz
def test_fmow_npz_partitions_are_loaded(tmp_path):
    rng = np.random.default_rng(3)
    part = os.path.join(tmp_path, "fmow", "partitions", "rand")
    os.makedirs(part)
    truth = {}
    for c in range(C):
        for t in range(T + 1):
            x = rng.random((3, 32, 32, 3)).astype(np.float32)  # < N: wraps
            y = rng.integers(0, 62, 3).astype(np.int32)
            np.savez(os.path.join(part, f"client_{c}_iter_{t}.npz"), x=x, y=y)
            truth[(c, t)] = (x, y)
    ds = make_dataset(_cfg(tmp_path, "fmow"))
    assert ds.meta["real_data"] is True
    take = np.arange(N) % 3
    for (c, t), (x, y) in truth.items():
        np.testing.assert_array_equal(np.asarray(ds.x[c, t]), x[take])
        np.testing.assert_array_equal(np.asarray(ds.y[c, t]), y[take])


def test_fmow_incomplete_partitions_fall_back(tmp_path):
    part = os.path.join(tmp_path, "fmow", "partitions", "rand")
    os.makedirs(part)
    np.savez(os.path.join(part, "client_0_iter_0.npz"),
             x=np.zeros((2, 32, 32, 3), np.float32),
             y=np.zeros(2, np.int32))         # only one of C*(T+1) files
    ds = make_dataset(_cfg(tmp_path, "fmow"))
    assert ds.meta["real_data"] is False


def test_fmow_wrong_resolution_is_rejected(tmp_path):
    part = os.path.join(tmp_path, "fmow", "partitions", "rand")
    os.makedirs(part)
    for c in range(C):
        for t in range(T + 1):
            np.savez(os.path.join(part, f"client_{c}_iter_{t}.npz"),
                     x=np.zeros((2, 16, 16, 3), np.float32),
                     y=np.zeros(2, np.int32))
    with pytest.raises(ValueError, match="fmow_image_size"):
        make_dataset(_cfg(tmp_path, "fmow"))


# ----------------------------------------------------------------- UCI CSV
def test_susy_csv_is_loaded(tmp_path):
    rng = np.random.default_rng(11)
    rows = rng.normal(size=(C * (T + 1) * N, 18)).astype(np.float32)
    labels = rng.integers(0, 2, len(rows))
    with open(os.path.join(tmp_path, "SUSY.csv"), "w") as f:
        for lab, r in zip(labels, rows):
            f.write(",".join([f"{float(lab):.1f}"] + [f"{v:.6f}" for v in r])
                    + "\n")
    ds = make_dataset(_cfg(tmp_path, "susy"))
    assert ds.meta["source"] == "csv"
    # file order, z-scored: client 0 / t=0 serves the first N rows
    mu, sd = rows.mean(0), rows.std(0) + 1e-6
    np.testing.assert_allclose(np.asarray(ds.x[0, 0]),
                               (rows[:N] - mu) / sd, atol=1e-4)
    # concept 0 keeps the true labels
    if int(ds.concepts[0, 0]) == 0:
        np.testing.assert_array_equal(np.asarray(ds.y[0, 0]), labels[:N])


def test_ro_csv_is_loaded_with_header_skipped(tmp_path):
    rng = np.random.default_rng(13)
    n = C * (T + 1) * N
    feats = rng.normal(size=(n, 5)).astype(np.float32)
    labels = rng.integers(0, 2, n)
    with open(os.path.join(tmp_path, "datatraining.txt"), "w") as f:
        f.write('"id","date","Temperature","Humidity","Light","CO2",'
                '"HumidityRatio","Occupancy"\n')       # header: skipped
        for i in range(n):
            f.write(",".join(
                [str(i + 1), "2015-02-04 17:51:00"]
                + [f"{v:.6f}" for v in feats[i]] + [str(labels[i])]) + "\n")
    ds = make_dataset(_cfg(tmp_path, "ro"))
    assert ds.meta["source"] == "csv"
    mu, sd = feats.mean(0), feats.std(0) + 1e-6
    np.testing.assert_allclose(np.asarray(ds.x[0, 0]),
                               (feats[:N] - mu) / sd, atol=1e-4)


def test_uci_without_csv_synthesizes(tmp_path):
    ds = make_dataset(_cfg(tmp_path, "susy"))
    assert ds.meta["source"] == "synthetic"
