"""Real-format data ingestion: miniature fixture files in the reference's
on-disk layouts must be loaded by the registry instead of prototype
synthesis (VERDICT round-1 item 4).

Formats covered:
- LEAF MNIST train JSON  (reference MNIST/data_loader_cont.py:152-171)
- FMoW npz partitions    (reference fmow/data_loader.py:63-103 layout)
- UCI SUSY / RO CSV      (reference data_loader_for_susy_and_ro.py)
"""

import json
import os

import numpy as np
import pytest

from feddrift_tpu.config import ExperimentConfig
from feddrift_tpu.data.registry import make_dataset

C, T, N = 2, 2, 5   # tiny: 2 clients, 2 iterations (+1 test step), 5 samples


def _cfg(tmp_path, dataset, **kw):
    return ExperimentConfig(
        dataset=dataset, model="fnn", concept_drift_algo="win-1",
        change_points="rand", drift_together=1,
        client_num_in_total=C, client_num_per_round=C,
        train_iterations=T, comm_round=1, sample_num=N,
        data_dir=str(tmp_path), **kw)


# ----------------------------------------------------------------- LEAF MNIST
def _write_leaf_mnist(tmp_path, n_samples=40):
    rng = np.random.default_rng(7)
    d = os.path.join(tmp_path, "MNIST", "train")
    os.makedirs(d)
    users = ["f_0001", "f_0002"]
    xs = rng.random((n_samples, 784)).round(4)
    ys = rng.integers(0, 10, n_samples)
    half = n_samples // 2
    payload = {
        "users": users,
        "num_samples": [half, n_samples - half],
        "user_data": {
            users[0]: {"x": xs[:half].tolist(), "y": ys[:half].tolist()},
            users[1]: {"x": xs[half:].tolist(), "y": ys[half:].tolist()},
        },
    }
    with open(os.path.join(d, "all_data_niid_0_keep_10_train_9.json"), "w") as f:
        json.dump(payload, f)
    return xs, ys


def test_leaf_mnist_json_is_loaded(tmp_path):
    xs, _ = _write_leaf_mnist(tmp_path)
    ds = make_dataset(_cfg(tmp_path, "MNIST"))
    assert ds.meta["real_data"] is True
    # every served sample is one of the fixture images (shuffled + wrapped,
    # never synthesized)
    source = {r.tobytes() for r in xs.astype(np.float32)}
    flat = ds.x.reshape(-1, 784)
    for row in flat[:: max(1, len(flat) // 16)]:
        assert np.asarray(row, np.float32).tobytes() in source


def test_leaf_mnist_label_swap_applies_to_real_labels(tmp_path):
    """Drift semantics on real data: a concept-k step serves the same images
    with the reference's swapped label pairs (data_loader_cont.py:179-214)."""
    from feddrift_tpu.data.prototype import apply_label_swap
    xs, ys = _write_leaf_mnist(tmp_path)
    cfg = _cfg(tmp_path, "MNIST", concept_num=2)
    ds = make_dataset(cfg)
    by_img = {xs[i].astype(np.float32).tobytes(): int(ys[i])
              for i in range(len(xs))}
    flat_x = np.asarray(ds.x).reshape(C, T + 1, N, 784)
    for c in range(C):
        for t in range(T + 1):
            k = int(ds.concepts[t, c])
            true = np.array([by_img[flat_x[c, t, i].astype(np.float32)
                                    .tobytes()] for i in range(N)], np.int32)
            np.testing.assert_array_equal(
                np.asarray(ds.y[c, t]), apply_label_swap(true, k, 10))


def test_missing_leaf_dir_falls_back_to_prototypes(tmp_path):
    ds = make_dataset(_cfg(tmp_path, "MNIST"))
    assert ds.meta["real_data"] is False


# ----------------------------------------------------------------- FMoW npz
def test_fmow_npz_partitions_are_loaded(tmp_path):
    rng = np.random.default_rng(3)
    part = os.path.join(tmp_path, "fmow", "partitions", "rand")
    os.makedirs(part)
    truth = {}
    for c in range(C):
        for t in range(T + 1):
            x = rng.random((3, 32, 32, 3)).astype(np.float32)  # < N: wraps
            y = rng.integers(0, 62, 3).astype(np.int32)
            np.savez(os.path.join(part, f"client_{c}_iter_{t}.npz"), x=x, y=y)
            truth[(c, t)] = (x, y)
    ds = make_dataset(_cfg(tmp_path, "fmow"))
    assert ds.meta["real_data"] is True
    take = np.arange(N) % 3
    for (c, t), (x, y) in truth.items():
        np.testing.assert_array_equal(np.asarray(ds.x[c, t]), x[take])
        np.testing.assert_array_equal(np.asarray(ds.y[c, t]), y[take])


def test_fmow_incomplete_partitions_fall_back(tmp_path):
    part = os.path.join(tmp_path, "fmow", "partitions", "rand")
    os.makedirs(part)
    np.savez(os.path.join(part, "client_0_iter_0.npz"),
             x=np.zeros((2, 32, 32, 3), np.float32),
             y=np.zeros(2, np.int32))         # only one of C*(T+1) files
    ds = make_dataset(_cfg(tmp_path, "fmow"))
    assert ds.meta["real_data"] is False


def test_fmow_wrong_resolution_is_rejected(tmp_path):
    part = os.path.join(tmp_path, "fmow", "partitions", "rand")
    os.makedirs(part)
    for c in range(C):
        for t in range(T + 1):
            np.savez(os.path.join(part, f"client_{c}_iter_{t}.npz"),
                     x=np.zeros((2, 16, 16, 3), np.float32),
                     y=np.zeros(2, np.int32))
    with pytest.raises(ValueError, match="fmow_image_size"):
        make_dataset(_cfg(tmp_path, "fmow"))


# ----------------------------------------------------------------- UCI CSV
def test_susy_csv_is_loaded(tmp_path):
    rng = np.random.default_rng(11)
    rows = rng.normal(size=(C * (T + 1) * N, 18)).astype(np.float32)
    labels = rng.integers(0, 2, len(rows))
    with open(os.path.join(tmp_path, "SUSY.csv"), "w") as f:
        for lab, r in zip(labels, rows):
            f.write(",".join([f"{float(lab):.1f}"] + [f"{v:.6f}" for v in r])
                    + "\n")
    ds = make_dataset(_cfg(tmp_path, "susy"))
    assert ds.meta["source"] == "csv"
    # file order, z-scored: client 0 / t=0 serves the first N rows
    mu, sd = rows.mean(0), rows.std(0) + 1e-6
    np.testing.assert_allclose(np.asarray(ds.x[0, 0]),
                               (rows[:N] - mu) / sd, atol=1e-4)
    # concept 0 keeps the true labels
    if int(ds.concepts[0, 0]) == 0:
        np.testing.assert_array_equal(np.asarray(ds.y[0, 0]), labels[:N])


def test_ro_csv_is_loaded_with_header_skipped(tmp_path):
    rng = np.random.default_rng(13)
    n = C * (T + 1) * N
    feats = rng.normal(size=(n, 5)).astype(np.float32)
    labels = rng.integers(0, 2, n)
    with open(os.path.join(tmp_path, "datatraining.txt"), "w") as f:
        f.write('"id","date","Temperature","Humidity","Light","CO2",'
                '"HumidityRatio","Occupancy"\n')       # header: skipped
        for i in range(n):
            f.write(",".join(
                [str(i + 1), "2015-02-04 17:51:00"]
                + [f"{v:.6f}" for v in feats[i]] + [str(labels[i])]) + "\n")
    ds = make_dataset(_cfg(tmp_path, "ro"))
    assert ds.meta["source"] == "csv"
    mu, sd = feats.mean(0), feats.std(0) + 1e-6
    np.testing.assert_allclose(np.asarray(ds.x[0, 0]),
                               (feats[:N] - mu) / sd, atol=1e-4)


def test_uci_without_csv_synthesizes(tmp_path):
    ds = make_dataset(_cfg(tmp_path, "susy"))
    assert ds.meta["source"] == "synthetic"


# ------------------------------------------------- fed_shakespeare TFF h5
def _char_corpus(snippet_groups):
    """Expected id stream for TFF snippets: [bos] ids [eos] per snippet,
    clients in sorted-key order (mirrors text._try_load_char_corpus)."""
    from feddrift_tpu.data.text import BOS_ID, EOS_ID, _char_ids
    parts = []
    for snips in snippet_groups:
        for s in snips:
            parts.extend([[BOS_ID], _char_ids(s), [EOS_ID]])
    return np.concatenate([np.asarray(p, np.int32) for p in parts])


def _write_fed_shakespeare_h5(tmp_path, snippet_groups):
    import h5py
    d = os.path.join(tmp_path, "fed_shakespeare", "datasets")
    os.makedirs(d)
    with h5py.File(os.path.join(d, "shakespeare_train.h5"), "w") as f:
        g = f.create_group("examples")
        for i, snips in enumerate(snippet_groups):
            g.create_group(f"client_{i}").create_dataset(
                "snippets", data=[s.encode("utf8") for s in snips])


def _assert_windows_from_corpus(ds, corpus, seq_len, vocab):
    """Every served (x, y) window must be a contiguous corpus slice after
    undoing the concept's alphabet rotation (text._real_text_windows)."""
    hay = corpus.astype(np.int32).tobytes()
    C_, T1 = ds.x.shape[0], ds.x.shape[1]
    for c in range(C_):
        for t in range(T1):
            k = int(ds.concepts[t, c])
            win = np.concatenate(
                [np.asarray(ds.x[c, t]),
                 np.asarray(ds.y[c, t])[:, None]], axis=1).astype(np.int32)
            win = (win - 31 * k) % vocab
            for row in win[:: max(1, len(win) // 4)]:
                assert hay.find(row.tobytes()) >= 0, (c, t, row)


def test_fed_shakespeare_h5_is_loaded(tmp_path):
    groups = [["to be or not to be that is the question",
               "all the worlds a stage and all the men players"],
              ["now is the winter of our discontent"]]
    _write_fed_shakespeare_h5(tmp_path, groups)
    ds = make_dataset(_cfg(tmp_path, "fed_shakespeare", text_seq_len=8,
                           concept_num=2))
    assert ds.meta["real_data"] is True and ds.is_sequence
    _assert_windows_from_corpus(ds, _char_corpus(groups), 8, 90)


def test_fed_shakespeare_without_files_synthesizes(tmp_path):
    ds = make_dataset(_cfg(tmp_path, "fed_shakespeare", text_seq_len=8))
    assert not ds.meta.get("real_data", False)


# ------------------------------------------------- shakespeare LEAF JSON
def test_leaf_shakespeare_json_is_loaded(tmp_path):
    from feddrift_tpu.data.text import EOS_ID, _char_ids
    d = os.path.join(tmp_path, "shakespeare", "train")
    os.makedirs(d)
    users = {"ROMEO": (["but soft what light through yonder window break"],
                       ["s"]),
             "JULIET": (["deny thy father and refuse thy nam"], ["e"])}
    payload = {"users": list(users),
               "user_data": {u: {"x": x, "y": y}
                             for u, (x, y) in users.items()}}
    with open(os.path.join(d, "all_data_train_9.json"), "w") as f:
        json.dump(payload, f)
    ds = make_dataset(_cfg(tmp_path, "shakespeare", text_seq_len=8,
                           concept_num=2))
    assert ds.meta["real_data"] is True
    corpus = np.concatenate(
        [np.concatenate([_char_ids(x[0] + y[0]), [EOS_ID]])
         for x, y in (users[u] for u in payload["users"])]).astype(np.int32)
    _assert_windows_from_corpus(ds, corpus, 8, 90)


# ------------------------------------------------- stackoverflow NWP h5
def test_stackoverflow_nwp_h5_is_loaded(tmp_path):
    import h5py
    d = os.path.join(tmp_path, "stackoverflow", "datasets")
    os.makedirs(d)
    vocab_words = [f"w{i}" for i in range(40)]
    rng = np.random.default_rng(5)
    sents = [" ".join(vocab_words[j] for j in rng.integers(0, 40, 30))
             for _ in range(6)]
    with h5py.File(os.path.join(d, "stackoverflow_train.h5"), "w") as f:
        g = f.create_group("examples")
        g.create_group("c0").create_dataset(
            "tokens", data=[s.encode("utf8") for s in sents[:3]])
        g.create_group("c1").create_dataset(
            "tokens", data=[s.encode("utf8") for s in sents[3:]])
    with open(os.path.join(d, "stackoverflow.word_count"), "w") as f:
        for i, w in enumerate(vocab_words):
            f.write(f"{w} {1000 - i}\n")
    ds = make_dataset(_cfg(tmp_path, "stackoverflow_nwp", concept_num=2))
    assert ds.meta["real_data"] is True and ds.is_sequence
    # expected stream: frequency rank r -> id r+1 (0=pad, V-1=oov)
    wid = {w: i + 1 for i, w in enumerate(vocab_words)}
    corpus = np.asarray([wid[w] for s in sents for w in s.split()], np.int32)
    _assert_windows_from_corpus(ds, corpus, 20, 10000)


# ------------------------------------------------- stackoverflow LR h5
def test_stackoverflow_lr_h5_is_loaded(tmp_path):
    import h5py
    d = os.path.join(tmp_path, "stackoverflow", "datasets")
    os.makedirs(d)
    vocab_words = [f"w{i}" for i in range(10)]
    tags = ["python", "jax", "tpu", "xla"]
    with open(os.path.join(d, "stackoverflow.word_count"), "w") as f:
        for i, w in enumerate(vocab_words):
            f.write(f"{w} {100 - i}\n")
    with open(os.path.join(d, "stackoverflow.tag_count"), "w") as f:
        json.dump({t: 50 - i for i, t in enumerate(tags)}, f)
    rows = [("w0 w0 w3", "w5", "python|offvocab"),
            ("w1 w2", "w1", "jax"),
            ("w9 w9 w9", "", "tpu|xla"),
            ("w4", "w4 w4", "xla")]
    with h5py.File(os.path.join(d, "stackoverflow_train.h5"), "w") as f:
        g = f.create_group("examples").create_group("c0")
        g.create_dataset("tokens", data=[r[0].encode() for r in rows])
        g.create_dataset("title", data=[r[1].encode() for r in rows])
        g.create_dataset("tags", data=[r[2].encode() for r in rows])
    ds = make_dataset(_cfg(tmp_path, "stackoverflow_lr",
                           so_vocab_size=10, so_tag_size=4, concept_num=2))
    assert ds.meta["real_data"] is True
    # sample 0 under concept 0: counts w0 x2, w3 x1, w5 x1; tag python=0
    expect0 = np.zeros(10, np.float32)
    expect0[[0, 3, 5]] = [2, 1, 1]
    flat_x = np.asarray(ds.x).reshape(-1, 10)
    assert any(np.array_equal(r, expect0) for r in flat_x)
    for c in range(C):
        for t in range(T + 1):
            if int(ds.concepts[t, c]) == 0:
                # identity permutation serves the true principal tags
                assert set(np.asarray(ds.y[c, t]).tolist()) <= {0, 1, 2, 3}


# ------------------------------------------------- FederatedEMNIST h5
def test_federated_emnist_h5_is_loaded(tmp_path):
    import h5py
    rng = np.random.default_rng(17)
    d = os.path.join(tmp_path, "FederatedEMNIST")
    os.makedirs(d)
    px = rng.random((30, 28, 28)).astype(np.float32)
    lab = rng.integers(0, 62, 30)
    with h5py.File(os.path.join(d, "emnist_train.h5"), "w") as f:
        f.create_dataset("pixels", data=px)
        f.create_dataset("label", data=lab)
        f.create_dataset("id", data=np.zeros(30, np.int64))
    ds = make_dataset(_cfg(tmp_path, "femnist", concept_num=2))
    assert ds.meta["real_data"] is True
    source = {p.reshape(784).astype(np.float32).tobytes() for p in px}
    flat = np.asarray(ds.x).reshape(-1, 784)
    for row in flat[:: max(1, len(flat) // 8)]:
        assert row.astype(np.float32).tobytes() in source


# ------------------------------------------------- fed_cifar100 h5
def test_fed_cifar100_h5_is_loaded(tmp_path):
    import h5py
    rng = np.random.default_rng(19)
    d = os.path.join(tmp_path, "fed_cifar100")
    os.makedirs(d)
    img = rng.integers(0, 256, (24, 32, 32, 3)).astype(np.uint8)
    lab = rng.integers(0, 100, 24)
    with h5py.File(os.path.join(d, "cifar100_train.h5"), "w") as f:
        f.create_dataset("image", data=img)
        f.create_dataset("label", data=lab)
        f.create_dataset("id", data=np.zeros(24, np.int64))
    ds = make_dataset(_cfg(tmp_path, "fed_cifar100", concept_num=2))
    assert ds.meta["real_data"] is True
    # uint8 -> [0, 1] float; every served image is one of the fixtures
    source = {(img[i] / 255.0).astype(np.float32).tobytes()
              for i in range(len(img))}
    flat = np.asarray(ds.x).reshape(-1, 32, 32, 3)
    for row in flat[:: max(1, len(flat) // 8)]:
        assert row.astype(np.float32).tobytes() in source


# ------------------------------------------------- CIFAR pickle batches
def test_cifar10_pickle_batches_are_loaded(tmp_path):
    import pickle
    rng = np.random.default_rng(23)
    d = os.path.join(tmp_path, "cifar-10-batches-py")
    os.makedirs(d)
    imgs = rng.integers(0, 256, (20, 3, 32, 32)).astype(np.uint8)
    labs = rng.integers(0, 10, 20)
    for i in range(1, 6):
        sl = slice((i - 1) * 4, i * 4)
        with open(os.path.join(d, f"data_batch_{i}"), "wb") as f:
            pickle.dump({b"data": imgs[sl].reshape(4, 3072),
                         b"labels": labs[sl].tolist()}, f)
    ds = make_dataset(_cfg(tmp_path, "cifar10", concept_num=2))
    assert ds.meta["real_data"] is True
    source = {(imgs[i].transpose(1, 2, 0) / 255.0).astype(np.float32).tobytes()
              for i in range(len(imgs))}
    flat = np.asarray(ds.x).reshape(-1, 32, 32, 3)
    for row in flat[:: max(1, len(flat) // 8)]:
        assert row.astype(np.float32).tobytes() in source


def test_cifar100_pickle_train_is_loaded(tmp_path):
    import pickle
    rng = np.random.default_rng(29)
    d = os.path.join(tmp_path, "cifar-100-python")
    os.makedirs(d)
    imgs = rng.integers(0, 256, (16, 3, 32, 32)).astype(np.uint8)
    labs = rng.integers(0, 100, 16)
    with open(os.path.join(d, "train"), "wb") as f:
        pickle.dump({b"data": imgs.reshape(16, 3072),
                     b"fine_labels": labs.tolist()}, f)
    ds = make_dataset(_cfg(tmp_path, "cifar100", concept_num=2))
    assert ds.meta["real_data"] is True


# ------------------------------------------------- CINIC-10 image folder
def _write_cinic_tree(tmp_path, per_class=3):
    """The reference's CINIC-10 layout: a torchvision ImageFolder tree of
    32x32 PNGs (cinic10/data_loader.py) — class index = sorted dir order."""
    import io
    from PIL import Image

    rng = np.random.default_rng(31)
    classes = ["airplane", "automobile", "bird"]
    by_class = {}
    for cls in classes:
        d = os.path.join(tmp_path, "cinic10", "train", cls)
        os.makedirs(d)
        imgs = rng.integers(0, 256, (per_class, 32, 32, 3)).astype(np.uint8)
        by_class[cls] = imgs
        for i, img in enumerate(imgs):
            Image.fromarray(img).save(os.path.join(d, f"img_{i}.png"))
        # ImageFolder ignores non-images sitting in the tree
        with open(os.path.join(d, "notes.txt"), "w") as f:
            f.write("not an image")
    return classes, by_class


def test_cinic10_image_folder_is_loaded(tmp_path):
    pytest.importorskip("PIL.Image")
    classes, by_class = _write_cinic_tree(tmp_path)
    ds = make_dataset(_cfg(tmp_path, "cinic10", concept_num=2))
    assert ds.meta["real_data"] is True
    # every served sample must be one of the fixture images, with the class
    # index implied by sorted directory order
    source = {}
    for ci, cls in enumerate(classes):
        for img in by_class[cls]:
            source[(img / 255.0).astype(np.float32).tobytes()] = ci
    flat_x = np.asarray(ds.x).reshape(-1, 32, 32, 3)
    flat_y = np.asarray(ds.y).reshape(-1)
    # labels may be drift-swapped; un-swap per the concept of each cell
    from feddrift_tpu.data.prototype import apply_label_swap
    concepts = np.broadcast_to(
        ds.concepts[..., None],
        (ds.concepts.shape[0], ds.concepts.shape[1], N)).transpose(1, 0, 2)
    flat_c = concepts.reshape(-1)
    for i in range(0, len(flat_x), max(1, len(flat_x) // 10)):
        key = flat_x[i].astype(np.float32).tobytes()
        assert key in source
        y_orig = apply_label_swap(np.array([flat_y[i]]), int(flat_c[i]),
                                  ds.num_classes)[0]
        assert y_orig == source[key]


def test_cinic10_without_tree_synthesizes(tmp_path):
    ds = make_dataset(_cfg(tmp_path, "cinic10", concept_num=2))
    assert ds.meta["real_data"] is False


def test_cinic10_wrong_resolution_is_rejected(tmp_path):
    pytest.importorskip("PIL.Image")
    from PIL import Image

    d = os.path.join(tmp_path, "cinic10", "train", "cat")
    os.makedirs(d)
    Image.fromarray(np.zeros((16, 16, 3), np.uint8)).save(
        os.path.join(d, "small.png"))
    with pytest.raises(ValueError, match="16"):
        make_dataset(_cfg(tmp_path, "cinic10", concept_num=2))
