"""Vertical FL, hierarchical FL, and GKT trainer tests."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import optax


class TestVerticalFL:
    def test_two_party_learns_split_features(self):
        from feddrift_tpu.platform.vertical import VflTrainer, make_linear_party
        rng = np.random.default_rng(0)
        x = rng.normal(size=(128, 6)).astype(np.float32)
        y = ((x[:, 0] + x[:, 4]) > 0).astype(np.float32)
        xg, xh = jnp.asarray(x[:, :3]), jnp.asarray(x[:, 3:])

        guest, host = make_linear_party(3), make_linear_party(3)
        gp = guest.init(jax.random.PRNGKey(0), xg[:2])["params"]
        hp = host.init(jax.random.PRNGKey(1), xh[:2])["params"]
        tr = VflTrainer(
            guest_apply=lambda p, xx: guest.apply({"params": p}, xx),
            host_applies=[lambda p, xx: host.apply({"params": p}, xx)],
            optimizer=optax.sgd(0.5))
        g_opt, h_opts = tr.init_states(gp, [hp])
        for _ in range(100):
            gp, hps, g_opt, h_opts, loss = tr.train_step(
                gp, [hp], g_opt, h_opts, xg, [xh], jnp.asarray(y))
            hp = hps[0]
        preds = tr.predict(gp, [hp], xg, [xh])
        acc = float(((np.asarray(preds) > 0.5) == y).mean())
        assert acc > 0.9, acc


class TestHierarchical:
    def test_group_then_global_average(self):
        from feddrift_tpu.platform.hierarchical import (HierarchicalSchedule,
                                                        group_average,
                                                        global_average)
        params = {"w": jnp.arange(8.0)[:, None] * jnp.ones((8, 2))}
        n = jnp.ones((8,))
        gids = jnp.asarray([0, 0, 0, 0, 1, 1, 1, 1])
        gp, gn = group_average(params, n, gids, 2)
        np.testing.assert_allclose(np.asarray(gp["w"][0]), 1.5)
        np.testing.assert_allclose(np.asarray(gp["w"][1]), 5.5)
        g = global_average(gp, gn)
        np.testing.assert_allclose(np.asarray(g["w"]), 3.5)

        sched = HierarchicalSchedule(2, gids, global_period=2)
        out = sched.end_of_round(params, n, round_idx=0)   # group-only round
        np.testing.assert_allclose(np.asarray(out["w"][0]), 1.5)
        out = sched.end_of_round(params, n, round_idx=1)   # global round
        np.testing.assert_allclose(np.asarray(out["w"][7]), 3.5)


class TestGkt:
    def test_bidirectional_distillation_learns(self):
        import flax.linen as nn
        from feddrift_tpu.platform.gkt import GktTrainer, kl_divergence

        class Ext(nn.Module):
            @nn.compact
            def __call__(self, x):
                return nn.relu(nn.Dense(8)(x))

        class Head(nn.Module):
            @nn.compact
            def __call__(self, f):
                return nn.Dense(2)(f)

        class Server(nn.Module):
            @nn.compact
            def __call__(self, f):
                return nn.Dense(2)(nn.relu(nn.Dense(16)(f)))

        rng = np.random.default_rng(0)
        x = rng.normal(size=(64, 4)).astype(np.float32)
        y = (x[:, 0] - x[:, 2] > 0).astype(np.int32)
        ext, head, srv = Ext(), Head(), Server()
        pe = ext.init(jax.random.PRNGKey(0), x[:2])["params"]
        f2 = ext.apply({"params": pe}, x[:2])
        ph = head.init(jax.random.PRNGKey(1), f2)["params"]
        ps = srv.init(jax.random.PRNGKey(2), f2)["params"]

        tr = GktTrainer(
            client_extractor=lambda p, xx: ext.apply({"params": p}, xx),
            client_head=lambda p, f: head.apply({"params": p}, f),
            server_apply=lambda p, f: srv.apply({"params": p}, f),
            client_opt=optax.sgd(0.3), server_opt=optax.sgd(0.3))
        c_opt = tr.client_opt.init((pe, ph))
        s_opt = tr.server_opt.init(ps)
        for _ in range(30):
            pe, ph, c_opt, ps, s_opt, cl, sl = tr.alternating_round(
                pe, ph, c_opt, ps, s_opt, jnp.asarray(x), jnp.asarray(y))
        logits = tr.server_logits(ps, tr.extract(pe, jnp.asarray(x)))
        acc = float((np.asarray(logits).argmax(-1) == y).mean())
        assert acc > 0.85, acc

    @pytest.mark.slow
    def test_resnet8_split_round_runs(self):
        # the reference-shaped split: resnet8 trunk -> feature maps -> server
        # tail (tiny server_depth to keep single-core compile cheap)
        from feddrift_tpu.platform.gkt import GktTrainer, make_gkt_split
        ext, head, srv = make_gkt_split(num_classes=2, client_depth=8,
                                        server_depth=8, norm="group")
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.normal(size=(4, 32, 32, 3)).astype(np.float32))
        y = jnp.asarray((rng.random(4) > 0.5).astype(np.int32))
        pe = ext.init(jax.random.PRNGKey(0), x)["params"]
        feats = ext.apply({"params": pe}, x)
        assert feats.shape == (4, 32, 32, 16)
        ph = head.init(jax.random.PRNGKey(1), feats)["params"]
        ps = srv.init(jax.random.PRNGKey(2), feats)["params"]
        tr = GktTrainer(
            client_extractor=lambda p, xx: ext.apply({"params": p}, xx),
            client_head=lambda p, f: head.apply({"params": p}, f),
            server_apply=lambda p, f: srv.apply({"params": p}, f),
            client_opt=optax.sgd(0.1), server_opt=optax.sgd(0.1))
        c_opt = tr.client_opt.init((pe, ph))
        s_opt = tr.server_opt.init(ps)
        pe, ph, c_opt, ps, s_opt, cl, sl = tr.alternating_round(
            pe, ph, c_opt, ps, s_opt, x, y)
        assert np.isfinite(cl) and np.isfinite(sl)

    def test_kl_zero_for_identical(self):
        from feddrift_tpu.platform.gkt import kl_divergence
        logits = jnp.asarray(np.random.default_rng(0).normal(size=(8, 5)),
                             jnp.float32)
        assert float(kl_divergence(logits, logits)) < 1e-6
