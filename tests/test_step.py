"""TrainStep unit tests: masking, aggregation math, batched eval."""

import jax
import pytest
import jax.numpy as jnp
import numpy as np

from feddrift_tpu.config import ExperimentConfig
from feddrift_tpu.core.pool import ModelPool
from feddrift_tpu.core.step import TrainStep, make_optimizer
from feddrift_tpu.data.registry import make_dataset
from feddrift_tpu.models import create_model


def _setup(M=3, C=4, T=3, N=40, B=20):
    cfg = ExperimentConfig(dataset="sine", train_iterations=T, sample_num=N,
                           batch_size=B, epochs=4, client_num_in_total=C,
                           client_num_per_round=C, lr=0.05)
    ds = make_dataset(cfg)
    mod = create_model("fnn", ds, cfg)
    pool = ModelPool.create(mod, jnp.zeros((2, 2)), M, seed=1)
    step = TrainStep(pool.apply, make_optimizer("adam", cfg.lr, cfg.wd),
                     B, cfg.epochs, ds.num_classes)
    x, y = jnp.asarray(ds.x), jnp.asarray(ds.y)
    opt = step.init_opt_states(pool.params, M, C)
    sw = jnp.ones((M, C, N), jnp.float32)
    fm = jnp.ones((M, 2), jnp.float32)
    return cfg, ds, pool, step, x, y, opt, sw, fm


def _leafdiff(a, b):
    return sum(float(jnp.abs(la - lb).sum())
               for la, lb in zip(jax.tree_util.tree_leaves(a),
                                 jax.tree_util.tree_leaves(b)))


@pytest.mark.slow
class TestTrainRound:
    def test_unused_models_untouched(self):
        cfg, ds, pool, step, x, y, opt, sw, fm = _setup()
        tw = np.zeros((3, 4, 4), np.float32)
        tw[0, :, 0] = 1.0          # only model 0 trains
        newp, _, _, n, _ = step.train_round(
            pool.params, opt, jax.random.PRNGKey(0), x, y,
            jnp.asarray(tw), sw, fm, jnp.float32(1.0))
        n = np.asarray(n)
        assert (n[0] == 40).all() and (n[1:] == 0).all()
        assert _leafdiff(jax.tree_util.tree_map(lambda p: p[0], newp),
                         jax.tree_util.tree_map(lambda p: p[0], pool.params)) > 0
        for m in (1, 2):
            assert _leafdiff(jax.tree_util.tree_map(lambda p: p[m], newp),
                             jax.tree_util.tree_map(lambda p: p[m], pool.params)) == 0

    def test_per_client_zero_weight_masked(self):
        cfg, ds, pool, step, x, y, opt, sw, fm = _setup()
        tw = np.zeros((3, 4, 4), np.float32)
        tw[0, :2, 0] = 1.0         # model 0: only clients 0, 1 participate
        newp, _, client_params, n, _ = step.train_round(
            pool.params, opt, jax.random.PRNGKey(0), x, y,
            jnp.asarray(tw), sw, fm, jnp.float32(1.0))
        n = np.asarray(n)
        assert (n[0, :2] == 40).all() and (n[0, 2:] == 0).all()
        # non-participating clients' local params remain the broadcast globals
        cp0 = jax.tree_util.tree_leaves(client_params)[0]
        p0 = jax.tree_util.tree_leaves(pool.params)[0]
        assert np.allclose(cp0[0, 2], p0[0]) and np.allclose(cp0[0, 3], p0[0])

    def test_aggregation_is_weighted_mean(self):
        cfg, ds, pool, step, x, y, opt, sw, fm = _setup()
        tw = np.zeros((3, 4, 4), np.float32)
        tw[0, 0, :2] = 1.0         # client 0 trains on steps 0+1 (n=80)
        tw[0, 1, 0] = 1.0          # client 1 trains on step 0    (n=40)
        newp, _, client_params, n, _ = step.train_round(
            pool.params, opt, jax.random.PRNGKey(1), x, y,
            jnp.asarray(tw), sw, fm, jnp.float32(1.0))
        n = np.asarray(n)
        assert n[0, 0] == 80 and n[0, 1] == 40
        for la, lc in zip(jax.tree_util.tree_leaves(newp),
                          jax.tree_util.tree_leaves(client_params)):
            manual = (lc[0, 0] * 80 + lc[0, 1] * 40) / 120
            assert np.allclose(la[0], manual, atol=1e-5)

    def test_determinism(self):
        cfg, ds, pool, step, x, y, opt, sw, fm = _setup()
        tw = jnp.ones((3, 4, 4), jnp.float32)
        a = step.train_round(pool.params, opt, jax.random.PRNGKey(3), x, y,
                             tw, sw, fm, jnp.float32(1.0))[0]
        b = step.train_round(pool.params, opt, jax.random.PRNGKey(3), x, y,
                             tw, sw, fm, jnp.float32(1.0))[0]
        assert _leafdiff(a, b) == 0

    def test_lr_scale_zero_freezes(self):
        cfg, ds, pool, step, x, y, opt, sw, fm = _setup()
        tw = jnp.ones((3, 4, 4), jnp.float32)
        newp, _, _, _, _ = step.train_round(
            pool.params, opt, jax.random.PRNGKey(0), x, y, tw, sw, fm,
            jnp.float32(0.0))
        assert _leafdiff(newp, pool.params) == 0

    def test_feature_mask_blocks_features(self):
        cfg, ds, pool, step, x, y, opt, sw, fm = _setup()
        # masking all features: inputs become 0; training still runs
        fm0 = jnp.zeros((3, 2), jnp.float32)
        tw = jnp.ones((3, 4, 4), jnp.float32)
        newp, *_ = step.train_round(pool.params, opt, jax.random.PRNGKey(0),
                                    x, y, tw, sw, fm0, jnp.float32(1.0))
        assert np.isfinite(jax.tree_util.tree_leaves(newp)[0]).all()


class TestEval:
    def test_acc_matrix_matches_manual(self):
        cfg, ds, pool, step, x, y, opt, sw, fm = _setup()
        correct, loss_sum, total = step.acc_matrix(pool.params, x[:, 0], y[:, 0], fm)
        m0 = pool.slot(0)
        logits = pool.apply(m0, x[0, 0])
        manual = int((jnp.argmax(logits, -1) == y[0, 0]).sum())
        assert int(correct[0, 0]) == manual
        assert int(total[0]) == 40

    def test_ensemble_hard_single_model_equals_plain(self):
        cfg, ds, pool, step, x, y, opt, sw, fm = _setup()
        w = jnp.asarray([1.0, 0.0, 0.0])
        ec, et, el = step.ensemble_eval(pool.params, x[:, 0], y[:, 0], w, "hard")
        correct, _, _ = step.acc_matrix(pool.params, x[:, 0], y[:, 0], fm)
        assert np.array_equal(np.asarray(ec), np.asarray(correct[0]))
        assert np.isfinite(np.asarray(el)).all()

    def test_confusion_matrix_sums(self):
        cfg, ds, pool, step, x, y, opt, sw, fm = _setup()
        cm = step.confusion_matrices(pool.params, x[:, 0], y[:, 0], fm)
        assert cm.shape == (3, 4, 2, 2)
        assert np.allclose(np.asarray(cm).sum(axis=(-1, -2)), 40)


class TestWeightedSamplingDistribution:
    def test_inverse_cdf_draw_matches_weights(self):
        """The KUE batch draw (inverse-CDF over w_t x s_n) must sample each
        (t, n) cell proportionally to its weight — the semantics of the
        reference's Poisson-bootstrap batch choice (retrain.py:65-74 +
        FedAvgEnsTrainerKue), independent of the sampler implementation."""
        import jax
        import jax.numpy as jnp
        from feddrift_tpu.core.step import inverse_cdf_draw, weight_cdf

        T1, N, B = 3, 8, 4096
        w_t = jnp.asarray([0.0, 1.0, 3.0])
        s_n = jnp.asarray([1.0, 0.0, 2.0, 1.0, 1.0, 0.0, 1.0, 2.0])
        probs = (w_t[:, None] * s_n[None, :]).reshape(-1)
        idx = inverse_cdf_draw(jax.random.PRNGKey(0), weight_cdf(probs), B)
        counts = np.bincount(np.asarray(idx), minlength=T1 * N)
        expected = np.asarray(probs / probs.sum()) * B
        # zero-weight cells must never be drawn; others within 5 sigma
        assert (counts[np.asarray(probs) == 0] == 0).all()
        nonzero = np.asarray(probs) > 0
        sigma = np.sqrt(expected[nonzero].clip(1))
        assert (np.abs(counts[nonzero] - expected[nonzero]) < 5 * sigma + 5).all()
