"""Communication-layer tests: message schema, loopback transport, role
managers driving a full FedAvg round-trip state machine, multihost gates."""

import threading

import jax.numpy as jnp
import numpy as np

from feddrift_tpu.comm import (Message, MsgType, LoopbackNetwork,
                               ServerManager, ClientManager)
from feddrift_tpu.comm.message import (ARG_MODEL_PARAMS,
                                       ARG_MODEL_AND_NUM_SAMPLES,
                                       ARG_CLIENT_INDEX, ARG_EXTRA_INFO)


class _FedAvgServer(ServerManager):
    """Minimal server state machine mirroring FedAvgEnsServerManager: send
    init, collect client models, aggregate (weighted mean), next round or
    finish."""

    def __init__(self, rank, size, com, rounds, init_params):
        self.rounds = rounds
        self.params = init_params
        self.round_idx = 0
        self.received = {}
        super().__init__(rank, size, com)

    def register_message_receive_handlers(self):
        self.register_message_receive_handler(
            MsgType.C2S_SEND_MODEL, self._on_model)

    def send_init_msg(self):
        for c in range(1, self.size):
            msg = Message(MsgType.S2C_INIT_CONFIG, 0, c)
            msg.add_params(ARG_MODEL_PARAMS, self.params)
            msg.add_params(ARG_CLIENT_INDEX, c - 1)
            msg.add_params(ARG_EXTRA_INFO, {"round": 0})
            self.send_message(msg)

    def rebroadcast(self):
        """At-least-once nudge for lossy transports (test_resilience chaos
        e2e): resend the current round's sync — or the init — to every
        client. Duplicate-safe, including from another thread: a client's
        response is a deterministic function of the round's params, and
        _on_model overwrites by sender, so a repeated message can never
        skew the aggregate or double-count a client."""
        if self.round_idx == 0 and not self.received:
            self.send_init_msg()
            return
        for c in range(1, self.size):
            msg = Message(MsgType.S2C_SYNC_MODEL, 0, c)
            msg.add_params(ARG_MODEL_PARAMS, self.params)
            msg.add_params(ARG_EXTRA_INFO, {"round": self.round_idx})
            self.send_message(msg)

    def _on_model(self, msg):
        self.received[msg.sender_id] = msg.get(ARG_MODEL_AND_NUM_SAMPLES)
        if len(self.received) < self.size - 1:
            return
        total = sum(n for _, n in self.received.values())
        self.params = sum(p * (n / total) for p, n in self.received.values())
        self.received = {}
        self.round_idx += 1
        if self.round_idx == self.rounds:
            for c in range(1, self.size):
                self.send_message(Message(MsgType.C2S_SEND_STATS, 0, c))
            self.finish()
            return
        for c in range(1, self.size):
            msg = Message(MsgType.S2C_SYNC_MODEL, 0, c)
            msg.add_params(ARG_MODEL_PARAMS, self.params)
            msg.add_params(ARG_EXTRA_INFO, {"round": self.round_idx})
            self.send_message(msg)


class _FedAvgClient(ClientManager):
    def __init__(self, rank, size, com, delta):
        self.delta = delta  # this client's 'training' result offset
        super().__init__(rank, size, com)

    def register_message_receive_handlers(self):
        self.register_message_receive_handler(
            MsgType.S2C_INIT_CONFIG, self._train)
        self.register_message_receive_handler(
            MsgType.S2C_SYNC_MODEL, self._train)
        self.register_message_receive_handler(
            MsgType.C2S_SEND_STATS, lambda msg: self.finish())

    def _train(self, msg):
        params = msg.get(ARG_MODEL_PARAMS)
        out = Message(MsgType.C2S_SEND_MODEL, self.rank, 0)
        out.add_params(ARG_MODEL_AND_NUM_SAMPLES,
                       (params + self.delta, self.rank))  # n = rank
        self.send_message(out)


class TestLoopbackFedAvg:
    def test_round_trip_state_machine(self):
        C, rounds = 3, 4
        net = LoopbackNetwork(C + 1)
        server = _FedAvgServer(0, C + 1, net.endpoint(0), rounds,
                               init_params=np.float64(0.0))
        clients = [_FedAvgClient(c, C + 1, net.endpoint(c), delta=float(c))
                   for c in range(1, C + 1)]
        threads = [threading.Thread(target=m.run)
                   for m in [server, *clients]]
        for th in threads:
            th.start()
        server.send_init_msg()
        for th in threads:
            th.join(timeout=30)
        assert not any(th.is_alive() for th in threads)
        assert server.round_idx == rounds
        # weighted mean of deltas with n=rank: (1*1+2*2+3*3)/6 = 14/6 per round
        expected = rounds * (14.0 / 6.0)
        assert abs(float(server.params) - expected) < 1e-9

    def test_unregistered_type_dropped_not_fatal(self, caplog):
        # unknown types are logged and dropped so the receive loop (possibly
        # a daemon thread) survives; a raise here would wedge the endpoint
        import logging
        net = LoopbackNetwork(2)
        client = _FedAvgClient(1, 2, net.endpoint(1), delta=0.0)
        with caplog.at_level(logging.WARNING, logger="feddrift_tpu"):
            client.receive_message(999, Message(999, 0, 1))
        assert any("unhandled type" in r.message for r in caplog.records)


class TestPubSub:
    def test_fedavg_over_json_wire(self):
        # the same manager state machines run over the MQTT-shaped transport
        from feddrift_tpu.comm.pubsub import Broker, PubSubCommManager
        C, rounds = 2, 2
        broker = Broker()
        server = _FedAvgServer(0, C + 1, PubSubCommManager(broker, 0), rounds,
                               init_params=0.0)   # JSON wire: plain floats
        clients = [_FedAvgClient(c, C + 1, PubSubCommManager(broker, c),
                                 delta=float(c)) for c in range(1, C + 1)]
        threads = [threading.Thread(target=m.run) for m in [server, *clients]]
        for th in threads:
            th.start()
        server.send_init_msg()
        for th in threads:
            th.join(timeout=30)
        assert not any(th.is_alive() for th in threads)
        # weighted mean with n=rank: (1*1 + 2*2)/3 per round
        assert abs(float(server.params) - rounds * (5.0 / 3.0)) < 1e-9

    def test_array_payload_json_roundtrip(self):
        import time as _time
        from feddrift_tpu.comm.pubsub import Broker, PubSubCommManager
        from feddrift_tpu.comm.message import ARG_MODEL_PARAMS
        broker = Broker()
        a, b = PubSubCommManager(broker, 0), PubSubCommManager(broker, 1)
        got = []

        class Sink:
            def receive_message(self, mt, msg):
                got.append(msg.get(ARG_MODEL_PARAMS))

        b.add_observer(Sink())
        b.run_async()
        m = Message(MsgType.S2C_SYNC_MODEL, 0, 1)
        m.add_params(ARG_MODEL_PARAMS,
                     np.arange(6, dtype=np.float32).reshape(2, 3))
        a.send_message(m)
        for _ in range(100):
            if got:
                break
            _time.sleep(0.02)
        b.stop_receive_message()
        # arrays arrive as nested lists: the JSON wire constraint of MQTT
        np.testing.assert_allclose(np.asarray(got[0]),
                                   [[0, 1, 2], [3, 4, 5]])
        # stopped endpoints are deregistered: no orphaned-queue growth
        assert "1" not in broker._subs
        a.send_message(m)   # dropped, not accumulated

    def test_jax_array_payload(self):
        import jax.numpy as jnp
        from feddrift_tpu.comm.pubsub import _jsonify
        out = _jsonify({"w": jnp.ones((2, 2)), "n": np.int64(3)})
        assert out == {"w": [[1.0, 1.0], [1.0, 1.0]], "n": 3}


class TestMultihost:
    def test_single_process_gates(self):
        from feddrift_tpu.comm import multihost as mh
        assert mh.process_count() == 1 and mh.is_coordinator()
        tree = {"a": jnp.ones((3,)), "b": jnp.full((2, 2), 5.0)}
        out = mh.broadcast_from_coordinator(tree)
        np.testing.assert_allclose(out["a"], tree["a"])
        out = mh.broadcast_sum(tree)
        np.testing.assert_allclose(out["b"], tree["b"])
        out = mh.all_hosts_mean(tree)
        np.testing.assert_allclose(out["b"], tree["b"])


class TestMessage:
    def test_repr_hides_payload(self):
        m = Message(MsgType.S2C_SYNC_MODEL, 0, 1)
        m.add_params(ARG_MODEL_PARAMS, np.zeros((1000, 1000)))
        assert "model_params" in repr(m) and "0." not in repr(m)
