"""Serving executor + Dirichlet partition tests."""

import json
import urllib.request

import numpy as np


def _post(url, obj):
    req = urllib.request.Request(url, data=json.dumps(obj).encode(),
                                 headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=10) as r:
        return json.loads(r.read())


def _get(url):
    with urllib.request.urlopen(url, timeout=10) as r:
        return json.loads(r.read())


class TestServingExecutor:
    def test_register_train_aggregate_cycle(self):
        from feddrift_tpu.platform.serving import ServingExecutor
        ex = ServingExecutor({"w": np.zeros((2,), np.float32)})
        ex.start()
        try:
            d0 = _post(ex.url + "/api/register", {})["device_id"]
            d1 = _post(ex.url + "/api/register", {})["device_id"]
            assert {d0, d1} == {0, 1}
            m = _get(ex.url + "/api/get_model")
            assert m["round"] == 0 and m["params"]["w"] == [0.0, 0.0]
            # device 0 uploads w=[2,2] with n=1; device 1 w=[8,8] with n=3
            r = _post(ex.url + "/api/upload_model",
                      {"device_id": d0, "num_samples": 1,
                       "params": {"w": [2.0, 2.0]}})
            assert r["round"] == 0      # waiting for device 1
            r = _post(ex.url + "/api/upload_model",
                      {"device_id": d1, "num_samples": 3,
                       "params": {"w": [8.0, 8.0]}})
            assert r["round"] == 1      # aggregated
            m = _get(ex.url + "/api/get_model")
            np.testing.assert_allclose(m["params"]["w"], [6.5, 6.5])
        finally:
            ex.stop()

    def test_unregistered_device_rejected(self):
        from feddrift_tpu.platform.serving import ServingExecutor
        ex = ServingExecutor({"w": np.zeros((1,), np.float32)})
        ex.start()
        try:
            _post(ex.url + "/api/register", {})
            try:
                _post(ex.url + "/api/upload_model",
                      {"device_id": 100, "num_samples": 1,
                       "params": {"w": [1.0]}})
                assert False, "expected 400"
            except urllib.error.HTTPError as e:
                assert e.code == 400
            assert ex.state.round == 0 and not ex.state.uploads
        finally:
            ex.stop()

    def test_wrong_param_keys_rejected(self):
        from feddrift_tpu.platform.serving import ServingExecutor
        ex = ServingExecutor({"w": np.zeros((1,), np.float32)})
        ex.start()
        try:
            d = _post(ex.url + "/api/register", {})["device_id"]
            try:
                _post(ex.url + "/api/upload_model",
                      {"device_id": d, "num_samples": 1,
                       "params": {"not_w": [1.0]}})
                assert False, "expected 400"
            except urllib.error.HTTPError as e:
                assert e.code == 400
            # server not wedged: a correct upload still aggregates
            r = _post(ex.url + "/api/upload_model",
                      {"device_id": d, "num_samples": 1,
                       "params": {"w": [3.0]}})
            assert r["round"] == 1
        finally:
            ex.stop()

    def test_bad_request(self):
        from feddrift_tpu.platform.serving import ServingExecutor
        ex = ServingExecutor({"w": np.zeros((1,), np.float32)})
        ex.start()
        try:
            try:
                _post(ex.url + "/api/upload_model", {"device_id": 0})
                assert False, "expected 400"
            except urllib.error.HTTPError as e:
                assert e.code == 400
        finally:
            ex.stop()


import urllib.error  # noqa: E402


class TestPartition:
    def test_homo_covers_all(self):
        from feddrift_tpu.data.partition import partition_homo
        parts = partition_homo(103, 4, seed=1)
        allidx = np.concatenate(parts)
        assert len(allidx) == 103 and len(np.unique(allidx)) == 103

    def test_hetero_dirichlet_skew(self):
        from feddrift_tpu.data.partition import (partition_hetero,
                                                 partition_counts)
        rng = np.random.default_rng(0)
        y = rng.integers(0, 10, size=2000).astype(np.int64)
        parts = partition_hetero(y, 8, alpha=0.2, seed=3)
        allidx = np.concatenate(parts)
        assert len(np.unique(allidx)) == len(allidx) == 2000
        assert min(len(p) for p in parts) >= 10
        counts = partition_counts(y, parts, 10)
        assert counts.shape == (8, 10)
        # low alpha -> label skew: per-client class distribution far from
        # uniform for at least some clients
        frac = counts / counts.sum(axis=1, keepdims=True)
        assert (frac.max(axis=1) > 0.3).any()

    def test_hetero_high_alpha_balanced(self):
        from feddrift_tpu.data.partition import partition_hetero
        rng = np.random.default_rng(1)
        y = rng.integers(0, 10, size=2000).astype(np.int64)
        parts = partition_hetero(y, 4, alpha=100.0, seed=5)
        sizes = np.array([len(p) for p in parts])
        assert sizes.min() > 0.5 * sizes.mean()
