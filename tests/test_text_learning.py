"""The text pipeline demonstrably learns (VERDICT round-1 item 8): the
char-LSTM must climb far above the 1/90 chance floor on the Markov
next-char task (ceiling ~0.47, data/text.py peaked transitions)."""

import numpy as np
import pytest

from feddrift_tpu.config import ExperimentConfig
from feddrift_tpu.simulation.runner import run_experiment

pytestmark = pytest.mark.slow   # LSTM training: full-tier only


def test_shakespeare_rnn_learns_above_chance():
    cfg = ExperimentConfig(
        dataset="fed_shakespeare", model="rnn", concept_drift_algo="win-1",
        change_points="rand", drift_together=1, concept_num=2,
        client_num_in_total=2, client_num_per_round=2,
        train_iterations=2, comm_round=30, epochs=5,
        sample_num=800, batch_size=100, lr=0.003,
        frequency_of_the_test=10, text_seq_len=20, report_client=0)
    exp = run_experiment(cfg)
    accs = [v for _, v in exp.logger.series("Test/Acc")]
    # chance = 1/90 ~ 0.011; require ~10x chance and a rising trajectory
    assert accs[-1] > 0.10, accs
    assert accs[-1] > accs[0], accs


def test_text_seq_len_is_configurable():
    from feddrift_tpu.data.registry import make_dataset
    cfg = ExperimentConfig(dataset="fed_shakespeare", model="rnn",
                           train_iterations=2, sample_num=8,
                           client_num_in_total=2, client_num_per_round=2,
                           text_seq_len=16)
    ds = make_dataset(cfg)
    assert ds.x.shape[-1] == 16
