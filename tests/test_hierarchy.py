"""Fault-tolerant two-tier aggregation (platform/hierarchical.py,
platform/faults.py::EdgeFaultInjector, simulation/runner.py wiring).

Covers the acceptance criteria of the hierarchical-aggregation PR:
- the empty-group bug fix in group_average (a group whose weights are all
  zero keeps its previous params instead of dividing toward zero);
- EdgeMap determinism + round-robin re-homing of a dead edge's clients;
- E=1 with mean/mean is bitwise-identical to the flat legacy path on BOTH
  the per-round and the fused program (IEEE x/x == 1.0 exactly);
- per-tier Byzantine containment: two sign-flippers inside one edge are
  rejected at the server tier while a flat mean degrades;
- killing an edge mid-run completes with edge_failed -> edge_rehomed
  evidence and a NaN-free trajectory;
- edge quorum: too few reporting edges degrades the round (params kept);
- the vectorized ring_adjacency is bitwise-equal to the reference loop.
"""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from feddrift_tpu import obs
from feddrift_tpu.config import ExperimentConfig
from feddrift_tpu.platform.faults import BYZ_MODES, EdgeFaultInjector
from feddrift_tpu.platform.hierarchical import (EdgeMap, group_average,
                                                two_tier_aggregate)
from feddrift_tpu.platform.topology import ring_adjacency
from feddrift_tpu.resilience.robust_agg import RobustAggConfig
from feddrift_tpu.simulation.runner import Experiment, run_experiment

KEY = jax.random.PRNGKey(0)


def _cfg(**kw):
    base = dict(dataset="sine", model="fnn", concept_drift_algo="win-1",
                train_iterations=2, comm_round=8, epochs=2, sample_num=48,
                batch_size=24, frequency_of_the_test=4, lr=0.05,
                client_num_in_total=10, client_num_per_round=10, seed=0,
                report_client=0, divergence_guard=False)
    base.update(kw)
    return ExperimentConfig(**base)


def _leaves_equal(a, b):
    la, lb = jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    return all((np.asarray(x) == np.asarray(y)).all() for x, y in zip(la, lb))


class TestGroupAverageEmptyGroup:
    """Regression for the empty-group divide-toward-zero bug: group 1 has
    members but every member weight is 0 this round."""

    def test_empty_group_keeps_previous_params(self):
        # 4 clients, 2 groups; group 1 (clients 2,3) reports zero weight
        cp = {"w": jnp.asarray([[1.0], [3.0], [100.0], [200.0]])}
        n = jnp.asarray([1.0, 1.0, 0.0, 0.0])
        gids = jnp.asarray([0, 0, 1, 1], jnp.int32)
        prev = {"w": jnp.asarray([[7.0], [7.0]])}     # [G, ...]
        out, seg_n = group_average(cp, n, gids, 2, prev_group_params=prev)
        got = np.asarray(out["w"])
        np.testing.assert_allclose(got[0], 2.0)       # (1+3)/2
        np.testing.assert_allclose(got[1], 7.0)       # kept, NOT 0
        assert np.isfinite(got).all()
        np.testing.assert_allclose(np.asarray(seg_n), [2.0, 0.0])

    def test_empty_group_without_prev_falls_back_to_member_mean(self):
        cp = {"w": jnp.asarray([[1.0], [3.0], [10.0], [30.0]])}
        n = jnp.asarray([1.0, 1.0, 0.0, 0.0])
        gids = jnp.asarray([0, 0, 1, 1], jnp.int32)
        out, _ = group_average(cp, n, gids, 2)
        got = np.asarray(out["w"])
        np.testing.assert_allclose(got[0], 2.0)
        np.testing.assert_allclose(got[1], 20.0)      # unweighted membership


class TestEdgeMap:
    def test_contiguous_assignment_is_deterministic(self):
        m1, m2 = EdgeMap(10, 3), EdgeMap(10, 3)
        assert (m1.ids == m2.ids).all()
        assert (m1.ids == np.array([0, 0, 0, 0, 1, 1, 1, 2, 2, 2])).all()

    def test_round_robin_assignment(self):
        m = EdgeMap(7, 3, assign="round_robin")
        assert (m.ids == np.array([0, 1, 2, 0, 1, 2, 0])).all()

    def test_rehome_moves_only_dead_edges_clients(self):
        obs.configure(None)
        m = EdgeMap(10, 3)
        before = m.ids.copy()
        dead = np.array([True, False, False])
        moved = m.rehome(dead, round_idx=5)
        assert moved == 4                      # edge 0 held clients 0-3
        assert not (m.ids == 0).any()          # nobody points at the corpse
        # survivors' own clients did not move
        assert (m.ids[before != 0] == before[before != 0]).all()
        # deterministic round-robin over survivors
        assert (m.ids[:4] == np.array([1, 2, 1, 2])).all()
        evs = obs.get_bus().events("edge_rehomed")
        assert evs and evs[-1]["clients"] == [0, 1, 2, 3]
        # unchanged dead set: no-op, no duplicate event
        assert m.rehome(dead, round_idx=6) == 0
        assert len(obs.get_bus().events("edge_rehomed")) == 1


class TestEdgeFaultInjector:
    def test_draws_are_seeded_and_reproducible(self):
        a = EdgeFaultInjector(4, crash_prob=0.5, stall_prob=0.5, seed=7)
        b = EdgeFaultInjector(4, crash_prob=0.5, stall_prob=0.5, seed=7)
        for r in (0, 3, 11):
            assert (a.crashes(r) == b.crashes(r)).all()
            assert (a.latencies(r) == b.latencies(r)).all()

    def test_kill_is_permanent_and_idempotent(self):
        obs.configure(None)
        inj = EdgeFaultInjector(3)
        inj.kill(1, round_idx=2)
        inj.kill(1, round_idx=3)               # no duplicate event
        assert inj.dead[1] and not inj.dead[0]
        assert inj.crashes(9)[1]               # dead edges never report
        assert len(obs.get_bus().events("edge_failed")) == 1

    def test_corrupt_modes_emit_evidence(self):
        obs.configure(None)
        inj = EdgeFaultInjector(3, corrupt_prob=0.99, seed=1)
        modes = inj.corrupt_modes(0)
        assert (modes == BYZ_MODES["sign_flip"]).any()
        assert obs.get_bus().events("edge_failed")[-1]["reason"] == "corrupt"


class TestTwoTierAggregate:
    def test_masked_edge_never_reaches_server_tier(self):
        """Edge 1's poisoned summary is weight-masked: plain mean at the
        server must equal the clean edges' average."""
        cp = {"w": jnp.asarray([[[1.0], [3.0], [1e9], [5.0]]])}
        n = jnp.asarray([[1.0, 1.0, 1.0, 1.0]])
        prev = {"w": jnp.asarray([[0.0]])}
        eids = jnp.asarray([0, 0, 1, 2], jnp.int32)
        mask = jnp.asarray([1.0, 0.0, 1.0])
        out, stats = two_tier_aggregate(
            "mean", "mean", cp, n, prev, eids, 3, mask, None, KEY,
            RobustAggConfig())
        # edges: e0=(1+3)/2=2, e1 masked, e2=5; server mean over w=[2,0,1]
        np.testing.assert_allclose(np.asarray(out["w"][0]), 3.0)
        assert np.asarray(stats).shape == (4, 1, 3)   # [1+E, M, 3]

    def test_all_edges_masked_keeps_previous_params(self):
        cp = {"w": jnp.asarray([[[1.0], [3.0], [5.0], [7.0]]])}
        n = jnp.asarray([[1.0, 1.0, 1.0, 1.0]])
        prev = {"w": jnp.asarray([[42.0]])}
        eids = jnp.asarray([0, 0, 1, 1], jnp.int32)
        out, _ = two_tier_aggregate(
            "mean", "mean", cp, n, prev, eids, 2, jnp.zeros(2), None, KEY,
            RobustAggConfig())
        np.testing.assert_allclose(np.asarray(out["w"][0]), 42.0)


class TestFlatParity:
    """E=1 + mean/mean must be bitwise-identical to the legacy flat
    aggregation: one edge's weighted mean IS the global weighted mean, and
    the server tier's w/w == 1.0 exactly in IEEE arithmetic."""

    @pytest.mark.parametrize("chunk", [False, True],
                             ids=["per_round", "fused"])
    def test_single_edge_matches_flat_bitwise(self, chunk):
        flat = Experiment(_cfg(chunk_rounds=chunk))
        flat.run()
        hier = Experiment(_cfg(chunk_rounds=chunk, hierarchy_edges=1))
        hier.run()
        assert flat.logger.series("Test/Acc") == hier.logger.series("Test/Acc")
        assert _leaves_equal(flat.pool.params, hier.pool.params)

    def test_fused_matches_per_round_with_hierarchy(self):
        a = Experiment(_cfg(chunk_rounds=False, hierarchy_edges=3,
                            compress_codec="int8"))
        a.run()
        b = Experiment(_cfg(chunk_rounds=True, hierarchy_edges=3,
                            compress_codec="int8"))
        b.run()
        assert a.logger.series("Test/Acc") == b.logger.series("Test/Acc")
        assert _leaves_equal(a.pool.params, b.pool.params)


@pytest.mark.slow
class TestContainment:
    """The documented acceptance scenario: 10 clients, 3 edges, 2
    sign-flippers both inside edge 0. Per-tier trimmed mean rejects the
    poisoned edge summary at the server tier; a flat mean absorbs it."""

    DELTA = 0.10

    def test_two_tier_contains_byzantine_edge(self):
        clean = run_experiment(_cfg()).logger.last("Test/Acc")
        byz = dict(byzantine_clients="0,1", byzantine_mode="sign_flip")
        flat = run_experiment(_cfg(**byz)).logger.last("Test/Acc")
        hier = run_experiment(_cfg(
            **byz, hierarchy_edges=3, edge_robust_agg="trimmed_mean",
            server_robust_agg="trimmed_mean",
            robust_trim_frac=0.4)).logger.last("Test/Acc")
        assert clean - hier <= self.DELTA, (clean, hier)
        assert clean - flat > self.DELTA, (clean, flat)

    def test_corrupt_edge_summary_rejected_at_server_tier(self):
        """A sign-flipped EDGE summary is contained by the server-tier
        trimmed mean: the top tier sees one corrupted row among E and
        trims it (deterministic: modes injected directly)."""
        cp = {"w": jnp.full((1, 4, 2), 2.0)}
        n = jnp.ones((1, 4))
        prev = {"w": jnp.zeros((1, 2))}
        eids = jnp.asarray([0, 0, 1, 2], jnp.int32)
        modes = jnp.asarray([BYZ_MODES["sign_flip"], 0, 0], jnp.int32)
        out, _ = two_tier_aggregate(
            "mean", "trimmed_mean", cp, n, prev, eids, 3, None, modes, KEY,
            RobustAggConfig(trim_frac=0.4), byz_scale=10.0)
        np.testing.assert_allclose(np.asarray(out["w"][0]), 2.0, atol=1e-5)
        # control: a plain mean absorbs the poisoned summary
        bad, _ = two_tier_aggregate(
            "mean", "mean", cp, n, prev, eids, 3, None, modes, KEY,
            RobustAggConfig(), byz_scale=10.0)
        assert abs(float(np.asarray(bad["w"][0])[0]) - 2.0) > 1.0


class TestEdgeFailover:
    def test_killed_edge_rehomes_and_run_completes(self):
        exp = Experiment(_cfg(hierarchy_edges=3, edge_kill_round=5,
                              edge_kill_edge=0))
        exp.run()
        acc = exp.logger.last("Test/Acc")
        assert math.isfinite(acc) and acc > 0.5
        evs = obs.get_bus()
        failed = evs.events("edge_failed")
        assert any(e["reason"] == "killed" for e in failed)
        rehomed = evs.events("edge_rehomed")
        assert rehomed and rehomed[-1]["edge"] == 0
        # every slot edge 0 originally held moved to a survivor (the slot
        # count depends on device padding, so derive it from the map)
        initial0 = np.flatnonzero(exp.edge_map._initial == 0)
        assert rehomed[-1]["clients"] == [int(s) for s in initial0]
        assert not (np.asarray(exp.edge_map.ids) == 0).any()
        # params stayed finite through the failover
        for leaf in jax.tree_util.tree_leaves(exp.pool.params):
            assert np.isfinite(np.asarray(leaf)).all()

    def test_below_edge_quorum_degrades_round(self):
        exp = Experiment(_cfg(hierarchy_edges=2, edge_kill_round=0,
                              edge_kill_edge=0, edge_quorum_frac=1.0,
                              train_iterations=1))
        exp.run()
        deg = obs.get_bus().events("round_degraded")
        assert deg and all(e.get("tier") == "edge" for e in deg)
        assert math.isfinite(exp.logger.last("Test/Acc"))

    def test_edge_aggregated_evidence_every_round(self):
        exp = Experiment(_cfg(hierarchy_edges=3, train_iterations=1))
        exp.run()
        eagg = obs.get_bus().events("edge_aggregated")
        assert len(eagg) == exp.cfg.comm_round
        assert eagg[0]["edge_strategy"] == "mean"
        assert len(eagg[0]["edge_active"]) == 3


class TestRingAdjacencyVectorized:
    """Satellite: the circulant-gather ring must be bitwise-equal to the
    reference O(n*k) loop, including the n=1 and k>=2n edge cases."""

    @staticmethod
    def _loop(n, k):
        A = np.zeros((n, n), dtype=np.float32)
        half = max(k // 2, 1)
        for i in range(n):
            for d in range(1, half + 1):
                A[i, (i + d) % n] = 1.0
                A[i, (i - d) % n] = 1.0
        return A

    @pytest.mark.parametrize("n", [1, 2, 3, 5, 17, 64])
    @pytest.mark.parametrize("k", [0, 1, 2, 3, 4, 10])
    def test_bitwise_equal_to_loop(self, n, k):
        got, want = ring_adjacency(n, k), self._loop(n, k)
        assert got.dtype == want.dtype
        assert (got == want).all()

    def test_wraparound_degree_exceeds_n(self):
        n = 4
        for k in (2 * n, 2 * n + 1):
            assert (ring_adjacency(n, k) == self._loop(n, k)).all()


class TestConfigValidation:
    def test_hierarchy_rejects_flat_robust_agg(self):
        with pytest.raises(ValueError, match="hierarchy"):
            _cfg(hierarchy_edges=2, robust_agg="median")

    def test_edges_bounded_by_clients(self):
        with pytest.raises(ValueError):
            _cfg(hierarchy_edges=11)

    def test_unknown_codec_rejected(self):
        with pytest.raises(ValueError, match="compress_codec"):
            _cfg(compress_codec="gzip")


class TestReportSection:
    def test_summarize_renders_hierarchy(self, tmp_path):
        import json

        from feddrift_tpu.obs.report import render, summarize
        evs = [
            {"_ts": 0, "kind": "edge_aggregated", "round": 0,
             "edge_strategy": "trimmed_mean", "server_strategy":
             "trimmed_mean", "edge_active": [4, 3, 3], "edge_rejected": 2,
             "server_active": [3], "server_rejected": 1},
            {"_ts": 1, "kind": "edge_failed", "fault_round": 1,
             "edges": [0], "reason": "killed"},
            {"_ts": 2, "kind": "edge_rehomed", "fault_round": 1, "edge": 0,
             "clients": [0, 1], "targets": [1, 2]},
            {"_ts": 3, "kind": "update_compressed", "topic": "fl/u",
             "update": "w", "codec": "int8", "raw_bytes": 4000,
             "wire_bytes": 1000},
            {"_ts": 4, "kind": "compress_corrupt", "topic": "fl/u",
             "fid": 7, "reason": "digest mismatch"},
        ]
        with open(tmp_path / "events.jsonl", "w") as f:
            for e in evs:
                f.write(json.dumps(e) + "\n")
        s = summarize(str(tmp_path))
        hier = s["hierarchy"]
        assert hier["tiers"]["server_rejected_total"] == 1
        assert hier["edge_failures"]["by_reason"] == {"killed": 1}
        assert hier["rehomed"]["clients_total"] == 2
        assert hier["compression"]["int8"]["ratio"] == 4.0
        assert hier["corrupt_frames"] == 1
        text = render(s)
        assert "hierarchy:" in text
        assert "wire int8" in text
        assert "re-homed" in text
