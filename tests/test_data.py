"""Unit tests for change points, synthetic generators, and retrain specs."""

import numpy as np
import pytest

from feddrift_tpu.config import ExperimentConfig
from feddrift_tpu.data import changepoints as cp
from feddrift_tpu.data.registry import make_dataset, available_datasets
from feddrift_tpu.data.retrain import time_weights, poisson_sample_counts
from feddrift_tpu.data.synthetic import generate_synthetic, SEA_THRESHOLDS
from feddrift_tpu.data.prototype import apply_label_swap


class TestChangePoints:
    def test_presets_load(self):
        for name in ("A", "B", "C", "D", "E", "F", "W", "X", "Y", "Z", "R0", "R9"):
            m = cp.load_change_points(name)
            assert m.shape == (11, 10)
            assert m.dtype == np.int32

    def test_preset_a_is_binary_staggered(self):
        a = cp.load_change_points("A")
        assert set(np.unique(a)) <= {0, 1}
        # drifts are staggered: not all clients change at the same step
        change_steps = [np.nonzero(np.diff(a[:, c]))[0] for c in range(10)]
        assert len({tuple(s) for s in change_steps}) > 1

    def test_preset_d_has_four_concepts(self):
        d = cp.load_change_points("D")
        assert set(np.unique(d)) == {0, 1, 2, 3}

    def test_random_generation(self):
        m = cp.generate_random_change_points(10, 7, drift_together=0, seed=3)
        assert m.shape == (11, 7)
        assert (np.diff(m, axis=0) >= 0).all()
        assert (m[0] == 0).all() and (m[-1] == 1).all()
        m2 = cp.generate_random_change_points(10, 7, drift_together=1, seed=3)
        # all clients share one change point
        assert len({tuple(col) for col in m2.T}) == 1

    def test_time_stretch_indexing(self):
        a = cp.load_change_points("A")
        mat = cp.concept_matrix(a, num_steps=20, num_clients=10, time_stretch=2)
        assert mat.shape == (20, 10)
        assert (mat[0] == a[0]).all() and (mat[19] == a[9]).all()


class TestSynthetic:
    def test_sea_shapes_and_labels(self):
        cps = cp.load_change_points("A")
        ds = generate_synthetic("sea", cps, 10, 10, 200, seed=0)
        assert ds.x.shape == (10, 11, 200, 3)
        assert ds.y.shape == (10, 11, 200)
        assert ds.num_classes == 2
        assert ds.num_steps == 10 and ds.samples_per_step == 200

    def test_sea_boundary_statistics(self):
        # label mean approx P(f2+f3 > theta) with 10% flip noise
        cps = np.zeros((2, 4), dtype=np.int32)
        ds = generate_synthetic("sea", cps, 1, 4, 5000, seed=1)
        theta = SEA_THRESHOLDS[0]
        p_clean = 1 - theta**2 / 200.0
        expect = p_clean * 0.9 + (1 - p_clean) * 0.1
        assert abs(ds.y.mean() - expect) < 0.02

    def test_drift_changes_distribution(self):
        cps = cp.load_change_points("A")
        ds = generate_synthetic("sine", cps, 10, 10, 500, seed=0)
        # client 1 drifts at t=1 in preset A: label rule flips
        below = ds.x[1, :, :, 1] <= np.sin(ds.x[1, :, :, 0])
        acc_c0 = (ds.y[1, 0] == below[0]).mean()   # concept 0 at t=0
        acc_c1 = (ds.y[1, 2] == below[2]).mean()   # concept 1 at t=2 (preset A)
        assert acc_c0 > 0.95 and acc_c1 < 0.05

    def test_noise_prob_flips(self):
        cps = np.zeros((2, 2), dtype=np.int32)
        clean = generate_synthetic("circle", cps, 1, 2, 2000, seed=5)
        noisy = generate_synthetic("circle", cps, 1, 2, 2000, noise_prob=0.3, seed=5)
        frac_diff = (clean.y != noisy.y).mean()
        assert 0.25 < frac_diff < 0.35

    def test_determinism(self):
        cps = cp.load_change_points("B")
        a = generate_synthetic("sea", cps, 3, 5, 50, seed=9)
        b = generate_synthetic("sea", cps, 3, 5, 50, seed=9)
        assert (a.x == b.x).all() and (a.y == b.y).all()


class TestLabelSwap:
    def test_swaps(self):
        y = np.arange(10)
        assert (apply_label_swap(y, 0, 10) == y).all()
        s1 = apply_label_swap(y, 1, 10)
        assert s1[1] == 2 and s1[2] == 1 and s1[3] == 3
        s3 = apply_label_swap(y, 3, 10)
        assert s3[5] == 6 and s3[6] == 5


class TestRegistry:
    def test_available(self):
        names = available_datasets()
        for n in ("sea", "sine", "circle", "MNIST", "cifar10", "femnist", "shakespeare"):
            assert n in names

    def test_make_sea(self):
        cfg = ExperimentConfig(dataset="sea", train_iterations=3, sample_num=40,
                               client_num_in_total=10, client_num_per_round=10)
        ds = make_dataset(cfg)
        assert ds.x.shape == (10, 4, 40, 3)

    def test_make_mnist_synthetic(self):
        cfg = ExperimentConfig(dataset="MNIST", train_iterations=2, sample_num=30,
                               change_points="D")
        ds = make_dataset(cfg)
        assert ds.x.shape == (10, 3, 30, 784)
        assert ds.num_classes == 10

    def test_make_text(self):
        cfg = ExperimentConfig(dataset="shakespeare", train_iterations=2, sample_num=16)
        ds = make_dataset(cfg)
        assert ds.x.shape == (10, 3, 16, 80)
        assert ds.is_sequence and ds.num_classes == 90

    def test_make_fmow(self):
        cfg = ExperimentConfig(dataset="fmow", train_iterations=2, sample_num=8,
                               client_num_in_total=4, client_num_per_round=4,
                               change_points="A")
        ds = make_dataset(cfg)
        assert ds.x.shape == (4, 3, 8, 32, 32, 3)
        assert ds.num_classes == 62
        # covariate drift: same labels, shifted inputs across concepts
        import numpy as np
        k = ds.concepts  # [T+1, C]
        drifted = [(c, t) for c in range(4) for t in range(3)
                   if k[t, c] != k[0, c]]
        if drifted:
            c, t = drifted[0]
            assert abs(ds.x[c, t].mean() - ds.x[c, 0].mean()) > 0.01

    def test_make_cifar100_cinic(self):
        for name, k in (("cifar100", 100), ("cinic10", 10)):
            cfg = ExperimentConfig(dataset=name, train_iterations=1,
                                   sample_num=6, client_num_in_total=3,
                                   client_num_per_round=3)
            ds = make_dataset(cfg)
            assert ds.x.shape == (3, 2, 6, 32, 32, 3)
            assert ds.num_classes == k

    def test_make_stackoverflow_nwp(self):
        cfg = ExperimentConfig(dataset="stackoverflow_nwp", train_iterations=2,
                               sample_num=8, client_num_in_total=4,
                               client_num_per_round=4, change_points="A")
        ds = make_dataset(cfg)
        assert ds.x.shape == (4, 3, 8, 20)
        assert ds.num_classes == 10000 and ds.is_sequence
        # labels follow the concept's affine map for non-noise steps
        assert (ds.y >= 0).all() and (ds.y < 10000).all()

    def test_rand_changepoints(self):
        cfg = ExperimentConfig(dataset="sea", change_points="rand",
                               train_iterations=6, sample_num=20)
        ds = make_dataset(cfg)
        assert ds.concepts.shape == (7, 10)


class TestSmoothFamily:
    """The conv-learnable "-smooth" synthetic image family (round-5 fix for
    the round-4 finding that the white-noise basis is conv-unlearnable)."""

    def test_registered_and_shapes(self):
        names = available_datasets()
        for n in ("femnist-smooth", "cifar10-smooth", "MNIST-smooth",
                  "fmow-smooth"):
            assert n in names
        cfg = ExperimentConfig(dataset="cifar10-smooth", train_iterations=1,
                               sample_num=6, client_num_in_total=3,
                               client_num_per_round=3)
        ds = make_dataset(cfg)
        assert ds.x.shape == (3, 2, 6, 32, 32, 3)
        assert ds.meta["smooth_sigma"] == cfg.smooth_sigma > 0

    def test_always_synthetic_even_with_real_files(self, tmp_path):
        # the whole point of the family: a reproducible conv benchmark —
        # mounted real files must NOT silently replace the task
        import json as _json
        d = tmp_path / "MNIST" / "train"
        d.mkdir(parents=True)
        xs = [[0.5] * 784] * 4
        (d / "u.json").write_text(_json.dumps(
            {"users": ["u0"], "user_data": {"u0": {"x": xs, "y": [1, 2, 3, 4]}}}))
        cfg = ExperimentConfig(dataset="MNIST-smooth", train_iterations=1,
                               sample_num=4, client_num_in_total=2,
                               client_num_per_round=2, data_dir=str(tmp_path))
        ds = make_dataset(cfg)
        assert ds.meta["real_data"] is False
        plain = ExperimentConfig(dataset="MNIST", train_iterations=1,
                                 sample_num=4, client_num_in_total=2,
                                 client_num_per_round=2,
                                 data_dir=str(tmp_path))
        assert make_dataset(plain).meta["real_data"] is True

    @staticmethod
    def neighbour_corr(imgs):
        """Horizontal neighbouring-pixel correlation of [..., H, W] images —
        high for spatially smooth fields, ~0 for white noise."""
        a = (imgs[..., :, :-1] - imgs.mean()).ravel()
        b = (imgs[..., :, 1:] - imgs.mean()).ravel()
        return float((a * b).mean()
                     / np.sqrt((a * a).mean() * (b * b).mean() + 1e-12))

    def test_basis_is_spatially_smooth(self):
        # neighbouring-pixel correlation of the prototypes must be high
        # under smoothing and near zero for the white-noise basis — the
        # property that makes the signal visible to local conv kernels
        from feddrift_tpu.data.prototype import PrototypeSampler

        def neighbour_corr(protos):
            return self.neighbour_corr(protos.reshape(protos.shape[0], 28, 28))

        smooth = PrototypeSampler((784,), 10, smooth_sigma=3.0)
        white = PrototypeSampler((784,), 10, smooth_sigma=0.0)
        assert neighbour_corr(smooth.prototypes) > 0.8
        assert abs(neighbour_corr(white.prototypes)) < 0.2

    def test_subspace_geometry_preserved(self):
        # smoothing must not change the calibration story: prototypes stay
        # rank-16, unit-norm basis, same coefficient scale => pairwise
        # prototype distances in the same regime as the white-noise task
        from feddrift_tpu.data.prototype import PrototypeSampler
        s = PrototypeSampler((784,), 10, smooth_sigma=3.0)
        w = PrototypeSampler((784,), 10, smooth_sigma=0.0)
        ds = np.linalg.matrix_rank(
            (s.prototypes.reshape(10, -1) - 0.5), tol=1e-3)
        assert ds <= 16
        dist_s = np.linalg.norm(
            s.prototypes[0].ravel() - s.prototypes[1:].reshape(9, -1),
            axis=1).mean()
        dist_w = np.linalg.norm(
            w.prototypes[0].ravel() - w.prototypes[1:].reshape(9, -1),
            axis=1).mean()
        assert 0.5 < dist_s / dist_w < 2.0

    def test_determinism(self):
        cfg = ExperimentConfig(dataset="femnist-smooth", train_iterations=1,
                               sample_num=5, client_num_in_total=2,
                               client_num_per_round=2)
        a, b = make_dataset(cfg), make_dataset(cfg)
        assert np.array_equal(a.x, b.x) and np.array_equal(a.y, b.y)

    def test_fmow_smooth_covariate_drift(self):
        # fmow-smooth keeps fmow's drift semantics (fixed labels, shifted
        # inputs) with a SMOOTHED concept shift of preserved magnitude
        cfg = ExperimentConfig(dataset="fmow-smooth", train_iterations=2,
                               sample_num=8, client_num_in_total=4,
                               client_num_per_round=4, change_points="A")
        ds = make_dataset(cfg)
        assert ds.x.shape == (4, 3, 8, 32, 32, 3)
        assert ds.num_classes == 62
        assert ds.meta["smooth_sigma"] > 0
        k = ds.concepts
        drifted = [(c, t) for c in range(4) for t in range(3)
                   if k[t, c] != k[0, c]]
        assert drifted, "preset A must drift someone in 2 iterations"
        c, t = drifted[0]
        assert abs(ds.x[c, t].mean() - ds.x[c, 0].mean()) > 0.01
        # the shift itself is spatially smooth: neighbouring-pixel corr of
        # the mean concept difference is high (white shift would be ~0)
        diff = (ds.x[c, t].mean(0) - ds.x[c, 0].mean(0))[:, :, 0]
        assert self.neighbour_corr(diff) > 0.5

    @pytest.mark.slow
    def test_conv_learnability(self):
        # The family's reason to exist, regression-tested: a CNN trained
        # from scratch beats chance clearly at sigma=3 and decisively
        # beats the white-noise control (which at this 10-class budget is
        # weakly conv-visible, NOT chance — the round-4 chance-level
        # failure is acute at 62 classes). Small budget — the full
        # calibration table is scripts/probe_smooth_conv.py.
        import importlib.util
        import os
        spec = importlib.util.spec_from_file_location(
            "probe_smooth_conv",
            os.path.join(os.path.dirname(__file__), "..", "scripts",
                         "probe_smooth_conv.py"))
        probe = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(probe)
        smooth = probe.probe_one("MNIST", 3.0, steps=250, n_train=2000,
                                 n_test=800, lr=3e-3, batch=64)
        white = probe.probe_one("MNIST", 0.0, steps=250, n_train=2000,
                                n_test=800, lr=3e-3, batch=64)
        chance = 0.1
        assert smooth["cnn_acc"] > chance + 0.15, smooth
        assert smooth["cnn_acc"] < smooth["bayes_acc"], smooth
        # The discriminating property is the GAP, not an absolute control
        # floor: at 10 classes the white-noise projection is weakly
        # conv-visible (~0.2-0.3 — probe table in BASELINE.md; the
        # round-4 chance-level failure is acute at 62 classes, which this
        # budget-bounded test doesn't train). Smoothing must still beat
        # the white control decisively, and a control that itself becomes
        # strongly learnable (label leakage into the sigma=0 path) is a
        # broken control, gap or no gap.
        assert smooth["cnn_acc"] > white["cnn_acc"] + 0.2, (smooth, white)
        assert white["cnn_acc"] < 0.45, white


class TestRetrain:
    def test_all(self):
        w = time_weights("all", 3, 2, 6)
        assert (w[:, :3] == 1).all() and (w[:, 3:] == 0).all()

    def test_win(self):
        w = time_weights("win-2", 2, 4, 6)
        assert (w[:, 3:5] == 1).all()
        assert w.sum() == 4
        w0 = time_weights("win-3", 2, 0, 6)
        assert w0.sum() == 2  # clipped at 0

    def test_weight_exp_linear(self):
        w = time_weights("weight-exp", 1, 3, 5)
        assert list(w[0, :4]) == [1, 2, 4, 8]
        w = time_weights("weight-linear", 1, 3, 5)
        assert list(w[0, :4]) == [1, 2, 3, 4]

    def test_sel_and_clientsel(self):
        w = time_weights("sel-0,2", 2, 3, 5)
        assert (w[:, [0, 2]] == 1).all() and w.sum() == 4
        w = time_weights("clientsel-[[0],[1,2]]", 2, 2, 5)
        assert w[0, 0] == 1 and w[1, 1] == 1 and w[1, 2] == 1 and w.sum() == 3

    def test_poisson(self):
        w = time_weights("poisson", 2, 3, 5)
        assert (w[:, 3] == 1).all() and w.sum() == 2
        counts = poisson_sample_counts(4, 100, np.random.default_rng(0))
        assert counts.shape == (4, 100)
        assert (counts.sum(axis=1) > 0).all()
        assert abs(counts.mean() - 1.0) < 0.15

    def test_unknown_raises(self):
        with pytest.raises(NameError):
            time_weights("bogus", 1, 0, 2)
