"""Two-process multi-controller integration: the round program SPMD across a
process boundary (the single-box analog of a multi-host pod over DCN).

Spawns two fresh interpreters (each owning 2 virtual CPU devices) that join
one jax.distributed runtime and run the REAL federated round over a 4-device
global mesh — validating comm/multihost.py against an actual multi-process
runtime rather than its single-process identity fallbacks.
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys
from pathlib import Path

import pytest

pytestmark = pytest.mark.slow   # heavy compiles: full-tier only

WORKER = Path(__file__).resolve().parent / "_multihost_worker.py"


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_round_program_spans_two_processes():
    addr = f"127.0.0.1:{_free_port()}"
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    procs = [
        subprocess.Popen(
            [sys.executable, str(WORKER), str(pid), "2", addr],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env)
        for pid in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=540)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        # Processes whose communicate() already finished have a closed
        # stdout; only drain the ones that were still running.
        drained = list(outs)
        for p in procs[len(outs):]:
            try:
                drained.append(p.communicate()[0] or "")
            except Exception:
                drained.append("<unreadable>")
        pytest.fail("multihost workers timed out:\n" + "\n---\n".join(drained))
    for p, out in zip(procs, outs):
        assert p.returncode == 0, out[-3000:]
    assert any("WORKER_OK 0" in o for o in outs), outs[0][-1500:]
    assert any("WORKER_OK 1" in o for o in outs), outs[1][-1500:]
    # both processes computed the identical aggregated model
    digests = {o.split("digest=")[1].split()[0]
               for o in outs if "digest=" in o}
    assert len(digests) == 1, digests
