"""Checkpoint/resume: iteration-granular continuation must reproduce the
uninterrupted run (the reference's cross-mpirun state-file semantics,
SURVEY.md §5, made atomic)."""

import numpy as np

from feddrift_tpu.config import ExperimentConfig
from feddrift_tpu.simulation.runner import Experiment
import pytest

pytestmark = pytest.mark.slow   # heavy compiles: full-tier only


def _cfg(**kw):
    base = dict(dataset="sine", model="fnn", concept_drift_algo="softcluster",
                concept_drift_algo_arg="H_A_C_1_10_0", concept_num=2,
                train_iterations=3, comm_round=6, epochs=4, sample_num=80,
                batch_size=40, frequency_of_the_test=3, lr=0.05,
                client_num_in_total=8, client_num_per_round=8, seed=3)
    base.update(kw)
    return ExperimentConfig(**base)


class TestCheckpointResume:
    def test_resume_matches_uninterrupted(self, tmp_path):
        out = str(tmp_path / "run")
        cfg = _cfg()

        # uninterrupted reference trajectory
        full = Experiment(cfg)
        full.run()
        full_accs = full.logger.series("Test/Acc")

        # run 2 iterations, checkpoint, resume for the third. Simulate a
        # crash that logged part of iteration 2 after the checkpoint: resume
        # must drop those partial rows, not duplicate them.
        part = Experiment(cfg, out_dir=out)
        part.run_iteration(0)
        part.run_iteration(1)
        part.logger.log({"iteration": 2, "round": 2 * cfg.comm_round,
                         "Test/Acc": -1.0})
        part.logger.close()

        resumed = Experiment.resume(cfg, out, use_wandb=False)
        assert resumed.start_iteration == 2
        assert resumed.global_round == 2 * cfg.comm_round
        resumed.run()

        # the resumed iteration-2 metrics must match the uninterrupted run
        tail = [v for r, v in full_accs if r >= 2 * cfg.comm_round]
        tail_resumed = [v for r, v in resumed.logger.series("Test/Acc")]
        np.testing.assert_allclose(tail_resumed, tail, rtol=1e-5)

        # and the on-disk file must hold exactly one row per logged round
        import json as _json
        with open(f"{out}/metrics.jsonl") as f:
            rows = [_json.loads(line) for line in f]
        seen = [(r["iteration"], r["round"]) for r in rows]
        assert len(seen) == len(set(seen))
        assert all(r.get("Test/Acc") != -1.0 for r in rows)

    def test_checkpoint_atomic_overwrite(self, tmp_path):
        out = str(tmp_path / "run")
        cfg = _cfg(train_iterations=2)
        exp = Experiment(cfg, out_dir=out)
        exp.run_iteration(0)
        exp.run_iteration(1)   # overwrites the iteration-0 checkpoint
        resumed = Experiment.resume(cfg, out)
        assert resumed.start_iteration == 2

    def test_driftsurf_key_params_roundtrip(self, tmp_path):
        out = str(tmp_path / "run")
        cfg = _cfg(concept_drift_algo="driftsurf", concept_drift_algo_arg="")
        exp = Experiment(cfg, out_dir=out)
        exp.run_iteration(0)
        resumed = Experiment.resume(cfg, out)
        assert resumed.algo.train_keys == exp.algo.train_keys
        a = np.asarray(list(jax_leaves(resumed.algo.key_params["pred"]))[0])
        b = np.asarray(list(jax_leaves(exp.algo.key_params["pred"]))[0])
        np.testing.assert_allclose(a, b, rtol=1e-6)


def jax_leaves(tree):
    import jax
    return jax.tree_util.tree_leaves(tree)
