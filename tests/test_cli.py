"""CLI parity: python -m feddrift_tpu {run,resume,list}.

The reference's experiment mains are shell scripts with 24 positional args
(run_fedavg_distributed_pytorch.sh:3-26); here every ExperimentConfig field
is a generated --flag, and the packed algo-arg strings parse unchanged.
"""

import json
import os

import pytest

from feddrift_tpu.cli import main


@pytest.mark.slow
class TestCli:
    def test_list(self, capsys):
        assert main(["list"]) == 0
        out = capsys.readouterr().out
        for needle in ("softcluster", "kue", "sea", "fnn", "resnet110"):
            assert needle in out

    def test_run_and_resume(self, tmp_path, capsys):
        args = ["--dataset", "sine", "--model", "fnn",
                "--concept_drift_algo", "win-1", "--concept_num", "2",
                "--client_num_in_total", "4", "--client_num_per_round", "4",
                "--train_iterations", "2", "--comm_round", "3",
                "--epochs", "1", "--batch_size", "16", "--sample_num", "32",
                "--frequency_of_the_test", "2",
                "--out_dir", str(tmp_path)]
        assert main(["run", *args]) == 0
        out = capsys.readouterr().out.strip().splitlines()
        final = json.loads(out[-1])
        assert "Test/Acc" in final and final["rounds"] == 6
        # runs nest under out_dir/<run-name>/ckpt -> resume from that dir
        (run_dir,) = [d for d in os.listdir(tmp_path)
                      if os.path.isdir(os.path.join(tmp_path, d))]
        assert os.path.exists(os.path.join(tmp_path, run_dir, "ckpt"))
        assert main(["resume", "--out_dir",
                     os.path.join(tmp_path, run_dir)]) == 0

    def test_flat_out_dir(self, tmp_path, capsys):
        # Driver-script regression (round-4 verdict): scripts pass a LEAF
        # name as --out_dir; without --flat_out_dir the CLI nested an
        # auto-named duplicate dir inside it, which post-hoc flattening
        # then copied (not moved), committing byte-identical twins. With
        # the flag, metrics/ckpt land directly in out_dir and nothing
        # nests.
        out = tmp_path / "sine-fnn-win-1-leaf-s0"
        args = ["--dataset", "sine", "--model", "fnn",
                "--concept_drift_algo", "win-1", "--concept_num", "2",
                "--client_num_in_total", "4", "--client_num_per_round", "4",
                "--train_iterations", "2", "--comm_round", "3",
                "--epochs", "1", "--batch_size", "16", "--sample_num", "32",
                "--frequency_of_the_test", "2",
                "--flat_out_dir", "--out_dir", str(out)]
        assert main(["run", *args]) == 0
        capsys.readouterr()
        assert (out / "metrics.jsonl").exists()
        assert (out / "ckpt").is_dir()
        # ckpt.old is the deliberately-kept previous checkpoint generation
        # (the corruption fallback, utils/checkpoint.py) — not a nesting bug
        nested = [d for d in os.listdir(out)
                  if (out / d).is_dir() and d not in ("ckpt", "ckpt.old")]
        assert nested == [], f"unexpected nested dirs: {nested}"
        # and the flat layout resumes from out_dir itself
        assert main(["resume", "--out_dir", str(out)]) == 0
        capsys.readouterr()

    def test_stream_and_debug_flags(self, tmp_path, capsys):
        # the generated bool flags drive the new execution modes end-to-end
        args = ["--dataset", "sine", "--model", "fnn",
                "--concept_drift_algo", "win-1", "--concept_num", "2",
                "--client_num_in_total", "4", "--client_num_per_round", "4",
                "--train_iterations", "2", "--comm_round", "3",
                "--epochs", "1", "--batch_size", "16", "--sample_num", "32",
                "--frequency_of_the_test", "2", "--out_dir", str(tmp_path),
                "--stream_data", "true", "--debug_checks", "true"]
        assert main(["run", *args]) == 0
        final = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert "Test/Acc" in final
        import jax
        jax.config.update("jax_debug_nans", False)   # restore for the suite

    def test_unknown_algo_fails_cleanly(self, tmp_path):
        import pytest
        with pytest.raises(KeyError, match="nope"):
            main(["run", "--dataset", "sine", "--model", "fnn",
                  "--concept_drift_algo", "nope", "--out_dir", str(tmp_path)])
