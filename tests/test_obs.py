"""Telemetry subsystem tests: event bus, instruments, report CLI, logging
setup, schema consistency. Pure host logic except the overhead micro-test
(slow tier: needs a compiled train_round)."""

from __future__ import annotations

import json
import os
import re
import threading
import time

import pytest

from feddrift_tpu import obs
from feddrift_tpu.obs.events import EVENT_KINDS, EventBus
from feddrift_tpu.obs.instruments import Registry


class TestEventBus:
    def test_schema_round_trip_every_kind(self, tmp_path):
        """Every kind in the taxonomy emits, persists, and JSON-decodes with
        the required _ts/kind envelope."""
        path = str(tmp_path / "events.jsonl")
        bus = EventBus(path)
        for kind in sorted(EVENT_KINDS):
            bus.emit(kind, detail=f"payload-{kind}")
        bus.close()
        with open(path) as f:
            rows = [json.loads(line) for line in f]
        assert len(rows) == len(EVENT_KINDS)
        for r in rows:
            assert isinstance(r["_ts"], float)
            assert r["kind"] in EVENT_KINDS
            assert r["detail"] == f"payload-{r['kind']}"

    def test_unknown_kind_rejected(self):
        bus = EventBus(None)
        with pytest.raises(ValueError, match="unknown event kind"):
            bus.emit("totally_new_event")

    def test_context_merging_and_removal(self, tmp_path):
        bus = EventBus(str(tmp_path / "events.jsonl"))
        bus.set_context(iteration=3, round=17)
        rec = bus.emit("eval", test_acc=0.5)
        assert rec["iteration"] == 3 and rec["round"] == 17
        bus.set_context(round=None)
        rec = bus.emit("eval", test_acc=0.6)
        assert rec["iteration"] == 3 and "round" not in rec
        # explicit field wins over ambient context
        rec = bus.emit("eval", iteration=9)
        assert rec["iteration"] == 9
        bus.close()

    def test_numpy_fields_serialize(self, tmp_path):
        import numpy as np
        path = str(tmp_path / "events.jsonl")
        bus = EventBus(path)
        bus.emit("fault_injected", clients=np.array([1, 2]),
                 acc=np.float32(0.5))
        bus.close()
        with open(path) as f:
            (row,) = [json.loads(line) for line in f]
        assert row["clients"] == [1, 2]

    def test_emit_thread_safe(self, tmp_path):
        path = str(tmp_path / "events.jsonl")
        bus = EventBus(path)

        def worker(i):
            for _ in range(200):
                bus.emit("conn_drop", transport=f"w{i}")

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        bus.close()
        with open(path) as f:
            rows = [json.loads(line) for line in f]   # no torn lines
        assert len(rows) == 800

    def test_configure_swaps_default_bus(self, tmp_path):
        old = obs.get_bus()
        try:
            bus = obs.configure(str(tmp_path / "events.jsonl"))
            assert obs.get_bus() is bus
            obs.emit("run_start", dataset="x")
            assert bus.events("run_start")
        finally:
            obs.configure(None)
        assert obs.get_bus() is not old


class TestInstruments:
    def test_counter_gauge_histogram(self):
        reg = Registry()
        reg.counter("c", transport="t").inc()
        reg.counter("c", transport="t").inc(2)
        reg.gauge("g").set(5)
        h = reg.histogram("h")
        for v in (0.0005, 0.02, 3.0):
            h.observe(v)
        snap = reg.snapshot()
        assert snap['c{transport="t"}'] == 3
        assert snap["g"] == 5
        assert snap["h"]["count"] == 3
        assert abs(snap["h"]["sum"] - 3.0205) < 1e-9

    def test_counter_rejects_negative(self):
        with pytest.raises(ValueError):
            Registry().counter("c").inc(-1)

    def test_type_collision_raises(self):
        reg = Registry()
        reg.counter("x")
        with pytest.raises(TypeError):
            reg.gauge("x")

    def test_prometheus_text_format(self):
        reg = Registry()
        reg.counter("bytes_out", transport="mqtt").inc(10)
        reg.gauge("num_models").set(3)
        reg.histogram("lat", buckets=(0.1, 1.0)).observe(0.5)
        text = reg.to_prometheus_text()
        assert "# TYPE bytes_out counter" in text
        assert 'bytes_out{transport="mqtt"} 10.0' in text
        assert "# TYPE num_models gauge" in text
        assert "# TYPE lat histogram" in text
        # cumulative le buckets + the +Inf catch-all
        assert 'lat_bucket{le="0.1"} 0' in text
        assert 'lat_bucket{le="1.0"} 1' in text
        assert 'lat_bucket{le="+Inf"} 1' in text
        assert "lat_sum 0.5" in text
        assert "lat_count 1" in text

    def test_textfile_atomic_write(self, tmp_path):
        reg = Registry()
        reg.counter("c").inc()
        path = str(tmp_path / "metrics.prom")
        reg.write_textfile(path)
        assert open(path).read().endswith("c 1.0\n")
        assert not os.path.exists(path + ".tmp")

    def test_reset(self):
        reg = Registry()
        reg.counter("c").inc()
        reg.reset()
        assert reg.snapshot() == {}


class TestExpositionFormat:
    """Prometheus exposition-format compliance: label escaping, the
    strict line grammar, histogram triplet invariants, and torn-read
    freedom under concurrent observes."""

    # one metric line: name{labels} value  — label values are quoted
    # strings where only \\, \" and \n escapes are legal
    _LINE = re.compile(
        r'^[a-zA-Z_:][a-zA-Z0-9_:]*'
        r'(\{[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\\n]|\\\\|\\"|\\n)*"'
        r'(,[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\\n]|\\\\|\\"|\\n)*")*\})?'
        r' -?[0-9+.eEinf]+$')
    _TYPE = re.compile(
        r"^# TYPE [a-zA-Z_:][a-zA-Z0-9_:]* "
        r"(counter|gauge|histogram|summary)$")

    def test_label_value_escaping(self):
        """Backslash, double quote and newline in a label value must be
        escaped per the exposition spec — raw they corrupt the line
        grammar (a bare quote ends the value early)."""
        reg = Registry()
        reg.counter("c", path='we"ird\\x\ny').inc(3)
        text = reg.to_prometheus_text()
        assert 'c{path="we\\"ird\\\\x\\ny"} 3.0' in text
        for line in text.strip().splitlines():
            if not line.startswith("#"):
                assert self._LINE.match(line), f"unparseable line: {line!r}"
        # snapshot keys carry the same escaping (same _label_str)
        assert 'c{path="we\\"ird\\\\x\\ny"}' in reg.snapshot()

    def test_strict_parse_golden(self):
        """Every line of a mixed-instrument export matches the exposition
        grammar; TYPE lines precede their family; histogram buckets are
        cumulative and the +Inf bucket equals _count."""
        reg = Registry()
        reg.counter("bytes_out", transport="netbroker").inc(7)
        reg.gauge("num_models").set(3)
        h = reg.histogram("lat", buckets=(0.1, 1.0))
        for v in (0.05, 0.5, 0.7, 5.0):
            h.observe(v)
        q = reg.quantile_sketch("lat_q")
        for i in range(100):
            q.observe(i / 100.0)
        text = reg.to_prometheus_text()
        assert text.endswith("\n")
        typed = set()
        for line in text.strip().splitlines():
            if line.startswith("#"):
                assert self._TYPE.match(line), f"bad TYPE line: {line!r}"
                typed.add(line.split()[2])
            else:
                assert self._LINE.match(line), f"unparseable line: {line!r}"
        assert typed == {"bytes_out", "num_models", "lat", "lat_q"}
        # histogram triplet: cumulative buckets, +Inf == _count
        assert 'lat_bucket{le="0.1"} 1' in text
        assert 'lat_bucket{le="1.0"} 3' in text
        assert 'lat_bucket{le="+Inf"} 4' in text
        assert "lat_count 4" in text
        # summary: per-quantile lines + _sum/_count
        assert 'lat_q{quantile="0.5"}' in text
        assert 'lat_q{quantile="0.99"}' in text
        assert "lat_q_count 100" in text
        # TYPE precedes the family's first sample line
        lines = text.splitlines()
        assert lines.index("# TYPE lat histogram") \
            < lines.index('lat_bucket{le="0.1"} 1')

    def test_no_torn_reads_under_concurrent_observe(self):
        """Exports racing a hot observe loop must stay self-consistent:
        within one export the +Inf cumulative bucket equals _count for
        every histogram (both copied under the instrument lock)."""
        reg = Registry()
        h = reg.histogram("hot", buckets=(0.5,))
        q = reg.quantile_sketch("hot_q")
        stop = threading.Event()

        def writer():
            while not stop.is_set():
                h.observe(0.1)
                h.observe(0.9)
                q.observe(0.3)

        threads = [threading.Thread(target=writer) for _ in range(4)]
        for t in threads:
            t.start()
        try:
            for _ in range(200):
                text = reg.to_prometheus_text()
                inf = count = qsum = qcount = None
                for line in text.splitlines():
                    if line.startswith('hot_bucket{le="+Inf"} '):
                        inf = int(line.rsplit(" ", 1)[1])
                    elif line.startswith("hot_count "):
                        count = int(line.rsplit(" ", 1)[1])
                    elif line.startswith("hot_q_sum "):
                        qsum = float(line.rsplit(" ", 1)[1])
                    elif line.startswith("hot_q_count "):
                        qcount = int(line.rsplit(" ", 1)[1])
                assert inf == count, f"torn histogram: +Inf={inf} count={count}"
                # sketch sum/count snapshotted together: sum == 0.3 * count
                assert abs(qsum - 0.3 * qcount) < 1e-6 * max(qcount, 1), \
                    f"torn sketch: sum={qsum} count={qcount}"
                snap = reg.snapshot()["hot"]
                assert sum(snap["buckets"].values()) == snap["count"]
        finally:
            stop.set()
            for t in threads:
                t.join()


class TestPhaseTracerConcurrency:
    def test_nested_and_reentrant_phases(self):
        from feddrift_tpu.utils.tracing import PhaseTracer
        tr = PhaseTracer()
        with tr.phase("outer"):
            with tr.phase("inner"):
                pass
            with tr.phase("outer"):         # re-entrant same name
                pass
        s = tr.summary()
        assert s["outer"]["count"] == 2
        assert s["inner"]["count"] == 1
        # outer's outer entry spans the nested ones
        assert s["outer"]["total_s"] >= s["inner"]["total_s"]

    def test_thread_safety(self):
        """Comm brokers record phases from background threads; totals must
        not lose updates."""
        from feddrift_tpu.utils.tracing import PhaseTracer
        tr = PhaseTracer()

        def worker():
            for _ in range(500):
                with tr.phase("shared"):
                    pass

        threads = [threading.Thread(target=worker) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert tr.summary()["shared"]["count"] == 2000

    def test_registry_hook_records_histogram(self):
        from feddrift_tpu.utils.tracing import PhaseTracer
        reg = Registry()
        tr = PhaseTracer(registry=reg)
        with tr.phase("train_round"):
            pass
        snap = reg.snapshot()
        assert snap['phase_seconds{phase="train_round"}']["count"] == 1


class TestMetricsLoggerLifecycle:
    def test_context_manager_closes_handle(self, tmp_path):
        from feddrift_tpu.utils.metrics import MetricsLogger
        with MetricsLogger(str(tmp_path)) as lg:
            lg.log({"iteration": 0, "Test/Acc": 0.5})
            fh = lg._fh
            assert fh is not None
        assert lg._fh is None and fh.closed
        assert lg.last("Test/Acc") == 0.5      # history survives close

    def test_close_idempotent(self, tmp_path):
        from feddrift_tpu.utils.metrics import MetricsLogger
        lg = MetricsLogger(str(tmp_path))
        lg.close()
        lg.close()                             # second close: no raise

    def test_exception_path_closes(self, tmp_path):
        from feddrift_tpu.utils.metrics import MetricsLogger
        try:
            with MetricsLogger(str(tmp_path)) as lg:
                raise RuntimeError("runner crash")
        except RuntimeError:
            pass
        assert lg._fh is None


def _write_jsonl(path, rows):
    with open(path, "w") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")


class TestReport:
    def test_smoke_against_committed_run(self, capsys):
        """The report CLI renders committed metrics-only runs (they predate
        events.jsonl) without error."""
        from feddrift_tpu.obs.report import main
        run = os.path.join(os.path.dirname(__file__), os.pardir, "runs",
                           "sea-fnn-softcluster-H_A_C_1_10_0-s0")
        assert main([run]) == 0
        out = capsys.readouterr().out
        assert "Test/Acc final=" in out
        assert "phase breakdown:" in out
        assert "predates events.jsonl" in out

    def test_full_report_with_events(self, tmp_path, capsys):
        from feddrift_tpu.obs.report import main
        _write_jsonl(tmp_path / "metrics.jsonl", [
            {"_ts": 1.0, "iteration": 0, "round": 0, "Test/Acc": 0.5},
            {"_ts": 2.0, "iteration": 1, "round": 1, "Test/Acc": 0.7},
        ])
        _write_jsonl(tmp_path / "events.jsonl", [
            {"_ts": 1.0, "kind": "iteration_end", "iteration": 0,
             "wall_s": 2.0, "rounds": 4, "examples": 800,
             "phases": {"train_round": {"total_s": 1.5, "count": 4},
                        "eval": {"total_s": 0.2, "count": 2}}},
            {"_ts": 1.2, "kind": "drift_detected", "iteration": 1,
             "client": 3, "acc_drop": 0.2},
            {"_ts": 1.3, "kind": "cluster_create", "iteration": 1,
             "model": 1, "init_from": 0},
            {"_ts": 1.4, "kind": "cluster_merge", "iteration": 1,
             "base": 0, "merged": 1},
            {"_ts": 1.5, "kind": "cluster_state", "iteration": 1,
             "num_models": 2, "spawns": 1, "merges": 1},
            {"_ts": 1.6, "kind": "fault_injected", "fault_round": 7,
             "clients": [2, 5]},
            {"_ts": 1.7, "kind": "jit_compile", "fn": "train_round",
             "signature_count": 1},
            {"_ts": 1.8, "kind": "jit_recompile", "fn": "train_round",
             "signature_count": 2},
        ])
        assert main([str(tmp_path)]) == 0
        out = capsys.readouterr().out
        assert "train_round" in out and "n=4" in out         # breakdown shown
        assert "drift_detected" in out
        assert "cluster_merge" in out
        assert "rounds in" in out                            # throughput
        assert "clients ever dropped: [2, 5]" in out
        assert "compiles=1 recompiles=1" in out

    def test_json_output(self, tmp_path, capsys):
        from feddrift_tpu.obs.report import main
        _write_jsonl(tmp_path / "metrics.jsonl",
                     [{"_ts": 1.0, "iteration": 0, "round": 0,
                       "Test/Acc": 0.5}])
        assert main([str(tmp_path), "--json"]) == 0
        d = json.loads(capsys.readouterr().out)
        assert d["accuracy"]["final_test_acc"] == 0.5

    def test_empty_dir_fails(self, tmp_path, capsys):
        from feddrift_tpu.obs.report import main
        assert main([str(tmp_path)]) == 1

    def test_cli_report_verb(self, capsys):
        """`python -m feddrift_tpu report <dir>` routes without touching
        the jax backend."""
        from feddrift_tpu.cli import main
        run = os.path.join(os.path.dirname(__file__), os.pardir, "runs",
                           "sea-fnn-win-1-H_A_C_1_10_0-s0")
        assert main(["report", run]) == 0
        assert "throughput:" in capsys.readouterr().out


class TestLoggingSetup:
    def test_log_level_applies(self):
        import logging
        obs.setup_logging("debug")
        assert logging.getLogger("feddrift_tpu").level == logging.DEBUG
        obs.setup_logging("info")
        assert logging.getLogger("feddrift_tpu").level == logging.INFO

    def test_unknown_level_raises(self):
        with pytest.raises(ValueError):
            obs.setup_logging("loud")


class TestSchemaConsistency:
    def _mod(self):
        import importlib.util
        path = os.path.join(os.path.dirname(__file__), os.pardir, "scripts",
                            "check_events_schema.py")
        spec = importlib.util.spec_from_file_location("check_events_schema",
                                                      path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    def test_static_taxonomy_check(self):
        """The tier-1 incarnation of scripts/check_events_schema.py: every
        emitted kind is in EVENT_KINDS and documented, no stale docs."""
        assert self._mod().check() == []

    def test_strict_no_dead_kinds(self):
        """--strict additionally rejects taxonomy members with ZERO emit
        sites in the tree (dead kinds): an event that can never be
        produced must not stay documented as if it could."""
        assert self._mod().check(strict=True) == []

    def test_strict_detects_a_dead_kind(self, monkeypatch):
        """Negative control: inject a phantom kind into EVENT_KINDS and
        strict mode must flag it while the lax check stays quiet about
        emission (it only cross-checks docs)."""
        mod = self._mod()
        from feddrift_tpu.obs import events as ev
        monkeypatch.setattr(
            ev, "EVENT_KINDS", frozenset(ev.EVENT_KINDS | {"phantom_kind"}))
        problems = mod.check(strict=True)
        assert any("phantom_kind" in p and "ZERO emit sites" in p
                   for p in problems)


@pytest.mark.slow
class TestOverhead:
    def test_instruments_under_5pct_of_train_round(self):
        """Bounded-overhead budget: the telemetry operations an instrumented
        round performs must cost <5% of a tiny CPU train_round. Measured as
        per-op cost x a generous per-round op count, against the steady
        state round wall time — deterministic, unlike an A/B wall-clock
        diff on a 1-core CI box."""
        import jax
        import jax.numpy as jnp
        from feddrift_tpu.config import ExperimentConfig
        from feddrift_tpu.simulation.runner import Experiment

        cfg = ExperimentConfig(dataset="sea", model="fnn",
                               concept_drift_algo="win-1", concept_num=1,
                               client_num_in_total=4, client_num_per_round=4,
                               train_iterations=2, comm_round=2, epochs=1,
                               sample_num=32, batch_size=16,
                               frequency_of_the_test=1, chunk_rounds=False,
                               report_client=0)
        exp = Experiment(cfg)
        tw, sw, fm, lr = exp.algo.round_inputs(0, 0)
        exp.algo.begin_iteration(0)
        tw, sw, fm, lr = exp.algo.round_inputs(0, 0)
        tw = exp._pad_clients(tw)
        sw = exp._pad_clients(sw, value=1.0)
        opt = exp.step.init_opt_states(exp.pool.params,
                                       exp.pool.num_models, exp.C_pad)
        key = jax.random.PRNGKey(0)

        def one_round():
            out = exp.step.train_round(exp.pool.params, opt, key,
                                       exp.x, exp.y, tw, sw, fm, lr)
            jax.block_until_ready(out[0])

        one_round()                           # compile
        t0 = time.perf_counter()
        for _ in range(10):
            one_round()
        round_s = (time.perf_counter() - t0) / 10

        # A real round performs ~1 signature note (inside train_round,
        # already included above), plus at most ~5 counter/gauge ops, 2
        # histogram observes and 2 event emissions. Budget 20 of each.
        bus = EventBus(None)
        reg = Registry()
        c = reg.counter("x")
        h = reg.histogram("h")
        N = 200
        t0 = time.perf_counter()
        for _ in range(N):
            c.inc()
            h.observe(0.001)
            bus.emit("eval", test_acc=0.5)
        per_op = (time.perf_counter() - t0) / N
        obs_per_round = 20 * per_op
        assert obs_per_round < 0.05 * round_s, (
            f"telemetry {obs_per_round * 1e6:.1f}us/round vs round "
            f"{round_s * 1e6:.1f}us")
