"""Test harness: force an 8-device virtual CPU platform.

Mirrors how the reference smoke-tests its MPI pipeline on one box with
``--ci 1`` (FedAvgEnsAggregatorSoftCluster.py:259-264): the pjit/collective
paths run against XLA's host-platform device simulation so multi-chip sharding
is exercised without TPU hardware.

Note: this environment pre-imports jax via sitecustomize (axon TPU tunnel),
so the platform must be overridden through jax.config, not env vars — and
XLA_FLAGS must be appended before the first backend initialisation.
"""

import os

_flag = "--xla_force_host_platform_device_count=8"
if _flag not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") + " " + _flag).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

assert len(jax.devices()) >= 8, jax.devices()


import pytest  # noqa: E402


@pytest.fixture(autouse=True, scope="module")
def _clear_jax_caches_between_modules():
    """Full-suite runs accumulate hundreds of compiled executables across
    modules; XLA:CPU has been observed to segfault inside backend_compile
    late in the run (reproducibly at the same test in-suite, never when the
    module runs alone). Dropping compiled programs between modules keeps the
    compiler's heap small; per-module recompiles are the price."""
    yield
    import jax
    jax.clear_caches()


# The threaded suites run under the lock-order recorder
# (analysis/lockorder.py): every repo-created lock is instrumented, a
# same-thread re-acquisition of a non-reentrant Lock (the PR 9 tap
# re-entrancy deadlock) raises instead of hanging, and at module teardown
# the accumulated acquisition graph must be ACYCLIC — a cycle is a latent
# deadlock two threads can hit even if this run didn't.
_LOCKORDER_MODULES = ("test_live_ops", "test_resilience", "test_prefetch")


@pytest.fixture(autouse=True, scope="module")
def _lock_order_recorder(request):
    name = request.module.__name__.rsplit(".", 1)[-1]
    if name not in _LOCKORDER_MODULES:
        yield None
        return
    from feddrift_tpu.analysis.lockorder import LockOrderRecorder
    rec = LockOrderRecorder()
    rec.install()
    try:
        yield rec
    finally:
        rec.uninstall()
    rec.check()     # raises LockOrderViolation on any recorded cycle
