"""Debug-mode invariant checks (utils/invariants.py, cfg.debug_checks)."""

import numpy as np
import pytest

from feddrift_tpu.utils.invariants import (InvariantError,
                                           check_round_inputs,
                                           check_weight_partition)

pytestmark = pytest.mark.slow   # heavy compiles: full-tier only


class TestCheckRoundInputs:
    def _ok(self):
        M, C, T1, N = 2, 3, 4, 8
        return (np.ones((M, C, T1), np.float32),
                np.ones((M, C, N), np.float32),
                np.ones((M, 5), np.float32),
                dict(num_models=M, num_clients=C, num_steps_p1=T1,
                     sample_num=N))

    def test_accepts_valid(self):
        tw, sw, fm, kw = self._ok()
        check_round_inputs(tw, sw, fm, **kw)

    @pytest.mark.parametrize("mutation,match", [
        (lambda tw, sw, fm: (tw[:, :, :2], sw, fm), "time_w shape"),
        (lambda tw, sw, fm: (tw, sw[:1], fm), "sample_w shape"),
        (lambda tw, sw, fm: (tw * np.nan, sw, fm), "non-finite"),
        (lambda tw, sw, fm: (tw - 2.0, sw, fm), "negative"),
        (lambda tw, sw, fm: (tw * 0.0, sw, fm), "all-zero"),
    ])
    def test_rejects_invalid(self, mutation, match):
        tw, sw, fm, kw = self._ok()
        with pytest.raises(InvariantError, match=match):
            check_round_inputs(*mutation(tw, sw, fm), **kw)


class TestWeightPartition:
    def test_partition_holds_in_softcluster_run(self):
        w = np.zeros((3, 2, 4), np.float32)
        w[1, 0, :] = 0.3
        w[1, 1, :] = 0.7
        check_weight_partition(w, 1)
        with pytest.raises(InvariantError):
            check_weight_partition(w, 0)

    def test_e2e_with_debug_checks(self):
        from feddrift_tpu.config import ExperimentConfig
        from feddrift_tpu.simulation.runner import run_experiment
        cfg = ExperimentConfig(dataset="sea", model="fnn",
                               concept_drift_algo="softcluster",
                               concept_drift_algo_arg="H_A_C_1_10_0",
                               concept_num=3, change_points="A",
                               client_num_in_total=10, client_num_per_round=10,
                               train_iterations=2, comm_round=4, epochs=2,
                               batch_size=32, sample_num=32,
                               frequency_of_the_test=2, debug_checks=True)
        exp = run_experiment(cfg)
        assert exp.logger.last("Test/Acc") is not None
        # restore global flag for the rest of the suite
        import jax
        jax.config.update("jax_debug_nans", False)
