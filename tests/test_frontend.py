"""Serving frontend tests (platform/frontend.py + the read-path
robustness satellites in platform/serving.py).

The open-socket frontend has three load-bearing behaviors, each pinned
here:

- admission control sheds EXPLICITLY: refusal is an `EngineOverloaded`
  with a reason + retry-after hint (and an HTTP 503 with `Retry-After`),
  never a silent queue into the void — under a saturating OPEN-loop
  storm the bounded frontend keeps the admitted requests' tail bounded,
  never deadlocks, and recovers the moment the storm passes;
- replica management is health-gated with ONE-shot failover: a replica
  whose dispatcher died (or whose forward wedged) is drained from
  rotation, requests caught in flight on it get the explicit
  ``EngineStopped`` and are retried exactly once on a survivor;
- both request planes (in-process / HTTP) speak the same exception
  taxonomy in both directions.
"""

import threading
import time
import types

import numpy as np
import pytest

import jax.numpy as jnp

from feddrift_tpu import obs
from feddrift_tpu.config import ExperimentConfig
from feddrift_tpu.core.pool import ModelPool
from feddrift_tpu.data.registry import make_dataset
from feddrift_tpu.models import create_model
from feddrift_tpu.platform.faults import ReplicaFaultInjector
from feddrift_tpu.platform.frontend import (
    AdmissionController, BackpressureController, FrontendClient,
    ReplicaSet, ServingFrontend, TokenBucket, build_replica_set,
    frontend_slos)
from feddrift_tpu.platform.serving import (
    DeadlineExceededError, EngineOverloaded, EngineStopped,
    MalformedRequestError, RoutingTable, TrafficGenerator,
    UnknownClientError)


def _pool(M=2):
    cfg = ExperimentConfig(dataset="sea", train_iterations=2, sample_num=16)
    ds = make_dataset(cfg)
    mod = create_model("fnn", ds, cfg)
    return ModelPool.create(mod, jnp.zeros((2, 3)), M, seed=7,
                            identical=False)


def _replicas(pool, table, n=1, **kw):
    kw.setdefault("buckets", (1, 2))
    kw.setdefault("max_wait_s", 0.002)
    kw.setdefault("health_interval_s", 0.02)
    return build_replica_set(pool, RoutingTable(table), n=n, **kw)


def _wait_for(pred, what, timeout_s=10.0):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.01)
    raise AssertionError(f"timed out waiting for {what}")


# ----------------------------------------------------------------------
# admission control units (no engine, fake clocks)
class TestTokenBucket:
    def test_burst_then_refill(self):
        t = [0.0]
        tb = TokenBucket(10.0, burst=2, time_fn=lambda: t[0])
        assert tb.try_acquire()
        assert tb.try_acquire()
        assert not tb.try_acquire()          # burst exhausted
        assert 0.0 < tb.retry_after_s() <= 0.1
        t[0] += 0.1                          # exactly one token refills
        assert tb.try_acquire()
        assert not tb.try_acquire()

    def test_refill_caps_at_burst(self):
        t = [0.0]
        tb = TokenBucket(100.0, burst=3, time_fn=lambda: t[0])
        t[0] += 60.0                         # idle forever != infinite burst
        got = sum(tb.try_acquire() for _ in range(10))
        assert got == 3


class TestBackpressure:
    def test_multiplicative_shrink_floor_and_stepwise_recovery(self):
        t = [0.0]
        bp = BackpressureController(shrink=0.5, floor=0.25, recovery_s=1.0,
                                    time_fn=lambda: t[0])
        assert bp.current() == 1.0
        burn = {"kind": "slo_burn", "slo": "serve_p99_latency"}
        bp.observe(burn)
        assert bp.current() == 0.5
        bp.observe(burn)
        bp.observe(burn)                     # floor-clamped
        assert bp.current() == 0.25
        bp.observe({"kind": "slo_burn", "slo": "other_objective"})
        bp.observe({"kind": "request_served", "slo": "serve_p99_latency"})
        assert bp.current() == 0.25          # unwatched records ignored
        t[0] += 1.0
        assert bp.current() == 0.5           # one shrink healed per window
        t[0] += 1.0
        assert bp.current() == 1.0
        t[0] += 10.0
        assert bp.current() == 1.0           # never overshoots

    def test_slo_burn_on_bus_drives_the_factor(self):
        from feddrift_tpu.obs.live import SLOEngine
        bus = obs.get_bus()
        slo = SLOEngine(frontend_slos(1.0)).attach(bus)
        bp = BackpressureController().attach(bus)
        try:
            # objective: p99 <= 1ms; every observation violates -> the
            # burn-rate rule fires once min_samples is reached
            for _ in range(16):
                obs.emit("request_served", client=0, model=0, version=1,
                         batch=1, latency_ms=500.0)
            assert bp.current() < 1.0
        finally:
            bp.detach()
            bus.remove_tap(slo.observe)


class TestAdmissionController:
    def test_window_and_release(self):
        adm = AdmissionController(max_pending=2)
        assert adm.try_admit() == (True, None, 0.0)
        assert adm.try_admit()[0]
        ok, reason, retry_after = adm.try_admit()
        assert (ok, reason) == (False, "queue_full")
        assert retry_after > 0
        adm.release()
        assert adm.try_admit()[0]
        assert adm.pending == 2

    def test_rate_limit_checked_first(self):
        t = [0.0]
        tb = TokenBucket(1.0, burst=1, time_fn=lambda: t[0])
        adm = AdmissionController(max_pending=8, bucket=tb)
        assert adm.try_admit()[0]
        ok, reason, retry_after = adm.try_admit()
        assert (ok, reason) == (False, "rate_limited")
        assert retry_after > 0
        assert adm.pending == 1              # the refusal held no slot

    def test_backpressure_scales_window_and_names_the_reason(self):
        t = [0.0]
        bp = BackpressureController(shrink=0.5, floor=0.25, recovery_s=60.0,
                                    time_fn=lambda: t[0])
        adm = AdmissionController(max_pending=4, backpressure=bp)
        bp.observe({"kind": "slo_burn", "slo": "serve_p99_latency"})
        assert adm.try_admit()[0]
        assert adm.try_admit()[0]            # scaled window: 4 * 0.5 = 2
        ok, reason, _ = adm.try_admit()
        assert (ok, reason) == (False, "backpressure")

    def test_max_pending_must_be_positive(self):
        with pytest.raises(ValueError):
            AdmissionController(max_pending=0)


# ----------------------------------------------------------------------
# replica management over engine-shaped fakes (failover logic isolated
# from JAX)
class _FakeEngine:
    def __init__(self, name, behavior=None):
        self.name = name
        self.failed = None
        self._stop = False
        self._thread = None
        self._queue = []
        self._batches = types.SimpleNamespace(value=0)
        self.calls = 0
        self.behavior = behavior

    def submit(self, client_id, x, timeout=30.0, trace=None,
               deadline_s=None):
        self.calls += 1
        if self.behavior is not None:
            return self.behavior(self)
        return f"ok:{self.name}"

    def close(self):
        self._stop = True


class TestReplicaSetFailover:
    def test_unique_names_required(self):
        with pytest.raises(ValueError):
            ReplicaSet([_FakeEngine("a"), _FakeEngine("a")])
        with pytest.raises(ValueError):
            ReplicaSet([_FakeEngine(None)])
        with pytest.raises(ValueError):
            ReplicaSet([])

    def test_round_robin_over_healthy(self):
        fakes = [_FakeEngine(f"r{i}") for i in range(3)]
        rs = ReplicaSet(fakes)
        for _ in range(6):
            rs.submit(0, [0.0])
        assert [f.calls for f in fakes] == [2, 2, 2]

    def test_engine_stopped_drains_and_retries_once(self):
        def die(eng):
            raise EngineStopped("dispatcher died")
        dead = _FakeEngine("r0", behavior=die)
        live = _FakeEngine("r1")
        rs = ReplicaSet([dead, live])
        before = obs.registry().counter("request_retries").value
        results = [rs.submit(0, [0.0]) for _ in range(4)]
        assert all(r == "ok:r1" for r in results)
        assert dead.calls == 1               # drained after the first death
        assert rs.drained_names() == {"r0": "dispatcher_dead"}
        assert rs.healthy_names() == ["r1"]
        assert obs.registry().counter("request_retries").value == before + 1

    def test_overload_on_sole_replica_propagates(self):
        def full(eng):
            raise EngineOverloaded("queue full", retry_after_s=0.02)
        rs = ReplicaSet([_FakeEngine("r0", behavior=full)])
        with pytest.raises(EngineOverloaded):
            rs.submit(0, [0.0])
        assert rs.healthy_names() == ["r0"]  # overload is NOT a death

    def test_overload_retries_another_replica(self):
        def full(eng):
            raise EngineOverloaded("queue full", retry_after_s=0.02)
        busy = _FakeEngine("r0", behavior=full)
        idle = _FakeEngine("r1")
        rs = ReplicaSet([busy, idle])
        assert rs.submit(0, [0.0]) == "ok:r1"
        assert rs.healthy_names() == ["r0", "r1"]

    def test_all_drained_raises_engine_stopped(self):
        rs = ReplicaSet([_FakeEngine("r0")])
        rs.drain("r0", reason="manual")
        with pytest.raises(EngineStopped, match="no healthy"):
            rs.submit(0, [0.0])

    def test_monitor_drains_dead_dispatcher(self):
        dead = _FakeEngine("r0")             # _thread is None -> not alive
        live = _FakeEngine("r1")
        live._thread = threading.Thread(target=lambda: time.sleep(30),
                                        daemon=True)
        live._thread.start()
        rs = ReplicaSet([dead, live], health_interval_s=0.01).start()
        try:
            _wait_for(lambda: rs.drained_names().get("r0")
                      == "dispatcher_dead", "monitor to drain r0")
            assert rs.healthy_names() == ["r1"]
        finally:
            rs._stop.set()

    def test_monitor_drains_stalled_replica(self):
        # alive thread, work queued, batch counter frozen = a wedged
        # forward; liveness checks can't see it, the stall detector must
        stalled = _FakeEngine("r0")
        stalled._thread = threading.Thread(target=lambda: time.sleep(30),
                                           daemon=True)
        stalled._thread.start()
        stalled._queue = [object()]
        rs = ReplicaSet([stalled], health_interval_s=0.01,
                        stall_after_s=0.05).start()
        try:
            _wait_for(lambda: rs.drained_names().get("r0") == "stalled",
                      "stall detector to drain r0")
        finally:
            rs._stop.set()


# ----------------------------------------------------------------------
# frontend shed semantics (fake replicas)
class TestFrontendShed:
    def test_shed_is_explicit_with_reason_and_hint(self):
        rs = ReplicaSet([_FakeEngine("r0")])
        fe = ServingFrontend(rs, admission=AdmissionController(max_pending=1))
        shed_before = obs.registry().counter(
            "frontend_sheds", reason="queue_full").value
        assert fe.admission.try_admit()[0]   # occupy the only slot
        with pytest.raises(EngineOverloaded) as ei:
            fe.submit(0, [0.0])
        assert ei.value.retry_after_s > 0
        assert obs.registry().counter(
            "frontend_sheds", reason="queue_full").value == shed_before + 1
        fe.admission.release()
        assert fe.submit(0, [0.0]) == "ok:r0"

    def test_replica_queue_overload_counts_at_the_frontend(self):
        def full(eng):
            raise EngineOverloaded("queue full", retry_after_s=0.02)
        rs = ReplicaSet([_FakeEngine("r0", behavior=full)])
        fe = ServingFrontend(rs)
        before = obs.registry().counter(
            "frontend_sheds", reason="replica_queue").value
        with pytest.raises(EngineOverloaded):
            fe.submit(0, [0.0])
        assert obs.registry().counter(
            "frontend_sheds", reason="replica_queue").value == before + 1
        assert fe.admission.pending == 0     # slot released on the way out

    def test_healthz_degrades_and_downs(self):
        fakes = [_FakeEngine("r0"), _FakeEngine("r1")]
        rs = ReplicaSet(fakes)
        fe = ServingFrontend(rs)
        assert fe.healthz()["status"] == "ok"
        rs.drain("r0", reason="manual")
        hc = fe.healthz()
        assert hc["status"] == "degraded"
        assert "replicas_down" in hc["degraded"]
        rs.drain("r1", reason="manual")
        assert fe.healthz()["status"] == "down"


# ----------------------------------------------------------------------
# replica fault injection (wraps step.forward; no JAX needed here)
class TestReplicaFaultInjector:
    def _engine_shell(self, name="rX"):
        calls = []

        def forward(params, x, midx):
            calls.append(1)
            return "logits"

        return types.SimpleNamespace(
            name=name, step=types.SimpleNamespace(forward=forward)), calls

    def test_crash_fires_once_at_the_seeded_batch(self):
        eng, calls = self._engine_shell()
        inj = ReplicaFaultInjector(mode="crash", after_batches=3, seed=0)
        inj.arm(eng)
        assert eng.step.forward(None, None, None) == "logits"
        assert eng.step.forward(None, None, None) == "logits"
        with pytest.raises(RuntimeError, match="injected replica crash"):
            eng.step.forward(None, None, None)
        assert inj.fired and len(calls) == 2     # the crash batch never ran

    def test_slow_delays_every_batch_from_fire_at(self):
        eng, _ = self._engine_shell()
        inj = ReplicaFaultInjector(mode="slow", after_batches=2,
                                   slow_s=0.05, seed=0)
        inj.arm(eng)
        t0 = time.perf_counter()
        eng.step.forward(None, None, None)
        assert time.perf_counter() - t0 < 0.04   # before fire_at: untouched
        t0 = time.perf_counter()
        eng.step.forward(None, None, None)
        eng.step.forward(None, None, None)
        assert time.perf_counter() - t0 >= 0.1   # every batch after: +slow_s

    def test_disarm_restores_and_double_arm_rejected(self):
        eng, _ = self._engine_shell()
        original = eng.step.forward
        inj = ReplicaFaultInjector(mode="crash", after_batches=1, seed=0)
        inj.arm(eng)
        with pytest.raises(RuntimeError, match="already armed"):
            inj.arm(eng)
        inj.disarm()
        assert eng.step.forward is original

    def test_jitter_is_seed_deterministic(self):
        a = ReplicaFaultInjector(mode="crash", after_batches=5, jitter=4,
                                 seed=11)
        b = ReplicaFaultInjector(mode="crash", after_batches=5, jitter=4,
                                 seed=11)
        assert a.fire_at == b.fire_at
        assert 5 <= a.fire_at <= 9


# ----------------------------------------------------------------------
# overload semantics end-to-end: saturating OPEN-loop storm against a
# bounded frontend over a real (deliberately slowed) engine
class TestOverloadSemantics:
    def test_open_loop_storm_sheds_explicitly_and_recovers(self):
        pool = _pool(M=2)
        rs = _replicas(pool, [0, 1] * 4, n=1, max_queue=8)
        # slow every forward: capacity collapses far below the offered
        # rate, so the bounded frontend MUST shed
        inj = ReplicaFaultInjector(mode="slow", after_batches=1,
                                   slow_s=0.02, seed=0)
        inj.arm(rs.engines[0])
        fe = ServingFrontend(rs, admission=AdmissionController(max_pending=4))
        try:
            gen = TrafficGenerator(fe, clients=range(8), seed=3,
                                   concurrency=16)
            stats = gen.run_open(150, rate_rps=300.0, timeout=2.0)
            # every request is accounted for: no deadlock, nothing lost
            assert (stats["completed"] + stats["sheds"] + stats["expired"]
                    + stats["timeouts"] + stats["errors"]) == 150
            assert stats["errors"] == 0
            assert stats["sheds"] > 0, stats
            assert stats["completed"] > 0, stats
            # the admitted requests' tail stays bounded by the admit
            # window x service time, NOT by the storm's queueing
            assert stats["p99_ms"] < 1500.0, stats
            # recovery: the moment the storm passes, admission is open
            res = fe.submit(0, np.zeros(3, np.float32), timeout=10.0)
            assert res.model == 0
        finally:
            fe.close()

    def test_closed_loop_hides_what_open_loop_sees(self):
        # the satellite's reason to exist: a closed loop against the
        # same saturated server simply slows down with it (coordinated
        # omission) and reports ZERO sheds
        pool = _pool(M=2)
        rs = _replicas(pool, [0, 1] * 4, n=1, max_queue=8)
        inj = ReplicaFaultInjector(mode="slow", after_batches=1,
                                   slow_s=0.02, seed=0)
        inj.arm(rs.engines[0])
        fe = ServingFrontend(rs, admission=AdmissionController(max_pending=4))
        try:
            gen = TrafficGenerator(fe, clients=range(8), seed=3,
                                   concurrency=2)
            stats = gen.run(30, timeout=10.0)
            assert stats["errors"] == 0      # nobody shed: workers just wait
            assert stats["requests_per_s"] < 300.0
        finally:
            fe.close()


# ----------------------------------------------------------------------
# crash failover end-to-end over real engines
class TestCrashFailover:
    def test_admitted_requests_survive_a_replica_crash(self):
        pool = _pool(M=2)
        rs = _replicas(pool, [0, 1] * 4, n=2, max_queue=64)
        ReplicaFaultInjector(mode="crash", after_batches=3, seed=1)\
            .arm(rs.engines[0])
        fe = ServingFrontend(rs)
        failures = []
        lock = threading.Lock()

        def pump(w):
            rng = np.random.RandomState(w)
            for _ in range(40):
                try:
                    fe.submit(int(rng.randint(8)),
                              rng.standard_normal(3).astype(np.float32),
                              timeout=10.0)
                except EngineOverloaded:
                    time.sleep(0.005)
                except Exception as e:       # noqa: BLE001 — the assert
                    with lock:
                        failures.append(repr(e))

        threads = [threading.Thread(target=pump, args=(w,)) for w in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        try:
            assert not failures, failures[:5]
            _wait_for(lambda: rs.drained_names().get("r0")
                      == "dispatcher_dead", "r0 to drain")
            assert rs.healthy_names() == ["r1"]
            assert rs.engines[0].failed is not None
            hc = fe.healthz()
            assert hc["status"] == "degraded"
            assert "replicas_down" in hc["degraded"]
        finally:
            fe.close()


# ----------------------------------------------------------------------
# the HTTP plane: taxonomy over the wire, both directions
class TestHttpPlane:
    def test_submit_errors_and_healthz_roundtrip(self):
        pool = _pool(M=2)
        rs = _replicas(pool, [0, 1] * 4, n=1)
        fe = ServingFrontend(rs).start(port=0)
        try:
            cli = FrontendClient(f"http://{fe.host}:{fe.port}", timeout=10.0)
            # geometry read off /status so TrafficGenerator can drive it
            assert cli._example_shape == (3,)
            assert cli.population == 8
            res = cli.submit(3, np.zeros(3, np.float32))
            assert res.model == 1
            assert np.asarray(res.logits).shape[-1] >= 2
            with pytest.raises(UnknownClientError):
                cli.submit(99, np.zeros(3, np.float32))
            with pytest.raises(MalformedRequestError):
                cli.submit(0, [1.0, 2.0])    # wrong example shape
            assert cli.healthz()["status"] == "ok"
            # drain the bucket -> 503 overloaded + retry hint on the wire
            bucket = TokenBucket(0.5, burst=1)
            assert bucket.try_acquire()
            fe.admission.bucket = bucket
            with pytest.raises(EngineOverloaded) as ei:
                cli.submit(0, np.zeros(3, np.float32))
            assert ei.value.retry_after_s > 0
            fe.admission.bucket = None
            # traffic generator drives the socket exactly like an engine
            gen = TrafficGenerator(cli, clients=range(8), seed=5,
                                   concurrency=4)
            stats = gen.run(24, timeout=10.0)
            assert stats["errors"] == 0
        finally:
            fe.close()
