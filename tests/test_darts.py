"""DARTS search-space parity: 8 primitives, two-input cells with reduction,
shared alpha tensors, reference-shaped genotype derivation (reference
darts/genotypes.py:5-14, model_search.py:258-297)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from feddrift_tpu.models.darts import (
    PRIMITIVES, Cell, DARTSNetwork, FactorizedReduce, Genotype, MixedOp,
    derive_genotype, genotype_of, num_edges, split_arch_params)


def test_primitives_match_reference():
    assert list(PRIMITIVES) == [
        "none", "max_pool_3x3", "avg_pool_3x3", "skip_connect",
        "sep_conv_3x3", "sep_conv_5x5", "dil_conv_3x3", "dil_conv_5x5"]


@pytest.mark.slow
@pytest.mark.parametrize("kind", PRIMITIVES)
@pytest.mark.parametrize("stride", [1, 2])
def test_each_primitive_forward(kind, stride):
    from feddrift_tpu.models.darts import _Op
    op = _Op(kind, filters=8, stride=stride)
    x = jnp.ones((2, 8, 8, 8))
    params = op.init(jax.random.PRNGKey(0), x)
    y = op.apply(params, x)
    assert y.shape == (2, 8 // stride, 8 // stride, 8)
    if kind == "none":
        assert np.all(np.asarray(y) == 0)


@pytest.mark.slow
def test_mixed_op_is_weighted_sum():
    op = MixedOp(filters=4, stride=1)
    x = jnp.ones((1, 4, 4, 4))
    w = jnp.zeros((len(PRIMITIVES),)).at[PRIMITIVES.index("none")].set(1.0)
    params = op.init(jax.random.PRNGKey(0), x, w)
    y = op.apply(params, x, w)
    np.testing.assert_allclose(np.asarray(y), 0.0, atol=1e-6)


@pytest.mark.slow
def test_cell_shapes_normal_and_reduce():
    k = num_edges(2)
    w = jnp.full((k, len(PRIMITIVES)), 1.0 / len(PRIMITIVES))
    s0 = jnp.ones((2, 8, 8, 6))
    s1 = jnp.ones((2, 8, 8, 6))
    normal = Cell(filters=4, steps=2, multiplier=2)
    p = normal.init(jax.random.PRNGKey(0), s0, s1, w)
    y = normal.apply(p, s0, s1, w)
    assert y.shape == (2, 8, 8, 8)          # multiplier * filters channels
    red = Cell(filters=4, steps=2, multiplier=2, reduction=True)
    p = red.init(jax.random.PRNGKey(0), s0, s1, w)
    y = red.apply(p, s0, s1, w)
    assert y.shape == (2, 4, 4, 8)          # spatial halved


@pytest.mark.slow
def test_factorized_reduce_halves_spatial():
    fr = FactorizedReduce(filters=6)
    x = jnp.ones((2, 8, 8, 3))
    p = fr.init(jax.random.PRNGKey(0), x)
    assert fr.apply(p, x).shape == (2, 4, 4, 6)


@pytest.mark.slow
def test_network_has_two_shared_alpha_tensors():
    net = DARTSNetwork(num_classes=5, filters=4, cells=3, nodes=2)
    x = jnp.ones((2, 16, 16, 3))
    params = net.init(jax.random.PRNGKey(0), x)["params"]
    k = num_edges(2)
    assert params["arch_alphas_normal"].shape == (k, len(PRIMITIVES))
    assert params["arch_alphas_reduce"].shape == (k, len(PRIMITIVES))
    out = net.apply({"params": params}, x)
    assert out.shape == (2, 5)
    wmask, amask = split_arch_params(params)
    n_arch = sum(jax.tree_util.tree_leaves(amask))
    assert n_arch == 2                       # exactly the two shared tensors


def test_genotype_derivation_golden():
    """Alphas engineered so the expected genotype is known: node 0 prefers
    sep_conv_3x3 on both input edges; node 1's best two edges are 0 and 2
    with max_pool_3x3 / dil_conv_5x5.  'none' never wins even when its raw
    weight is highest (reference excludes it, model_search.py:272-283)."""
    steps = 2
    k = num_edges(steps)                     # 5 edges: [0,1 | 2,3,4]
    a = np.full((k, len(PRIMITIVES)), -5.0)
    sep3 = PRIMITIVES.index("sep_conv_3x3")
    mp = PRIMITIVES.index("max_pool_3x3")
    dil5 = PRIMITIVES.index("dil_conv_5x5")
    none = PRIMITIVES.index("none")
    a[0, sep3] = 3.0
    a[1, sep3] = 2.0
    a[2, mp] = 4.0          # node 1, edge from state 0
    a[2, none] = 4.5        # none outweighs mp but must be ignored as an op
    a[4, dil5] = 3.5        # node 1, edge from state 2
    g = derive_genotype(jnp.asarray(a), jnp.asarray(a), steps)
    assert isinstance(g, Genotype)
    assert g.normal[0] == ("sep_conv_3x3", 0)
    assert g.normal[1] == ("sep_conv_3x3", 1)
    assert set(g.normal[2:]) == {("max_pool_3x3", 0), ("dil_conv_5x5", 2)}
    assert g.normal_concat == [2, 3]
    assert g.reduce == g.normal


@pytest.mark.slow
def test_genotype_of_infers_steps():
    net = DARTSNetwork(num_classes=3, filters=4, cells=1, nodes=2)
    x = jnp.ones((1, 8, 8, 3))
    params = net.init(jax.random.PRNGKey(0), x)["params"]
    g = genotype_of(params)
    assert len(g.normal) == 2 * 2            # top-2 edges per node
    for op, j in g.normal:
        assert op in PRIMITIVES and op != "none"
