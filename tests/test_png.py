"""Pure-Python PNG decoder (feddrift_tpu/data/png.py), cross-validated
against PIL (available in this image, used here as an independent oracle
only — the product path has no image-library dependency).

Reference format being matched: the torchvision ImageFolder tree of CINIC-10
PNGs (fedml_api/data_preprocessing/cinic10/data_loader.py)."""

import io
import struct
import zlib

import numpy as np
import pytest

from feddrift_tpu.data.png import decode_png, decode_png_rgb

PIL = pytest.importorskip("PIL.Image")


def _pil_bytes(arr: np.ndarray, mode: str) -> bytes:
    buf = io.BytesIO()
    PIL.fromarray(arr, mode=mode).save(buf, format="PNG")
    return buf.getvalue()


def _chunk(ctype: bytes, payload: bytes) -> bytes:
    return (struct.pack(">I", len(payload)) + ctype + payload
            + struct.pack(">I", zlib.crc32(ctype + payload)))


def _raw_png(height, width, color_type, scanlines: bytes,
             palette: bytes | None = None) -> bytes:
    """Hand-assemble a PNG with explicit per-row filter bytes, so every
    filter type is exercised regardless of what an encoder would choose."""
    ihdr = struct.pack(">IIBBBBB", width, height, 8, color_type, 0, 0, 0)
    out = b"\x89PNG\r\n\x1a\n" + _chunk(b"IHDR", ihdr)
    if palette is not None:
        out += _chunk(b"PLTE", palette)
    out += _chunk(b"IDAT", zlib.compress(scanlines)) + _chunk(b"IEND", b"")
    return out


class TestAgainstPIL:
    @pytest.mark.parametrize("mode,shape", [
        ("RGB", (32, 32, 3)), ("RGBA", (32, 32, 4)), ("L", (32, 32)),
        ("RGB", (7, 5, 3)),                       # non-square, odd stride
    ])
    def test_roundtrip_matches_source(self, mode, shape):
        rng = np.random.default_rng(hash(mode) % 1000 + shape[0])
        arr = rng.integers(0, 256, shape).astype(np.uint8)
        decoded = decode_png(_pil_bytes(arr, mode))
        np.testing.assert_array_equal(decoded, arr)

    def test_gradient_image_exercises_filter_heuristics(self):
        # smooth gradients push PIL's adaptive filter chooser toward
        # Sub/Up/Average/Paeth rather than None
        g = np.arange(64 * 64 * 3, dtype=np.int64).reshape(64, 64, 3)
        arr = (g % 251).astype(np.uint8)
        np.testing.assert_array_equal(decode_png(_pil_bytes(arr, "RGB")), arr)

    def test_rgb_normalization_helper(self):
        rng = np.random.default_rng(3)
        gray = rng.integers(0, 256, (8, 8)).astype(np.uint8)
        out = decode_png_rgb(_pil_bytes(gray, "L"))
        assert out.shape == (8, 8, 3)
        np.testing.assert_array_equal(out[..., 0], gray)
        rgba = rng.integers(0, 256, (8, 8, 4)).astype(np.uint8)
        np.testing.assert_array_equal(decode_png_rgb(_pil_bytes(rgba, "RGBA")),
                                      rgba[..., :3])


class TestExplicitFilters:
    """Each PNG filter type decoded from hand-filtered scanlines; PIL
    re-decodes the same bytes as the oracle."""

    @pytest.mark.parametrize("ftype", [0, 1, 2, 3, 4])
    def test_filter_type(self, ftype):
        rng = np.random.default_rng(40 + ftype)
        h, w, bpp = 6, 4, 3
        img = rng.integers(0, 256, (h, w * bpp)).astype(np.int64)
        rows = []
        prev = np.zeros(w * bpp, np.int64)
        for r in range(h):
            cur, line = img[r], np.zeros(w * bpp, np.int64)
            for i in range(w * bpp):
                a = cur[i - bpp] if i >= bpp else 0
                b, c = prev[i], (prev[i - bpp] if i >= bpp else 0)
                if ftype == 0:
                    pred = 0
                elif ftype == 1:
                    pred = a
                elif ftype == 2:
                    pred = b
                elif ftype == 3:
                    pred = (a + b) // 2
                else:
                    p = a + b - c
                    pa, pb, pc = abs(p - a), abs(p - b), abs(p - c)
                    pred = a if pa <= pb and pa <= pc else (b if pb <= pc else c)
                line[i] = (cur[i] - pred) % 256
            rows.append(bytes([ftype]) + bytes(line.astype(np.uint8)))
            prev = cur
        data = _raw_png(h, w, 2, b"".join(rows))
        expect = img.reshape(h, w, bpp).astype(np.uint8)
        np.testing.assert_array_equal(decode_png(data), expect)
        np.testing.assert_array_equal(                      # PIL agrees
            np.asarray(PIL.open(io.BytesIO(data)).convert("RGB")), expect)

    def test_palette(self):
        pal = np.array([[255, 0, 0], [0, 255, 0], [0, 0, 255], [7, 8, 9]],
                       np.uint8)
        idx = np.array([[0, 1], [2, 3]], np.uint8)
        rows = b"".join(bytes([0]) + bytes(r) for r in idx)
        data = _raw_png(2, 2, 3, rows, palette=pal.tobytes())
        np.testing.assert_array_equal(decode_png(data), pal[idx])
        np.testing.assert_array_equal(
            np.asarray(PIL.open(io.BytesIO(data)).convert("RGB")), pal[idx])


class TestRejections:
    def test_not_png(self):
        with pytest.raises(ValueError, match="not a PNG"):
            decode_png(b"JFIF not a png")

    def test_truncated_pixels(self):
        good = _pil_bytes(np.zeros((4, 4, 3), np.uint8), "RGB")
        # rebuild with an IDAT holding too few scanline bytes
        ihdr = struct.pack(">IIBBBBB", 4, 4, 8, 2, 0, 0, 0)
        bad = (b"\x89PNG\r\n\x1a\n" + _chunk(b"IHDR", ihdr)
               + _chunk(b"IDAT", zlib.compress(b"\x00" * 10))
               + _chunk(b"IEND", b""))
        assert decode_png(good) is not None
        with pytest.raises(ValueError, match="size mismatch"):
            decode_png(bad)

    def test_16bit_rejected(self):
        # hand-assembled 16-bit header (PIL's 16-bit save path is deprecated)
        ihdr = struct.pack(">IIBBBBB", 4, 4, 16, 0, 0, 0, 0)
        data = (b"\x89PNG\r\n\x1a\n" + _chunk(b"IHDR", ihdr)
                + _chunk(b"IDAT", zlib.compress(b"\x00" * (4 * 9)))
                + _chunk(b"IEND", b""))
        with pytest.raises(ValueError, match="bit depth"):
            decode_png(data)
