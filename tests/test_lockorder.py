"""Runtime lock-order recorder tests, including the PR 9 fixture: an
AlertMonitor-shaped tap that emits under its own non-reentrant Lock. The
real EventBus swallows tap exceptions (a failing tap must never take the
run down), so the detector's evidence is the recorder state — the
violation list and the self-edge that makes the acquisition graph cyclic
— not a propagated exception."""

import threading

import pytest

from feddrift_tpu.analysis.lockorder import (
    LockOrderRecorder,
    LockOrderViolation,
)
from feddrift_tpu.obs.events import EventBus


@pytest.fixture()
def rec():
    r = LockOrderRecorder()
    r.install()
    try:
        yield r
    finally:
        r.uninstall()


def test_repo_created_locks_are_instrumented(rec):
    lk = threading.Lock()
    assert rec.locks_created == 1
    with lk:
        pass
    assert rec.violations == []
    assert rec.find_cycle() is None
    rec.check()     # acyclic: no-op


def test_consistent_order_is_acyclic(rec):
    a, b = threading.Lock(), threading.Lock()
    for _ in range(3):
        with a:
            with b:
                pass
    assert len(rec.edges) == 1
    assert rec.find_cycle() is None
    rec.check()


def test_order_inversion_is_a_cycle(rec):
    a, b = threading.Lock(), threading.Lock()
    with a:
        with b:
            pass
    with b:
        with a:
            pass
    cyc = rec.find_cycle()
    assert cyc is not None and cyc[0] == cyc[-1]
    with pytest.raises(LockOrderViolation, match="cycle"):
        rec.check()


def test_self_reacquire_raises_instead_of_hanging(rec):
    lk = threading.Lock()
    with lk:
        with pytest.raises(LockOrderViolation, match="self-deadlock"):
            lk.acquire()
    assert rec.violations


def test_rlock_reentry_is_fine(rec):
    lk = threading.RLock()
    with lk:
        with lk:
            pass
    assert rec.violations == []
    assert rec.find_cycle() is None


class BadMonitor:
    """The PR 9 re-entrancy class, verbatim in shape: a bus tap that holds
    its own non-reentrant Lock while emitting. Taps run synchronously on
    the emitting thread, so the nested emit re-enters observe() and
    re-acquires the held lock."""

    def __init__(self, bus):
        self._lock = threading.Lock()   # the bug: Lock, not RLock
        self.bus = bus
        self.seen = 0

    def attach(self):
        self.bus.add_tap(self.observe)

    def observe(self, recd):
        with self._lock:
            self.seen += 1
            if self.seen == 1:
                # re-entrant emit while holding _lock — PR 9's deadlock
                self.bus.emit("alert_raised", source="bad_monitor")


def test_pr9_fixture_detected(rec, tmp_path):
    bus = EventBus(path=str(tmp_path / "events.jsonl"))
    mon = BadMonitor(bus)
    mon.attach()
    # Without the recorder this call would hang forever. With it, the
    # instrumented lock raises inside the tap; the bus swallows the
    # exception (taps must never kill the run), and the evidence lands in
    # the recorder.
    bus.emit("alert_raised", source="test")
    assert any("self-deadlock" in v for v in rec.violations), rec.violations
    cyc = rec.find_cycle()
    assert cyc is not None and cyc[0] == cyc[-1]
    with pytest.raises(LockOrderViolation, match="self-deadlock"):
        rec.check()
    bus.close()


def test_pr9_fix_rlock_monitor_is_clean(rec, tmp_path):
    bus = EventBus(path=str(tmp_path / "events.jsonl"))
    mon = BadMonitor(bus)
    mon._lock = threading.RLock()       # the PR 9 fix
    mon.attach()
    bus.emit("alert_raised", source="test")
    assert mon.seen == 2                # re-entered, completed both times
    assert rec.violations == []
    rec.check()
    bus.close()


def test_cross_thread_inversion_detected(rec):
    """Two threads taking two locks in opposite orders never deadlock in
    this run (barrier-free, sequential), but the graph records the latent
    hazard."""
    a, b = threading.Lock(), threading.Lock()

    def forward():
        with a:
            with b:
                pass

    def backward():
        with b:
            with a:
                pass

    t1 = threading.Thread(target=forward)
    t1.start()
    t1.join()
    t2 = threading.Thread(target=backward)
    t2.start()
    t2.join()
    assert rec.find_cycle() is not None


def test_uninstall_restores_factories():
    orig_lock, orig_rlock = threading.Lock, threading.RLock
    r = LockOrderRecorder()
    r.install()
    assert threading.Lock is not orig_lock
    r.uninstall()
    assert threading.Lock is orig_lock
    assert threading.RLock is orig_rlock


def test_summary_renders(rec):
    with threading.Lock():
        pass
    s = rec.summary()
    assert "locks instrumented" in s and "violations" in s
