"""SoftCluster family tests: FedDrift, Eager, IFCA, softmax, geni, CFL utils.

Golden/trajectory tests in the spirit of SURVEY.md §4: deterministic seeds,
assert clustering decisions and accuracy recovery after drift.
"""

import numpy as np
import pytest

from feddrift_tpu.config import ExperimentConfig
from feddrift_tpu.simulation.runner import Experiment, run_experiment

pytestmark = pytest.mark.slow   # heavy compiles: full-tier only


def _cfg(**kw):
    base = dict(dataset="sine", model="fnn", concept_num=4,
                concept_drift_algo="softcluster",
                concept_drift_algo_arg="H_A_C_1_10_0",
                train_iterations=4, comm_round=6, epochs=3, sample_num=50,
                batch_size=25, frequency_of_the_test=3, lr=0.05,
                client_num_in_total=10, client_num_per_round=10,
                report_client=0, seed=0)
    base.update(kw)
    return ExperimentConfig(**base)


class TestFedDrift:
    def test_recovers_after_drift(self):
        exp = run_experiment(_cfg())
        accs = [v for _, v in exp.logger.series("Test/Acc")]
        # pre-drift learning works
        assert accs[2] > 0.8
        # final iteration: drifted clients are served by a second model,
        # so accuracy recovers well above the oblivious-baseline ~0.5-0.7
        assert accs[-1] > 0.8, accs

    def test_spawns_second_model(self):
        exp = run_experiment(_cfg())
        assert exp.logger.summary.get("num_models", 0) >= 2
        # drifted clients moved off model 0 (preset A: client 1 drifts early)
        idx = exp.algo.test_model_idx(3)
        assert len(set(idx.tolist())) >= 2

    def test_weights_are_unit_partition(self):
        exp = run_experiment(_cfg())
        w = exp.algo.weights
        for t in range(4):
            col = w[t].sum(axis=0)
            assert np.allclose(col, 1.0), (t, col)

    def test_event_counters_track_drift_machinery(self):
        # The scaling bench's event ledger (SCALING_r05) relies on these.
        # Invariant assertions, NOT golden counts: the exact
        # {spawns, merges, linkage_calls} triple is coupled to the default
        # seed/config and environment-dependent float details, so equality
        # here flaked across environments. What the ledger actually needs
        # is that counters track the observable pool state and behave like
        # counters (non-negative, consistent with each other).
        exp = run_experiment(_cfg())
        ev = exp.algo.event_counts
        assert set(ev) == {"spawns", "merges", "linkage_calls"}, ev
        assert all(v >= 0 for v in ev.values()), ev
        # this drift preset must provoke at least one spawn, and linkage is
        # only evaluated once a second model exists
        assert ev["spawns"] >= 1, ev
        assert ev["linkage_calls"] >= 1, ev
        # every model beyond the initial one came from a counted spawn, and
        # merges can never exceed the spawns that created their operands
        assert exp.logger.summary["num_models"] <= 1 + ev["spawns"]
        assert ev["merges"] <= ev["spawns"]

    def test_feddrift_f_requires_enough_models(self):
        with pytest.raises(ValueError):
            run_experiment(_cfg(concept_drift_algo_arg="H_A_F_1_10_0"))

    def test_feddrift_f_one_model_per_client(self):
        exp = run_experiment(_cfg(concept_drift_algo_arg="H_A_F_1_10_0",
                                  concept_num=12, train_iterations=2))
        # starts one-model-per-client, then merging collapses same-concept
        # models: strictly fewer models than clients by the end
        assert exp.logger.summary["num_models"] < 10


class TestEager:
    def test_mmacc_runs_and_recovers(self):
        exp = run_experiment(_cfg(concept_drift_algo_arg="mmacc_06"))
        accs = [v for _, v in exp.logger.series("Test/Acc")]
        assert accs[-1] > 0.75, accs
        assert exp.logger.summary.get("num_models", 0) >= 2


class TestIFCA:
    def test_hard_assigns_best_model(self):
        exp = run_experiment(_cfg(concept_drift_algo="softclusterwin-1",
                                  concept_drift_algo_arg="hard"))
        w = exp.algo.weights
        # hard assignment: one-hot columns
        assert set(np.unique(w)) <= {0.0, 1.0}
        # win-1: all weights before the final iteration are zeroed
        assert w[:3].sum() == 0

    def test_hard_r_reclusters_every_round(self):
        exp = run_experiment(_cfg(concept_drift_algo="softclusterwin-1",
                                  concept_drift_algo_arg="hard-r",
                                  train_iterations=2))
        assert exp.logger.last("Test/Acc") > 0.5


class TestSoftVariants:
    def test_softmax_fractional_weights(self):
        exp = run_experiment(_cfg(concept_drift_algo_arg="softmax_0",
                                  train_iterations=2))
        w = exp.algo.weights[1]
        assert np.allclose(w.sum(axis=0), 1.0)
        assert (w > 0).all()          # softmax never exactly zero

    def test_geni_oracle_follows_changepoints(self):
        exp = run_experiment(_cfg(concept_drift_algo_arg="geni",
                                  dataset="sea", train_iterations=3))
        from feddrift_tpu.data.changepoints import load_change_points
        cp = load_change_points("A")
        idx = exp.algo.test_model_idx(2)
        assert np.array_equal(idx, cp[2, :10] % 4)


class TestHostLogic:
    def _algo(self):
        exp = Experiment(_cfg())
        return exp, exp.algo

    def test_merge_math(self):
        exp, algo = self._algo()
        import jax
        # slot 0 := 1.0, slot 1 := 3.0; weights: model0 3 cells, model1 1 cell
        algo.pool.set_slot(0, jax.tree_util.tree_map(
            lambda p: p * 0 + 1.0, algo.pool.slot(0)))
        algo.pool.set_slot(1, jax.tree_util.tree_map(
            lambda p: p * 0 + 3.0, algo.pool.slot(1)))
        algo.weights[0, 0, :3] = 1.0
        algo.weights[0, 1, 3] = 1.0
        algo._merge(0, base=0, second=1)
        merged = jax.tree_util.tree_leaves(algo.pool.slot(0))[0]
        assert np.allclose(np.asarray(merged), 1.0 * 0.75 + 3.0 * 0.25)
        assert algo.weights[0, 0, 3] == 1.0 and algo.weights[0, 1].sum() == 0

    def test_lru_allocation_caps(self):
        exp, algo = self._algo()
        # fill the pool
        assert algo._find_unused_model_lru(0, 0) == 1
        assert algo._find_unused_model_lru(0, 0) == 2
        assert algo._find_unused_model_lru(0, 0) == 3
        # all models used at current step -> give up (-1)
        algo.weights[0] = 1.0
        assert algo._find_unused_model_lru(0, 0) == -1
        # a model unused at current step gets recycled
        algo.weights[:, 2, :] = 0.0
        algo.weights[0, 2, :] = 0.0
        got = algo._find_unused_model_lru(1, 0)
        assert got == 2
        assert algo.weights[:, 2, :].sum() == 0

    def test_bipartition_blocks(self):
        from feddrift_tpu.algorithms.softcluster import SoftCluster
        S = np.full((6, 6), -0.9)
        S[:3, :3] = 0.9
        S[3:, 3:] = 0.9
        np.fill_diagonal(S, 1.0)
        cl1, cl2 = SoftCluster._bipartition(S)
        groups = {tuple(sorted(cl1)), tuple(sorted(cl2))}
        assert groups == {(0, 1, 2), (3, 4, 5)}

    def test_state_roundtrip(self):
        exp, algo = self._algo()
        exp.run_iteration(0)
        d = algo.state_dict()
        exp2 = Experiment(_cfg())
        exp2.algo.load_state_dict(d)
        assert np.array_equal(exp2.algo.weights, algo.weights)
        assert exp2.algo.h_next_free == algo.h_next_free


class TestSoftClusterCFL:
    """The cfl_{gamma}_{rt} variant: gradient-norm gated bipartition inside
    the round loop (cluster_cfl, FedAvgEnsDataLoader.py:1159-1223)."""

    def test_cfl_e2e_runs_and_partitions(self):
        exp = run_experiment(_cfg(concept_drift_algo_arg="cfl_0.1_win-1",
                                  train_iterations=2, comm_round=3))
        accs = [v for _, v in exp.logger.series("Test/Acc")]
        assert accs and np.isfinite(accs).all()
        # win-1 retrain zeroes past steps; the CURRENT step must partition
        np.testing.assert_allclose(exp.algo.weights[1].sum(axis=0), 1.0,
                                   atol=1e-5)

    def test_cfl_round_splits_on_crafted_updates(self):
        """Direct exercise of _cluster_cfl_round: two client blocks pushing
        in opposite directions with a tiny mean update must bipartition once
        the norm gate opens."""
        import jax
        import jax.numpy as jnp
        exp = Experiment(_cfg(concept_drift_algo_arg="cfl_0.05_win-1",
                              train_iterations=2, comm_round=3,
                              client_num_in_total=8, client_num_per_round=8))
        algo = exp.algo
        algo.begin_iteration(0)
        prev = exp.pool.params
        C_pad = exp.C_pad

        def crafted_with_signs(signs):
            def crafted(leaf):
                u = jnp.ones_like(leaf[0])
                sb = jnp.asarray(signs).reshape(
                    (-1,) + (1,) * leaf[0].ndim)
                return leaf[:, None, ...] + sb[None] * u[None, None] * 10.0
            return jax.tree_util.tree_map(crafted, prev)
        n = jnp.ones((algo.M, C_pad), jnp.float32) * 50.0

        # round 1: coherent updates (all +u) arm the norm gate: cfl_norm
        # jumps, eps1 = norm/10, eps2 = 0.6*norm
        algo._cluster_cfl_round(0, 1, prev,
                                crafted_with_signs([1.0] * C_pad), n)
        assert algo.cfl_norm > 0
        # round 2: opposite halves -> mean ~0 < eps1, per-client max > eps2
        did = algo._cluster_cfl_round(
            0, 2, prev,
            crafted_with_signs([1.0] * 4 + [-1.0] * (C_pad - 4)), n)
        assert did, (algo.cfl_norm, algo.cfl_eps1, algo.cfl_eps2)
        w = algo.weights[0]
        assert set(np.argmax(w, axis=0)[:8].tolist()) == {0, 1}
        np.testing.assert_allclose(w.sum(axis=0), 1.0, atol=1e-5)
