"""Resilience layer: retry/chaos/reconnect transport hardening, preemption
+ auto-resume, checkpoint corruption fallback, divergence guard.

Every blocking operation in this module carries an explicit timeout
(queue gets, thread joins, wall-clock deadlines) — the socket-level tests
must not be able to wedge the fast tier even if a reconnect loop hangs;
all background threads are daemons.
"""

import json
import os
import queue
import signal
import threading
import time

import numpy as np
import pytest

from feddrift_tpu import obs
from feddrift_tpu.comm.netbroker import NetworkBroker, NetworkBrokerClient
from feddrift_tpu.comm.pubsub import Broker, PubSubCommManager
from feddrift_tpu.resilience import (ChaosBroker, ChaosPolicy,
                                     DivergenceError, DivergenceGuard,
                                     PreemptionHandler,
                                     ReconnectingBrokerClient, RetryPolicy)

E2E_DEADLINE = 60.0          # hard cap for any socket-level scenario


@pytest.fixture()
def bus():
    """Fresh memory-only event bus per test (socket threads emit into it)."""
    b = obs.configure(None)
    yield b
    obs.configure(None)


def _drain_until(q, want: int, deadline: float) -> list:
    got = []
    end = time.monotonic() + deadline
    while len(got) < want and time.monotonic() < end:
        try:
            got.append(q.get(timeout=0.25))
        except queue.Empty:
            pass
    return got


class TestRetryPolicy:
    def test_seeded_schedule_is_deterministic(self):
        a = [RetryPolicy(seed=7).delay(k) for k in range(6)]
        b = [RetryPolicy(seed=7).delay(k) for k in range(6)]
        assert a == b
        assert a != [RetryPolicy(seed=8).delay(k) for k in range(6)]

    def test_backoff_grows_and_caps(self):
        p = RetryPolicy(base_delay=0.1, max_delay=0.4, multiplier=2.0,
                        jitter=0.0, max_attempts=6, seed=0)
        assert list(p.delays()) == [0.1, 0.2, 0.4, 0.4, 0.4, 0.4]

    def test_jitter_bounds(self):
        p = RetryPolicy(base_delay=1.0, max_delay=1.0, jitter=0.5,
                        max_attempts=50, deadline_s=None, seed=1)
        ds = [p.delay(k) for k in range(50)]
        assert all(0.5 <= d <= 1.5 for d in ds)
        assert len(set(ds)) > 1

    def test_run_retries_then_succeeds(self):
        calls = {"n": 0}

        def flaky():
            calls["n"] += 1
            if calls["n"] < 3:
                raise OSError("boom")
            return "ok"

        p = RetryPolicy(base_delay=0.001, max_attempts=5, seed=0)
        assert p.run(flaky) == "ok"
        assert calls["n"] == 3

    def test_run_exhausts_and_raises(self):
        p = RetryPolicy(base_delay=0.001, max_attempts=2, seed=0)
        with pytest.raises(OSError):
            p.run(lambda: (_ for _ in ()).throw(OSError("always")))

    def test_deadline_stops_schedule(self):
        p = RetryPolicy(base_delay=0.05, max_delay=0.05, jitter=0.0,
                        max_attempts=1000, deadline_s=0.12, seed=0)
        t0 = time.monotonic()
        n = sum(1 for d in p.delays() if time.sleep(d) or True)
        assert time.monotonic() - t0 < 5.0
        assert n <= 4          # ~0.12s budget at 0.05s steps (+1 grace)

    def test_validation(self):
        with pytest.raises(ValueError):
            RetryPolicy(jitter=2.0)
        with pytest.raises(ValueError):
            RetryPolicy(max_attempts=0)


class TestChaos:
    def test_seeded_decisions_reproducible(self, bus):
        a = ChaosPolicy(seed=3, drop_prob=0.3, dup_prob=0.2)
        b = ChaosPolicy(seed=3, drop_prob=0.3, dup_prob=0.2)
        assert [a.draw("t") for _ in range(64)] == \
               [b.draw("t") for _ in range(64)]

    def test_drop_dup_over_inprocess_broker(self, bus):
        inner = Broker()
        chaos = ChaosBroker(inner, seed=5, drop_prob=0.4, dup_prob=0.2)
        q = chaos.subscribe("t")
        n = 50
        for i in range(n):
            chaos.publish("t", f"m{i}")
        got = _drain_until(q, n, deadline=2.0)
        c = chaos.policy.counts
        assert c["drop"] > 0 and c["dup"] > 0
        # conservation: delivered = sent - dropped + duplicated
        assert len(got) == n - c["drop"] + c["dup"]
        assert any(e["kind"] == "chaos_injected" for e in bus.events())

    def test_delay_still_delivers(self, bus):
        chaos = ChaosBroker(Broker(), seed=0, delay_prob=1.0, delay_s=0.05)
        q = chaos.subscribe("t")
        chaos.publish("t", "late")
        with pytest.raises(queue.Empty):
            q.get(timeout=0.01)            # not synchronous
        assert q.get(timeout=2.0) == "late"

    def test_partition_blackholes_until_heal(self, bus):
        chaos = ChaosBroker(Broker(), seed=0)
        q = chaos.subscribe("t")
        chaos.policy.partition(["t"])
        chaos.publish("t", "lost")
        with pytest.raises(queue.Empty):
            q.get(timeout=0.1)
        chaos.policy.heal()
        chaos.publish("t", "through")
        assert q.get(timeout=2.0) == "through"

    def test_validation(self):
        with pytest.raises(ValueError):
            ChaosPolicy(drop_prob=1.5)


class TestPublishAcks:
    def test_acked_publish_clears_pending(self, bus):
        broker = NetworkBroker()
        try:
            c = NetworkBrokerClient(broker.host, broker.port)
            q = c.subscribe("t")
            seq = c.publish("t", "x")
            assert q.get(timeout=5) == "x"
            end = time.monotonic() + 5
            while seq in c.unacked() and time.monotonic() < end:
                time.sleep(0.01)
            assert seq not in c.unacked()
            c.close()
        finally:
            broker.close()

    def test_dropped_publish_stays_pending_and_resends(self, bus):
        chaos = ChaosPolicy(seed=0, drop_prob=1.0)
        broker = NetworkBroker(chaos=chaos)
        try:
            c = NetworkBrokerClient(broker.host, broker.port)
            seq = c.publish("t", "x")
            time.sleep(0.2)
            assert seq in c.unacked()      # no ack for a dropped message
            chaos.drop_prob = 0.0          # heal the wire
            assert c.resend(seq) is True
            end = time.monotonic() + 5
            while seq in c.unacked() and time.monotonic() < end:
                time.sleep(0.01)
            assert seq not in c.unacked()
            c.close()
        finally:
            broker.close()


def _reconnecting(broker_host, broker_port, **kw):
    kw.setdefault("retry", RetryPolicy(base_delay=0.05, max_delay=0.2,
                                       max_attempts=60, deadline_s=30,
                                       seed=0))
    kw.setdefault("ack_timeout", 0.2)
    return ReconnectingBrokerClient(
        lambda: NetworkBrokerClient(broker_host, broker_port), **kw)


class TestReconnectingClient:
    def test_survives_broker_kill_and_restart(self, bus):
        broker = NetworkBroker()
        host, port = broker.host, broker.port
        cli = _reconnecting(host, port)
        broker2 = None
        try:
            q = cli.subscribe("t")
            cli.publish("t", "before")
            assert _drain_until(q, 1, 5.0) == ["before"]
            broker.close()                       # broker dies
            time.sleep(0.2)
            cli.publish("t", "while-down")       # buffered, not raised
            broker2 = NetworkBroker(host=host, port=port)   # same address
            got = set()                          # at-least-once: "before"
            end = time.monotonic() + E2E_DEADLINE  # may be redelivered too
            while "while-down" not in got and time.monotonic() < end:
                try:
                    got.add(q.get(timeout=0.25))
                except queue.Empty:
                    pass
            assert "while-down" in got           # replayed after reconnect
            assert cli.reconnects >= 1
            kinds = [e["kind"] for e in bus.events()]
            assert "conn_reconnect" in kinds
            assert "publish_retry" in kinds
        finally:
            cli.close()
            broker.close()
            if broker2 is not None:
                broker2.close()

    def test_crash_replay_preserves_publish_order(self, bus):
        # the redeliver window replays an acked-then-crashed publish; it
        # must land BEFORE anything published later (while the broker was
        # down), or an order-sensitive consumer (serving cluster events)
        # ends on the stale state. Force the race deterministically: wait
        # for the ack reap to move "old" into the recent-replay buffer,
        # THEN kill the broker with "new" still unconfirmed.
        broker = NetworkBroker()
        host, port = broker.host, broker.port
        cli = _reconnecting(host, port)
        broker2 = None
        try:
            q = cli.subscribe("t")
            cli.publish("t", "old")
            assert _drain_until(q, 1, 5.0) == ["old"]
            end = time.monotonic() + 5
            while cli.pending_count and time.monotonic() < end:
                time.sleep(0.02)         # ack reaped -> "old" now in _recent
            assert cli.pending_count == 0
            broker.close()
            time.sleep(0.2)
            cli.publish("t", "new")      # unconfirmed, queued for replay
            broker2 = NetworkBroker(host=host, port=port)
            got = []
            end = time.monotonic() + E2E_DEADLINE
            while "new" not in got and time.monotonic() < end:
                try:
                    got.append(q.get(timeout=0.25))
                except queue.Empty:
                    pass
            # drain the tail: nothing may arrive AFTER the newest publish
            while True:
                try:
                    got.append(q.get(timeout=0.5))
                except queue.Empty:
                    break
            assert "new" in got
            assert cli.reconnects >= 1
            # at-least-once allows duplicates of "old", but every one of
            # them must precede the final "new"
            assert got.index("new") > max(
                i for i, p in enumerate(got) if p == "old")
            assert got[-1] == "new"
        finally:
            cli.close()
            broker.close()
            if broker2 is not None:
                broker2.close()

    def test_publish_never_raises_on_dead_broker(self, bus):
        broker = NetworkBroker()
        cli = _reconnecting(broker.host, broker.port,
                            retry=RetryPolicy(base_delay=0.01, max_delay=0.02,
                                              max_attempts=3, deadline_s=1,
                                              seed=0))
        broker.close()
        time.sleep(0.3)
        cli.publish("t", "x")                    # bare client raises OSError
        end = time.monotonic() + 10
        while not cli.is_dead and time.monotonic() < end:
            time.sleep(0.05)
        assert cli.is_dead                       # schedule exhausted, no spin
        cli.close()

    def test_heartbeat_missed_forces_reconnect(self, bus):
        # partition ONLY the heartbeat loopback: the TCP session stays up
        # (the half-open-link case), so liveness must come from the beat
        chaos = ChaosPolicy(seed=0)
        broker = NetworkBroker(chaos=chaos)
        cli = _reconnecting(broker.host, broker.port,
                            heartbeat_interval=0.1, heartbeat_timeout=0.4,
                            client_id="hb")
        try:
            chaos.partition(["__hb__/hb"])
            end = time.monotonic() + E2E_DEADLINE
            while time.monotonic() < end:
                if any(e["kind"] == "heartbeat_missed"
                       for e in bus.events()):
                    break
                time.sleep(0.05)
            assert any(e["kind"] == "heartbeat_missed"
                       for e in bus.events())
            chaos.heal()
            end = time.monotonic() + E2E_DEADLINE
            while cli.reconnects < 1 and time.monotonic() < end:
                time.sleep(0.05)
            assert cli.reconnects >= 1
        finally:
            cli.close()
            broker.close()


# ----------------------------------------------------------------------
# the chaos e2e of the acceptance criteria: a full FedAvg manager exchange
# over a real TCP broker with 20% message drop AND a broker kill/restart
# mid-run; the run completes and events.jsonl shows the healing.
from tests.test_comm import _FedAvgClient, _FedAvgServer  # noqa: E402


class TestChaosEndToEnd:
    def test_fedavg_completes_under_chaos_and_broker_restart(self, tmp_path):
        events_path = str(tmp_path / "events.jsonl")
        bus = obs.configure(events_path)
        chaos = ChaosPolicy(seed=11, drop_prob=0.2)
        broker = NetworkBroker(chaos=chaos)
        host, port = broker.host, broker.port
        C, rounds = 2, 12
        clients_cli = [_reconnecting(host, port) for _ in range(C + 1)]
        server = _FedAvgServer(0, C + 1,
                               PubSubCommManager(clients_cli[0], 0),
                               rounds, init_params=0.0)
        clients = [_FedAvgClient(c, C + 1,
                                 PubSubCommManager(clients_cli[c], c),
                                 delta=float(c)) for c in range(1, C + 1)]
        threads = [threading.Thread(target=m.run, daemon=True)
                   for m in [server, *clients]]
        broker2 = None
        try:
            for th in threads:
                th.start()
            # SUBACK-analog barrier (tests/test_netbroker._sync): publishes
            # route only to ALREADY-processed subscriptions (and are acked
            # even when routed to nobody), so the init message must not
            # race the clients' sub frames. The wrapper retries the sync
            # publish itself if chaos drops it.
            for i, cli in enumerate(clients_cli):
                sq = cli.subscribe(f"__sync__/{i}")
                cli.publish(f"__sync__/{i}", "ready")
                assert _drain_until(sq, 1, 30.0), f"client {i} never synced"
                cli.unsubscribe(f"__sync__/{i}", sq)
            server.send_init_msg()
            end = time.monotonic() + E2E_DEADLINE
            while server.round_idx < 3 and time.monotonic() < end:
                time.sleep(0.02)             # let a few rounds run first
            assert server.round_idx >= 3, "no progress before the kill"
            broker.close()                   # kill the broker mid-run...
            time.sleep(0.3)
            broker2 = NetworkBroker(host=host, port=port, chaos=chaos)
            # ...and the run still completes. One wrinkle: the broker acks
            # a publish after ROUTING — even to zero subscribers — so a
            # round message replayed by one session's reconnect BEFORE the
            # other side's subscription replay lands on broker2 is
            # confirmed-but-lost (pub/sub is at-most-once across a
            # restart). Lockstep FedAvg stalls forever on one lost
            # message, so the server re-broadcasts the current round
            # whenever progress stalls; rebroadcast() is duplicate-safe.
            end = time.monotonic() + E2E_DEADLINE
            stalled_since = time.monotonic()
            last_round = server.round_idx
            while any(th.is_alive() for th in threads) \
                    and time.monotonic() < end:
                time.sleep(0.05)
                if server.round_idx != last_round:
                    last_round = server.round_idx
                    stalled_since = time.monotonic()
                elif time.monotonic() - stalled_since > 2.0 \
                        and server.round_idx < rounds:
                    server.rebroadcast()
                    stalled_since = time.monotonic()
            for th in threads:
                th.join(timeout=1.0)
            assert not any(th.is_alive() for th in threads), \
                f"hung at round {server.round_idx}/{rounds}"
            assert server.round_idx >= rounds
            assert np.isfinite(float(server.params))
        finally:
            obs.configure(None)
            for cli in clients_cli:
                cli.close()
            broker.close()
            if broker2 is not None:
                broker2.close()
        with open(events_path) as f:
            kinds = [json.loads(line)["kind"] for line in f]
        assert kinds.count("conn_reconnect") >= 1, kinds
        assert kinds.count("publish_retry") >= 1, kinds
        assert kinds.count("chaos_injected") >= 1, kinds


class TestPreemptionHandler:
    def test_signal_sets_flag_and_restores(self, bus):
        h = PreemptionHandler(signals=(signal.SIGTERM,))
        old = signal.getsignal(signal.SIGTERM)
        with h:
            assert not h.requested
            os.kill(os.getpid(), signal.SIGTERM)
            assert h.requested
            assert h.signal_name == "SIGTERM"
        assert signal.getsignal(signal.SIGTERM) is old

    def test_disabled_handler_is_noop(self):
        h = PreemptionHandler(enabled=False)
        old = signal.getsignal(signal.SIGTERM)
        with h:
            assert signal.getsignal(signal.SIGTERM) is old

    def test_off_main_thread_is_noop(self):
        out = {}

        def worker():
            with PreemptionHandler() as h:
                out["installed"] = h._installed

        th = threading.Thread(target=worker)
        th.start()
        th.join(timeout=10)
        assert out == {"installed": False}


class TestDivergenceGuard:
    def test_nonfinite_trips(self):
        g = DivergenceGuard()
        n = np.ones((2, 2))
        diverged, reason, _ = g.check([[1.0, np.inf], [1.0, 1.0]], n)
        assert diverged and reason == "nonfinite"

    def test_masked_cells_ignored(self):
        g = DivergenceGuard()
        losses = np.array([[1.0, np.nan]])
        n = np.array([[1.0, 0.0]])          # the NaN cell never trained
        assert g.check(losses, n) == (False, "", 1.0)

    def test_spike_needs_warmup(self):
        g = DivergenceGuard(spike_factor=5, warmup=3)
        n = np.ones((1, 1))
        # warmup absorbs the early descent into the high-water mark
        for loss in (4.0, 2.0, 1.0):
            assert not g.check([[loss]], n)[0]
        assert not g.check([[1.0]], n)[0]            # armed, healthy
        diverged, reason, _ = g.check([[50.0]], n)   # 12x the window peak
        assert diverged and reason == "loss_spike"

    def test_heterogeneous_subsets_do_not_trip(self):
        # client subsampling: round means legitimately swing an order of
        # magnitude between subsets (a freshly-drifted client enters the
        # sample); the high-water reference absorbs that healthy variance
        g = DivergenceGuard(spike_factor=10, warmup=2)
        n = np.ones((1, 1))
        for loss in (2.3, 0.1, 0.05, 1.8, 0.02, 2.0):
            assert not g.check([[loss]], n)[0], loss

    def test_consecutive_rollbacks_abort(self):
        g = DivergenceGuard(max_rollbacks=2)
        g.record_rollback()
        with pytest.raises(DivergenceError):
            g.record_rollback()

    def test_new_window_resets_baseline_not_rollbacks(self):
        # drift boundary: the re-learning spike of a NEW concept must not
        # trip the guard, but a rollback streak spanning the boundary must
        # still count toward the abort budget
        g = DivergenceGuard(spike_factor=5, warmup=1, max_rollbacks=3)
        n = np.ones((1, 1))
        g.check([[10.0]], n)                 # warmup
        for _ in range(3):
            g.check([[0.05]], n)             # converged window
        g.record_rollback()
        g.new_window()                       # next time step begins
        assert g.baseline is None
        assert g.consecutive_rollbacks == 1
        assert not g.check([[2.0]], n)[0]    # 40x the old level: healthy

    def test_healthy_round_resets_consecutive(self):
        g = DivergenceGuard(max_rollbacks=2, warmup=0)
        n = np.ones((1, 1))
        g.record_rollback()
        g.check([[1.0]], n)                  # healthy round in between
        assert g.consecutive_rollbacks == 0
        g.record_rollback()                  # does not abort


class TestPreemptAutoResume:
    """The process-domain acceptance path: SIGTERM mid-run -> checkpoint at
    the iteration boundary -> `run --auto_resume` continues bitwise."""

    _CLI_ARGS = ["--dataset", "sine", "--model", "fnn",
                 "--concept_drift_algo", "win-1", "--concept_num", "2",
                 "--client_num_in_total", "4", "--client_num_per_round", "4",
                 "--train_iterations", "3", "--comm_round", "3",
                 "--epochs", "1", "--batch_size", "16", "--sample_num", "32",
                 "--frequency_of_the_test", "2", "--report_client", "0"]

    def _cfg(self):
        from feddrift_tpu.config import ExperimentConfig
        return ExperimentConfig(
            dataset="sine", model="fnn", concept_drift_algo="win-1",
            concept_num=2, client_num_in_total=4, client_num_per_round=4,
            train_iterations=3, comm_round=3, epochs=1, batch_size=16,
            sample_num=32, frequency_of_the_test=2, report_client=0)

    def test_sigterm_then_auto_resume_matches_uninterrupted(self, tmp_path,
                                                            capsys):
        from feddrift_tpu.cli import main
        from feddrift_tpu.simulation.runner import Experiment

        cfg = self._cfg()
        full = Experiment(cfg)
        full.run()
        full_accs = dict(full.logger.series("Test/Acc"))

        # SIGTERM delivered right after iteration 1 completes: the handler
        # flags it, the runner checkpoints at the boundary and exits cleanly
        out = str(tmp_path / "run")
        part = Experiment(cfg, out_dir=out)
        orig = part.run_iteration

        def hooked(t):
            orig(t)
            if t == 1:
                os.kill(os.getpid(), signal.SIGTERM)

        part.run_iteration = hooked
        part.run()
        assert part.preempted
        kinds = [e["kind"] for e in part.events.events()]
        assert "preempt_checkpoint" in kinds and "run_end" in kinds

        # same `run` command plus --auto_resume continues from the ckpt
        assert main(["run", *self._CLI_ARGS, "--flat_out_dir",
                     "--out_dir", out, "--auto_resume"]) == 0
        final = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert final["preempted"] is False

        with open(os.path.join(out, "metrics.jsonl")) as f:
            rows = [json.loads(line) for line in f]
        seen = [(r["iteration"], r["round"]) for r in rows]
        assert len(seen) == len(set(seen)), "duplicate (iteration, round) rows"
        # the stitched run is bitwise the uninterrupted one
        assert {r["round"]: r["Test/Acc"] for r in rows} == full_accs
        assert final["Test/Acc"] == full.logger.last("Test/Acc")

    def test_auto_resume_on_fresh_dir_is_plain_run(self, tmp_path, capsys):
        from feddrift_tpu.cli import main
        out = str(tmp_path / "fresh")
        assert main(["run", *self._CLI_ARGS, "--flat_out_dir",
                     "--out_dir", out, "--auto_resume"]) == 0
        final = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert final["rounds"] == 9          # ran from scratch: 3 iters x 3


class TestDivergenceInRunner:
    """Numeric-domain wiring: poisoned round losses -> rollback events,
    params restored, eval skipped, bounded abort."""

    def _cfg(self, **kw):
        from feddrift_tpu.config import ExperimentConfig
        base = dict(dataset="sine", model="fnn", concept_drift_algo="win-1",
                    concept_num=2, client_num_in_total=4,
                    client_num_per_round=4, train_iterations=2, comm_round=3,
                    epochs=1, batch_size=16, sample_num=32,
                    frequency_of_the_test=2, report_client=0,
                    divergence_warmup_rounds=0)
        base.update(kw)
        return ExperimentConfig(**base)

    @staticmethod
    def _leaf0(params):
        import jax
        return np.asarray(jax.tree_util.tree_leaves(params)[0])

    def test_per_round_nan_rolls_back_then_aborts(self, monkeypatch):
        import jax.numpy as jnp
        from feddrift_tpu.core.step import TrainStep
        from feddrift_tpu.simulation.runner import Experiment

        exp = Experiment(self._cfg(chunk_rounds=False,
                                   divergence_max_rollbacks=2))
        before = self._leaf0(exp.pool.params)
        orig = TrainStep.train_round

        def poisoned(self, *a, **k):
            p, o, cp, n, losses, *rest = orig(self, *a, **k)
            return (p, o, cp, n, jnp.full_like(losses, jnp.nan), *rest)

        monkeypatch.setattr(TrainStep, "train_round", poisoned)
        with pytest.raises(DivergenceError):
            exp.run()
        evs = exp.events.events("divergence_detected")
        assert len(evs) == 2 and evs[0]["reason"] == "nonfinite"
        # both diverged rounds rolled back: params are still the initials
        np.testing.assert_array_equal(self._leaf0(exp.pool.params), before)
        assert exp.logger.series("Test/Acc") == []   # evals were skipped

    def test_fused_nan_restores_snapshot_and_skips_eval(self, monkeypatch):
        import jax.numpy as jnp
        from feddrift_tpu.core.step import TrainStep
        from feddrift_tpu.simulation.runner import Experiment

        exp = Experiment(self._cfg(chunk_rounds=True,
                                   divergence_max_rollbacks=2))
        before = self._leaf0(exp.pool.params)
        orig = TrainStep.train_iteration_eval

        def poisoned(self, *a, **k):
            p, o, n, losses, bufs, total, *rest = orig(self, *a, **k)
            return (p, o, n, jnp.full_like(losses, jnp.nan), bufs, total,
                    *rest)

        monkeypatch.setattr(TrainStep, "train_iteration_eval", poisoned)
        with pytest.raises(DivergenceError):
            exp.run()
        assert len(exp.events.events("divergence_detected")) == 2
        # the fused rollback restores the host-side snapshot (the program
        # DONATED the device input buffers)
        np.testing.assert_array_equal(self._leaf0(exp.pool.params), before)
        assert exp.logger.series("Test/Acc") == []

    def test_healthy_run_is_untouched_by_the_guard(self):
        from feddrift_tpu.simulation.runner import Experiment
        a = Experiment(self._cfg(divergence_guard=True))
        a.run()
        b = Experiment(self._cfg(divergence_guard=False))
        b.run()
        assert a.logger.series("Test/Acc") == b.logger.series("Test/Acc")
        assert not a.events.events("divergence_detected")


class TestScheduledOutage:
    def test_outage_window_fails_clients_then_heals(self, bus):
        from feddrift_tpu.platform.faults import FaultInjector
        inj = FaultInjector(6)
        inj.schedule_outage(3, 6, [0, 1, 2])       # correlated AZ outage
        assert inj.mask(2).tolist() == [1] * 6
        for r in range(3, 6):
            assert inj.mask(r).tolist() == [0, 0, 0, 1, 1, 1]
        assert inj.mask(6).tolist() == [1] * 6      # healed
        assert len(bus.events("fault_injected")) == 3

    def test_outage_composes_with_kill_and_quorum(self, bus):
        from feddrift_tpu.platform.faults import FaultInjector
        inj = FaultInjector(3)
        inj.kill(2)
        inj.schedule_outage(0, 2, [0, 1])           # everyone down...
        m = inj.mask(0)
        assert m.sum() == 1 and m[0] == 1           # ...quorum floor holds

    def test_outage_validation(self):
        from feddrift_tpu.platform.faults import FaultInjector
        with pytest.raises(ValueError):
            FaultInjector(4).schedule_outage(5, 5, [0])


class TestCheckpointIntegrity:
    def _save(self, path, it=0, rnd=0, val=1.0):
        import jax.numpy as jnp
        from feddrift_tpu.utils.checkpoint import save_checkpoint
        save_checkpoint(path, config_json='{"seed": 0}', iteration=it,
                        global_round=rnd,
                        pool_params={"w": jnp.full((2, 3), val)},
                        algo_state={"s": np.arange(3)})

    def _template(self):
        import jax.numpy as jnp
        return {"w": jnp.zeros((2, 3))}

    def test_checksums_written_and_verified(self, tmp_path):
        from feddrift_tpu.utils.checkpoint import verify_checkpoint
        path = str(tmp_path / "ckpt")
        self._save(path)
        manifest = verify_checkpoint(path)
        assert set(manifest["checksums"]) == {"pool.msgpack", "algo.pkl"}

    def test_corrupt_pool_falls_back_to_old_generation(self, tmp_path, bus):
        from feddrift_tpu.utils.checkpoint import load_checkpoint
        path = str(tmp_path / "ckpt")
        self._save(path, it=0, rnd=5, val=1.0)
        self._save(path, it=1, rnd=10, val=2.0)
        assert os.path.isdir(path + ".old")
        with open(os.path.join(path, "pool.msgpack"), "r+b") as f:
            f.truncate(4)                    # torn write
        state = load_checkpoint(path, self._template())
        assert state["iteration"] == 0       # the .old generation
        assert float(np.asarray(state["pool_params"]["w"])[0, 0]) == 1.0
        evs = bus.events("checkpoint_corrupt")
        assert evs and "sha256 mismatch" in evs[0]["reason"]

    def test_all_generations_corrupt_raises_loudly(self, tmp_path, bus):
        from feddrift_tpu.utils.checkpoint import (CheckpointCorruptError,
                                                   load_checkpoint)
        path = str(tmp_path / "ckpt")
        self._save(path, it=0)
        self._save(path, it=1)
        for gen in (path, path + ".old"):
            with open(os.path.join(gen, "MANIFEST.json"), "w") as f:
                f.write("{not json")
        with pytest.raises(CheckpointCorruptError, match="no loadable"):
            load_checkpoint(path, self._template())
        assert len(bus.events("checkpoint_corrupt")) == 2

    def test_legacy_manifest_without_checksums_loads(self, tmp_path):
        from feddrift_tpu.utils.checkpoint import load_checkpoint
        path = str(tmp_path / "ckpt")
        self._save(path, it=3, rnd=30, val=4.0)
        with open(os.path.join(path, "MANIFEST.json")) as f:
            manifest = json.load(f)
        del manifest["checksums"]            # pre-checksum era checkpoint
        with open(os.path.join(path, "MANIFEST.json"), "w") as f:
            json.dump(manifest, f)
        state = load_checkpoint(path, self._template())
        assert state["iteration"] == 3
