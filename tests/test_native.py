"""Native C++ drift generator: build, determinism, distributional parity
with the numpy path, and threading-invariance."""

import numpy as np
import pytest

from feddrift_tpu import native
from feddrift_tpu.data.changepoints import load_change_points
from feddrift_tpu.data.synthetic import generate_synthetic

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native library failed to build")


def _concepts(T1=4, C=10):
    cp = load_change_points("A")
    from feddrift_tpu.data.changepoints import concept_matrix
    return concept_matrix(cp, T1, C, 1)


class TestNativeGenerator:
    def test_deterministic_and_thread_invariant(self):
        conc = _concepts()
        x1, y1 = native.generate("sea", conc, 200, 0.0, seed=7, n_threads=1)
        x2, y2 = native.generate("sea", conc, 200, 0.0, seed=7, n_threads=8)
        np.testing.assert_array_equal(x1, x2)
        np.testing.assert_array_equal(y1, y2)
        x3, _ = native.generate("sea", conc, 200, 0.0, seed=8)
        assert not np.array_equal(x1, x3)

    @pytest.mark.parametrize("name,fdim", [("sea", 3), ("sine", 2),
                                           ("circle", 2)])
    def test_label_rules_match_numpy_semantics(self, name, fdim):
        conc = _concepts()
        x, y = native.generate(name, conc, 500, 0.0, seed=0)
        assert x.shape == (10, 4, 500, fdim)
        assert set(np.unique(y)) <= {0, 1}
        # verify the label rule analytically on concept-0 cells
        c0_cells = np.argwhere(conc.T == 0)     # (client, t) pairs
        c, t = c0_cells[0]
        xs, ys = x[c, t], y[c, t]
        if name == "sea":
            clean = (xs[:, 1] + xs[:, 2] > 8.0).astype(np.int32)
            agree = (clean == ys).mean()
            assert 0.85 < agree <= 1.0          # 10% base label noise
        elif name == "sine":
            np.testing.assert_array_equal(
                ys, (xs[:, 1] <= np.sin(xs[:, 0])).astype(np.int32))
        else:
            z = (xs[:, 0] - 0.2) ** 2 + (xs[:, 1] - 0.5) ** 2 - 0.15**2
            np.testing.assert_array_equal(ys, (z > 0).astype(np.int32))

    def test_distribution_matches_numpy_backend(self):
        ds_np = generate_synthetic("sea", load_change_points("A"), 3, 10,
                                   2000, seed=0, backend="numpy")
        ds_nat = generate_synthetic("sea", load_change_points("A"), 3, 10,
                                    2000, seed=0, backend="native")
        assert ds_np.x.shape == ds_nat.x.shape
        # same uniform feature distribution and label rates per concept
        np.testing.assert_allclose(ds_np.x.mean(), ds_nat.x.mean(), atol=0.05)
        np.testing.assert_allclose(ds_np.y.mean(), ds_nat.y.mean(), atol=0.02)

    def test_noise_prob_flips_labels(self):
        conc = _concepts()
        _, y0 = native.generate("sine", conc, 1000, 0.0, seed=3)
        _, y1 = native.generate("sine", conc, 1000, 0.5, seed=3)
        flip_rate = (y0 != y1).mean()
        assert 0.4 < flip_rate < 0.6, flip_rate

    @pytest.mark.slow
    def test_e2e_training_on_native_data(self):
        from feddrift_tpu.config import ExperimentConfig
        from feddrift_tpu.simulation.runner import Experiment
        import os
        os.environ["FEDDRIFT_NATIVE_DATA"] = "1"
        try:
            cfg = ExperimentConfig(
                dataset="sine", model="fnn", concept_drift_algo="win-1",
                train_iterations=2, comm_round=8, epochs=4, sample_num=80,
                batch_size=40, frequency_of_the_test=4, lr=0.05,
                client_num_in_total=8, client_num_per_round=8, seed=0)
            exp = Experiment(cfg)
            exp.run()
            assert exp.logger.last("Test/Acc") > 0.7
        finally:
            del os.environ["FEDDRIFT_NATIVE_DATA"]
