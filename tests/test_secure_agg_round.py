"""Secure aggregation as a round mode (ISSUE 18): field-primitive
property tests (BGW/LCC encode -> drop-k -> decode), quantize boundary
semantics, threshold validation, the dropout-tolerant protocol engine
(parity with plaintext masked sums under injected share faults, explicit
degrade below threshold), the digested wire layer, and the runner
round-path integration."""

from __future__ import annotations

import json
import threading
import warnings

import numpy as np
import pytest

from feddrift_tpu import obs
from feddrift_tpu.comm.compress import CorruptFrameError
from feddrift_tpu.comm.pubsub import Broker
from feddrift_tpu.config import ExperimentConfig
from feddrift_tpu.platform import secure_agg
from feddrift_tpu.platform.faults import ShareDropInjector
from feddrift_tpu.platform.turboagg import RingConfig
from feddrift_tpu.resilience.secure_round import (
    SecureAggregator,
    SecureRoundDriver,
    SecureShareHolder,
    decode_share_frame,
    encode_share_frame,
    run_secure_wire_round,
)


@pytest.fixture(autouse=True)
def _fresh_bus():
    obs.configure(None)
    yield
    obs.configure(None)


def _events(kind):
    return obs.get_bus().events(kind)


# ----------------------------------------------------------------------
class TestQuantize:
    """Satellite: quantize must clamp (or raise) instead of silently
    wrapping past the field bound."""

    def test_round_trip_boundary_and_negatives(self):
        scale, p = 2 ** 16, secure_agg.P_DEFAULT
        bound = (int(p) // 2) / scale
        x = np.array([0.0, 1.5, -1.5, bound, -bound, bound / 2, -1e-4])
        rt = secure_agg.dequantize(secure_agg.quantize(x, scale, p),
                                   scale, p)
        np.testing.assert_allclose(rt, x, atol=0.5 / scale)

    def test_overflow_clamps_with_warning(self):
        scale, p = 2 ** 16, secure_agg.P_DEFAULT
        bound = (int(p) // 2) / scale
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            q = secure_agg.quantize(np.array([bound * 10, -bound * 10]))
            assert any("clamp" in str(x.message) for x in w)
        rt = secure_agg.dequantize(q, scale, p)
        # clamped to the boundary, NOT wrapped to the opposite sign
        np.testing.assert_allclose(rt, [bound, -bound], atol=1.0 / scale)

    def test_overflow_raises_under_strict(self):
        with pytest.raises(ValueError, match="representable range"):
            secure_agg.quantize(np.array([1e12]), strict=True)
        with pytest.raises(ValueError, match="representable range"):
            secure_agg.quantize(np.array([np.nan]), strict=True)

    def test_in_range_never_warns(self):
        with warnings.catch_warnings():
            warnings.simplefilter("error")
            secure_agg.quantize(np.linspace(-100, 100, 64), strict=True)


class TestThresholdValidation:
    """Satellite: T vs N validated up front with a clear error."""

    def test_bgw_encode_rejects_impossible(self):
        X = np.zeros((1, 4))
        with pytest.raises(ValueError, match="N >= 2T\\+1"):
            secure_agg.bgw_encode(X, N=4, T=2)
        with pytest.raises(ValueError, match="must be >= 0"):
            secure_agg.bgw_encode(X, N=4, T=-1)

    def test_bgw_encode_largest_valid_t(self):
        # N=7 -> largest tolerable T is 3 (2*3+1 = 7)
        X = np.arange(8, dtype=np.float64).reshape(1, 8)
        q = secure_agg.quantize(X)
        shares = secure_agg.bgw_encode(q, N=7, T=3,
                                       rng=np.random.default_rng(0))
        idx = np.arange(4)
        dec = secure_agg.bgw_decode(shares[idx, 0, :], idx)
        np.testing.assert_array_equal(dec[0], q[0])
        with pytest.raises(ValueError):
            secure_agg.bgw_encode(q, N=7, T=4)

    def test_secure_sum_explicit_n_validated(self):
        v = np.ones((3, 4))
        with pytest.raises(ValueError, match="secure_sum"):
            secure_agg.secure_sum(v, T=2, N=4)
        out = secure_agg.secure_sum(v, T=2, N=5)
        np.testing.assert_allclose(out, 3.0, atol=1e-3)

    def test_ring_config_rejects_thin_groups(self):
        with pytest.raises(ValueError, match="N >= 2T\\+1"):
            RingConfig(num_clients=12, group_size=4, privacy_t=2)
        RingConfig(num_clients=12, group_size=5, privacy_t=2)  # 5 >= 2*2+1

    def test_aggregator_rejects_impossible(self):
        with pytest.raises(ValueError, match="SecureAggregator"):
            SecureAggregator("shamir", num_contributors=4, threshold=2)


# ----------------------------------------------------------------------
class TestFieldProperties:
    """Satellite: seeded encode -> drop-k -> decode round-trips for the
    primitives test_turboagg.py only smoke-tests."""

    @pytest.mark.parametrize("seed", range(5))
    def test_bgw_random_dropouts_up_to_threshold(self, seed):
        rng = np.random.default_rng(seed)
        N, T, d = 7, 3, 12
        X = rng.normal(size=(2, d)) * 10
        q = secure_agg.quantize(X)
        shares = secure_agg.bgw_encode(q, N, T, rng=rng)
        for k in range(T + 1):               # drop 0..T shares
            dead = rng.choice(N, size=k, replace=False)
            alive = np.setdiff1d(np.arange(N), dead)
            use = rng.permutation(alive)[: T + 1]
            dec = secure_agg.bgw_decode(shares[use, 0, :], use)
            np.testing.assert_array_equal(dec[0], q[0])
            rt = secure_agg.dequantize(dec[0])
            np.testing.assert_allclose(rt, X[0], atol=0.5 / 2 ** 16)

    @pytest.mark.parametrize("seed", range(5))
    def test_lcc_random_dropouts_up_to_threshold(self, seed):
        rng = np.random.default_rng(100 + seed)
        N, K, T, d = 8, 2, 2, 6
        X = secure_agg.quantize(rng.normal(size=(4, d)))
        enc = secure_agg.lcc_encode(X, N, K, T, rng=rng)
        max_drop = N - (K + T)               # decode needs K+T shares
        for k in range(max_drop + 1):
            dead = rng.choice(N, size=k, replace=False)
            alive = np.setdiff1d(np.arange(N), dead)
            use = np.sort(rng.permutation(alive)[: K + T])
            dec = secure_agg.lcc_decode(enc[use], use, K, T, N)
            np.testing.assert_array_equal(
                dec.reshape(4, d), X)

    def test_bgw_linearity_share_sums_decode_to_sum(self):
        # the property the whole protocol rests on: sum of shares
        # decodes to the sum of secrets, exactly, in the field
        rng = np.random.default_rng(7)
        N, T, d, C = 5, 2, 9, 4
        qs = [secure_agg.quantize(rng.normal(size=(1, d))) for _ in range(C)]
        acc = np.zeros((N, 1, d), dtype=np.int64)
        for q in qs:
            acc = np.mod(acc + secure_agg.bgw_encode(q, N, T, rng=rng),
                         secure_agg.P_DEFAULT)
        use = np.array([4, 1, 2])            # any T+1 shares
        dec = secure_agg.bgw_decode(acc[use, 0, :], use)
        expect = np.mod(sum(q[0] for q in qs), secure_agg.P_DEFAULT)
        np.testing.assert_array_equal(dec[0], expect)


# ----------------------------------------------------------------------
class TestShareDropInjector:
    def test_deterministic_and_round_varying(self):
        a = ShareDropInjector(4, 5, drop_prob=0.2, corrupt_prob=0.1, seed=3)
        b = ShareDropInjector(4, 5, drop_prob=0.2, corrupt_prob=0.1, seed=3)
        np.testing.assert_array_equal(a.share_fates(7), b.share_fates(7))
        np.testing.assert_array_equal(a.holder_latencies(7),
                                      b.holder_latencies(7))
        assert not np.array_equal(a.share_fates(7), a.share_fates(8))

    def test_killed_holder_loses_everything(self):
        inj = ShareDropInjector(3, 4, deadline=1.0, seed=0)
        inj.kill_holder(2)
        assert (inj.share_fates(0)[:, 2] == ShareDropInjector.DROP).all()
        assert (inj.holder_latencies(0)[2] > 1.0)
        assert (inj.holder_latencies(5)[2] > 1.0)     # stays dead

    def test_prob_validation(self):
        with pytest.raises(ValueError):
            ShareDropInjector(2, 3, drop_prob=1.5)


# ----------------------------------------------------------------------
class TestSecureAggregatorEngine:
    def _payloads(self, C=6, D=40, seed=0):
        return np.random.default_rng(seed).normal(size=(C, D))

    @pytest.mark.parametrize("mode", ["shamir", "turbo"])
    def test_faultfree_parity(self, mode):
        pay = self._payloads(8)
        eng = SecureAggregator(mode, num_contributors=8, threshold=1, seed=1)
        res = eng.secure_masked_sum(pay, 0)
        assert not res.degraded and res.included == list(range(8))
        tol = 8 * 0.5 / 2 ** 16 + 1e-9
        np.testing.assert_allclose(res.total, pay.sum(axis=0), atol=tol)
        assert res.max_abs_err <= tol
        assert len(_events("secure_round_started")) == 1
        assert len(_events("secure_reconstructed")) == 1

    @pytest.mark.parametrize("round_idx", range(6))
    def test_shamir_parity_under_injected_faults(self, round_idx):
        """Per-round parity vs the plaintext masked sum on the IDENTICAL
        inclusion mask, driven by the seeded fault injector."""
        C = 7
        pay = self._payloads(C, seed=round_idx)
        inj = ShareDropInjector(C, C, drop_prob=0.08, delay_prob=0.05,
                                corrupt_prob=0.05, holder_stall_prob=0.15,
                                seed=11)
        eng = SecureAggregator("shamir", C, threshold=2, seed=2,
                               injector=inj)
        res = eng.secure_masked_sum(pay, round_idx)
        if res.degraded:
            assert res.total is None and res.included == []
            assert _events("secure_degraded")
            return
        # recompute the expected inclusion set from the same pure draws
        fates = inj.share_fates(round_idx)
        alive = inj.holder_latencies(round_idx) <= 1.0
        expect_inc = [c for c in range(C)
                      if (fates[c, alive] == ShareDropInjector.OK).all()]
        assert res.included == expect_inc
        plain = pay[expect_inc].sum(axis=0)
        np.testing.assert_allclose(
            res.total, plain, atol=len(expect_inc) * 0.5 / 2 ** 16 + 1e-9)

    def test_degrades_below_threshold_keeps_no_partial_sum(self):
        C, T = 5, 1
        inj = ShareDropInjector(C, C, seed=0)
        for h in range(C - 1):               # leave 1 alive < T+1 = 2
            inj.kill_holder(h)
        eng = SecureAggregator("shamir", C, threshold=T, seed=0,
                               injector=inj)
        res = eng.secure_masked_sum(self._payloads(C), 0)
        assert res.degraded and res.reason == "holders_below_threshold"
        assert res.total is None
        ev = _events("secure_degraded")
        assert ev and ev[-1]["reason"] == "holders_below_threshold"
        # the participation plane saw it too, at the secure_agg tier
        deg = _events("round_degraded")
        assert deg and deg[-1]["tier"] == "secure_agg"

    def test_survives_exactly_t_dropped_holders(self):
        C, T = 5, 2
        inj = ShareDropInjector(C, C, seed=0)
        inj.kill_holder(0)
        inj.kill_holder(3)                   # T dead, N-T = 3 = T+1 alive
        eng = SecureAggregator("shamir", C, threshold=T, seed=0,
                               injector=inj)
        pay = self._payloads(C)
        res = eng.secure_masked_sum(pay, 0)
        assert not res.degraded
        assert res.holders_alive == C - T
        np.testing.assert_allclose(res.total, pay.sum(axis=0),
                                   atol=C * 0.5 / 2 ** 16 + 1e-9)
        drops = _events("share_dropped")
        assert any(e["reason"] == "holder_dropout" for e in drops)

    def test_turbo_excluded_contributor(self):
        C = 8
        inj = ShareDropInjector(C, C, drop_prob=0.06, seed=5)
        eng = SecureAggregator("turbo", C, threshold=1, seed=1,
                               injector=inj)
        pay = self._payloads(C, seed=2)
        res = eng.secure_masked_sum(pay, 1)
        assert not res.degraded
        plain = pay[res.included].sum(axis=0)
        np.testing.assert_allclose(
            res.total, plain, atol=len(res.included) * 0.5 / 2 ** 16 + 1e-9)

    def test_weighted_mean_matches_plaintext(self):
        C = 6
        pay = self._payloads(C, D=20)
        w = np.abs(np.random.default_rng(3).normal(size=C)) * 50 + 1
        eng = SecureAggregator("shamir", C, threshold=1, seed=4)
        mean, res = eng.secure_weighted_mean(pay, w, 0)
        assert not res.degraded
        ref = (pay * w[:, None]).sum(axis=0) / w.sum()
        np.testing.assert_allclose(mean, ref, atol=1e-3)

    def test_unknown_mode_rejected(self):
        with pytest.raises(ValueError, match="unknown secure_agg mode"):
            SecureAggregator("rot13", num_contributors=5)


# ----------------------------------------------------------------------
class TestWireLayer:
    def test_frame_round_trip_and_digest(self):
        vec = np.arange(17, dtype=np.int64) * 12345
        wire = encode_share_frame(vec, sender=3, holder=1, round_idx=9)
        f = decode_share_frame(wire)
        assert f["sender"] == 3 and f["holder"] == 1 and f["round"] == 9
        np.testing.assert_array_equal(f["vec"], vec)

    def test_tampered_frame_detected(self):
        wire = encode_share_frame(np.arange(8), sender=0, holder=0)
        d = json.loads(wire)
        d["data"] = ("A" if d["data"][0] != "A" else "B") + d["data"][1:]
        with pytest.raises(CorruptFrameError, match="digest"):
            decode_share_frame(json.dumps(d))
        with pytest.raises(CorruptFrameError):
            decode_share_frame("not json at all")
        with pytest.raises(CorruptFrameError, match="missing"):
            decode_share_frame(json.dumps({"v": 1}))

    def _spawn_holders(self, broker, ids):
        holders = [SecureShareHolder(broker, h) for h in ids]
        threads = [threading.Thread(target=h.run, kwargs={"timeout": 15},
                                    daemon=True) for h in holders]
        for t in threads:
            t.start()
        return holders, threads

    def test_wire_round_with_corruption_and_dead_holder(self):
        broker = Broker()
        # holder 2 never comes up: a silent topic = a dead process
        self._spawn_holders(broker, [0, 1])
        pay = np.random.default_rng(0).normal(size=(4, 16))

        def tamper(wire, sender, holder):
            if (sender, holder) == (1, 0):   # flip a payload byte in transit
                d = json.loads(wire)
                d["data"] = ("B" if d["data"][0] != "B" else "C") \
                    + d["data"][1:]
                return json.dumps(d)
            return wire

        res = run_secure_wire_round(broker, pay, threshold=1, num_holders=3,
                                    deadline=2.0, tamper=tamper)
        assert not res.degraded
        assert res.included == [0, 2, 3]     # sender 1 excluded (corrupt)
        assert res.holders_alive == 2
        plain = pay[res.included].sum(axis=0)
        np.testing.assert_allclose(res.total[:-1], plain, atol=1e-3)
        assert abs(res.total[-1] - 3) < 1e-3  # opened contributor count
        reasons = {e["reason"] for e in _events("share_dropped")}
        assert {"corrupt", "holder_dropout"} <= reasons
        assert _events("secure_reconstructed")

    def test_wire_round_degrades_without_quorum_no_hang(self):
        broker = Broker()
        self._spawn_holders(broker, [0])     # 1 alive < T+1 = 2
        pay = np.zeros((3, 4))
        res = run_secure_wire_round(broker, pay, threshold=1, num_holders=3,
                                    deadline=0.7)
        assert res.degraded
        assert res.reason == "holders_below_threshold"
        assert _events("secure_degraded")


# ----------------------------------------------------------------------
class TestSecureRoundDriver:
    def _tree(self, M=2, C=5, seed=0):
        rng = np.random.default_rng(seed)
        prev = {"w": rng.normal(size=(M, 4, 3)).astype(np.float32),
                "b": rng.normal(size=(M, 3)).astype(np.float32)}
        cp = {k: v[:, None] + rng.normal(
            size=(M, C) + v.shape[1:]).astype(np.float32) * 0.01
            for k, v in prev.items()}
        n = np.abs(rng.normal(size=(M, C))) * 100 + 1
        return prev, cp, n

    def test_matches_plaintext_weighted_mean(self):
        prev, cp, n = self._tree()
        drv = SecureRoundDriver("shamir", num_clients=5, threshold=1, seed=0)
        newp, res = drv.aggregate_params(prev, cp, n, 0)
        assert not res.degraded
        wt = n / n.sum(axis=1, keepdims=True)
        for k in prev:
            ref = prev[k] + np.einsum(
                "mc,mc...->m...", wt, cp[k] - prev[k][:, None])
            np.testing.assert_allclose(newp[k], ref, atol=1e-3)
            assert newp[k].dtype == prev[k].dtype

    def test_untrained_model_keeps_prev(self):
        prev, cp, n = self._tree()
        n[1, :] = 0.0                        # model 1 untouched this round
        drv = SecureRoundDriver("shamir", num_clients=5, threshold=1, seed=0)
        newp, res = drv.aggregate_params(prev, cp, n, 0)
        assert not res.degraded
        for k in prev:
            np.testing.assert_allclose(newp[k][1], prev[k][1], atol=1e-3)

    def test_degraded_returns_none(self):
        prev, cp, n = self._tree()
        drv = SecureRoundDriver("shamir", num_clients=5, threshold=1, seed=0)
        for h in range(4):
            drv.injector.kill_holder(h)
        newp, res = drv.aggregate_params(prev, cp, n, 0)
        assert newp is None and res.degraded


# ----------------------------------------------------------------------
class TestConfigValidation:
    def _cfg(self, **kw):
        base = dict(dataset="sine", model="fnn", concept_num=2,
                    concept_drift_algo="softcluster",
                    concept_drift_algo_arg="mmacc_10",
                    client_num_in_total=5, client_num_per_round=5,
                    train_iterations=1, comm_round=2, sample_num=24,
                    batch_size=12, report_client=0)
        base.update(kw)
        return ExperimentConfig(**base)

    def test_accepts_valid(self):
        self._cfg(secure_agg="shamir", secure_threshold_t=2)

    def test_rejects_bad_combos(self):
        with pytest.raises(ValueError, match="unknown secure_agg"):
            self._cfg(secure_agg="bgw")
        with pytest.raises(ValueError, match="2T\\+1"):
            self._cfg(secure_agg="shamir", secure_threshold_t=3)
        with pytest.raises(ValueError, match="robust_agg"):
            self._cfg(secure_agg="shamir", robust_agg="median")
        with pytest.raises(ValueError, match="hierarchy"):
            self._cfg(secure_agg="shamir", hierarchy_edges=2)
        with pytest.raises(ValueError, match="megastep"):
            self._cfg(secure_agg="shamir", megastep_k=4)


# ----------------------------------------------------------------------
@pytest.mark.slow
class TestRunnerIntegration:
    def _run(self, **overrides):
        from feddrift_tpu.simulation.runner import Experiment
        base = dict(dataset="sine", model="fnn", concept_num=2,
                    concept_drift_algo="softcluster",
                    concept_drift_algo_arg="mmacc_10",
                    client_num_in_total=5, client_num_per_round=5,
                    train_iterations=2, comm_round=3, epochs=1,
                    sample_num=24, batch_size=12,
                    frequency_of_the_test=2, report_client=0,
                    checkpoint_every_iteration=False, seed=0)
        base.update(overrides)
        exp = Experiment(ExperimentConfig(**base))
        exp.run()
        return exp

    def test_secure_run_tracks_plaintext(self):
        import jax
        exp_sec = self._run(secure_agg="shamir")
        sec_leaves = [np.asarray(l) for l in
                      jax.tree_util.tree_leaves(exp_sec.pool.params)]
        recs = _events("secure_reconstructed")
        assert len(recs) == 6                # 2 iterations x 3 rounds
        assert max(e["max_abs_err"] for e in recs) < 1e-3
        assert all(np.isfinite(l).all() for l in sec_leaves)
        obs.configure(None)
        exp_ref = self._run()                # plaintext, same seed
        ref_leaves = [np.asarray(l) for l in
                      jax.tree_util.tree_leaves(exp_ref.pool.params)]
        for s, r in zip(sec_leaves, ref_leaves):
            np.testing.assert_allclose(s, r, atol=5e-2)

    def test_secure_run_with_faults_degrades_not_hangs(self):
        exp = self._run(secure_agg="shamir",
                        secure_holder_stall_prob=0.45,
                        secure_fault_seed=7, train_iterations=1)
        import jax
        assert all(np.isfinite(np.asarray(l)).all()
                   for l in jax.tree_util.tree_leaves(exp.pool.params))
        started = _events("secure_round_started")
        rec = _events("secure_reconstructed")
        deg = _events("secure_degraded")
        assert len(started) == 3
        assert len(rec) + len(deg) == 3      # every round closed, no hang
