"""End-to-end simulation tests on the 8-device CPU mesh.

Mirrors the reference's reproducibility-as-testing stance (SURVEY.md §4):
fixed seeds, assert accuracy trajectories.
"""


from feddrift_tpu.config import ExperimentConfig
from feddrift_tpu.simulation.runner import run_experiment
import pytest

pytestmark = pytest.mark.slow   # heavy compiles: full-tier only


def _cfg(**kw):
    base = dict(dataset="sine", model="fnn", concept_drift_algo="win-1",
                train_iterations=2, comm_round=16, epochs=5, sample_num=100,
                batch_size=50, frequency_of_the_test=5, lr=0.05,
                client_num_in_total=10, client_num_per_round=10, seed=0)
    base.update(kw)
    return ExperimentConfig(**base)


class TestEndToEnd:
    def test_win1_learns_sine(self):
        exp = run_experiment(_cfg())
        accs = dict(exp.logger.series("Test/Acc"))
        # end of iteration 0 (round 15): model must beat chance solidly
        assert accs[15] > 0.8, accs

    def test_drift_hurts_oblivious_baseline(self):
        exp = run_experiment(_cfg(train_iterations=3, comm_round=12))
        accs = exp.logger.series("Test/Acc")
        by_round = dict(accs)
        # test at iteration 2 covers step-3 data where half the clients have
        # flipped concepts (preset A) -> win-1 single model falls toward 0.5
        assert by_round[35] < 0.75, by_round

    def test_chunked_matches_per_round(self):
        # the scanned multi-round program must reproduce the per-round host
        # loop bitwise (same fold_in key sequence)
        a = run_experiment(_cfg(chunk_rounds=True)).logger.series("Test/Acc")
        b = run_experiment(_cfg(chunk_rounds=False)).logger.series("Test/Acc")
        assert a == b, (a, b)

    def test_chunked_matches_per_round_softcluster(self):
        kw = dict(concept_drift_algo="softcluster",
                  concept_drift_algo_arg="H_A_C_1_10_0", concept_num=3,
                  train_iterations=3, comm_round=8, frequency_of_the_test=4)
        a = run_experiment(_cfg(chunk_rounds=True, **kw)).logger.series("Test/Acc")
        b = run_experiment(_cfg(chunk_rounds=False, **kw)).logger.series("Test/Acc")
        assert a == b, (a, b)

    def test_acc_matrix_ride_along_cache(self, monkeypatch):
        # The fused path offers its final eval slot as next iteration's
        # cluster-phase acc matrix (runner._run_iteration_fused ->
        # DriftAlgorithm.offer_acc_matrix): the cache must actually hit
        # (saving one device round trip per iteration) AND the clustering
        # trajectory must be identical with the cache defeated.
        from feddrift_tpu.algorithms.base import DriftAlgorithm
        from feddrift_tpu.core.step import TrainStep

        kw = dict(concept_drift_algo="softcluster",
                  concept_drift_algo_arg="H_A_C_1_10_0", concept_num=3,
                  train_iterations=3, comm_round=8, frequency_of_the_test=4)

        calls = {"n": 0}
        orig = TrainStep.acc_matrix

        def counting(self, *a, **k):
            calls["n"] += 1
            return orig(self, *a, **k)

        monkeypatch.setattr(TrainStep, "acc_matrix", counting)
        exp_a = run_experiment(_cfg(chunk_rounds=True, **kw))
        hits = calls["n"]

        monkeypatch.setattr(DriftAlgorithm, "offer_acc_matrix",
                            lambda self, params, offers: None)
        calls["n"] = 0
        exp_b = run_experiment(_cfg(chunk_rounds=True, **kw))
        misses = calls["n"]

        # cache removes >= (iterations - 1) standalone acc_matrix dispatches
        assert misses - hits >= kw["train_iterations"] - 1, (hits, misses)
        # and changes nothing observable
        assert exp_a.logger.series("Test/Acc") == exp_b.logger.series("Test/Acc")
        import numpy as np
        assert np.array_equal(exp_a.algo.weights, exp_b.algo.weights)

    def test_fused_iteration_eval_cadence(self):
        # the fully-fused iteration program must log evals at the reference
        # cadence — every frequency_of_the_test rounds plus the final round
        # (AggregatorSoftCluster.py:211) — with correct global round numbers
        exp = run_experiment(_cfg(chunk_rounds=True, train_iterations=2,
                                  comm_round=13, frequency_of_the_test=5))
        rounds = [r for r, _ in exp.logger.series("Test/Acc")]
        assert rounds == [0, 5, 10, 12, 13, 18, 23, 25], rounds

    def test_client_subsampling_paths_agree(self):
        # client_num_per_round < C: round-seeded sampling masks
        # (client_sampling, AggregatorSoftCluster.py:197-205) must give
        # identical trajectories on the fused and per-round paths
        kw = dict(client_num_per_round=4, train_iterations=2, comm_round=9,
                  frequency_of_the_test=4)
        a = run_experiment(_cfg(chunk_rounds=True, **kw)).logger.series("Test/Acc")
        b = run_experiment(_cfg(chunk_rounds=False, **kw)).logger.series("Test/Acc")
        assert a == b, (a, b)
        # and subsampling must actually change the trajectory vs full clients
        c = run_experiment(_cfg(chunk_rounds=True, train_iterations=2,
                                comm_round=9,
                                frequency_of_the_test=4)).logger.series("Test/Acc")
        assert a != c

    def test_remat_identical_numerics(self):
        # jax.checkpoint rematerialization must not change trajectories
        a = run_experiment(_cfg(comm_round=6)).logger.series("Test/Acc")
        b = run_experiment(_cfg(comm_round=6, remat=True)).logger.series("Test/Acc")
        assert a == b

    def test_determinism(self):
        a = run_experiment(_cfg()).logger.series("Test/Acc")
        b = run_experiment(_cfg()).logger.series("Test/Acc")
        assert a == b

    def test_all_retrain_all_data(self):
        exp = run_experiment(_cfg(concept_drift_algo="all", comm_round=10))
        assert exp.logger.last("Test/Acc") > 0.7

    def test_recency_exp(self):
        exp = run_experiment(_cfg(concept_drift_algo="exp", comm_round=10))
        assert exp.logger.last("Test/Acc") > 0.6

    def test_metrics_names_reference_compatible(self):
        exp = run_experiment(_cfg(comm_round=6))
        rec = exp.logger.history[-1]
        for key in ("Train/Acc", "Train/Loss", "Test/Acc", "Test/Loss",
                    "Train/Acc-CL-0", "Test/Acc-CL-9", "Plurality/CL-0"):
            assert key in rec, key
