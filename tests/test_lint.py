"""graftlint engine tests: golden bad-code fixtures per rule (each fires
exactly once with the expected rule id and file:line), suppression
comments silence findings, the --json schema is stable, exit codes are
1-on-findings / 0-on-clean, and — the tier-1 gate — the merged tree
itself lints clean."""

import io
import json
import os
import textwrap

import pytest

from feddrift_tpu.analysis import events_schema
from feddrift_tpu.analysis.engine import LintEngine, run_lint
from feddrift_tpu.analysis.findings import (
    Finding,
    exit_code,
    findings_to_json,
    parse_suppressions,
)
from feddrift_tpu.cli import main as cli_main

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "feddrift_tpu")


@pytest.fixture(scope="module")
def engine():
    return LintEngine()


def _lint_file(engine, tmp_path, name, source):
    p = tmp_path / name
    p.write_text(textwrap.dedent(source))
    return p, engine.run([str(p)])


# ---------------------------------------------------------------- fixtures
GOLDEN = {
    "R1": """\
        def f(cfg):
            return cfg.not_a_real_knob
        """,
    "R2": """\
        def hot(x):
            # lint: hot-path-begin
            v = x.item()
            # lint: hot-path-end
            return v
        """,
    "R3": """\
        import threading

        class BadMonitor:
            def __init__(self, bus):
                self._lock = threading.Lock()
                self.bus = bus

            def attach(self, bus):
                bus.add_tap(self.observe)

            def observe(self, rec):
                with self._lock:
                    self._raise(rec)

            def _raise(self, rec):
                self.bus.emit("alert_raised", source="bad")
        """,
    "R4": """\
        import time

        def decide():
            return time.time()
        """,
    "R5": """\
        from functools import partial
        import jax

        @partial(jax.jit, static_argnames=("num_steps",))
        def body(x, steps):
            return x
        """,
    "R7": """\
        import numpy as np

        def decode(pool):
            return pool.astype(np.float32)
        """,
}
GOLDEN_LINE = {"R1": 2, "R2": 3, "R3": 16, "R4": 4, "R5": 4, "R7": 4}


@pytest.mark.parametrize("rule", sorted(GOLDEN))
def test_golden_fixture_fires_exactly_once(engine, tmp_path, rule):
    p, findings = _lint_file(engine, tmp_path, f"bad_{rule.lower()}.py",
                             GOLDEN[rule])
    assert [f.rule for f in findings] == [rule], findings
    f = findings[0]
    assert not f.suppressed
    assert f.path == str(p)
    assert f.line == GOLDEN_LINE[rule], f.render()
    assert f.severity == "error"


@pytest.mark.parametrize("rule", sorted(GOLDEN))
def test_golden_fixture_exits_1_via_cli(tmp_path, rule):
    p = tmp_path / f"bad_{rule.lower()}.py"
    p.write_text(textwrap.dedent(GOLDEN[rule]))
    assert cli_main(["lint", str(p)]) == 1


def test_suppression_comment_silences(engine, tmp_path):
    src = """\
        def f(cfg):
            return cfg.not_a_real_knob  # lint: r1-ok (golden suppression)
        """
    p, findings = _lint_file(engine, tmp_path, "ok.py", src)
    assert len(findings) == 1 and findings[0].suppressed
    assert findings[0].justification == "golden suppression"
    assert exit_code(findings) == 0
    assert cli_main(["lint", str(p)]) == 0


def test_standalone_suppression_covers_next_code_line(tmp_path):
    src = textwrap.dedent("""\
        # lint: r1-ok (standalone)
        # a second comment line between suppression and code
        x = cfg.not_a_real_knob
        """)
    sup = parse_suppressions(src)
    assert sup[3] == {"R1": "standalone"}


def test_clean_file_exits_0(engine, tmp_path):
    p, findings = _lint_file(engine, tmp_path, "clean.py",
                             "def f():\n    return 1\n")
    assert findings == []
    assert cli_main(["lint", str(p)]) == 0


def test_json_schema_stable(tmp_path, capsys):
    p = tmp_path / "bad_r1.py"
    p.write_text(textwrap.dedent(GOLDEN["R1"]))
    rc = run_lint([str(p)], as_json=True, out=io.StringIO())
    assert rc == 1
    buf = io.StringIO()
    run_lint([str(p)], as_json=True, out=buf)
    doc = json.loads(buf.getvalue())
    assert sorted(doc) == ["counts", "findings", "strict", "suppressed",
                           "version"]
    assert doc["version"] == 1
    assert doc["counts"] == {"R1": 1}
    assert doc["suppressed"] == 0
    (f,) = doc["findings"]
    assert sorted(f) == ["hint", "justification", "line", "message", "path",
                         "rule", "severity", "suppressed"]
    assert f["rule"] == "R1" and f["line"] == 2


# ---------------------------------------------------------- rule precision
def test_r1_non_experiment_config_annotation_exempt(engine, tmp_path):
    src = """\
        class RingConfig:
            pass

        class Ring:
            def __init__(self, cfg: RingConfig):
                self.cfg = cfg

            def use(self):
                cfg = self.cfg
                return cfg.not_a_knob + self.cfg.also_not_a_knob

        def free(cfg: RingConfig):
            return cfg.whatever
        """
    _, findings = _lint_file(engine, tmp_path, "ring.py", src)
    assert findings == [], [f.render() for f in findings]


def test_r1_getattr_literal_checked(engine, tmp_path):
    src = """\
        def f(cfg):
            a = getattr(cfg, "fnn_hidden_dim", 10)   # declared: ok
            b = getattr(cfg, "not_a_real_knob", 0)   # undeclared: fires
            return a + b
        """
    _, findings = _lint_file(engine, tmp_path, "ga.py", src)
    assert [(f.rule, f.line) for f in findings] == [("R1", 3)]


def test_r2_outside_region_not_flagged(engine, tmp_path):
    src = """\
        def cold(x):
            return x.item()
        """
    _, findings = _lint_file(engine, tmp_path, "cold.py", src)
    assert findings == []


def test_r2_unbalanced_markers_flagged(engine, tmp_path):
    src = """\
        def f(x):
            # lint: hot-path-begin
            return x
        """
    _, findings = _lint_file(engine, tmp_path, "unbal.py", src)
    assert [f.rule for f in findings] == ["R2"]
    assert "never closed" in findings[0].message


def test_r3_rlock_emit_is_safe(engine, tmp_path):
    # the PR 9 FIX: emit under the monitor's own RLock is the documented
    # safe pattern and must not fire
    src = GOLDEN["R3"].replace("threading.Lock()", "threading.RLock()")
    _, findings = _lint_file(engine, tmp_path, "good_monitor.py", src)
    assert findings == [], [f.render() for f in findings]


def test_r4_seeded_constructors_allowed(engine, tmp_path):
    src = """\
        import numpy as np
        import random

        def setup(seed):
            a = np.random.default_rng(seed)
            b = np.random.RandomState(seed)
            c = random.Random(seed)
            return a, b, c
        """
    _, findings = _lint_file(engine, tmp_path, "seeded.py", src)
    assert findings == []


def test_r4_only_applies_to_seeded_modules_in_package(engine):
    # obs/ is telemetry, outside the seeded-replay module set: its
    # time.time() wall stamps must not fire R4
    findings = engine.run([os.path.join(PKG, "obs", "events.py")])
    assert [f for f in findings if f.rule == "R4"] == []


def test_r5_matching_signature_clean(engine, tmp_path):
    src = """\
        from functools import partial
        import jax

        @partial(jax.jit, static_argnums=0, static_argnames=("steps",))
        def body(self, x, steps):
            return x
        """
    _, findings = _lint_file(engine, tmp_path, "goodjit.py", src)
    assert findings == []


def test_r7_asarray_dtype_kwarg_flagged(engine, tmp_path):
    src = """\
        import numpy as np

        def coerce(update):
            return np.asarray(update, dtype=np.float32)
        """
    _, findings = _lint_file(engine, tmp_path, "coerce.py", src)
    assert [f.rule for f in findings] == ["R7"]
    assert findings[0].line == 4


def test_r7_dtype_preserving_calls_clean(engine, tmp_path):
    src = """\
        import numpy as np

        def keep(update, expected):
            a = np.asarray(update)
            b = update.astype(expected.dtype)
            c = update.astype(np.int32)
            return a, b, c
        """
    _, findings = _lint_file(engine, tmp_path, "keep.py", src)
    assert [f for f in findings if f.rule == "R7"] == []


def test_r7_only_applies_to_pool_modules_in_package(engine):
    # simulation/runner.py is outside the R7 pool/update module set: its
    # f32 ensemble-weight coercions are report-path, must not fire R7
    findings = engine.run([os.path.join(PKG, "simulation", "runner.py")])
    assert [f for f in findings if f.rule == "R7"] == []


def test_r5_donated_read_after_dispatch(engine, tmp_path):
    src = """\
        import jax

        def drive(params, other):
            step = jax.jit(body, donate_argnums=(0,))
            new = step(params, other)
            return params
        """
    _, findings = _lint_file(engine, tmp_path, "donate.py", src)
    assert [(f.rule, f.line) for f in findings] == [("R5", 6)]
    src_ok = src.replace("return params", "return new")
    _, findings = _lint_file(engine, tmp_path, "donate_ok.py", src_ok)
    assert findings == []


def test_r6_adapter_maps_problems_to_findings(monkeypatch):
    monkeypatch.setattr(
        events_schema, "check",
        lambda strict=False: [
            "emitted kind 'zzz' not in EVENT_KINDS "
            "(feddrift_tpu/comm/pubsub.py:42)",
            "kind 'dead' in EVENT_KINDS but undocumented in "
            "docs/OBSERVABILITY.md",
        ])
    out = events_schema.rule_r6()
    assert [(f.rule, f.path, f.line) for f in out] == [
        ("R6", "feddrift_tpu/comm/pubsub.py", 42),
        ("R6", os.path.join("feddrift_tpu", "obs", "events.py"), 1),
    ]


# ---------------------------------------------------------------- tier-1
def test_merged_tree_is_lint_clean():
    """THE dogfood gate: zero unsuppressed findings over the package, and
    every suppression carries a justification."""
    engine = LintEngine()
    findings = engine.run([PKG], strict=True)
    active = [f for f in findings if not f.suppressed]
    assert active == [], "\n".join(f.render() for f in active)
    for f in findings:
        assert f.justification, f"suppression without justification: " \
                                f"{f.render()}"


def test_findings_to_json_counts_exclude_suppressed():
    fs = [Finding("R1", "error", "a.py", 1, "m"),
          Finding("R2", "error", "a.py", 2, "m", suppressed=True,
                  justification="why")]
    doc = json.loads(findings_to_json(fs))
    assert doc["counts"] == {"R1": 1}
    assert doc["suppressed"] == 1
