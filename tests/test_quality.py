"""Model-quality plane tests (obs/quality.py + platform/canary.py).

Covers the three streaming estimators host-side (delayed-label join with
TTL/capacity bounds, calibration sketch, entropy-shift KS detector), the
engine-attached quality monitor (live accuracy equals the client-side
oracle on the same stream), and the lineage-aware shadow canary: a clean
merge COMMITS, a corrupted candidate ROLLS BACK with a crit alert, events
arriving mid-canary defer and drain, a dried-up canary fails open on
timeout, and operator abort discards the candidate without a verdict.
"""

import json

import jax.numpy as jnp
import numpy as np
import pytest

from feddrift_tpu import obs
from feddrift_tpu.config import ExperimentConfig
from feddrift_tpu.core.pool import ModelPool
from feddrift_tpu.data.registry import make_dataset
from feddrift_tpu.models import create_model
from feddrift_tpu.obs.quality import (EntropyShiftDetector, LabelJoiner,
                                      QualityMonitor, StreamingECE,
                                      _Pending, prediction_stats)
from feddrift_tpu.platform.canary import CanaryController
from feddrift_tpu.platform.serving import (InferenceEngine, RoutingTable,
                                           UnknownClientError)


@pytest.fixture()
def bus():
    b = obs.configure(None)
    yield b
    obs.configure(None)


def _pool(M=2, identical=False):
    cfg = ExperimentConfig(dataset="sea", train_iterations=2, sample_num=16)
    ds = make_dataset(cfg)
    mod = create_model("fnn", ds, cfg)
    return ModelPool.create(mod, jnp.zeros((2, 3)), M, seed=7,
                            identical=identical)


def _engine(pool, table, **kw):
    kw.setdefault("buckets", (1, 2, 4))
    kw.setdefault("max_wait_s", 0.002)
    return InferenceEngine(pool, RoutingTable(table), **kw)


def _anti(params):
    """Negate the classifier (last) layer: logits flip, so every
    prediction disagrees with the original — same entropy, wrong class."""
    last = sorted(params.keys())[-1]
    return {k: ({kk: -vv for kk, vv in v.items()} if k == last else v)
            for k, v in params.items()}


class TestPredictionStats:
    def test_confident_vs_uniform(self):
        pred, conf, ent = prediction_stats([10.0, 0.0])
        assert pred == 0 and conf > 0.99 and ent < 0.01
        _, conf_u, ent_u = prediction_stats([0.0, 0.0])
        assert abs(conf_u - 0.5) < 1e-9
        assert abs(ent_u - np.log(2)) < 1e-9


class TestLabelJoiner:
    def test_join_and_miss(self):
        j = LabelJoiner(ttl_s=60, time_fn=lambda: 100.0)
        j.record(1, _Pending(0, 5, 1, 0.9, 0.1, 100.0))
        assert j.pop(1).pred == 1
        assert j.pop(1) is None          # consumed
        assert j.pop(42) is None         # never recorded

    def test_garbage_request_id_is_a_miss(self):
        # labels come from external feedback loops: a non-numeric or
        # wrong-typed id must degrade to a miss, never raise
        j = LabelJoiner(ttl_s=60, time_fn=lambda: 100.0)
        j.record(1, _Pending(0, 5, 1, 0.9, 0.1, 100.0))
        assert j.pop("not-a-request-id") is None
        assert j.pop(None) is None
        assert j.pop(1.0).pred == 1      # numeric strings/floats coerce

    def test_ttl_expiry(self):
        t = [0.0]
        j = LabelJoiner(ttl_s=10, time_fn=lambda: t[0])
        j.record(1, _Pending(0, 0, 1, 0.9, 0.1, t[0]))
        t[0] = 11.0
        assert j.pop(1) is None and j.expired == 1
        # the sweep also evicts from the front on later inserts
        j.record(2, _Pending(0, 0, 1, 0.9, 0.1, t[0]))
        t[0] = 30.0
        j.record(3, _Pending(0, 0, 1, 0.9, 0.1, t[0]))
        assert len(j) == 1 and j.expired == 2

    def test_capacity_eviction(self):
        j = LabelJoiner(ttl_s=1e9, capacity=3, time_fn=lambda: 100.0)
        for rid in range(5):
            j.record(rid, _Pending(0, 0, 1, 0.9, 0.1, 100.0))
        assert len(j) == 3 and j.evicted == 2
        assert j.pop(0) is None and j.pop(4) is not None


class TestStreamingECE:
    def test_empty_is_none(self):
        assert StreamingECE().ece() is None

    def test_perfect_calibration_near_zero(self):
        e = StreamingECE(bins=10)
        rng = np.random.RandomState(0)
        for _ in range(4000):
            conf = rng.uniform(0.5, 1.0)
            e.observe(conf, bool(rng.uniform() < conf))
        assert e.ece() < 0.05

    def test_overconfidence_shows_up(self):
        e = StreamingECE(bins=10)
        for _ in range(100):
            e.observe(0.95, False)       # always wrong at conf .95
        assert e.ece() > 0.9


class TestEntropyShiftDetector:
    def test_stationary_never_fires(self):
        # window 32: the two-sample KS null for n=m=32 sits well below
        # the 0.5 threshold, so iid noise cannot cross it
        d = EntropyShiftDetector(window=32, threshold=0.5)
        rng = np.random.RandomState(0)
        assert all(d.observe(0.5 + 0.01 * rng.standard_normal()) is None
                   for _ in range(500))

    def test_step_shift_fires_once_and_reanchors(self):
        d = EntropyShiftDetector(window=16, threshold=0.5)
        rng = np.random.RandomState(1)
        fired = [s for s in (d.observe(0.6 + 0.02 * rng.standard_normal())
                             for _ in range(100)) if s is not None]
        fired += [s for s in (d.observe(0.1 + 0.02 * rng.standard_normal())
                              for _ in range(100)) if s is not None]
        assert len(fired) == 1 and fired[0] >= 0.5
        # after re-anchoring the shifted regime is the new normal
        assert all(d.observe(0.1 + 0.02 * rng.standard_normal()) is None
                   for _ in range(100))

    def test_reset_reanchors(self):
        d = EntropyShiftDetector(window=8, threshold=0.5)
        for _ in range(20):
            d.observe(0.9)
        d.reset()
        assert all(d.observe(0.1) is None for _ in range(50))


class TestQualityMonitor:
    def test_join_accuracy_and_event_cadence(self, bus):
        m = QualityMonitor(window=5)
        rng = np.random.RandomState(0)
        correct = []                     # prediction is always class 0
        for rid in range(10):
            m.record_prediction(rid, model=rid % 2, logits=[2.0, -1.0])
            y = 0 if rng.uniform() < 0.7 else 1
            rec = m.observe_label(rid, y)
            assert rec is not None and rec["model"] == rid % 2
            correct.append(y == 0)
        snap = m.snapshot()
        assert snap["labeled"] == 10 and snap["missed"] == 0
        # the estimate is WINDOWED: last `window` labels only
        assert snap["accuracy"] == pytest.approx(np.mean(correct[-5:]))
        # one model_quality event per full window of labels
        assert sum(1 for e in bus.events()
                   if e["kind"] == "model_quality") == 2
        assert set(snap["per_model"]) == {"0", "1"}

    def test_unknown_label_counts_missed(self):
        m = QualityMonitor(window=5)
        assert m.observe_label(999, 0) is None
        assert m.snapshot()["missed"] == 1

    def test_drift_event_from_prediction_stream(self, bus):
        m = QualityMonitor(window=100, drift_window=8, drift_threshold=0.5)
        for rid in range(16):
            m.record_prediction(rid, 0, [8.0, 0.0])     # low entropy
        for rid in range(16, 64):
            m.record_prediction(rid, 0, [0.05, 0.0])    # high entropy
        assert m.drift_suspected >= 1
        kinds = [e["kind"] for e in bus.events()]
        assert "serve_drift_suspected" in kinds

    def test_on_swap_resets_detector(self):
        m = QualityMonitor(window=100, drift_window=8, drift_threshold=0.5)
        for rid in range(16):
            m.record_prediction(rid, 0, [8.0, 0.0])
        m.on_swap()
        for rid in range(16, 64):
            m.record_prediction(rid, 0, [0.05, 0.0])
        assert m.drift_suspected == 0   # new regime became the reference


class TestEngineQuality:
    def test_live_accuracy_matches_client_oracle(self, bus):
        pool = _pool(M=2)
        eng = _engine(pool, [0, 1, 0, 1]).start()
        eng.enable_quality(window=50)
        try:
            eng.warmup()
            rng = np.random.RandomState(3)
            oracle = []
            for i in range(40):
                r = eng.submit(i % 4, rng.standard_normal(3)
                               .astype(np.float32))
                pred = int(np.argmax(r.logits))
                y = pred if rng.uniform() >= 0.25 else 1 - pred
                assert eng.observe_label(r.request_id, y)
                oracle.append(pred == y)
            snap = eng.quality.snapshot()
            assert snap["labeled"] == 40
            assert snap["accuracy"] == pytest.approx(np.mean(oracle))
        finally:
            eng.close()


class TestCanary:
    def _run_labeled(self, eng, ctl, n=200, seed=0):
        """Closed loop y := live prediction — live is 'always right',
        so the verdict isolates the candidate's (dis)agreement."""
        rng = np.random.RandomState(seed)
        pop = eng._gen.routing.population
        for i in range(n):
            if ctl.verdicts:
                return
            r = eng.submit(i % pop, rng.standard_normal(3)
                           .astype(np.float32))
            eng.observe_label(r.request_id, int(np.argmax(r.logits)))

    def test_clean_merge_commits_with_lineage(self, bus):
        pool = _pool(M=2)
        pool.copy_slot(1, 0)             # genuinely converged clusters
        eng = _engine(pool, [0, 1, 0, 1]).start()
        ctl = CanaryController(eng, fraction=1.0, min_samples=8, seed=1)
        eng.attach_canary(ctl)
        try:
            eng.warmup()
            v0 = eng.version
            eng.apply_cluster_event({"kind": "cluster_merge", "base": 0,
                                     "merged": 1, "iteration": 1})
            assert eng.version == v0     # gated: no immediate swap
            assert ctl.state().startswith("cluster_merge")
            self._run_labeled(eng, ctl)
            assert ctl.verdicts, "canary never reached min_samples"
            v = ctl.verdicts[-1]
            assert v["verdict"] == "commit"
            assert v["agreement"] == pytest.approx(1.0)
            assert v["samples"] >= 8
            assert len(v["lineage_ids"]) == 2
            assert eng.version > v0      # the swap published on commit
            assert eng.submit(1, np.zeros(3, np.float32)).model == 0
            kinds = [e["kind"] for e in bus.events()]
            assert "canary_started" in kinds and "canary_verdict" in kinds
        finally:
            eng.close()

    def test_corrupt_candidate_rolls_back_with_crit_alert(self, bus,
                                                          tmp_path):
        pool = _pool(M=2)
        # survivor slot 0 is the ANTI-model of slot 1: the candidate
        # answers every re-homed client with flipped logits
        pool.set_slot(0, _anti(pool.slot(1)))
        eng = _engine(pool, [1, 1, 1, 1]).start()
        alerts = tmp_path / "alerts.jsonl"
        ctl = CanaryController(eng, fraction=1.0, min_samples=8, seed=1,
                               alerts_path=str(alerts))
        eng.attach_canary(ctl)
        try:
            eng.warmup()
            v0 = eng.version
            eng.apply_cluster_event({"kind": "cluster_merge", "base": 0,
                                     "merged": 1, "iteration": 1})
            self._run_labeled(eng, ctl)
            assert ctl.verdicts
            v = ctl.verdicts[-1]
            assert v["verdict"] == "rollback"
            assert v["shadow_acc"] < v["live_acc"] - 0.02
            assert v["agreement"] < 0.1
            assert eng.version == v0     # live generation kept
            assert eng.submit(0, np.zeros(3, np.float32)).model == 1
            lines = [json.loads(ln) for ln in
                     alerts.read_text().splitlines()]
            assert any(a["rule"] == "canary_rollback"
                       and a["severity"] == "crit" for a in lines)
            al = [e for e in bus.events() if e["kind"] == "alert_raised"]
            assert any(a["rule"] == "canary_rollback" for a in al)
        finally:
            eng.close()

    def test_event_during_open_canary_defers_then_drains(self, bus):
        pool = _pool(M=3)
        pool.copy_slot(1, 0)
        eng = _engine(pool, [0, 1, 2]).start()
        ctl = CanaryController(eng, fraction=1.0, min_samples=4, seed=1)
        eng.attach_canary(ctl)
        try:
            eng.warmup()
            eng.apply_cluster_event({"kind": "cluster_merge", "base": 0,
                                     "merged": 1, "iteration": 1})
            eng.apply_cluster_event({"kind": "cluster_merge", "base": 0,
                                     "merged": 2, "iteration": 2})
            assert ctl.stats()["deferred"] == 1
            self._run_labeled(eng, ctl)
            assert ctl.verdicts[0]["verdict"] == "commit"
            # the deferred merge opened its own canary after the verdict
            assert ctl.state().startswith("cluster_merge")
            assert ctl.stats()["pending"]["reason"] == "cluster_merge"
            assert ctl.stats()["deferred"] == 0
        finally:
            eng.close()

    def test_timeout_fails_open(self, bus):
        t = [0.0]
        pool = _pool(M=2)
        eng = _engine(pool, [0, 1]).start()
        ctl = CanaryController(eng, fraction=1.0, min_samples=8, seed=1,
                               timeout_s=5.0, time_fn=lambda: t[0])
        eng.attach_canary(ctl)
        try:
            eng.warmup()
            v0 = eng.version
            eng.apply_cluster_event({"kind": "cluster_merge", "base": 0,
                                     "merged": 1, "iteration": 1})
            t[0] = 6.0                   # labels dried up; past deadline
            eng.submit(0, np.zeros(3, np.float32))
            deadline = 100
            while not ctl.verdicts and deadline:
                eng.submit(0, np.zeros(3, np.float32))
                deadline -= 1
            v = ctl.verdicts[-1]
            assert v["decided_by"] == "timeout"
            assert v["verdict"] == "commit"      # fail OPEN, ungated
            assert v["samples"] < 8
            assert eng.version > v0
        finally:
            eng.close()

    def test_abort_discards_candidate_without_verdict(self, bus):
        pool = _pool(M=2)
        eng = _engine(pool, [0, 1]).start()
        ctl = CanaryController(eng, fraction=1.0, min_samples=8, seed=1)
        eng.attach_canary(ctl)
        try:
            eng.warmup()
            v0 = eng.version
            eng.apply_cluster_event({"kind": "cluster_merge", "base": 0,
                                     "merged": 1, "iteration": 1})
            assert ctl.abort() is True
            assert ctl.state() == "idle"
            assert not ctl.verdicts
            assert eng.version == v0
            assert ctl.abort() is False  # idempotent: nothing open
        finally:
            eng.close()

    def test_shadow_adds_no_compiles(self, bus):
        def serve_compiles():
            snap = obs.registry().snapshot()
            return sum(v for k, v in snap.items()
                       if k.startswith('jit_compiles{fn="serve_forward'))

        pool = _pool(M=2)
        pool.copy_slot(1, 0)
        eng = _engine(pool, [0, 1, 0, 1]).start()
        ctl = CanaryController(eng, fraction=1.0, min_samples=8, seed=1)
        eng.attach_canary(ctl)
        try:
            eng.warmup()
            c0 = serve_compiles()
            eng.apply_cluster_event({"kind": "cluster_merge", "base": 0,
                                     "merged": 1, "iteration": 1})
            self._run_labeled(eng, ctl)
            assert ctl.verdicts
            assert ctl.verdicts[-1]["shadow_batches"] > 0
            assert serve_compiles() == c0, \
                "shadow forward compiled a new program"
        finally:
            eng.close()

    def test_commit_replans_against_current_generation(self, bus):
        # a non-canaried event swapping while the canary is open must
        # survive the commit: the verdict re-plans against the CURRENT
        # generation instead of replaying the intercept-time snapshot
        pool = _pool(M=3)
        pool.copy_slot(1, 0)
        eng = _engine(pool, [0, 1, 2, 2]).start()
        ctl = CanaryController(eng, fraction=1.0, min_samples=4, seed=1)
        eng.attach_canary(ctl)
        try:
            eng.warmup()
            eng.apply_cluster_event({"kind": "cluster_merge", "base": 0,
                                     "merged": 1, "iteration": 1})
            # cluster 2 is deleted mid-canary: non-canaried, swaps NOW
            eng.apply_cluster_event({"kind": "cluster_delete", "model": 2,
                                     "iteration": 2})
            with pytest.raises(UnknownClientError):
                eng.submit(2, np.zeros(3, np.float32))
            rng = np.random.RandomState(0)
            for i in range(300):
                if ctl.verdicts:
                    break
                r = eng.submit(i % 2, rng.standard_normal(3)
                               .astype(np.float32))
                eng.observe_label(r.request_id, int(np.argmax(r.logits)))
            assert ctl.verdicts and \
                ctl.verdicts[-1]["verdict"] == "commit"
            # the merge re-homing published on top of the current state…
            assert eng.submit(1, np.zeros(3, np.float32)).model == 0
            # …and the mid-canary delete was NOT rolled back
            with pytest.raises(UnknownClientError):
                eng.submit(3, np.zeros(3, np.float32))
        finally:
            eng.close()

    def test_timeout_fires_from_event_feed_without_traffic(self, bus):
        # traffic stops entirely while a canary is open: the next event
        # arriving on the feed must finalize the expired canary (fail
        # open) and proceed, instead of deferring forever
        t = [0.0]
        pool = _pool(M=3)
        eng = _engine(pool, [0, 1, 2]).start()
        ctl = CanaryController(eng, fraction=1.0, min_samples=8, seed=1,
                               timeout_s=5.0, time_fn=lambda: t[0])
        eng.attach_canary(ctl)
        try:
            eng.warmup()
            eng.apply_cluster_event({"kind": "cluster_merge", "base": 0,
                                     "merged": 1, "iteration": 1})
            t[0] = 6.0
            eng.apply_cluster_event({"kind": "cluster_merge", "base": 0,
                                     "merged": 2, "iteration": 2})
            assert ctl.verdicts and \
                ctl.verdicts[0]["decided_by"] == "timeout"
            # the new event opened its own canary rather than deferring
            assert ctl.stats()["deferred"] == 0
            assert ctl.stats()["pending"]["reason"] == "cluster_merge"
        finally:
            eng.close()

    def test_timeout_fires_from_label_path(self, bus):
        t = [0.0]
        pool = _pool(M=2)
        eng = _engine(pool, [0, 1]).start()
        ctl = CanaryController(eng, fraction=1.0, min_samples=8, seed=1,
                               timeout_s=5.0, time_fn=lambda: t[0])
        eng.attach_canary(ctl)
        try:
            eng.warmup()
            eng.apply_cluster_event({"kind": "cluster_merge", "base": 0,
                                     "merged": 1, "iteration": 1})
            t[0] = 6.0
            assert eng.observe_label(12345, 0) is False
            assert ctl.verdicts and \
                ctl.verdicts[0]["decided_by"] == "timeout"
        finally:
            eng.close()

    def test_observe_label_true_with_canary_only(self, bus):
        # quality plane disabled: observe_label must still report True
        # when an open canary consumed the label
        pool = _pool(M=2)
        pool.copy_slot(1, 0)
        eng = _engine(pool, [0, 1]).start()
        ctl = CanaryController(eng, fraction=1.0, min_samples=64, seed=1)
        eng.attach_canary(ctl)
        try:
            eng.warmup()
            r = eng.submit(1, np.zeros(3, np.float32))
            assert eng.observe_label(r.request_id, 0) is False
            eng.apply_cluster_event({"kind": "cluster_merge", "base": 0,
                                     "merged": 1, "iteration": 1})
            r = eng.submit(1, np.zeros(3, np.float32))
            assert eng.observe_label(r.request_id, 0) is True
        finally:
            eng.close()

    def test_deferred_backlog_is_bounded(self, bus):
        pool = _pool(M=4)
        eng = _engine(pool, [0, 1, 2, 3]).start()
        ctl = CanaryController(eng, fraction=1.0, min_samples=8, seed=1,
                               max_deferred=2)
        eng.attach_canary(ctl)
        try:
            eng.warmup()
            for it in range(5):
                eng.apply_cluster_event(
                    {"kind": "cluster_merge", "base": 0,
                     "merged": 1 + it % 3, "iteration": it})
            assert ctl.stats()["deferred"] == 2
        finally:
            eng.close()
