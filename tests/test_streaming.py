"""Host-streaming execution (cfg.stream_data): only a [C, 2, N] window of
the dataset occupies device memory, prefetched one iteration ahead."""

import numpy as np
import pytest

from feddrift_tpu.config import ExperimentConfig
from feddrift_tpu.simulation.runner import Experiment, run_experiment

pytestmark = pytest.mark.slow   # heavy compiles: full-tier only


def _cfg(**kw):
    base = dict(dataset="sine", model="fnn", concept_drift_algo="win-1",
                change_points="A", client_num_in_total=10,
                client_num_per_round=10, train_iterations=4, comm_round=8,
                epochs=3, batch_size=32, sample_num=64,
                frequency_of_the_test=4, lr=0.02, seed=11)
    base.update(kw)
    return ExperimentConfig(**base)


class TestStreaming:
    def test_matches_resident_bitwise(self):
        resident = run_experiment(_cfg(stream_data=False))
        streamed = run_experiment(_cfg(stream_data=True))
        for series in ("Test/Acc", "Train/Acc", "Test/Loss", "Train/Loss"):
            np.testing.assert_array_equal(resident.logger.series(series),
                                          streamed.logger.series(series))
        # and the final models are identical
        import jax
        for a, b in zip(jax.tree_util.tree_leaves(resident.pool.params),
                        jax.tree_util.tree_leaves(streamed.pool.params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_dataset_not_device_resident(self):
        exp = Experiment(_cfg(stream_data=True))
        assert exp.x is None and exp.y is None
        exp.run()
        # the final step's holdout lands on a drift boundary; learning shows
        # in the best pre-drift eval point
        assert max(v for _, v in exp.logger.series("Test/Acc")) > 0.7

    def test_rejects_full_horizon_algorithms(self):
        with pytest.raises(ValueError, match="stream_data"):
            Experiment(_cfg(stream_data=True, concept_drift_algo="all"))
        with pytest.raises(ValueError, match="stream_data"):
            Experiment(_cfg(stream_data=True, concept_drift_algo="softcluster",
                            concept_drift_algo_arg="H_A_C_1_10_0",
                            concept_num=4))

    def test_composes_with_client_sampling(self):
        acc_r = run_experiment(
            _cfg(stream_data=False, client_num_per_round=5)).logger.series("Test/Acc")
        acc_s = run_experiment(
            _cfg(stream_data=True, client_num_per_round=5)).logger.series("Test/Acc")
        np.testing.assert_array_equal(acc_r, acc_s)
