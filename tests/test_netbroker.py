"""TCP pub/sub binding: PubSubCommManager over an actual network socket
(reference MQTT manager, mqtt_comm_manager.py:14-135)."""

import queue

import numpy as np

from feddrift_tpu.comm.message import Message
from feddrift_tpu.comm.netbroker import NetworkBroker, NetworkBrokerClient
from feddrift_tpu.comm.pubsub import PubSubCommManager


def _sync(client, topic="__sync__"):
    """Wait until the broker has processed this client's subscriptions:
    publish to a private topic and wait for the loopback (the MQTT
    SUBACK-analog; frames per connection are processed in order)."""
    q = client.subscribe(topic)
    client.publish(topic, "ready")
    assert q.get(timeout=5) == "ready"
    client.unsubscribe(topic, q)


def test_pub_sub_roundtrip_over_tcp():
    broker = NetworkBroker()
    try:
        a = NetworkBrokerClient(broker.host, broker.port)
        b = NetworkBrokerClient(broker.host, broker.port)
        qa = a.subscribe("t")
        _sync(a)
        b.publish("t", "hello")
        assert qa.get(timeout=5) == "hello"
        # unsubscribed clients stop receiving
        a.unsubscribe("t", qa)
        _sync(a)
        b.publish("t", "again")
        try:
            got = qa.get(timeout=0.3)
            raise AssertionError(f"received after unsubscribe: {got}")
        except queue.Empty:
            pass
        a.close(); b.close()
    finally:
        broker.close()


def test_comm_manager_over_network_broker():
    """The SAME PubSubCommManager used with the in-process broker runs
    unchanged over TCP, arrays surviving the JSON wire."""
    broker = NetworkBroker()
    try:
        m0 = PubSubCommManager(NetworkBrokerClient(broker.host, broker.port), 0)
        m1 = PubSubCommManager(NetworkBrokerClient(broker.host, broker.port), 1)
        _sync(m0.broker); _sync(m1.broker)

        got = []

        class Obs:
            def receive_message(self, msg_type, msg):
                got.append(msg)

        m1.add_observer(Obs())
        m1.run_async()
        params = {"w": np.arange(6, dtype=np.float32).reshape(2, 3), "n": 7}
        m0.send_message(Message(3, 0, 1, params))
        import time
        for _ in range(100):
            if got:
                break
            time.sleep(0.05)
        assert got, "message never delivered over TCP"
        msg = got[0]
        assert msg.msg_type == 3 and msg.sender_id == 0
        np.testing.assert_allclose(np.asarray(msg.params["w"]),
                                   params["w"])
        assert msg.params["n"] == 7
        m1.stop_receive_message()
        m0.broker.close()
        m1.broker.close()
    finally:
        broker.close()


def test_dead_subscriber_does_not_break_broker():
    broker = NetworkBroker()
    try:
        a = NetworkBrokerClient(broker.host, broker.port)
        b = NetworkBrokerClient(broker.host, broker.port)
        qa = a.subscribe("t")
        _sync(a)
        a.close()                       # dies while subscribed
        _sync(b)
        b.publish("t", "x")             # must not wedge the broker
        qb = b.subscribe("t")
        _sync(b)
        b.publish("t", "y")
        assert qb.get(timeout=5) == "y"
        b.close()
    finally:
        broker.close()
