"""MQTT 3.1.1 wire protocol: broker + client interop
(reference mqtt_comm_manager.py:14-135 speaks this via paho-mqtt)."""

import queue
import struct

import numpy as np
import pytest

from feddrift_tpu.comm import mqtt
from feddrift_tpu.comm.message import Message
from feddrift_tpu.comm.mqtt import MqttBroker, MqttBrokerClient
from feddrift_tpu.comm.pubsub import PubSubCommManager


# ----------------------------------------------------------------------
# Frame-level golden tests (byte layouts from OASIS MQTT 3.1.1)
def test_varint_encoding_spec_examples():
    # §2.2.3 table examples
    assert mqtt.encode_varint(0) == b"\x00"
    assert mqtt.encode_varint(127) == b"\x7f"
    assert mqtt.encode_varint(128) == b"\x80\x01"
    assert mqtt.encode_varint(16_383) == b"\xff\x7f"
    assert mqtt.encode_varint(16_384) == b"\x80\x80\x01"
    assert mqtt.encode_varint(268_435_455) == b"\xff\xff\xff\x7f"
    with pytest.raises(ValueError):
        mqtt.encode_varint(268_435_456)


def test_connect_packet_golden_bytes():
    pkt = mqtt.connect_packet("cid", keepalive=60)
    # fixed header: type 1, flags 0; remaining length 15
    assert pkt[0] == 0x10
    assert pkt[1] == 10 + 5   # var header 10 + payload 2+3
    body = pkt[2:]
    assert body[:6] == b"\x00\x04MQTT"      # protocol name
    assert body[6] == 4                     # protocol level 3.1.1
    assert body[7] == 0x02                  # clean session
    assert body[8:10] == struct.pack(">H", 60)
    assert body[10:] == b"\x00\x03cid"


def test_publish_packet_golden_bytes():
    pkt = mqtt.publish_packet("a/b", b"hi")
    assert pkt[0] == 0x30                   # PUBLISH, QoS 0
    assert pkt[1] == 2 + 3 + 2
    assert pkt[2:] == b"\x00\x03a/bhi"


def test_subscribe_packet_reserved_flags():
    pkt = mqtt.subscribe_packet(7, "t")
    assert pkt[0] == 0x82                   # §3.8: flags MUST be 0b0010
    assert pkt[2:4] == struct.pack(">H", 7)
    assert pkt[4:7] == b"\x00\x01t"
    assert pkt[7] == 0                      # requested QoS


def test_topic_wildcards():
    assert mqtt.topic_matches("a/b", "a/b")
    assert not mqtt.topic_matches("a/b", "a/c")
    assert mqtt.topic_matches("a/+", "a/b")
    assert not mqtt.topic_matches("a/+", "a/b/c")
    assert mqtt.topic_matches("a/#", "a/b/c")
    assert mqtt.topic_matches("#", "anything/at/all")
    assert not mqtt.topic_matches("a/+/c", "a/b/d")


# ----------------------------------------------------------------------
# Broker/client behavior over a real socket
def _sync(client, topic="__sync__"):
    """SUBSCRIBE then loopback-publish: frames per connection are
    processed in order, so receipt proves the subscription landed."""
    q = client.subscribe(topic)
    client.publish(topic, "ready")
    assert q.get(timeout=5) == "ready"
    client.unsubscribe(topic, q)


def test_mqtt_pub_sub_roundtrip():
    broker = MqttBroker()
    try:
        a = MqttBrokerClient(broker.host, broker.port)
        b = MqttBrokerClient(broker.host, broker.port)
        qa = a.subscribe("fed/t")
        _sync(a)
        b.publish("fed/t", "hello")
        assert qa.get(timeout=5) == "hello"
        a.unsubscribe("fed/t", qa)
        _sync(a)
        b.publish("fed/t", "again")
        with pytest.raises(queue.Empty):
            qa.get(timeout=0.3)
        a.ping()                            # PINGREQ must not disrupt
        b.publish("fed/t2", "x")
        a.close(); b.close()
    finally:
        broker.close()


def test_mqtt_wildcard_subscription():
    broker = MqttBroker()
    try:
        a = MqttBrokerClient(broker.host, broker.port)
        b = MqttBrokerClient(broker.host, broker.port)
        qa = a.subscribe("fl/+/update")
        _sync(a)
        b.publish("fl/3/update", "m3")
        assert qa.get(timeout=5) == "m3"
        a.close(); b.close()
    finally:
        broker.close()


def test_comm_manager_over_mqtt():
    """PubSubCommManager runs unchanged over the MQTT wire (the same
    drop-in swap the reference makes between MPI and MQTT backends)."""
    broker = MqttBroker()
    try:
        m0 = PubSubCommManager(MqttBrokerClient(broker.host, broker.port), 0)
        m1 = PubSubCommManager(MqttBrokerClient(broker.host, broker.port), 1)
        _sync(m0.broker); _sync(m1.broker)

        got = []

        class Obs:
            def receive_message(self, msg_type, msg):
                got.append(msg)

        m1.add_observer(Obs())
        m1.run_async()
        params = {"w": np.arange(6, dtype=np.float32).reshape(2, 3), "n": 7}
        m0.send_message(Message(3, 0, 1, params))
        import time
        for _ in range(100):
            if got:
                break
            time.sleep(0.05)
        assert got, "message never delivered over MQTT"
        msg = got[0]
        assert msg.msg_type == 3 and msg.sender_id == 0
        np.testing.assert_allclose(np.asarray(msg.params["w"]), params["w"])
        m1.stop_receive_message()
        m0.broker.close(); m1.broker.close()
    finally:
        broker.close()


def test_dead_client_does_not_break_mqtt_broker():
    broker = MqttBroker()
    try:
        a = MqttBrokerClient(broker.host, broker.port)
        b = MqttBrokerClient(broker.host, broker.port)
        a.subscribe("t")
        _sync(a)
        a.close()
        _sync(b)
        b.publish("t", "x")
        qb = b.subscribe("t")
        _sync(b)
        b.publish("t", "y")
        assert qb.get(timeout=5) == "y"
        b.close()
    finally:
        broker.close()


def test_qos1_publish_is_acked_and_delivered():
    """A compliant client publishing at QoS 1 gets a PUBACK and the
    packet-id bytes are NOT leaked into the delivered payload."""
    import socket as socketlib

    broker = MqttBroker()
    try:
        sub = MqttBrokerClient(broker.host, broker.port)
        q = sub.subscribe("t")
        _sync(sub)
        raw = socketlib.create_connection((broker.host, broker.port))
        raw.sendall(mqtt.connect_packet("qos1-client"))
        f = raw.makefile("rb")
        ptype, _, body = mqtt.read_packet(f)
        assert ptype == mqtt.CONNACK and body == b"\x00\x00"
        # PUBLISH QoS 1 (flags 0b0010): topic, packet id 0x0102, payload
        pub_body = b"\x00\x01t" + struct.pack(">H", 0x0102) + b"payload"
        raw.sendall(mqtt.make_packet(mqtt.PUBLISH, 0x02, pub_body))
        ptype, _, body = mqtt.read_packet(f)
        assert ptype == mqtt.PUBACK and body == struct.pack(">H", 0x0102)
        assert q.get(timeout=5) == "payload"
        raw.close(); sub.close()
    finally:
        broker.close()


def test_paho_interop_if_available():
    """True third-party interop when paho-mqtt is installed (skipped in
    this image); the golden-byte tests above pin the wire format."""
    paho = pytest.importorskip("paho.mqtt.client")
    broker = MqttBroker()
    try:
        received = []
        c = paho.Client(client_id="paho-test", clean_session=True)
        c.on_message = lambda cl, ud, m: received.append(m.payload)
        c.connect(broker.host, broker.port)
        c.loop_start()
        c.subscribe("t", qos=0)
        import time
        time.sleep(0.5)
        ours = MqttBrokerClient(broker.host, broker.port)
        ours.publish("t", "from-feddrift")
        for _ in range(100):
            if received:
                break
            time.sleep(0.05)
        assert received == [b"from-feddrift"]
        c.loop_stop()
        ours.close()
    finally:
        broker.close()
