"""Model zoo and pool tests."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from feddrift_tpu.core.pool import ModelPool
from feddrift_tpu.models import create_model, available_models
from feddrift_tpu.config import ExperimentConfig
from feddrift_tpu.data.registry import make_dataset


def _ds(name="sea", **kw):
    cfg = ExperimentConfig(dataset=name, train_iterations=2, sample_num=16, **kw)
    return make_dataset(cfg), cfg


class TestModels:
    @pytest.mark.parametrize("name,dataset,xshape", [
        ("lr", "sea", (4, 3)),
        ("fnn", "sea", (4, 3)),
        ("cnn", "MNIST", (4, 784)),
        pytest.param("resnet20", "cifar10", (4, 32, 32, 3),
                     marks=pytest.mark.slow),
        ("resnet8", "cifar10", (4, 32, 32, 3)),
    ])
    def test_forward_shapes(self, name, dataset, xshape):
        ds, cfg = _ds(dataset)
        mod = create_model(name, ds, cfg)
        x = jnp.zeros(xshape, jnp.float32)
        params = mod.init(jax.random.PRNGKey(0), x)["params"]
        out = mod.apply({"params": params}, x)
        assert out.shape == (4, ds.num_classes)

    def test_rnn_forward(self):
        ds, cfg = _ds("shakespeare")
        mod = create_model("rnn", ds, cfg)
        x = jnp.zeros((2, 80), jnp.int32)
        params = mod.init(jax.random.PRNGKey(0), x)["params"]
        out = mod.apply({"params": params}, x)
        assert out.shape == (2, 90)

    @pytest.mark.slow
    @pytest.mark.parametrize("name", ["mobilenet", "mobilenet_gn", "densenet"])
    def test_cv_zoo_forward(self, name):
        ds, cfg = _ds("cifar10")
        mod = create_model(name, ds, cfg)
        x = jnp.zeros((2, 32, 32, 3), jnp.float32)
        params = mod.init(jax.random.PRNGKey(0), x)["params"]
        out = mod.apply({"params": params}, x)
        assert out.shape == (2, ds.num_classes)

    @pytest.mark.slow
    def test_darts_forward_and_arch_split(self):
        from feddrift_tpu.models.darts import split_arch_params
        ds, cfg = _ds("cifar10")
        mod = create_model("darts", ds, cfg)
        x = jnp.zeros((2, 32, 32, 3), jnp.float32)
        params = mod.init(jax.random.PRNGKey(0), x)["params"]
        out = mod.apply({"params": params}, x)
        assert out.shape == (2, ds.num_classes)
        wmask, amask = split_arch_params(params)
        leaves_w = jax.tree_util.tree_leaves(wmask)
        leaves_a = jax.tree_util.tree_leaves(amask)
        # masks partition the tree: exactly one of (w, a) true per leaf
        assert all(w != a for w, a in zip(leaves_w, leaves_a))
        assert any(leaves_a)   # some arch alphas exist

    def test_unknown_model(self):
        ds, cfg = _ds()
        with pytest.raises(KeyError):
            create_model("transformer9000", ds, cfg)

    def test_registry_nonempty(self):
        assert {"lr", "fnn", "cnn", "resnet", "rnn"} <= set(available_models())


class TestModelPool:
    def _pool(self, M=3):
        ds, cfg = _ds()
        mod = create_model("fnn", ds, cfg)
        return ModelPool.create(mod, jnp.zeros((2, 3)), M, seed=7)

    def test_identical_init(self):
        pool = self._pool()
        # reference parity: all models reinitialized with the same fixed seed
        for leaf in jax.tree_util.tree_leaves(pool.params):
            assert np.allclose(leaf[0], leaf[1]) and np.allclose(leaf[1], leaf[2])

    def test_reinit_restores(self):
        pool = self._pool()
        perturbed = jax.tree_util.tree_map(lambda p: p + 1.0, pool.slot(1))
        pool.set_slot(1, perturbed)
        assert not np.allclose(
            jax.tree_util.tree_leaves(pool.slot(1))[0],
            jax.tree_util.tree_leaves(pool.slot(0))[0])
        pool.reinit_slot(1)
        for a, b in zip(jax.tree_util.tree_leaves(pool.slot(1)),
                        jax.tree_util.tree_leaves(pool.init_params)):
            assert np.allclose(a, b)

    def test_merge(self):
        pool = self._pool()
        pool.set_slot(0, jax.tree_util.tree_map(lambda p: p * 0 + 1.0, pool.slot(0)))
        pool.set_slot(1, jax.tree_util.tree_map(lambda p: p * 0 + 3.0, pool.slot(1)))
        pool.merge_slots(0, 1, w1=0.25, w2=0.75)
        merged = jax.tree_util.tree_leaves(pool.slot(0))[0]
        assert np.allclose(merged, 2.5)
        # second model reset to init
        for a, b in zip(jax.tree_util.tree_leaves(pool.slot(1)),
                        jax.tree_util.tree_leaves(pool.init_params)):
            assert np.allclose(a, b)

    def test_distinct_reinit(self):
        pool = self._pool()
        pool.distinct_reinit_slot(2, seed=123)
        a = jax.tree_util.tree_leaves(pool.slot(0))[-1]
        b = jax.tree_util.tree_leaves(pool.slot(2))[-1]
        assert not np.allclose(a, b)

    def test_copy_slot(self):
        pool = self._pool()
        pool.set_slot(0, jax.tree_util.tree_map(lambda p: p * 0 + 5.0, pool.slot(0)))
        pool.copy_slot(2, 0)
        assert np.allclose(jax.tree_util.tree_leaves(pool.slot(2))[0], 5.0)
